"""Benchmark: contrastive-training + bulk-embed throughput in pages/sec/chip
(the primary metric, BASELINE.json:2), with analytic-FLOPs MFU, run on
whatever accelerator the environment provides (the driver runs this on one
real TPU chip).

Robustness (VERDICT round 1 #1): the TPU backend behind the tunnel can be
transiently UNAVAILABLE or hang during init, which cost round 1 its only
perf datapoint. This file is therefore a thin wrapper that runs the actual
bench in a worker subprocess with a per-attempt timeout, retries with
backoff while the backend is down, and on persistent failure prints ONE
parseable JSON line with "value": null and an "error" field (rc 0) instead
of a traceback (rc 1).

Method (worker): flagship two-tower BERT-mini (config 3 geometry),
pre-tokenized batches resident on device (host tokenization is benched by
tests, not the device metric), jit-compiled train step with donated state;
warmup then timed steps; then a forward-only encode_page sweep (the 1B-page
bulk-embed workload, BASELINE.md:16). MFU comes from
dnn_page_vectors_tpu/utils/flops.py analytic counts over the device's peak
bf16 rate.

vs_baseline: BASELINE.json publishes no reference numbers ("published": {},
see BASELINE.md) — the ratio is computed against the most recent
BENCH_r*.json recorded by the driver, or 1.0 when none exists yet.
"""
from __future__ import annotations

import glob
import json
import os
import re
import subprocess
import sys
import time

METRIC = "train_pages_per_sec_per_chip"
UNIT = "pages/sec/chip"
# Budget knobs (seconds); env-overridable so the driver can tighten them.
# The round-5 worker runs SEVEN optional sweeps after the required metrics
# (1M embed-from-text fp16 + int8, mt5, kim_cnn, lstm, long bert, long t5)
# whose cost is dominated by compiles (~60-90 s each on the tunneled
# backend) plus the two timed 1M text sweeps (~60 s each); the default
# allows one full pass; the record-early protocol still bounds the damage
# of any overrun to the not-yet-printed optional fields.
ATTEMPT_TIMEOUT = int(os.environ.get("BENCH_ATTEMPT_TIMEOUT_S", "1500"))
TOTAL_BUDGET = int(os.environ.get("BENCH_TOTAL_BUDGET_S", "3200"))


def _previous_bench() -> float | None:
    best = None
    for path in glob.glob(os.path.join(os.path.dirname(__file__) or ".",
                                       "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
            val = rec.get("parsed", rec)["value"] if "parsed" in rec else rec["value"]
            cand = (int(m.group(1)), float(val))
        except Exception:
            continue
        if best is None or cand[0] > best[0]:
            best = cand
    return None if best is None else best[1]


def _previous_bench_record() -> dict | None:
    """Full record of the NEWEST BENCH_r*.json (highest round number) —
    the baseline the regression gate diffs EVERY shared numeric key
    against. `_previous_bench()` above stays the headline-value scan with
    its original candidacy rule (a record only counts if its `value`
    parses), so `vs_baseline` semantics are byte-stable."""
    best = None
    for path in glob.glob(os.path.join(os.path.dirname(__file__) or ".",
                                       "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
            rec = rec["parsed"] if "parsed" in rec else rec
            if not isinstance(rec, dict):
                continue
            cand = (int(m.group(1)), rec)
        except Exception:
            continue
        if best is None or cand[0] > best[0]:
            best = cand
    return None if best is None else best[1]


# Regression gate (docs/SERVING.md "SLO methodology"): keys where a LOWER
# value is better — latency, build/refresh cost, list imbalance, error
# rates — regress by RISING; everything else (throughput, recall, MFU,
# cache hit rate) regresses by dropping. Ratio-vs-previous keys and
# metadata are excluded: they re-derive from the gated keys anyway.
# compact_* contract values scale with the injected tombstone count (a
# protocol constant), not with performance — excluded like the p99 target.
# partitioned_* protocol constants (store geometry, the routing drill's
# fixed shed count) are excluded the same way; the phase's MEASURED keys
# gate with their suffixes: p99 (_ms) and scan bytes (_bytes) regress by
# rising, qps / scaling-efficiency keys by dropping, and "shed" joins the
# lower-is-better tokens so routing-health counts flag like latency.
_GATE_SKIP = {"vs_baseline", "attempts", "slo_p99_target_ms",
              "compact_bytes_reclaimed", "compact_dead_rows_dropped",
              "partitioned_store_rows", "partitioned_shards",
              "partitioned_dim", "partitioned_k", "partitioned_iters",
              "partitioned_shed_drill_sheds",
              "partitioned_shed_drill_degraded_serves",
              # net_serve protocol constants (store geometry, the SLO
              # target, drill worker counts, the detected core count,
              # and the raw-frame A/B reference arm — its size is fixed
              # by the frame layout, not by performance) — the phase's
              # MEASURED keys (net_qps_at_p99_p*, net_wire_bytes_per_query,
              # net_wire_compression_ratio, net_scaling_eff_p*,
              # net_hedge_fire_rate, net_deadline_shed_rate) all gate
              "net_store_rows", "net_shards", "net_dim", "net_k",
              "net_p99_target_ms", "net_workers", "net_cores",
              "net_wire_bytes_per_query_raw",
              # resize drill protocol constants (the hammer's fixed
              # request count and the drill's worker heartbeat) — the
              # MEASURED keys (resize_qps_dip_pct, resize_recovery_
              # seconds lower-is-better; resize_baseline_qps gates
              # higher-is-better) stay gated
              "resize_hammer_n", "resize_heartbeat_s", "net_front_ends",
              # cache_serve protocol constants (store geometry, the
              # workload's distinct-query count) and state gauges
              # (entry count tracks the workload, not performance) —
              # the phase's MEASURED keys (cache_serve_qps_at_p99_on/
              # _off, cache_serve_speedup, cache_hit_rate higher-is-
              # better; cache_serve_us_per_hit lower-is-better) all gate
              "cache_store_rows", "cache_dim", "cache_k",
              "cache_distinct", "cache_entries",
              # migration drill protocol constants (unit count tracks
              # store geometry, the stamp is a counter) — the MEASURED
              # keys (migrate_pages_per_s higher-is-better;
              # migration_sweep_seconds, migration_swap_ms,
              # serve_p99_during_migration_ms lower-is-better) all gate
              "migration_units", "post_migration_model_step",
              # filtered_serve protocol constants (store geometry, the
              # workload's distinct-query count) — the phase's MEASURED
              # keys (filtered_serve_qps_at_p99_*, filtered_recall_*,
              # filtered_ivf_recall_* higher-is-better; filtered_scan_
              # bytes_per_query_* and the s10 bytes ratio lower-is-
              # better via the "_bytes" token) all gate
              "filtered_store_rows", "filtered_dim", "filtered_k",
              "filtered_distinct"}
_LOWER_IS_BETTER = ("_ms", "seconds", "imbalance", "error", "_bytes",
                    "lint_", "shed", "hedge", "_us_per_", "dip")


def _lower_is_better(key: str) -> bool:
    return any(tok in key for tok in _LOWER_IS_BETTER)


def _regression_gate(rec: dict, prev: dict | None,
                     threshold: float = 0.05) -> tuple[dict, dict]:
    """Diff every shared TOP-LEVEL numeric key of `rec` against `prev`.
    Returns (deltas, regressions): deltas maps key -> new/prev ratio for
    every compared key; regressions keeps the direction-aware changes
    worse than `threshold` (>5% drop for higher-is-better keys, >5% rise
    for lower-is-better ones) with prev/new/ratio spelled out."""
    if not prev:
        return {}, {}
    deltas: dict = {}
    regs: dict = {}
    for key, new in rec.items():
        if key in _GATE_SKIP or isinstance(new, bool) \
                or not isinstance(new, (int, float)):
            continue
        old = prev.get(key)
        if isinstance(old, bool) or not isinstance(old, (int, float)) \
                or old == 0:
            continue
        ratio = float(new) / float(old)
        deltas[key] = round(ratio, 4)
        worse = (ratio > 1.0 + threshold if _lower_is_better(key)
                 else ratio < 1.0 - threshold)
        if worse:
            regs[key] = {"prev": old, "new": new, "ratio": round(ratio, 4)}
    return deltas, regs


def _print_delta_table(rec: dict, prev: dict | None) -> None:
    """Human-readable per-key delta table on stderr (the record carries
    the machine-readable `regressions` block)."""
    deltas, regs = _regression_gate(rec, prev)
    if not deltas:
        print("[bench] no prior BENCH_r*.json record to diff against",
              file=sys.stderr)
        return
    print(f"[bench] delta vs newest prior record "
          f"({len(deltas)} shared keys, {len(regs)} regressions):",
          file=sys.stderr)
    for key in sorted(deltas):
        mark = " REGRESSION" if key in regs else ""
        arrow = "\\/" if deltas[key] < 1.0 else ("/\\" if deltas[key] > 1.0
                                                 else "==")
        print(f"[bench]   {key:46s} {prev[key]:>14} -> "
              f"{rec[key]:>14}  x{deltas[key]:<8} {arrow}{mark}",
              file=sys.stderr)


# ---------------------------------------------------------------------------
# Worker: the actual measurement (runs in a subprocess).
# ---------------------------------------------------------------------------

def _stamp(msg: str) -> None:
    # Progress stamps on stderr: if an attempt times out, the wrapper's
    # captured stderr tail says exactly which stage hung (round-2 timeouts
    # were undiagnosable without this).
    print(f"[bench +{time.perf_counter() - _T0:.1f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.perf_counter()
_PREV_RECORD: dict | None = None      # newest prior record, loaded lazily


def _emit(rec: dict) -> None:
    """Print a (possibly partial) worker record with the regression gate
    applied: `rec["regressions"]` is recomputed on every emit as keys
    accrue, so the LAST printed record — the one the wrapper parses —
    carries the full-key diff against the newest prior BENCH_r*.json."""
    global _PREV_RECORD
    if _PREV_RECORD is None:
        _PREV_RECORD = _previous_bench_record() or {}
    _, regs = _regression_gate(rec, _PREV_RECORD)
    rec["regressions"] = regs
    print(json.dumps(rec), flush=True)


class _SyntheticTok:
    """vocab-true random-id tokenizer (ids never 0 = pad) for perf phases
    where host vocab training is data-prep cost, not step cost (mt5's 250k
    SentencePiece ~115 s, kim_cnn/lstm's 100k word vocab over 1M pages);
    uniform ids make the embedding gather/scatter no cheaper than text."""

    def __init__(self, vocab_size, max_tokens, seed):
        import numpy as np
        self.vocab_size = vocab_size
        self.max_tokens = max_tokens
        self._rng = np.random.default_rng(seed)

    def encode_batch(self, texts):
        import numpy as np
        return self._rng.integers(
            1, self.vocab_size,
            size=(len(texts), self.max_tokens), dtype=np.int32)


def _roofline_keys(prefix: str, cfg, batch: int, pps: float, peak,
                   dev) -> dict:
    """<prefix>roofline_util + the binding wall next to every MFU column
    (docs/MFU.md "roofline methodology"): achieved pairs/sec over the
    analytic min(compute, memory) ceiling — the number that stays
    meaningful for gather-dominated encoders where bf16-peak MFU reads
    as 3% by construction."""
    from dnn_page_vectors_tpu.utils.flops import (
        device_peak_hbm_bps, roofline, train_bytes_per_pair,
        train_flops_per_pair)
    ceil, bound = roofline(train_flops_per_pair(cfg, batch),
                           train_bytes_per_pair(cfg, batch),
                           peak, device_peak_hbm_bps(dev))
    if ceil is None:
        return {}
    return {f"{prefix}roofline_ceiling_pps": round(ceil, 1),
            f"{prefix}roofline_util": round(pps / ceil, 4),
            f"{prefix}roofline_bound": bound}


def run_worker() -> None:
    from dnn_page_vectors_tpu.utils.platform import hard_sync, honor_jax_platforms_env
    honor_jax_platforms_env()
    import jax

    from dnn_page_vectors_tpu.config import get_config
    from dnn_page_vectors_tpu.train.loop import Trainer
    from dnn_page_vectors_tpu.utils.flops import (
        device_peak_flops, embed_flops_per_page, train_flops_per_pair)

    _stamp("initializing backend")
    devs = jax.devices()
    n_dev = len(devs)
    peak = device_peak_flops(devs[0])
    _stamp(f"backend up: {n_dev}x {getattr(devs[0], 'device_kind', '?')}")

    # Scale knobs: defaults sized for one real TPU chip; the CPU smoke path
    # (tests, debugging) shrinks via env.
    # 1024/chip: embed throughput measured ~25% higher than at 256 (larger
    # dispatches amortize better) and train is flat; real bulk-embed jobs
    # run large batches anyway (eval.embed_batch_size default 512).
    per_chip = int(os.environ.get("BENCH_BATCH_PER_CHIP", "1024"))
    steps = int(os.environ.get("BENCH_STEPS", "80"))
    embed_iters = int(os.environ.get("BENCH_EMBED_ITERS", "60"))
    # Fused steps per dispatch (train.scan_steps). Default 1: measured on the
    # tunneled v5e, dispatch pipelines with device compute, so fusing buys
    # nothing single-chip (it matters multi-host); the knob stays for
    # experiments.
    scan_k = max(1, int(os.environ.get("BENCH_SCAN_STEPS", "1")))
    steps = max(scan_k, steps - steps % scan_k)   # never a 0-step timed loop
    # The tunneled chip shows +-20% run-to-run variance (shared tenancy);
    # report the best of REPS timed repetitions, the standard estimator for
    # "what the hardware can do" under external interference.
    reps = max(1, int(os.environ.get("BENCH_REPS", "3")))
    # optional sweeps (mt5, long bert/t5) are secondary datapoints: cap at
    # best-of-2 so they can't eat the attempt budget (primary keeps `reps`)
    opt_reps = min(reps, 2)
    batch = per_chip * n_dev
    # TRUE config-3 vocab (VERDICT r3 Missing #4): 100k toy pages supply
    # enough unique words to train the full 30,522-piece WordPiece (~13 s,
    # proven by tests/test_vocab_honesty.py), so the real embedding-table
    # gather/scatter-add is inside the measured step. The tokenizer is
    # cached under the workdir, so bench retries skip the training cost.
    cfg = get_config("bert_mini_v5p16", {
        "data.num_pages": max(100_000, batch),
        "data.query_len": 16,
        "data.page_len": 64,
        "train.batch_size": batch,
        "train.steps": steps,
        "train.log_every": 1_000_000,  # keep logging off the timed path
        "mesh.data": n_dev,
    })
    trainer = Trainer(cfg, workdir="/tmp/dnn_page_vectors_tpu_bench")
    _stamp("trainer built (tokenizer trained)")
    state = trainer.init_state()
    _stamp("state initialized")

    if scan_k > 1:
        step_fn = trainer.compiled_multi_step(state)
        it = iter(trainer.stacked_batches(k=scan_k))
    else:
        step_fn = trainer.compiled_step(state)
        it = iter(trainer.batches())
    batches = [next(it) for _ in range(2 if scan_k > 1 else 4)]
    base_rng = trainer.base_rng()
    _stamp(f"batches staged; compiling train step (scan_k={scan_k})")

    for i in range(2):  # warmup + compile
        state, metrics = step_fn(state, batches[i % len(batches)], base_rng)
    hard_sync(metrics)  # NOT block_until_ready: see utils/platform.hard_sync
    _stamp("train step compiled+warm; timing")

    def _best_time(loop, reps: int) -> float:
        """min over `reps` of: run `loop`, hard-sync its return value."""
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            hard_sync(loop())
            best = min(best, time.perf_counter() - t0)
        return best

    timed_steps = steps

    def _train_loop():
        nonlocal state
        for i in range(timed_steps // scan_k):
            state, metrics = step_fn(state, batches[i % len(batches)],
                                     base_rng)
        return metrics

    dt = _best_time(_train_loop, reps)
    train_pps_chip = batch * timed_steps / dt / n_dev
    train_flops = train_flops_per_pair(cfg, batch)
    train_mfu = (train_pps_chip * train_flops / peak) if peak else None
    _stamp(f"train timed: {train_pps_chip:.1f} pages/s/chip")

    # ---- fused-loss A/B (round 11, train.loss_chunk) --------------------
    # The chunked contrastive loss streams query chunks against the
    # GSPMD-gathered page pool instead of materializing [B, B] logits
    # (models/losses.py) — numerically pinned equal, so the A/B here is a
    # PERF datapoint: the fused step must hold the dense step's rate
    # while freeing the logits HBM that caps the in-batch negative pool.
    # Skippable via BENCH_FUSED=0.
    fused_chunk = int(os.environ.get("BENCH_LOSS_CHUNK", "256"))
    if os.environ.get("BENCH_FUSED", "1") != "0" and fused_chunk > 0 \
            and batch % fused_chunk == 0:
        try:
            import dataclasses as _dcf

            fcfg = cfg.replace(train=_dcf.replace(cfg.train,
                                                  loss_chunk=fused_chunk))
            ftrainer = Trainer(fcfg, corpus=trainer.corpus,
                               workdir="/tmp/dnn_page_vectors_tpu_bench")
            fstate = ftrainer.init_state()
            fstep = ftrainer.compiled_step(fstate)
            fit = iter(ftrainer.batches())
            fbatches = [next(fit) for _ in range(2)]
            frng = ftrainer.base_rng()
            for i in range(2):
                fstate, fm = fstep(fstate, fbatches[i % 2], frng)
            hard_sync(fm)
            _stamp(f"fused-loss step compiled (chunk={fused_chunk}); timing")
            fsteps = max(8, timed_steps // 2)

            def _fused_loop():
                nonlocal fstate
                for i in range(fsteps):
                    fstate, fm = fstep(fstate, fbatches[i % 2], frng)
                return fm

            fdt = _best_time(_fused_loop, opt_reps)
            f_pps = batch * fsteps / fdt / n_dev
            rec_fused = {
                "train_fused_loss_pages_per_sec_per_chip": round(f_pps, 2),
                "train_fused_loss_vs_dense": round(f_pps / train_pps_chip,
                                                   4),
                "train_loss_chunk": fused_chunk,
            }
            del fstate, fstep, fbatches
        except Exception as e:   # optional A/B must never cost the round
            rec_fused = {"fused_error": f"{type(e).__name__}: {e}"[:300]}
    else:
        rec_fused = {}
    _stamp("compiling embed")

    # ---- bulk-embed sweep (forward-only encode_page, device-resident) ----
    from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
    embedder = BulkEmbedder(cfg, trainer.model, state.params,
                            trainer.page_tok, trainer.mesh,
                            query_tok=trainer.query_tok)
    if scan_k > 1:
        page_stack = batches[0]["page"]          # [K, B, L] already stacked
        encode = embedder._encode_page_stack
        per_iter = batch * scan_k
        embed_iters = max(1, embed_iters // scan_k)
    else:
        # measure the PRODUCTION embed path: eval.embed_stack batches fused
        # per dispatch, exactly what embed_corpus runs (round 4 default 8)
        import numpy as _np

        from dnn_page_vectors_tpu.parallel.sharding import (
            stacked_batch_sharding)
        E = max(1, cfg.eval.embed_stack)
        # device-resident BEFORE timing: a numpy arg would re-pay the H2D
        # copy every timed iteration and understate the device metric
        page_stack = jax.device_put(
            _np.stack([_np.asarray(batches[i % len(batches)]["page"])
                       for i in range(E)]),
            stacked_batch_sharding(trainer.mesh))
        encode = embedder._encode_page_stack
        per_iter = batch * E
        embed_iters = max(1, embed_iters // E)
    out = encode(embedder.params, page_stack)
    hard_sync(out)

    def _embed_loop():
        for _ in range(embed_iters):
            out = encode(embedder.params, page_stack)
        return out

    dt_e = _best_time(_embed_loop, reps)
    embed_pps_chip = per_iter * embed_iters / dt_e / n_dev
    embed_flops = embed_flops_per_page(cfg)
    embed_mfu = (embed_pps_chip * embed_flops / peak) if peak else None

    prev = _previous_bench()
    vs = train_pps_chip / prev if prev else 1.0
    from dnn_page_vectors_tpu.utils import faults
    rec = {
        "metric": METRIC,
        "value": round(train_pps_chip, 2),
        "unit": UNIT,
        "vs_baseline": round(vs, 4),
        "train_mfu": round(train_mfu, 4) if train_mfu is not None else None,
        "embed_pages_per_sec_per_chip": round(embed_pps_chip, 2),
        "embed_mfu": round(embed_mfu, 4) if embed_mfu is not None else None,
        "train_flops_per_pair": train_flops,
        "embed_flops_per_page": embed_flops,
        "n_devices": n_dev,
        "device_kind": getattr(devs[0], "device_kind", "unknown"),
        "peak_bf16_flops": peak,
        **rec_fused,
        **_roofline_keys("train_", cfg, batch, train_pps_chip, peak,
                         devs[0]),
        # recovery-path activity during the bench (docs/ROBUSTNESS.md):
        # normally {} / False — a non-empty counter set in a bench record
        # means the run survived faults (retries, quarantines, rollbacks)
        # and the numbers were measured on a degraded pipeline
        "fault_counters": faults.counters(),
        "degraded": bool(faults.counters()),
    }
    # graftcheck counts ride the bench record (docs/ANALYSIS.md): every
    # "lint_" key is lower-is-better, so the regression gate flags
    # suppression growth — per family, so a new lock-order/lifecycle/
    # async/proto pragma flags exactly like a latency regression — and
    # analyzer wall time (lint_ms) regresses visibly too (the `cli lint
    # --changed` pre-commit loop depends on it staying fast). AST-only.
    try:
        from dnn_page_vectors_tpu.tools.analyze import RULES
        from dnn_page_vectors_tpu.tools.analyze import analyze as _lint
        _t_lint = time.time()
        _lint_report = _lint()
        rec["lint_ms"] = round((time.time() - _t_lint) * 1000.0, 1)
        rec["lint_findings"] = len(_lint_report.findings)
        rec["lint_suppressions"] = len(_lint_report.suppressed)
        rec["lint_baselined"] = len(_lint_report.baselined)
        _fam_of = {name: r.family for name, r in RULES.items()}
        for fam in sorted({r.family for r in RULES.values()}):
            fkey = fam.replace("-", "_")
            rec[f"lint_{fkey}_findings"] = sum(
                1 for f in _lint_report.findings
                if _fam_of.get(f.rule) == fam)
            rec[f"lint_{fkey}_suppressions"] = sum(
                1 for s in _lint_report.suppressed
                if _fam_of.get(s["rule"]) == fam)
    except Exception as e:   # the analyzer must never cost a bench round
        rec["lint_error"] = f"{type(e).__name__}: {e}"[:300]
    # The REQUIRED metrics are safe from this point: print them before the
    # optional sweeps, and again merged with their fields on success — the
    # wrapper parses the LAST record, and a sweep crash or per-attempt
    # timeout can no longer destroy the measured primary datapoint (the
    # timeout path recovers records from partial stdout).
    _emit(rec)

    on_tpu = getattr(devs[0], "platform", "") == "tpu"

    # ---- serve phase: QPS / latency of the query-serving layer -----------
    # The serving treatment (round 6, docs/SERVING.md): a store embedded
    # from this run's corpus is pre-staged in HBM, then N queries run (a)
    # strictly sequentially through search() — the pre-round-6 behavior,
    # one padded bucket per query — and (b) through the dynamic
    # micro-batcher at BENCH_SERVE_CONCURRENCY threads, where concurrent
    # callers coalesce into shared bucket-filling dispatches and repeat
    # queries hit the embedding cache. serve_qps / serve_p50_ms /
    # serve_p99_ms / serve_cache_hit_rate land in the record; the stage
    # breakdown (queue_wait/tokenize/encode/topk/merge/format) says where
    # serving time goes. Skippable via BENCH_SERVE=0; skipped off-TPU.
    if os.environ.get("BENCH_SERVE", "1") != "0" and on_tpu:
        try:
            import concurrent.futures
            import shutil

            from dnn_page_vectors_tpu.infer.serve import SearchService
            from dnn_page_vectors_tpu.infer.vector_store import VectorStore
            from dnn_page_vectors_tpu.utils.profiling import (
                LatencyStats, PipelineProfiler)

            shard_rows = 16_384
            n_store = int(os.environ.get("BENCH_SERVE_PAGES",
                                         str(4 * shard_rows)))
            conc = int(os.environ.get("BENCH_SERVE_CONCURRENCY", "32"))
            n_q = int(os.environ.get("BENCH_SERVE_QUERIES", "512"))
            distinct = int(os.environ.get("BENCH_SERVE_DISTINCT", "64"))
            sdir = "/tmp/dnn_page_vectors_tpu_bench/serve_store"
            shutil.rmtree(sdir, ignore_errors=True)
            sstore = VectorStore(sdir, dim=cfg.model.out_dim,
                                 shard_size=shard_rows)
            _stamp(f"serve phase: embedding {n_store}-page store "
                   f"({n_store // shard_rows} shards)")
            embedder.embed_corpus(trainer.corpus, sstore, stop=n_store)
            sprof = PipelineProfiler()
            svc = SearchService(cfg, embedder, trainer.corpus, sstore,
                                preload_hbm_gb=4.0, profiler=sprof)
            kq = 10
            svc.warmup(k=kq)
            qtexts = [trainer.corpus.query_text(i) for i in range(distinct)]
            _stamp(f"serve warm ({svc.warm_latency_ms:.1f} ms median); "
                   f"timing {conc} sequential then {n_q}@{conc} batched")
            svc.clear_cache()
            t0 = time.perf_counter()
            for i in range(conc):
                svc.search(qtexts[i % distinct], k=kq)
            seq_qps = conc / (time.perf_counter() - t0)
            svc.clear_cache()
            sprof.reset()
            lat = LatencyStats()
            svc.start_batcher()

            def _one(i):
                with lat.timed():
                    return svc.search(qtexts[i % distinct], k=kq)

            # burst 1 (sequential, above) vs burst 2 (batched): the
            # windowed registry gauges move between the two — proof the
            # live SLO view (docs/OBSERVABILITY.md) tracks traffic, while
            # the wall-clock serve_qps/serve_p99_ms keys stay authoritative
            win_after_seq = svc.metrics()["serve_window_qps"]
            t0 = time.perf_counter()
            with concurrent.futures.ThreadPoolExecutor(conc) as ex:
                list(ex.map(_one, range(n_q)))
            dt = time.perf_counter() - t0
            svc.close()
            smet = svc.metrics()
            rec.update({
                "serve_qps": round(n_q / dt, 2),
                "serve_seq_qps": round(seq_qps, 2),
                "serve_speedup_vs_sequential": round(n_q / dt / seq_qps, 2),
                "serve_p50_ms": round(lat.percentile_ms(50), 3),
                "serve_p99_ms": round(lat.percentile_ms(99), 3),
                "serve_cache_hit_rate": smet["serve_cache_hit_rate"],
                "serve_warm_latency_ms": round(svc.warm_latency_ms, 3),
                "serve_concurrency": conc,
                "serve_queries": n_q,
                "serve_distinct_queries": distinct,
                "serve_store_vectors": sstore.num_vectors,
                "serve_mean_batch": smet.get("serve_mean_batch"),
                # the registry's live windowed view (docs/OBSERVABILITY.md)
                # — read from the SAME instruments tests and serve-metrics
                # exposition read, not recomputed here
                "serve_window_s": smet["serve_window_s"],
                "serve_window_qps": smet["serve_window_qps"],
                "serve_window_qps_after_seq_burst": round(win_after_seq, 3),
                "serve_window_p50_ms": smet["serve_window_p50_ms"],
                "serve_window_p99_ms": smet["serve_window_p99_ms"],
                "serve_window_cache_hit_rate":
                    smet["serve_window_cache_hit_rate"],
                "serve_stage_seconds": {
                    key: round(val, 3)
                    for key, val in sorted(sprof.stages().items())},
            })

            # ---- ann sub-phase: IVF index over the same >=50k store ----
            # Build the inverted file (TPU k-means), measure index quality
            # (recall@10 of the exact top-10 at the default nprobe) and
            # ANN serving QPS under the IDENTICAL protocol as serve_qps
            # (same store, queries, concurrency, batcher, cache) — so
            # ann_qps / serve_qps isolates the retrieval algorithm.
            # Skippable via BENCH_ANN=0.
            if os.environ.get("BENCH_ANN", "1") != "0":
                try:
                    import dataclasses as _dc

                    import numpy as _np3

                    from dnn_page_vectors_tpu.evals.recall import (
                        recall_vs_exact)
                    from dnn_page_vectors_tpu.index.ivf import IVFIndex
                    _stamp(f"ann phase: building IVF index over "
                           f"{sstore.num_vectors} vectors")
                    t0 = time.perf_counter()
                    aidx = IVFIndex.build(sstore, embedder.mesh,
                                          nlist=cfg.serve.nlist,
                                          iters=cfg.serve.kmeans_iters,
                                          seed=0)
                    build_s = time.perf_counter() - t0
                    qv = _np3.asarray(
                        embedder.embed_texts(qtexts, tower="query"),
                        _np3.float32)
                    r10 = recall_vs_exact(aidx, sstore, qv, embedder.mesh,
                                          k=10, nprobe=cfg.serve.nprobe)
                    _stamp(f"ann index built ({build_s:.1f}s, nlist="
                           f"{aidx.nlist}); recall@10 vs exact {r10:.3f}; "
                           f"timing {n_q}@{conc} batched")
                    acfg = cfg.replace(serve=_dc.replace(cfg.serve,
                                                         index="ivf"))
                    asvc = SearchService(acfg, embedder, trainer.corpus,
                                         sstore, preload_hbm_gb=0.0)
                    asvc.warmup(k=kq)
                    asvc.clear_cache()
                    asvc.start_batcher()
                    gb0 = asvc.ann_gather_bytes
                    t0 = time.perf_counter()
                    with concurrent.futures.ThreadPoolExecutor(conc) as ex:
                        list(ex.map(
                            lambda i: asvc.search(qtexts[i % distinct],
                                                  k=kq), range(n_q)))
                    adt = time.perf_counter() - t0
                    ann_bytes = asvc.ann_gather_bytes - gb0
                    asvc.close()
                    amet = asvc.metrics()
                    rec.update({
                        "ann_recall_at_10": round(r10, 4),
                        "ann_qps": round(n_q / adt, 2),
                        "ann_build_seconds": round(build_s, 3),
                        "ann_nlist": aidx.nlist,
                        "ann_nprobe": cfg.serve.nprobe,
                        "ann_imbalance": aidx.imbalance,
                        "ann_fallbacks": amet.get("ann_fallbacks", 0),
                        "ann_lists_scanned": amet.get(
                            "ann_lists_scanned", 0),
                        "ann_candidates_reranked": amet.get(
                            "ann_candidates_reranked", 0),
                        # measured candidate-payload traffic (docs/ANN.md):
                        # bytes the posting gather moved over the host
                        # path, per query and per second — the 4x claim
                        # is a measurement, not an assertion
                        "ann_gather_bytes_per_query": round(
                            ann_bytes / max(n_q, 1), 1),
                        "ann_gather_mbytes_per_s": round(
                            ann_bytes / max(adt, 1e-9) / 1e6, 2),
                        "ann_vs_exact_qps": round(
                            (n_q / adt) / max(rec.get("serve_qps") or 1e-9,
                                              1e-9), 3),
                    })

                    # ---- pq sub-phase: OPQ+PQ codes + on-device ADC ----
                    # Same store / queries / concurrency / batcher
                    # protocol as the ann phase, with compressed posting
                    # payloads and the HBM-resident hot posting set: the
                    # qps and bytes/query deltas vs the r05-style ann
                    # numbers above isolate the payload treatment.
                    # Skippable via BENCH_PQ=0.
                    try:
                      if os.environ.get("BENCH_PQ", "1") != "0":
                        from dnn_page_vectors_tpu.index.pq import auto_pq_m
                        _stamp(f"pq phase: OPQ+PQ build (m="
                               f"{auto_pq_m(sstore.dim)}) over "
                               f"{sstore.num_vectors} vectors")
                        t0 = time.perf_counter()
                        pidx = IVFIndex.build(
                            sstore, embedder.mesh, nlist=cfg.serve.nlist,
                            iters=cfg.serve.kmeans_iters, seed=0,
                            pq_m=cfg.serve.pq_m or auto_pq_m(sstore.dim),
                            pq_iters=cfg.serve.pq_iters,
                            opq_iters=cfg.serve.pq_opq_iters)
                        pq_build_s = time.perf_counter() - t0
                        r10p = recall_vs_exact(pidx, sstore, qv,
                                               embedder.mesh, k=10,
                                               nprobe=cfg.serve.nprobe)
                        pcfg = cfg.replace(serve=_dc.replace(
                            cfg.serve, index="ivf", hot_postings_gb=2.0))
                        psvc = SearchService(pcfg, embedder,
                                             trainer.corpus, sstore,
                                             preload_hbm_gb=0.0)
                        psvc.warmup(k=kq)
                        psvc.clear_cache()
                        psvc.start_batcher()
                        gb0 = psvc.ann_gather_bytes
                        t0 = time.perf_counter()
                        with concurrent.futures.ThreadPoolExecutor(
                                conc) as ex:
                            list(ex.map(
                                lambda i: psvc.search(
                                    qtexts[i % distinct], k=kq),
                                range(n_q)))
                        pdt = time.perf_counter() - t0
                        pq_bytes = psvc.ann_gather_bytes - gb0
                        psvc.close()
                        pmet = psvc.metrics()
                        bpq = pq_bytes / max(n_q, 1)
                        rec.update({
                            "ann_pq_recall_at_10": round(r10p, 4),
                            "ann_pq_qps": round(n_q / pdt, 2),
                            "ann_pq_m": pidx.pq_m,
                            "codebook_build_seconds":
                                (pidx.manifest.get("pq") or {}).get(
                                    "train_seconds"),
                            "ann_pq_build_seconds": round(pq_build_s, 3),
                            "ann_pq_gather_bytes_per_query": round(bpq, 1),
                            "ann_pq_gather_mbytes_per_s": round(
                                pq_bytes / max(pdt, 1e-9) / 1e6, 2),
                            "ann_pq_payload_reduction": round(
                                (ann_bytes / max(n_q, 1)) / max(bpq, 1e-9),
                                2),
                            "ann_pq_hot_rows": pmet.get(
                                "ann_index", {}).get("hot_rows", 0),
                            "ann_pq_fallbacks": pmet.get(
                                "ann_fallbacks", 0),
                            "ann_pq_vs_ann_qps": round(
                                (n_q / pdt) / max(n_q / adt, 1e-9), 3),
                        })
                        _stamp(
                            f"pq phase done: recall@10 {r10p:.3f}, "
                            f"{n_q / pdt:.0f} qps "
                            f"({rec['ann_pq_payload_reduction']}x fewer "
                            "payload bytes/query)")
                    except Exception as e:  # keep serve + ann + update data
                        rec["pq_error"] = f"{type(e).__name__}: {e}"[:300]

                    # ---- update sub-phase: live append + hot-swap ----
                    # The live-update treatment (docs/UPDATES.md): append
                    # one shard of new pages to the serve store as a
                    # generation, refresh() a live ANN service (incremental
                    # index update + atomic view swap), and measure the
                    # operator-facing numbers — append throughput, index
                    # update cost (O(new shards)), the swap's downtime
                    # window, and post-append ANN recall on the NEW pages.
                    # Skippable via BENCH_UPDATE=0.
                    if os.environ.get("BENCH_UPDATE", "1") != "0":
                        try:
                            from dnn_page_vectors_tpu.updates import (
                                append_corpus)
                            n_app = int(os.environ.get(
                                "BENCH_UPDATE_PAGES", str(shard_rows)))
                            base_n = sstore.num_vectors
                            _stamp(f"update phase: appending {n_app} pages "
                                   f"to the {base_n}-page serve store")
                            usvc = SearchService(acfg, embedder,
                                                 trainer.corpus, sstore,
                                                 preload_hbm_gb=4.0)
                            usvc.warmup(k=kq)
                            astats = append_corpus(
                                embedder, trainer.corpus, sstore,
                                stop=base_n + n_app, tombstone=[0])
                            t0 = time.perf_counter()
                            rinfo = usvc.refresh()
                            uq = [trainer.corpus.query_text(base_n + i)
                                  for i in range(min(distinct, n_app))]
                            uqv = _np3.asarray(
                                embedder.embed_texts(uq, tower="query"),
                                _np3.float32)
                            r10u = (recall_vs_exact(
                                usvc._index, sstore, uqv, embedder.mesh,
                                k=10, nprobe=cfg.serve.nprobe)
                                if usvc._index is not None else None)
                            usvc.close()
                            iupd = rinfo.get("index_update") or {}
                            rec.update({
                                "append_pages": n_app,
                                "append_docs_per_s":
                                    astats["append_docs_per_s"],
                                "index_update_seconds": iupd.get("seconds"),
                                "index_update_action": iupd.get("action"),
                                "refresh_seconds":
                                    rinfo["refresh_seconds"],
                                "refresh_swap_ms": rinfo["swap_ms"],
                                "post_append_recall_at_10":
                                    (round(r10u, 4) if r10u is not None
                                     else None),
                                "store_generation":
                                    rinfo["store_generation"],
                            })
                            _stamp(
                                f"update phase done: append "
                                f"{astats['append_docs_per_s']:.0f} docs/s, "
                                f"index {iupd.get('action')} in "
                                f"{iupd.get('seconds')}s, swap "
                                f"{rinfo['swap_ms']:.1f} ms")
                        except Exception as e:  # keep serve + ann data
                            rec["update_error"] = \
                                f"{type(e).__name__}: {e}"[:300]

                    # ---- maintenance sub-phase: compaction + bg rebuild
                    # under load (docs/MAINTENANCE.md): tombstone a slice
                    # of the serve store past a lowered compaction
                    # threshold, then run ONE maintenance pass (janitor →
                    # compaction → background index rebuild, every swap
                    # hot-swapped into the live service) while 4 query
                    # threads hammer it — the measured numbers are the
                    # operator-facing ones: compaction throughput, bytes
                    # reclaimed, the bg rebuild's swap window, and serve
                    # p99 WHILE maintenance ran. BENCH_MAINTENANCE=0 skips.
                    if os.environ.get("BENCH_MAINTENANCE", "1") != "0":
                        try:
                            import threading as _threading

                            from dnn_page_vectors_tpu.updates import (
                                append_corpus as _append)
                            _stamp("maintenance phase: tombstone burst + "
                                   "compaction + bg rebuild under load")
                            mcfg = acfg.replace(maintenance=_dc.replace(
                                acfg.maintenance,
                                compact_tombstone_density=0.02))
                            msvc = SearchService(mcfg, embedder,
                                                 trainer.corpus, sstore,
                                                 preload_hbm_gb=4.0)
                            msvc.warmup(k=kq)
                            msvc.start_batcher()
                            maint = msvc.start_maintenance(threads=False)
                            n_dead = max(64,
                                         int(0.03 * sstore.num_vectors))
                            _append(embedder, trainer.corpus, sstore,
                                    tombstone=list(range(1, 1 + n_dead)))
                            msvc.refresh()
                            mlat = LatencyStats()
                            mstop = _threading.Event()

                            def _hammer(wid):
                                i = wid
                                while not mstop.is_set():
                                    with mlat.timed():
                                        msvc.search(qtexts[i % distinct],
                                                    k=kq)
                                    i += 1

                            hthreads = [
                                _threading.Thread(target=_hammer,
                                                  args=(w,), daemon=True)
                                for w in range(4)]
                            for t in hthreads:
                                t.start()
                            mt0 = time.perf_counter()
                            mout = maint.run_once()
                            m_dt = time.perf_counter() - mt0
                            mstop.set()
                            for t in hthreads:
                                t.join()
                            comp = mout.get("compaction") or {}
                            rb = (comp.get("index_rebuild")
                                  or mout.get("rebuild") or {})
                            mmet = msvc.metrics()
                            msvc.close()
                            rec.update({
                                "compact_docs_per_s":
                                    comp.get("compact_docs_per_s"),
                                "compact_bytes_reclaimed":
                                    comp.get("bytes_reclaimed"),
                                "compact_dead_rows_dropped":
                                    comp.get("dead_rows_dropped"),
                                "bg_rebuild_swap_ms": rb.get("swap_ms"),
                                "bg_rebuild_seconds":
                                    rb.get("build_seconds"),
                                "serve_p99_during_compaction_ms": round(
                                    mlat.percentile_ms(99), 3),
                                "maintenance_pass_seconds": round(m_dt, 3),
                                "maintenance_full_rebuilds":
                                    mmet["full_rebuilds"],
                            })
                            _stamp(
                                f"maintenance phase done: compacted "
                                f"{comp.get('rows')} rows "
                                f"({comp.get('bytes_reclaimed')} B "
                                f"reclaimed), bg swap "
                                f"{rb.get('swap_ms')} ms, p99 under "
                                f"maintenance "
                                f"{mlat.percentile_ms(99):.1f} ms")
                        except Exception as e:  # keep serve + ann data
                            rec["maintenance_error"] = \
                                f"{type(e).__name__}: {e}"[:300]

                    # ---- migration sub-phase: rolling re-embed under
                    # load (docs/MAINTENANCE.md "Rolling model
                    # migration"): the migrate pillar sweeps the live
                    # serve store to a new model stamp unit-by-unit —
                    # every flip hot-swapped into the service, queries
                    # running dual-stamp mid-sweep — while 4 query
                    # threads hammer it. Measured: re-embed throughput,
                    # the sweep's wall clock, and serve p99 WHILE the
                    # store flipped stamps. The target params are the
                    # same trained tower (the drill prices the sweep
                    # machinery, not a second training run), so results
                    # stay comparable across rounds. BENCH_MIGRATE=0
                    # skips.
                    if os.environ.get("BENCH_MIGRATE", "1") != "0":
                        try:
                            import threading as _threading
                            _stamp("migration phase: rolling re-embed "
                                   "under query load")
                            # fresh handle: the compaction sub-phase may
                            # have purged files sstore still references
                            gstore = VectorStore(sstore.directory)
                            gsvc = SearchService(acfg, embedder,
                                                 trainer.corpus, gstore,
                                                 preload_hbm_gb=4.0)
                            gsvc.warmup(k=kq)
                            gmaint = gsvc.start_maintenance(threads=False)
                            g_to = int(gstore.model_step) + 1
                            gmaint.request_migration(g_to, trainer.corpus,
                                                     embedder)
                            glat = LatencyStats()
                            gstop = _threading.Event()

                            def _ghammer(wid):
                                i = wid
                                while not gstop.is_set():
                                    with glat.timed():
                                        gsvc.search(qtexts[i % distinct],
                                                    k=kq)
                                    i += 1

                            gthreads = [
                                _threading.Thread(target=_ghammer,
                                                  args=(w,), daemon=True)
                                for w in range(4)]
                            for t in gthreads:
                                t.start()
                            gt0 = time.perf_counter()
                            g_units, g_rows, g_swaps = 0, 0, []
                            while True:
                                gout = gmaint.run_once().get("migrate")
                                if gout is None:
                                    break
                                if gout.get("refresh_swap_ms") is not None:
                                    g_swaps.append(gout["refresh_swap_ms"])
                                if gout.get("action") == "migrating":
                                    g_units += len(gout.get("units") or [])
                                    g_rows += int(gout.get("rows", 0))
                                else:
                                    break
                            g_dt = time.perf_counter() - gt0
                            gstop.set()
                            for t in gthreads:
                                t.join()
                            gsvc.close()
                            rec.update({
                                "migration_units": g_units,
                                "migrate_pages_per_s": round(
                                    g_rows / max(g_dt, 1e-9), 2),
                                "migration_sweep_seconds": round(g_dt, 3),
                                "migration_swap_ms": (round(
                                    max(g_swaps), 3) if g_swaps else None),
                                "serve_p99_during_migration_ms": round(
                                    glat.percentile_ms(99), 3),
                                "post_migration_model_step":
                                    VectorStore(sstore.directory,
                                                verify=False).model_step,
                            })
                            _stamp(
                                f"migration phase done: {g_units} units "
                                f"({g_rows} rows) in {g_dt:.1f}s, p99 "
                                f"under migration "
                                f"{glat.percentile_ms(99):.1f} ms")
                        except Exception as e:  # keep serve + ann data
                            rec["migration_error"] = \
                                f"{type(e).__name__}: {e}"[:300]
                except Exception as e:  # ann failure must keep serve data
                    rec["ann_error"] = f"{type(e).__name__}: {e}"[:300]

            # ---- slo phase: measured "qps @ p99 < X ms" ----------------
            # The production metric the serve_qps keys above proxy
            # (docs/SERVING.md "SLO methodology"): a seeded open-loop
            # Poisson workload over the same store/queries, the loadgen
            # driver binary-searching offered load for the max sustained
            # QPS whose windowed p99 — read from the telemetry registry,
            # not re-derived — stays under the target. Adaptive batching
            # is ON for this phase (it exists for exactly this traffic);
            # every number regression-gates against the prior round via
            # the `regressions` block. Skippable via BENCH_SLO=0.
            if os.environ.get("BENCH_SLO", "1") != "0":
                try:
                    import dataclasses as _dcs

                    from dnn_page_vectors_tpu.loadgen import (
                        find_qps_at_p99, make_workload)
                    slo_p99 = float(os.environ.get("BENCH_SLO_P99_MS",
                                                   "250"))
                    slo_trial = float(os.environ.get("BENCH_SLO_TRIAL_S",
                                                     "6"))
                    slo_cfg = cfg.replace(
                        serve=_dcs.replace(cfg.serve,
                                           batch_window_adaptive=True),
                        obs=_dcs.replace(cfg.obs, window_s=slo_trial))
                    ssvc = SearchService(slo_cfg, embedder, trainer.corpus,
                                         sstore, preload_hbm_gb=4.0)
                    ssvc.warmup(k=kq)
                    ssvc.start_batcher()
                    wl = make_workload("poisson", seed=0, distinct=distinct,
                                       profile=((kq, None, 1.0),))
                    _stamp(f"slo phase: searching qps @ p99<{slo_p99:.0f}ms"
                           f" ({slo_trial:.0f}s trials, poisson)")
                    srep = find_qps_at_p99(
                        ssvc, wl, qtexts, p99_target_ms=slo_p99,
                        start=float(os.environ.get("BENCH_SLO_START_QPS",
                                                   "16")),
                        iters=int(os.environ.get("BENCH_SLO_ITERS", "3")),
                        duration_s=slo_trial, warmup_s=1.0,
                        progress=_stamp, progress_every_s=slo_trial)
                    ssvc.close()
                    rec.update({
                        "slo_qps_at_p99": srep["qps_at_p99"],
                        "slo_p99_target_ms": srep["p99_target_ms"],
                        "slo_shape": srep["shape"],
                        "slo_trials": [
                            {key: t[key] for key in (
                                "offered_qps", "achieved_qps", "p50_ms",
                                "p99_ms", "error_rate", "cache_hit_rate",
                                "met")} for t in srep["trials"]],
                        "slo_recompiles": ssvc.recompiles,
                        "slo_batch_window_ms": round(
                            ssvc.batch_window_ms, 3),
                        "slo_window_adapts": sum(
                            1 for e in srep["events"]
                            if e["event"] == "window_adapt"),
                    })
                    _stamp(f"slo phase done: {srep['qps_at_p99']:.0f} qps @"
                           f" p99<{slo_p99:.0f}ms over "
                           f"{len(srep['trials'])} trials")
                except Exception as e:  # keep serve + ann + update data
                    rec["slo_error"] = f"{type(e).__name__}: {e}"[:300]
        except Exception as e:  # optional phase must never cost the round
            rec["serve_error"] = f"{type(e).__name__}: {e}"[:300]
        _emit(rec)

    # ---- embed-FROM-TEXT phase (VERDICT r4 Missing #1 / next-round #1) ---
    # The device-resident number above deliberately isolates chip compute;
    # THIS phase measures the production job: a 1M-page jsonl corpus on
    # disk -> per-batch reads (JsonlCorpus fast-extract) -> C++ WordPiece
    # tokenize (data.tokenize_threads) -> prefetch/device -> fp16 store,
    # wall-clock end to end, store writes included. Corpus and trained
    # tokenizer are cached on disk so retries/rounds skip the one-time
    # ~45 s setup. Skippable via BENCH_EMBED_TEXT=0; skipped off-TPU.
    if os.environ.get("BENCH_EMBED_TEXT", "1") != "0" and on_tpu:
        try:
            import shutil

            from dnn_page_vectors_tpu.data.synth import write_synth_jsonl
            from dnn_page_vectors_tpu.infer.vector_store import VectorStore

            n_text = int(os.environ.get("BENCH_TEXT_PAGES", "1000000"))
            tdir = "/tmp/dnn_page_vectors_tpu_bench_text"
            os.makedirs(tdir, exist_ok=True)
            jpath = os.path.join(tdir, f"synth_{n_text}.jsonl")
            if not os.path.exists(jpath):
                _stamp(f"generating {n_text}-page jsonl corpus (one-time)")
                write_synth_jsonl(jpath, n_text, seed=7, page_len=48,
                                  query_len=16)
            ecfg = get_config("bert_mini_v5p16", {
                "data.corpus": f"jsonl:{jpath}",
                "data.num_pages": n_text,
                "data.query_len": 16,
                "data.page_len": 64,
                "data.tokenize_threads": int(
                    os.environ.get("BENCH_TOKENIZE_THREADS", "8")),
                # parallel host producer (round 6): N tokenizer workers
                # read+tokenize batch ranges concurrently and the store
                # writeback overlaps device compute — the serial producer
                # held embed-from-text to 57% of the transport ceiling
                # (BENCH_r05) while the device sat idle between batches
                "data.tokenize_workers": int(
                    os.environ.get("BENCH_TOKENIZE_WORKERS", "6")),
                # 32 batches per dispatch (vs the default 8): the tunneled
                # chip pays ~100 ms per result materialization, so fewer,
                # bigger D2H pulls move the from-text rate toward the
                # bandwidth ceiling (56% -> measured below); real PCIe
                # hosts are insensitive to this knob beyond the default
                "eval.embed_stack": int(
                    os.environ.get("BENCH_EMBED_STACK", "32")),
                "train.batch_size": batch,
                "train.log_every": 1_000_000,
                "mesh.data": n_dev,
            })
            etrainer = Trainer(ecfg, workdir=tdir)  # wordpiece cached here
            _stamp("text-phase trainer built (tokenizer trained/cached)")
            eembedder = BulkEmbedder(
                ecfg, etrainer.model, etrainer.init_state().params,
                etrainer.page_tok, etrainer.mesh,
                query_tok=etrainer.query_tok)
            sdir = os.path.join(tdir, "store")
            from dnn_page_vectors_tpu.utils.profiling import PipelineProfiler
            eprof = PipelineProfiler()

            def _sweep():
                eprof.reset()   # summary reported below = the LAST rep's
                shutil.rmtree(sdir, ignore_errors=True)
                store = VectorStore(sdir, dim=ecfg.model.out_dim,
                                    shard_size=ecfg.eval.store_shard_size)
                eembedder.embed_corpus(etrainer.corpus, store,
                                       profiler=eprof)
                assert store.num_vectors == n_text, store.num_vectors
                # already host-complete (every vector was materialized into
                # the store); give _best_time's hard_sync a device no-op
                import jax.numpy as jnp
                return jnp.zeros(())

            _stamp("warming text-embed (compile + first shard)")
            shutil.rmtree(sdir, ignore_errors=True)
            warm = VectorStore(sdir, dim=ecfg.model.out_dim,
                               shard_size=ecfg.eval.store_shard_size)
            eembedder.embed_corpus(etrainer.corpus, warm,
                                   stop=ecfg.eval.store_shard_size)
            # Raw device->host bandwidth: the embed job's entire output IS
            # D2H traffic (2 B/dim/page after the on-device fp16 cast), so
            # this sets a transport-imposed ceiling on the from-text rate.
            # Behind the sandbox tunnel it is ~3 orders below PCIe; the
            # ratio of achieved rate to THIS ceiling — not to the compute
            # rate — is the honest pipeline-efficiency number here
            # (docs/SCALING.md "host budget").
            import jax.numpy as _jnp
            import numpy as _np2
            big = _jnp.zeros((32 * 1024 * 1024 // 2,), _jnp.float16) + 1
            _np2.asarray(big)                       # warm the path
            t0 = time.perf_counter()
            _np2.asarray(big * 2)
            d2h_bps = big.nbytes / (time.perf_counter() - t0)
            ceiling = d2h_bps / (ecfg.model.out_dim * 2)
            _stamp(f"D2H {d2h_bps / 1e6:.0f} MB/s -> transport ceiling "
                   f"{ceiling:,.0f} pages/s; timing full 1M sweep")
            tdt = _best_time(_sweep, opt_reps)
            etext_pps = n_text / tdt / n_dev
            # MEASURED drain rate of the job's own packed d2h transfers
            # (bytes and seconds from the PipelineProfiler, round 11) —
            # the probe-based number keeps setting the transport CEILING,
            # but the recorded embed_d2h_mbytes_per_sec is now what the
            # sweep actually achieved, one packed device_get per dispatch
            eprof_s = eprof.stages().get("d2h", 0.0)
            d2h_measured = (eprof.stage_bytes().get("d2h", 0) / eprof_s
                            / 1e6 if eprof_s > 0 else 0.0)
            rec.update({
                "embed_from_text_pages_per_sec_per_chip": round(etext_pps, 2),
                "embed_from_text_pages": n_text,
                "embed_from_text_vs_device": round(
                    etext_pps / embed_pps_chip, 4),
                "embed_d2h_mbytes_per_sec": round(d2h_measured, 1),
                "embed_d2h_probe_mbytes_per_sec": round(d2h_bps / 1e6, 1),
                "embed_from_text_transport_ceiling_pps": round(ceiling, 1),
                "embed_from_text_vs_transport_ceiling": round(
                    min(etext_pps / ceiling, 9.99), 4),
                "embed_tokenize_threads": ecfg.data.tokenize_threads,
                "embed_tokenize_workers": ecfg.data.tokenize_workers,
                # which stage binds (PipelineProfiler; LAST rep's sweep —
                # read/tokenize are cumulative over the worker pool, so
                # compare ratios, and produce_wait against wall clock)
                "embed_stage_seconds": {
                    k: round(v, 2) for k, v in sorted(
                        eprof.stages().items())},
            })
            _emit(rec)

            # int8 store variant: quantization happens ON DEVICE (bulk_embed
            # q8 wire), so the job ships 1 B/dim codes + 2 B/row scales —
            # the config-4 1B-page recipe (docs/SCALING.md), and another
            # ~2x off the transport-bound sandbox number.
            def _sweep_q8():
                shutil.rmtree(sdir, ignore_errors=True)
                store = VectorStore(sdir, dim=ecfg.model.out_dim,
                                    shard_size=ecfg.eval.store_shard_size,
                                    dtype="int8")
                eembedder.embed_corpus(etrainer.corpus, store)
                assert store.num_vectors == n_text, store.num_vectors
                import jax.numpy as jnp
                return jnp.zeros(())

            _stamp("warming int8 text-embed (q8 wire compile)")
            shutil.rmtree(sdir, ignore_errors=True)
            warm8 = VectorStore(sdir, dim=ecfg.model.out_dim,
                                shard_size=ecfg.eval.store_shard_size,
                                dtype="int8")
            eembedder.embed_corpus(etrainer.corpus, warm8,
                                   stop=ecfg.eval.store_shard_size)
            _stamp("int8 text-embed compiled; timing full 1M sweep")
            qdt = _best_time(_sweep_q8, 1)   # secondary datapoint: one rep
            q_pps = n_text / qdt / n_dev
            rec.update({
                "embed_from_text_int8_pages_per_sec_per_chip": round(
                    q_pps, 2),
                "embed_from_text_int8_vs_transport_ceiling": round(
                    min(q_pps / (2 * ceiling), 9.99), 4),  # 1 B/dim wire
            })
        except Exception as e:  # optional phase must never cost the round
            rec["embed_text_error"] = f"{type(e).__name__}: {e}"[:300]
        _emit(rec)

    # ---- mT5-base geometry sweep (config 5: d=768, L=12, seq 128) --------
    # Config 5's first perf datapoint (VERDICT r3 Missing #4) and the
    # cleanest test of whether the stack reaches high MFU when
    # matmul-bound (d=768 vs bert-mini's 256; see docs/MFU.md). The model
    # carries the TRUE 250,112-row mT5 embedding table; batches are
    # synthetic uniform token ids via Trainer's tokenizers hook — training
    # the 250k SentencePiece is ~115 s of host data prep (proven real by
    # tests/test_vocab_honesty.py), not step cost, and uniform ids make
    # the gather/scatter no cheaper than Zipfian text. Skippable via
    # BENCH_MT5=0; skipped off-TPU.
    if os.environ.get("BENCH_MT5", "1") != "0" and on_tpu:
        # one in-phase retry: the tunneled backend's remote_compile
        # transiently drops connections (~minutes-long mt5 compile is the
        # most exposed), and the wrapper only retries the WHOLE worker when
        # the REQUIRED metrics are missing — an optional-phase failure after
        # the primary record printed would otherwise be final
        for _mt5_attempt in range(2):
          try:
            import numpy as np

            _stamp(f"building mt5-base phase (synthetic-id batches, "
                   f"attempt {_mt5_attempt + 1})")
            m_batch = int(os.environ.get("BENCH_MT5_BATCH", "256")) * n_dev
            mcfg = get_config("mt5_multilingual", {
                "data.num_pages": max(2_048, m_batch),
                "train.batch_size": m_batch,
                "train.log_every": 1_000_000,
                "mesh.data": n_dev, "mesh.model": 1,
            })
            mvocab = mcfg.data.vocab_size          # config 5's true 250,112
            toks = (_SyntheticTok(mvocab, mcfg.data.query_len, 1),
                    _SyntheticTok(mvocab, mcfg.data.page_len, 2))
            mstate = mstep = mbatches = None
            try:
                mtrainer = Trainer(
                    mcfg, workdir="/tmp/dnn_page_vectors_tpu_bench_mt5",
                    tokenizers=toks)
                mstate = mtrainer.init_state()
                mstep = mtrainer.compiled_step(mstate)
                mit = iter(mtrainer.batches())
                mbatches = [next(mit) for _ in range(2)]
                mrng = mtrainer.base_rng()
                for i in range(2):
                    mstate, mm = mstep(mstate, mbatches[i % 2], mrng)
                hard_sync(mm)
                _stamp("mt5 step compiled; timing")
                msteps = int(os.environ.get("BENCH_MT5_STEPS", "12"))

                def _mt5_loop():
                    nonlocal mstate
                    for i in range(msteps):
                        mstate, mm = mstep(mstate, mbatches[i % 2], mrng)
                    return mm

                mdt = _best_time(_mt5_loop, opt_reps)
                mpps = m_batch * msteps / mdt / n_dev
                mflops = train_flops_per_pair(mcfg, m_batch)
                rec.update({
                    "mt5_train_pages_per_sec_per_chip": round(mpps, 2),
                    "mt5_train_mfu": (round(mpps * mflops / peak, 4)
                                      if peak else None),
                    "mt5_vocab_size": mvocab,
                    "mt5_model_dim": mcfg.model.model_dim,
                    **_roofline_keys("mt5_", mcfg, m_batch, mpps, peak,
                                     devs[0]),
                })
            finally:
                # free the multi-GB mt5 state even on failure, or the
                # long-context sweep below inherits an OOM-primed chip
                del mstate, mstep, mbatches
          except Exception as e:  # optional sweep must never cost the round
            rec["mt5_error"] = f"{type(e).__name__}: {e}"[:300]
            continue
          rec.pop("mt5_error", None)     # a retry succeeded: drop the error
          break
        _emit(rec)

    # ---- word-family sweep: kim_cnn + lstm at config-2 geometry ----------
    # Configs 1-2's first real-chip datapoints (VERDICT r4 Weak #5): the
    # Kim-CNN and BiLSTM encoders at config-2 per-chip geometry (batch
    # 512/chip, 100k-word vocab — BASELINE.json:8) with synthetic-id
    # batches (the 100k vocab over 1M pages is one-time host prep, not step
    # cost). cdssm is deliberately absent: config 1 is the single-process
    # CPU toy oracle (BASELINE.json:7), timed continuously by the e2e test
    # suite, not a TPU reference workload (docs/MFU.md). Skippable via
    # BENCH_WORD=0; skipped off-TPU.
    if os.environ.get("BENCH_WORD", "1") != "0" and on_tpu:
        for cname, key in (("kim_cnn_v5e8", "kim_cnn"),
                           ("lstm_words", "lstm")):
          # in-phase retry: the tunnel's remote_compile transiently drops
          # (see the mt5 phase) and optional phases never re-run otherwise
          for _w_attempt in range(2):
            try:
                _stamp(f"building {key} phase (synthetic-id batches, "
                       f"attempt {_w_attempt + 1})")
                # 2048/chip (round 11, was 512): the word-family step is
                # ~1 ms of analytic device work at 512 — far below the
                # per-dispatch floor of the tunneled backend, so the old
                # batch measured dispatch latency, not the encoder. The
                # per-model batch sizing puts enough work per step that
                # the MFU/roofline columns describe the model
                # (docs/MFU.md "word-family accounting fix").
                w_batch = int(os.environ.get("BENCH_WORD_BATCH",
                                             "2048")) * n_dev
                wcfg = get_config(cname, {
                    "data.num_pages": max(4_096, w_batch),
                    "train.batch_size": w_batch,
                    "train.log_every": 1_000_000,
                    "mesh.data": n_dev,
                })
                toks = (_SyntheticTok(wcfg.data.vocab_size,
                                      wcfg.data.query_len, 3),
                        _SyntheticTok(wcfg.data.vocab_size,
                                      wcfg.data.page_len, 4))
                wstate = wstep = wbatches = None
                try:
                    wtrainer = Trainer(
                        wcfg,
                        workdir=f"/tmp/dnn_page_vectors_tpu_bench_{key}",
                        tokenizers=toks)
                    wstate = wtrainer.init_state()
                    wstep = wtrainer.compiled_step(wstate)
                    wit = iter(wtrainer.batches())
                    wbatches = [next(wit) for _ in range(2)]
                    wrng = wtrainer.base_rng()
                    for i in range(2):
                        wstate, wm = wstep(wstate, wbatches[i % 2], wrng)
                    hard_sync(wm)
                    _stamp(f"{key} step compiled; timing")
                    wsteps = int(os.environ.get("BENCH_WORD_STEPS", "16"))

                    def _word_loop():
                        nonlocal wstate
                        for i in range(wsteps):
                            wstate, wm = wstep(wstate, wbatches[i % 2], wrng)
                        return wm

                    wdt = _best_time(_word_loop, opt_reps)
                    wpps = w_batch * wsteps / wdt / n_dev
                    wflops = train_flops_per_pair(wcfg, w_batch)
                    rec.update({
                        f"{key}_train_pages_per_sec_per_chip": round(wpps, 2),
                        f"{key}_train_mfu": (round(wpps * wflops / peak, 4)
                                             if peak else None),
                        f"{key}_batch_per_chip": w_batch // n_dev,
                        **_roofline_keys(f"{key}_", wcfg, w_batch, wpps,
                                         peak, devs[0]),
                    })
                finally:
                    del wstate, wstep, wbatches
            except Exception as e:  # optional sweep must never cost the round
                rec[f"{key}_error"] = f"{type(e).__name__}: {e}"[:300]
                continue
            rec.pop(f"{key}_error", None)
            break
        _emit(rec)

    # ---- long-context sweep (bert_long_sp geometry, Pallas flash) --------
    # Single chip can't form a seq ring, so the single-chip long-page path
    # is the flash kernel (fwd + custom-VJP bwd, O(L) HBM); SP is validated
    # by the driver's dryrun_multichip instead. Skippable via BENCH_LONG=0;
    # skipped off-TPU (interpret-mode Pallas at L=1024 is not a benchmark).
    if os.environ.get("BENCH_LONG", "1") == "0" or \
            getattr(devs[0], "platform", "") != "tpu":
        return
    # in-phase retry: see the mt5 phase (transient remote_compile drops)
    for _l_attempt in range(2):
      try:
        _stamp(f"building long-context trainer (L=1024, flash, "
               f"attempt {_l_attempt + 1})")
        lcfg = get_config("bert_long_sp", {
            "data.num_pages": 2_048,
            "data.vocab_size": 8_192,
            "model.attention": "flash",
            "train.batch_size": int(os.environ.get("BENCH_LONG_BATCH", "64")),
            "train.log_every": 1_000_000,
            "mesh.data": n_dev, "mesh.seq": 1,
        })
        ltrainer = Trainer(lcfg, workdir="/tmp/dnn_page_vectors_tpu_bench_long")
        lstate = ltrainer.init_state()
        lstep = ltrainer.compiled_step(lstate)
        lit = iter(ltrainer.batches())
        lbatches = [next(lit) for _ in range(2)]
        lrng = ltrainer.base_rng()
        for i in range(2):
            lstate, lm = lstep(lstate, lbatches[i % 2], lrng)
        hard_sync(lm)
        _stamp("long-context step compiled; timing")
        lsteps = int(os.environ.get("BENCH_LONG_STEPS", "24"))

        def _long_loop():
            nonlocal lstate
            for i in range(lsteps):
                lstate, lm = lstep(lstate, lbatches[i % 2], lrng)
            return lm

        ldt = _best_time(_long_loop, opt_reps)
        lpps = lcfg.train.batch_size * lsteps / ldt / n_dev
        lflops = train_flops_per_pair(lcfg, lcfg.train.batch_size)
        rec.update({
            "long_train_pages_per_sec_per_chip": round(lpps, 2),
            "long_train_mfu": (round(lpps * lflops / peak, 4)
                               if peak else None),
            "long_page_len": lcfg.data.page_len,
            **_roofline_keys("long_", lcfg, lcfg.train.batch_size, lpps,
                             peak, devs[0]),
        })
        del lstate, lstep, lbatches     # free HBM for the t5 variant

        # sequence-packing A/B at long geometry (round 11, BENCH_PACK=0
        # skips): see _long_pack for the protocol + accounting
        if os.environ.get("BENCH_PACK", "1") != "0":
            for _p_attempt in range(2):
                try:
                    _long_pack(rec, n_dev, peak, opt_reps, _best_time,
                               _stamp, devs[0])
                except Exception as e:
                    rec["long_pack_error"] = f"{type(e).__name__}: {e}"[:300]
                    continue
                rec.pop("long_pack_error", None)
                break

        # t5 long-context variant (round 4): the Pallas dbias backward
        # keeps the T5-biased flash path O(L) in training too, so long
        # multilingual pages get their first perf datapoint. Own
        # try/except + error key: a crash here keeps the bert-long numbers
        # above and is distinguishable from a bert-long failure.
        for _t_attempt in range(2):
            try:
                _long_t5(rec, n_dev, peak, lsteps, opt_reps, _best_time,
                         _stamp)
            except Exception as e:
                rec["long_t5_error"] = f"{type(e).__name__}: {e}"[:300]
                continue
            rec.pop("long_t5_error", None)
            break
      except Exception as e:  # optional sweep must never cost the round
        rec["long_error"] = f"{type(e).__name__}: {e}"[:300]
        continue
      rec.pop("long_error", None)
      break
    _emit(rec)


def _long_pack(rec, n_dev, peak, opt_reps, _best_time, _stamp,
               dev) -> None:
    """Sequence-packing A/B at bert_long_sp geometry (train.pack_pages,
    docs/MFU.md "packing accounting").

    The production long-page scenario: the program compiles ONE static
    [B, 1024] row shape, but real long-page corpora are mixed-length —
    short pages ride padded rows and the pad tokens burn full-row
    compute. Protocol: a corpus of ~230-word pages through the SAME
    flash bert-long model, (a) unpacked — each page padded to the 1024
    row, the pre-packing behavior — and (b) packed 4-per-row with the
    segment mask. Accounting: both runs report USEFUL-flops MFU (flops
    of the pages' actual tokens, measured from the batch, NOT the padded
    row), so the pad waste the unpacked run burns is visible instead of
    flattered; long_pack_mfu_gain is the packing win in those terms and
    long_pack_speedup the raw pages/sec ratio. The full-length-page
    long_train_mfu above is untouched (its rows have no pad to pack)."""
    import dataclasses as _dcp

    import numpy as _npp

    from dnn_page_vectors_tpu.config import get_config
    from dnn_page_vectors_tpu.data.toy import ToyCorpus
    from dnn_page_vectors_tpu.train.loop import Trainer
    from dnn_page_vectors_tpu.utils.flops import encoder_flops_per_example
    from dnn_page_vectors_tpu.utils.platform import hard_sync

    pack = int(os.environ.get("BENCH_PACK_PAGES", "4"))
    batch = int(os.environ.get("BENCH_LONG_BATCH", "64"))
    psteps = int(os.environ.get("BENCH_PACK_STEPS", "24"))
    base = get_config("bert_long_sp", {
        "data.num_pages": 2_048,
        "data.vocab_size": 8_192,
        "model.attention": "flash",
        "train.batch_size": batch,
        "train.log_every": 1_000_000,
        "mesh.data": n_dev, "mesh.seq": 1,
    })
    # ~215-word pages tokenize to ~243 wordpieces on the toy corpus
    # (~1.13 tokens/word measured), so 4 pages fit one 1024-token row
    # with headroom — pack=4 rows carry 4x the pages, no truncation
    corpus = ToyCorpus(num_pages=2_048, seed=0,
                       page_len=int(os.environ.get("BENCH_PACK_WORDS",
                                                   "215")),
                       query_len=32)
    results = {}
    for tag, p in (("nopack", 1), ("pack", pack)):
        cfg = base.replace(train=_dcp.replace(base.train, pack_pages=p))
        _stamp(f"long-pack phase: building {tag} trainer (pack={p})")
        tr = Trainer(cfg, corpus=corpus,
                     workdir="/tmp/dnn_page_vectors_tpu_bench_long_pack")
        state = tr.init_state()
        step = tr.compiled_step(state)
        it = iter(tr.batches())
        batches = [next(it) for _ in range(2)]
        rng = tr.base_rng()
        for i in range(2):
            state, m = step(state, batches[i % 2], rng)
        hard_sync(m)
        _stamp(f"long-pack {tag} compiled; timing")

        def _loop():
            nonlocal state
            for i in range(psteps):
                state, m = step(state, batches[i % 2], rng)
            return m

        pdt = _best_time(_loop, opt_reps)
        pps = batch * psteps / pdt / n_dev
        # useful flops: the pages' ACTUAL tokens (host-side, from batch 0)
        page_tok_count = int((_npp.asarray(batches[0]["page"]) != 0).sum())
        mean_tok = page_tok_count / batch
        useful = 3.0 * (
            encoder_flops_per_example(cfg.model, cfg.data.query_len)
            + encoder_flops_per_example(cfg.model, int(round(mean_tok)))
            + 2.0 * batch * cfg.model.out_dim)
        results[tag] = (pps, (pps * useful / peak) if peak else None,
                        mean_tok)
        del state, step, batches

    (np_pps, np_mfu, np_tok), (pk_pps, pk_mfu, pk_tok) = \
        results["nopack"], results["pack"]
    rec.update({
        "long_pack_pages": pack,
        "long_pack_mean_page_tokens": round(pk_tok, 1),
        "long_nopack_pages_per_sec_per_chip": round(np_pps, 2),
        "long_pack_pages_per_sec_per_chip": round(pk_pps, 2),
        "long_pack_speedup": round(pk_pps / np_pps, 3),
        "long_nopack_train_mfu": (round(np_mfu, 4)
                                  if np_mfu is not None else None),
        "long_pack_train_mfu": (round(pk_mfu, 4)
                                if pk_mfu is not None else None),
        "long_pack_mfu_gain": (round(pk_mfu / np_mfu, 3)
                               if np_mfu and pk_mfu else None),
    })
    _stamp(f"long-pack phase done: {np_pps:.0f} -> {pk_pps:.0f} "
           f"pages/s/chip ({pk_pps / np_pps:.2f}x via pack={pack})")


def _long_t5(rec, n_dev, peak, lsteps, opt_reps, _best_time, _stamp) -> None:
    import os

    from dnn_page_vectors_tpu.config import get_config
    from dnn_page_vectors_tpu.train.loop import Trainer
    from dnn_page_vectors_tpu.utils.flops import train_flops_per_pair
    from dnn_page_vectors_tpu.utils.platform import hard_sync

    _stamp("building long-context t5 variant (flash + rel bias)")
    tcfg = get_config("bert_long_sp", {
        "data.num_pages": 2_048,
        "data.vocab_size": 8_192,
        "model.encoder": "t5",
        "model.attention": "flash",
        "train.batch_size": int(os.environ.get("BENCH_LONG_BATCH", "64")),
        "train.log_every": 1_000_000,
        "mesh.data": n_dev, "mesh.seq": 1,
    })
    ttrainer = Trainer(tcfg,
                       workdir="/tmp/dnn_page_vectors_tpu_bench_long_t5")
    tstate = ttrainer.init_state()
    tstep = ttrainer.compiled_step(tstate)
    tit = iter(ttrainer.batches())
    tbatches = [next(tit) for _ in range(2)]
    trng = ttrainer.base_rng()
    for i in range(2):
        tstate, tm = tstep(tstate, tbatches[i % 2], trng)
    hard_sync(tm)
    _stamp("long-context t5 step compiled; timing")

    def _long_t5_loop():
        nonlocal tstate
        for i in range(lsteps):
            tstate, tm = tstep(tstate, tbatches[i % 2], trng)
        return tm

    tdt = _best_time(_long_t5_loop, opt_reps)
    tpps = tcfg.train.batch_size * lsteps / tdt / n_dev
    tflops = train_flops_per_pair(tcfg, tcfg.train.batch_size)
    rec.update({
        "long_t5_train_pages_per_sec_per_chip": round(tpps, 2),
        "long_t5_train_mfu": (round(tpps * tflops / peak, 4)
                              if peak else None),
    })


# ---------------------------------------------------------------------------
# Partitioned-serving phase (docs/SCALING.md "Partitioned serving").
#
# HOST-SIMULATED BY DESIGN: the scatter-gather's partition workers stand in
# for P serving hosts, so this phase runs on the CPU backend in its own
# subprocess — it produces real measured numbers even when the TPU is
# unreachable (the device phases stay null-honest), and on a TPU round its
# keys merge into the same record. Scaling is accounted the only honest way
# a one-box simulation of P hosts can be: each partition's local top-k runs
# SEQUENTIALLY and is timed individually, and the simulated per-query
# latency is the critical path max(partition seconds) + the measured merge
# fold (PartitionSet.simulate) — wall-clock thread concurrency on a shared
# core would measure the box, not the topology. Scan bytes per query are
# the critical-path partition's candidate payload, measured by the same
# accounting serving itself reports.
# ---------------------------------------------------------------------------

def run_partitioned_worker() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from dnn_page_vectors_tpu.config import get_config
    from dnn_page_vectors_tpu.infer.serve import SearchService
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore

    dim = int(os.environ.get("BENCH_PART_DIM", "64"))
    shard_rows = int(os.environ.get("BENCH_PART_SHARD_ROWS", "16384"))
    n_shards = int(os.environ.get("BENCH_PART_SHARDS", "8"))
    iters = int(os.environ.get("BENCH_PART_ITERS", "12"))
    kq = 10
    rows = shard_rows * n_shards
    _stamp(f"partitioned phase: building {rows}-row synthetic store "
           f"({n_shards} shards, dim {dim})")
    rng = np.random.default_rng(0)
    sdir = "/tmp/dnn_page_vectors_tpu_bench/part_store"
    import shutil
    shutil.rmtree(sdir, ignore_errors=True)
    store = VectorStore(sdir, dim=dim, shard_size=shard_rows)
    for si in range(n_shards):
        v = rng.standard_normal((shard_rows, dim)).astype(np.float32)
        v /= np.linalg.norm(v, axis=1, keepdims=True)
        store.write_shard(si, np.arange(si * shard_rows,
                                        (si + 1) * shard_rows,
                                        dtype=np.int64), v)
    store = VectorStore(sdir)

    class _MeshOnly:
        """The partitioned phase drives retrieval by pre-computed query
        vectors (SearchService.topk_vectors), so the embedder stub only
        needs the mesh — no model, tokenizer, or checkpoint."""

    emb = _MeshOnly()
    emb.mesh = Mesh(np.array(jax.devices("cpu")[:1]), ("data",))
    qv = rng.standard_normal((1, dim)).astype(np.float32)
    qv /= np.linalg.norm(qv, axis=1, keepdims=True)

    rec = {"partitioned_store_rows": rows, "partitioned_shards": n_shards,
           "partitioned_dim": dim, "partitioned_k": kq,
           "partitioned_iters": iters}
    # Build EVERY topology first, then INTERLEAVE the timed rounds: the
    # sandbox's shared-tenancy noise comes and goes on a minutes scale,
    # so measuring P=1 and P=4 in different minutes would let one slow
    # window misprice the scaling ratio — round-robin sampling puts every
    # topology under the same noise, and the MEDIAN critical path is the
    # robust per-topology estimator on top.
    combos = [(P, R) for P in (1, 2, 4) for R in (1, 2)]
    services = {}
    for P, R in combos:
        cfg = get_config("cdssm_toy", {
            "model.out_dim": dim, "serve.partitions": P,
            "serve.replicas": R})
        svc = SearchService(cfg, emb, None, store, preload_hbm_gb=4.0)
        pset = svc.partition_set
        extra = None
        if pset is None:
            # P=R=1: the single-view path IS the baseline — simulate
            # through a 1-partition set for identical accounting
            from dnn_page_vectors_tpu.infer.partition import PartitionSet
            extra = pset = PartitionSet(svc, store, partitions=1,
                                        replicas=1)
        pset.simulate(qv, 1, kq)               # warm: compile every shape
        services[(P, R)] = (svc, pset, extra)
    stats = {key: {"crit": [], "merge": [], "scan": 0, "ids": None}
             for key in combos}
    for _ in range(iters):
        for key in combos:
            sim = services[key][1].simulate(qv, 1, kq)
            st = stats[key]
            st["crit"].append(sim["critical_path_seconds"])
            st["merge"].append(sim["merge_seconds"])
            st["scan"] = max(sim["scan_bytes"])
            st["ids"] = sim["ids"]
    qps = {}
    scan = {}
    base_ids = stats[(1, 1)]["ids"]
    for P, R in combos:
        st = stats[(P, R)]
        if not np.array_equal(st["ids"], base_ids):
            rec["partitioned_identity_error"] = f"P={P} R={R}"
        # BEST critical path -> qps (the _best_time estimator the train/
        # embed phases use): shared-tenancy interference only ever ADDS
        # time, so min is the honest "what the topology can do" number;
        # the p99 key next to it reports the observed spread
        qps[(P, R)] = 1.0 / float(np.min(np.asarray(st["crit"])))
        scan[(P, R)] = st["scan"]
        rec[f"partitioned_qps_p{P}_r{R}"] = round(qps[(P, R)], 2)
        rec[f"partitioned_p99_ms_p{P}_r{R}"] = round(
            float(np.percentile(np.asarray(st["crit"]), 99)) * 1000.0, 3)
        rec[f"partitioned_scan_bytes_per_query_p{P}_r{R}"] = st["scan"]
        rec[f"partitioned_merge_ms_p{P}_r{R}"] = round(
            sum(st["merge"]) / len(st["merge"]) * 1000.0, 4)
        _stamp(f"partitioned P={P} R={R}: "
               f"{qps[(P, R)]:.1f} sim qps, "
               f"{st['scan']} scan B/query")
        svc, _, extra = services[(P, R)]
        if extra is not None:
            extra.close()
        svc.close()
    for P in (2, 4):
        rec[f"partitioned_scaling_efficiency_p{P}"] = round(
            qps[(P, 1)] / qps[(1, 1)] / P, 4)
    rec["partitioned_scan_bytes_ratio_p4"] = round(
        scan[(4, 1)] / max(scan[(1, 1)], 1), 4)

    # routing drill (fixed protocol, excluded from the gate): a restaging
    # primary sheds to its replica; a partition with EVERY replica
    # degraded serves degraded locally — results stay non-empty and
    # identical (the availability half of the acceptance criteria)
    cfg = get_config("cdssm_toy", {"model.out_dim": dim,
                                   "serve.partitions": 2,
                                   "serve.replicas": 2})
    svc = SearchService(cfg, emb, None, store, preload_hbm_gb=4.0)
    pset = svc.partition_set
    pset._parts[0][0].set_restaging(True)
    svc.topk_vectors(qv, k=kq)
    pset._parts[0][0].set_restaging(False)
    for rep in pset._parts[0]:
        rep.view.stream_entries = list(rep.view.entries)
        rep.view.shards = None
    _, ids = svc.topk_vectors(qv, k=kq)
    rec["partitioned_shed_drill_sheds"] = svc.replica_shed
    rec["partitioned_shed_drill_degraded_serves"] = \
        svc.partition_degraded_serves
    rec["partitioned_degraded_results_identical"] = bool(
        np.array_equal(ids, base_ids))
    svc.close()
    print(json.dumps(rec), flush=True)


def run_net_worker() -> None:
    """The `net_serve` phase (docs/SERVING.md "Network front end"),
    CPU-honest like the partitioned phase: a synthetic store served by
    the REAL network stack — asyncio front end over loopback, partition
    workers as genuine subprocesses behind the WorkerGateway — measured
    by the loadgen driver's qps@p99 search with the issue path crossing
    the socket. HONEST about cores: the P in {1, 2, 4} topology sweep
    runs only where P worker processes can genuinely parallelize
    (P <= detected cores, `BENCH_NET_CORES` overrides) — the PR-13
    flat-30-qps artifact came from pricing a 4-process fan-out on one
    core — with per-step scaling efficiency next to each measured qps.
    Wire-byte accounting is an explicit A/B: the same fixed request
    stream once with `serve.wire_compress` on (the headline
    `net_wire_bytes_per_query`) and once negotiated down to raw frames,
    with the ratio recorded (`net_wire_compression_ratio`). Drills:
    hedge fire rate (one deliberately slow replica) and deadline-shed
    rate under an over-budget burst."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import shutil

    import numpy as np

    import jax
    from jax.sharding import Mesh

    from dnn_page_vectors_tpu.config import get_config
    from dnn_page_vectors_tpu.infer.partition_host import (
        MeshEmbedder, WorkerGateway)
    from dnn_page_vectors_tpu.infer.serve import SearchService
    from dnn_page_vectors_tpu.infer.server import serve_in_background
    from dnn_page_vectors_tpu.infer.transport import (
        DeadlineExceeded, SocketSearchClient)
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore
    from dnn_page_vectors_tpu.loadgen import find_qps_at_p99, make_workload

    dim = int(os.environ.get("BENCH_NET_DIM", "64"))
    shard_rows = int(os.environ.get("BENCH_NET_SHARD_ROWS", "16384"))
    n_shards = int(os.environ.get("BENCH_NET_SHARDS", "8"))
    trial_s = float(os.environ.get("BENCH_NET_TRIAL_S", "1.5"))
    # the p99 target carries headroom for the 1-core sandbox, where P=4
    # worker PROCESSES serialize on one core under the front end — the
    # gate tracks the measured qps, the target is a protocol constant.
    # start_qps stays >= 16: below that, a short trial's rolling window
    # sees too few Poisson arrivals for the driver's open-loop sustain
    # check (achieved >= 0.8x offered) to be statistically meaningful
    p99_ms = float(os.environ.get("BENCH_NET_P99_MS", "200"))
    iters = int(os.environ.get("BENCH_NET_ITERS", "2"))
    start_qps = float(os.environ.get("BENCH_NET_START_QPS", "16"))
    # best-of-REPS qps@p99 searches per topology: the _best_time
    # estimator applied to the driver — shared-tenancy noise on this
    # box can sink ALL of one search's short trials, and best-of keeps
    # one bad minute from mispricing a topology
    reps = max(1, int(os.environ.get("BENCH_NET_REPS", "2")))
    # available cores gate the topology sweep: a P-process fan-out on
    # fewer than P cores measures scheduler overhead, not the fleet —
    # BENCH_NET_CORES overrides detection (containers/cgroup quotas the
    # affinity mask can't see)
    try:
        detected = len(os.sched_getaffinity(0))
    except AttributeError:
        detected = os.cpu_count() or 1
    cores = int(os.environ.get("BENCH_NET_CORES", "0") or 0) or detected
    kq = 10
    rows = shard_rows * n_shards
    wdir = "/tmp/dnn_page_vectors_tpu_bench/net"
    sdir = os.path.join(wdir, "store")
    _stamp(f"net phase: building {rows}-row synthetic store "
           f"({n_shards} shards, dim {dim})")
    rng = np.random.default_rng(0)
    shutil.rmtree(wdir, ignore_errors=True)
    store = VectorStore(sdir, dim=dim, shard_size=shard_rows)
    for si in range(n_shards):
        v = rng.standard_normal((shard_rows, dim)).astype(np.float32)
        v /= np.linalg.norm(v, axis=1, keepdims=True)
        store.write_shard(si, np.arange(si * shard_rows,
                                        (si + 1) * shard_rows,
                                        dtype=np.int64), v)
    store = VectorStore(sdir)
    mesh = Mesh(np.array(jax.devices("cpu")[:1]), ("data",))
    distinct = 32
    qvs = rng.standard_normal((distinct, dim)).astype(np.float32)
    qvs /= np.linalg.norm(qvs, axis=1, keepdims=True)
    qnames = [f"q{i}" for i in range(distinct)]
    qvec = {name: qvs[i:i + 1] for i, name in enumerate(qnames)}

    class _VecClient:
        """run_trial-compatible issue shim: query text -> its
        pre-computed vector over the T_VQUERY wire path."""

        def __init__(self, client):
            self._client = client

        def search(self, query, k=None, nprobe=None):
            return self._client.topk_vectors(qvec[query], k=k,
                                             nprobe=nprobe)

    def _spawn_workers(gw, P, R=1, slow_rids=(), slow_ms=0, connect=None):
        procs = []
        for wp in range(P):
            for wr in range(R):
                env = dict(os.environ, JAX_PLATFORMS="cpu")
                if wr in slow_rids:
                    env["DPV_WORKER_SLOW_MS"] = str(slow_ms)
                procs.append(subprocess.Popen(
                    [sys.executable, "-m", "dnn_page_vectors_tpu.cli",
                     "partition-worker", "--config", "cdssm_toy",
                     "--workdir", wdir,
                     "--set", f"model.out_dim={dim}",
                     "--connect", connect or f"{gw.host}:{gw.port}",
                     "--partition", str(wp), "--partitions", str(P),
                     "--replica", str(wr)],
                    cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
                    env=env, stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL))
        return procs

    rec = {"net_store_rows": rows, "net_shards": n_shards, "net_dim": dim,
           "net_k": kq, "net_p99_target_ms": p99_ms, "net_cores": cores}
    wl = make_workload("poisson", seed=0, distinct=distinct,
                       profile=((kq, None, 1.0),))
    sweep = [P for P in (1, 2, 4) if P <= cores] or [1]
    if len(sweep) < 3:
        _stamp(f"net: {cores} core(s) — sweeping only P={sweep} (a "
               "P-process fan-out beyond the core count would measure "
               "scheduler overhead, not scaling)")
    qps_by_p = {}
    for P in sweep:
        cfg = get_config("cdssm_toy", {
            "model.out_dim": dim,
            # window == trial duration: each trial's p99 reads its OWN
            # window, not the previous trial's load (the slo-phase
            # discipline)
            "obs.window_s": trial_s,
            "serve.partitions": P, "serve.replicas": 1})
        svc = SearchService(cfg, MeshEmbedder(mesh), None, store,
                            preload_hbm_gb=4.0)
        gw = WorkerGateway(svc, heartbeat_s=0.5)
        svc.attach_gateway(gw)
        procs = _spawn_workers(gw, P)
        up = gw.wait_for_workers(P, timeout_s=60.0)
        srv = serve_in_background(svc)
        client = _VecClient(SocketSearchClient(srv.host, srv.port))
        try:
            client.search(qnames[0], k=kq)     # warm every compiled shape
            _stamp(f"net P={P}: workers_up={up}; searching qps @ "
                   f"p99<{p99_ms:.0f}ms over loopback (best of {reps})")
            best, n_trials = 0.0, 0
            for _ in range(reps):
                rep = find_qps_at_p99(
                    svc, wl, qnames, p99_target_ms=p99_ms,
                    start=start_qps, iters=iters, duration_s=trial_s,
                    warmup_s=0.5, workers=16, client=client)
                best = max(best, rep["qps_at_p99"])
                n_trials += len(rep["trials"])
            rec[f"net_qps_at_p99_p{P}"] = round(best, 2)
            qps_by_p[P] = best
            _stamp(f"net P={P}: {best:.1f} qps @ "
                   f"p99<{p99_ms:.0f}ms ({n_trials} trials)")
        finally:
            client._client.close()
            srv.close()
            for pr in procs:
                pr.terminate()
            for pr in procs:
                try:
                    pr.wait(timeout=10)
                except Exception:  # noqa: BLE001
                    pr.kill()
            gw.close()
            svc.close()
    # scaling efficiency: measured qps at P over P x the 1-partition
    # qps — only for topologies that actually ran on enough cores
    if qps_by_p.get(1):
        for P in (2, 4):
            if qps_by_p.get(P):
                rec[f"net_scaling_eff_p{P}"] = round(
                    qps_by_p[P] / (P * qps_by_p[1]), 4)

    # multi-front-end sweep (docs/SCALING.md "Scale-out tier"): N
    # listeners + N gateways over ONE shared worker that registers with
    # all of them, priced as one unit through the driver's seeded
    # balancer. fe1 IS the P=1 single-front-end number measured above
    # (same topology, already best-of-reps); fe2 runs only where a
    # second front end has a core to run on (BENCH_NET_CORES honored —
    # two front ends on one core measure the scheduler, not the tier).
    if rec.get("net_qps_at_p99_p1") is not None:
        rec["net_qps_at_p99_fe1"] = rec["net_qps_at_p99_p1"]
    if cores >= 2 and os.environ.get("BENCH_FE", "1") != "0":
        from dnn_page_vectors_tpu.loadgen import BalancedClient
        fe_n = 2
        cfg = get_config("cdssm_toy", {
            "model.out_dim": dim, "obs.window_s": trial_s,
            "serve.partitions": 1, "serve.replicas": 1})
        fe_svcs, fe_gws, fe_srvs, fe_clients = [], [], [], []
        for _ in range(fe_n):
            fsvc = SearchService(cfg, MeshEmbedder(mesh), None, store,
                                 preload_hbm_gb=4.0)
            fgw = WorkerGateway(fsvc, heartbeat_s=0.5)
            fsvc.attach_gateway(fgw)
            fe_svcs.append(fsvc)
            fe_gws.append(fgw)
        connect = ",".join(f"{g.host}:{g.port}" for g in fe_gws)
        procs = _spawn_workers(fe_gws[0], 1, connect=connect)
        up = all(g.wait_for_workers(1, timeout_s=60.0) for g in fe_gws)
        for fe_i, fsvc in enumerate(fe_svcs):
            srv = serve_in_background(fsvc, front_end=fe_i)
            fe_srvs.append(srv)
            fe_clients.append(SocketSearchClient(srv.host, srv.port))
        bal = BalancedClient([_VecClient(c) for c in fe_clients],
                             policy="round_robin", seed=0)
        try:
            for c in fe_clients:                 # warm EVERY front end
                _VecClient(c).search(qnames[0], k=kq)
            _stamp(f"net FE={fe_n}: workers_up={up}; searching tier "
                   f"qps @ p99<{p99_ms:.0f}ms (best of {reps})")
            best, n_trials = 0.0, 0
            for _ in range(reps):
                rep = find_qps_at_p99(
                    fe_svcs[0], wl, qnames, p99_target_ms=p99_ms,
                    start=start_qps, iters=iters, duration_s=trial_s,
                    warmup_s=0.5, workers=16, client=bal,
                    front_ends=fe_svcs)
                best = max(best, rep["qps_at_p99"])
                n_trials += len(rep["trials"])
            rec[f"net_qps_at_p99_fe{fe_n}"] = round(best, 2)
            rec["net_front_ends"] = fe_n
            _stamp(f"net FE={fe_n}: {best:.1f} qps @ "
                   f"p99<{p99_ms:.0f}ms ({n_trials} trials)")
        finally:
            for c in fe_clients:
                c.close()
            for srv in fe_srvs:
                srv.close()
            for pr in procs:
                pr.terminate()
            for pr in procs:
                try:
                    pr.wait(timeout=10)
                except Exception:  # noqa: BLE001
                    pr.kill()
            for g in fe_gws:
                g.close()
            for fsvc in fe_svcs:
                fsvc.close()

    # wire-byte A/B (the compression headline): the SAME fixed request
    # stream over the full stack — client edge + worker RPC hop — once
    # with wire compression negotiated and once forced to raw frames.
    # A fixed count (not a qps search) so both arms move identical
    # traffic and the ratio is load-independent.
    # probe length trades time for steady-state honesty: the first send
    # of each distinct query block is a full PUT, so too few requests
    # over-weigh the intern warm-up against the REF steady state
    probe_p = 2 if cores >= 2 else 1
    probe_n = int(os.environ.get("BENCH_NET_PROBE_N", "400"))
    wire_ab = {}
    for label, compress in (("", True), ("_raw", False)):
        cfg = get_config("cdssm_toy", {
            "model.out_dim": dim, "serve.partitions": probe_p,
            "serve.wire_compress": compress})
        svc = SearchService(cfg, MeshEmbedder(mesh), None, store,
                            preload_hbm_gb=4.0)
        gw = WorkerGateway(svc, heartbeat_s=0.5)
        svc.attach_gateway(gw)
        procs = _spawn_workers(gw, probe_p)
        up = gw.wait_for_workers(probe_p, timeout_s=60.0)
        srv = serve_in_background(svc)
        sclient = SocketSearchClient(srv.host, srv.port,
                                     compress=compress)
        try:
            sclient.topk_vectors(qvs[:1], k=kq)          # warm compiles
            wire0 = svc.wire_bytes
            for i in range(probe_n):
                sclient.topk_vectors(qvs[i % distinct: i % distinct + 1],
                                     k=kq)
            wire_ab[label] = (svc.wire_bytes - wire0) / probe_n
            rec[f"net_wire_bytes_per_query{label}"] = round(
                wire_ab[label], 1)
        finally:
            sclient.close()
            srv.close()
            for pr in procs:
                pr.terminate()
            for pr in procs:
                try:
                    pr.wait(timeout=10)
                except Exception:  # noqa: BLE001
                    pr.kill()
            gw.close()
            svc.close()
    if wire_ab.get("") and wire_ab.get("_raw"):
        rec["net_wire_compression_ratio"] = round(
            wire_ab["_raw"] / wire_ab[""], 3)
        _stamp(f"net wire A/B (P={probe_p}, workers_up={up}): "
               f"{wire_ab['_raw']:.0f} raw -> {wire_ab['']:.0f} "
               f"compressed bytes/query "
               f"(x{rec['net_wire_compression_ratio']:.2f})")

    # hedge drill: P=1, R=2 over real loopback sockets (thread workers —
    # their slow_ms is mutable, which the drill needs: the latency
    # history warms on a HEALTHY primary, then the primary turns slow
    # and the fan-out must hedge to the fast sibling at the warmed
    # quantile point)
    import threading as _threading

    from dnn_page_vectors_tpu.infer.partition_host import PartitionWorker
    cfg = get_config("cdssm_toy", {
        "model.out_dim": dim, "serve.partitions": 1, "serve.replicas": 2,
        "serve.hedge_quantile": 0.9})
    svc = SearchService(cfg, MeshEmbedder(mesh), None, store,
                        preload_hbm_gb=4.0)
    gw = WorkerGateway(svc, heartbeat_s=0.5)
    svc.attach_gateway(gw)
    tworkers = []
    for wr in range(2):
        w = PartitionWorker(cfg, sdir, ("127.0.0.1", gw.port), partition=0,
                            partitions=1, replica=wr, mesh=mesh)
        _threading.Thread(target=w.run, daemon=True).start()
        tworkers.append(w)
    gw.wait_for_workers(2, timeout_s=60.0)
    try:
        for i in range(12):                    # warm the latency history
            svc.topk_vectors(qvs[i % distinct: i % distinct + 1], k=kq)
        tworkers[0].slow_ms = 40.0             # the primary goes slow
        h0, n_drill = svc.hedge_fires, 30
        t0 = time.perf_counter()
        for i in range(n_drill):
            svc.topk_vectors(qvs[i % distinct: i % distinct + 1], k=kq)
        drill_ms = (time.perf_counter() - t0) / n_drill * 1000.0
        rec["net_hedge_fire_rate"] = round(
            (svc.hedge_fires - h0) / n_drill, 4)
        rec["net_hedged_latency_ms"] = round(drill_ms, 3)
        _stamp(f"net hedge drill: fire rate "
               f"{rec['net_hedge_fire_rate']:.2f}, "
               f"{drill_ms:.1f} ms/query against a 40 ms-slow primary")
    finally:
        for w in tworkers:
            w.stop()
        gw.close()
        svc.close()

    # deadline-shed drill: a burst of requests whose budget is smaller
    # than the socket->executor hop itself — admission finds them
    # EXPIRED at the door and sheds (T_SHED), never errors
    cfg = get_config("cdssm_toy", {"model.out_dim": dim})
    svc = SearchService(cfg, MeshEmbedder(mesh), None, store,
                        preload_hbm_gb=4.0)
    srv = serve_in_background(svc)
    vclient = SocketSearchClient(srv.host, srv.port)
    try:
        vclient.topk_vectors(qvs[:1], k=kq)    # warm: compile off-drill
        sheds0 = svc.deadline_sheds
        errors = 0
        n_burst, shed_seen = 200, 0
        for i in range(n_burst):
            try:
                vclient.topk_vectors(qvs[i % distinct: i % distinct + 1],
                                     k=kq, deadline_ms=0.05)
            except DeadlineExceeded:
                shed_seen += 1
            except Exception:  # noqa: BLE001 — drill metric, not fatal
                errors += 1
        rec["net_deadline_shed_rate"] = round(
            max(svc.deadline_sheds - sheds0, shed_seen) / n_burst, 4)
        rec["net_deadline_drill_errors"] = errors
        _stamp(f"net deadline drill: shed rate "
               f"{rec['net_deadline_shed_rate']:.2f} at a 0.05 ms budget "
               f"({errors} errors)")
    finally:
        vclient.close()
        srv.close()
        svc.close()

    # resize_serve drill (docs/SCALING.md "Scale-out tier";
    # BENCH_RESIZE=0 skips): elastic membership priced under fire. A
    # second worker JOINS mid-hammer, the gateway re-splits the
    # partition map live (fleet_resplit) and hands off through the
    # generation-gated REFRESH barrier. Headline numbers: the qps dip
    # depth while the handoff runs (resize_qps_dip_pct) and the seconds
    # from join until the whole fleet serves the new split
    # (resize_recovery_seconds; acceptance pin <= 3x the heartbeat).
    # Hard pins: zero errors, zero mixed-split result sets — every
    # answer must stay byte-identical to the pre-attach oracle THROUGH
    # the re-split (a mixed-split merge would break identity and counts
    # as an error).
    if os.environ.get("BENCH_RESIZE", "1") != "0":
        import threading as _rthreading

        from dnn_page_vectors_tpu.infer.partition_host import (
            PartitionWorker as _RWorker)
        hb_s = 0.25
        cfg = get_config("cdssm_toy", {
            "model.out_dim": dim, "serve.partitions": 1,
            "serve.replicas": 1, "serve.elastic": True,
            "serve.heartbeat_s": hb_s})
        svc = SearchService(cfg, MeshEmbedder(mesh), None, store,
                            preload_hbm_gb=4.0)
        # the oracle: in-process answers BEFORE any gateway attaches —
        # both splits must reproduce these exactly
        oracle = [svc.topk_vectors(qvs[i:i + 1], k=kq)
                  for i in range(distinct)]
        gw = WorkerGateway(svc, heartbeat_s=hb_s)
        svc.attach_gateway(gw)
        w0 = _RWorker(cfg, sdir, ("127.0.0.1", gw.port), partition=0,
                      partitions=1, replica=0, mesh=mesh)
        _rthreading.Thread(target=w0.run, daemon=True).start()
        gw.wait_for_workers(1, timeout_s=60.0)
        joiner = None
        errors = 0
        stamps = []
        try:
            svc.topk_vectors(qvs[:1], k=kq)      # warm over the wire
            n_hammer = int(os.environ.get("BENCH_RESIZE_N", "1200"))
            join_at = n_hammer // 3
            resplits0 = len(svc.registry.events("fleet_resplit"))
            t_join = recovery = None
            for i in range(n_hammer):
                if i == join_at:
                    joiner = _RWorker(cfg, sdir, ("127.0.0.1", gw.port),
                                      partition=1, partitions=2,
                                      replica=0, mesh=mesh)
                    _rthreading.Thread(target=joiner.run,
                                       daemon=True).start()
                    t_join = time.perf_counter()
                qi = i % distinct
                try:
                    s, ids2 = svc.topk_vectors(qvs[qi:qi + 1], k=kq)
                    osc, oid = oracle[qi]
                    if not (np.array_equal(s, osc)
                            and np.array_equal(ids2, oid)):
                        errors += 1   # mixed-split bytes land here
                except Exception:  # noqa: BLE001 — drill metric
                    errors += 1
                stamps.append(time.perf_counter())
                if t_join is not None and recovery is None:
                    table = gw.partition_set._view_table
                    if (len(svc.registry.events("fleet_resplit"))
                            > resplits0 and len(table) == 2
                            and len(gw.live_workers()) == 2
                            and gw.stale_workers(
                                table[0][0].generation, split=2) == 0):
                        recovery = time.perf_counter() - t_join
            # qps trajectory from completion stamps: baseline = median
            # pre-join bucket, dip = slowest bucket in the 3 s after
            bucket_s = 0.5
            t0b = stamps[0]
            counts: dict = {}
            for t in stamps:
                b = int((t - t0b) / bucket_s)
                counts[b] = counts.get(b, 0) + 1
            pre = sorted(c / bucket_s for b, c in counts.items()
                         if t0b + (b + 1) * bucket_s <= t_join)
            post = [c / bucket_s for b, c in counts.items()
                    if t_join <= t0b + b * bucket_s <= t_join + 3.0]
            baseline = pre[len(pre) // 2] if pre else 0.0
            dip = min(post) if post else baseline
            rec["resize_baseline_qps"] = round(baseline, 1)
            rec["resize_qps_dip_pct"] = round(
                max(0.0, (baseline - dip) / baseline * 100.0)
                if baseline else 0.0, 2)
            rec["resize_recovery_seconds"] = round(
                recovery if recovery is not None else 999.0, 3)
            rec["resize_errors"] = errors
            rec["resize_hammer_n"] = n_hammer
            rec["resize_heartbeat_s"] = hb_s
            _stamp(f"net resize drill: dip "
                   f"{rec['resize_qps_dip_pct']:.1f}% off a "
                   f"{baseline:.0f} qps baseline, recovery "
                   f"{rec['resize_recovery_seconds']:.3f}s (pin <= "
                   f"{3 * hb_s:.2f}s), {errors} errors")
        finally:
            if joiner is not None:
                joiner.stop()
            w0.stop()
            gw.close()
            svc.close()

    # chaos_serve drill (docs/ROBUSTNESS.md "Availability drills";
    # BENCH_CHAOS=0 skips): the self-healing pin priced on real loopback
    # sockets. Phase 1 — tear the sole worker's connection under a query
    # hammer and time kill -> rejoined + live again
    # (chaos_recovery_seconds; the acceptance pin is <= 3x the heartbeat
    # interval). Phase 2 — a seeded wire-fault schedule (torn frames,
    # dup frames, drops, stalls) fires under the hammer; every answer
    # must stay byte-identical to the in-process oracle
    # (chaos_availability = answered/offered, chaos_errors pinned 0 —
    # a mismatch counts as an error).
    if os.environ.get("BENCH_CHAOS", "1") != "0":
        from dnn_page_vectors_tpu.utils import faults as _faults
        hb_s = 0.25
        cfg = get_config("cdssm_toy", {
            "model.out_dim": dim, "serve.partitions": 1,
            "serve.replicas": 1, "serve.heartbeat_s": hb_s})
        svc = SearchService(cfg, MeshEmbedder(mesh), None, store,
                            preload_hbm_gb=4.0)
        # the never-faulted oracle: in-process answers BEFORE any
        # gateway attaches — the wire must reproduce these exactly
        oracle = [svc.topk_vectors(qvs[i:i + 1], k=kq)
                  for i in range(distinct)]
        gw = WorkerGateway(svc, heartbeat_s=hb_s)
        svc.attach_gateway(gw)
        w = PartitionWorker(cfg, sdir, ("127.0.0.1", gw.port), partition=0,
                            partitions=1, replica=0, mesh=mesh)
        _threading.Thread(target=w.run, daemon=True).start()
        gw.wait_for_workers(1, timeout_s=60.0)
        offered = answered = errors = sheds = 0

        def _hammer_one(qi: int):
            nonlocal offered, answered, errors, sheds
            offered += 1
            try:
                s, ids2 = svc.topk_vectors(qvs[qi:qi + 1], k=kq)
            except DeadlineExceeded:
                sheds += 1
                offered -= 1          # sheds excluded from availability
                return
            except Exception:  # noqa: BLE001 — drill metric, not fatal
                errors += 1
                return
            osc, oid = oracle[qi]
            if np.array_equal(s, osc) and np.array_equal(ids2, oid):
                answered += 1
            else:
                errors += 1           # wrong bytes are worse than none
        try:
            svc.topk_vectors(qvs[:1], k=kq)    # warm over the wire
            rejoined0 = len(svc.registry.events("worker_rejoined"))
            t_kill = time.perf_counter()
            w.kill_connection()
            recovery = None
            qi = 0
            while time.perf_counter() - t_kill < 30.0:
                _hammer_one(qi % distinct)     # fallback serves the gap
                qi += 1
                if (len(svc.registry.events("worker_rejoined")) > rejoined0
                        and gw.worker_alive(0, 0)):
                    recovery = time.perf_counter() - t_kill
                    break
            rec["chaos_recovery_seconds"] = round(
                recovery if recovery is not None else 999.0, 3)
            _faults.install(_faults.FaultPlan.parse(
                "wire_send:frame_trunc:40,wire_recv:frame_delay:30,"
                "wire_send:frame_dup:90,wire_send:conn_drop:140", seed=0))
            n_chaos = int(os.environ.get("BENCH_CHAOS_N", "150"))
            for i in range(n_chaos):
                _hammer_one(i % distinct)
            injected = sum(v for key, v in _faults.counters().items()
                           if key.startswith("injected_"))
            rec["chaos_availability"] = round(
                answered / max(offered, 1), 4)
            rec["chaos_errors"] = errors
            _stamp(f"net chaos drill: recovery "
                   f"{rec['chaos_recovery_seconds']:.3f}s (pin <= "
                   f"{3 * hb_s:.2f}s), availability "
                   f"{rec['chaos_availability']:.4f} over {offered} "
                   f"offered ({injected} faults injected, {errors} "
                   f"errors, {sheds} sheds)")
        finally:
            _faults.reset()
            w.stop()
            gw.close()
            svc.close()
    print(json.dumps(rec), flush=True)


def run_cache_worker() -> None:
    """cache_serve phase: CPU-honest A/B of the generation-keyed result
    cache on the Zipfian head. The SAME synthetic store and the SAME
    Zipf-mix workload are priced twice through the real serving path —
    once with `serve.result_cache` on (a hit short-circuits BEFORE the
    request consumes a micro-batch slot) and once off — reported as
    qps@p99 per arm plus the measured hit rate and the per-hit serve
    cost. The embed hop is stubbed to a deterministic name->vector map:
    the result cache keys on query TEXT, and what this phase prices is
    everything after the key (probe, skipped top-k, format) — the off
    arm still pays the full scan, so the ratio isolates the cache."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import shutil

    import numpy as np

    import jax
    from jax.sharding import Mesh

    from dnn_page_vectors_tpu.config import get_config
    from dnn_page_vectors_tpu.infer.partition_host import MeshEmbedder
    from dnn_page_vectors_tpu.infer.serve import SearchService
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore
    from dnn_page_vectors_tpu.loadgen import find_qps_at_p99, make_workload

    dim = int(os.environ.get("BENCH_CACHE_DIM", "64"))
    shard_rows = int(os.environ.get("BENCH_CACHE_SHARD_ROWS", "16384"))
    n_shards = int(os.environ.get("BENCH_CACHE_SHARDS", "4"))
    trial_s = float(os.environ.get("BENCH_CACHE_TRIAL_S", "1.5"))
    p99_ms = float(os.environ.get("BENCH_CACHE_P99_MS", "200"))
    iters = int(os.environ.get("BENCH_CACHE_ITERS", "2"))
    start_qps = float(os.environ.get("BENCH_CACHE_START_QPS", "16"))
    reps = max(1, int(os.environ.get("BENCH_CACHE_REPS", "2")))
    # 32 distinct queries under the workload's Zipfian repeat profile:
    # small enough that the head fits the default cache, large enough
    # that the off arm can't live off the embed LRU alone
    distinct = int(os.environ.get("BENCH_CACHE_DISTINCT", "32"))
    kq = 10
    rows = shard_rows * n_shards
    wdir = "/tmp/dnn_page_vectors_tpu_bench/cache"
    sdir = os.path.join(wdir, "store")
    _stamp(f"cache phase: building {rows}-row synthetic store "
           f"({n_shards} shards, dim {dim})")
    rng = np.random.default_rng(0)
    shutil.rmtree(wdir, ignore_errors=True)
    store = VectorStore(sdir, dim=dim, shard_size=shard_rows)
    for si in range(n_shards):
        v = rng.standard_normal((shard_rows, dim)).astype(np.float32)
        v /= np.linalg.norm(v, axis=1, keepdims=True)
        store.write_shard(si, np.arange(si * shard_rows,
                                        (si + 1) * shard_rows,
                                        dtype=np.int64), v)
    store = VectorStore(sdir)
    mesh = Mesh(np.array(jax.devices("cpu")[:1]), ("data",))
    qvs = rng.standard_normal((distinct, dim)).astype(np.float32)
    qvs /= np.linalg.norm(qvs, axis=1, keepdims=True)
    qnames = [f"q{i}" for i in range(distinct)]
    qvec = {name: qvs[i:i + 1] for i, name in enumerate(qnames)}

    def _stub_embed(queries):
        return np.concatenate([qvec[q] for q in queries], axis=0)

    class _StubCorpus:
        def page_text(self, i):
            return f"page {i}"

    rec = {"cache_store_rows": rows, "cache_dim": dim, "cache_k": kq,
           "cache_distinct": distinct}
    wl = make_workload("poisson", seed=0, distinct=distinct,
                       profile=((kq, None, 1.0),))
    qps = {}
    for label, on in (("on", True), ("off", False)):
        cfg = get_config("cdssm_toy", {
            "model.out_dim": dim,
            # window == trial duration: each trial's p99 reads its OWN
            # window (the slo-phase discipline)
            "obs.window_s": trial_s,
            "serve.result_cache": on})
        svc = SearchService(cfg, MeshEmbedder(mesh), None, store,
                            preload_hbm_gb=4.0)
        svc._embed_queries_cached = _stub_embed
        svc.corpus = _StubCorpus()
        try:
            svc.search(qnames[0], k=kq)        # warm every compiled shape
            _stamp(f"cache arm={label}: searching qps @ "
                   f"p99<{p99_ms:.0f}ms (best of {reps})")
            best, n_trials = 0.0, 0
            for _ in range(reps):
                rep = find_qps_at_p99(
                    svc, wl, qnames, p99_target_ms=p99_ms,
                    start=start_qps, iters=iters, duration_s=trial_s,
                    warmup_s=0.5, workers=16)
                best = max(best, rep["qps_at_p99"])
                n_trials += len(rep["trials"])
            qps[label] = best
            rec[f"cache_serve_qps_at_p99_{label}"] = round(best, 2)
            _stamp(f"cache arm={label}: {best:.1f} qps @ "
                   f"p99<{p99_ms:.0f}ms ({n_trials} trials)")
            if on:
                met = svc.metrics().get("result_cache") or {}
                hits = int(met.get("hits") or 0)
                misses = int(met.get("misses") or 0)
                if hits + misses:
                    rec["cache_hit_rate"] = round(
                        hits / (hits + misses), 4)
                rec["cache_entries"] = int(met.get("entries") or 0)
                # per-hit serve cost: one resident key hammered on a
                # quiet service — the probe+copy path alone, no scan
                svc.search(qnames[0], k=kq)
                n_hot = 2000
                t0 = time.perf_counter()
                for _ in range(n_hot):
                    svc.search(qnames[0], k=kq)
                rec["cache_serve_us_per_hit"] = round(
                    (time.perf_counter() - t0) / n_hot * 1e6, 2)
        finally:
            svc.close()
    if qps.get("on") and qps.get("off"):
        rec["cache_serve_speedup"] = round(qps["on"] / qps["off"], 3)
        _stamp(f"cache A/B: x{rec['cache_serve_speedup']:.2f} qps@p99 "
               f"with the result cache on (hit rate "
               f"{rec.get('cache_hit_rate', 0):.2f})")
    print(json.dumps(rec), flush=True)


def run_filtered_worker() -> None:
    """filtered_serve phase: CPU-honest pricing of predicate-filtered
    retrieval (docs/ANN.md "Filtered retrieval"). A synthetic store is
    built with a packed attribute word per row laid out so three
    predicates hit fixed selectivities — `lang==0` keeps 1/2 the rows
    (s50), `site in {0}` keeps 1/10 (s10), `recency>=3` keeps 1/100
    (s1). Each arm plus the unfiltered baseline is priced through the
    real serving path (find_qps_at_p99 over a 100%%-filtered workload
    mix), and the exact filtered scan's per-query byte count is recorded
    per arm: the s10 arm's bytes-vs-unfiltered ratio is the <=0.3x
    acceptance gate. An IVF index over the same store prices the
    predicate-intersected posting path: recall@10 vs the exact
    post-filter oracle at each selectivity (the >=0.95 contract)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import shutil

    import numpy as np

    import jax
    from jax.sharding import Mesh

    from dnn_page_vectors_tpu.config import get_config
    from dnn_page_vectors_tpu.index import attrs as attrs_mod
    from dnn_page_vectors_tpu.index.ivf import IVFIndex
    from dnn_page_vectors_tpu.infer.partition_host import MeshEmbedder
    from dnn_page_vectors_tpu.infer.serve import SearchService
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore
    from dnn_page_vectors_tpu.loadgen import find_qps_at_p99, make_workload

    dim = int(os.environ.get("BENCH_FILTERED_DIM", "64"))
    shard_rows = int(os.environ.get("BENCH_FILTERED_SHARD_ROWS", "16384"))
    n_shards = int(os.environ.get("BENCH_FILTERED_SHARDS", "4"))
    trial_s = float(os.environ.get("BENCH_FILTERED_TRIAL_S", "1.5"))
    p99_ms = float(os.environ.get("BENCH_FILTERED_P99_MS", "200"))
    iters = int(os.environ.get("BENCH_FILTERED_ITERS", "2"))
    start_qps = float(os.environ.get("BENCH_FILTERED_START_QPS", "16"))
    reps = max(1, int(os.environ.get("BENCH_FILTERED_REPS", "2")))
    distinct = int(os.environ.get("BENCH_FILTERED_DISTINCT", "32"))
    kq = 10
    rows = shard_rows * n_shards
    wdir = "/tmp/dnn_page_vectors_tpu_bench/filtered"
    sdir = os.path.join(wdir, "store")
    _stamp(f"filtered phase: building {rows}-row attributed store "
           f"({n_shards} shards, dim {dim})")
    rng = np.random.default_rng(0)
    shutil.rmtree(wdir, ignore_errors=True)
    store = VectorStore(sdir, dim=dim, shard_size=shard_rows)
    store.init_attrs()
    all_ids = np.arange(rows, dtype=np.int64)
    # deterministic attribute layout -> pinned selectivities (see docstring)
    words = attrs_mod.pack_words(
        lang=(all_ids % 2).astype(np.uint32),
        site=(all_ids % 10).astype(np.uint32),
        recency=np.where(all_ids % 100 == 0, 3, 0).astype(np.uint32))
    for si in range(n_shards):
        lo, hi = si * shard_rows, (si + 1) * shard_rows
        v = rng.standard_normal((shard_rows, dim)).astype(np.float32)
        v /= np.linalg.norm(v, axis=1, keepdims=True)
        store.write_shard(si, all_ids[lo:hi], v, attrs=words[lo:hi])
    store = VectorStore(sdir)
    mesh = Mesh(np.array(jax.devices("cpu")[:1]), ("data",))
    qvs = rng.standard_normal((distinct, dim)).astype(np.float32)
    qvs /= np.linalg.norm(qvs, axis=1, keepdims=True)
    qnames = [f"q{i}" for i in range(distinct)]
    qvec = {name: qvs[i:i + 1] for i, name in enumerate(qnames)}

    def _stub_embed(queries):
        return np.concatenate([qvec[q] for q in queries], axis=0)

    class _StubCorpus:
        def page_text(self, i):
            return f"page {i}"

    rec = {"filtered_store_rows": rows, "filtered_dim": dim,
           "filtered_k": kq, "filtered_distinct": distinct}
    arms = (("unfiltered", None),
            ("s50", "lang==0"),
            ("s10", "site in {0}"),
            ("s1", "recency>=3"))
    cfg = get_config("cdssm_toy", {
        "model.out_dim": dim,
        "obs.window_s": trial_s,
        # the cache would absorb the repeats and price the probe, not
        # the filtered scan — this phase wants the scan
        "serve.result_cache": False})
    svc = SearchService(cfg, MeshEmbedder(mesh), None, store,
                        preload_hbm_gb=4.0)
    svc._embed_queries_cached = _stub_embed
    svc.corpus = _StubCorpus()
    # exact post-filter oracle over the DEQUANTIZED store rows (the
    # store holds fp16; comparing against the fp32 originals would
    # charge quantization error to the filter)
    deq = np.concatenate([store._load_entry(e)[1] for e in store.shards()])
    deq = np.asarray(deq, np.float32)
    scores = qvs @ deq.T
    try:
        svc.search(qnames[0], k=kq)            # warm every compiled shape
        for label, pred_text in arms:
            pred = (attrs_mod.Predicate.parse(pred_text)
                    if pred_text else None)
            # per-query scan bytes on the exact path (n=1 so shared
            # gathers are not amortized across a batch)
            probe = 8
            scan = 0
            for i in range(probe):
                _, ids1, sb = svc._topk_view(svc._view, qvs[i:i + 1], 1,
                                             kq, None, predicate=pred)
                scan += int(sb)
            rec[f"filtered_scan_bytes_per_query_{label}"] = scan // probe
            if pred is not None:
                keep = pred.matches(words)
                hits = 0
                for i in range(probe):
                    sc = scores[i].copy()
                    sc[~keep] = -np.inf
                    oracle = np.argsort(-sc)[:kq]
                    _, ids1, _ = svc._topk_view(svc._view, qvs[i:i + 1],
                                                1, kq, None,
                                                predicate=pred)
                    hits += len(set(int(x) for x in ids1[0] if x >= 0)
                                & set(int(o) for o in oracle))
                rec[f"filtered_recall_{label}"] = round(
                    hits / (probe * kq), 4)
            scen = ((label, pred_text, 1.0),) if pred_text else None
            wl = make_workload("poisson", seed=0, distinct=distinct,
                               profile=((kq, None, 1.0),),
                               filter_scenarios=scen)
            _stamp(f"filtered arm={label}: searching qps @ "
                   f"p99<{p99_ms:.0f}ms (best of {reps})")
            best = 0.0
            for _ in range(reps):
                rep = find_qps_at_p99(
                    svc, wl, qnames, p99_target_ms=p99_ms,
                    start=start_qps, iters=iters, duration_s=trial_s,
                    warmup_s=0.5, workers=16)
                best = max(best, rep["qps_at_p99"])
            rec[f"filtered_serve_qps_at_p99_{label}"] = round(best, 2)
            _stamp(f"filtered arm={label}: {best:.1f} qps, "
                   f"{rec[f'filtered_scan_bytes_per_query_{label}']} "
                   f"scan B/query")
    finally:
        svc.close()
    base = rec.get("filtered_scan_bytes_per_query_unfiltered") or 0
    s10 = rec.get("filtered_scan_bytes_per_query_s10")
    if base and s10 is not None:
        rec["filtered_scan_bytes_ratio_s10"] = round(s10 / base, 4)
        _stamp(f"filtered s10 scan ratio: "
               f"x{rec['filtered_scan_bytes_ratio_s10']:.3f} of the "
               f"unfiltered exact bytes (gate <=0.3)")
    # IVF predicate intersection: recall@10 vs the exact post-filter
    # oracle with the predicate applied BEFORE ADC/payload gather
    _stamp("filtered ivf: building IVF index for the intersected path")
    idx = IVFIndex.build(store, mesh, nlist=64, iters=4, seed=0)
    nprobe = int(os.environ.get("BENCH_FILTERED_NPROBE", "16"))
    for label, pred_text in arms[1:]:
        pred = attrs_mod.Predicate.parse(pred_text)
        keep = pred.matches(words)
        sf, if_, st = idx.search(qvs[:8], kq, nprobe=nprobe,
                                 predicate=pred)
        hits = 0
        for i in range(8):
            sc = scores[i].copy()
            sc[~keep] = -np.inf
            oracle = np.argsort(-sc)[:kq]
            hits += len(set(int(x) for x in if_[i] if x >= 0)
                        & set(int(o) for o in oracle))
        rec[f"filtered_ivf_recall_{label}"] = round(hits / (8 * kq), 4)
    _stamp(f"filtered ivf recall@{kq}: "
           + ", ".join(f"{lab}={rec[f'filtered_ivf_recall_{lab}']:.2f}"
                       for lab, _ in arms[1:]))
    print(json.dumps(rec), flush=True)


def _run_filtered() -> dict:
    """Run the filtered_serve phase in a CPU subprocess and return its
    keys — merged into every record like the cache and net phases, so
    the predicate-pricing numbers re-seed the baseline with no TPU."""
    if os.environ.get("BENCH_FILTERED", "1") == "0":
        return {}
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--filtered-worker"],
            capture_output=True, text=True,
            timeout=int(os.environ.get("BENCH_FILTERED_TIMEOUT_S", "600")),
            cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
            env=env)
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "filtered_store_rows" in rec:
                return rec
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        return {"filtered_error":
                (" | ".join(tail[-3:]) if tail
                 else f"rc={proc.returncode}")[:300]}
    except subprocess.TimeoutExpired:
        return {"filtered_error": "filtered worker timed out"}
    except Exception as e:  # noqa: BLE001 — the phase never costs a round
        return {"filtered_error": f"{type(e).__name__}: {e}"[:300]}


def _run_cache() -> dict:
    """Run the result-cache A/B phase in a CPU subprocess and return its
    keys — merged into every record like the partitioned and net phases,
    so the Zipf-head cache numbers re-seed the baseline with no TPU."""
    if os.environ.get("BENCH_CACHE", "1") == "0":
        return {}
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--cache-worker"],
            capture_output=True, text=True,
            timeout=int(os.environ.get("BENCH_CACHE_TIMEOUT_S", "600")),
            cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
            env=env)
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "cache_store_rows" in rec:
                return rec
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        return {"cache_error":
                (" | ".join(tail[-3:]) if tail
                 else f"rc={proc.returncode}")[:300]}
    except subprocess.TimeoutExpired:
        return {"cache_error": "cache worker timed out"}
    except Exception as e:  # noqa: BLE001 — the phase never costs a round
        return {"cache_error": f"{type(e).__name__}: {e}"[:300]}


def _run_net() -> dict:
    """Run the net_serve phase in a CPU subprocess and return its keys —
    merged into every record (null-honest device phases included), so
    this sandbox produces real over-the-wire numbers with no TPU."""
    if os.environ.get("BENCH_NET", "1") == "0":
        return {}
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--net-worker"],
            capture_output=True, text=True,
            timeout=int(os.environ.get("BENCH_NET_TIMEOUT_S", "900")),
            cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
            env=env)
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "net_store_rows" in rec:
                return rec
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        return {"net_error":
                (" | ".join(tail[-3:]) if tail
                 else f"rc={proc.returncode}")[:300]}
    except subprocess.TimeoutExpired:
        return {"net_error": "net worker timed out"}
    except Exception as e:  # noqa: BLE001 — the phase never costs a round
        return {"net_error": f"{type(e).__name__}: {e}"[:300]}


def _run_partitioned() -> dict:
    """Run the host-simulated partitioned phase in a CPU subprocess and
    return its keys (merged into whatever record the wrapper prints —
    including the backend-unreachable null record, which is the point:
    this sandbox produces real numbers for the partitioned phase)."""
    if os.environ.get("BENCH_PARTITIONED", "1") == "0":
        return {}
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--partitioned-worker"],
            capture_output=True, text=True,
            timeout=int(os.environ.get("BENCH_PARTITIONED_TIMEOUT_S",
                                       "600")),
            cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
            env=env)
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "partitioned_store_rows" in rec:
                return rec
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        return {"partitioned_error":
                (" | ".join(tail[-3:]) if tail
                 else f"rc={proc.returncode}")[:300]}
    except subprocess.TimeoutExpired:
        return {"partitioned_error": "partitioned worker timed out"}
    except Exception as e:  # noqa: BLE001 — the phase never costs a round
        return {"partitioned_error": f"{type(e).__name__}: {e}"[:300]}


# ---------------------------------------------------------------------------
# Wrapper: retry the worker while the backend is down; never leak a traceback
# as the only output.
# ---------------------------------------------------------------------------

def _try_parse_last_json(stdout: str) -> dict | None:
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("metric") == METRIC:
            return rec
    return None


def main() -> None:
    deadline = time.time() + TOTAL_BUDGET
    delay = 10.0
    attempt = 0
    last_err = "no attempts ran"
    while True:
        attempt += 1
        # effective bound: the attempt knob, clipped by the remaining budget
        attempt_s = int(min(ATTEMPT_TIMEOUT, max(60, deadline - time.time())))
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--worker"],
                capture_output=True, text=True,
                timeout=attempt_s,
                cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
            )
            rec = _try_parse_last_json(proc.stdout)
            if rec is not None:
                # a parsed record means the required metrics were measured;
                # a nonzero rc after that can only come from optional work
                if proc.returncode != 0:
                    rec.setdefault("long_error", f"worker rc={proc.returncode}")
                _finalize(rec)
                return
            tail = (proc.stderr or proc.stdout or "").strip().splitlines()
            last_err = " | ".join(tail[-3:]) if tail else f"rc={proc.returncode}"
        except subprocess.TimeoutExpired as e:
            # The required metrics print BEFORE the optional long-context
            # sweep: a record recovered from partial stdout means the hang
            # happened in optional work and the primary datapoint is valid.
            partial = e.stdout or b""
            if isinstance(partial, bytes):
                partial = partial.decode(errors="replace")
            rec = _try_parse_last_json(partial)
            if rec is not None:
                rec.setdefault("long_error",
                               f"timed out after {attempt_s}s")
                _finalize(rec)
                return
            # surface the worker's progress stamps so the hung stage is named
            err = e.stderr or b""
            if isinstance(err, bytes):
                err = err.decode(errors="replace")
            tail = " | ".join(err.strip().splitlines()[-3:])
            last_err = (f"worker attempt {attempt} timed out after "
                        f"{attempt_s}s; stderr tail: {tail}")
        if time.time() + delay >= deadline:
            break
        time.sleep(delay)
        delay = min(delay * 2, 120.0)
    # Persistent failure: one parseable JSON line, rc 0 (VERDICT r1 #1).
    # The host-simulated partitioned phase still runs (CPU subprocess):
    # its measured keys ride the null record, so this sandbox re-seeds the
    # partitioned regression baseline even with the TPU unreachable.
    rec = {
        "metric": METRIC, "value": None, "unit": UNIT, "vs_baseline": None,
        "error": last_err[-500:], "attempts": attempt,
    }
    rec.update(_run_partitioned())
    rec.update(_run_net())
    rec.update(_run_cache())
    rec.update(_run_filtered())
    print(json.dumps(rec))


def _finalize(rec: dict) -> None:
    """Merge the host-simulated partitioned phase into the worker record,
    re-run the regression gate over the full key set, and print the final
    record (the one the driver parses)."""
    rec.update(_run_partitioned())
    rec.update(_run_net())
    rec.update(_run_cache())
    rec.update(_run_filtered())
    prev = _previous_bench_record()
    _, regs = _regression_gate(rec, prev)
    rec["regressions"] = regs
    _print_delta_table(rec, prev)
    print(json.dumps(rec))


if __name__ == "__main__":
    if "--worker" in sys.argv:
        run_worker()
    elif "--partitioned-worker" in sys.argv:
        run_partitioned_worker()
    elif "--net-worker" in sys.argv:
        run_net_worker()
    elif "--cache-worker" in sys.argv:
        run_cache_worker()
    elif "--filtered-worker" in sys.argv:
        run_filtered_worker()
    else:
        main()
