"""Benchmark: contrastive-training throughput in pages/sec/chip
(the primary metric, BASELINE.json:2), run on whatever accelerator the
environment provides (the driver runs this on one real TPU chip).

Method: flagship two-tower BERT-mini (config 3 geometry), pre-tokenized
batches resident on device (host tokenization is benched separately and is
not the device metric), jit-compiled train step with donated state; warmup
then timed steps. Prints ONE JSON line.

vs_baseline: BASELINE.json publishes no reference numbers ("published": {},
see BASELINE.md) — the ratio is computed against the most recent
BENCH_r*.json recorded by the driver, or 1.0 when none exists yet.
"""
from __future__ import annotations

import glob
import json
import os
import re
import time

import numpy as np


def _previous_bench() -> float | None:
    best = None
    for path in glob.glob(os.path.join(os.path.dirname(__file__) or ".",
                                       "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                rec = json.load(f)
            cand = (int(m.group(1)), float(rec["value"]))
        except Exception:
            continue
        if best is None or cand[0] > best[0]:
            best = cand
    return None if best is None else best[1]


def main() -> None:
    import jax

    from dnn_page_vectors_tpu.config import get_config
    from dnn_page_vectors_tpu.train.loop import Trainer

    n_dev = len(jax.devices())
    cfg = get_config("bert_mini_v5p16", {
        "data.num_pages": max(2_048, 256 * n_dev),
        "data.query_len": 16,
        "data.page_len": 64,
        "train.batch_size": 256 * n_dev,
        "train.steps": 40,
        "train.log_every": 1_000_000,   # keep logging off the timed path
        "mesh.data": n_dev,
    })
    trainer = Trainer(cfg, workdir="/tmp/dnn_page_vectors_tpu_bench")
    state = trainer.init_state()
    step_fn = trainer.compiled_step(state)

    # Pre-materialize a few batches on device: the metric is device
    # training throughput; the host pipeline overlaps in production.
    from dnn_page_vectors_tpu.parallel.sharding import replicated
    it = iter(trainer.batches())
    batches = [next(it) for _ in range(4)]
    base_rng = jax.device_put(jax.random.PRNGKey(0), replicated(trainer.mesh))

    for i in range(5):  # warmup + compile
        state, metrics = step_fn(state, batches[i % len(batches)], base_rng)
    jax.block_until_ready(state.params)

    timed_steps = cfg.train.steps
    t0 = time.perf_counter()
    for i in range(timed_steps):
        state, metrics = step_fn(state, batches[i % len(batches)], base_rng)
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    pages_per_sec_per_chip = cfg.train.batch_size * timed_steps / dt / n_dev
    prev = _previous_bench()
    vs = pages_per_sec_per_chip / prev if prev else 1.0
    print(json.dumps({
        "metric": "train_pages_per_sec_per_chip",
        "value": round(pages_per_sec_per_chip, 2),
        "unit": "pages/sec/chip",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()
