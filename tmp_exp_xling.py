import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
import numpy as np, tempfile, time
from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.evals.recall import evaluate_recall
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.train.loop import Trainer

t0 = time.time()
cfg = get_config("mt5_multilingual", {
    "data.num_pages": 600,
    "data.languages": 3,
    "data.vocab_size": 1024,
    "data.page_len": 48,
    "data.query_len": 12,
    "model.num_layers": 2,
    "model.num_heads": 4,
    "model.model_dim": 96,
    "model.mlp_dim": 192,
    "model.out_dim": 64,
    "model.dropout": 0.0,
    "mesh.data": 1, "mesh.model": 1,
    "train.batch_size": 64,
    "train.steps": 300,
    "train.warmup_steps": 20,
    "train.learning_rate": 2e-3,
    "train.log_every": 100,
    "eval.eval_queries": 200,
    "eval.embed_batch_size": 128,
})
wd = tempfile.mkdtemp()
trainer = Trainer(cfg, workdir=wd)
print("tok vocab", trainer.page_tok.vocab_size, "setup", round(time.time()-t0,1))
state, metrics = trainer.train()
print("train done", round(time.time()-t0,1), {k: round(float(v),3) for k,v in metrics.items()})
store = VectorStore(os.path.join(wd, "store"), dim=cfg.model.out_dim, shard_size=256)
embedder = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                        trainer.mesh, query_tok=trainer.query_tok)
embedder.embed_corpus(trainer.corpus, store, batch_size=128)
recall, nq = evaluate_recall(embedder, trainer.corpus, store, num_queries=200, k=10)
print("XLING recall@10", recall, "nq", nq, "total", round(time.time()-t0,1))
