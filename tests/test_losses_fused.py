"""Numeric-parity pins for the chunked/fused contrastive loss
(models/losses.py, train.loss_chunk): the chunked path must reproduce the
dense reference loss AND its gradients to fp32 tolerance — with and
without mined negatives, symmetric on and off — and behave identically
under jit with the batch sharded over the 8-fake-device data mesh (the
GSPMD configuration whose all-gathered page pool the chunking exists to
stream against).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_page_vectors_tpu.models.losses import cosine_contrastive_loss

pytestmark = pytest.mark.mfu

B, D, H = 24, 16, 3
TOL = 1e-5


def _inputs(seed=1):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, D)), jnp.float32)
    p = jnp.asarray(rng.normal(size=(B, D)), jnp.float32)
    neg = jnp.asarray(rng.normal(size=(B, H, D)), jnp.float32)
    return q, p, neg, jnp.float32(20.0)


@pytest.mark.parametrize("symmetric", [True, False])
@pytest.mark.parametrize("use_neg", [True, False])
@pytest.mark.parametrize("chunk", [4, 8, 12])
def test_chunked_matches_dense_loss_and_grads(symmetric, use_neg, chunk):
    q, p, neg, scale = _inputs()
    n = neg if use_neg else None

    def dense(q, p, s):
        return cosine_contrastive_loss(q, p, s, n, symmetric=symmetric)[0]

    def chunked(q, p, s):
        return cosine_contrastive_loss(q, p, s, n, symmetric=symmetric,
                                       chunk=chunk)[0]

    ld, lc = dense(q, p, scale), chunked(q, p, scale)
    assert abs(float(ld - lc)) < TOL, (float(ld), float(lc))
    gd = jax.grad(dense, (0, 1, 2))(q, p, scale)
    gc = jax.grad(chunked, (0, 1, 2))(q, p, scale)
    for a, b in zip(gd, gc):
        assert float(jnp.abs(a - b).max()) < TOL
    # the aux metrics (in_batch_acc over the full negative pool) agree too
    md = cosine_contrastive_loss(q, p, scale, n, symmetric=symmetric)[1]
    mc = cosine_contrastive_loss(q, p, scale, n, symmetric=symmetric,
                                 chunk=chunk)[1]
    assert float(md["in_batch_acc"]) == float(mc["in_batch_acc"])


def test_chunk_must_divide_batch():
    q, p, neg, scale = _inputs()
    with pytest.raises(ValueError, match="divide"):
        cosine_contrastive_loss(q, p, scale, chunk=7)


def test_oversized_chunk_falls_back_to_dense():
    q, p, neg, scale = _inputs()
    ld = cosine_contrastive_loss(q, p, scale)[0]
    lc = cosine_contrastive_loss(q, p, scale, chunk=B)[0]
    # chunk >= B is the dense path itself — bitwise, not just close
    assert float(ld) == float(lc)


def test_chunked_under_jit_sharded_batch(eight_devices):
    """The production configuration: jit, batch sharded over 'data', the
    page pool all-gathered by GSPMD, chunks streamed per shard."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    q, p, neg, scale = _inputs(seed=3)
    mesh = Mesh(np.array(eight_devices), ("data",))
    sh = NamedSharding(mesh, P("data"))
    qs = jax.device_put(q, sh)
    ps = jax.device_put(p, sh)

    def loss(q, p, chunk):
        return cosine_contrastive_loss(q, p, scale, chunk=chunk)[0]

    dense = jax.jit(lambda q, p: loss(q, p, 0))(qs, ps)
    chunked = jax.jit(lambda q, p: loss(q, p, 8))(qs, ps)
    assert abs(float(dense - chunked)) < TOL

    gd = jax.jit(jax.grad(lambda q, p: loss(q, p, 0), (0, 1)))(qs, ps)
    gc = jax.jit(jax.grad(lambda q, p: loss(q, p, 8), (0, 1)))(qs, ps)
    for a, b in zip(gd, gc):
        assert float(jnp.abs(np.asarray(a) - np.asarray(b)).max()) < TOL


def test_chunked_train_step_end_to_end(tmp_path):
    """Three optimizer steps with train.loss_chunk on == off (same data,
    dropout off): the fused loss slots into the full jitted train step."""
    from dnn_page_vectors_tpu.config import get_config
    from dnn_page_vectors_tpu.data.toy import ToyCorpus
    from dnn_page_vectors_tpu.train.loop import Trainer

    losses = {}
    for chunk in (0, 8):
        cfg = get_config("bert_mini_v5p16", {
            "data.num_pages": 256, "data.vocab_size": 512,
            "data.page_len": 32, "data.query_len": 8,
            "model.num_layers": 1, "model.dropout": 0.0,
            "train.batch_size": 32, "train.loss_chunk": chunk,
            "train.log_every": 1000,
        })
        corpus = ToyCorpus(num_pages=256, seed=0, page_len=6, query_len=4)
        tr = Trainer(cfg, corpus=corpus,
                     workdir=str(tmp_path / f"chunk{chunk}"))
        state = tr.init_state()
        step = tr.compiled_step(state)
        it = iter(tr.batches())
        rng = tr.base_rng()
        curve = []
        for _ in range(3):
            state, m = step(state, next(it), rng)
            curve.append(float(m["loss"]))
        losses[chunk] = curve
    diff = np.abs(np.array(losses[0]) - np.array(losses[8])).max()
    assert diff < 1e-4, losses
