"""Rolling model migration (docs/MAINTENANCE.md "Rolling model
migration"): re-embed a LIVE store to a new model step unit-by-unit while
it serves. Pins: the sweep is resumable and byte-deterministic, appends
that land mid-sweep become pending units, the crash-anywhere fault matrix
over migrate_write/migrate_swap_dump/migrate_swap_file leaves a serveable
store at every commit point and resumes to completion, dual-stamp serving
routes every shard through the tower that embedded it (top-1 exact on
both stamps mid-sweep — a cross-tower scoring would be observably wrong,
not merely noisy), the maintenance pillar sweeps a live service under a
concurrent query hammer with zero errors and recall@10 >= 0.95, the
result-cache key carries the serving model stamp so a pre-flip entry can
never answer post-flip, and a socket client rides one connection through
the whole migration (no restart anywhere).

Model-free (the test_net / test_result_cache idiom): a deterministic
(text, step) -> unit-vector stub stands in for the two towers, so the
routing is discriminating — vectors from different steps are independent
random directions, and only stamp-correct routing scores ~1.0.
"""
import os
import shutil
import threading
import time
import zlib

import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.maintenance.migrate import (
    MigrationPlan, migrate_store)
from dnn_page_vectors_tpu.utils import faults, telemetry

pytestmark = pytest.mark.migrate

DIM = 24
SHARD = 40


# ---------------------------------------------------------------------------
# fixtures: two fake towers + a synthetic stamped store
# ---------------------------------------------------------------------------

def _vec(text, step):
    """Deterministic unit vector keyed on (text, model step): the two
    towers' embeddings of the SAME text are independent random directions,
    so any cross-stamp scoring is observably wrong."""
    seed = zlib.crc32(f"{int(step)}|{text}".encode()) & 0xFFFFFFFF
    v = np.random.default_rng(seed).standard_normal(DIM).astype(np.float32)
    return v / np.linalg.norm(v)


class _Corpus:
    def page_text(self, i):
        return f"page {int(i)}"


class _Embedder:
    """The page tower MigrationPlan drives: embed_texts at one step."""

    def __init__(self, step, mesh=None):
        self.step = int(step)
        self.params = ("tower", int(step))
        self.mesh = mesh
        self.query_tok = None
        self.page_tok = None

    def embed_texts(self, texts, tower="page", batch_size=None):
        return np.stack([_vec(t, self.step) for t in texts])


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    telemetry.reset_default()
    yield
    faults.reset()
    telemetry.reset_default()


@pytest.fixture(scope="module")
def mesh():
    import jax
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:1]), ("data",))


def _build_store(sdir, nbase=2, gen_rows=(20,), step=1, corpus=None):
    """nbase full base shards + one generation per gen_rows entry, every
    row embedded at `step` from the corpus text (so querying a page's own
    text is an exact self-hit under the matching tower)."""
    corpus = corpus or _Corpus()
    emb = _Embedder(step)
    store = VectorStore(sdir, dim=DIM, shard_size=SHARD)
    store.ensure_model_step(step)
    for si in range(nbase):
        ids = np.arange(si * SHARD, (si + 1) * SHARD, dtype=np.int64)
        store.write_shard(si, ids, emb.embed_texts(
            [corpus.page_text(i) for i in ids]))
    store = VectorStore(sdir)
    for rows in gen_rows:
        base = store.next_page_id()
        ids = np.arange(base, base + rows, dtype=np.int64)
        w = store.begin_generation()
        w.write_shard(ids, emb.embed_texts(
            [corpus.page_text(i) for i in ids]))
        w.commit()
        store = VectorStore(sdir)
    return store


def _append_gen(sdir, rows, step, corpus=None):
    corpus = corpus or _Corpus()
    store = VectorStore(sdir)
    base = store.next_page_id()
    ids = np.arange(base, base + rows, dtype=np.int64)
    w = store.begin_generation()
    w.write_shard(ids, _Embedder(step).embed_texts(
        [corpus.page_text(i) for i in ids]))
    w.commit()
    return ids


def _service(store, mesh, corpus=None, hbm=4.0, **serve_over):
    import dataclasses

    from dnn_page_vectors_tpu.infer.partition_host import MeshEmbedder
    from dnn_page_vectors_tpu.infer.serve import SearchService
    cfg = get_config("cdssm_toy", {"model.out_dim": DIM})
    if serve_over:
        cfg = cfg.replace(serve=dataclasses.replace(cfg.serve,
                                                    **serve_over))
    svc = SearchService(cfg, MeshEmbedder(mesh), None, store,
                        preload_hbm_gb=hbm)

    def _embed(queries, steps=None):
        ss = list(steps) if steps is not None else []
        if len(ss) <= 1:
            use = ss[0] if ss else svc.store.model_step
            return np.stack([_vec(q, use) for q in queries])
        # the dual-stamp stacked block: one D-slice per stamp, ascending
        return np.concatenate(
            [np.stack([_vec(q, s) for q in queries]) for s in ss], axis=1)

    svc._embed_queries_cached = _embed
    svc.corpus = corpus or _Corpus()
    return svc


def _self_hit_ok(svc, pid, k=10):
    hits = svc.search(f"page {int(pid)}", k=k)
    return bool(hits) and int(hits[0]["page_id"]) == int(pid)


def _assert_all_self_hits(svc, ids, what):
    bad = [int(i) for i in ids if not _self_hit_ok(svc, i)]
    assert not bad, f"{what}: routed to the wrong tower for pages {bad}"


# ---------------------------------------------------------------------------
# sweep mechanics
# ---------------------------------------------------------------------------

def test_sweep_is_byte_deterministic_across_drive_paths(tmp_path):
    """migrate_store (the cli path) and unit-at-a-time begin/migrate_unit/
    complete (the pillar path) over identical stores produce identical
    migrated shard BYTES, a [2]-stamped store, and preserved ids."""
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    _build_store(a), _build_store(b)
    corpus = _Corpus()

    out = migrate_store(VectorStore(a), corpus, _Embedder(2), 2)
    assert out["action"] == "migrated" and out["completed"]
    assert out["units"] == 2 and out["rows"] == 100

    plan = MigrationPlan(VectorStore(b), corpus, _Embedder(2), 2)
    assert plan.begin()["action"] == "started"
    assert plan.pending_units() == [0, 1]          # oldest (base) first
    for u in (0, 1):
        st = plan.migrate_unit(u)
        assert st["action"] == "migrated_unit" and st["rows"] > 0
        assert st["stale_files"]                   # superseded old-stamp
    fin = plan.complete()
    assert fin == {"action": "completed", "from_step": 1, "to_step": 2}

    for sdir in (a, b):
        store = VectorStore(sdir)
        assert store.model_step == 2 and store.model_steps() == [2]
        assert store.migration is None and store.num_vectors == 100
        assert all(store.entry_step(e) == 2 for e in store.shards())
    sa, sb = VectorStore(a), VectorStore(b)
    for ea, eb in zip(sa.shards(), sb.shards()):
        assert ea["vec"] == eb["vec"] and ea["crc"] == eb["crc"]
        for key in ("vec", "ids"):
            with open(os.path.join(a, ea[key]), "rb") as f1, \
                    open(os.path.join(b, eb[key]), "rb") as f2:
                assert f1.read() == f2.read(), f"{ea[key]} diverged"
    # re-running a finished migration is a noop, not a second sweep
    assert migrate_store(VectorStore(a), corpus, _Embedder(2),
                         2)["action"] == "noop"


def test_appends_mid_sweep_become_pending_units(tmp_path):
    sdir = str(tmp_path / "store")
    _build_store(sdir)
    plan = MigrationPlan(VectorStore(sdir), _Corpus(), _Embedder(2), 2)
    plan.begin()
    plan.migrate_unit(0)
    # an append lands mid-sweep, stamped by the OLD serving model
    new_ids = _append_gen(sdir, 15, step=1)
    plan = MigrationPlan(VectorStore(sdir), _Corpus(), _Embedder(2), 2)
    assert plan.begin()["action"] == "resumed"
    assert plan.pending_units() == [1, 2]
    assert plan.complete() is None                 # units still pending
    for u in (1, 2):
        plan.migrate_unit(u)
    assert plan.complete()["action"] == "completed"
    store = VectorStore(sdir)
    assert store.model_steps() == [2]
    assert store.num_vectors == 100 + 15
    got = set(int(i) for i in store.load_all()[0])
    assert set(int(i) for i in new_ids) <= got


# ---------------------------------------------------------------------------
# crash-anywhere fault matrix
# ---------------------------------------------------------------------------

# every check-point of the sweep: per-shard re-embed writes (0 = first
# base shard, 1 = mid-unit-0 with a torn dir behind it, 2 = the gen unit
# after the base flip committed — a dual-stamp store), and every atomic
# flip (dump call 0 = begin's record, 1 = the base-unit flip, 2 = the gen
# flip, 3 = complete's stamp flip; persistent so the retry wrapper can't
# absorb them)
_CRASH_PLANS = [
    "migrate_write:io_error:0",
    "migrate_write:io_error:1",
    "migrate_write:io_error:2",
    "migrate_swap_dump:io_error:0:*",
    "migrate_swap_dump:io_error:1:*",
    "migrate_swap_dump:io_error:2:*",
    "migrate_swap_dump:io_error:3:*",
]


@pytest.mark.parametrize("plan_txt", _CRASH_PLANS)
def test_crash_anywhere_leaves_serveable_store_and_resumes(
        tmp_path, mesh, plan_txt):
    sdir = str(tmp_path / "store")
    _build_store(sdir)
    corpus = _Corpus()
    faults.install(faults.FaultPlan.parse(plan_txt, seed=0))
    with pytest.raises(IOError):
        migrate_store(VectorStore(sdir), corpus, _Embedder(2), 2)
    faults.install(faults.FaultPlan())
    # the store reopens serveable on exactly one side of the torn flip:
    # whatever stamp mix it holds, every page still self-hits through the
    # stamp-routed query path
    cold = VectorStore(sdir)
    assert cold.num_vectors == 100
    assert set(cold.model_steps()) <= {1, 2}
    svc = _service(cold, mesh, corpus=corpus)
    _assert_all_self_hits(svc, range(0, 100, 7), f"after {plan_txt}")
    svc.close()
    # and the sweep RESUMES from the manifest to completion
    out = migrate_store(VectorStore(sdir), corpus, _Embedder(2), 2)
    assert out["action"] in ("migrated", "noop")
    store = VectorStore(sdir)
    assert store.model_step == 2 and store.model_steps() == [2]
    assert store.migration is None
    svc = _service(store, mesh, corpus=corpus)
    _assert_all_self_hits(svc, range(0, 100, 7), f"resumed {plan_txt}")
    svc.close()


def test_transient_swap_fault_absorbed_by_retry(tmp_path):
    """A once-off io_error on the flip dump is absorbed by the shared
    retry wrapper — the sweep completes without surfacing it."""
    sdir = str(tmp_path / "store")
    _build_store(sdir)
    faults.install(faults.FaultPlan.parse("migrate_swap_dump:io_error:1",
                                          seed=0))
    out = migrate_store(VectorStore(sdir), _Corpus(), _Embedder(2), 2)
    assert out["action"] == "migrated" and out["completed"]
    assert faults.counters().get("injected_migrate_swap_dump_io_error") == 1
    assert faults.counters().get("retry_migrate_swap_dump", 0) >= 1
    assert VectorStore(sdir).model_step == 2


def test_corrupted_flip_file_quarantines_main_manifest(tmp_path):
    """Post-fsync damage to the flip's tmp file (NOT a crash — the bytes
    were torn after the fault window) lands a torn MAIN manifest: reopen
    quarantines it with a clear restore-me error, never a JSON traceback,
    and counts it. The damage must hit the LAST flip (complete()'s) — an
    earlier torn manifest is simply overwritten by the next unit's good
    dump, which is itself a recovery property."""
    sdir = str(tmp_path / "store")
    _build_store(sdir)
    faults.install(faults.FaultPlan.parse("migrate_swap_file:truncate:3",
                                          seed=0))
    migrate_store(VectorStore(sdir), _Corpus(), _Embedder(2), 2)
    faults.install(faults.FaultPlan())
    with pytest.raises(ValueError, match="corrupt"):
        VectorStore(sdir)
    assert os.path.exists(os.path.join(sdir, "manifest.json.quarantined"))
    assert faults.counters().get("quarantined_manifests") == 1


# ---------------------------------------------------------------------------
# dual-stamp serving
# ---------------------------------------------------------------------------

def test_dual_stamp_serving_routes_each_shard_through_its_tower(
        tmp_path, mesh):
    sdir = str(tmp_path / "store")
    _build_store(sdir)
    corpus = _Corpus()
    svc = _service(VectorStore(sdir), mesh, corpus=corpus)
    svc.begin_migration(("tower", 2), 2)
    plan = MigrationPlan(VectorStore(sdir), corpus, _Embedder(2), 2)
    plan.begin()
    plan.migrate_unit(0)                 # base re-stamped, gen still old
    info = svc.refresh()
    view = svc._view
    assert view.steps == [1, 2]
    assert sorted(set(view.shard_steps)) == [1, 2]
    # one stamp per STAGED SHARD, never mixed within one — and the view's
    # stamps agree with the store's recorded per-entry stamps
    assert view.shard_steps == [view.store.entry_step(e)
                                for e in view.entries]
    mig = info.get("migration")
    assert mig and mig["from_step"] == 1 and mig["to_step"] == 2
    assert mig["stamps_serving"] == [1, 2]
    # every page self-hits: base pages through tower 2, gen pages through
    # tower 1 — a cross-stamp scoring would randomize these top-1s
    _assert_all_self_hits(svc, range(0, 100, 5), "resident dual-stamp")
    svc.close()
    # the streaming path (no HBM residency) routes identically
    svc2 = _service(VectorStore(sdir), mesh, corpus=corpus, hbm=0.0)
    _assert_all_self_hits(svc2, range(0, 100, 5), "streaming dual-stamp")
    svc2.close()


# ---------------------------------------------------------------------------
# the maintenance pillar, live under a query+append hammer
# ---------------------------------------------------------------------------

def test_pillar_migrates_live_service_under_hammer(tmp_path, mesh):
    """request_migration -> run_once passes on a SERVING store with a
    3-thread query hammer and an append landing mid-sweep: zero request
    errors, recall@10 >= 0.95 throughout, per-pass view swaps, gauges and
    events emitted, and the completing refresh adopts the new tower."""
    sdir = str(tmp_path / "store")
    _build_store(sdir, nbase=3, gen_rows=(20,))    # 140 rows
    corpus = _Corpus()
    svc = _service(VectorStore(sdir), mesh, corpus=corpus)
    maint = svc.start_maintenance(threads=False)
    emb2 = _Embedder(2, mesh=mesh)
    maint.request_migration(2, corpus, emb2)
    assert svc._towers == {2: ("tower", 2)}        # dual-stamp armed now

    stop = threading.Event()
    stats = {"total": 0, "hit10": 0, "errors": 0}
    lock = threading.Lock()

    def hammer(ti):
        rng = np.random.default_rng(ti)
        while not stop.is_set():
            pid = int(rng.integers(0, 140))
            try:
                hits = svc.search(f"page {pid}", k=10)
                ok = pid in [int(r["page_id"]) for r in hits]
            except Exception:
                with lock:
                    stats["errors"] += 1
                continue
            with lock:
                stats["total"] += 1
                stats["hit10"] += int(ok)

    threads = [threading.Thread(target=hammer, args=(t,)) for t in range(3)]
    for t in threads:
        t.start()
    passes, appended = [], False
    try:
        for _ in range(32):
            # let the hammer sample THIS stamp mix before the next flip —
            # the sweep itself is sub-second on a toy store
            time.sleep(0.2)
            out = maint.run_once().get("migrate")
            if out is None:
                break
            passes.append(out)
            if out.get("action") == "completed":
                break
            if not appended:                        # mid-sweep append
                _append_gen(sdir, 10, step=1, corpus=corpus)
                appended = True
    finally:
        stop.set()
        for t in threads:
            t.join()

    assert appended and passes
    assert passes[-1]["action"] == "completed"
    assert passes[-1]["from_step"] == 1 and passes[-1]["to_step"] == 2
    migrating = [p for p in passes if p["action"] == "migrating"]
    assert migrating and all("refresh_swap_ms" in p for p in migrating)
    assert stats["errors"] == 0, f"hammer saw {stats['errors']} errors"
    assert stats["total"] > 50
    recall = stats["hit10"] / stats["total"]
    assert recall >= 0.95, f"recall@10 {recall:.3f} through migration"

    store = VectorStore(sdir)
    assert store.model_step == 2 and store.model_steps() == [2]
    assert store.num_vectors == 150
    # the completing refresh adopted the new tower and dropped the old
    assert svc.embedder.params == ("tower", 2)
    assert svc._towers == {}
    assert svc._view.steps == [2]
    reg = maint.registry
    assert reg.gauge("migrate.generations_done").value >= 1
    assert reg.gauge("migrate.pages_per_s").value > 0
    assert reg.counter("maintenance.migrations").value == 1
    names = [e["event"] for e in reg.events()]
    assert "migration_started" in names
    assert "migration_generation_done" in names
    assert "migration_complete" in names
    _assert_all_self_hits(svc, range(0, 150, 11), "post-migration")
    svc.close()


# ---------------------------------------------------------------------------
# result-cache stamp pin (the key-composition bug this PR fixes)
# ---------------------------------------------------------------------------

class _PinCorpus:
    """page 7's text IS the probe query: post-migration its re-embedded
    vector equals the step-2 query vector, so the correct answer flips
    from the planted page 3 to page 7 — a stale cached result is
    observably wrong, not merely old."""
    QUERY = "the zipf head probe"

    def page_text(self, i):
        return self.QUERY if int(i) == 7 else f"page {int(i)}"


def test_result_cache_key_carries_model_stamp(tmp_path, mesh):
    sdir = str(tmp_path / "store")
    corpus = _PinCorpus()
    store = VectorStore(sdir, dim=DIM, shard_size=SHARD)
    store.ensure_model_step(1)
    vecs = _Embedder(1).embed_texts(
        [corpus.page_text(i) for i in range(SHARD)])
    vecs[3] = _vec(corpus.QUERY, 1)       # planted step-1 top-1
    vecs[7] = _vec("decoy", 1)            # page 7 does NOT match at step 1
    store.write_shard(0, np.arange(SHARD, dtype=np.int64), vecs)
    svc = _service(VectorStore(sdir), mesh, corpus=corpus,
                   result_cache=True)
    q = corpus.QUERY
    first = svc.search(q, k=5)
    assert int(first[0]["page_id"]) == 3
    assert svc.search(q, k=5) == first and svc.result_cache_hits == 1
    key1 = svc._result_cache_key(q, 5, None)
    assert (key1[3] >> 32) == 1           # serving stamp in the high word

    svc.begin_migration(("tower", 2), 2)
    out = migrate_store(VectorStore(sdir), corpus, _Embedder(2), 2)
    assert out["completed"]
    svc.refresh()
    after = svc.search(q, k=5)
    # the stamp (and the epoch-folded generation) changed: the cached
    # step-1 answer is unreachable, and the fresh scan finds page 7
    assert svc.result_cache_hits == 1 and svc.result_cache_misses == 2
    assert int(after[0]["page_id"]) == 7
    key2 = svc._result_cache_key(q, 5, None)
    assert (key2[3] >> 32) == 2
    assert key1 != key2
    svc.close()


# ---------------------------------------------------------------------------
# socket fleet: one connection through the whole migration
# ---------------------------------------------------------------------------

def test_socket_client_rides_one_connection_through_migration(
        tmp_path, mesh):
    from dnn_page_vectors_tpu.infer.server import serve_in_background
    from dnn_page_vectors_tpu.infer.transport import SocketSearchClient
    sdir = str(tmp_path / "store")
    _build_store(sdir)
    corpus = _Corpus()
    svc = _service(VectorStore(sdir), mesh, corpus=corpus)
    maint = svc.start_maintenance(threads=False)
    srv = serve_in_background(svc)
    client = SocketSearchClient(srv.host, srv.port)
    try:
        assert int(client.search("page 5", k=5)[0]["page_id"]) == 5
        maint.request_migration(2, corpus, _Embedder(2, mesh=mesh))
        done = False
        for _ in range(16):
            out = maint.run_once().get("migrate")
            if out is None or out.get("action") == "completed":
                done = out is not None
                break
            # mid-sweep, the SAME connection keeps answering correctly
            # across both stamps — no worker restart, no reconnect
            for pid in (5, 45, 85, 95):
                assert int(client.search(f"page {pid}",
                                         k=5)[0]["page_id"]) == pid
        assert done
        assert VectorStore(sdir).model_step == 2
        for pid in (5, 45, 85, 95):
            assert int(client.search(f"page {pid}",
                                     k=5)[0]["page_id"]) == pid
    finally:
        client.close()
        srv.close()
        svc.close()
