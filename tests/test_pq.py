"""OPQ+PQ compressed posting payloads (index/pq.py, the ADC path in
index/ivf.py, docs/ANN.md): seeded codebook build determinism, the
ADC+exact-re-rank recall@10 >= 0.95 contract on the toy corpus, the
measured candidate-payload-bytes drop vs stored-width gather, hot
posting staging parity (resident lists answer without the host gather,
results identical), balanced-assignment capping, incremental code
append after a store append, and seeded-fault corruption of a code file
quarantining the index into the exact fallback."""
import json
import os
import shutil

import numpy as np
import pytest

from dnn_page_vectors_tpu.config import MeshConfig, get_config
from dnn_page_vectors_tpu.evals.recall import recall_vs_exact
from dnn_page_vectors_tpu.index.ivf import (
    IndexUnavailable, IVFIndex, index_dir)
from dnn_page_vectors_tpu.index.pq import auto_pq_m
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.serve import SearchService
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.ops.topk import topk_over_store
from dnn_page_vectors_tpu.parallel.mesh import make_mesh
from dnn_page_vectors_tpu.train.loop import Trainer
from dnn_page_vectors_tpu.utils import faults

pytestmark = pytest.mark.pq

_OV = {
    "data.num_pages": 300,
    "data.trigram_buckets": 2048,
    "model.embed_dim": 48,
    "model.conv_channels": 96,
    "model.out_dim": 48,
    "train.batch_size": 64,
    "train.steps": 60,
    "train.warmup_steps": 10,
    "train.learning_rate": 2e-3,
    "train.log_every": 1000,
    "eval.embed_batch_size": 100,
    "eval.store_shard_size": 100,   # 3 shards: per-shard code files
}


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    """One trained model + embedded 3-shard store for the whole module;
    destructive tests copy the store directory instead of mutating it."""
    wd = tmp_path_factory.mktemp("pq_env")
    cfg = get_config("cdssm_toy", _OV)
    trainer = Trainer(cfg, workdir=str(wd))
    state, _ = trainer.train()
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, query_tok=trainer.query_tok)
    store = VectorStore(os.path.join(str(wd), "store"),
                        dim=cfg.model.out_dim, shard_size=100)
    store.ensure_model_step(int(state.step))
    emb.embed_corpus(trainer.corpus, store)
    from dnn_page_vectors_tpu.train.checkpoint import CheckpointManager
    mgr = CheckpointManager(os.path.join(str(wd), "ckpt"))
    mgr.save(int(state.step), state, wait=True)
    mgr.close()
    return {"cfg": cfg, "trainer": trainer, "emb": emb, "store": store,
            "wd": str(wd)}


def _copy_store(env, tmp_path):
    dst = os.path.join(str(tmp_path), "store")
    shutil.copytree(env["store"].directory, dst)
    shutil.rmtree(os.path.join(dst, "ivf"), ignore_errors=True)
    return VectorStore(dst)


def _ivf_cfg(env, **serve_kw):
    import dataclasses
    serve = dataclasses.replace(env["cfg"].serve, index="ivf", **serve_kw)
    return env["cfg"].replace(serve=serve)


def _synth_store(tmp_path, n=2000, d=64, nclust=32, seed=3, dtype="int8",
                 shard=1000):
    """Clustered unit-norm synthetic store: big enough that probed-list
    candidate sets dwarf the re-rank unions (the payload-ratio regime)."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(nclust, d))
    vecs = (centers[rng.integers(0, nclust, n)]
            + 0.3 * rng.normal(size=(n, d))).astype(np.float32)
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    store = VectorStore(str(tmp_path / "synth"), dim=d, shard_size=shard,
                        dtype=dtype)
    store.ensure_model_step(1)
    for i in range(0, n, shard):
        store.write_shard(i // shard, np.arange(i, min(i + shard, n)),
                          vecs[i: i + shard])
    return store, vecs


def test_auto_pq_m_divides():
    assert auto_pq_m(48) == 6 and auto_pq_m(128) == 16
    assert auto_pq_m(30) == 5 and 30 % auto_pq_m(30) == 0


def test_pq_build_is_seed_deterministic(env, tmp_path):
    """Same store bytes + seed -> byte-identical rotation, codebooks, and
    code files (the manifest differs only in wall-clock); a different
    seed moves the codebooks."""
    a = _copy_store(env, tmp_path / "a")
    b = _copy_store(env, tmp_path / "b")
    mesh = env["emb"].mesh
    ia = IVFIndex.build(a, mesh, nlist=16, iters=5, seed=3, pq_m=6)
    ib = IVFIndex.build(b, mesh, nlist=16, iters=5, seed=3, pq_m=6)
    names = sorted(n for n in os.listdir(index_dir(a))
                   if n.endswith(".npy"))
    assert any(n.startswith("pq_") for n in names)
    assert any(n.endswith(".pqc.npy") for n in names)
    assert names == sorted(
        n for n in os.listdir(index_dir(b)) if n.endswith(".npy"))
    for n in names:
        with open(os.path.join(index_dir(a), n), "rb") as f:
            bytes_a = f.read()
        with open(os.path.join(index_dir(b), n), "rb") as f:
            bytes_b = f.read()
        assert bytes_a == bytes_b, f"{n} differs between seeded builds"
    ma, mb = dict(ia.manifest), dict(ib.manifest)
    for m in (ma, mb):
        m.pop("build_seconds")
        m["pq"] = {k: v for k, v in m["pq"].items()
                   if k != "train_seconds"}
    assert ma == mb
    c = _copy_store(env, tmp_path / "c")
    ic = IVFIndex.build(c, mesh, nlist=16, iters=5, seed=4, pq_m=6)
    assert not np.array_equal(ic.pq.codebooks, ia.pq.codebooks)


def test_adc_recall_contract_and_serving(env):
    """The acceptance pin: on the toy corpus at the DEFAULT nprobe, ADC
    search with the exact re-rank holds recall@10 >= 0.95 vs exact, the
    serving path through serve.index=ivf matches, and the payload
    counters move (gather_bytes > 0, reranked rows bounded by rerank)."""
    cfg = env["cfg"]
    store, emb, trainer = env["store"], env["emb"], env["trainer"]
    IVFIndex.build(store, emb.mesh, seed=0, pq_m=6)   # auto nlist
    idx = IVFIndex.open(store)
    assert idx.pq is not None and idx.pq_m == 6
    queries = [trainer.corpus.query_text(i) for i in range(0, 300, 7)]
    qv = np.asarray(emb.embed_texts(queries, tower="query"), np.float32)
    r = recall_vs_exact(idx, store, qv, emb.mesh, k=10,
                        nprobe=cfg.serve.nprobe)
    assert r >= 0.95, f"ADC recall@10 vs exact {r:.3f} < 0.95"
    assert idx.stats["gather_bytes"] > 0
    assert idx.stats["reranked_rows"] > 0

    exact_svc = SearchService(cfg, emb, trainer.corpus, store,
                              preload_hbm_gb=4.0)
    ann_svc = SearchService(_ivf_cfg(env), emb, trainer.corpus, store,
                            preload_hbm_gb=0.0)
    assert ann_svc._index is not None and ann_svc._index.pq is not None
    got = ann_svc.search_many(queries, k=10)
    want = exact_svc.search_many(queries, k=10)
    overlap = np.mean([
        len({r["page_id"] for r in g} & {r["page_id"] for r in w})
        / max(len(w), 1)
        for g, w in zip(got, want)])
    assert overlap >= 0.95, f"serving overlap {overlap:.3f} < 0.95"
    assert ann_svc.ann_fallbacks == 0
    met = ann_svc.metrics()
    assert met["ann_gather_bytes"] > 0
    assert met["ann_index"]["pq_m"] == 6
    assert met["ann_index"]["hot_rows"] == 0      # hot staging is opt-in


def test_payload_bytes_drop_vs_stored_width(tmp_path):
    """The bandwidth acceptance: on an int8 store at a serving-shaped
    operating point, the measured candidate-gather bytes (codes + exact
    re-rank rows) drop >= 3x vs the stored-width gather for the SAME
    queries, and hot staging removes the code gather on top. Results of
    the hot and mmap paths are identical."""
    store, vecs = _synth_store(tmp_path)
    mesh = make_mesh(MeshConfig(data=4))
    rng = np.random.default_rng(0)
    q = vecs[rng.choice(store.num_vectors, 8, replace=False)]

    # rerank pinned at the serving-shaped depth: at this toy scale the
    # re-rank union is a visible fraction of the corpus, while at real
    # scale the code gather dominates and the ratio tends to row_bytes/m
    plain = IVFIndex.build(store, mesh, nlist=32, iters=4, seed=0)
    _, ids_plain, st_plain = plain.search(q, k=10, nprobe=8, rerank=32)
    pq = IVFIndex.build(store, mesh, nlist=32, iters=4, seed=0, pq_m=8)
    _, ids_pq, st_pq = pq.search(q, k=10, nprobe=8, rerank=32)
    assert st_plain["gather_bytes"] >= 3 * st_pq["gather_bytes"], (
        f"payload drop {st_plain['gather_bytes']}/{st_pq['gather_bytes']}"
        f" = {st_plain['gather_bytes'] / st_pq['gather_bytes']:.2f}x < 3x")
    # same coarse quantizer (same seed): candidate accounting agrees
    assert st_pq["candidates_reranked"] == st_plain["candidates_reranked"]

    hot_info = pq.stage_hot(1 << 30)
    assert hot_info["hot_rows"] == store.num_vectors
    s_hot, ids_hot, st_hot = pq.search(q, k=10, nprobe=8, rerank=32)
    np.testing.assert_array_equal(ids_hot, ids_pq)
    assert st_hot["gather_bytes"] < st_pq["gather_bytes"]
    assert st_hot["hot_rows_scored"] > 0

    # a partial budget stages only the biggest lists — results identical
    part = IVFIndex.open(store)
    info = part.stage_hot(12 * store.num_vectors // 4)
    assert 0 < info["hot_lists"] < part.nlist
    _, ids_part, _ = part.search(q, k=10, nprobe=8, rerank=32)
    np.testing.assert_array_equal(ids_part, ids_pq)


def test_full_probe_adc_contract_fp16(tmp_path):
    """fp16 store end to end: at FULL probe with a deep re-rank the
    ADC+re-rank path recovers >= 0.95 of the exact top-10 (the re-rank
    scores are exact, so any miss is the ADC cut, bounded by rerank)."""
    store, vecs = _synth_store(tmp_path, n=600, d=32, nclust=12,
                               dtype="float16", shard=200)
    mesh = make_mesh(MeshConfig(data=4))
    idx = IVFIndex.build(store, mesh, nlist=8, iters=4, seed=0, pq_m=4)
    q = vecs[np.random.default_rng(1).choice(600, 16, replace=False)]
    _, ann_ids, _ = idx.search(q, k=10, nprobe=8, rerank=64)
    _, exact_ids = topk_over_store(q, store, mesh, k=10)
    rec = np.mean([len(set(a.tolist()) & set(e.tolist())) / 10
                   for a, e in zip(ann_ids, exact_ids)])
    assert rec >= 0.95, f"full-probe ADC recall {rec:.3f} < 0.95"


def test_balanced_assignment_caps_lists(tmp_path):
    """serve.kmeans_balance (the carried-over ROADMAP item): the capped
    final sweep lowers the imbalance factor vs the raw argmax, keeps
    every row in exactly one list, and full-probe results are unaffected
    (which list a row waits in never changes exact-scored outcomes)."""
    store, vecs = _synth_store(tmp_path, n=600, d=32, nclust=6,
                               dtype="float16", shard=200)
    mesh = make_mesh(MeshConfig(data=4))
    raw = IVFIndex.build(store, mesh, nlist=12, iters=4, seed=0)
    bal = IVFIndex.build(store, mesh, nlist=12, iters=4, seed=0,
                         balance=1.2)
    assert int(bal.list_sizes.sum()) == store.num_vectors
    assert bal.manifest["balance_cap"] == int(np.ceil(1.2 * 600 / 12))
    assert bal.manifest["imbalance_raw"] == raw.manifest["imbalance"]
    assert bal.imbalance <= bal.manifest["imbalance_raw"]
    q = vecs[np.random.default_rng(2).choice(600, 8, replace=False)]
    _, ids_bal, _ = bal.search(q, k=10, nprobe=12)
    _, ids_exact = topk_over_store(q, store, mesh, k=10)
    for a, e in zip(ids_bal, ids_exact):
        assert set(a.tolist()) == set(e.tolist())


def test_incremental_update_appends_codes(env, tmp_path):
    """A store append extends a PQ index in O(new shards): the new
    shard gets a code file encoded with the EXISTING rotation/codebooks
    (byte-stable across the update), and appended rows are servable
    through the ADC path."""
    from dnn_page_vectors_tpu.data.toy import ToyCorpus
    from dnn_page_vectors_tpu.updates import append_corpus
    emb, trainer = env["emb"], env["trainer"]
    store = _copy_store(env, tmp_path)
    IVFIndex.build(store, emb.mesh, nlist=8, iters=3, seed=0, pq_m=6)
    rot_before = open(os.path.join(index_dir(store), "pq_rotation.npy"),
                      "rb").read()
    corpus2 = ToyCorpus(num_pages=400, seed=trainer.corpus.seed,
                        num_topics=trainer.corpus.num_topics,
                        page_len=trainer.corpus.page_len,
                        query_len=trainer.corpus.query_len,
                        languages=trainer.corpus.languages)
    append_corpus(emb, corpus2, store)
    idx, info = IVFIndex.update(store, emb.mesh, rebuild_drift=0.5)
    assert info["action"] == "incremental"
    assert idx.pq is not None
    new_meta = [s for s in idx.manifest["shards"] if s["index"] == 3][0]
    assert "pqc" in new_meta
    assert open(os.path.join(index_dir(store), "pq_rotation.npy"),
                "rb").read() == rot_before
    # appended rows come back through ADC at full probe, queried with
    # their own stored vectors (exact re-rank puts self at top-1)
    all_ids, all_vecs = store.load_all()
    lut = {int(i): np.asarray(v, np.float32)
           for i, v in zip(all_ids, all_vecs) if i >= 0}
    qv = np.stack([lut[320], lut[399]])
    _, got, _ = idx.search(qv, k=10, nprobe=idx.nlist)
    assert got[0][0] == 320 and got[1][0] == 399


def test_code_file_corruption_quarantines_to_exact(env, tmp_path):
    """A seeded FaultPlan corrupts one PQ code file post-fsync: open()
    must quarantine it and report the index unavailable; a
    serve.index=ivf service answers every query through the exact path
    with identical results to an exact service, counting fallbacks.
    (Write order: centroids, 3x(ord, off), rotation, codebooks, codes —
    occurrence 9 is the first code file.)"""
    store = _copy_store(env, tmp_path)
    emb, trainer = env["emb"], env["trainer"]
    faults.install(faults.FaultPlan.parse("index_file:bit_flip:9", seed=7))
    IVFIndex.build(store, emb.mesh, nlist=8, iters=3, seed=0, pq_m=6)
    with pytest.raises(IndexUnavailable):
        IVFIndex.open(store)
    assert faults.counters().get("quarantined_index_files") == 1
    quarantined = [n for n in os.listdir(index_dir(store))
                   if n.endswith(".pqc.npy.quarantined")]
    assert len(quarantined) == 1
    svc = SearchService(_ivf_cfg(env), emb, trainer.corpus, store,
                        preload_hbm_gb=4.0)
    assert svc._index is None and "rebuild" in (svc._index_error or "")
    exact = SearchService(env["cfg"], emb, trainer.corpus, store,
                          preload_hbm_gb=4.0)
    queries = [trainer.corpus.query_text(i) for i in (2, 77, 290)]
    got = svc.search_many(queries, k=10)
    want = exact.search_many(queries, k=10)
    assert [[r["page_id"] for r in g] for g in got] == \
        [[r["page_id"] for r in w] for w in want]
    assert svc.ann_fallbacks == len(queries)


def test_cli_index_pq_flag_and_json(env, capsys):
    """`cli index --pq` wires the small-config PQ build end to end: the
    JSON reports the auto subspace count, the codebook build time, and
    the balance fields; `search --nprobe` then serves through ADC."""
    from dnn_page_vectors_tpu import cli
    base = ["--config", "cdssm_toy", "--workdir", env["wd"]] + [
        x for key, val in _OV.items() for x in ("--set", f"{key}={val}")]
    cli.main(["index", "--pq"] + base + [
        "--set", "serve.nlist=16", "--set", "serve.kmeans_balance=1.2"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["nlist"] == 16 and out["pq_m"] == 6      # auto: 48 / 8
    assert out["codebook_build_seconds"] > 0
    assert out["balance_cap"] == int(np.ceil(1.2 * 300 / 16))
    assert round(out["imbalance_raw"] - out["imbalance"], 4) == \
        out["imbalance_balance_delta"]
    gold = 3
    query = env["trainer"].corpus.query_text(gold)
    cli.main(["search", "--query", query, "--nprobe", "12"] + base)
    res = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(res["results"]) == 10
    assert gold in [r["page_id"] for r in res["results"]]


def test_hot_postings_through_service(env):
    """serve.hot_postings_gb stages the hot posting set at view build:
    results match the mmap-gather service exactly and the hot rows
    surface in metrics()."""
    store, emb, trainer = env["store"], env["emb"], env["trainer"]
    IVFIndex.build(store, emb.mesh, seed=0, pq_m=6)
    cold = SearchService(_ivf_cfg(env), emb, trainer.corpus, store,
                         preload_hbm_gb=0.0)
    hot = SearchService(_ivf_cfg(env, hot_postings_gb=1.0), emb,
                        trainer.corpus, store, preload_hbm_gb=0.0)
    assert hot._index.hot_rows == store.num_vectors
    queries = [trainer.corpus.query_text(i) for i in range(0, 300, 31)]
    got = hot.search_many(queries, k=10)
    want = cold.search_many(queries, k=10)
    assert [[r["page_id"] for r in g] for g in got] == \
        [[r["page_id"] for r in w] for w in want]
    met = hot.metrics()
    assert met["ann_index"]["hot_rows"] == store.num_vectors
    assert met["ann_gather_bytes"] < cold.metrics()["ann_gather_bytes"]


@pytest.mark.slow
def test_large_codebook_build(env, tmp_path):
    """Large-codebook variant: a finer split (m=12, dsub=4) over the toy
    store still builds deterministically-shaped artifacts, every row
    encodes, and a deep re-rank at full probe recovers the exact set."""
    store = _copy_store(env, tmp_path)
    emb = env["emb"]
    idx = IVFIndex.build(store, emb.mesh, nlist=16, iters=8, seed=0,
                         pq_m=12, opq_iters=4)
    assert idx.pq.m == 12 and idx.pq.dsub == 4
    assert int(idx.list_sizes.sum()) == store.num_vectors
    for s in idx.manifest["shards"]:
        if s["count"]:
            codes = np.load(os.path.join(index_dir(store), s["pqc"]))
            assert codes.shape == (s["count"], 12)
    qv = np.asarray(emb.embed_texts(
        [env["trainer"].corpus.query_text(i) for i in range(40)],
        tower="query"), np.float32)
    r = recall_vs_exact(idx, store, qv, emb.mesh, k=10, nprobe=16)
    assert r >= 0.95
