"""Ring attention (sequence parallelism) must be exactly full attention:
shard the sequence over the 'seq' mesh axis, rotate KV around the ring, and
compare against the dense reference on the 8-fake-device CPU mesh — values
AND gradients (ppermute transposes correctly under autodiff)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_page_vectors_tpu.config import MeshConfig
from dnn_page_vectors_tpu.ops.flash_attention import reference_attention
from dnn_page_vectors_tpu.parallel.mesh import make_mesh
from dnn_page_vectors_tpu.parallel.ring_attention import ring_attention


def _mk(B=4, H=2, L=64, Dh=16, seed=0, pad_tail=9):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, H, L, Dh)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, L, Dh)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, L, Dh)), jnp.float32)
    mask = np.ones((B, L), bool)
    mask[:, -pad_tail:] = False
    return q, k, v, jnp.asarray(mask)


@pytest.mark.parametrize("mesh_cfg", [MeshConfig(1, 1, 8),
                                      MeshConfig(2, 1, 4),
                                      MeshConfig(2, 2, 2)])
def test_ring_matches_reference(mesh_cfg, eight_devices):
    mesh = make_mesh(mesh_cfg)
    q, k, v, mask = _mk()
    want = reference_attention(q, k, v, mask)
    got = jax.jit(lambda *a: ring_attention(mesh, *a))(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_ring_gradients_match_reference(eight_devices):
    mesh = make_mesh(MeshConfig(1, 1, 8))
    q, k, v, mask = _mk(B=2, L=32, pad_tail=5)

    g_ring = jax.grad(
        lambda q, k, v: (ring_attention(mesh, q, k, v, mask) ** 2).sum(),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: (reference_attention(q, k, v, mask) ** 2).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_ring_t5_bias_matches_reference(eight_devices):
    # T5 relative-position bias across the ring: each step rebuilds its
    # bias block from global positions; must equal the dense reference with
    # the full materialised [H, L, L] bias (values and gradients through
    # the bias table).
    from dnn_page_vectors_tpu.models.transformer import _relative_position_bucket

    mesh = make_mesh(MeshConfig(1, 1, 8))
    B, H, L, Dh = 2, 2, 64, 16
    q, k, v, mask = _mk(B=B, H=H, L=L, Dh=Dh)
    rng = np.random.default_rng(7)
    table = jnp.asarray(rng.normal(size=(32, H)), jnp.float32)

    pos = jnp.arange(L)
    buckets = _relative_position_bucket(pos[None, :] - pos[:, None])

    def dense_bias(t):
        return t[buckets].transpose(2, 0, 1)       # [H, L, L]

    want = reference_attention(q, k, v, mask, bias=dense_bias(table))
    got = jax.jit(lambda *a: ring_attention(
        mesh, *a, bias_table=table,
        bucket_fn=_relative_position_bucket))(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    g_ring = jax.grad(lambda t: (ring_attention(
        mesh, q, k, v, mask, bias_table=t,
        bucket_fn=_relative_position_bucket) ** 2).sum())(table)
    g_ref = jax.grad(lambda t: (reference_attention(
        q, k, v, mask, bias=dense_bias(t)) ** 2).sum())(table)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-4)


def test_ring_single_seq_device_degenerates(eight_devices):
    # seq=1: the ring is one hop; must still equal reference
    mesh = make_mesh(MeshConfig(8, 1, 1))
    q, k, v, mask = _mk(B=8)
    want = reference_attention(q, k, v, mask)
    got = ring_attention(mesh, q, k, v, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
