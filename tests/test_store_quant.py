"""int8 vector store: quantization round-trip bounds and end-to-end recall
parity with the fp16 store (eval.store_dtype knob)."""
import os

import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.evals.recall import evaluate_recall
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.train.loop import Trainer


def test_int8_round_trip_error_bound(tmp_path):
    rng = np.random.default_rng(0)
    v = rng.normal(size=(100, 64)).astype(np.float32)
    v /= np.linalg.norm(v, axis=1, keepdims=True)
    store = VectorStore(str(tmp_path), dim=64, shard_size=128, dtype="int8")
    store.write_shard(0, np.arange(100), v)
    ids, got = store.load_shard(0)
    # symmetric per-row quantization with a shared fp16-rounded scale:
    # |err| <= scale/2; the fp16 rounding can inflate scale by <= 2^-11
    bound = ((np.abs(v).max(axis=1) / 254.0) * (1 + 2**-10) + 1e-7)[:, None]
    assert (np.abs(np.asarray(got) - v) <= bound).all()
    # int8 codes on disk: vec file ~half the fp16 size
    vec = os.path.getsize(str(tmp_path / "shard_00000.vec.npy"))
    assert vec < 100 * 64 * 2  # smaller than the fp16 layout
    # degenerate all-zero row: no div-by-zero, exact zero round-trip
    z = np.zeros((3, 64), np.float32)
    z[1] = v[0]
    store.write_shard(1, np.arange(100, 103), z)
    _, got_z = store.load_shard(1)
    assert np.asarray(got_z)[0].max() == 0.0
    assert np.asarray(got_z)[2].max() == 0.0


@pytest.mark.slow
def test_int8_store_recall_matches_fp16(tmp_path):
    cfg = get_config("cdssm_toy", {
        "data.num_pages": 300,
        "data.trigram_buckets": 2048,
        "model.embed_dim": 48,
        "model.conv_channels": 96,
        "model.out_dim": 48,
        "train.batch_size": 64,
        "train.steps": 60,
        "train.warmup_steps": 10,
        "train.learning_rate": 2e-3,
        "train.log_every": 1000,
        "eval.embed_batch_size": 100,
    })
    trainer = Trainer(cfg, workdir=str(tmp_path))
    state, _ = trainer.train()
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, query_tok=trainer.query_tok)
    recalls = {}
    for dtype in ("float16", "int8"):
        store = VectorStore(str(tmp_path / f"store_{dtype}"),
                            dim=cfg.model.out_dim, shard_size=100,
                            dtype=dtype)
        emb.embed_corpus(trainer.corpus, store)
        recalls[dtype], _ = evaluate_recall(emb, trainer.corpus, store,
                                            num_queries=300, k=10)
    assert recalls["float16"] > 0.3          # trained above chance (~3%)
    assert abs(recalls["int8"] - recalls["float16"]) <= 0.02, recalls


def test_dtype_switch_requires_reset(tmp_path):
    store = VectorStore(str(tmp_path), dim=16, shard_size=32, dtype="int8")
    store.write_shard(0, np.arange(4), np.ones((4, 16), np.float32))
    with pytest.raises(ValueError, match="dtype"):
        VectorStore(str(tmp_path), dtype="float16")
    # empty store adopts the new dtype
    store.reset()
    s2 = VectorStore(str(tmp_path), dtype="float16")
    assert s2.manifest["dtype"] == "float16"


def test_staged_bytes_at_stored_width(tmp_path, eight_devices):
    """VERDICT r4 Weak #3 done-criterion: the device arrays staged for an
    int8 store are ~half the fp16 store's bytes (int8 codes + fp16 per-row
    scales vs fp16 rows; both are 2x/4x under the old fp32 staging), and the
    device-side dequant reproduces the host-dequant scores exactly."""
    import jax.numpy as jnp

    from dnn_page_vectors_tpu.config import MeshConfig
    from dnn_page_vectors_tpu.ops.topk import stage_shard, topk_over_store
    from dnn_page_vectors_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(5)
    n, dim = 96, 32
    v = rng.normal(size=(n, dim)).astype(np.float32)
    v /= np.linalg.norm(v, axis=1, keepdims=True)
    mesh = make_mesh(MeshConfig(data=8))
    staged = {}
    stores = {}
    for dtype in ("float16", "int8"):
        store = VectorStore(str(tmp_path / dtype), dim=dim, shard_size=n,
                            dtype=dtype)
        store.write_shard(0, np.arange(n), v)
        stores[dtype] = store
        ids, raw, scl = next(store.iter_shards(raw=True))
        pages, scales = stage_shard(raw, n, dim, mesh, scales=scl)
        staged[dtype] = pages.nbytes + (scales.nbytes if scales is not None
                                        else 0)
        assert pages.dtype == (jnp.float16 if dtype == "float16"
                               else jnp.int8)
    assert staged["float16"] == n * dim * 2
    assert staged["int8"] == n * dim + n * 2     # codes + fp16 scales
    assert staged["int8"] < 0.6 * staged["float16"]

    # device-side (q @ codes) * scale == host-dequant oracle, exactly: the
    # scale multiply commutes out of the dot product in REAL arithmetic and
    # both paths round identically ordered fp32 ops
    q = rng.normal(size=(7, dim)).astype(np.float32)
    s8, i8 = topk_over_store(q, stores["int8"], mesh, k=5, chunk=16)
    _, host_rows = stores["int8"].load_shard(0)   # host-dequant fp32 rows
    ref = q @ np.asarray(host_rows, np.float32).T
    ref_idx = np.argsort(-ref, axis=1)[:, :5]
    np.testing.assert_allclose(
        s8, np.take_along_axis(ref, ref_idx, axis=1), rtol=2e-5, atol=2e-5)
    assert (i8 == ref_idx).mean() > 0.95          # ranking parity


def test_device_quantize_matches_host_quantize(tmp_path, eight_devices):
    """Round 5: int8 stores quantize ON DEVICE (bulk_embed q8 wire, 1 B/dim
    over the D2H wire). The device path must produce byte-identical shards
    to host-side write_shard quantizing the same fp16 vectors — same scale
    rounding, same floor, same rint — so int8 stores stay bit-reproducible
    across wire paths and process topologies."""
    from dnn_page_vectors_tpu.config import MeshConfig
    from dnn_page_vectors_tpu.parallel.mesh import make_mesh

    cfg = get_config("cdssm_toy", {
        "data.num_pages": 256,
        "data.trigram_buckets": 2048,
        "model.embed_dim": 32,
        "model.conv_channels": 64,
        "model.out_dim": 32,
        "eval.embed_batch_size": 64,    # divides the 8-device mesh
        "eval.store_shard_size": 128,
        "eval.store_dtype": "int8",
    })
    trainer = Trainer(cfg, workdir=str(tmp_path))
    state = trainer.init_state()
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       make_mesh(MeshConfig(data=8)), trainer.query_tok)
    dev_store = VectorStore(str(tmp_path / "dev"), dim=32, shard_size=128,
                            dtype="int8")
    emb.embed_corpus(trainer.corpus, dev_store)

    fp_store = VectorStore(str(tmp_path / "fp16"), dim=32, shard_size=128,
                           dtype="float16")
    emb.embed_corpus(trainer.corpus, fp_store)
    host_store = VectorStore(str(tmp_path / "host"), dim=32, shard_size=128,
                             dtype="int8")
    for entry in fp_store.shards():
        ids, v16, _ = fp_store._load_entry(entry, raw=True)
        host_store.write_shard(entry["index"], ids, np.asarray(v16))

    for entry in dev_store.shards():
        i = entry["index"]
        ids_d, codes_d, scl_d = dev_store._load_entry(entry, raw=True)
        ids_h, codes_h, scl_h = host_store._load_entry(
            {s["index"]: s for s in host_store.shards()}[i], raw=True)
        np.testing.assert_array_equal(ids_d, ids_h)
        np.testing.assert_array_equal(np.asarray(scl_d), np.asarray(scl_h))
        np.testing.assert_array_equal(np.asarray(codes_d),
                                      np.asarray(codes_h))
