"""Fault-matrix tests (docs/ROBUSTNESS.md): every recovery path driven by a
seeded FaultPlan — transient-write retry, persistent-fault re-raise,
truncated-shard quarantine + re-embed, torn writer manifest, corrupt-latest
checkpoint rollback, serve degradation — plus the end-to-end
embed→train-resume→serve run under the combined fault plan."""
import json
import os

import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.serve import SearchService
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.train.checkpoint import CheckpointManager
from dnn_page_vectors_tpu.train.loop import Trainer
from dnn_page_vectors_tpu.utils import faults
from dnn_page_vectors_tpu.utils.logging import MetricsLogger

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_faults():
    """Every test starts and ends with no plan installed and zero counters
    (the module state is process-global by design)."""
    faults.reset()
    yield
    faults.reset()


def _cfg(**extra):
    ov = {
        "data.num_pages": 256,
        "data.trigram_buckets": 1024,
        "model.embed_dim": 32,
        "model.conv_channels": 32,
        "model.out_dim": 32,
        "model.dtype": "float32",
        "train.batch_size": 64,
        "train.steps": 6,
        "train.warmup_steps": 2,
        "train.log_every": 100,
        "train.checkpoint_every": 2,
        "eval.embed_batch_size": 32,
        "eval.store_shard_size": 64,
    }
    ov.update(extra)
    return get_config("cdssm_toy", ov)


def _embedder(cfg, tmp_path, train=False):
    trainer = Trainer(cfg, workdir=str(tmp_path / "t"))
    state, _ = (trainer.train() if train else (trainer.init_state(), None))
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, query_tok=trainer.query_tok)
    return trainer, state, emb


def _store_bytes(store):
    ids, vecs = store.load_all()
    order = np.argsort(ids)
    return ids[order], np.asarray(vecs)[order]


# -- FaultPlan unit behaviour ------------------------------------------------

def test_fault_plan_parse_and_schedule():
    plan = faults.FaultPlan.parse(
        "a:io_error:1,b:truncate:0:2,c:delay:0,d:io_error:0:*", seed=7)
    # a: fires only on the 2nd call
    plan.check("a")
    with pytest.raises(faults.InjectedFault):
        plan.check("a")
    plan.check("a")                       # transient: exhausted after count=1
    # d: persistent — every call raises
    for _ in range(3):
        with pytest.raises(IOError):      # InjectedFault IS an IOError
            plan.check("d")
    assert plan.pending("b") and not plan.pending("a")
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("a:nonsense:0")


def test_retry_transient_succeeds_persistent_reraises():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert faults.retry(flaky, op="t", backoff=0.001, jitter=0.0) == "ok"
    assert faults.counters()["retry_t"] == 2

    class Specific(OSError):
        pass

    def dead():
        raise Specific("persistent")

    # the ORIGINAL exception type survives the retry wrapper
    with pytest.raises(Specific):
        faults.retry(dead, op="p", backoff=0.001, jitter=0.0)


# -- store integrity ---------------------------------------------------------

def test_truncated_shard_quarantined_and_reembedded(tmp_path):
    cfg = _cfg()
    trainer, _, emb = _embedder(cfg, tmp_path)

    clean = VectorStore(str(tmp_path / "clean"), dim=32, shard_size=64)
    emb.embed_corpus(trainer.corpus, clean)

    hurt = VectorStore(str(tmp_path / "hurt"), dim=32, shard_size=64)
    emb.embed_corpus(trainer.corpus, hurt)
    # externally truncate shard 2's vector file (4 shards of 64 pages)
    victim = os.path.join(hurt.directory, "shard_00002.vec.npy")
    with open(victim, "r+b") as f:
        f.truncate(os.path.getsize(victim) // 2)

    # reopening verifies + quarantines; the shard falls out of the table
    reopened = VectorStore(str(tmp_path / "hurt"))
    assert reopened.completed_shards() == {0, 1, 3}
    assert os.path.exists(victim + ".quarantined")
    assert faults.counters()["quarantined_shards"] == 1

    # resume re-embeds exactly the quarantined range; bytes match clean
    emb.embed_corpus(trainer.corpus, reopened)
    ids_a, vecs_a = _store_bytes(clean)
    ids_b, vecs_b = _store_bytes(reopened)
    np.testing.assert_array_equal(ids_a, ids_b)
    np.testing.assert_array_equal(vecs_a, vecs_b)   # byte-identical fp16


def test_bit_flip_detected_by_crc(tmp_path):
    store = VectorStore(str(tmp_path / "s"), dim=8, shard_size=4)
    store.write_shard(0, np.arange(4), np.ones((4, 8), np.float32))
    path = os.path.join(store.directory, "shard_00000.vec.npy")
    # flip one bit in the payload (past the 128-byte npy header) — size is
    # unchanged, so only the CRC can catch it
    with open(path, "r+b") as f:
        f.seek(130)
        b = f.read(1)
        f.seek(130)
        f.write(bytes([b[0] ^ 0x04]))
    entry = store.shards()[0]
    err = store.entry_error(entry)
    assert err and "CRC" in err
    assert VectorStore(str(tmp_path / "s")).completed_shards() == set()


def test_torn_writer_manifest_quarantined(tmp_path):
    store = VectorStore(str(tmp_path / "s"), dim=8, shard_size=4)
    store.write_shard(0, np.arange(4), np.ones((4, 8), np.float32))
    torn = os.path.join(store.directory, "manifest.w0002.json")
    with open(torn, "w") as f:
        f.write('{"shards": [{"index"')      # torn mid-write
    fresh = VectorStore(str(tmp_path / "s"))
    assert fresh.completed_shards() == {0}   # reader survives
    assert not os.path.exists(torn)
    assert os.path.exists(torn + ".quarantined")
    assert faults.counters()["quarantined_manifests"] == 1


def test_transient_write_fault_retries_inside_embed(tmp_path):
    cfg = _cfg()
    trainer, _, emb = _embedder(cfg, tmp_path)
    faults.install(faults.FaultPlan.parse("shard_write:io_error:1", seed=0))
    store = VectorStore(str(tmp_path / "s"), dim=32, shard_size=64)
    emb.embed_corpus(trainer.corpus, store)    # survives via retry
    assert store.num_vectors == 256
    fc = faults.counters()
    assert fc["injected_shard_write_io_error"] == 1
    assert fc["retry_shard_write"] == 1


def test_persistent_write_fault_reraises_at_close(tmp_path):
    cfg = _cfg()
    trainer, _, emb = _embedder(cfg, tmp_path)
    faults.install(faults.FaultPlan.parse("shard_write:io_error:1:*", seed=0))
    store = VectorStore(str(tmp_path / "s"), dim=32, shard_size=64)
    with pytest.raises(IOError):
        emb.embed_corpus(trainer.corpus, store)
    # the shard before the persistent fault is durably recorded; resume
    # bookkeeping is intact
    assert VectorStore(str(tmp_path / "s")).completed_shards() == {0}


# -- checkpoint rollback -----------------------------------------------------

def test_corrupt_latest_checkpoint_rolls_back(tmp_path):
    cfg = _cfg()
    trainer = Trainer(cfg, workdir=str(tmp_path))
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    state, _ = trainer.train(steps=6, ckpt_manager=mgr)  # saves at 2 and 4
    mgr.save(6, state, wait=True)
    assert mgr.all_steps() == [2, 4, 6]

    plan = faults.install(faults.FaultPlan.parse("ckpt_file:truncate:0",
                                                 seed=1))
    plan.corrupt_dir("ckpt_file", os.path.join(str(tmp_path / "ckpt"), "6"))
    restored = mgr.restore(trainer.init_state())
    assert int(restored.step) == 4
    fc = faults.counters()
    assert fc["ckpt_rollback"] == 1 and fc["ckpt_restore_failed"] >= 1
    # the rolled-back state trains on
    resumed, _ = trainer.train(steps=2, state=restored)
    assert int(resumed.step) == 6
    mgr.close()


def test_restore_explicit_missing_step_and_idempotent_close(tmp_path):
    cfg = _cfg()
    trainer = Trainer(cfg, workdir=str(tmp_path))
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    state = trainer.init_state()
    mgr.save(2, state, wait=True)
    with pytest.raises(FileNotFoundError) as ei:
        mgr.restore(state, step=7)
    assert "step 7" in str(ei.value) and "[2]" in str(ei.value)
    empty = CheckpointManager(str(tmp_path / "none"))
    with pytest.raises(FileNotFoundError):
        empty.restore(state)
    # close() twice (e.g. explicit + finally-block cleanup) must not raise
    mgr.close()
    mgr.close()
    empty.close()
    empty.close()


# -- serve degradation -------------------------------------------------------

def test_serve_falls_back_to_streaming_on_staging_fault(tmp_path):
    cfg = _cfg()
    trainer, state, emb = _embedder(cfg, tmp_path, train=True)
    store = VectorStore(str(tmp_path / "s"), dim=32, shard_size=64)
    emb.embed_corpus(trainer.corpus, store)

    # ground truth: a fault-free fully-streaming service
    stream = SearchService(cfg, emb, trainer.corpus, store,
                           preload_hbm_gb=0.0)
    assert not stream.preloaded

    # second shard's HBM staging fails -> per-shard streaming fallback
    faults.install(faults.FaultPlan.parse("hbm_stage:io_error:1", seed=0))
    log = MetricsLogger(str(tmp_path / "m"), echo=False)
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0,
                        log=log)
    assert svc.preloaded and svc.degraded
    assert len(svc._shards) == 3 and len(svc._stream_entries) == 1
    assert svc.fault_counters["serve_stage_faults"] == 1

    # fault counters are in the metrics log
    with open(os.path.join(str(tmp_path / "m"), "metrics.jsonl")) as f:
        rec = json.loads(f.readlines()[-1])
    assert rec["serve_degraded"] is True
    assert rec["serve_stream_shards"] == 1
    assert rec["fault_counters"]["serve_stage_faults"] == 1

    # degraded results == streaming results (same vectors, same ranking)
    for qi in (0, 42, 200):
        q = trainer.corpus.query_text(qi)
        a, b = svc.search(q, k=10), stream.search(q, k=10)
        assert [r["page_id"] for r in a] == [r["page_id"] for r in b]
        np.testing.assert_allclose([r["score"] for r in a],
                                   [r["score"] for r in b], atol=1e-4)


def test_serve_quarantines_corrupt_shard_at_staging(tmp_path):
    cfg = _cfg()
    trainer, state, emb = _embedder(cfg, tmp_path, train=False)
    store = VectorStore(str(tmp_path / "s"), dim=32, shard_size=64)
    emb.embed_corpus(trainer.corpus, store)
    # corrupt shard 1 AFTER the store object verified on open
    victim = os.path.join(store.directory, "shard_00001.vec.npy")
    with open(victim, "r+b") as f:
        f.truncate(os.path.getsize(victim) // 2)
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    assert svc.degraded
    assert svc.fault_counters["serve_quarantined_shards"] == 1
    assert store.completed_shards() == {0, 2, 3}    # dropped from the table
    # the service still answers (without the quarantined range)
    assert len(svc.search(trainer.corpus.query_text(0), k=5)) == 5


# -- the end-to-end acceptance scenario --------------------------------------

def test_e2e_fault_matrix_embed_train_serve(tmp_path):
    """One seeded plan: a transient write fault, a truncated shard, a
    corrupt latest checkpoint, and a staging fault — one
    embed -> resume -> train -> rollback-restore -> serve run survives all
    four, with byte-identical surviving vectors and visible counters."""
    cfg = _cfg()
    trainer = Trainer(cfg, workdir=str(tmp_path / "t"))
    state = trainer.init_state()
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, query_tok=trainer.query_tok)

    # fault-free reference store
    clean = VectorStore(str(tmp_path / "clean"), dim=32, shard_size=64)
    emb.embed_corpus(trainer.corpus, clean)

    faults.install(faults.FaultPlan.parse(
        # embed: 2nd shard write fails once (retried), 3rd shard's file is
        # truncated on disk after its checksum was recorded
        "shard_write:io_error:1,shard_file:truncate:2,"
        # train: the 3rd checkpoint save's files are torn on disk
        "ckpt_file:truncate:2,"
        # serve: the 1st shard staging attempt fails
        "hbm_stage:io_error:0", seed=42))

    # -- embed under faults ------------------------------------------------
    store = VectorStore(str(tmp_path / "s"), dim=32, shard_size=64)
    emb.embed_corpus(trainer.corpus, store)      # transient fault retried
    assert store.num_vectors == 256              # all shards recorded...
    # ...but shard 2's bytes are silently corrupt; resume catches it
    resumed = VectorStore(str(tmp_path / "s"))
    assert resumed.completed_shards() == {0, 1, 3}
    emb.embed_corpus(trainer.corpus, resumed)    # re-embeds exactly shard 2
    ids_a, vecs_a = _store_bytes(clean)
    ids_b, vecs_b = _store_bytes(resumed)
    np.testing.assert_array_equal(ids_a, ids_b)
    np.testing.assert_array_equal(vecs_a, vecs_b)

    # -- train with a corrupt latest checkpoint ----------------------------
    # (train on its OWN state: the compiled step donates its input state,
    # and the embedder above must keep its params alive for serving)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    tstate, _ = trainer.train(steps=6, ckpt_manager=mgr)
    mgr.save(6, tstate, wait=True)               # ckpt_file spec tears this
    restored = mgr.restore(trainer.init_state())
    assert int(restored.step) == 4               # rolled back
    tstate, _ = trainer.train(steps=2, state=restored)
    assert int(tstate.step) == 6                 # resumed to completion
    mgr.close()

    # -- serve in degraded mode --------------------------------------------
    log = MetricsLogger(str(tmp_path / "m"), echo=False)
    svc = SearchService(cfg, emb, trainer.corpus, resumed,
                        preload_hbm_gb=4.0, log=log)
    assert svc.preloaded and svc.degraded
    assert len(svc._stream_entries) == 1
    stream = SearchService(cfg, emb, trainer.corpus, resumed,
                           preload_hbm_gb=0.0)
    for qi in (0, 100):
        q = trainer.corpus.query_text(qi)
        a, b = svc.search(q, k=10), stream.search(q, k=10)
        assert [r["page_id"] for r in a] == [r["page_id"] for r in b]

    # -- every recovery path left a visible counter ------------------------
    with open(os.path.join(str(tmp_path / "m"), "metrics.jsonl")) as f:
        rec = json.loads(f.readlines()[-1])
    fc = rec["fault_counters"]
    assert fc["injected_shard_write_io_error"] == 1
    assert fc["retry_shard_write"] == 1
    assert fc["injected_shard_file_truncate"] == 1
    assert fc["quarantined_shards"] == 1
    assert fc["injected_ckpt_file_truncate"] == 1
    assert fc["ckpt_rollback"] == 1
    assert fc["injected_hbm_stage_io_error"] == 1
    assert fc["serve_stage_faults"] == 1
