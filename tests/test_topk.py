"""Unit tests: chunked on-device top-k vs numpy reference."""
import jax.numpy as jnp
import numpy as np

from dnn_page_vectors_tpu.ops.topk import chunked_topk


def _np_topk(q, pages, k):
    s = q @ pages.T
    idx = np.argsort(-s, axis=1)[:, :k]
    return np.take_along_axis(s, idx, axis=1), idx


def test_chunked_topk_matches_numpy():
    rng = np.random.default_rng(0)
    q = rng.normal(size=(5, 32)).astype(np.float32)
    pages = rng.normal(size=(1000, 32)).astype(np.float32)
    for chunk in (64, 128, 1000, 4096):
        s, i = chunked_topk(jnp.asarray(q), jnp.asarray(pages), k=7,
                            chunk=chunk)
        ns, ni = _np_topk(q, pages, 7)
        np.testing.assert_allclose(np.asarray(s), ns, rtol=1e-4, atol=1e-5)
        # indices can differ on exact ties; scores matching is the contract
        assert np.asarray(i).shape == (5, 7)
        top1_scores = (q * pages[np.asarray(i)[:, 0]]).sum(-1)
        np.testing.assert_allclose(top1_scores, ns[:, 0], rtol=1e-4)


def test_chunked_topk_small_corpus():
    # N < k: pad columns must come back as -inf / -1
    q = jnp.ones((2, 4))
    pages = jnp.ones((3, 4))
    s, i = chunked_topk(q, pages, k=5, chunk=8)
    s, i = np.asarray(s), np.asarray(i)
    assert (i[:, :3] >= 0).all()
    assert (i[:, 3:] == -1).all()
    assert np.isinf(s[:, 3:]).all()
