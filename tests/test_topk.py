"""Unit tests: chunked / sharded / store-streaming top-k vs numpy reference,
the argpartition host merge, and the double-buffered shard read-ahead."""
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_page_vectors_tpu.ops.topk import (
    chunked_topk, merge_topk_host, sharded_topk, topk_over_store)
from dnn_page_vectors_tpu.parallel.mesh import make_mesh
from dnn_page_vectors_tpu.config import MeshConfig


def _np_topk(q, pages, k):
    s = q @ pages.T
    idx = np.argsort(-s, axis=1)[:, :k]
    return np.take_along_axis(s, idx, axis=1), idx


def test_chunked_topk_matches_numpy():
    rng = np.random.default_rng(0)
    q = rng.normal(size=(5, 32)).astype(np.float32)
    pages = rng.normal(size=(1000, 32)).astype(np.float32)
    for chunk in (64, 128, 1000, 4096):
        s, i = chunked_topk(jnp.asarray(q), jnp.asarray(pages), k=7,
                            chunk=chunk)
        ns, ni = _np_topk(q, pages, 7)
        np.testing.assert_allclose(np.asarray(s), ns, rtol=1e-4, atol=1e-5)
        # indices can differ on exact ties; scores matching is the contract
        assert np.asarray(i).shape == (5, 7)
        top1_scores = (q * pages[np.asarray(i)[:, 0]]).sum(-1)
        np.testing.assert_allclose(top1_scores, ns[:, 0], rtol=1e-4)


def test_sharded_topk_matches_single_device(eight_devices):
    """VERDICT r1 #2: pages sharded over 'data' must reproduce the
    single-device ranking (cross-shard merge correctness)."""
    mesh = make_mesh(MeshConfig(data=8))
    rng = np.random.default_rng(1)
    q = rng.normal(size=(6, 16)).astype(np.float32)
    pages = rng.normal(size=(512, 16)).astype(np.float32)  # 64 rows/shard
    s1, i1 = chunked_topk(jnp.asarray(q), jnp.asarray(pages), k=9)
    s8, i8 = sharded_topk(jnp.asarray(q), jnp.asarray(pages), mesh, k=9,
                          chunk=32)
    np.testing.assert_allclose(np.asarray(s8), np.asarray(s1),
                               rtol=1e-4, atol=1e-5)
    # `valid` must mask the tail rows exactly like truncating the input
    sv, iv = sharded_topk(jnp.asarray(q), jnp.asarray(pages), mesh, k=9,
                          chunk=32, valid=200)
    st, _ = chunked_topk(jnp.asarray(q), jnp.asarray(pages[:200]), k=9)
    np.testing.assert_allclose(np.asarray(sv), np.asarray(st),
                               rtol=1e-4, atol=1e-5)
    assert (np.asarray(iv) < 200).all()


def test_topk_over_store_matches_brute_force(eight_devices, tmp_path):
    """Streaming the store shard-by-shard over the mesh must equal one giant
    in-memory search — no step materializes the full store."""
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore

    mesh = make_mesh(MeshConfig(data=8))
    rng = np.random.default_rng(2)
    dim, n = 16, 700                       # 3 shards: 256, 256, 188
    vecs = rng.normal(size=(n, dim)).astype(np.float32)
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    ids = np.arange(1000, 1000 + n)        # page ids != row numbers
    store = VectorStore(str(tmp_path / "store"), dim=dim, shard_size=256)
    for si in range(3):
        sl = slice(si * 256, min((si + 1) * 256, n))
        store.write_shard(si, ids[sl], vecs[sl])
    q = rng.normal(size=(33, dim)).astype(np.float32)
    scores, pids = topk_over_store(q, store, mesh, k=10, chunk=64,
                                   query_batch=8)
    # the store rounds vectors to fp16; the oracle must score what it stores
    ref_s = q @ vecs.astype(np.float16).astype(np.float32).T
    ref_idx = np.argsort(-ref_s, axis=1)[:, :10]
    np.testing.assert_allclose(
        scores, np.take_along_axis(ref_s, ref_idx, axis=1),
        rtol=1e-4, atol=1e-4)
    # ids must be the store's page ids, not row numbers
    assert set(np.unique(pids)) <= set(ids.tolist())


def test_chunked_topk_small_corpus():
    # N < k: pad columns must come back as -inf / -1
    q = jnp.ones((2, 4))
    pages = jnp.ones((3, 4))
    s, i = chunked_topk(q, pages, k=5, chunk=8)
    s, i = np.asarray(s), np.asarray(i)
    assert (i[:, :3] >= 0).all()
    assert (i[:, 3:] == -1).all()
    assert np.isinf(s[:, 3:]).all()


def test_merge_topk_host_partition_matches_full_sort():
    """The O(W) argpartition merge must select exactly the scores a full
    stable argsort selects (ids may differ only on exact ties), keep the
    row sorted descending, and keep -1 empty slots masked to -inf."""
    rng = np.random.default_rng(11)
    for nq, k in ((1, 1), (4, 10), (33, 7)):
        best_s = rng.normal(size=(nq, k)).astype(np.float32)
        best_i = rng.integers(0, 10_000, size=(nq, k)).astype(np.int64)
        new_s = rng.normal(size=(nq, k)).astype(np.float32)
        new_i = rng.integers(0, 10_000, size=(nq, k)).astype(np.int64)
        # empty slots (running merge mid-sweep) must never win
        best_i[:, -1] = -1
        new_i[0, 0] = -1
        ms, mi = merge_topk_host(best_s, best_i, new_s, new_i)
        cat_s = np.concatenate([best_s, new_s], axis=1)
        cat_i = np.concatenate([best_i, new_i], axis=1)
        cat_s = np.where(cat_i < 0, -np.inf, cat_s)
        ref = np.take_along_axis(
            cat_s, np.argsort(-cat_s, axis=1, kind="stable")[:, :k], axis=1)
        np.testing.assert_array_equal(ms, ref)
        assert (ms[:, :-1] >= ms[:, 1:]).all()
        assert (mi[np.isneginf(ms)] == -1).all() if np.isneginf(ms).any() \
            else True
        # every surviving id scores what the merge says it scores
        lookup = {}
        for r in range(nq):
            lookup.clear()
            for s, i in zip(cat_s[r], cat_i[r]):
                if i >= 0:
                    lookup.setdefault(int(i), set()).add(float(s))
            for s, i in zip(ms[r], mi[r]):
                if i >= 0:
                    assert float(s) in lookup[int(i)]


def test_read_ahead_order_and_error_propagation():
    from dnn_page_vectors_tpu.infer.vector_store import read_ahead

    assert list(read_ahead(iter(range(20)), depth=1)) == list(range(20))
    assert list(read_ahead(iter([]), depth=2)) == []

    def _boom():
        yield 1
        yield 2
        raise IOError("disk died mid-sweep")

    it = read_ahead(_boom(), depth=1)
    got = []
    with pytest.raises(IOError, match="disk died"):
        for x in it:
            got.append(x)
    assert got == [1, 2]    # items before the fault are delivered in order
    # an abandoning consumer must not deadlock against a blocked reader
    it = read_ahead(iter(range(1000)), depth=1)
    assert next(it) == 0
    it.close()


def test_topk_over_store_read_fault_reraises(eight_devices, tmp_path):
    """The prefetched sweep keeps the serial exception surface: a shard
    read failing on the reader thread re-raises at the consumer."""
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore
    from dnn_page_vectors_tpu.utils import faults

    mesh = make_mesh(MeshConfig(data=8))
    rng = np.random.default_rng(5)
    vecs = rng.normal(size=(64, 16)).astype(np.float32)
    store = VectorStore(str(tmp_path / "store"), dim=16, shard_size=32)
    store.write_shard(0, np.arange(32), vecs[:32])
    store.write_shard(1, np.arange(32, 64), vecs[32:])
    q = rng.normal(size=(3, 16)).astype(np.float32)
    faults.install(faults.FaultPlan.parse("shard_read:io_error:1", seed=0))
    try:
        with pytest.raises(IOError):
            topk_over_store(q, store, mesh, k=5, chunk=16)
    finally:
        faults.reset()


def test_topk_over_store_skips_empty_shard(eight_devices, tmp_path):
    """A zero-count shard (a writer whose whole range was padding) holds an
    empty page_ids array; the merge must skip it instead of indexing into it
    (ADVICE r4: page_ids[0] raised IndexError)."""
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore

    mesh = make_mesh(MeshConfig(data=8))
    rng = np.random.default_rng(3)
    dim = 16
    vecs = rng.normal(size=(40, dim)).astype(np.float32)
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    store = VectorStore(str(tmp_path / "store"), dim=dim, shard_size=64)
    store.write_shard(0, np.arange(40), vecs)
    # an all-padding write records a count=0 shard entry
    store.write_shard(1, np.full(8, -1, np.int64), np.zeros((8, dim)))
    assert [s["count"] for s in store.shards()] == [40, 0]
    q = rng.normal(size=(5, dim)).astype(np.float32)
    scores, pids = topk_over_store(q, store, mesh, k=10, chunk=16)
    ref_s = q @ vecs.astype(np.float16).astype(np.float32).T
    ref_idx = np.argsort(-ref_s, axis=1)[:, :10]
    np.testing.assert_allclose(
        scores, np.take_along_axis(ref_s, ref_idx, axis=1),
        rtol=1e-4, atol=1e-4)
    assert (pids >= 0).all() and (pids < 40).all()
