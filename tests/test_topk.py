"""Unit tests: chunked / sharded / store-streaming top-k vs numpy reference."""
import jax.numpy as jnp
import numpy as np

from dnn_page_vectors_tpu.ops.topk import (
    chunked_topk, sharded_topk, topk_over_store)
from dnn_page_vectors_tpu.parallel.mesh import make_mesh
from dnn_page_vectors_tpu.config import MeshConfig


def _np_topk(q, pages, k):
    s = q @ pages.T
    idx = np.argsort(-s, axis=1)[:, :k]
    return np.take_along_axis(s, idx, axis=1), idx


def test_chunked_topk_matches_numpy():
    rng = np.random.default_rng(0)
    q = rng.normal(size=(5, 32)).astype(np.float32)
    pages = rng.normal(size=(1000, 32)).astype(np.float32)
    for chunk in (64, 128, 1000, 4096):
        s, i = chunked_topk(jnp.asarray(q), jnp.asarray(pages), k=7,
                            chunk=chunk)
        ns, ni = _np_topk(q, pages, 7)
        np.testing.assert_allclose(np.asarray(s), ns, rtol=1e-4, atol=1e-5)
        # indices can differ on exact ties; scores matching is the contract
        assert np.asarray(i).shape == (5, 7)
        top1_scores = (q * pages[np.asarray(i)[:, 0]]).sum(-1)
        np.testing.assert_allclose(top1_scores, ns[:, 0], rtol=1e-4)


def test_sharded_topk_matches_single_device(eight_devices):
    """VERDICT r1 #2: pages sharded over 'data' must reproduce the
    single-device ranking (cross-shard merge correctness)."""
    mesh = make_mesh(MeshConfig(data=8))
    rng = np.random.default_rng(1)
    q = rng.normal(size=(6, 16)).astype(np.float32)
    pages = rng.normal(size=(512, 16)).astype(np.float32)  # 64 rows/shard
    s1, i1 = chunked_topk(jnp.asarray(q), jnp.asarray(pages), k=9)
    s8, i8 = sharded_topk(jnp.asarray(q), jnp.asarray(pages), mesh, k=9,
                          chunk=32)
    np.testing.assert_allclose(np.asarray(s8), np.asarray(s1),
                               rtol=1e-4, atol=1e-5)
    # `valid` must mask the tail rows exactly like truncating the input
    sv, iv = sharded_topk(jnp.asarray(q), jnp.asarray(pages), mesh, k=9,
                          chunk=32, valid=200)
    st, _ = chunked_topk(jnp.asarray(q), jnp.asarray(pages[:200]), k=9)
    np.testing.assert_allclose(np.asarray(sv), np.asarray(st),
                               rtol=1e-4, atol=1e-5)
    assert (np.asarray(iv) < 200).all()


def test_topk_over_store_matches_brute_force(eight_devices, tmp_path):
    """Streaming the store shard-by-shard over the mesh must equal one giant
    in-memory search — no step materializes the full store."""
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore

    mesh = make_mesh(MeshConfig(data=8))
    rng = np.random.default_rng(2)
    dim, n = 16, 700                       # 3 shards: 256, 256, 188
    vecs = rng.normal(size=(n, dim)).astype(np.float32)
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    ids = np.arange(1000, 1000 + n)        # page ids != row numbers
    store = VectorStore(str(tmp_path / "store"), dim=dim, shard_size=256)
    for si in range(3):
        sl = slice(si * 256, min((si + 1) * 256, n))
        store.write_shard(si, ids[sl], vecs[sl])
    q = rng.normal(size=(33, dim)).astype(np.float32)
    scores, pids = topk_over_store(q, store, mesh, k=10, chunk=64,
                                   query_batch=8)
    # the store rounds vectors to fp16; the oracle must score what it stores
    ref_s = q @ vecs.astype(np.float16).astype(np.float32).T
    ref_idx = np.argsort(-ref_s, axis=1)[:, :10]
    np.testing.assert_allclose(
        scores, np.take_along_axis(ref_s, ref_idx, axis=1),
        rtol=1e-4, atol=1e-4)
    # ids must be the store's page ids, not row numbers
    assert set(np.unique(pids)) <= set(ids.tolist())


def test_chunked_topk_small_corpus():
    # N < k: pad columns must come back as -inf / -1
    q = jnp.ones((2, 4))
    pages = jnp.ones((3, 4))
    s, i = chunked_topk(q, pages, k=5, chunk=8)
    s, i = np.asarray(s), np.asarray(i)
    assert (i[:, :3] >= 0).all()
    assert (i[:, 3:] == -1).all()
    assert np.isinf(s[:, 3:]).all()


def test_topk_over_store_skips_empty_shard(eight_devices, tmp_path):
    """A zero-count shard (a writer whose whole range was padding) holds an
    empty page_ids array; the merge must skip it instead of indexing into it
    (ADVICE r4: page_ids[0] raised IndexError)."""
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore

    mesh = make_mesh(MeshConfig(data=8))
    rng = np.random.default_rng(3)
    dim = 16
    vecs = rng.normal(size=(40, dim)).astype(np.float32)
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    store = VectorStore(str(tmp_path / "store"), dim=dim, shard_size=64)
    store.write_shard(0, np.arange(40), vecs)
    # an all-padding write records a count=0 shard entry
    store.write_shard(1, np.full(8, -1, np.int64), np.zeros((8, dim)))
    assert [s["count"] for s in store.shards()] == [40, 0]
    q = rng.normal(size=(5, dim)).astype(np.float32)
    scores, pids = topk_over_store(q, store, mesh, k=10, chunk=16)
    ref_s = q @ vecs.astype(np.float16).astype(np.float32).T
    ref_idx = np.argsort(-ref_s, axis=1)[:, :10]
    np.testing.assert_allclose(
        scores, np.take_along_axis(ref_s, ref_idx, axis=1),
        rtol=1e-4, atol=1e-4)
    assert (pids >= 0).all() and (pids < 40).all()
