"""Unit tests: encoder zoo shapes + finite grads (SURVEY.md §5 unit tests)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.models.factory import build_two_tower
from dnn_page_vectors_tpu.models.losses import cosine_contrastive_loss, l2_normalize

# cdssm stays in the fast subset (one encoder covers the harness); the
# rest are ~15-25 s each of CPU compile and run under -m slow
CASES = [
    ("cdssm_toy", {}),
    pytest.param("kim_cnn_v5e8", {}, marks=pytest.mark.slow),
    pytest.param("lstm_words",
                 {"model.model_dim": 64, "model.embed_dim": 64,
                  "model.num_layers": 2, "model.out_dim": 32},
                 marks=pytest.mark.slow),
    pytest.param("bert_mini_v5p16", {}, marks=pytest.mark.slow),
    pytest.param("mt5_multilingual",
                 {"model.num_layers": 2, "model.model_dim": 64,
                  "model.num_heads": 2, "model.mlp_dim": 128,
                  "model.out_dim": 32},
                 marks=pytest.mark.slow),
]


def _dummy_batch(cfg, B=4):
    extra = ((cfg.data.trigrams_per_word,)
             if cfg.data.tokenizer == "trigram" else ())
    rng = np.random.default_rng(0)
    q = rng.integers(1, 50, size=(B, cfg.data.query_len) + extra).astype(np.int32)
    p = rng.integers(1, 50, size=(B, cfg.data.page_len) + extra).astype(np.int32)
    q[:, -2:] = 0  # some padding
    p[:, -5:] = 0
    return jnp.asarray(q), jnp.asarray(p)


@pytest.mark.parametrize("name,overrides", CASES)
def test_encoder_shapes_and_grads(name, overrides):
    cfg = get_config(name, overrides)
    model = build_two_tower(cfg, vocab_size=64)
    q_ids, p_ids = _dummy_batch(cfg)
    params = model.init(jax.random.PRNGKey(0), q_ids, p_ids)

    def loss_fn(params):
        q, p, _, scale = model.apply(params, q_ids, p_ids)
        loss, _ = cosine_contrastive_loss(q, p, scale)
        return loss, (q, p)

    (loss, (q, p)), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    assert q.shape == (4, cfg.model.out_dim)
    assert p.shape == (4, cfg.model.out_dim)
    assert q.dtype == jnp.float32
    assert np.isfinite(float(loss))
    leaves = jax.tree_util.tree_leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in leaves)
    # towers are NOT shared by default: page-tower grads must be nonzero
    flat = jax.tree_util.tree_flatten_with_path(grads)[0]
    page_grads = [np.abs(np.asarray(g)).sum() for path, g in flat
                  if "page_tower" in "/".join(str(k) for k in path)]
    assert page_grads and sum(page_grads) > 0


def test_padding_invariance():
    """Vectors must not depend on content past the padding mask."""
    cfg = get_config("cdssm_toy")
    model = build_two_tower(cfg, vocab_size=64)
    q_ids, p_ids = _dummy_batch(cfg)
    params = model.init(jax.random.PRNGKey(0), q_ids, p_ids)
    v1 = model.apply(params, p_ids, method="encode_page")
    junk = p_ids.at[:, -5:].set(0)  # already 0 — now perturb nothing valid
    v2 = model.apply(params, junk, method="encode_page")
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v2), rtol=1e-6)


def test_lstm_padding_invariance():
    """The recurrent carry must pass through padded steps untouched:
    lengthening the pad tail cannot change the page vector."""
    cfg = get_config("lstm_words", {"model.model_dim": 32,
                                    "model.embed_dim": 32})
    model = build_two_tower(cfg, vocab_size=64)
    q_ids, p_ids = _dummy_batch(cfg)
    params = model.init(jax.random.PRNGKey(0), q_ids, p_ids)
    v1 = model.apply(params, p_ids, method="encode_page")
    longer = jnp.pad(p_ids, ((0, 0), (0, 8)))  # 8 more pad steps to carry over
    v2 = model.apply(params, longer, method="encode_page")
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v2),
                               rtol=1e-5, atol=1e-6)


def test_lstm_order_sensitivity():
    """Unlike the max-pooled CNNs, the recurrent encoder must distinguish
    word order (the reason the reference lineage carries an LSTM at all)."""
    cfg = get_config("lstm_words", {"model.model_dim": 32,
                                    "model.embed_dim": 32})
    model = build_two_tower(cfg, vocab_size=64)
    q_ids, p_ids = _dummy_batch(cfg)
    params = model.init(jax.random.PRNGKey(0), q_ids, p_ids)
    fwd = model.apply(params, p_ids, method="encode_page")
    rev = model.apply(params, p_ids[:, ::-1], method="encode_page")
    assert np.abs(np.asarray(fwd) - np.asarray(rev)).max() > 1e-4


def test_loss_prefers_aligned_embeddings():
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    scale = jnp.asarray(20.0)
    aligned, m_aligned = cosine_contrastive_loss(q, q, scale)
    shuffled, _ = cosine_contrastive_loss(q, jnp.roll(q, 1, axis=0), scale)
    assert float(aligned) < float(shuffled)
    assert float(m_aligned["in_batch_acc"]) == 1.0


def test_loss_hard_negatives_increase_difficulty():
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    p = q + 0.1 * jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    scale = jnp.asarray(10.0)
    base, _ = cosine_contrastive_loss(q, p, scale, symmetric=False)
    # hard negatives very close to the positives -> higher loss
    neg = (p + 0.05 * jnp.asarray(rng.normal(size=(8, 16)), jnp.float32))
    neg = neg[:, None, :]
    hard, _ = cosine_contrastive_loss(q, p, scale, neg=neg, symmetric=False)
    assert float(hard) > float(base)


def test_l2_normalize():
    x = jnp.asarray([[3.0, 4.0]])
    n = l2_normalize(x)
    np.testing.assert_allclose(np.asarray((n * n).sum()), 1.0, rtol=1e-5)
