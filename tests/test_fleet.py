"""Scale-out front-end tier + elastic worker fleet (docs/SCALING.md
"Scale-out tier"): N front ends over ONE shared worker set must stay
byte-identical to the single-front-end oracle at every topology; a
worker JOINING re-splits the partition map live through the
generation-gated REFRESH handoff and a DRAINING worker hands its slice
back — with the PR-14 pin extended: no result set ever mixes partition
splits (any mixed-split merge breaks byte identity and fails here); the
autoscale pillar ladders windowed queue-wait/shed pressure into
spawn/drain decisions on a fake clock; and kill -9 of one front end
leaves the other serving (front ends share workers, not fate)."""
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config

pytestmark = pytest.mark.fleet

DIM = 32
SHARD = 50
NSHARDS = 6


# ---------------------------------------------------------------------------
# fixtures: synthetic store + model-free services (the test_net.py idiom)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def net_store(tmp_path_factory):
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore
    sdir = str(tmp_path_factory.mktemp("fleet_store") / "store")
    rng = np.random.default_rng(0)
    store = VectorStore(sdir, dim=DIM, shard_size=SHARD)
    for si in range(NSHARDS):
        v = rng.standard_normal((SHARD, DIM)).astype(np.float32)
        v /= np.linalg.norm(v, axis=1, keepdims=True)
        store.write_shard(si, np.arange(si * SHARD, (si + 1) * SHARD,
                                        dtype=np.int64), v)
    return VectorStore(sdir)


@pytest.fixture(scope="module")
def mesh():
    import jax
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:1]), ("data",))


def _qv(n=3, seed=1):
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((n, DIM)).astype(np.float32)
    return q / np.linalg.norm(q, axis=1, keepdims=True)


def _service(net_store, mesh, **serve_over):
    import dataclasses

    from dnn_page_vectors_tpu.infer.partition_host import MeshEmbedder
    from dnn_page_vectors_tpu.infer.serve import SearchService
    cfg = get_config("cdssm_toy", {"model.out_dim": DIM})
    if serve_over:
        cfg = cfg.replace(serve=dataclasses.replace(cfg.serve,
                                                    **serve_over))
    svc = SearchService(cfg, MeshEmbedder(mesh), None, net_store,
                        preload_hbm_gb=4.0)
    return svc


def _fleet_worker(cfg, store_dir, ports, partition, partitions, replica,
                  mesh):
    """One in-thread worker registered with EVERY listed gateway port
    (the multi-front-end link fan-out)."""
    from dnn_page_vectors_tpu.infer.partition_host import PartitionWorker
    w = PartitionWorker(cfg, store_dir, [("127.0.0.1", p) for p in ports],
                        partition=partition, partitions=partitions,
                        replica=replica, mesh=mesh)
    t = threading.Thread(target=w.run, daemon=True)
    t.start()
    return w, t


# ---------------------------------------------------------------------------
# multi-gateway byte identity: 2 front ends x (P=2, R=2), one worker set
# ---------------------------------------------------------------------------

def test_two_front_ends_byte_identical_p2_r2(net_store, mesh):
    """Both front ends must answer byte-identically to the
    single-front-end in-process oracle captured BEFORE any gateway
    attached — the shared fleet serves N gateways as one worker set."""
    from dnn_page_vectors_tpu.infer.partition_host import WorkerGateway
    over = dict(partitions=2, replicas=2, heartbeat_s=0.5)
    svc0 = _service(net_store, mesh, **over)
    qvs = _qv(8, seed=7)
    oracle = [svc0.topk_vectors(qvs[i:i + 1], k=10) for i in range(8)]
    svc1 = _service(net_store, mesh, **over)
    gw0 = WorkerGateway(svc0, heartbeat_s=0.5)
    svc0.attach_gateway(gw0)
    gw1 = WorkerGateway(svc1, heartbeat_s=0.5)
    svc1.attach_gateway(gw1)
    cfg = get_config("cdssm_toy", {"model.out_dim": DIM,
                                   "serve.partitions": 2,
                                   "serve.replicas": 2})
    workers = []
    try:
        for p in range(2):
            for r in range(2):
                w, _ = _fleet_worker(cfg, net_store.directory,
                                     [gw0.port, gw1.port], p, 2, r, mesh)
                workers.append(w)
        assert gw0.wait_for_workers(4, timeout_s=60.0)
        assert gw1.wait_for_workers(4, timeout_s=60.0)
        for i in range(8):
            for svc in (svc0, svc1):
                s, ids = svc.topk_vectors(qvs[i:i + 1], k=10)
                assert np.array_equal(s, oracle[i][0])
                assert np.array_equal(ids, oracle[i][1])
        # every worker holds one live session PER gateway
        for w in workers:
            assert w.sessions == 2
        assert len(gw0.live_workers()) == 4
        assert len(gw1.live_workers()) == 4
    finally:
        for w in workers:
            w.stop()
        gw0.close()
        gw1.close()
        svc0.close()
        svc1.close()


# ---------------------------------------------------------------------------
# elastic membership: join -> re-split -> drain, under a concurrent hammer
# ---------------------------------------------------------------------------

def test_join_resplit_drain_under_hammer(net_store, mesh):
    """A worker joins mid-hammer (deterministic re-split to width 2),
    then drains back out (re-split to width 1) — through both handoffs
    every answer stays byte-identical to the pre-attach oracle. A
    mixed-split result set would merge two different partition cuts and
    break identity, so zero mismatches IS the zero-mixed-splits pin."""
    from dnn_page_vectors_tpu.infer.partition_host import WorkerGateway
    svc = _service(net_store, mesh, partitions=1, replicas=1,
                   elastic=True, heartbeat_s=0.25)
    qvs = _qv(6, seed=3)
    oracle = [svc.topk_vectors(qvs[i:i + 1], k=10) for i in range(6)]
    gw = WorkerGateway(svc, heartbeat_s=0.25)
    svc.attach_gateway(gw)
    cfg = get_config("cdssm_toy", {"model.out_dim": DIM,
                                   "serve.heartbeat_s": 0.25})
    w0, _ = _fleet_worker(cfg, net_store.directory, [gw.port], 0, 1, 0,
                          mesh)
    assert gw.wait_for_workers(1, timeout_s=60.0)
    stop = threading.Event()
    errors = []
    mismatches = []

    def _hammer():
        i = 0
        while not stop.is_set():
            qi = i % 6
            i += 1
            try:
                s, ids = svc.topk_vectors(qvs[qi:qi + 1], k=10)
            except Exception as e:  # noqa: BLE001 — the pin is zero
                errors.append(repr(e))
                continue
            if not (np.array_equal(s, oracle[qi][0])
                    and np.array_equal(ids, oracle[qi][1])):
                mismatches.append(qi)

    threads = [threading.Thread(target=_hammer, daemon=True)
               for _ in range(2)]
    w1 = None
    try:
        for t in threads:
            t.start()
        time.sleep(0.3)
        # JOIN: the tail index appears -> width 2 re-split
        w1, _ = _fleet_worker(cfg, net_store.directory, [gw.port], 1, 2,
                              0, mesh)
        deadline = time.perf_counter() + 15.0
        while time.perf_counter() < deadline:
            table = gw.partition_set._view_table
            if (len(table) == 2 and len(gw.live_workers()) == 2
                    and gw.stale_workers(table[0][0].generation,
                                         split=2) == 0):
                break
            time.sleep(0.02)
        else:
            pytest.fail("join re-split never completed")
        time.sleep(0.5)                      # hammer ON the new split
        # DRAIN: the tail worker hands its slice back -> width 1
        threading.Thread(target=w1.drain, kwargs={"wait_s": 0.3},
                         daemon=True).start()
        deadline = time.perf_counter() + 15.0
        while time.perf_counter() < deadline:
            if len(gw.partition_set._view_table) == 1:
                break
            time.sleep(0.02)
        else:
            pytest.fail("drain re-split never completed")
        time.sleep(0.3)                      # hammer past the handoff
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        if w1 is not None:
            w1.stop()
        w0.stop()
        gw.close()
        svc.close()
    assert errors == []
    assert mismatches == []                  # zero mixed-split sets
    triggers = [e["attrs"]["trigger"]
                for e in svc.registry.events("fleet_resplit")]
    assert "join" in triggers and "drain" in triggers
    assert svc.registry.events("worker_draining")
    assert gw.stats()["resplits"] >= 2


# ---------------------------------------------------------------------------
# autoscale pillar: the policy ladder on a fake clock
# ---------------------------------------------------------------------------

class _SvcStub:
    """A service exposing only what the pillar reads."""

    def __init__(self):
        self.sig = {"queue_wait_p99_ms": 0.0, "queue_wait_samples": 0.0,
                    "shed_rate": 0.0, "window_s": 10.0}

    def autoscale_signals(self):
        return dict(self.sig)


def _scaler(tmp_path, over):
    from dnn_page_vectors_tpu.maintenance.service import MaintenanceService
    from dnn_page_vectors_tpu.utils.telemetry import MetricsRegistry
    cfg = get_config("cdssm_toy", {"maintenance.autoscale": True,
                                   "maintenance.autoscale_min_workers": 1,
                                   "maintenance.autoscale_max_workers": 3,
                                   "maintenance.autoscale_cooldown_s":
                                       30.0, **over})
    stub = _SvcStub()
    ms = MaintenanceService(cfg, str(tmp_path), None, svc=stub,
                            registry=MetricsRegistry())
    clock = [1000.0]
    ms._clock = lambda: clock[0]
    size = [1]
    spawned, drained = [], []

    def _spawn(i):
        spawned.append(i)
        size[0] += 1

    def _drain(i):
        drained.append(i)
        size[0] -= 1

    ms.attach_scaler(_spawn, _drain, size=lambda: size[0])
    return ms, stub, clock, size, spawned, drained


def test_autoscale_ladder_on_fake_clock(tmp_path):
    """Up on queue-wait pressure, up on shed rate, bounded by max,
    cooled down between actions, down when calm, bounded by min —
    spawn targets the next TAIL index, drain the highest."""
    ms, stub, clock, size, spawned, drained = _scaler(tmp_path, {})
    hot = {"queue_wait_p99_ms": 120.0, "queue_wait_samples": 16.0,
           "shed_rate": 0.0, "window_s": 10.0}
    calm = {"queue_wait_p99_ms": 1.0, "queue_wait_samples": 16.0,
            "shed_rate": 0.0, "window_s": 10.0}
    stub.sig = hot
    out = ms._autoscale_once()
    assert out["decision"] == "up" and spawned == [1] and size[0] == 2
    # inside the cooldown: pressure persists but NO second action
    assert ms._autoscale_once() is None and spawned == [1]
    clock[0] += 31.0
    assert ms._autoscale_once()["decision"] == "up"
    assert spawned == [1, 2] and size[0] == 3
    # at max: no up decision even under pressure
    clock[0] += 31.0
    assert ms._autoscale_once() is None
    # calm: drain the highest index, one cooldown apart
    stub.sig = calm
    assert ms._autoscale_once()["decision"] == "down"
    assert drained == [2] and size[0] == 2
    assert ms._autoscale_once() is None          # cooling down
    clock[0] += 31.0
    assert ms._autoscale_once()["decision"] == "down"
    assert drained == [2, 1] and size[0] == 1
    # at min: calm no longer drains
    clock[0] += 31.0
    assert ms._autoscale_once() is None
    ups = ms.registry.events("autoscale_up")
    downs = ms.registry.events("autoscale_down")
    assert len(ups) == 2 and len(downs) == 2
    assert all(e["attrs"]["acted"] for e in ups + downs)
    assert ups[0]["attrs"]["trigger"] == "queue_wait"


def test_autoscale_shed_trigger_and_sample_floor(tmp_path):
    ms, stub, clock, size, spawned, drained = _scaler(tmp_path, {})
    # a hot percentile off a near-empty window is noise, not pressure
    stub.sig = {"queue_wait_p99_ms": 500.0, "queue_wait_samples": 3.0,
                "shed_rate": 0.0, "window_s": 10.0}
    assert ms._autoscale_once() is None
    # the shed rate is evidence by itself (every shed was a real miss)
    stub.sig = {"queue_wait_p99_ms": 0.0, "queue_wait_samples": 0.0,
                "shed_rate": 0.9, "window_s": 10.0}
    out = ms._autoscale_once()
    assert out["decision"] == "up" and spawned == [1]
    ev = ms.registry.events("autoscale_up")
    assert ev[-1]["attrs"]["trigger"] == "shed_rate"


def test_autoscale_off_is_inert(tmp_path):
    from dnn_page_vectors_tpu.maintenance.service import MaintenanceService
    from dnn_page_vectors_tpu.utils.telemetry import MetricsRegistry
    cfg = get_config("cdssm_toy")
    assert cfg.maintenance.autoscale is False
    stub = _SvcStub()
    stub.sig["shed_rate"] = 1.0
    ms = MaintenanceService(cfg, str(tmp_path), None, svc=stub,
                            registry=MetricsRegistry())
    assert ms._autoscale_once() is None
    assert ms.registry.events("autoscale_up") == []


# ---------------------------------------------------------------------------
# wait barriers report why they timed out (stats + event, not a bare False)
# ---------------------------------------------------------------------------

def test_wait_for_workers_timeout_reports_state(net_store, mesh):
    from dnn_page_vectors_tpu.infer.partition_host import WorkerGateway
    svc = _service(net_store, mesh, partitions=1, replicas=1)
    gw = WorkerGateway(svc, heartbeat_s=0.5)
    svc.attach_gateway(gw)
    try:
        t0 = time.perf_counter()
        assert gw.wait_for_workers(1, timeout_s=0.3) is False
        assert time.perf_counter() - t0 >= 0.3
        ev = svc.registry.events("gateway_wait_timeout")
        assert len(ev) == 1
        attrs = ev[0]["attrs"]
        assert attrs["barrier"] == "workers"
        assert attrs["waited_s"] >= 0.3 and attrs["wanted"] == 1
        assert attrs["live"] == 0
        assert gw.stats()["wait_timeouts"] == 1
    finally:
        gw.close()
        svc.close()


# ---------------------------------------------------------------------------
# one front end dies (kill -9); the other keeps serving the shared fleet
# ---------------------------------------------------------------------------

_FE_SCRIPT = """
import json, os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import jax
from jax.sharding import Mesh
from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.infer.partition_host import (MeshEmbedder,
                                                       WorkerGateway)
from dnn_page_vectors_tpu.infer.serve import SearchService
from dnn_page_vectors_tpu.infer.server import serve_in_background
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
store = VectorStore(sys.argv[1])
mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
cfg = get_config("cdssm_toy", {"model.out_dim": int(sys.argv[2]),
                               "serve.partitions": 1,
                               "serve.replicas": 1})
svc = SearchService(cfg, MeshEmbedder(mesh), None, store,
                    preload_hbm_gb=4.0)
gw = WorkerGateway(svc, heartbeat_s=0.5)
svc.attach_gateway(gw)
srv = serve_in_background(svc, front_end=1)
print(json.dumps({"gw_port": gw.port, "srv_port": srv.port}), flush=True)
while True:
    time.sleep(1)
"""


@pytest.mark.slow
def test_kill_9_one_front_end_other_keeps_serving(net_store, mesh,
                                                  tmp_path):
    """Two front ends share one worker; SIGKILL the second front end's
    whole process mid-serve. The worker's link to the dead gateway goes
    into its reconnect loop, the surviving front end keeps answering
    byte-identically — front ends share the fleet, not fate."""
    from dnn_page_vectors_tpu.infer.partition_host import WorkerGateway
    from dnn_page_vectors_tpu.infer.transport import SocketSearchClient
    svc0 = _service(net_store, mesh, partitions=1, replicas=1,
                    heartbeat_s=0.5)
    qvs = _qv(4, seed=11)
    oracle = [svc0.topk_vectors(qvs[i:i + 1], k=10) for i in range(4)]
    gw0 = WorkerGateway(svc0, heartbeat_s=0.5)
    svc0.attach_gateway(gw0)
    script = tmp_path / "fe.py"
    script.write_text(_FE_SCRIPT)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # a script path puts ITS directory on sys.path, not the cwd — the
    # package only resolves through PYTHONPATH
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    proc = subprocess.Popen(
        [sys.executable, str(script), net_store.directory, str(DIM)],
        cwd=repo, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    w = None
    client = None
    try:
        ready = json.loads(proc.stdout.readline())
        cfg = get_config("cdssm_toy", {"model.out_dim": DIM,
                                       "serve.heartbeat_s": 0.5})
        w, _ = _fleet_worker(cfg, net_store.directory,
                             [gw0.port, ready["gw_port"]], 0, 1, 0, mesh)
        assert gw0.wait_for_workers(1, timeout_s=60.0)
        # the second front end serves the shared worker over its socket
        client = SocketSearchClient("127.0.0.1", ready["srv_port"])
        deadline = time.perf_counter() + 30.0
        while time.perf_counter() < deadline:
            s, ids, _scan = client.topk_vectors(qvs[0:1], k=10)
            if np.array_equal(s, oracle[0][0]):
                break
            time.sleep(0.1)
        assert np.array_equal(ids, oracle[0][1])
        client.close()
        client = None
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
        # the survivor serves on, byte-identical, across heartbeats
        t_end = time.perf_counter() + 2.0
        n = 0
        while time.perf_counter() < t_end:
            qi = n % 4
            s, ids = svc0.topk_vectors(qvs[qi:qi + 1], k=10)
            assert np.array_equal(s, oracle[qi][0])
            assert np.array_equal(ids, oracle[qi][1])
            n += 1
        assert n > 0
        assert gw0.worker_alive(0, 0)
    finally:
        if client is not None:
            client.close()
        if proc.poll() is None:
            proc.kill()
        if w is not None:
            w.stop()
        gw0.close()
        svc0.close()


# ---------------------------------------------------------------------------
# the client-side balancer (loadgen/driver.py BalancedClient)
# ---------------------------------------------------------------------------

class _CountClient:
    def __init__(self):
        self.calls = 0

    def search(self, query, k=10, nprobe=None):
        self.calls += 1
        return query


class _BoomClient:
    def search(self, query, k=10, nprobe=None):
        raise RuntimeError("down")


def test_balanced_client_round_robin_is_seeded():
    from dnn_page_vectors_tpu.loadgen import BalancedClient
    cs = [_CountClient() for _ in range(3)]
    bc = BalancedClient(cs, policy="round_robin", seed=1)
    for _ in range(6):
        bc.search("q")
    assert [c.calls for c in cs] == [2, 2, 2]
    # the seed sets the rotation phase: seed=1 starts at client 1
    cs2 = [_CountClient() for _ in range(3)]
    BalancedClient(cs2, policy="round_robin", seed=1).search("q")
    assert [c.calls for c in cs2] == [0, 1, 0]
    assert bc.stats()["sent"] == [2, 2, 2]


def test_balanced_client_least_loaded_and_errors():
    from dnn_page_vectors_tpu.loadgen import BalancedClient
    cs = [_CountClient(), _CountClient()]
    bc = BalancedClient(cs, policy="least_loaded", seed=0)
    for _ in range(4):
        bc.search("q")
    # nothing in flight between synchronous calls: least-loaded
    # degenerates to the seeded rotation — deterministic spread
    assert [c.calls for c in cs] == [2, 2]
    bc2 = BalancedClient([_BoomClient()], policy="round_robin")
    with pytest.raises(RuntimeError):
        bc2.search("x")
    assert bc2.stats()["errors"] == [1]
    with pytest.raises(ValueError):
        BalancedClient(cs, policy="nope")
    with pytest.raises(ValueError):
        BalancedClient([], policy="round_robin")
