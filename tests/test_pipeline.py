"""Periodic re-mining pipeline tests (SURVEY.md §4.4; VERDICT r1 #5).

Two claims:

1. `run_pipeline` alternates train -> embed -> mine -> continue-train as one
   command, recall improves across rounds, and the mined table is sane.

2. Mined hard negatives beat in-batch-only training: from the same
   partially-trained snapshot, the same number of further steps reaches
   higher Recall@10 with mined negatives in the loss than without.

Regime notes (calibrated by round-3 experiments): the branch point must be a
partially-trained model — mining from a near-random model returns arbitrary
same-topic near-duplicates (false negatives) and measurably HURTS training,
while a saturated model leaves no headroom (this toy task reaches recall 1.0
from in-batch negatives alone given enough steps). Everything here is
deterministic (fixed seeds, CPU backend), so the comparison is exact, not
statistical.
"""
import os

import pytest

import jax
import numpy as np

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.evals.recall import evaluate_recall
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.mine.ann import mine_hard_negatives
from dnn_page_vectors_tpu.train.loop import Trainer
from dnn_page_vectors_tpu.train.pipeline import run_pipeline


def _eval(cfg, trainer, state, wd, tag):
    store = VectorStore(os.path.join(wd, "store_" + tag),
                        dim=cfg.model.out_dim, shard_size=256)
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, query_tok=trainer.query_tok)
    emb.embed_corpus(trainer.corpus, store, batch_size=128)
    r, _ = evaluate_recall(emb, trainer.corpus, store, num_queries=400, k=10)
    return r, emb, store


@pytest.mark.slow
def test_hard_negatives_beat_in_batch_only(tmp_path):
    # Hard regime: 40 near-duplicate pages per topic and queries that are
    # mostly topic words, so within-topic discrimination is the whole task
    # and random recall@10 is 10/1200 ~ 0.8%.
    warm, extra = 75, 12
    cfg = get_config("cdssm_toy", {
        "data.num_pages": 1200,
        "data.num_topics": 30,
        "data.query_len": 24,
        "data.trigram_buckets": 4096,
        "model.embed_dim": 48,
        "model.conv_channels": 96,
        "model.out_dim": 48,
        "train.batch_size": 64,
        "train.steps": warm + extra,
        "train.warmup_steps": 10,
        "train.learning_rate": 2e-3,
        "train.log_every": 1000,
        "train.hard_negatives": 7,
        "eval.eval_queries": 400,
        "eval.embed_batch_size": 128,
    })
    wd = str(tmp_path)
    trainer = Trainer(cfg, workdir=wd)
    state, _ = trainer.train(steps=warm)
    snap = jax.device_get(state)        # host copy survives donation
    r_warm, emb, store = _eval(cfg, trainer, state, wd, "warm")
    negs = mine_hard_negatives(emb, trainer.corpus, store, num_negatives=7)

    # table sanity: right shape, in-range, never the gold page
    assert negs.table.shape == (1200, 7)
    assert negs.table.min() >= 0 and negs.table.max() < 1200
    assert not (negs.table == np.arange(1200)[:, None]).any()

    trainer.hard_negative_lookup = None
    s_a, _ = trainer.train(steps=extra, state=jax.device_put(snap))
    r_in_batch, _, _ = _eval(cfg, trainer, s_a, wd, "in_batch")

    trainer.hard_negative_lookup = negs
    s_b, _ = trainer.train(steps=extra, state=jax.device_put(snap))
    r_mined, _, _ = _eval(cfg, trainer, s_b, wd, "mined")

    assert r_warm > 0.1, f"warmup failed to train at all: {r_warm}"
    assert r_mined > r_warm, (r_warm, r_mined)
    assert r_mined > r_in_batch, (
        f"mined negatives ({r_mined}) should beat in-batch-only "
        f"({r_in_batch}) from the same snapshot + step budget")


@pytest.mark.slow
def test_run_pipeline_end_to_end(tmp_path):
    # Easy regime so two short rounds converge: the point here is the
    # orchestration (round alternation, store regeneration, table refresh),
    # not the mining-benefit claim above.
    cfg = get_config("cdssm_toy", {
        "data.num_pages": 600,
        "data.trigram_buckets": 4096,
        "model.embed_dim": 48,
        "model.conv_channels": 96,
        "model.out_dim": 48,
        "train.batch_size": 64,
        "train.steps": 120,
        "train.warmup_steps": 10,
        "train.learning_rate": 2e-3,
        "train.log_every": 1000,
        "train.hard_negatives": 7,
        "eval.eval_queries": 300,
        "eval.embed_batch_size": 128,
    })
    trainer = Trainer(cfg, workdir=str(tmp_path))
    out = run_pipeline(cfg, rounds=2, trainer=trainer)
    recalls = out["recalls"]
    assert len(recalls) == 2
    assert recalls[1] >= recalls[0], recalls
    assert recalls[1] > 0.5, recalls     # random ~ 1.7%
    # the mined table was refreshed and persisted for resume
    assert out["negatives"] is not None
    assert os.path.exists(os.path.join(trainer.workdir, "hard_negatives.npy"))
    # store holds the FINAL round's vectors (regenerated, not stale)
    store = VectorStore(os.path.join(trainer.workdir, "store"),
                        dim=cfg.model.out_dim)
    assert store.num_vectors == 600
    assert store.manifest["model_step"] == 120


def test_cli_fleet_embed_start_stop(tmp_path, capsys):
    """The manual-fleet recipe (docs/SCALING.md; VERDICT r3 next-round #6):
    `init-store` once, then N uncoordinated `embed --start/--stop` slices
    (here run sequentially — the protocol is writer-manifest based, so order
    does not matter), then `merge-store`. The merged store must hold every
    page exactly once and serve eval."""
    import json

    from dnn_page_vectors_tpu import cli

    wd = str(tmp_path)
    base = ["--config", "cdssm_toy", "--workdir", wd,
            "--set", "data.num_pages=384",
            "--set", "data.trigram_buckets=2048",
            "--set", "model.embed_dim=48",
            "--set", "model.conv_channels=96",
            "--set", "model.out_dim=48",
            "--set", "train.batch_size=64",
            "--set", "train.warmup_steps=10",
            "--set", "train.learning_rate=2e-3",
            "--set", "train.log_every=1000",
            "--set", "eval.embed_batch_size=128",
            "--set", "eval.eval_queries=200",
            "--set", "eval.store_shard_size=128",
            "--set", "mesh.data=1"]
    cli.main(["train"] + base + ["--steps", "60"])

    # fleet slices without init-store must refuse (unstamped store)
    import pytest as _pytest
    with _pytest.raises(SystemExit, match="init-store"):
        cli.main(["embed"] + base + ["--start", "128", "--stop", "256"])

    cli.main(["init-store"] + base)
    cli.main(["embed"] + base + ["--start", "256"])          # out of order
    cli.main(["embed"] + base + ["--start", "0", "--stop", "128"])
    cli.main(["embed"] + base + ["--start", "128", "--stop", "256"])
    store_dir = os.path.join(wd, "store")
    # slices recorded under per-writer manifests (no shared-manifest races)
    writers = [f for f in os.listdir(store_dir) if f.startswith("manifest.w")]
    assert len(writers) == 3, writers
    # readers see the union even before the merge
    store = VectorStore(store_dir)
    assert store.num_vectors == 384
    cli.main(["merge-store"] + base)
    assert not [f for f in os.listdir(store_dir)
                if f.startswith("manifest.w")]
    store = VectorStore(store_dir)
    assert store.num_vectors == 384
    assert [s["index"] for s in store.manifest["shards"]] == [0, 1, 2]
    capsys.readouterr()
    cli.main(["eval"] + base)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["num_queries"] == 200
    assert out["recall@10"] > 0.2      # random ~ 10/384


def test_cli_search_returns_gold_page(tmp_path, capsys):
    """`cli search --query <text>` embeds the query and retrieves from the
    store: after a short train + embed, the gold page for a training query
    must appear in the top-k results with a snippet."""
    import json

    from dnn_page_vectors_tpu import cli

    wd = str(tmp_path)
    base = ["--config", "cdssm_toy", "--workdir", wd,
            "--set", "data.num_pages=400",
            "--set", "data.trigram_buckets=2048",
            "--set", "model.embed_dim=48",
            "--set", "model.conv_channels=96",
            "--set", "model.out_dim=48",
            "--set", "train.batch_size=64",
            "--set", "train.warmup_steps=10",
            "--set", "train.learning_rate=2e-3",
            "--set", "train.log_every=1000",
            "--set", "eval.embed_batch_size=128",
            "--set", "mesh.data=1"]
    cli.main(["train"] + base + ["--steps", "80"])
    cli.main(["embed"] + base)
    capsys.readouterr()

    # the oracle corpus must be built EXACTLY as the pipeline builds it —
    # a bare ToyCorpus(num_pages, seed) uses different page/query lengths
    # than cfg.data and generates different text, so its query_text(7)
    # would never match the trained store
    from dnn_page_vectors_tpu.config import get_config
    from dnn_page_vectors_tpu.data.loader import build_corpus
    corpus = build_corpus(get_config("cdssm_toy", {"data.num_pages": 400}))
    query = corpus.query_text(7)
    cli.main(["search"] + base + ["--query", query, "--topk", "5"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["query"] == query
    assert len(out["results"]) == 5
    assert all(r["snippet"] for r in out["results"])
    assert 7 in [r["page_id"] for r in out["results"]]
    # ranked: scores non-increasing
    scores = [r["score"] for r in out["results"]]
    assert scores == sorted(scores, reverse=True)


def test_prepare_store_stale_with_geometry_change(tmp_path):
    """ADVICE r4 (cli.py): a stale store (older model_step) whose
    shard_size/dtype overrides ALSO changed used to trip the populated-store
    geometry guard before the stale shards could be dropped. _prepare_store
    must reset first, then apply the new geometry."""
    import numpy as np

    from dnn_page_vectors_tpu.cli import _prepare_store
    from dnn_page_vectors_tpu.config import get_config

    cfg = get_config("cdssm_toy", {"model.out_dim": 16,
                                   "eval.store_shard_size": 128,
                                   "eval.store_dtype": "int8"})
    sd = str(tmp_path / "store")
    old = VectorStore(sd, dim=16, shard_size=64, dtype="float16")
    old.ensure_model_step(1)
    old.write_shard(0, np.arange(4), np.ones((4, 16), np.float32))
    assert old.num_vectors == 4
    store = _prepare_store(sd, cfg, model_step=2)
    assert store.num_vectors == 0                       # stale shards dropped
    assert store.manifest["shard_size"] == 128          # new geometry applied
    assert store.manifest["dtype"] == "int8"
    assert store.manifest["model_step"] == 2
    # same step + same geometry must be a no-op (resumable work preserved)
    store.write_shard(0, np.arange(4), np.ones((4, 16), np.float32))
    again = _prepare_store(sd, cfg, model_step=2)
    assert again.num_vectors == 4
