"""Integration oracle (SURVEY.md §5): config 1 — 'CDSSM char-trigram CNN,
toy corpus, single-process CPU' (BASELINE.json:7) — trained end-to-end until
Recall@10 beats random by a wide margin, exercising train -> bulk-embed ->
vector store -> retrieval eval as one pipeline.

Shrunk from 10k pages to 600 so the CPU run stays fast; the full-size run is
bench.py's job.
"""
import numpy as np

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.evals.recall import evaluate_recall
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.train.loop import Trainer


def test_cdssm_toy_end_to_end(tmp_path):
    cfg = get_config("cdssm_toy", {
        "data.num_pages": 600,
        "data.trigram_buckets": 4096,
        "model.embed_dim": 64,
        "model.conv_channels": 128,
        "model.out_dim": 64,
        "train.batch_size": 64,
        "train.steps": 80,
        "train.warmup_steps": 10,
        "train.learning_rate": 2e-3,
        "train.log_every": 40,
        "eval.eval_queries": 200,
        "eval.embed_batch_size": 128,
    })
    trainer = Trainer(cfg, workdir=str(tmp_path))
    state, metrics = trainer.train()
    assert np.isfinite(metrics["loss"])
    assert metrics["in_batch_acc"] > 0.5, metrics

    store = VectorStore(str(tmp_path / "store"), dim=cfg.model.out_dim,
                        shard_size=256)
    embedder = BulkEmbedder(cfg, trainer.model, state.params,
                            trainer.page_tok, trainer.mesh,
                            query_tok=trainer.query_tok)
    embedder.embed_corpus(trainer.corpus, store, batch_size=128)
    assert store.num_vectors == 600

    recall, nq = evaluate_recall(embedder, trainer.corpus, store,
                                 num_queries=200, k=10)
    # random recall@10 over 600 pages ~ 1.7%; a trained CDSSM must crush it
    assert recall > 0.5, f"recall@10={recall} over {nq} queries"
