"""Native C++ trigram tokenizer: bit-equality with the Python reference
implementation, plus a smoke check that it is actually faster."""
import time

import numpy as np
import pytest

from dnn_page_vectors_tpu.data.toy import ToyCorpus
from dnn_page_vectors_tpu.data.trigram import TrigramTokenizer

native = pytest.importorskip("dnn_page_vectors_tpu.native.trigram_native",
                             reason="g++ unavailable / native build failed")


def test_native_matches_python_exactly():
    corpus = ToyCorpus(num_pages=50, seed=3)
    tok_py = TrigramTokenizer(buckets=4096, max_words=32, k=6,
                              use_native=False)
    texts = ([corpus.page_text(i) for i in range(50)]
             + [corpus.query_text(i) for i in range(50)]
             + ["", "a", "ab", "abc", "  spaced   out  ",
                "ünïcôdé wörds ärë fïne", "日本語 テキスト",
                "x" * 500,  # longer than the native word buffer
                "\tmixed\nwhitespace\r here"])
    for t in texts:
        got = native.encode(t, 4096, 32, 6)
        want = tok_py._encode_py(t)
        if len(t.encode()) < 300:
            np.testing.assert_array_equal(got, want, err_msg=repr(t))
        else:
            # oversized words: native truncates at its buffer; both must
            # still produce valid ids in range
            assert got.shape == want.shape
            assert (got >= 0).all() and (got <= 4096).all()


def test_native_batch_matches_single():
    corpus = ToyCorpus(num_pages=20, seed=1)
    texts = [corpus.page_text(i) for i in range(20)]
    batch = native.encode_batch(texts, 2048, 16, 4)
    for j, t in enumerate(texts):
        np.testing.assert_array_equal(batch[j], native.encode(t, 2048, 16, 4))


def test_native_is_faster():
    corpus = ToyCorpus(num_pages=200, seed=0)
    texts = [corpus.page_text(i) for i in range(200)]
    tok_py = TrigramTokenizer(buckets=16384, max_words=64, k=8,
                              use_native=False)
    t0 = time.perf_counter()
    tok_py.encode_batch(texts)
    t_py = time.perf_counter() - t0
    t0 = time.perf_counter()
    native.encode_batch(texts, 16384, 64, 8)
    t_c = time.perf_counter() - t0
    # conservative bar: the C++ path must win clearly (typically 50-300x)
    assert t_c < t_py / 5, (t_py, t_c)


def _py_offsets(path):
    offsets, pos = [], 0
    with open(path, "rb") as f:
        for line in f:
            if line.strip():
                offsets.append(pos)
            pos += len(line)
    return np.asarray(offsets, dtype=np.int64)


def test_jsonl_index_matches_python_exactly(tmp_path):
    from dnn_page_vectors_tpu.native import jsonl_native
    p = tmp_path / "corpus.jsonl"
    # blank lines, whitespace-only lines, CRLF, unicode, no trailing newline
    p.write_bytes(
        b'{"page": "one"}\n'
        b'\n'
        b'   \t  \n'
        b'{"page": "two"}\r\n'
        b'{"page": "\xc3\xbcnic\xc3\xb4de"}\n'
        b'\r\n'
        b'{"page": "last, no newline"}')
    np.testing.assert_array_equal(jsonl_native.index_offsets(str(p)),
                                  _py_offsets(str(p)))
    # degenerate files
    empty = tmp_path / "empty.jsonl"
    empty.write_bytes(b"")
    assert jsonl_native.index_offsets(str(empty)).size == 0
    blank = tmp_path / "blank.jsonl"
    blank.write_bytes(b"\n  \n\t\n")
    assert jsonl_native.index_offsets(str(blank)).size == 0


def test_jsonl_index_large_and_fast(tmp_path):
    p = tmp_path / "big.jsonl"
    with open(p, "wb") as f:
        for i in range(200_000):
            f.write(b'{"query": "q%d", "page": "page text %d"}\n' % (i, i))
    from dnn_page_vectors_tpu.native import jsonl_native
    t0 = time.perf_counter()
    native_off = jsonl_native.index_offsets(str(p))
    t_c = time.perf_counter() - t0
    t0 = time.perf_counter()
    py_off = _py_offsets(str(p))
    t_py = time.perf_counter() - t0
    np.testing.assert_array_equal(native_off, py_off)
    assert len(native_off) == 200_000
    assert t_c < t_py, (t_py, t_c)  # conservative: typically ~10x


def test_jsonl_corpus_uses_native_index(tmp_path):
    from dnn_page_vectors_tpu.data.jsonl import JsonlCorpus
    p = tmp_path / "c.jsonl"
    p.write_text('{"query": "q0", "page": "p0"}\n\n{"page": "p1"}\n')
    c = JsonlCorpus(str(p))
    assert c.native_index  # the fast path actually ran, not the fallback
    assert c.num_pages == 2
    assert c.page_text(1) == "p1"
    assert c.query_text(0) == "q0"


def test_tokenizer_uses_native_by_default():
    tok = TrigramTokenizer(buckets=1024, max_words=8, k=4)
    assert tok._native is not None
    np.testing.assert_array_equal(tok.encode("hello world"),
                                  tok._encode_py("hello world"))


def _trained_subword(style):
    from dnn_page_vectors_tpu.data.subword import SubwordTokenizer
    corpus = ToyCorpus(num_pages=300, seed=5)
    texts = [corpus.page_text(i) for i in range(300)]
    return SubwordTokenizer.train(texts, vocab_size=600, style=style,
                                  max_tokens=24), texts


@pytest.mark.parametrize("style", ["wordpiece", "sentencepiece"])
def test_bpe_native_matches_python_exactly(style):
    tok, texts = _trained_subword(style)
    assert tok._native_encoder() is not None  # fast path actually active
    cases = texts[:50] + [
        "", "a", "unknownwordxyz", "  spaced   out  ",
        "ünïcôdé wörds ärë fïne", "日本語 テキスト",
        "x" * 500, "\tmixed\nwhitespace\r here",
        " nbsp separated　words",
        "lone " + chr(0xD800) + " surrogate",  # json.loads(chr(92)+"ud800") case
        " ".join("tok" for _ in range(64)),  # mid-word truncation
    ]
    want = np.stack([tok.encode(t) for t in cases])
    got = tok.encode_batch(cases)
    np.testing.assert_array_equal(got, want)


def test_bpe_native_is_faster():
    tok, texts = _trained_subword("wordpiece")
    batch = [texts[i % len(texts)] for i in range(2_000)]
    native = tok._native_encoder()
    assert native is not None
    native.encode_batch(batch[:10], tok.max_tokens, 1)  # warm
    t0 = time.perf_counter()
    native.encode_batch(batch, tok.max_tokens, 1)
    t_c = time.perf_counter() - t0
    t0 = time.perf_counter()
    np.stack([tok.encode(t) for t in batch])
    t_py = time.perf_counter() - t0
    assert t_c < t_py / 3, (t_py, t_c)  # measured ~6x; /3 rides out noise


def test_bpe_shared_encoder_cache():
    """Query and page tokenizers share one vocab dict (loader.py) — they
    must share one C++ map, not build two 250k-piece copies."""
    from dnn_page_vectors_tpu.native import subword_native
    tok, _ = _trained_subword("wordpiece")
    a = subword_native.shared_encoder(tok.vocab)
    b = subword_native.shared_encoder(dict(tok.vocab))  # equal content
    assert a is b


def test_bpe_threaded_encode_matches_single():
    """data.tokenize_threads > 1 chunks the batch over a thread pool; the
    result must be row-identical to the single-call encode regardless of
    chunk boundaries (1024 texts -> 4 chunks of 256)."""
    from dnn_page_vectors_tpu.data import subword
    tok, texts = _trained_subword("wordpiece")
    batch = [texts[i % len(texts)] for i in range(1_024)]
    want = tok.encode_batch(batch)
    tok.threads = 4
    got = tok.encode_batch(batch)
    np.testing.assert_array_equal(got, want)
    assert subword._POOL is not None  # the threaded path actually dispatched


def test_native_fuzz_equality_random_unicode():
    """Randomized bit-equality sweep for both native tokenizer paths over a
    seeded unicode soup: ASCII, accents, CJK, emoji, every Python split()
    whitespace class, combining marks, and lone surrogates."""
    import random
    rng = random.Random(0)
    pool = (list("abcdefgh0123 ")
            + list("äöüßéñç")
            + list("日本語中文한국")
            + ["🙂", "👍", "́"]          # astral + combining
            + ["\t", "\n", "\r", "\x0b", "\x0c", "\x1c", "\x85",
               "\xa0", " ", " ", " ", " ", "　"]
            + [chr(0xD800)])                   # lone surrogate
    texts = ["".join(rng.choice(pool) for _ in range(rng.randint(0, 60)))
             for _ in range(300)]

    tok = TrigramTokenizer(buckets=512, max_words=16, k=4)
    assert tok._native is not None
    for t in texts:
        np.testing.assert_array_equal(tok.encode(t), tok._encode_py(t),
                                      err_msg=repr(t))

    sub, _ = _trained_subword("sentencepiece")
    assert sub._native_encoder() is not None
    want = np.stack([sub.encode(t) for t in texts])
    got = sub.encode_batch(texts)
    np.testing.assert_array_equal(got, want)


def test_bpe_fused_jsonl_matches_plain_path(tmp_path):
    """Round 11 (MFU campaign): the fused C++ jsonl-extract+encode
    (dpv_bpe_encode_jsonl_batch) must be byte-identical to the plain
    read->extract->decode->encode path, including every punt rule —
    escapes, nesting, duplicate keys, missing field, non-string value —
    where it falls back to json.loads per record."""
    import json

    tok, _ = _trained_subword("wordpiece")
    assert tok._native_encoder() is not None

    lines = [
        b'{"query": "q", "page": "hello world"}\n',
        b'{"query": "q", "page": "esc \\" aped"}\n',          # escape: punt
        b'{"page": "first", "page": "second"}\n',             # dup: punt
        b'{"obj": {"page": "inner"}, "page": "outer"}\n',     # nest: punt
        b'{"query": "only a query"}\n',                       # missing
        b'{"page": 42}\n',                                    # non-string
        '{"page": "ünïcôdé wörds 日本語"}\n'.encode("utf-8"),
        b'{"page": "   spaced   out   "}\n',
        b'{"page": ""}\n',
    ]

    def plain(field):
        out = []
        for ln in lines:
            rec = json.loads(ln)
            out.append(rec[field] if field == "page" and field in rec
                       else rec.get(field, ""))
        return tok.encode_batch(out)

    # records 4/5 have no usable "page": plain path would KeyError on a
    # strict read, so compare on the well-formed subset for "page"...
    ok_lines = [ln for ln in lines if b'"page": 42' not in ln
                and b"only a query" not in ln]
    got = tok.encode_jsonl_lines(ok_lines, "page")
    want = tok.encode_batch([json.loads(ln)["page"] for ln in ok_lines])
    np.testing.assert_array_equal(got, want)

    # the "query" field exercises the .get fallback for missing keys
    gotq = tok.encode_jsonl_lines(lines, "query")
    wantq = tok.encode_batch([json.loads(ln).get("query", "")
                              for ln in lines])
    np.testing.assert_array_equal(gotq, wantq)


def test_fused_jsonl_through_iter_corpus_batches(tmp_path):
    """iter_corpus_batches takes the fused path automatically for a
    JsonlCorpus + subword tokenizer and yields byte-identical batches to
    the plain read+tokenize path."""
    from dnn_page_vectors_tpu.data.jsonl import JsonlCorpus
    from dnn_page_vectors_tpu.data.loader import iter_corpus_batches

    path = tmp_path / "c.jsonl"
    corpus0 = ToyCorpus(num_pages=200, seed=3)
    with open(path, "w") as f:
        for i in range(200):
            import json as _json
            f.write(_json.dumps({"query": corpus0.query_text(i),
                                 "page": corpus0.page_text(i)}) + "\n")
    corpus = JsonlCorpus(str(path))
    tok, _ = _trained_subword("wordpiece")
    assert tok._native_encoder() is not None

    fused = [b["page"] for b in iter_corpus_batches(corpus, tok, 64)]

    class _NoLines:                     # same corpus, fused path disabled
        num_pages = corpus.num_pages

        def page_texts(self, ids):
            return corpus.page_texts(ids)

    plain = [b["page"] for b in iter_corpus_batches(_NoLines(), tok, 64)]
    assert len(fused) == len(plain)
    for a, b in zip(fused, plain):
        np.testing.assert_array_equal(a, b)
