"""Native C++ trigram tokenizer: bit-equality with the Python reference
implementation, plus a smoke check that it is actually faster."""
import time

import numpy as np
import pytest

from dnn_page_vectors_tpu.data.toy import ToyCorpus
from dnn_page_vectors_tpu.data.trigram import TrigramTokenizer

native = pytest.importorskip("dnn_page_vectors_tpu.native.trigram_native",
                             reason="g++ unavailable / native build failed")


def test_native_matches_python_exactly():
    corpus = ToyCorpus(num_pages=50, seed=3)
    tok_py = TrigramTokenizer(buckets=4096, max_words=32, k=6,
                              use_native=False)
    texts = ([corpus.page_text(i) for i in range(50)]
             + [corpus.query_text(i) for i in range(50)]
             + ["", "a", "ab", "abc", "  spaced   out  ",
                "ünïcôdé wörds ärë fïne", "日本語 テキスト",
                "x" * 500,  # longer than the native word buffer
                "\tmixed\nwhitespace\r here"])
    for t in texts:
        got = native.encode(t, 4096, 32, 6)
        want = tok_py._encode_py(t)
        if len(t.encode()) < 300:
            np.testing.assert_array_equal(got, want, err_msg=repr(t))
        else:
            # oversized words: native truncates at its buffer; both must
            # still produce valid ids in range
            assert got.shape == want.shape
            assert (got >= 0).all() and (got <= 4096).all()


def test_native_batch_matches_single():
    corpus = ToyCorpus(num_pages=20, seed=1)
    texts = [corpus.page_text(i) for i in range(20)]
    batch = native.encode_batch(texts, 2048, 16, 4)
    for j, t in enumerate(texts):
        np.testing.assert_array_equal(batch[j], native.encode(t, 2048, 16, 4))


def test_native_is_faster():
    corpus = ToyCorpus(num_pages=200, seed=0)
    texts = [corpus.page_text(i) for i in range(200)]
    tok_py = TrigramTokenizer(buckets=16384, max_words=64, k=8,
                              use_native=False)
    t0 = time.perf_counter()
    tok_py.encode_batch(texts)
    t_py = time.perf_counter() - t0
    t0 = time.perf_counter()
    native.encode_batch(texts, 16384, 64, 8)
    t_c = time.perf_counter() - t0
    # conservative bar: the C++ path must win clearly (typically 50-300x)
    assert t_c < t_py / 5, (t_py, t_c)


def test_tokenizer_uses_native_by_default():
    tok = TrigramTokenizer(buckets=1024, max_words=8, k=4)
    assert tok._native is not None
    np.testing.assert_array_equal(tok.encode("hello world"),
                                  tok._encode_py("hello world"))
