"""train.scan_steps fuses K optimizer steps into one lax.scan dispatch
(config.py TrainConfig.scan_steps). The contract: numerically equivalent
training to the per-step path — same rng folding (the step counter advances
inside the scan), same data order, same donation semantics. Equivalence is
up to float reassociation: GSPMD schedules the sharded-batch collectives of
the scanned program differently, so per-step drift of ~1e-5 is expected on
the 8-device mesh (observed 1.2e-5 after 12 steps), not a bug.
"""
import jax
import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.train.loop import Trainer

_OV = {
    "data.num_pages": 512,
    "data.trigram_buckets": 2048,
    "model.embed_dim": 32,
    "model.conv_channels": 64,
    "model.out_dim": 32,
    "train.batch_size": 64,
    "train.steps": 12,
    "train.warmup_steps": 2,
    "train.log_every": 12,
    "train.learning_rate": 2e-3,
}


def test_scan_steps_matches_per_step(tmp_path):
    t1 = Trainer(get_config("cdssm_toy", _OV), workdir=str(tmp_path / "a"))
    s1, m1 = t1.train()

    t2 = Trainer(get_config("cdssm_toy", dict(_OV, **{"train.scan_steps": 4})),
                 workdir=str(tmp_path / "b"))
    s2, m2 = t2.train()

    assert int(s1.step) == int(s2.step) == 12
    assert abs(m1["loss"] - m2["loss"]) < 1e-4, (m1["loss"], m2["loss"])
    diffs = jax.tree_util.tree_map(
        lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()),
        s1.params, s2.params)
    assert max(jax.tree_util.tree_leaves(diffs)) < 5e-4


def test_scan_steps_rejects_misaligned_boundaries(tmp_path):
    # misalignment surfaces BEFORE any step runs, with or without a ckpt
    # manager (ADVICE r3) — but NOT at construction, which inference
    # commands use for the model/tokenizers only
    cfg = get_config("cdssm_toy", dict(_OV, **{
        "train.scan_steps": 5}))        # log_every 12 % 5 != 0
    t = Trainer(cfg, workdir=str(tmp_path))
    with pytest.raises(ValueError, match="multiple of"):
        t.train()
    # checkpoint_every misalignment raises even with NO ckpt_manager passed
    cfg = get_config("cdssm_toy", dict(_OV, **{
        "train.scan_steps": 4, "train.checkpoint_every": 6}))
    t = Trainer(cfg, workdir=str(tmp_path / "b"))
    with pytest.raises(ValueError, match="checkpoint_every"):
        t.train()
    # aligned log/checkpoint but a misaligned per-call step count
    cfg = get_config("cdssm_toy", dict(_OV, **{
        "train.scan_steps": 4, "train.checkpoint_every": 4}))
    t = Trainer(cfg, workdir=str(tmp_path / "c"))
    with pytest.raises(ValueError, match="multiple of"):
        t.train(steps=7)
