"""Partitioned + replicated serving (docs/SCALING.md "Partitioned
serving"): the scatter-gather must be an OPTIMIZATION, not a different
algorithm — partitioned results byte-identical to the single-partition
exact path at every tested (P, R), including tombstoned rows, PQ/ADC +
exact-fallback partitions mixed, and under a concurrent refresh hammer
(the PR-5 no-mixed-result-sets pin extended to P views) — plus the
availability half: health-based routing sheds on restage / degraded /
queue budget, and a partition whose replicas are ALL degraded still
answers (never an empty slice), with the counters and events asserted.
The host-simulation accounting behind the bench `partitioned_serve`
phase (critical-path seconds, per-partition scan bytes) is pinned here
too."""
import threading
import time

import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.data.toy import ToyCorpus
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.serve import SearchService
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.train.loop import Trainer
from dnn_page_vectors_tpu.utils import faults

pytestmark = pytest.mark.part

_OV = {
    "data.num_pages": 300,
    "data.trigram_buckets": 2048,
    "model.embed_dim": 48,
    "model.conv_channels": 96,
    "model.out_dim": 48,
    "train.batch_size": 64,
    "train.steps": 60,
    "train.warmup_steps": 10,
    "train.learning_rate": 2e-3,
    "train.log_every": 1000,
    "eval.embed_batch_size": 50,
    "eval.store_shard_size": 50,    # 6 shards: room for P in {2, 3, 4}
}


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    """One trained model + embedded 6-shard store for the whole module."""
    wd = str(tmp_path_factory.mktemp("partition_serve"))
    cfg = get_config("cdssm_toy", _OV)
    trainer = Trainer(cfg, workdir=wd)
    state, _ = trainer.train()
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, query_tok=trainer.query_tok)
    store = VectorStore(wd + "/store", dim=cfg.model.out_dim, shard_size=50)
    emb.embed_corpus(trainer.corpus, store)
    return cfg, trainer, emb, store


def _cfg(**serve_over):
    import dataclasses
    cfg = get_config("cdssm_toy", _OV)
    if serve_over:
        cfg = cfg.replace(
            serve=dataclasses.replace(cfg.serve, **serve_over))
    return cfg


def _fresh_store(served, tmp_path):
    cfg, trainer, emb, _ = served
    store = VectorStore(str(tmp_path / "store"), dim=cfg.model.out_dim,
                        shard_size=50)
    store.ensure_model_step(0)          # appends require a stamped store
    emb.embed_corpus(trainer.corpus, store)
    return store


# ---------------------------------------------------------------------------
# the split
# ---------------------------------------------------------------------------

def test_partition_split_contiguous_balanced():
    from dnn_page_vectors_tpu.parallel.multihost import (
        partition_shard_ranges)
    counts = [64] * 6
    assert partition_shard_ranges(counts, 1) == [(0, 6)]
    assert partition_shard_ranges(counts, 2) == [(0, 3), (3, 6)]
    assert partition_shard_ranges(counts, 3) == [(0, 2), (2, 4), (4, 6)]
    # more partitions than shards: clamp, one shard each
    assert partition_shard_ranges(counts, 99) == [
        (i, i + 1) for i in range(6)]
    assert partition_shard_ranges([], 4) == [(0, 0)]
    # uneven counts: cuts land closest to the row-balanced targets, and
    # the ranges always tile [0, n) contiguously with no empty slice
    for counts in ([100, 1, 1, 1, 1, 100], [5, 90, 5, 90, 5, 90],
                   [1, 2, 3, 4, 5, 6, 7, 8]):
        for parts in (2, 3, 4):
            r = partition_shard_ranges(counts, parts)
            assert r[0][0] == 0 and r[-1][1] == len(counts)
            assert all(lo < hi for lo, hi in r)
            assert all(r[i][1] == r[i + 1][0] for i in range(len(r) - 1))
    r = partition_shard_ranges([100, 1, 1, 1, 1, 100], 2)
    assert r == [(0, 3), (3, 6)]        # 102 | 102, not 100 | 104


def test_partition_specs_cover_store_and_cut_hot_budget():
    from dnn_page_vectors_tpu.infer.partition import make_partition_specs
    entries = [{"index": i, "count": c}
               for i, c in enumerate([50, 50, 100, 50, 50])]
    specs = make_partition_specs(entries, 3, hot_gb=3.0)
    assert [s.pid for s in specs] == [0, 1, 2]
    assert sum(s.rows for s in specs) == 300
    flat = [i for s in specs for i in s.shard_indices]
    assert flat == [0, 1, 2, 3, 4]      # contiguous, disjoint, in order
    # hot budget cut proportional to rows
    assert abs(sum(s.hot_gb for s in specs) - 3.0) < 1e-9
    for s in specs:
        assert abs(s.hot_gb - 3.0 * s.rows / 300) < 1e-9


# ---------------------------------------------------------------------------
# byte-identity with the single-partition exact path
# ---------------------------------------------------------------------------

def test_partitioned_matches_single_partition_exact(served):
    cfg, trainer, emb, store = served
    svc1 = SearchService(_cfg(), emb, trainer.corpus, store,
                         preload_hbm_gb=4.0)
    qis = [0, 7, 42, 123, 299, 5, 13, 77, 200, 250]
    queries = [trainer.corpus.query_text(qi) for qi in qis]
    base = svc1.search_many(queries, k=10)
    for P, R in ((2, 1), (4, 1), (2, 2)):
        svc = SearchService(_cfg(partitions=P, replicas=R), emb,
                            trainer.corpus, store, preload_hbm_gb=4.0)
        assert svc.partition_set is not None
        assert svc.search_many(queries, k=10) == base, f"P={P} R={R}"
        assert svc.search_many([], k=10) == []
        met = svc.metrics()
        assert met["serve_partitions"] == P
        assert met["serve_replicas"] == R
        parts = met["partitions"]
        assert len(parts) == P
        assert sum(p["rows"] for p in parts) == 300
        shards = [s for p in parts for s in p["shards"]]
        assert shards == list(range(6))  # contiguous cover, in order
        for p in parts:
            assert len(p["replicas"]) == R
        svc.close()
    # a partitioned STREAMING service (no HBM staging) agrees too
    stream = SearchService(_cfg(partitions=3), emb, trainer.corpus, store,
                           preload_hbm_gb=0.0)
    assert stream.search_many(queries, k=10) == base
    stream.close()
    svc1.close()


def test_partitioned_tombstones_identical(served, tmp_path):
    cfg, trainer, emb, _ = served
    from dnn_page_vectors_tpu.updates import append_corpus
    store = _fresh_store(served, tmp_path)
    dead = [3, 42, 123, 250]
    append_corpus(emb, trainer.corpus, store, tombstone=dead)
    store = VectorStore(store.directory)
    svc1 = SearchService(_cfg(), emb, trainer.corpus, store,
                         preload_hbm_gb=4.0)
    svcp = SearchService(_cfg(partitions=3, replicas=2), emb,
                         trainer.corpus, store, preload_hbm_gb=4.0)
    queries = [trainer.corpus.query_text(qi)
               for qi in (3, 42, 123, 250, 0, 7, 200)]
    base = svc1.search_many(queries, k=10)
    res = svcp.search_many(queries, k=10)
    assert res == base
    for r in res:
        assert not set(x["page_id"] for x in r) & set(dead)
    svcp.close()
    svc1.close()


def test_partitioned_pq_adc_and_exact_fallback_mixed(served, tmp_path):
    """Mixed retrieval modes across partitions: a full-probe PQ/ADC
    partition and an index-degraded exact-fallback partition must still
    fold to results byte-identical to the single-partition exact path
    (full probe + full rerank makes the ADC path exact — the PR-4/PR-6
    contract — so partitioning must not perturb it)."""
    from dnn_page_vectors_tpu.index.ivf import IVFIndex
    cfg, trainer, emb, _ = served
    store = _fresh_store(served, tmp_path)
    IVFIndex.build(store, emb.mesh, seed=0, pq_m=6)
    exact = SearchService(_cfg(), emb, trainer.corpus, store,
                          preload_hbm_gb=4.0)
    queries = [trainer.corpus.query_text(qi)
               for qi in (0, 7, 42, 123, 299, 200)]
    base = exact.search_many(queries, k=10)
    svc = SearchService(
        _cfg(partitions=2, index="ivf", nprobe=10_000, pq_rerank=300),
        emb, trainer.corpus, store, preload_hbm_gb=4.0)
    pset = svc.partition_set
    for reps in pset._parts:            # both partitions ANN-capable
        assert reps[0].view.index is not None
        # each partition's index view is restricted to ITS shard slice
        assert set(reps[0].view.index._postings) == \
            set(reps[0].spec.shard_indices)
    assert svc.search_many(queries, k=10) == base
    assert svc.ann_fallbacks == 0
    # degrade partition 1's index: THAT partition serves the exact
    # fallback while partition 0 stays on ADC — mixed, still identical
    for rep in pset._parts[1]:
        rep.view.index = None
    assert svc.search_many(queries, k=10) == base
    assert svc.ann_fallbacks > 0
    svc.close()
    exact.close()


def test_over_the_wire_tombstones_and_pq_mixed_identical(served, tmp_path):
    """The PR-12 byte-identity pin extended over the socket
    (docs/SERVING.md "Network front end"): with tombstoned rows AND a
    full-probe PQ/ADC index, results through real partition-worker
    sockets — including one partition degraded to the exact fallback
    and one answering from the front end's LOCAL view after its worker
    dies — stay byte-identical to the single-partition exact path."""
    import threading

    from dnn_page_vectors_tpu.index.ivf import IVFIndex
    from dnn_page_vectors_tpu.infer.partition_host import (
        PartitionWorker, WorkerGateway)
    from dnn_page_vectors_tpu.updates import append_corpus
    cfg, trainer, emb, _ = served
    store = _fresh_store(served, tmp_path)
    dead = [3, 42, 123]
    append_corpus(emb, trainer.corpus, store, tombstone=dead)
    store = VectorStore(store.directory)
    IVFIndex.build(store, emb.mesh, seed=0, pq_m=6)
    exact = SearchService(_cfg(), emb, trainer.corpus, store,
                          preload_hbm_gb=4.0)
    queries = [trainer.corpus.query_text(qi)
               for qi in (3, 42, 123, 0, 7, 200)]
    base = exact.search_many(queries, k=10)
    svc = SearchService(
        _cfg(partitions=2, index="ivf", nprobe=10_000, pq_rerank=300),
        emb, trainer.corpus, store, preload_hbm_gb=4.0)
    gw = WorkerGateway(svc, heartbeat_s=0.25)
    svc.attach_gateway(gw)
    workers = []
    try:
        for p in range(2):
            w = PartitionWorker(svc.cfg, store.directory,
                                ("127.0.0.1", gw.port), partition=p,
                                partitions=2, replica=0, mesh=emb.mesh)
            threading.Thread(target=w.run, daemon=True).start()
            workers.append(w)
        assert gw.wait_for_workers(2, timeout_s=60.0)
        res = svc.search_many(queries, k=10)
        assert res == base
        assert gw.stats()["rpc_fallbacks"] == 0
        for r in res:
            assert not set(x["page_id"] for x in r) & set(dead)
        # partition 1's WORKER degrades to the exact fallback (its index
        # dropped) while partition 0 stays on ADC over the wire — mixed
        # retrieval modes across the RPC hop, still identical
        workers[1].view.index = None
        assert svc.search_many(queries, k=10) == base
        # kill partition 0's worker: its slice folds from the front
        # end's local view — identical again, kill -9 semantics
        workers[0].stop()
        deadline = time.perf_counter() + 2.0
        while gw.worker_alive(0, 0) and time.perf_counter() < deadline:
            time.sleep(0.01)
        assert svc.search_many(queries, k=10) == base
        assert gw.stats()["rpc_fallbacks"] >= 0
    finally:
        for w in workers:
            w.stop()
        gw.close()
        svc.close()
        exact.close()


# ---------------------------------------------------------------------------
# health-based replica routing
# ---------------------------------------------------------------------------

def _degrade(view) -> None:
    """Push a view's staged shards onto the streaming disk path — the
    state a staging failure leaves behind (docs/ROBUSTNESS.md)."""
    view.stream_entries = list(view.entries)
    view.shards = None


def test_replica_shed_and_degraded_local_fallback(served):
    cfg, trainer, emb, store = served
    svc = SearchService(_cfg(partitions=2, replicas=2), emb,
                        trainer.corpus, store, preload_hbm_gb=4.0)
    pset = svc.partition_set
    q = [trainer.corpus.query_text(7)]
    base = svc.search_many(q, k=10)
    # 1) primary mid-restage -> shed to the replica
    pset._parts[0][0].set_restaging(True)
    assert svc.search_many(q, k=10) == base
    assert svc.replica_shed == 1
    evs = [e for e in svc.registry.events()
           if e["event"] == "replica_shed"]
    assert evs and evs[-1]["attrs"]["reason"] == "restaging"
    assert evs[-1]["attrs"]["partition"] == 0
    pset._parts[0][0].set_restaging(False)
    # 2) primary degraded, replica healthy -> shed, reason degraded
    _degrade(pset._parts[0][0].view)
    assert svc.search_many(q, k=10) == base
    assert svc.replica_shed == 2
    assert svc.partition_degraded_serves == 0
    evs = [e for e in svc.registry.events()
           if e["event"] == "replica_shed"]
    assert evs[-1]["attrs"]["reason"] == "degraded"
    # 3) replica ALSO degraded -> serve degraded locally: identical,
    # NON-EMPTY results (the availability pin), counter + event move
    _degrade(pset._parts[0][1].view)
    res = svc.search_many(q, k=10)
    assert res == base and res[0]
    assert svc.partition_degraded_serves >= 1
    assert any(e["event"] == "partition_degraded"
               for e in svc.registry.events())
    met = svc.metrics()
    assert met["replica_shed"] >= 2
    assert met["partition_degraded"] >= 1
    p0 = met["partitions"][0]
    assert p0["sheds"] >= 2 and p0["degraded_serves"] >= 1
    assert p0["replicas"][0]["degraded"] and p0["replicas"][1]["degraded"]
    svc.close()


def test_shed_on_queue_budget(served):
    cfg, trainer, emb, store = served
    svc = SearchService(_cfg(partitions=1, replicas=2,
                             replica_shed_queue=0),
                        emb, trainer.corpus, store, preload_hbm_gb=4.0)
    pset = svc.partition_set
    base = svc.search_many([trainer.corpus.query_text(3)], k=10)
    rep0 = pset._parts[0][0]
    with rep0._lock:                    # simulate a stuck backlog
        rep0._outstanding = 5
    assert svc.search_many([trainer.corpus.query_text(3)], k=10) == base
    assert svc.replica_shed == 1
    evs = [e for e in svc.registry.events()
           if e["event"] == "replica_shed"]
    assert evs[-1]["attrs"]["reason"] == "queue"
    with rep0._lock:
        rep0._outstanding = 0
    # healthy again: traffic returns to the primary, no new sheds
    assert svc.search_many([trainer.corpus.query_text(3)], k=10) == base
    assert svc.replica_shed == 1
    svc.close()


# ---------------------------------------------------------------------------
# the PR-5 pin, extended: zero mixed result sets under partitioned refresh
# ---------------------------------------------------------------------------

def test_no_mixed_result_sets_under_partitioned_refresh(served, tmp_path):
    """Concurrent queries through the micro-batcher while append +
    refresh() restage a P=2 service partition by partition: zero
    exceptions, every observed result set is exactly the old table's or
    the new table's — never a cross-partition mix — the tombstoned page
    disappears, and the refresh info carries the per-partition restage
    record."""
    cfg, trainer, emb, _ = served
    from dnn_page_vectors_tpu.updates import append_corpus
    store = _fresh_store(served, tmp_path)
    svc = SearchService(_cfg(partitions=2, batch_window_ms=2.0,
                             max_batch=8),
                        emb, trainer.corpus, store, preload_hbm_gb=4.0)
    svc.start_batcher()
    cand = list(range(0, 300, 13))
    queries = {qi: trainer.corpus.query_text(qi) for qi in cand}
    first = {qi: tuple(r["page_id"] for r in svc.search(queries[qi], k=10))
             for qi in cand}
    victims = [qi for qi in cand if qi in first[qi]]
    assert victims, "test model retrieves no gold at all; cannot proceed"
    victim = victims[0]
    qids = [victim] + [qi for qi in cand if qi != victim][:3]
    before = {qi: first[qi] for qi in qids}
    stop = threading.Event()
    errors, observed = [], {qi: set() for qi in qids}

    def hammer(qi):
        while not stop.is_set():
            try:
                observed[qi].add(tuple(
                    r["page_id"] for r in svc.search(queries[qi], k=10)))
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=hammer, args=(qi,))
               for qi in qids for _ in range(2)]
    for t in threads:
        t.start()
    try:
        grown = ToyCorpus(num_pages=400, seed=trainer.corpus.seed,
                          num_topics=trainer.corpus.num_topics,
                          page_len=trainer.corpus.page_len,
                          query_len=trainer.corpus.query_len,
                          languages=trainer.corpus.languages)
        append_corpus(emb, grown, store, tombstone=[victim])
        info = svc.refresh()
        time.sleep(0.3)                 # let queries land on the new table
    finally:
        stop.set()                      # a failed append must not leave
        for t in threads:               # the hammers spinning forever
            t.join()
    after = {qi: tuple(r["page_id"] for r in svc.search(queries[qi], k=10))
             for qi in qids}
    assert not errors, f"partitioned hot-swap raised: {errors[:3]}"
    for qi in qids:
        extra = observed[qi] - {before[qi], after[qi]}
        assert not extra, (f"query {qi} saw a mixed result set during the "
                           f"partitioned swap: {extra}")
    assert victim not in after[victim]
    # per-partition restage record: both partitions restaged, with the
    # new generation's shards split contiguously between them
    parts = info["partitions"]
    assert len(parts) == 2
    assert all(p["restage_ms"] for p in parts)
    # spec rows count RAW shard rows (tombstones mask at read time)
    assert sum(p["rows"] for p in parts) == 400
    met = svc.metrics()
    assert met["refreshes"] == 1
    assert met["store_generation"] == 1
    svc.close()


# ---------------------------------------------------------------------------
# host-simulation accounting (the bench partitioned_serve phase)
# ---------------------------------------------------------------------------

def test_host_simulation_critical_path_and_scan_bytes(served):
    cfg, trainer, emb, store = served
    qv = np.asarray(emb.embed_texts([trainer.corpus.query_text(5)],
                                    tower="query"), np.float32)
    svc1 = SearchService(_cfg(partitions=1, replicas=2), emb,
                         trainer.corpus, store, preload_hbm_gb=4.0)
    svc4 = SearchService(_cfg(partitions=4), emb, trainer.corpus, store,
                         preload_hbm_gb=4.0)
    sim1 = svc1.partition_set.simulate(qv, 1, 10)
    sim4 = svc4.partition_set.simulate(qv, 1, 10)
    assert np.array_equal(sim1["ids"], sim4["ids"])
    assert np.array_equal(sim1["scores"], sim4["scores"])
    assert len(sim4["partition_seconds"]) == 4
    assert sim4["critical_path_seconds"] >= max(sim4["partition_seconds"])
    # the acceptance geometry: per-query critical-path scan bytes at P=4
    # are <= 1/3 of the single-partition scan (6 equal shards -> 1/3)
    assert sum(sim1["scan_bytes"]) == 300 * store.row_bytes
    assert max(sim4["scan_bytes"]) * 3 <= max(sim1["scan_bytes"])
    # topk_vectors drives the same paths by raw vectors
    s1, i1 = svc1.topk_vectors(qv, k=10)
    s4, i4 = svc4.topk_vectors(qv, k=10)
    assert np.array_equal(i1, i4) and np.array_equal(s1, s4)
    svc4.close()
    svc1.close()


def test_trial_record_carries_partition_block(served):
    from dnn_page_vectors_tpu.loadgen import make_workload, run_trial
    cfg, trainer, emb, store = served
    svc = SearchService(_cfg(partitions=2), emb, trainer.corpus, store,
                        preload_hbm_gb=4.0)
    svc.start_batcher()
    wl = make_workload("poisson", seed=3, distinct=4)
    queries = [trainer.corpus.query_text(i) for i in range(4)]
    tr = run_trial(svc, wl, 40.0, queries, duration_s=0.4, warmup_s=0.0,
                   workers=4)
    assert tr["errors"] == 0
    assert len(tr["partitions"]) == 2
    for p in tr["partitions"]:
        for key in ("partition", "shards", "rows", "qps", "p99_ms",
                    "sheds", "degraded_serves", "replicas"):
            assert key in p, key
    assert tr["replica_shed"] == 0 and tr["partition_degraded"] == 0
    svc.close()
