"""Subprocess worker for the elastic-restore half of tests/test_multihost.py
— NOT a test module.

One phase of an elastic training job on the 4-device global mesh: either
train from scratch and SAVE a collective checkpoint, or RESTORE a
checkpoint written by a job with a DIFFERENT process count and continue
training. The parent test chains phases across process topologies
(1-process save -> 2-process resume, and the reverse) and compares the
final params to an uninterrupted single-process run (VERDICT r4 Missing
#3: cross-topology restore had only ever been asserted, not executed).

Usage: python mh_elastic_worker.py PORT NPROC PID WORKDIR MODE STEPS
  MODE = "save"   — init fresh, train STEPS, save checkpoint (collective)
         "resume" — restore latest from WORKDIR/ckpt, train STEPS more
Both modes dump flat fp32 params to WORKDIR/params_after_MODE.npy (pid 0).
Env:   JAX_PLATFORMS=cpu, XLA_FLAGS=--xla_force_host_platform_device_count=K
"""
import os
import sys


def main() -> None:
    port, nproc, pid, workdir, mode, steps = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
        sys.argv[5], int(sys.argv[6]))
    import jax
    jax.config.update("jax_platforms", "cpu")
    if nproc > 1:
        jax.distributed.initialize(
            coordinator_address=f"127.0.0.1:{port}",
            num_processes=nproc, process_id=pid)

    import numpy as np
    from dnn_page_vectors_tpu.config import get_config
    from dnn_page_vectors_tpu.train.checkpoint import CheckpointManager
    from dnn_page_vectors_tpu.train.loop import Trainer

    cfg = get_config("cdssm_toy", {
        "data.num_pages": 64, "data.page_len": 12, "data.query_len": 6,
        "data.trigram_buckets": 512,
        "model.conv_channels": 32, "model.embed_dim": 32, "model.out_dim": 32,
        "mesh.data": 4,
        "train.batch_size": 8, "train.steps": 8, "train.log_every": 100,
    }).replace(workdir=workdir)

    trainer = Trainer(cfg)
    assert trainer.mesh.devices.size == 4
    mgr = CheckpointManager(os.path.join(workdir, "ckpt"))
    if mode == "save":
        state = trainer.init_state()
        state, _ = trainer.train(steps=steps, state=state)
        mgr.save(int(state.step), state, wait=True)
    elif mode == "resume":
        # restore a checkpoint SAVED UNDER A DIFFERENT PROCESS COUNT into
        # this topology's global shardings, then keep training (the data
        # cursor re-derives from the restored step, so batch order matches
        # an uninterrupted run)
        state = mgr.restore(trainer.init_state())
        state, _ = trainer.train(steps=steps, state=state)
    else:
        raise SystemExit(f"unknown mode {mode!r}")
    mgr.close()

    if pid == 0:
        leaves = jax.tree_util.tree_leaves(state.params)
        flat = np.concatenate(
            [np.asarray(l, np.float32).ravel() for l in leaves])
        out = os.path.join(workdir, f"params_after_{mode}.npy")
        with open(out + ".tmp", "wb") as f:
            np.save(f, flat)
        os.replace(out + ".tmp", out)
    if nproc > 1:
        from dnn_page_vectors_tpu.parallel.multihost import barrier
        barrier("elastic_done")


if __name__ == "__main__":
    main()
