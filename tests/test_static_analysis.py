"""graftcheck static-analysis tests (docs/ANALYSIS.md): the nine rule
families' true-positive/true-negative fixture matrix (determinism, lock
discipline, lock-order/deadlock, thread & resource lifecycle, asyncio
hygiene, jit purity + host-sync, manifest I/O, wire-protocol
conformance, doc drift), pragma-suppression semantics (line vs file
scope, missing-reason rejected), baseline add/expire behavior, the
`cli lint` JSON report + exit codes + `--changed` fast mode, and the
repo-is-clean tier-1 gate.

Everything here is AST-only: no jax, no devices, no stores — the cli
subprocess tests even strip JAX_PLATFORMS so the lint path is exercised
exactly as it runs on a jax-less box.
"""
import json
import os
import subprocess
import sys

import pytest

from dnn_page_vectors_tpu.tools.analyze import (
    BASELINE_NAME, RULES, analyze, analyze_source, write_baseline)

pytestmark = pytest.mark.lint

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(findings, name=None):
    return [f for f in findings if name is None or f.rule == name]


# ---------------------------------------------------------------------------
# family 1: determinism
# ---------------------------------------------------------------------------

_DET_POS = """
import random
import time
import numpy as np
import jax
from datetime import datetime

def bad():
    a = np.random.rand(3)                 # module-state sampler
    b = random.random()                   # stdlib module state
    c = np.random.default_rng()           # seedless constructor
    t = time.time()                       # wall clock
    d = datetime.now()                    # wall clock
    key = jax.random.PRNGKey(int(time.time()))   # clock-fed key
    return a, b, c, t, d, key
"""

_DET_NEG = """
import random
import time
import numpy as np
import jax

def good(seed: int):
    rng = np.random.default_rng(seed)
    r2 = random.Random(seed)
    t = time.perf_counter()               # duration, not wall clock
    key = jax.random.PRNGKey(seed)
    return rng.random(), r2.random(), t, key
"""


def test_determinism_true_positives():
    fs = _rules(analyze_source(
        _DET_POS, "dnn_page_vectors_tpu/infer/fixture.py"), "determinism")
    msgs = "\n".join(f.msg for f in fs)
    # 7 findings on 6 lines: the clock-fed PRNGKey line is both a
    # wall-clock read and a clock-seeded key
    assert len(fs) == 7, msgs
    assert "module-state RNG" in msgs
    assert "stdlib module-state RNG" in msgs
    assert "seedless RNG constructor" in msgs
    assert "wall-clock read" in msgs
    assert "seeded from the wall clock" in msgs


def test_determinism_true_negatives():
    assert not _rules(analyze_source(
        _DET_NEG, "dnn_page_vectors_tpu/infer/fixture.py"), "determinism")


def test_determinism_scope_is_byte_pinned_paths_only():
    # the same sins OUTSIDE the pinned paths (e.g. train/) are not this
    # rule's business
    assert not _rules(analyze_source(
        _DET_POS, "dnn_page_vectors_tpu/train/fixture.py"), "determinism")


# ---------------------------------------------------------------------------
# family 2: lock discipline
# ---------------------------------------------------------------------------

_LOCK_SRC = """
import threading

class Svc:
    def __init__(self):
        self._cache = {}                  # guarded-by: _cache_lock
        self._cache_lock = threading.Lock()
        self._view = None                 # swapped, never mutated
        self.sizes = []
        self._t = threading.Thread(target=self._run)

    def ok_locked(self, k, v):
        with self._cache_lock:
            self._cache[k] = v

    def ok_swap(self):
        self._cache = {}                  # whole-reference assignment

    def ok_snapshot(self):
        cache = self._cache               # snapshot read of the reference
        return cache

    def _evict(self):  # holds-lock: _cache_lock
        self._cache.clear()

    def bad_unlocked(self, k):
        return self._cache[k]             # read outside the lock

    def _run(self):
        self.sizes.append(1)              # thread mutates un-annotated attr
"""


def test_locks_rule_matrix():
    fs = _rules(analyze_source(
        _LOCK_SRC, "dnn_page_vectors_tpu/infer/serve.py"), "locks")
    lines = {f.line for f in fs}
    assert len(fs) == 2, [f.human() for f in fs]
    bad_read = next(f for f in fs if "read holds no lock" in f.msg)
    assert "self._cache" in bad_read.msg and "_cache_lock" in bad_read.msg
    thread_f = next(f for f in fs if "thread-reachable" in f.msg)
    assert "sizes" in thread_f.msg
    # the ok_* accesses, the holds-lock helper, and __init__ are all clean
    assert all("ok_" not in (f.snippet or "") for f in fs), lines


def test_locks_scope_is_the_three_threaded_files():
    assert not _rules(analyze_source(
        _LOCK_SRC, "dnn_page_vectors_tpu/infer/bulk_embed.py"), "locks")


# ---------------------------------------------------------------------------
# family: lock-order / deadlock analysis (project rule on a mini tree)
# ---------------------------------------------------------------------------

_CYCLE_SRC = """
import threading


class Svc:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            self._grab_b()

    def _grab_b(self):
        with self._b:
            pass

    def two(self):
        with self._b:
            with self._a:
                pass
"""


def _lock_project(tmp_path, src):
    pkg = os.path.join(str(tmp_path), "dnn_page_vectors_tpu", "infer")
    os.makedirs(pkg, exist_ok=True)
    with open(os.path.join(pkg, "conc.py"), "w") as f:
        f.write(src)
    return str(tmp_path)


def test_lock_order_cycle_reports_both_acquisition_paths(tmp_path):
    r = analyze(root=_lock_project(tmp_path, _CYCLE_SRC))
    fs = _rules(r.findings, "lock-order")
    assert len(fs) == 1, [f.human() for f in r.findings]
    msg = fs[0].msg
    assert "potential deadlock" in msg
    assert "`Svc._a` -> `Svc._b`" in msg or "`Svc._b` -> `Svc._a`" in msg
    # BOTH acquisition paths ride the finding: the call-closure edge
    # through _grab_b and the direct nested-with edge in two()
    assert msg.count("held") >= 2, msg
    assert "_grab_b" in msg
    assert msg.count("conc.py:") >= 2, msg


def test_lock_order_no_cycle_is_clean(tmp_path):
    src = _CYCLE_SRC.replace(
        "    def two(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n", "")
    r = analyze(root=_lock_project(tmp_path, src))
    assert not _rules(r.findings, "lock-order"), [
        f.human() for f in r.findings]


def test_lock_order_declaration_violation_and_unknown_name(tmp_path):
    src = """
import threading


class Svc:
    def __init__(self):
        # lock-order: Svc._b < Svc._a
        # lock-order: Svc._ghost < Svc._a
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass
"""
    r = analyze(root=_lock_project(tmp_path, src))
    msgs = "\n".join(f.msg for f in _rules(r.findings, "lock-order"))
    assert "violates the declared hierarchy" in msgs       # a->b vs b<a
    assert "Svc._ghost" in msgs and "no such lock" in msgs  # stale decl


def test_lock_order_declared_hierarchy_is_clean(tmp_path):
    src = """
import threading


class Svc:
    def __init__(self):
        # lock-order: Svc._a < Svc._b
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass
"""
    r = analyze(root=_lock_project(tmp_path, src))
    assert not _rules(r.findings, "lock-order"), [
        f.human() for f in r.findings]


def test_lock_order_rlock_reentry_is_not_a_self_deadlock(tmp_path):
    src = """
import threading


class Svc:
    def __init__(self):
        self._m = threading.RLock()

    def outer(self):
        with self._m:
            self.inner()

    def inner(self):
        with self._m:
            pass
"""
    r = analyze(root=_lock_project(tmp_path, src))
    assert not _rules(r.findings, "lock-order")
    plain = src.replace("RLock", "Lock")
    r2 = analyze(root=_lock_project(tmp_path, plain))
    msgs = "\n".join(f.msg for f in _rules(r2.findings, "lock-order"))
    assert "self-deadlock" in msgs


# ---------------------------------------------------------------------------
# family: thread & resource lifecycle
# ---------------------------------------------------------------------------

_LIFE_POS = """
import socket
import threading


def leaked_thread():
    t = threading.Thread(target=print)
    t.start()                             # never joined, not daemon


def happy_path_close(addr):
    s = socket.create_connection(addr)
    s.sendall(b"x")
    s.close()                             # skipped when sendall raises


def never_closed(addr):
    s = socket.create_connection(addr)
    s.sendall(b"x")


def gap_before_try(addr):
    s = socket.create_connection(addr)
    s.setsockopt(1, 2, 3)                 # raises -> finally never runs
    try:
        s.sendall(b"x")
    finally:
        s.close()
"""

_LIFE_NEG = """
import socket
import threading


def daemonized():
    t = threading.Thread(target=print, daemon=True)
    t.start()


def joined():
    t = threading.Thread(target=print)
    t.start()
    t.join()


def managed(addr):
    with socket.create_connection(addr) as s:
        s.sendall(b"x")


def closed_in_finally(addr):
    s = socket.create_connection(addr)
    try:
        s.sendall(b"x")
    finally:
        s.close()


def transferred(addr):
    s = socket.create_connection(addr)
    return s                              # the caller owns it now


class Owner:
    def __init__(self, addr):
        self._sock = socket.create_connection(addr)

    def close(self):
        self._sock.close()
"""


def test_lifecycle_true_positives():
    fs = _rules(analyze_source(
        _LIFE_POS, "dnn_page_vectors_tpu/infer/fixture.py"), "lifecycle")
    msgs = "\n".join(f.msg for f in fs)
    assert len(fs) == 4, [f.human() for f in fs]
    assert "neither daemonized nor joined" in msgs
    assert "happy path" in msgs
    assert "never closed" in msgs
    assert "between" in msgs and "try/finally" in msgs


def test_lifecycle_true_negatives():
    assert not _rules(analyze_source(
        _LIFE_NEG, "dnn_page_vectors_tpu/infer/fixture.py"), "lifecycle")


def test_lifecycle_unowned_self_attr_is_a_finding():
    src = ("import socket\n"
           "class Leaky:\n"
           "    def __init__(self, addr):\n"
           "        self._sock = socket.create_connection(addr)\n")
    fs = _rules(analyze_source(
        src, "dnn_page_vectors_tpu/infer/fixture.py"), "lifecycle")
    assert len(fs) == 1 and "leaked on shutdown" in fs[0].msg


def test_lifecycle_scope_excludes_models():
    assert not _rules(analyze_source(
        _LIFE_POS, "dnn_page_vectors_tpu/models/fixture.py"), "lifecycle")


# ---------------------------------------------------------------------------
# family: asyncio hygiene
# ---------------------------------------------------------------------------

_ASYNC_POS = """
import asyncio
import time


async def bad():
    time.sleep(0.1)                        # blocks the loop
    open("/tmp/x")                         # file I/O on the loop
    asyncio.create_task(asyncio.sleep(0))  # discarded task
    try:
        await asyncio.sleep(0)
    except:                                # swallows CancelledError
        pass
"""

_ASYNC_NEG = """
import asyncio
import time


async def good():
    await asyncio.sleep(0.1)
    t = asyncio.create_task(asyncio.sleep(0))
    await t
    try:
        await asyncio.sleep(0)
    except asyncio.CancelledError:
        raise
    except Exception:
        pass
    loop = asyncio.get_running_loop()
    await loop.run_in_executor(None, lambda: time.sleep(0.1))


def sync_helper():
    time.sleep(0.1)                        # executor payload: fine
"""


def test_async_hygiene_true_positives():
    fs = _rules(analyze_source(
        _ASYNC_POS, "dnn_page_vectors_tpu/infer/fixture.py"),
        "async-hygiene")
    msgs = "\n".join(f.msg for f in fs)
    assert len(fs) == 4, [f.human() for f in fs]
    assert "time.sleep" in msgs
    assert "file I/O" in msgs
    assert "create_task" in msgs and "discarded" in msgs
    assert "CancelledError" in msgs


def test_async_hygiene_true_negatives():
    assert not _rules(analyze_source(
        _ASYNC_NEG, "dnn_page_vectors_tpu/infer/fixture.py"),
        "async-hygiene")


# ---------------------------------------------------------------------------
# family: wire-protocol conformance (project rule on a mini tree)
# ---------------------------------------------------------------------------

_MINI_TRANSPORT = '''
import struct

T_PING = 1
T_PONG = 2

_TYPES = {T_PING, T_PONG}

_HEAD = struct.Struct("!Q")


def decode_ping(payload):
    if len(payload) != _HEAD.size:
        raise ValueError("bad ping")
    return _HEAD.unpack(payload)[0]
'''

_MINI_SERVING_CLEAN = """# Serving

| type | payload | notes |
|---|---|---|
| `PING` | req u64 | ping |
| `PONG` | empty | pong |
"""

_MINI_SERVING_DIRTY = """# Serving

| type | payload | notes |
|---|---|---|
| `PING` | req u64 | ping |
| `GONE` | empty | removed long ago |
"""


def _proto_project(tmp_path, doc):
    root = str(tmp_path)
    pkg = os.path.join(root, "dnn_page_vectors_tpu", "infer")
    os.makedirs(pkg, exist_ok=True)
    os.makedirs(os.path.join(root, "docs"), exist_ok=True)
    with open(os.path.join(pkg, "transport.py"), "w") as f:
        f.write(_MINI_TRANSPORT)
    with open(os.path.join(root, "docs", "SERVING.md"), "w") as f:
        f.write(doc)
    return root


def test_proto_drift_catches_missing_and_stale_rows(tmp_path):
    r = analyze(root=_proto_project(tmp_path, _MINI_SERVING_DIRTY))
    msgs = "\n".join(f.msg for f in _rules(r.findings, "proto-drift"))
    assert "T_PONG" in msgs and "no row" in msgs        # constant undocumented
    assert "GONE" in msgs and "stale" in msgs           # row without constant
    # PONG's payload is unknown (no row), so the missing decode branch
    # flags too
    assert "no bounded-length decode branch" in msgs


def test_proto_drift_clean_table_passes(tmp_path):
    r = analyze(root=_proto_project(tmp_path, _MINI_SERVING_CLEAN))
    assert not _rules(r.findings, "proto-drift"), [
        f.human() for f in r.findings]


def test_proto_drift_unregistered_type_and_unguarded_decoder(tmp_path):
    src = _MINI_TRANSPORT.replace(
        "_TYPES = {T_PING, T_PONG}", "_TYPES = {T_PING}").replace(
        '    if len(payload) != _HEAD.size:\n'
        '        raise ValueError("bad ping")\n', "").replace(
        "    return _HEAD.unpack(payload)[0]",
        "    return _HEAD.unpack_from(payload)[0]")
    root = _proto_project(tmp_path, _MINI_SERVING_CLEAN)
    with open(os.path.join(root, "dnn_page_vectors_tpu", "infer",
                           "transport.py"), "w") as f:
        f.write(src)
    msgs = "\n".join(f.msg for f in _rules(
        analyze(root=root).findings, "proto-drift"))
    assert "not registered in `_TYPES`" in msgs
    assert "no length guard" in msgs


# ---------------------------------------------------------------------------
# family 3: jit purity + host-sync
# ---------------------------------------------------------------------------

_JIT_SRC = """
from functools import partial
import jax

TRACE_LOG = []

@jax.jit
def bad(x):
    print("tracing", x)                  # trace-time-only side effect
    TRACE_LOG.append(x)                  # captured-state mutation
    return x * 2

@partial(jax.jit, static_argnames=("k",))
def also_jitted(x, k):
    acc = []
    acc.append(k)                        # local list: fine
    return x[:k]

def host_fn(x):
    print("host side is allowed", x)
    return x
"""

_HOT_SRC = """
import numpy as np

# graftcheck: hot
def dispatch(dev_results):
    out = [r.item() for r in dev_results]     # per-element sync
    arr = np.asarray(dev_results)             # device pull
    return out, arr

def cold(dev_results):
    return [r.item() for r in dev_results]    # not marked hot: fine
"""


def test_jit_purity_matrix():
    fs = _rules(analyze_source(
        _JIT_SRC, "dnn_page_vectors_tpu/ops/fixture.py"), "jit-purity")
    msgs = "\n".join(f.msg for f in fs)
    assert len(fs) == 2, msgs
    assert "print()" in msgs and "mutates captured state" in msgs
    # models/ and index/ are in scope too; train/ is not a compiled-op home
    assert not _rules(analyze_source(
        _JIT_SRC, "dnn_page_vectors_tpu/train/fixture.py"), "jit-purity")


def test_host_sync_fires_only_on_hot_functions():
    fs = _rules(analyze_source(
        _HOT_SRC, "dnn_page_vectors_tpu/infer/fixture.py"), "host-sync")
    assert len(fs) == 2, [f.human() for f in fs]
    assert any(".item()" in f.msg for f in fs)
    assert any("numpy.asarray" in f.msg for f in fs)
    assert all(f.line < 10 for f in fs)       # nothing from cold()


# ---------------------------------------------------------------------------
# family 4: manifest I/O
# ---------------------------------------------------------------------------

_IO_SRC = """
import json
import os
import numpy as np

from dnn_page_vectors_tpu.infer.vector_store import crc_file

def bad_write(path, obj):
    with open(path, "w") as f:            # unmanifested write
        json.dump(obj, f)

def bad_save(path, arr):
    np.save(path, arr)                    # unmanifested array

def _atomic_dump(obj, path):
    with open(path + ".tmp", "w") as f:   # the sanctioned writer itself
        json.dump(obj, f)
    os.replace(path + ".tmp", path)

def crc_recorded_write(path, arr):
    np.save(path, arr)                    # CRC recorded below: sanctioned
    return os.path.getsize(path), crc_file(path)

def reader(path):
    with open(path) as f:                 # reads are nobody's business
        return f.read()
"""


def test_manifest_io_matrix():
    fs = _rules(analyze_source(
        _IO_SRC, "dnn_page_vectors_tpu/index/fixture.py"), "manifest-io")
    assert len(fs) == 2, [f.human() for f in fs]
    assert any("open" in f.msg for f in fs)
    assert any("numpy.save" in f.msg for f in fs)
    # infer/ (vector_store's own home) is not in this rule's scope
    assert not _rules(analyze_source(
        _IO_SRC, "dnn_page_vectors_tpu/infer/fixture.py"), "manifest-io")


# ---------------------------------------------------------------------------
# family 5: drift (project rules on a mini tree)
# ---------------------------------------------------------------------------

_MINI_CONFIG = '''
import dataclasses

@dataclasses.dataclass(frozen=True)
class ServeConfig:
    nprobe: int = 8
    mystery_knob: int = 3

@dataclasses.dataclass(frozen=True)
class Config:
    name: str
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
'''

_MINI_OBS_DOC = """# Observability

Knobs: `serve.nprobe` steers probing. See also `serve.ghost_knob`.

| event | meaning |
|---|---|
| `view_swap` | serving view hot-swapped |
| `dead_event` | documented but never emitted |
"""

_MINI_EVENTS_PY = '''
def fire(registry):
    registry.event("view_swap")
    registry.event("secret_event")
'''

_MINI_PYTEST_INI = """[pytest]
markers =
    slow: long tests
    ghost: declared but never used
"""

_MINI_TEST_PY = """
import pytest

@pytest.mark.slow
def test_a():
    pass

@pytest.mark.rogue
def test_b():
    pass
"""


def _mini_project(root, clean=False):
    pkg = os.path.join(root, "dnn_page_vectors_tpu")
    os.makedirs(pkg, exist_ok=True)
    os.makedirs(os.path.join(root, "docs"), exist_ok=True)
    os.makedirs(os.path.join(root, "tests"), exist_ok=True)
    cfg = _MINI_CONFIG
    obs = _MINI_OBS_DOC
    events = _MINI_EVENTS_PY
    ini = _MINI_PYTEST_INI
    test_py = _MINI_TEST_PY
    if clean:
        cfg = cfg.replace("    mystery_knob: int = 3\n", "")
        obs = (obs.replace("See also `serve.ghost_knob`.", "")
                  .replace("| `dead_event` | documented but never emitted |\n",
                           ""))
        events = events.replace('    registry.event("secret_event")\n', "")
        ini = ini.replace("    ghost: declared but never used\n", "")
        test_py = test_py.replace(
            "@pytest.mark.rogue\ndef test_b():\n    pass\n", "")
    with open(os.path.join(pkg, "config.py"), "w") as f:
        f.write(cfg)
    with open(os.path.join(pkg, "telem.py"), "w") as f:
        f.write(events)
    with open(os.path.join(root, "docs", "OBSERVABILITY.md"), "w") as f:
        f.write(obs)
    with open(os.path.join(root, "pytest.ini"), "w") as f:
        f.write(ini)
    with open(os.path.join(root, "tests", "test_mini.py"), "w") as f:
        f.write(test_py)
    return root


def test_drift_rules_mini_project(tmp_path):
    root = _mini_project(str(tmp_path))
    r = analyze(root=root)
    by_rule = {}
    for f in r.findings:
        by_rule.setdefault(f.rule, []).append(f)
    knob_msgs = "\n".join(f.msg for f in by_rule.get("drift-knobs", []))
    assert "serve.mystery_knob" in knob_msgs          # undocumented knob
    assert "serve.ghost_knob" in knob_msgs            # stale doc reference
    ev_msgs = "\n".join(f.msg for f in by_rule.get("drift-events", []))
    assert "secret_event" in ev_msgs                  # emitted, undocumented
    assert "dead_event" in ev_msgs                    # documented, dead
    mk_msgs = "\n".join(f.msg for f in by_rule.get("drift-markers", []))
    assert "rogue" in mk_msgs                         # used, undeclared
    assert "ghost" in mk_msgs                         # declared, unused
    # and the `view_swap`/`slow`/`nprobe` matches stayed silent
    for quiet in ("view_swap", "`slow`", "serve.nprobe"):
        assert quiet not in knob_msgs + ev_msgs + mk_msgs


def test_drift_rules_clean_mini_project(tmp_path):
    root = _mini_project(str(tmp_path), clean=True)
    r = analyze(root=root)
    assert not r.findings, [f.human() for f in r.findings]


# ---------------------------------------------------------------------------
# pragma semantics
# ---------------------------------------------------------------------------

def test_pragma_inline_with_reason_suppresses():
    src = ("import numpy as np\n"
           "x = np.random.rand(3)  "
           "# graftcheck: off=determinism -- fixture wants raw entropy\n")
    fs = analyze_source(src, "dnn_page_vectors_tpu/infer/fixture.py")
    assert not _rules(fs, "determinism")
    assert not _rules(fs, "pragma")


def test_pragma_without_reason_is_rejected_and_reported():
    src = ("import numpy as np\n"
           "x = np.random.rand(3)  # graftcheck: off=determinism\n")
    fs = analyze_source(src, "dnn_page_vectors_tpu/infer/fixture.py")
    assert _rules(fs, "determinism")       # NOT suppressed
    assert _rules(fs, "pragma")            # and the naked pragma is flagged


def test_pragma_wrong_rule_does_not_suppress():
    src = ("import numpy as np\n"
           "x = np.random.rand(3)  # graftcheck: off=locks -- wrong family\n")
    fs = analyze_source(src, "dnn_page_vectors_tpu/infer/fixture.py")
    assert _rules(fs, "determinism")


def test_pragma_file_scope_at_top_of_file():
    src = ("# graftcheck: off=determinism -- synthetic chaos fixture\n"
           "import numpy as np\n"
           "x = np.random.rand(3)\n"
           "y = np.random.rand(4)\n")
    fs = analyze_source(src, "dnn_page_vectors_tpu/infer/fixture.py")
    assert not _rules(fs, "determinism")


def test_pragma_standalone_mid_file_covers_next_code_line_only():
    src = ("import numpy as np\n"
           "# graftcheck: off=determinism -- seeded upstream of this call\n"
           "x = np.random.rand(3)\n"
           "y = np.random.rand(4)\n")
    fs = _rules(analyze_source(
        src, "dnn_page_vectors_tpu/infer/fixture.py"), "determinism")
    assert len(fs) == 1 and fs[0].line == 4


# ---------------------------------------------------------------------------
# baseline add / expire
# ---------------------------------------------------------------------------

def test_baseline_add_and_expire(tmp_path):
    root = _mini_project(str(tmp_path))
    baseline = os.path.join(root, BASELINE_NAME)
    first = analyze(root=root)
    assert first.findings and first.exit_code == 1
    write_baseline(baseline, first.findings)

    second = analyze(root=root)             # same tree, accepted findings
    assert not second.findings and second.exit_code == 0
    assert len(second.baselined) == len(first.findings)
    assert not second.stale_baseline

    _mini_project(str(tmp_path), clean=True)  # everything fixed
    third = analyze(root=root)
    assert not third.findings and third.exit_code == 0
    assert not third.baselined
    assert third.stale_baseline              # entries now expired, listed


# ---------------------------------------------------------------------------
# cli lint: JSON report shape + exit codes (subprocess, no jax import)
# ---------------------------------------------------------------------------

def _run_lint(root):
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "dnn_page_vectors_tpu.cli", "lint",
         "--root", root],
        capture_output=True, text=True, env=env, timeout=120)


def test_cli_lint_exits_nonzero_on_seeded_violation(tmp_path):
    proc = _run_lint(_mini_project(str(tmp_path)))
    assert proc.returncode == 1, proc.stderr
    report = json.loads(proc.stdout)
    assert report["exit_code"] == 1
    assert report["counts"]["findings"] == len(report["findings"])
    assert report["findings"], report
    f = report["findings"][0]
    assert set(f) >= {"rule", "path", "line", "col", "msg", "snippet"}
    # human diagnostics ride stderr as file:line:col
    assert ":" in proc.stderr.splitlines()[0]


def test_cli_lint_exits_zero_on_clean_tree_and_after_write_baseline(tmp_path):
    clean_root = _mini_project(str(tmp_path / "clean"), clean=True)
    os.makedirs(clean_root, exist_ok=True)
    proc = _run_lint(clean_root)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["counts"]["findings"] == 0
    assert sorted(report["rules"]) == sorted(RULES)

    dirty_root = _mini_project(str(tmp_path / "dirty"))
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    wb = subprocess.run(
        [sys.executable, "-m", "dnn_page_vectors_tpu.cli", "lint",
         "--root", dirty_root, "--write-baseline"],
        capture_output=True, text=True, env=env, timeout=120)
    assert wb.returncode == 0, wb.stderr
    assert json.loads(wb.stdout)["entries"] > 0
    proc = _run_lint(dirty_root)             # baselined: now green
    assert proc.returncode == 0, proc.stderr
    assert json.loads(proc.stdout)["counts"]["baselined"] > 0


# ---------------------------------------------------------------------------
# cli lint --changed: the fast pre-commit mode (docs/ANALYSIS.md)
# ---------------------------------------------------------------------------

def test_analyze_paths_restricts_file_rules_only(tmp_path):
    root = str(tmp_path)
    pkg = os.path.join(root, "dnn_page_vectors_tpu", "infer")
    os.makedirs(pkg, exist_ok=True)
    bad = ("import numpy as np\n"
           "x = np.random.rand(3)\n")
    for name in ("one.py", "two.py"):
        with open(os.path.join(pkg, name), "w") as f:
            f.write(bad)
    full = analyze(root=root)
    assert len(_rules(full.findings, "determinism")) == 2
    part = analyze(root=root,
                   paths=["dnn_page_vectors_tpu/infer/one.py"])
    fs = _rules(part.findings, "determinism")
    assert len(fs) == 1 and fs[0].path.endswith("one.py")
    assert part.files_scanned == 1


def test_analyze_paths_suppresses_stale_baseline(tmp_path):
    root = _mini_project(str(tmp_path))
    baseline = os.path.join(root, BASELINE_NAME)
    write_baseline(baseline, analyze(root=root).findings)
    _mini_project(str(tmp_path), clean=True)     # everything fixed
    full = analyze(root=root)
    assert full.stale_baseline                   # full mode reports stale
    part = analyze(root=root, paths=[])
    assert not part.stale_baseline               # restricted mode cannot


def test_cli_lint_changed_runs_project_rules_on_the_real_repo():
    """`--changed HEAD` on this checkout: file rules over only the
    diffed files, project rules whole-repo, exit 0 (the repo is clean).
    Also pins the stderr mode banner and that the JSON shape is the
    plain report."""
    if not os.path.isdir(os.path.join(_REPO, ".git")):
        pytest.skip("not a git checkout")
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "dnn_page_vectors_tpu.cli", "lint",
         "--root", _REPO, "--changed", "HEAD"],
        capture_output=True, text=True, env=env, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["counts"]["findings"] == 0
    # the project-level rules ran regardless of the diff restriction
    assert "proto-drift" in report["rules"]
    assert "lock-order" in report["rules"]
    assert "--changed" in proc.stderr or "changed" in proc.stderr


def test_cli_lint_changed_bad_ref_exits_2(tmp_path):
    if not os.path.isdir(os.path.join(_REPO, ".git")):
        pytest.skip("not a git checkout")
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "dnn_page_vectors_tpu.cli", "lint",
         "--root", _REPO, "--changed", "no-such-ref-xyzzy"],
        capture_output=True, text=True, env=env, timeout=120)
    assert proc.returncode == 2
    assert "failed" in proc.stderr


# ---------------------------------------------------------------------------
# the repo itself is clean — the tier-1 gate behind `cli lint` exit 0
# ---------------------------------------------------------------------------

def test_repo_has_no_unsuppressed_findings():
    r = analyze(root=_REPO)
    assert not r.findings, "\n".join(f.human() for f in r.findings)
    assert not r.stale_baseline, r.stale_baseline
    # every suppression carries its reason (enforced by the pragma rule,
    # double-checked here so the report stays honest)
    assert all(s.get("reason") for s in r.suppressed)


def test_bulk_embed_sweep_is_host_sync_scoped():
    """Round 11 (MFU campaign): the bulk-embed sweep is `# graftcheck:
    hot`, so an accidental per-array `.item()`/`np.asarray` sync added
    inside the new packed-d2h pipeline fails `cli lint`. Pinned two ways:
    the annotation exists on embed_corpus (the repo's ONE packed
    device_get shows up as a reasoned host-sync suppression), and an
    accidental sync inserted into an identically-annotated loop is a
    finding."""
    r = analyze(root=_REPO)
    assert any(s["path"].endswith("infer/bulk_embed.py")
               and s["rule"] == "host-sync" and s.get("reason")
               for s in r.suppressed), (
        "embed_corpus lost its hot annotation (or its packed-d2h pragma)")
    findings = analyze_source(
        "import numpy as np\n"
        "# graftcheck: hot\n"
        "def embed_sweep(batches):\n"
        "    out = []\n"
        "    for b in batches:\n"
        "        out.append(np.asarray(b))\n"
        "    return out\n",
        "pkg/infer/sweep.py")
    assert _rules(findings, "host-sync"), \
        "np.asarray inside a hot embed loop must be a host-sync finding"


def test_analyzer_is_stdlib_only():
    """The lint path must run on a jax-less box: no jax/numpy imports
    anywhere under tools/analyze (the subprocess tests above strip
    JAX_PLATFORMS, this pins the import graph itself)."""
    import ast
    adir = os.path.join(_REPO, "dnn_page_vectors_tpu", "tools", "analyze")
    for name in os.listdir(adir):
        if not name.endswith(".py"):
            continue
        tree = ast.parse(open(os.path.join(adir, name)).read())
        for node in ast.walk(tree):
            mods = []
            if isinstance(node, ast.Import):
                mods = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom) and node.module:
                mods = [node.module]
            for m in mods:
                root_mod = m.split(".")[0]
                assert root_mod not in ("jax", "numpy", "jaxlib"), (
                    f"{name} imports {m}")


def test_rule_registry_documented():
    """Every registered rule appears (backticked) in docs/ANALYSIS.md —
    the analyzer eats its own drift dog food."""
    doc = open(os.path.join(_REPO, "docs", "ANALYSIS.md")).read()
    for name in RULES:
        assert f"`{name}`" in doc, f"rule `{name}` missing from ANALYSIS.md"
    families = {r.family for r in RULES.values()}
    assert {"determinism", "locks", "jit", "io", "drift",
            "lock-order", "lifecycle", "async", "proto"} <= families
