"""Cross-lingual retrieval oracle for config 5 (mt5_multilingual,
BASELINE.md:25: "mT5-base page encoder + cross-lingual retrieval eval").

The multilingual ToyCorpus writes page i in language i%L and its gold query
in language (i+1)%L, where each language is a bijective syllable permutation
of the same content (data/toy.py) — lexical overlap between a query and its
gold page is zero, so Recall@10 is only reachable by learning the
cross-language correspondences. This is the capability VERDICT r1 #4 found
half-built: encoder present, eval absent.

Shrunk geometry (2-layer T5-variant transformer, 600 pages, 3 languages) so
the CPU run stays in test budget; convergence at this scale was established
by the round-3 experiment run (recall@10 = 1.0 at 300 steps).
"""
import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.evals.recall import evaluate_recall
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.train.loop import Trainer


@pytest.mark.slow
def test_mt5_cross_lingual_end_to_end(tmp_path):
    cfg = get_config("mt5_multilingual", {
        "data.num_pages": 600,
        "data.languages": 3,
        "data.vocab_size": 1024,
        "data.page_len": 48,
        "data.query_len": 12,
        "model.num_layers": 2,
        "model.num_heads": 4,
        "model.model_dim": 96,
        "model.mlp_dim": 192,
        "model.out_dim": 64,
        "model.dropout": 0.0,
        "mesh.data": 1, "mesh.model": 1,
        "train.batch_size": 64,
        "train.steps": 200,
        "train.warmup_steps": 20,
        "train.learning_rate": 2e-3,
        "train.log_every": 100,
        "eval.eval_queries": 200,
        "eval.embed_batch_size": 128,
    })
    trainer = Trainer(cfg, workdir=str(tmp_path))
    # the corpus really is cross-lingual: gold query/page language differ
    corpus = trainer.corpus
    assert corpus.languages == 3
    assert all(corpus.query_language(i) != corpus.page_language(i)
               for i in range(12))

    state, metrics = trainer.train()
    assert np.isfinite(metrics["loss"])
    assert metrics["in_batch_acc"] > 0.5, metrics

    store = VectorStore(str(tmp_path / "store"), dim=cfg.model.out_dim,
                        shard_size=256)
    embedder = BulkEmbedder(cfg, trainer.model, state.params,
                            trainer.page_tok, trainer.mesh,
                            query_tok=trainer.query_tok)
    embedder.embed_corpus(trainer.corpus, store, batch_size=128)
    assert store.num_vectors == 600

    recall, nq = evaluate_recall(embedder, trainer.corpus, store,
                                 num_queries=200, k=10)
    # random recall@10 over 600 pages ~ 1.7%; cross-lingual retrieval must
    # crush it despite zero query<->page lexical overlap
    assert recall > 0.5, f"cross-lingual recall@10={recall} over {nq} queries"
