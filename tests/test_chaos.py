"""Seeded network chaos + self-healing fleet (docs/ROBUSTNESS.md
"Network failure model"): a kill -9'd worker connection re-dials,
re-REGISTERs and is serving again within <= 3x the heartbeat interval
while a continuous query hammer sees ZERO errors and byte-identical
results (the local-view fallback covers the gap), the per-target
CircuitBreaker walks its closed -> open -> half-open ladder on a fake
clock with doubling backoff and single-probe admission, seeded wire
faults (torn/dup/dropped/stalled frames at exact call counts) never
change a single result byte, a generation-lagging rejoiner serves
nothing until the catch-up T_REFRESH lands (results are always exactly
one generation — never a blend), and `cli loadtest --chaos` carries the
pinned availability record."""
import json
import os
import threading
import time

import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.utils import faults

pytestmark = pytest.mark.chaos

DIM = 32
SHARD = 50
NSHARDS = 6


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# fixtures: synthetic store + model-free service (the chaos surface is
# the wire + the supervisor loops, not the encoder)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def net_store(tmp_path_factory):
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore
    sdir = str(tmp_path_factory.mktemp("chaos_store") / "store")
    rng = np.random.default_rng(0)
    store = VectorStore(sdir, dim=DIM, shard_size=SHARD)
    for si in range(NSHARDS):
        v = rng.standard_normal((SHARD, DIM)).astype(np.float32)
        v /= np.linalg.norm(v, axis=1, keepdims=True)
        store.write_shard(si, np.arange(si * SHARD, (si + 1) * SHARD,
                                        dtype=np.int64), v)
    return VectorStore(sdir)


@pytest.fixture(scope="module")
def mesh():
    import jax
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:1]), ("data",))


def _qv(n=3, seed=1):
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((n, DIM)).astype(np.float32)
    return q / np.linalg.norm(q, axis=1, keepdims=True)


def _service(store, mesh, **serve_over):
    import dataclasses

    from dnn_page_vectors_tpu.infer.partition_host import MeshEmbedder
    from dnn_page_vectors_tpu.infer.serve import SearchService
    cfg = get_config("cdssm_toy", {"model.out_dim": DIM})
    if serve_over:
        cfg = cfg.replace(serve=dataclasses.replace(cfg.serve,
                                                    **serve_over))
    return SearchService(cfg, MeshEmbedder(mesh), None, store,
                         preload_hbm_gb=4.0)


def _thread_worker(cfg, store_dir, port, partition, partitions, replica,
                   mesh):
    from dnn_page_vectors_tpu.infer.partition_host import PartitionWorker
    w = PartitionWorker(cfg, store_dir, ("127.0.0.1", port),
                        partition=partition, partitions=partitions,
                        replica=replica, mesh=mesh)
    t = threading.Thread(target=w.run, daemon=True)
    t.start()
    return w, t


# ---------------------------------------------------------------------------
# self-healing: kill -9 the connection under live traffic
# ---------------------------------------------------------------------------

def test_worker_reconnects_after_kill_byte_identical(net_store, mesh):
    """The acceptance drill: tear the sole worker's connection (kill -9
    stand-in — the worker process survives, the socket does not) under a
    continuous hammer. Every answer stays byte-identical to the
    in-process oracle (the fallback serves the gap), zero errors, and
    the worker is re-REGISTERed and routable within <= 3x the heartbeat
    interval, with the `worker_rejoined` event emitted."""
    from dnn_page_vectors_tpu.infer.partition_host import WorkerGateway
    hb_s = 0.5
    svc = _service(net_store, mesh, partitions=1, heartbeat_s=hb_s)
    qv = _qv(2)
    base_s, base_i = svc.topk_vectors(qv, k=10)
    gw = WorkerGateway(svc, heartbeat_s=hb_s)
    svc.attach_gateway(gw)
    w, _t = _thread_worker(svc.cfg, net_store.directory, gw.port, 0, 1, 0,
                           mesh)
    errors, mismatches, results = [], [], [0]
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                s, i = svc.topk_vectors(qv, k=10)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                return
            results[0] += 1
            if not (np.array_equal(s, base_s)
                    and np.array_equal(i, base_i)):
                mismatches.append(i)

    try:
        assert gw.wait_for_workers(1, timeout_s=30.0)
        svc.topk_vectors(qv, k=10)            # warm over the wire
        threads = [threading.Thread(target=hammer) for _ in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        rejoined0 = len(svc.registry.events("worker_rejoined"))
        t_kill = time.perf_counter()
        w.kill_connection()
        recovery = None
        while time.perf_counter() - t_kill < 10.0:
            if (len(svc.registry.events("worker_rejoined")) > rejoined0
                    and gw.worker_alive(0, 0)):
                recovery = time.perf_counter() - t_kill
                break
            time.sleep(0.005)
        time.sleep(0.2)                       # hammer past the rejoin
        stop.set()
        for t in threads:
            t.join()
        assert not errors, errors[:2]
        assert not mismatches, "result bytes changed across the kill"
        assert results[0] > 0
        assert recovery is not None, "worker never rejoined"
        assert recovery <= 3 * hb_s, \
            f"rejoin took {recovery:.3f}s (> 3x the {hb_s}s heartbeat)"
        assert w.sessions >= 2                # the supervisor re-dialed
        ev = svc.registry.events("worker_rejoined")[-1]
        assert (ev["attrs"]["partition"], ev["attrs"]["replica"]) == (0, 0)
        # the rejoined worker actually carries traffic again
        rpcs0 = gw.stats()["rpcs"]
        svc.topk_vectors(qv, k=10)
        assert gw.stats()["rpcs"] > rpcs0
    finally:
        stop.set()
        w.stop()
        gw.close()
        svc.close()


# ---------------------------------------------------------------------------
# circuit breaker: the state ladder on a fake clock
# ---------------------------------------------------------------------------

def test_circuit_breaker_ladder_fake_clock():
    """closed -> (K consecutive failures) -> open -> (backoff elapses)
    -> half-open single probe -> failed probe re-opens with DOUBLED
    backoff (capped) / successful probe closes and resets the ramp. The
    on_open/on_close callbacks fire once per transition."""
    t = [0.0]
    opened, closed = [], []
    br = faults.CircuitBreaker(failures=3, open_s=1.0, max_open_s=4.0,
                               clock=lambda: t[0],
                               on_open=opened.append,
                               on_close=closed.append)
    assert br.state == "closed" and br.allow()
    br.record_failure()
    br.record_failure()
    assert br.allow()                     # 2 < K: still closed
    br.record_success()                   # success resets the streak
    br.record_failure()
    br.record_failure()
    assert br.state == "closed"
    br.record_failure()                   # the K-th consecutive failure
    assert br.state == "open" and br.trips == 1
    assert not br.allow()
    t[0] = 0.99
    assert not br.allow()                 # backoff not yet elapsed
    t[0] = 1.0
    assert br.allow()                     # THE half-open probe
    assert br.state == "half_open"
    assert not br.allow()                 # probe slot already consumed
    br.record_failure()                   # probe failed: re-open doubled
    assert br.state == "open" and br.trips == 2
    t[0] = 2.5
    assert not br.allow()                 # 1.5 s elapsed < 2.0 s backoff
    t[0] = 3.0
    assert br.allow()                     # second probe
    br.record_success()
    assert br.state == "closed" and br.allow()
    # the ramp reset: the next trip waits the BASE backoff again
    for _ in range(3):
        br.record_failure()
    assert br.state == "open" and br.trips == 3
    t[0] = 4.0                            # opened at 3.0 + base 1.0
    assert br.allow()
    # 3 open transitions; ONE close transition (the successful probe) —
    # the early record_success while already closed fires no callback
    assert len(opened) == 3 and len(closed) == 1


# ---------------------------------------------------------------------------
# seeded wire faults: torn / dup / dropped / stalled frames
# ---------------------------------------------------------------------------

def test_wire_faults_never_change_result_bytes(net_store, mesh):
    """A seeded schedule of wire faults — torn frame, duplicated frame,
    stalled read, dropped connection, at EXACT per-op call counts —
    fires under a query loop. Every fault either heals (dup frames are
    discarded by req-id, stalls just wait) or degrades to the local
    fallback; no answer ever differs from the oracle by a single byte
    and no error reaches the caller. The injection counters prove the
    faults actually fired."""
    from dnn_page_vectors_tpu.infer.partition_host import WorkerGateway
    svc = _service(net_store, mesh, partitions=1, heartbeat_s=0.25)
    qv = _qv(2)
    base_s, base_i = svc.topk_vectors(qv, k=10)
    gw = WorkerGateway(svc, heartbeat_s=0.25)
    svc.attach_gateway(gw)
    w, _t = _thread_worker(svc.cfg, net_store.directory, gw.port, 0, 1, 0,
                           mesh)
    try:
        assert gw.wait_for_workers(1, timeout_s=30.0)
        svc.topk_vectors(qv, k=10)            # warm over the wire
        faults.install(faults.FaultPlan.parse(
            "wire_send:frame_trunc:8,wire_send:frame_dup:20,"
            "wire_recv:frame_delay:6,wire_send:conn_drop:34", seed=1))
        for _ in range(50):
            s, i = svc.topk_vectors(qv, k=10)
            assert np.array_equal(s, base_s), "scores changed under chaos"
            assert np.array_equal(i, base_i), "ids changed under chaos"
            time.sleep(0.005)     # let torn connections re-dial between
            # queries, so the later-nth faults see wire traffic again
        c = faults.counters()
        fired = {k: v for k, v in c.items() if k.startswith("injected_")}
        assert sum(fired.values()) >= 3, fired
        assert any(k.startswith("injected_wire_send_") for k in fired), \
            fired
    finally:
        faults.reset()
        w.stop()
        gw.close()
        svc.close()


# ---------------------------------------------------------------------------
# generation gating: a lagging rejoiner never mixes generations
# ---------------------------------------------------------------------------

def test_generation_lagging_rejoiner_catches_up(tmp_path, mesh):
    """A worker that missed a store-generation swap while disconnected
    rejoins advertising its STALE generation. The gateway re-admits it
    but routes nothing to it (generation gating) and immediately sends
    the catch-up T_REFRESH; until the ack lands the front end serves the
    new generation locally. A hammer across the whole window sees every
    answer equal to exactly ONE generation's oracle — never a blend —
    and the worker ends up acked at the new generation and serving."""
    from dnn_page_vectors_tpu.infer.partition_host import WorkerGateway
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore
    sdir = str(tmp_path / "store")
    rng = np.random.default_rng(3)
    store = VectorStore(sdir, dim=DIM, shard_size=SHARD)
    for si in range(4):
        v = rng.standard_normal((SHARD, DIM)).astype(np.float32)
        v /= np.linalg.norm(v, axis=1, keepdims=True)
        store.write_shard(si, np.arange(si * SHARD, (si + 1) * SHARD,
                                        dtype=np.int64), v)
    store = VectorStore(sdir)
    svc = _service(store, mesh, partitions=1, heartbeat_s=0.25)
    qv = _qv(2)
    old_s, old_i = svc.topk_vectors(qv, k=10)
    gw = WorkerGateway(svc, heartbeat_s=0.25)
    svc.attach_gateway(gw)
    w, _t = _thread_worker(svc.cfg, sdir, gw.port, 0, 1, 0, mesh)
    errors, blends = [], []
    new_oracle = {}
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                s, i = svc.topk_vectors(qv, k=10)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                return
            ok_old = (np.array_equal(s, old_s)
                      and np.array_equal(i, old_i))
            ok_new = ("s" in new_oracle
                      and np.array_equal(s, new_oracle["s"])
                      and np.array_equal(i, new_oracle["i"]))
            if not (ok_old or ok_new):
                blends.append(i)

    try:
        assert gw.wait_for_workers(1, timeout_s=30.0)
        old_gen = svc._view.generation
        # hold the supervisor back so the refresh lands while the worker
        # is DISCONNECTED — it must rejoin generation-stale
        w.reconnect_base_s = w.reconnect_max_s = 0.6
        w.kill_connection()
        t0 = time.perf_counter()
        while gw.worker_alive(0, 0) and time.perf_counter() - t0 < 5.0:
            time.sleep(0.005)
        assert not gw.worker_alive(0, 0)
        # the store grows a generation behind the dead connection's back
        grow = VectorStore(sdir)
        writer = grow.begin_generation()
        start = grow.next_page_id()
        v = rng.standard_normal((SHARD, DIM)).astype(np.float32)
        v /= np.linalg.norm(v, axis=1, keepdims=True)
        writer.write_shard(np.arange(start, start + SHARD,
                                     dtype=np.int64), v)
        writer.commit()
        svc.refresh()                     # broadcast reaches 0 workers
        new_gen = svc._view.generation
        assert new_gen != old_gen
        oracle = _service(VectorStore(sdir), mesh, partitions=1)
        try:
            ns, ni = oracle.topk_vectors(qv, k=10)
        finally:
            oracle.close()
        new_oracle["s"], new_oracle["i"] = ns, ni
        th = threading.Thread(target=hammer)
        th.start()
        # the rejoiner REGISTERs with the stale generation, gets the
        # catch-up T_REFRESH, rebuilds, and acks the new generation
        # (wait_for_generation is vacuously true with zero live workers,
        # so wait for the ACK EVENT + liveness explicitly)
        t1 = time.perf_counter()
        acked = False
        while time.perf_counter() - t1 < 30.0:
            ref = svc.registry.events("worker_refreshed")
            if (ref and ref[-1]["attrs"]["generation"] == new_gen
                    and gw.worker_alive(0, 0)):
                acked = True
                break
            time.sleep(0.01)
        assert acked, "lagging rejoiner never acked the catch-up refresh"
        time.sleep(0.2)                   # hammer through the handover
        stop.set()
        th.join()
        assert not errors, errors[:2]
        assert not blends, "a result matched neither generation's oracle"
        regs = svc.registry.events("worker_registered")
        assert regs[-1]["attrs"]["generation"] == old_gen
        assert svc.registry.events("worker_rejoined")
        refreshed = svc.registry.events("worker_refreshed")
        assert refreshed and refreshed[-1]["attrs"]["generation"] == \
            new_gen
        # post-handover the worker carries wire traffic at the new gen
        rpcs0 = gw.stats()["rpcs"]
        s1, i1 = svc.topk_vectors(qv, k=10)
        assert gw.stats()["rpcs"] > rpcs0
        assert np.array_equal(s1, ns) and np.array_equal(i1, ni)
    finally:
        stop.set()
        w.stop()
        gw.close()
        svc.close()


# ---------------------------------------------------------------------------
# cli loadtest --chaos: the availability record
# ---------------------------------------------------------------------------

_OV = {
    "data.num_pages": 200,
    "data.trigram_buckets": 2048,
    "model.embed_dim": 48,
    "model.conv_channels": 96,
    "model.out_dim": 48,
    "train.batch_size": 64,
    "train.steps": 40,
    "train.warmup_steps": 10,
    "train.learning_rate": 2e-3,
    "train.log_every": 1000,
    "eval.embed_batch_size": 100,
    "eval.store_shard_size": 100,
}


@pytest.fixture(scope="module")
def served_wd(tmp_path_factory):
    """A tiny trained model + embedded store so `cli loadtest` can
    restore from the workdir (the chaos record rides the real report
    path, not a stub)."""
    from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore
    from dnn_page_vectors_tpu.train.checkpoint import CheckpointManager
    from dnn_page_vectors_tpu.train.loop import Trainer
    wd = str(tmp_path_factory.mktemp("chaos_loadtest"))
    cfg = get_config("cdssm_toy", _OV)
    trainer = Trainer(cfg, workdir=wd)
    state, _ = trainer.train()
    mgr = CheckpointManager(os.path.join(wd, "ckpt"))
    mgr.save(int(state.step), state, wait=True)
    mgr.close()
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, query_tok=trainer.query_tok)
    store = VectorStore(os.path.join(wd, "store"), dim=cfg.model.out_dim,
                        shard_size=100)
    store.ensure_model_step(int(state.step))
    emb.embed_corpus(trainer.corpus, store)
    return wd


def test_cli_loadtest_chaos_record_shape(served_wd, capsys):
    """`cli loadtest --chaos PLAN` installs the seeded plan after the
    fleet is up and the report carries the pinned `chaos` block: the
    plan echoed, offered/sheds/errors accounting, availability (sheds
    excluded from the denominator), and the injected-fault counters.
    In-process transport crosses no wire, so availability is 1.0 and
    errors 0 — the record SHAPE is the pin; the wire numbers are the
    bench chaos_serve drill's job."""
    from dnn_page_vectors_tpu import cli
    cli.main(["loadtest", "--config", "cdssm_toy", "--workdir", served_wd,
              "--shape", "poisson", "--p99-ms", "500", "--seed", "5",
              "--distinct", "8", "--trial-s", "0.5", "--warmup-s", "0.2",
              "--start-qps", "16", "--iters", "1",
              "--chaos", "wire_send:frame_trunc:5",
              "--set", "obs.window_s=0.5"]
             + [x for key, val in _OV.items()
                for x in ("--set", f"{key}={val}")])
    out = capsys.readouterr().out.strip().splitlines()
    rep = json.loads(out[-1])
    ch = rep["chaos"]
    assert ch["plan"] == "wire_send:frame_trunc:5"
    for key in ("offered", "sheds", "errors", "availability", "injected"):
        assert key in ch, key
    assert ch["errors"] == 0
    assert ch["offered"] > 0
    assert ch["availability"] == 1.0
    assert isinstance(ch["injected"], dict)
