"""Config/tokenizer vocab agreement (VERDICT r1 #3).

The named configs claim real vocab geometries (30,522 WordPiece for
BERT-mini, 100k words for Kim-CNN, 250,112 SentencePiece for mT5). Round 1
silently clamped training to 8,192 pieces / 20k pages, so configs 3-5 did
not train what they claimed. These tests pin the new contract:
`build_tokenizer` returns EXACTLY config.data.vocab_size ids or raises —
for every named config — and a cached vocab is never reused across a
config/corpus change (ADVICE r1: stale tokenizer cache).

Corpora are shrunk via num_pages (generation cost), never via vocab.
"""
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.data.loader import build_corpus, build_tokenizer
from dnn_page_vectors_tpu.data.subword import SubwordTokenizer
from dnn_page_vectors_tpu.data.words import WordTokenizer


def _built_vocab(name, overrides):
    cfg = get_config(name, overrides)
    corpus = build_corpus(cfg)
    q_tok, p_tok = build_tokenizer(cfg, corpus)
    return cfg, q_tok, p_tok


def test_config1_cdssm_trigram_buckets():
    cfg, q, p = _built_vocab("cdssm_toy", {"data.num_pages": 1_000})
    assert p.vocab_size == cfg.data.trigram_buckets + 1  # +1: pad row 0


@pytest.mark.slow
def test_config2_kim_cnn_true_100k_word_vocab():
    cfg, q, p = _built_vocab("kim_cnn_v5e8", {"data.num_pages": 200_000})
    assert p.vocab_size == cfg.data.vocab_size == 100_000


@pytest.mark.slow
def test_config3_bert_true_30522_vocab():
    cfg, q, p = _built_vocab("bert_mini_v5p16", {"data.num_pages": 100_000})
    assert p.vocab_size == cfg.data.vocab_size == 30_522
    # query tower shares the page vocab (two-tower invariant)
    assert q.vocab == p.vocab


def test_config4_hardneg_same_claim_as_config3():
    # config 4 shares config 3's tokenizer family and vocab claim; the
    # builder path is identical, so assert the claim equality instead of
    # re-training another 30,522-piece vocab
    c3 = get_config("bert_mini_v5p16")
    c4 = get_config("hardneg_v5p64")
    assert c4.data.tokenizer == c3.data.tokenizer
    assert c4.data.vocab_size == c3.data.vocab_size


@pytest.mark.slow
def test_config5_mt5_true_250112_vocab():
    cfg, q, p = _built_vocab("mt5_multilingual",
                             {"data.num_pages": 300_000})
    assert p.vocab_size == cfg.data.vocab_size == 250_112
    assert p.style == "sentencepiece"


def test_unreachable_vocab_raises():
    cfg = get_config("bert_mini_v5p16", {"data.num_pages": 50})
    corpus = build_corpus(cfg)
    with pytest.raises(ValueError, match="vocab_size"):
        build_tokenizer(cfg, corpus)


def test_word_vocab_unreachable_raises():
    with pytest.raises(ValueError, match="unique words"):
        WordTokenizer.train(["a b c"], vocab_size=100, strict_vocab=True)


def test_stale_cache_invalidated(tmp_path):
    """Changing data.vocab_size (or the corpus) must rebuild, not silently
    reuse, the cached vocab (ADVICE r1 loader.py:52)."""
    over = {"data.num_pages": 2_000, "data.vocab_size": 512}
    cfg = get_config("bert_mini_v5p16", over)
    corpus = build_corpus(cfg)
    _, p1 = build_tokenizer(cfg, corpus, cache_dir=str(tmp_path))
    assert p1.vocab_size == 512
    # same cache dir, new vocab size -> must NOT reuse the 512 vocab
    cfg2 = get_config("bert_mini_v5p16",
                      {"data.num_pages": 2_000, "data.vocab_size": 640})
    _, p2 = build_tokenizer(cfg2, build_corpus(cfg2),
                            cache_dir=str(tmp_path))
    assert p2.vocab_size == 640
    # unchanged config -> reuses the cache (vector-store reproducibility)
    _, p3 = build_tokenizer(cfg2, build_corpus(cfg2),
                            cache_dir=str(tmp_path))
    assert p3.vocab == p2.vocab


def test_fast_bpe_deterministic_at_scale():
    texts = [f"alpha{i % 97} beta{i % 31} gamma{i % 13}" for i in range(3_000)]
    v1 = SubwordTokenizer.train(texts, vocab_size=160).vocab
    v2 = SubwordTokenizer.train(texts, vocab_size=160).vocab
    assert v1 == v2 and len(v1) == 158  # + 2 reserved ids = 160
