"""Test harness: CPU backend with 8 fake devices (SURVEY.md §5).

Env must be set before jax initialises — this file is imported by pytest
before any test module touches jax. The 8-device CPU mesh is the standard
JAX idiom for testing multi-chip sharding without a pod; the driver's
separate `dryrun_multichip` uses the same mechanism.
"""
import os

# Force CPU: the sandbox exports JAX_PLATFORMS=axon (one real TPU chip) and a
# sitecustomize that imports jax at interpreter start, so plain env edits are
# too late — use config.update before any backend initialises. The test suite
# always wants the 8-fake-device CPU mesh.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 fake CPU devices, got {len(devs)}"
    return devs
