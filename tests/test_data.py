"""Unit tests: toy corpus + tokenizers (SURVEY.md §3 #1-3, #27)."""
import numpy as np

from dnn_page_vectors_tpu.data.toy import ToyCorpus
from dnn_page_vectors_tpu.data.trigram import TrigramTokenizer, fnv1a, word_trigrams
from dnn_page_vectors_tpu.data.words import WordTokenizer
from dnn_page_vectors_tpu.data.subword import SubwordTokenizer


def test_toy_corpus_deterministic():
    c1 = ToyCorpus(num_pages=100, seed=7)
    c2 = ToyCorpus(num_pages=100, seed=7)
    for i in (0, 13, 99):
        assert c1.page_text(i) == c2.page_text(i)
        assert c1.query_text(i) == c2.query_text(i)
    assert c1.page_text(3) != ToyCorpus(num_pages=100, seed=8).page_text(3)


def test_toy_query_page_overlap():
    c = ToyCorpus(num_pages=50, seed=0)
    for i in (0, 17, 42):
        page_words = set(c.page_text(i).split())
        query_words = set(c.query_text(i).split())
        # key words guarantee lexical overlap with the gold page
        assert len(page_words & query_words) >= 2
        # and little overlap with an unrelated page of another topic
        other = set(c.page_text((i + 3) % 50).split())
        assert len(query_words & other) < len(query_words & page_words)


def test_trigram_hash_stable():
    # FNV-1a must be process-stable (vector-store reproducibility)
    assert fnv1a(b"abc") == 0xE71FA2190541574B
    assert word_trigrams("cat") == ["#ca", "cat", "at#"]
    assert word_trigrams("a") == ["#a#"]


def test_trigram_tokenizer_shapes():
    tok = TrigramTokenizer(buckets=1024, max_words=8, k=4)
    out = tok.encode("hello world")
    assert out.shape == (8, 4) and out.dtype == np.int32
    assert out[0, 0] > 0 and out[2].sum() == 0  # 2 words, rest pad
    assert (out >= 0).all() and (out <= 1024).all()
    batch = tok.encode_batch(["a b", "c"])
    assert batch.shape == (2, 8, 4)
    # same word -> same ids regardless of position
    assert (tok.encode("hello x")[0] == tok.encode("y hello")[1]).all()


def test_word_tokenizer():
    texts = ["the cat sat", "the cat ran", "a dog ran"]
    tok = WordTokenizer.train(texts, vocab_size=10, max_words=4)
    a = tok.encode("the cat flew")
    assert a.shape == (4,)
    assert a[0] > 1 and a[1] > 1   # known words
    assert a[2] == 1               # unk
    assert a[3] == 0               # pad
    # determinism across retrains
    tok2 = WordTokenizer.train(texts, vocab_size=10, max_words=4)
    assert tok.vocab == tok2.vocab


def test_subword_tokenizer_styles(tmp_path):
    texts = ["banana bandana cabana"] * 20 + ["cab band ban"] * 10
    for style in ("wordpiece", "sentencepiece"):
        tok = SubwordTokenizer.train(texts, vocab_size=64, style=style,
                                     max_tokens=16)
        out = tok.encode("banana cab")
        assert out.shape == (16,)
        assert out[0] > 1  # known material, no unk at head
        toks = tok.tokens("banana")
        assert toks, toks
        if style == "sentencepiece":
            assert toks[0].startswith("▁")
        # round-trip through save/load
        p = str(tmp_path / f"{style}.json")
        tok.save(p)
        tok2 = SubwordTokenizer.load(p)
        assert (tok2.encode("banana cab") == out).all()


def test_subword_deterministic():
    texts = ["pagino pagina margine"] * 15
    v1 = SubwordTokenizer.train(texts, vocab_size=48).vocab
    v2 = SubwordTokenizer.train(texts, vocab_size=48).vocab
    assert v1 == v2


def test_train_batcher_per_process_slices_cover_global_batch():
    """Multi-host contract (VERDICT r1 #6): P processes each materialize
    only their contiguous slice, and the concatenation over process_index
    reproduces the single-process global batch exactly — same ids, same
    tokens, same order — for several steps and across an epoch boundary."""
    from dnn_page_vectors_tpu.data.loader import TrainBatcher
    corpus = ToyCorpus(num_pages=96, seed=2)
    texts = [corpus.page_text(i) for i in range(96)]
    tok = WordTokenizer.train(texts, vocab_size=500)
    P, B = 4, 32
    glob = iter(TrainBatcher(corpus, tok, tok, batch_size=B, seed=7,
                             process_index=0, process_count=1))
    locals_ = [iter(TrainBatcher(corpus, tok, tok, batch_size=B, seed=7,
                                 process_index=p, process_count=P))
               for p in range(P)]
    for _ in range(7):  # 96/32 = 3 steps/epoch -> crosses epoch boundaries
        want = next(glob)
        parts = [next(it) for it in locals_]
        for key in want:
            got = np.concatenate([part[key] for part in parts], axis=0)
            np.testing.assert_array_equal(got, want[key], err_msg=key)
        assert parts[0]["page"].shape[0] == B // P  # truly a slice


def test_train_batcher_resume_matches_uninterrupted():
    """start_step=k reproduces the tail of an uninterrupted stream (the
    data-order half of checkpoint resume, §5.4)."""
    from dnn_page_vectors_tpu.data.loader import TrainBatcher
    corpus = ToyCorpus(num_pages=64, seed=3)
    texts = [corpus.page_text(i) for i in range(64)]
    tok = WordTokenizer.train(texts, vocab_size=400)
    full = iter(TrainBatcher(corpus, tok, tok, batch_size=16, seed=1))
    for _ in range(5):
        next(full)
    resumed = iter(TrainBatcher(corpus, tok, tok, batch_size=16, seed=1,
                                start_step=5))
    for _ in range(3):
        np.testing.assert_array_equal(next(resumed)["page_id"],
                                      next(full)["page_id"])


def test_synth_jsonl_sharded_generation_matches_single_file(tmp_path):
    """The documented multi-host generation recipe (data/synth.py: each host
    writes its block-aligned [start, hi) range to its own file) must
    reproduce the single-process corpus byte-for-byte when the shards are
    concatenated — the determinism contract cross-host embed slices rely
    on. Also pins the aligned-start guard."""
    import pytest as _pytest

    from dnn_page_vectors_tpu.data.synth import write_synth_jsonl

    full = str(tmp_path / "full.jsonl")
    write_synth_jsonl(full, 2_000, seed=3, block=512)
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    write_synth_jsonl(a, 1_024, seed=3, block=512, start=0)
    write_synth_jsonl(b, 2_000, seed=3, block=512, start=1_024)
    with open(full, "rb") as f:
        want = f.read()
    with open(a, "rb") as fa, open(b, "rb") as fb:
        got = fa.read() + fb.read()
    assert got == want
    with _pytest.raises(ValueError, match="multiple of"):
        write_synth_jsonl(str(tmp_path / "c.jsonl"), 2_000, seed=3,
                          block=512, start=700)
