"""Sequence-packing parity pins (train.pack_pages; data/loader.py
pack_segments, the segment-masked transformer towers, and the flash
kernel's in-VMEM segment compare).

The contract: packing is a LAYOUT change, not a math change — when the
packed pages fit their row, the tokens are byte-identical to the unpacked
batch and the training loss curve matches the unpacked run to float
tolerance; attention and pooling never leak across packed pages.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.data.loader import TrainBatcher, pack_segments
from dnn_page_vectors_tpu.data.toy import ToyCorpus
from dnn_page_vectors_tpu.train.loop import Trainer

pytestmark = pytest.mark.mfu


def _enc(lens, L, base=1):
    """Left-aligned fake token rows with the given non-pad lengths."""
    out = np.zeros((len(lens), L), np.int32)
    for i, n in enumerate(lens):
        out[i, :n] = np.arange(base, base + n) + 100 * i
    return out


def test_pack_segments_tokens_byte_identical():
    enc = _enc([5, 3, 7, 2, 4, 6, 1, 0], L=32)
    rows, seg, pos = pack_segments(enc, pack=4)
    assert rows.shape == seg.shape == pos.shape == (2, 32)
    for r in range(2):
        c = 0
        for s in range(4):
            n = int((enc[r * 4 + s] != 0).sum())
            tokens = rows[r, c:c + n]
            # byte-identical token run, correctly labeled and positioned
            assert (tokens == enc[r * 4 + s, :n]).all()
            assert (seg[r, c:c + n] == s + 1).all()
            assert (pos[r, c:c + n] == np.arange(n)).all()
            c += n
        assert (rows[r, c:] == 0).all() and (seg[r, c:] == 0).all()


def test_pack_segments_waterfill_clips_largest_first():
    # combined 5+14+3+10 = 32 > L=16: waterfilling finds the threshold
    # T=4 (sum(min(len,4))=15), everything above the water line clips to
    # it, pages below keep every token, and the one slack token goes to
    # the LONGEST page — deterministic result [4, 5, 3, 4], exactly
    # filling the row. The longest page loses the most tokens.
    enc = _enc([5, 14, 3, 10], L=16)
    rows, seg, pos = pack_segments(enc, pack=4)
    kept = [int((seg[0] == s + 1).sum()) for s in range(4)]
    assert kept == [4, 5, 3, 4]
    # every clipped run is still a PREFIX of the original tokens
    c = int(kept[0])
    assert (rows[0, c:c + kept[1]] == enc[1, :kept[1]]).all()


def test_pack_segments_rejects_bad_shapes():
    with pytest.raises(ValueError, match="divide"):
        pack_segments(_enc([3, 3, 3], L=16), pack=2)
    with pytest.raises(ValueError, match="trigram"):
        pack_segments(np.zeros((4, 8, 3), np.int32), pack=2)


def _trainer(tmp_path, pack, attention="dense", tag=""):
    cfg = get_config("bert_mini_v5p16", {
        "data.num_pages": 512, "data.vocab_size": 512,
        "data.page_len": 96, "data.query_len": 12,
        "model.num_layers": 2, "model.attention": attention,
        "model.dropout": 0.0,
        "train.batch_size": 32, "train.pack_pages": pack,
        "train.log_every": 1000,
    })
    # pages of ~4 words tokenize well under 96/4 tokens: no truncation,
    # so packed tokens must be byte-identical to the unpacked batch
    corpus = ToyCorpus(num_pages=512, seed=0, page_len=4, query_len=8)
    return Trainer(cfg, corpus=corpus,
                   workdir=str(tmp_path / f"pack{pack}{attention}{tag}"))


def test_packed_batch_matches_unpacked_tokens(tmp_path):
    t1 = _trainer(tmp_path, 1)
    t4 = _trainer(tmp_path, 4)
    b1 = next(iter(t1._make_batcher(0)))
    b4 = next(iter(t4._make_batcher(0)))
    assert (b1["query"] == b4["query"]).all()
    assert (b1["page_id"] == b4["page_id"]).all()
    assert b4["page"].shape[0] == b1["page"].shape[0] // 4
    # page s of packed row r == unpacked page r*4+s, byte for byte
    for r in range(b4["page"].shape[0]):
        for s in range(4):
            n = int((b1["page"][r * 4 + s] != 0).sum())
            run = b4["page"][r][b4["page_seg"][r] == s + 1]
            assert (run == b1["page"][r * 4 + s, :n]).all()


def test_packed_training_matches_unpacked_loss_curve(tmp_path):
    curves = {}
    for pack in (1, 4):
        tr = _trainer(tmp_path, pack)
        state = tr.init_state()
        step = tr.compiled_step(state)
        it = iter(tr.batches())
        rng = tr.base_rng()
        curve = []
        for _ in range(3):
            state, m = step(state, next(it), rng)
            curve.append(float(m["loss"]))
        curves[pack] = curve
    diff = np.abs(np.array(curves[1]) - np.array(curves[4])).max()
    assert diff < 1e-3, curves


def test_packed_encoder_no_cross_page_leak(tmp_path):
    """Changing page B's tokens must not move page A's vector when the two
    are packed into one row — the segment mask is airtight."""
    tr = _trainer(tmp_path, 2, tag="leak")
    state = tr.init_state()
    model = tr.model
    L = tr.cfg.data.page_len
    rng = np.random.default_rng(0)
    a = rng.integers(2, 400, size=8).astype(np.int32)
    b1 = rng.integers(2, 400, size=10).astype(np.int32)
    b2 = rng.integers(2, 400, size=10).astype(np.int32)

    def packed_row(second):
        enc = np.zeros((2, L), np.int32)
        enc[0, :len(a)] = a
        enc[1, :len(second)] = second
        rows, seg, pos = pack_segments(enc, pack=2)
        return (jnp.asarray(rows), jnp.asarray(seg), jnp.asarray(pos))

    def vecs(second):
        rows, seg, pos = packed_row(second)
        return model.apply(state.params, rows, method="encode_page",
                           seg=seg, pos=pos, nseg=2)

    v1 = np.asarray(vecs(b1))
    v2 = np.asarray(vecs(b2))
    assert np.abs(v1[0, 0] - v2[0, 0]).max() < 1e-5   # page A unmoved
    assert np.abs(v1[0, 1] - v2[0, 1]).max() > 1e-3   # page B moved


def test_packed_flash_matches_dense(tmp_path):
    """The flash kernel's in-kernel segment compare == the dense [B,L,L]
    segment mask, through the full packed train step."""
    curves = {}
    for attention in ("dense", "flash"):
        tr = _trainer(tmp_path, 4, attention=attention)
        state = tr.init_state()
        step = tr.compiled_step(state)
        it = iter(tr.batches())
        rng = tr.base_rng()
        curve = []
        for _ in range(2):
            state, m = step(state, next(it), rng)
            curve.append(float(m["loss"]))
        curves[attention] = curve
    diff = np.abs(np.array(curves["dense"]) - np.array(curves["flash"])).max()
    assert diff < 5e-3, curves


def test_packing_rejects_non_transformer_towers(tmp_path):
    cfg = get_config("cdssm_toy", {
        "data.num_pages": 256, "train.batch_size": 32,
        "train.pack_pages": 2})
    corpus = ToyCorpus(num_pages=256, seed=0)
    tr = Trainer(cfg, corpus=corpus, workdir=str(tmp_path))
    with pytest.raises(ValueError, match="transformer"):
        tr._make_batcher(0)


def test_batcher_rejects_misaligned_pack():
    corpus = ToyCorpus(num_pages=64, seed=0)
    with pytest.raises(ValueError, match="pack_pages"):
        TrainBatcher(corpus, None, None, batch_size=30, pack=4,
                     process_index=0, process_count=1)
