"""Distributed tests without a cluster (SURVEY.md §5): on the 8-fake-device
CPU mesh, GSPMD data-parallel training must be numerically equal to
single-device training (the gradient-correctness guarantee torch-DDP gave
the reference, BASELINE.json:5), and sharded bulk-embed must reproduce
single-device vectors. TP (model axis) must compile and match too.
"""
import jax
import numpy as np
import pytest

from dnn_page_vectors_tpu.config import MeshConfig, get_config
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.parallel.mesh import fit_mesh_to_devices, make_mesh
from dnn_page_vectors_tpu.parallel.sharding import param_shardings, spec_for_param
from dnn_page_vectors_tpu.train.loop import Trainer


def _tiny_cfg(mesh_data=1, mesh_model=1, encoder="cdssm"):
    overrides = {
        "data.num_pages": 256,
        "data.trigram_buckets": 2048,
        "data.vocab_size": 512,
        "model.embed_dim": 32,
        "model.conv_channels": 64,
        "model.out_dim": 32,
        "model.dtype": "float32",
        "train.batch_size": 64,
        "train.steps": 4,
        "train.warmup_steps": 2,
        "train.log_every": 4,
        "mesh.data": mesh_data,
        "mesh.model": mesh_model,
    }
    name = {"cdssm": "cdssm_toy", "bert": "bert_mini_v5p16",
            "t5": "mt5_multilingual"}[encoder]
    if encoder in ("bert", "t5"):
        overrides.update({"model.num_layers": 2, "model.model_dim": 32,
                          "model.num_heads": 4, "model.mlp_dim": 64,
                          "model.dropout": 0.0})
    return get_config(name, overrides)


def _run_steps(cfg, tmp, n=4):
    trainer = Trainer(cfg, workdir=str(tmp))
    state, metrics = trainer.train(steps=n)
    flat, _ = jax.tree_util.tree_flatten(
        jax.tree_util.tree_map(np.asarray, state.params))
    return trainer, state, flat, metrics


def test_dp_training_equals_single_device(tmp_path, eight_devices):
    _, _, single, m1 = _run_steps(_tiny_cfg(1), tmp_path / "a")
    _, _, dp8, m8 = _run_steps(_tiny_cfg(8), tmp_path / "b")
    assert len(single) == len(dp8)
    for a, b in zip(single, dp8):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(m1["loss"], m8["loss"], rtol=1e-3)


@pytest.mark.parametrize("encoder", ["bert", "t5"])
@pytest.mark.slow
def test_tp_dp_training_equals_single_device(tmp_path, eight_devices, encoder):
    # SGD for the equality check: adam divides by sqrt(v), which on
    # zero-gradient params amplifies cross-mesh reduction-order noise to
    # full-lr magnitude and makes raw param comparison ill-conditioned.
    # The t5 case covers the TP surface that differs from bert's (no
    # biases, gated wi_0/wi_1 MLP pair, rel-bias table, P(None, "model")
    # embedding) — config 5's production mesh is DP x TP (docs/SCALING.md).
    import dataclasses

    def cfg(d, m):
        c = _tiny_cfg(d, m, encoder)
        return c.replace(train=dataclasses.replace(c.train, optimizer="sgd"))
    _, _, single, _ = _run_steps(cfg(1, 1), tmp_path / "a")
    _, _, tp, _ = _run_steps(cfg(2, 4), tmp_path / "b")
    for a, b in zip(single, tp):
        np.testing.assert_allclose(a, b, rtol=5e-4, atol=5e-5)


def test_tp_rules_hit_transformer_params(eight_devices):
    cfg = _tiny_cfg(2, 4, "bert")
    trainer = Trainer(cfg)
    state = trainer.init_state()
    mesh = trainer.mesh
    shardings = param_shardings(state.params, mesh)
    flat = jax.tree_util.tree_flatten_with_path(shardings)[0]
    model_sharded = [
        "/".join(str(getattr(k, "key", k)) for k in path)
        for path, s in flat if "model" in str(s.spec)]
    # attention qkv/o + both MLP matmuls + tok_embed per tower must be TP
    assert any("attn/wq/kernel" in p for p in model_sharded)
    assert any("wo_mlp/kernel" in p for p in model_sharded)
    assert any("tok_embed" in p for p in model_sharded)
    # and the rules only ever produce valid specs
    assert spec_for_param("params/query_tower/conv/kernel") is not None


def test_sharded_bulk_embed_equals_single_device(tmp_path, eight_devices):
    cfg = _tiny_cfg(1)
    trainer = Trainer(cfg, workdir=str(tmp_path / "t"))
    state = trainer.init_state()

    vecs = {}
    for tag, mesh_cfg in (("single", MeshConfig(1, 1)),
                          ("dp8", MeshConfig(8, 1))):
        mesh = make_mesh(fit_mesh_to_devices(mesh_cfg))
        store = VectorStore(str(tmp_path / f"store_{tag}"),
                            dim=cfg.model.out_dim, shard_size=256)
        emb = BulkEmbedder(cfg, trainer.model, state.params,
                           trainer.page_tok, mesh, trainer.query_tok)
        emb.embed_corpus(trainer.corpus, store, batch_size=64)
        ids, v = store.load_all()
        order = np.argsort(ids)
        vecs[tag] = v[order]
        assert store.num_vectors == cfg.data.num_pages
    np.testing.assert_allclose(vecs["single"].astype(np.float32),
                               vecs["dp8"].astype(np.float32),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("encoder", ["bert", "t5"])
@pytest.mark.slow
def test_ring_sp_training_equals_dense(tmp_path, eight_devices, encoder):
    """Full train steps with ring attention on a (data=2, seq=4) mesh match
    dense attention on a single device — sequence parallelism is exact
    through the whole model + loss + optimizer. The t5 case additionally
    exercises the per-step relative-bias rebuild across the ring."""
    import dataclasses

    def cfg(d, s, attn):
        c = _tiny_cfg(d, 1, encoder)
        c = c.replace(train=dataclasses.replace(c.train, optimizer="sgd"),
                      model=dataclasses.replace(c.model, attention=attn),
                      mesh=dataclasses.replace(c.mesh, data=d, seq=s))
        return c

    _, _, dense, m1 = _run_steps(cfg(1, 1, "dense"), tmp_path / "a")
    _, _, ring, m2 = _run_steps(cfg(2, 4, "ring"), tmp_path / "b")
    for a, b in zip(dense, ring):
        np.testing.assert_allclose(a, b, rtol=5e-4, atol=5e-5)
    np.testing.assert_allclose(m1["loss"], m2["loss"], rtol=1e-3)


def test_fit_mesh_to_devices():
    assert fit_mesh_to_devices(MeshConfig(64, 1)) == MeshConfig(8, 1)
    assert fit_mesh_to_devices(MeshConfig(4, 2)) == MeshConfig(4, 2)
    assert fit_mesh_to_devices(MeshConfig(1, 16)) == MeshConfig(1, 8)
