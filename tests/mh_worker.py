"""Subprocess worker for tests/test_multihost.py — NOT a test module.

Runs the full train -> embed -> eval -> mine pipeline as one process of an
N-process jax.distributed job (N=1 gives the single-process reference run).
The parent test launches N of these with a localhost coordinator and
compares the resulting stores/tables bit-for-bit across process topologies
(VERDICT r3 Missing #1/#5: the per-process data path and the multi-host
inference layer executing with process_count > 1 for real).

Usage: python mh_worker.py PORT NUM_PROCESSES PROCESS_ID WORKDIR
Env:   JAX_PLATFORMS=cpu, XLA_FLAGS=--xla_force_host_platform_device_count=K
"""
import json
import os
import sys


def main() -> None:
    port, nproc, pid, workdir = (sys.argv[1], int(sys.argv[2]),
                                 int(sys.argv[3]), sys.argv[4])
    import jax
    # must beat the axon sitecustomize's platform registration AND run
    # before jax.distributed touches the backend
    jax.config.update("jax_platforms", "cpu")
    if nproc > 1:
        jax.distributed.initialize(
            coordinator_address=f"127.0.0.1:{port}",
            num_processes=nproc, process_id=pid)

    import numpy as np
    from dnn_page_vectors_tpu.config import get_config
    from dnn_page_vectors_tpu.evals.recall import evaluate_recall
    from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore
    from dnn_page_vectors_tpu.mine.ann import mine_hard_negatives
    from dnn_page_vectors_tpu.parallel.multihost import (
        barrier, inference_mesh, process_info)
    from dnn_page_vectors_tpu.train.loop import Trainer

    cfg = get_config("cdssm_toy", {
        "data.num_pages": 64, "data.page_len": 12, "data.query_len": 6,
        "data.trigram_buckets": 512,
        "model.conv_channels": 32, "model.embed_dim": 32, "model.out_dim": 32,
        "mesh.data": 4,
        "train.batch_size": 8, "train.steps": 4, "train.log_every": 4,
        "eval.embed_batch_size": 8, "eval.eval_queries": 64,
    }).replace(workdir=workdir)

    trainer = Trainer(cfg)
    assert trainer.mesh.devices.size == 4, (
        f"expected the 4-device global mesh, got {trainer.mesh.devices.size}")
    state = trainer.init_state()
    state, _ = trainer.train(steps=cfg.train.steps, state=state)

    # Trained params are compared across topologies at float tolerance, NOT
    # bit-for-bit: the cross-process gradient all-reduce (Gloo on CPU, ICI
    # on TPU) sums shards in a different order than the intra-process
    # reduction, so the last ulp legitimately differs (measured ~5e-9
    # relative). Same sum semantically; reduction order is not part of the
    # DP contract.
    leaves = jax.tree_util.tree_leaves(state.params)
    flat = np.concatenate(
        [np.asarray(l, np.float32).ravel() for l in leaves])

    # Multi-host checkpointing (SURVEY.md §5.4 at config-4 scale): ALL
    # processes save collectively into the shared dir, then restore into a
    # fresh state's (global) shardings — the round trip must reproduce the
    # live state bit-for-bit on every process.
    from dnn_page_vectors_tpu.train.checkpoint import CheckpointManager
    mgr = CheckpointManager(os.path.join(workdir, "ckpt"))
    mgr.save(int(state.step), state, wait=True)
    restored = mgr.restore(trainer.init_state())
    mgr.close()
    assert int(restored.step) == int(state.step), (
        f"restored step {int(restored.step)} != {int(state.step)}")
    # bit-for-bit means BYTES (assert_array_equal would let -0.0 == 0.0
    # canonicalization slip through), and the WHOLE state — a resume with
    # dropped/zeroed adam moments must fail here, not in production
    for a, b in zip(jax.tree_util.tree_leaves(restored),
                    jax.tree_util.tree_leaves(state)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes(), (
            "checkpoint round-trip changed state bytes")

    # The INFERENCE layer, by contrast, must be exactly topology-invariant,
    # so its comparison runs from bit-identical params by construction:
    # a fresh seeded init (local compute, no collectives involved).
    embed_state = trainer.init_state(seed=123)

    pi, pc = process_info()
    mesh = inference_mesh(cfg.mesh, trainer.mesh)
    emb = BulkEmbedder(cfg, trainer.model, embed_state.params,
                       trainer.page_tok, mesh, query_tok=trainer.query_tok)
    store_dir = os.path.join(workdir, "store")
    if pi == 0:
        VectorStore(store_dir, dim=cfg.model.out_dim, shard_size=16)
    barrier("store_created")
    store = VectorStore(store_dir, dim=cfg.model.out_dim, shard_size=16,
                        writer_id=(pi if pc > 1 else None))
    emb.embed_corpus(trainer.corpus, store)

    recall, nq = evaluate_recall(emb, trainer.corpus, store, k=4)
    # out_path exercises the writer-slice protocol (VERDICT r4 Weak #4):
    # per-process memmap slices merged by process 0, O(query_block) RAM
    negs = mine_hard_negatives(emb, trainer.corpus, store, num_negatives=3,
                               search_k=8, query_block=16,
                               out_path=os.path.join(workdir,
                                                     "hard_negatives.npy"))
    if pi == 0:
        result = {
            "processes": pc,
            "devices": len(jax.devices()),
            "recall": recall,
            "nq": nq,
            "num_vectors": store.num_vectors,
            "train_params_sum": float(flat.astype(np.float64).sum()),
            "train_params_absmax": float(np.abs(flat).max()),
            "negatives": negs.table.tolist(),
        }
        with open(os.path.join(workdir, "result.json"), "w") as f:
            json.dump(result, f)
    barrier("result_written")


if __name__ == "__main__":
    main()
