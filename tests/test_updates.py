"""Live corpus updates (updates/, docs/UPDATES.md): append-only store
generations with tombstones, byte-deterministic appends, incremental IVF
refresh in O(new shards) with drift-triggered rebuilds, zero-downtime
serving hot-swap under concurrent queries, fault-injection on the new
write paths, and the no-double-assign contract after shard quarantine.

Presence checks query with the STORED vectors themselves (self-similarity
1 under the store's unit-norm invariant), so they pin the update
machinery — are appended rows servable, are tombstoned rows dead — rather
than the tiny test model's generalization to pages it never trained on."""
import json
import os
import shutil
import threading
import time

import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.data.toy import ToyCorpus
from dnn_page_vectors_tpu.evals.recall import recall_vs_exact
from dnn_page_vectors_tpu.index.ivf import IVFIndex
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.serve import SearchService
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.mine.ann import mine_hard_negatives
from dnn_page_vectors_tpu.ops.topk import topk_over_store
from dnn_page_vectors_tpu.train.loop import Trainer
from dnn_page_vectors_tpu.updates import append_corpus
from dnn_page_vectors_tpu.utils import faults

pytestmark = pytest.mark.updates

_OV = {
    "data.num_pages": 300,
    "data.trigram_buckets": 2048,
    "model.embed_dim": 48,
    "model.conv_channels": 96,
    "model.out_dim": 48,
    "train.batch_size": 64,
    "train.steps": 60,
    "train.warmup_steps": 10,
    "train.learning_rate": 2e-3,
    "train.log_every": 1000,
    "eval.embed_batch_size": 100,
    "eval.store_shard_size": 100,   # 3 base shards; appends add gen shards
}


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    """One trained model + embedded 3-shard base store for the module;
    every mutating test works on a private copy."""
    wd = tmp_path_factory.mktemp("updates_env")
    cfg = get_config("cdssm_toy", _OV)
    trainer = Trainer(cfg, workdir=str(wd))
    state, _ = trainer.train()
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, query_tok=trainer.query_tok)
    store = VectorStore(os.path.join(str(wd), "store"),
                        dim=cfg.model.out_dim, shard_size=100)
    store.ensure_model_step(int(state.step))
    emb.embed_corpus(trainer.corpus, store)
    from dnn_page_vectors_tpu.train.checkpoint import CheckpointManager
    mgr = CheckpointManager(os.path.join(str(wd), "ckpt"))
    mgr.save(int(state.step), state, wait=True)
    mgr.close()
    return {"cfg": cfg, "trainer": trainer, "emb": emb, "store": store,
            "wd": str(wd)}


def _grown(corpus: ToyCorpus, num_pages: int) -> ToyCorpus:
    """The same deterministic corpus with more pages: page i's text is a
    pure function of (seed, i), so growth never rewrites history."""
    return ToyCorpus(num_pages=num_pages, seed=corpus.seed,
                     num_topics=corpus.num_topics, page_len=corpus.page_len,
                     query_len=corpus.query_len, languages=corpus.languages)


def _copy_store(env, tmp_path):
    dst = os.path.join(str(tmp_path), "store")
    shutil.copytree(env["store"].directory, dst)
    shutil.rmtree(os.path.join(dst, "ivf"), ignore_errors=True)
    return VectorStore(dst)


def _ivf_cfg(env, **serve_kw):
    import dataclasses
    serve = dataclasses.replace(env["cfg"].serve, index="ivf", **serve_kw)
    return env["cfg"].replace(serve=serve)


def _stored_vecs(store, ids):
    """The live stored vectors for `ids` (fp32, unit-norm)."""
    all_ids, all_vecs = store.load_all()
    lut = {int(i): np.asarray(v, np.float32)
           for i, v in zip(all_ids, all_vecs) if i >= 0}
    return np.stack([lut[i] for i in ids])


def _self_hits(store, mesh, ids, k=10):
    """Exact top-k per id, queried with its OWN stored vector: a live row
    must come back top-1 (self-similarity 1); a tombstoned one must not
    come back at all."""
    _, got = topk_over_store(_stored_vecs(store, ids), store, mesh, k=k)
    return {i: row.tolist() for i, row in zip(ids, got)}


def test_append_covers_new_pages_and_is_byte_deterministic(env, tmp_path):
    """An append embeds only the new id-range into gen-0001, exact search
    serves the appended rows, and two fault-free appends of the same range
    are byte-identical (generation files AND manifest)."""
    emb, trainer = env["emb"], env["trainer"]
    corpus2 = _grown(trainer.corpus, 400)
    stores = []
    for sub in ("a", "b"):
        store = _copy_store(env, tmp_path / sub)
        stats = append_corpus(emb, corpus2, store)
        assert stats["generation"] == 1
        assert stats["appended"] == 100 and stats["tombstoned"] == 0
        assert store.num_vectors == 400 and store.generation == 1
        assert store.next_page_id() == 400
        stores.append(store)
    ga = os.path.join(stores[0].directory, "gen-0001")
    gb = os.path.join(stores[1].directory, "gen-0001")
    names = sorted(os.listdir(ga))
    assert names == sorted(os.listdir(gb)) and "manifest.json" in names
    for n in names:
        with open(os.path.join(ga, n), "rb") as f:
            ba = f.read()
        with open(os.path.join(gb, n), "rb") as f:
            bb = f.read()
        assert ba == bb, f"{n} differs between identical appends"
    # every sampled appended row is servable through the exact sweep
    hits = _self_hits(stores[0], emb.mesh, [310, 350, 399, 5])
    for qi in (310, 350, 399, 5):
        assert hits[qi][0] == qi, f"stored row {qi} not its own top-1"
    # a second append chains gen-0002 past the new cursor
    stats = append_corpus(emb, _grown(trainer.corpus, 450), stores[0])
    assert stats["generation"] == 2 and stats["appended"] == 50
    assert stores[0].num_vectors == 450


def test_tombstone_deletes_and_update_reembeds(env, tmp_path):
    """A tombstoned page vanishes from exact search; an updated page keeps
    serving (exactly once) from its new-generation row."""
    emb, trainer = env["emb"], env["trainer"]
    store = _copy_store(env, tmp_path)
    stats = append_corpus(emb, trainer.corpus, store,
                          tombstone=[7], update_ids=[12])
    assert stats["appended"] == 0 and stats["updated"] == 1
    assert stats["tombstoned"] == 2       # the delete + the update's old row
    assert store.num_vectors == 301       # 300 base + 1 re-embedded row
    # query with page 7's OLD stored vector (pre-tombstone copy): the row
    # itself must be dead — absent even from its own neighborhood
    pristine = VectorStore(env["store"].directory)
    dead_vec = _stored_vecs(pristine, [7])
    _, got = topk_over_store(dead_vec, store, emb.mesh, k=10)
    assert 7 not in got[0].tolist(), "tombstoned row still servable"
    # the updated page serves exactly once, from the new generation
    hits = _self_hits(store, emb.mesh, [12])
    assert hits[12][0] == 12 and hits[12].count(12) == 1
    # masking survives a cold re-open
    _, got2 = topk_over_store(dead_vec, VectorStore(store.directory),
                              emb.mesh, k=10)
    assert 7 not in got2[0].tolist()
    with pytest.raises(ValueError, match="not an existing page"):
        append_corpus(emb, trainer.corpus, store, tombstone=[500])


def test_incremental_ivf_update_is_o_new_shards(env, tmp_path):
    """IVFIndex.update after an append assigns ONLY the new generation's
    shards (info says so), keeps full-probe == exact on the merged corpus,
    and a drift overrun forces a rebuild instead."""
    emb, trainer = env["emb"], env["trainer"]
    store = _copy_store(env, tmp_path)
    IVFIndex.build(store, emb.mesh, nlist=8, iters=3, seed=0)
    corpus2 = _grown(trainer.corpus, 400)
    append_corpus(emb, corpus2, store, tombstone=[5])
    idx, info = IVFIndex.update(store, emb.mesh, rebuild_drift=0.5)
    assert info["action"] == "incremental"
    assert info["new_shards"] == 1 and info["appended_rows"] == 100
    assert idx.index_generation == 1
    assert int(idx.list_sizes.sum()) == 400
    # full probe == exact on the merged corpus, tombstone absent from both
    qv = np.asarray(emb.embed_texts(
        [corpus2.query_text(i) for i in (5, 50, 250, 320, 399)],
        tower="query"), np.float32)
    _, ann_ids, _ = idx.search(qv, k=10, nprobe=8)
    _, exact_ids = topk_over_store(qv, store, emb.mesh, k=10)
    for a, e in zip(ann_ids, exact_ids):
        assert set(a.tolist()) == set(e.tolist())
    # the tombstoned row is dead through the ANN path too (queried with
    # its own old vector, full probe)
    dead_vec = _stored_vecs(VectorStore(env["store"].directory), [5])
    _, ann_dead, _ = idx.search(dead_vec, k=10, nprobe=8)
    assert 5 not in ann_dead[0].tolist()
    # appended rows servable through the index at the default nprobe
    _, ann_new, _ = idx.search(_stored_vecs(store, [320, 399]), k=10,
                               nprobe=env["cfg"].serve.nprobe)
    assert ann_new[0][0] == 320 and ann_new[1][0] == 399
    # recall-vs-exact contract holds at the default nprobe
    r = recall_vs_exact(idx, store, qv, emb.mesh, k=10,
                        nprobe=env["cfg"].serve.nprobe)
    assert r >= 0.95, f"post-append ANN recall {r:.3f} < 0.95"
    # another append pushing drift over a tiny threshold -> full rebuild
    append_corpus(emb, _grown(trainer.corpus, 430), store)
    idx2, info2 = IVFIndex.update(store, emb.mesh, rebuild_drift=0.01)
    assert info2["action"] == "rebuild"
    assert idx2.index_generation == 0
    assert int(idx2.list_sizes.sum()) == 430


def test_refresh_hot_swap_under_concurrent_queries(env, tmp_path):
    """The e2e acceptance run: an IVF service under a concurrent query
    hammer (through the micro-batcher) while append + refresh() swap in a
    new generation — zero exceptions, every observed result set is exactly
    the old view's or the new view's (never a mix), appended pages become
    servable, the tombstoned page disappears, recall@10 vs exact stays
    >= 0.95 on the merged corpus, and the update cost was O(new shards)
    (full_rebuilds == 0)."""
    emb, trainer = env["emb"], env["trainer"]
    store = _copy_store(env, tmp_path)
    IVFIndex.build(store, emb.mesh, seed=0)          # auto nlist (~sqrt N)
    # nprobe 12 of ~17 lists: the toy corpus is tiny, so the recall>=0.95
    # contract needs a wider probe than the production default of 8 —
    # still sublinear, and the drift/O(new shards) accounting is identical
    cfg = _ivf_cfg(env, batch_window_ms=2.0, max_batch=8, nprobe=12)
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    assert svc._index is not None
    svc.start_batcher()
    cand = list(range(0, 300, 13))
    queries = {qi: trainer.corpus.query_text(qi) for qi in cand}
    first = {qi: tuple(r["page_id"] for r in svc.search(queries[qi], k=10))
             for qi in cand}
    # tombstone a page the service demonstrably RETRIEVES for its gold
    # query, so its disappearance is observable service-side
    victims = [qi for qi in cand if qi in first[qi]]
    assert victims, "test model retrieves no gold at all; cannot proceed"
    victim = victims[0]
    qids = [victim] + [qi for qi in cand if qi != victim][:3]
    before = {qi: first[qi] for qi in qids}
    stop = threading.Event()
    errors, observed = [], {qi: set() for qi in qids}

    def hammer(qi):
        while not stop.is_set():
            try:
                observed[qi].add(tuple(
                    r["page_id"] for r in svc.search(queries[qi], k=10)))
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=hammer, args=(qi,))
               for qi in qids for _ in range(2)]
    for t in threads:
        t.start()
    corpus2 = _grown(trainer.corpus, 400)
    append_corpus(emb, corpus2, store, tombstone=[victim])
    info = svc.refresh()
    time.sleep(0.3)                       # let queries land on the new view
    stop.set()
    for t in threads:
        t.join()
    after = {qi: tuple(r["page_id"] for r in svc.search(queries[qi], k=10))
             for qi in qids}
    assert not errors, f"hot-swap raised: {errors[:3]}"
    for qi in qids:
        extra = observed[qi] - {before[qi], after[qi]}
        assert not extra, (f"query {qi} saw a mixed result set during the "
                           f"swap: {extra}")
    # the swap took effect: tombstone out (service-level), appended rows
    # servable (vector-level, through the live service's index)
    assert victim not in after[victim]
    _, ann_new, _ = svc._index.search(
        _stored_vecs(svc.store, [320, 399]), k=10, nprobe=cfg.serve.nprobe)
    assert ann_new[0][0] == 320 and ann_new[1][0] == 399
    # O(new shards): the index was extended, never rebuilt
    assert info["index_update"]["action"] == "incremental"
    assert svc.incremental_updates == 1 and svc.full_rebuilds == 0
    assert svc.ann_fallbacks == 0
    met = svc.metrics()
    assert met["store_generation"] == 1
    assert met["index_generation"] == 1
    assert met["docs_appended"] == 100
    assert met["tombstoned"] == 1
    assert met["refreshes"] == 1
    assert met["incremental_updates"] == 1 and met["full_rebuilds"] == 0
    # recall@10 vs exact >= 0.95 on the merged corpus through the live index
    qv = np.asarray(emb.embed_texts(
        [corpus2.query_text(i) for i in range(0, 400, 13)],
        tower="query"), np.float32)
    r = recall_vs_exact(svc._index, svc.store, qv, emb.mesh, k=10,
                        nprobe=cfg.serve.nprobe)
    assert r >= 0.95, f"post-swap ANN recall {r:.3f} < 0.95"
    svc.close()


def test_torn_generation_manifest_quarantined_keeps_prev_generation(
        env, tmp_path):
    """A seeded fault tears the generation manifest mid-append: readers
    quarantine that generation (counted) and a serving refresh keeps
    answering from the previous one — results identical to pre-append."""
    emb, trainer = env["emb"], env["trainer"]
    store = _copy_store(env, tmp_path)
    svc = SearchService(env["cfg"], emb, trainer.corpus, store,
                        preload_hbm_gb=4.0)
    q = trainer.corpus.query_text(42)
    before = [r["page_id"] for r in svc.search(q, k=10)]
    faults.install(faults.FaultPlan.parse("gen_manifest_file:truncate:0",
                                          seed=3))
    corpus2 = _grown(trainer.corpus, 400)
    append_corpus(emb, corpus2, store, tombstone=[42])   # manifest lands torn
    faults.install(faults.FaultPlan())    # stop injecting, keep counters
    info = svc.refresh()
    assert faults.counters().get("quarantined_generations") == 1
    assert info["store_generation"] == 0 and info["new_docs"] == 0
    assert svc.metrics()["store_generation"] == 0
    assert svc.metrics()["tombstoned"] == 0
    after = [r["page_id"] for r in svc.search(q, k=10)]
    assert after == before                # previous generation still serves
    svc.close()
    # the next append REUSES the quarantined number and serves normally
    store2 = VectorStore(store.directory)
    stats = append_corpus(emb, corpus2, store2)
    assert stats["generation"] == 1 and store2.num_vectors == 400


def test_posting_append_fault_degrades_to_exact_with_counters(env, tmp_path):
    """A persistent injected fault on the posting-append write path makes
    the index update fail: the service keeps serving (exact fallback over
    the NEW generation — appended rows servable), the index manifest
    stays untouched, and the failure surfaces in metrics()."""
    emb, trainer = env["emb"], env["trainer"]
    store = _copy_store(env, tmp_path)
    IVFIndex.build(store, emb.mesh, nlist=8, iters=3, seed=0)
    cfg = _ivf_cfg(env)
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    assert svc._index is not None
    corpus2 = _grown(trainer.corpus, 400)
    append_corpus(emb, corpus2, store)
    faults.install(faults.FaultPlan.parse("index_write:io_error:0:*", seed=0))
    info = svc.refresh()
    faults.install(faults.FaultPlan())
    assert svc._index is None and "index_error" in info
    assert svc.fault_counters.get("serve_index_update_failures") == 1
    met = svc.metrics()
    assert met["store_generation"] == 1   # the STORE swap still happened
    assert met["index_generation"] is None
    assert "serve_index_update_failures" in met["fault_counters"]
    # exact fallback serves the new generation: an appended row queried
    # with its own stored vector comes back top-1, counted as a fallback
    res = svc.search_many(
        [corpus2.query_text(i) for i in (350, 399)], k=10)
    assert all(len(r) == 10 for r in res)
    assert svc.ann_fallbacks >= 2
    hits = _self_hits(svc.store, emb.mesh, [350, 399])
    assert hits[350][0] == 350 and hits[399][0] == 399
    # a later fault-free refresh repairs the index incrementally (the
    # on-disk manifest was never touched by the failed update)
    info2 = svc.refresh()
    assert info2["index_update"]["action"] == "incremental"
    assert svc._index is not None and svc._index.index_generation == 1
    svc.close()


def test_tombstone_aware_restage_policy(env, tmp_path):
    """The restage policy (updates.restage_tombstone_density,
    docs/UPDATES.md): a refresh after a SMALL tombstone burst reuses the
    staged device shards (restage_skipped counted, dead rows masked in
    the id table — the victim never surfaces), while a burst past the
    density threshold forces a compacted restage (restage_forced) whose
    results match a fresh exact service bit for bit."""
    import dataclasses
    emb, trainer = env["emb"], env["trainer"]
    store = _copy_store(env, tmp_path)
    cfg = env["cfg"].replace(updates=dataclasses.replace(
        env["cfg"].updates, restage_tombstone_density=0.05))
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    assert svc.preloaded
    # 1 dead row of 100 in shard 0 (1% <= 5%): reuse with masking
    append_corpus(emb, trainer.corpus, store, tombstone=[7])
    svc.refresh()
    assert svc.restage_skipped >= 1 and svc.restage_forced == 0
    met = svc.metrics()
    assert met["restage_skipped"] == svc.restage_skipped
    # the dead row's device copy was NOT restaged — the id-table masking
    # alone must keep it from ever surfacing, even for its gold query
    res = svc.search(trainer.corpus.query_text(7), k=10)
    assert all(r["page_id"] != 7 for r in res)
    # 10 more dead rows in shard 0 (11% > 5%): forced compacted restage
    append_corpus(emb, trainer.corpus, store,
                  tombstone=list(range(10, 20)))
    svc.refresh()
    assert svc.restage_forced >= 1
    fresh = SearchService(cfg, emb, trainer.corpus,
                          VectorStore(store.directory), preload_hbm_gb=4.0)
    queries = [trainer.corpus.query_text(i) for i in (2, 77, 290)]
    got = svc.search_many(queries, k=10)
    want = fresh.search_many(queries, k=10)
    assert [[r["page_id"] for r in g] for g in got] == \
        [[r["page_id"] for r in w] for w in want]
    svc.close()


def test_quarantine_plus_append_never_double_assigns(env, tmp_path):
    """The no-double-assign contract: a quarantined base shard leaves its
    id-range discoverable (missing_id_ranges), the append cursor skips it,
    and the range comes back through embed resume — never through new
    documents."""
    emb, trainer = env["emb"], env["trainer"]
    store = _copy_store(env, tmp_path)
    victim = os.path.join(store.directory, "shard_00001.vec.npy")
    with open(victim, "r+b") as f:
        f.truncate(os.path.getsize(victim) // 2)
    store = VectorStore(store.directory)          # verify -> quarantine
    assert store.missing_id_ranges() == [(100, 200)]
    assert store.num_vectors == 200
    assert store.next_page_id() == 300            # NOT 300-100
    corpus2 = _grown(trainer.corpus, 350)
    stats = append_corpus(emb, corpus2, store)
    assert stats["id_start"] == 300 and stats["id_end"] == 350
    gen_ids = store.load_ids(
        {s["index"]: s for s in store.shards()}[3])
    assert gen_ids.min() == 300, "append re-issued a quarantined id"
    # the appended shard index also skipped the quarantined one's slot
    assert sorted(s["index"] for s in store.shards()) == [0, 2, 3]
    # embed resume re-embeds exactly the quarantined range
    emb.embed_corpus(trainer.corpus, store)
    assert store.missing_id_ranges() == []
    assert store.num_vectors == 350
    hits = _self_hits(store, emb.mesh, [150, 320])
    assert hits[150][0] == 150 and hits[320][0] == 320


def test_mine_incremental_start_extends_table(env, tmp_path):
    """After an append, mine_hard_negatives(start=N) mines only the new
    queries against the grown store and splices them onto the existing
    table — old rows byte-identical, new rows valid."""
    emb, trainer = env["emb"], env["trainer"]
    store = _copy_store(env, tmp_path)
    out = os.path.join(str(tmp_path), "negs.npy")
    negs = mine_hard_negatives(emb, trainer.corpus, store, num_negatives=4,
                               search_k=20, out_path=out)
    base = np.array(negs.table)
    assert base.shape == (300, 4)
    corpus2 = _grown(trainer.corpus, 380)
    append_corpus(emb, corpus2, store)
    negs2 = mine_hard_negatives(emb, corpus2, store, num_negatives=4,
                                search_k=20, out_path=out, start=300)
    assert negs2.table.shape == (380, 4)
    np.testing.assert_array_equal(np.array(negs2.table[:300]), base)
    fresh = np.array(negs2.table[300:])
    assert (fresh >= 0).all() and (fresh < 380).all()
    gold = np.arange(300, 380)[:, None]
    assert not (fresh == gold).any(), "a gold page leaked into its negatives"
    with pytest.raises(ValueError, match="existing mined table"):
        mine_hard_negatives(emb, corpus2, store, num_negatives=4,
                            search_k=20, out_path=out + ".missing",
                            start=300)


def test_cli_append_refresh_and_index_json(env, tmp_path, capsys):
    """`cli index` reports the k-means++ seeding and imbalance delta;
    `cli append` grows the corpus into a generation and auto-updates the
    index; `cli refresh` is then a no-op; `cli search` serves the
    generational store through the index with the tombstone masked."""
    from dnn_page_vectors_tpu import cli
    wd = os.path.join(str(tmp_path), "wd")
    shutil.copytree(env["wd"], wd)
    base = ["--config", "cdssm_toy", "--workdir", wd] + [
        x for key, val in _OV.items() for x in ("--set", f"{key}={val}")]
    cli.main(["index"] + base + ["--set", "serve.nlist=16"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["kmeans_init"] == "kmeans++"
    assert out["imbalance_init"] >= 1.0 and out["imbalance"] >= 1.0
    assert round(out["imbalance_init"] - out["imbalance"], 4) == \
        out["imbalance_delta"]
    grown = ["--set", "data.num_pages=360"]
    cli.main(["append"] + base + grown + ["--tombstone", "3"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["store_generation"] == 1 and out["appended"] == 60
    assert out["tombstoned"] == 1
    assert out["index_update"]["action"] == "incremental"
    cli.main(["refresh"] + base + grown)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["action"] == "noop" and out["index_generation"] == 1
    assert out["store_generation"] == 1
    # search over the generational store through the index: full result
    # set, and the tombstoned page can never surface
    query = env["trainer"].corpus.query_text(3)
    cli.main(["search", "--query", query, "--nprobe", "8"] + base + grown)
    res = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(res["results"]) == 10
    assert 3 not in [r["page_id"] for r in res["results"]]


@pytest.mark.slow
def test_large_append_drift_rebuild_recall(env, tmp_path):
    """Large-corpus rebuild variant: an append big enough to cross the
    default drift threshold rebuilds the quantizer over the merged corpus
    and full probe stays exact."""
    emb, trainer = env["emb"], env["trainer"]
    store = _copy_store(env, tmp_path)
    IVFIndex.build(store, emb.mesh, nlist=16, iters=4, seed=0)
    corpus2 = _grown(trainer.corpus, 600)         # +100% > rebuild_drift
    append_corpus(emb, corpus2, store)
    idx, info = IVFIndex.update(store, emb.mesh)  # default drift 0.25
    assert info["action"] == "rebuild"
    assert int(idx.list_sizes.sum()) == 600
    qv = np.asarray(emb.embed_texts(
        [corpus2.query_text(i) for i in range(0, 600, 29)],
        tower="query"), np.float32)
    r = recall_vs_exact(idx, store, qv, emb.mesh, k=10, nprobe=idx.nlist)
    assert r == 1.0
