"""SLO harness (dnn_page_vectors_tpu/loadgen/, docs/SERVING.md "SLO
methodology"): seeded arrival processes are deterministic and hit their
nominal rates on a fake clock, the adaptive micro-batch window widens
under synthetic queue pressure and decays when idle (fake clock, no
sleeps), the driver's binary search converges on a stubbed service with a
known latency/load curve, `cli loadtest` emits the pinned JSON report
shape with seed-identical offered-load schedules, and the concurrent
append/refresh mutator variant serves through hot-swaps with
`full_rebuilds == 0`."""
import json
import os
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.loadgen import (
    Mutator, QueryMix, Workload, find_qps_at_p99, make_workload, run_trial,
    snapshot_line)
from dnn_page_vectors_tpu.utils import faults
from dnn_page_vectors_tpu.utils.telemetry import MetricsRegistry

pytestmark = pytest.mark.slo


# ---------------------------------------------------------------------------
# workload models: determinism + nominal rates (no service, no sleeps)
# ---------------------------------------------------------------------------

def test_poisson_schedule_is_seed_deterministic_and_near_nominal_rate():
    a = make_workload("poisson", seed=11, distinct=32)
    b = make_workload("poisson", seed=11, distinct=32)
    s1, s2 = a.schedule(10.0, 200.0), b.schedule(10.0, 200.0)
    assert s1 == s2                       # identical offered-load schedule
    assert Workload.digest(s1) == Workload.digest(s2)
    assert 0.85 * 2000 < len(s1) < 1.15 * 2000
    times = [t for t, _ in s1]
    assert times == sorted(times) and all(0 <= t < 10.0 for t in times)
    # a different seed is a different schedule
    s3 = make_workload("poisson", seed=12, distinct=32).schedule(10.0, 200.0)
    assert Workload.digest(s3) != Workload.digest(s1)
    # and a re-derived RNG per call: the same workload replays itself
    assert a.schedule(10.0, 200.0) == s1


def test_burst_schedule_has_on_off_structure_and_preserved_mean_rate():
    wl = make_workload("burst", seed=5, distinct=16, on_s=0.5, off_s=0.5)
    sched = wl.schedule(6.0, 60.0)
    assert sched == make_workload("burst", seed=5, distinct=16, on_s=0.5,
                                  off_s=0.5).schedule(6.0, 60.0)
    # arrivals land ONLY inside the on-windows of the 1 s period
    assert all((t % 1.0) < 0.5 for t, _ in sched)
    # duty-cycle scaling preserves the MEAN offered rate
    assert 0.75 * 360 < len(sched) < 1.25 * 360


def test_closed_loop_worker_streams_are_seeded_per_worker():
    wl = make_workload("closed", seed=3, distinct=8, think_s=0.01)
    assert wl.think_s == 0.01
    s0 = [r for r, _ in zip(wl.worker_stream(0), range(20))]
    again = [r for r, _ in zip(wl.worker_stream(0), range(20))]
    assert s0 == again                    # same worker, same stream
    s1 = [r for r, _ in zip(wl.worker_stream(1), range(20))]
    assert s0 != s1                       # workers draw distinct streams


def test_query_mix_is_head_skewed_with_mixed_profile():
    rng = np.random.default_rng(0)
    mix = QueryMix(distinct=50, alpha=1.1,
                   profile=((10, None, 0.75), (50, 4, 0.25)))
    reqs = mix.sample(rng, 4000)
    counts = np.bincount([r.query_id for r in reqs], minlength=50)
    assert counts[0] == counts.max()      # rank 0 is the head query
    assert counts[0] > 4 * counts[25:].mean()
    ks = {(r.k, r.nprobe) for r in reqs}
    assert ks == {(10, None), (50, 4)}    # both profile entries drawn
    frac_k50 = sum(r.k == 50 for r in reqs) / len(reqs)
    assert 0.18 < frac_k50 < 0.32
    # alpha=0 degrades to uniform: the head loses its dominance
    uni = QueryMix(distinct=50, alpha=0.0).sample(
        np.random.default_rng(0), 4000)
    ucounts = np.bincount([r.query_id for r in uni], minlength=50)
    assert ucounts[0] < 2.5 * ucounts[25:].mean()


# ---------------------------------------------------------------------------
# adaptive window: the control loop on a fake clock (no sleeps)
# ---------------------------------------------------------------------------

class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += max(0.0, dt)


def test_adaptive_window_widens_under_pressure_and_decays_when_idle():
    from dnn_page_vectors_tpu.infer.serve import AdaptiveWindow
    clock = _FakeClock()
    reg = MetricsRegistry(clock=clock)
    qw = reg.histogram("serve.queue_wait_ms", window_s=10.0)
    gauge = reg.gauge("serve.batch_window_ms")
    changes = []
    ctl = AdaptiveWindow(2.0, 25.0, qw, gauge=gauge,
                         on_change=lambda *a: changes.append(a))
    assert ctl.current_ms == 2.0 and gauge.value == 2.0
    # synthetic queue pressure: waits far above the current window
    for _ in range(8):
        qw.observe(50.0)
    assert ctl.update() == 4.0            # 2 -> 4
    assert ctl.update() == 8.0            # the pressure persists
    assert ctl.update() == 16.0
    assert ctl.update() == 25.0           # capped at batch_window_max_ms
    assert ctl.update() == 25.0
    assert gauge.value == 25.0
    assert all(c[3] == "pressure" for c in changes)
    # a lone caller's wait ~= the window itself: NO change either way
    clock.t = 20.0                        # pressure samples age out
    for _ in range(8):
        qw.observe(25.0)
    assert ctl.update() == 25.0
    # idle: the rolling window empties -> decay back toward the base
    clock.t = 40.0
    assert ctl.update() == 12.5
    assert ctl.update() == 6.25
    assert ctl.update() == 3.125
    assert ctl.update() == 2.0            # floored at the configured base
    assert ctl.update() == 2.0
    assert gauge.value == 2.0
    assert changes[-1][3] == "idle"


# ---------------------------------------------------------------------------
# driver: binary search on a stub with a known latency/load curve
# ---------------------------------------------------------------------------

class _StubService:
    """p99 = base_ms up to knee_qps, then a cubic blow-up — the analytic
    'qps @ p99 < X' is solvable in closed form, so the driver's answer is
    checkable: p99(q) = base * (q/knee)^3 above the knee."""

    def __init__(self, clock, knee_qps=100.0, base_ms=5.0, window_s=10.0):
        self.clock = clock
        self.knee = knee_qps
        self.base = base_ms
        self.window = window_s
        self.registry = MetricsRegistry(clock=clock)
        self.times = deque()
        self.calls = 0

    def search(self, query, k=None, nprobe=None):
        self.calls += 1
        self.times.append(self.clock())
        return []

    def metrics(self):
        now = self.clock()
        while self.times and self.times[0] < now - self.window:
            self.times.popleft()
        rate = len(self.times) / self.window
        p99 = (self.base if rate <= self.knee
               else self.base * (rate / self.knee) ** 3)
        return {"serve_window_qps": round(rate, 3),
                "serve_window_p50_ms": p99 / 2.0,
                "serve_window_p99_ms": p99,
                "serve_window_error_rate": 0.0,
                "serve_window_cache_hit_rate": 0.0,
                "serve_batch_window_ms": 2.0,
                "serve_recompiles": 0}


def test_driver_binary_search_converges_on_known_curve():
    clock = _FakeClock()
    svc = _StubService(clock)             # analytic answer: 200 qps @ 40 ms
    wl = make_workload("poisson", seed=0, distinct=16)
    rep = find_qps_at_p99(svc, wl, [f"q{i}" for i in range(16)],
                          p99_target_ms=40.0, start=25.0, iters=6,
                          duration_s=10.0, warmup_s=0.0, workers=0,
                          clock=clock, sleep=clock.sleep)
    assert 180.0 <= rep["qps_at_p99"] <= 220.0
    assert rep["p99_target_ms"] == 40.0 and rep["shape"] == "poisson"
    assert len(rep["trials"]) >= 5
    # every trial number was read back from the service's registry view
    for tr in rep["trials"]:
        for key in ("offered_qps", "achieved_qps", "p50_ms", "p99_ms",
                    "error_rate", "cache_hit_rate", "met",
                    "schedule_digest", "events"):
            assert key in tr
        assert tr["achieved_qps"] == pytest.approx(tr["offered_qps"],
                                                   rel=0.15)
    met = [tr for tr in rep["trials"] if tr["met"]]
    unmet = [tr for tr in rep["trials"] if not tr["met"]]
    assert met and unmet                  # the search bracketed the cliff
    assert max(t["offered_qps"] for t in met) <= \
        min(t["offered_qps"] for t in unmet)


def test_driver_trial_correlates_lifecycle_events_and_runs_mutator():
    clock = _FakeClock()
    svc = _StubService(clock)
    svc.registry.event("stale", {"before": True})    # pre-trial: excluded
    fired = []

    def _mutate():
        fired.append(clock())
        svc.registry.event("view_swap", {"swap_ms": 1.0})

    wl = make_workload("poisson", seed=1, distinct=4)
    tr = run_trial(svc, wl, 50.0, ["a", "b", "c", "d"], duration_s=10.0,
                   warmup_s=0.0, workers=0, clock=clock, sleep=clock.sleep,
                   mutator=Mutator(_mutate, period_s=2.5))
    assert tr["mutator_calls"] == len(fired) >= 3
    names = [e["event"] for e in tr["events"]]
    assert "view_swap" in names and "stale" not in names
    assert tr["requests_sent"] == svc.calls
    # two identical runs replay the identical offered-load schedule
    svc2 = _StubService(_FakeClock())
    tr2 = run_trial(svc2, make_workload("poisson", seed=1, distinct=4),
                    50.0, ["a", "b", "c", "d"], duration_s=10.0,
                    warmup_s=0.0, workers=0, clock=svc2.clock,
                    sleep=svc2.clock.sleep)
    assert tr2["schedule_digest"] == tr["schedule_digest"]


def test_snapshot_line_is_single_line_json():
    svc = _StubService(_FakeClock())
    line = snapshot_line(svc, {"offered": 10.0})
    assert "\n" not in line
    rec = json.loads(line)
    assert rec["offered"] == 10.0 and "window_qps" in rec


# ---------------------------------------------------------------------------
# end to end on a trained toy store
# ---------------------------------------------------------------------------

_OV = {
    "data.num_pages": 300,
    "data.trigram_buckets": 2048,
    "model.embed_dim": 48,
    "model.conv_channels": 96,
    "model.out_dim": 48,
    "train.batch_size": 64,
    "train.steps": 60,
    "train.warmup_steps": 10,
    "train.learning_rate": 2e-3,
    "train.log_every": 1000,
    "eval.embed_batch_size": 100,
    "eval.store_shard_size": 100,   # 3 shards: exercises the device merge
}


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    """One trained model + embedded 3-shard store, with the checkpoint
    saved so `cli loadtest` can restore it from the same workdir."""
    from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore
    from dnn_page_vectors_tpu.train.checkpoint import CheckpointManager
    from dnn_page_vectors_tpu.train.loop import Trainer
    wd = str(tmp_path_factory.mktemp("loadgen_serve"))
    cfg = get_config("cdssm_toy", _OV)
    trainer = Trainer(cfg, workdir=wd)
    state, _ = trainer.train()
    mgr = CheckpointManager(os.path.join(wd, "ckpt"))
    mgr.save(int(state.step), state, wait=True)
    mgr.close()
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, query_tok=trainer.query_tok)
    store = VectorStore(os.path.join(wd, "store"), dim=cfg.model.out_dim,
                        shard_size=100)
    store.ensure_model_step(int(state.step))
    emb.embed_corpus(trainer.corpus, store)
    return wd, cfg, trainer, emb, store


def _cfg_with(cfg, serve=None, obs=None, updates=None):
    import dataclasses
    out = cfg
    for name, over in (("serve", serve), ("obs", obs), ("updates", updates)):
        if over:
            out = out.replace(**{name: dataclasses.replace(
                getattr(out, name), **over)})
    return out


def test_adaptive_batched_results_equal_sequential(served):
    """The acceptance pin: batched == sequential still holds with
    adaptive batching ON — the window moving under load must never change
    results, only coalescing."""
    from dnn_page_vectors_tpu.infer.serve import SearchService
    _, cfg, trainer, emb, store = served
    acfg = _cfg_with(cfg, serve={"batch_window_adaptive": True,
                                 "batch_window_max_ms": 10.0})
    svc = SearchService(acfg, emb, trainer.corpus, store,
                        preload_hbm_gb=4.0)
    assert svc._window_ctl is not None    # knob actually engaged
    plain = SearchService(cfg, emb, trainer.corpus, store,
                          preload_hbm_gb=4.0)
    assert plain._window_ctl is None      # off by default
    qis = [0, 7, 42, 123, 299, 5, 13, 77, 200, 250, 1, 2, 3, 4, 6, 8]
    queries = [trainer.corpus.query_text(qi) for qi in qis]
    want = plain.search_many(queries, k=10)
    svc.start_batcher()
    try:
        with ThreadPoolExecutor(8) as ex:
            got = list(ex.map(lambda q: svc.search(q, k=10), queries))
    finally:
        svc.close()
    for a, b in zip(got, want):
        assert [r["page_id"] for r in a] == [r["page_id"] for r in b]
        np.testing.assert_allclose([r["score"] for r in a],
                                   [r["score"] for r in b], atol=1e-4)
    # the live window is exposed whichever way it moved
    assert svc.registry.gauge("serve.batch_window_ms").value >= 2.0
    assert svc.metrics()["serve_batch_window_ms"] >= 2.0


def test_recompile_counter_moves_on_new_shapes_only(served):
    from dnn_page_vectors_tpu.infer.serve import SearchService
    _, cfg, trainer, emb, store = served
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    q = trainer.corpus.query_text(9)
    svc.search_many([q], k=10)
    first = svc.recompiles
    assert first >= 2                     # encode + topk compiled
    evs = svc.registry.events("recompile")
    assert len(evs) == first
    assert {e["attrs"]["program"] for e in evs} >= {"encode_query",
                                                    "sharded_topk"}
    assert all("batch" in e["attrs"] for e in evs)
    svc.search_many([trainer.corpus.query_text(10)], k=10)
    assert svc.recompiles == first        # warm shapes: no new compiles
    svc.search_many([q], k=7)             # a NEW k = a new top-k program
    assert svc.recompiles == first + 1
    assert svc.metrics()["serve_recompiles"] == first + 1


def test_cli_loadtest_json_report_shape_and_seed_determinism(served,
                                                             capsys):
    from dnn_page_vectors_tpu import cli
    wd, _, _, _, _ = served

    def _run():
        cli.main(["loadtest", "--config", "cdssm_toy", "--workdir", wd,
                  "--shape", "poisson", "--p99-ms", "0.5", "--seed", "7",
                  "--distinct", "8", "--trial-s", "0.6", "--warmup-s",
                  "0.2", "--start-qps", "32", "--iters", "1",
                  "--partitions", "2", "--replicas", "1",
                  "--set", "obs.window_s=0.6",
                  "--set", "serve.batch_window_adaptive=true"]
                 + [x for key, val in _OV.items()
                    for x in ("--set", f"{key}={val}")])
        out = capsys.readouterr().out.strip().splitlines()
        return json.loads(out[-1])

    rep = _run()
    # the pinned report shape: qps_at_p99 + per-trial registry-read
    # offered/achieved/p50/p99 + correlated lifecycle events
    for key in ("qps_at_p99", "p99_target_ms", "shape", "seed", "trials",
                "events", "store_vectors", "recompiles",
                "batch_window_adaptive", "fault_counters"):
        assert key in rep, key
    assert rep["shape"] == "poisson" and rep["seed"] == 7
    assert rep["p99_target_ms"] == 0.5 and rep["store_vectors"] == 300
    assert rep["batch_window_adaptive"] is True
    # --partitions P: the report carries the partitioned topology +
    # per-partition qps/p99/shed block (docs/SCALING.md)
    assert rep["serve_partitions"] == 2 and rep["serve_replicas"] == 1
    assert len(rep["partitions"]) == 2
    for p in rep["partitions"]:
        for key in ("partition", "qps", "p99_ms", "sheds",
                    "degraded_serves", "replicas"):
            assert key in p, key
    assert len(rep["trials"]) >= 2
    for tr in rep["trials"]:
        for key in ("offered_qps", "achieved_qps", "p50_ms", "p99_ms",
                    "error_rate", "cache_hit_rate", "met", "events",
                    "schedule_digest"):
            assert key in tr, key
        assert tr["errors"] == 0
        assert tr["achieved_qps"] > 0     # real traffic hit the registry
    # an impossible 0.5 ms target: no trial can pass, the search brackets
    # downward deterministically -> the two runs probe the same loads
    assert all(not tr["met"] for tr in rep["trials"])
    rep2 = _run()
    assert [t["schedule_digest"] for t in rep2["trials"]] == \
        [t["schedule_digest"] for t in rep["trials"]]
    assert [t["offered_qps"] for t in rep2["trials"]] == \
        [t["offered_qps"] for t in rep["trials"]]


def test_cli_loadtest_socket_transport(served, capsys):
    """`cli loadtest --transport socket` (docs/SERVING.md "Network front
    end"): the asyncio front end binds, partition workers spawn as REAL
    subprocesses behind the WorkerGateway, the driver's issue path
    crosses the socket, and the report carries the transport block —
    qps@p99 over loopback covers the full network path."""
    from dnn_page_vectors_tpu import cli
    wd, _, _, _, _ = served
    cli.main(["loadtest", "--config", "cdssm_toy", "--workdir", wd,
              "--shape", "poisson", "--p99-ms", "500", "--seed", "3",
              "--distinct", "8", "--trial-s", "0.6", "--warmup-s", "0.2",
              "--start-qps", "16", "--iters", "1",
              "--transport", "socket", "--partitions", "2",
              "--set", "obs.window_s=0.6"]
             + [x for key, val in _OV.items()
                for x in ("--set", f"{key}={val}")])
    out = capsys.readouterr().out.strip().splitlines()
    rep = json.loads(out[-1])
    assert rep["transport"] == "socket"
    assert ":" in rep["listen"]
    assert rep["serve_partitions"] == 2
    # the wire was actually crossed: byte accounting moved, and the
    # worker fleet registered (2 partition-worker subprocesses)
    assert rep["transport_totals"]["wire_bytes"] > 0
    assert rep["transport_totals"]["workers_registered"] == 2
    assert rep["transport_totals"]["rpcs"] > 0
    for tr in rep["trials"]:
        assert tr["errors"] == 0
        assert tr["transport"]["wire_bytes"] > 0


def test_mutator_hot_swap_under_fire_no_full_rebuilds(served, tmp_path):
    """The append/refresh mutator exercises the zero-downtime hot-swap
    path DURING a load trial: incremental index updates only
    (full_rebuilds == 0 pinned), view_swap events correlated into the
    trial record, and zero request errors across the swaps."""
    from dnn_page_vectors_tpu.data.toy import ToyCorpus
    from dnn_page_vectors_tpu.index.ivf import IVFIndex
    from dnn_page_vectors_tpu.infer.serve import SearchService
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore
    from dnn_page_vectors_tpu.updates import append_corpus
    _, cfg, trainer, emb, fstore = served
    # a fresh store + index: appends must not disturb the shared fixture
    dstore = VectorStore(str(tmp_path / "store"), dim=cfg.model.out_dim,
                         shard_size=100)
    dstore.ensure_model_step(fstore.model_step)   # appends check the stamp
    emb.embed_corpus(trainer.corpus, dstore)
    IVFIndex.build(dstore, emb.mesh, seed=0)
    big = ToyCorpus(num_pages=340, seed=trainer.corpus.seed,
                    num_topics=trainer.corpus.num_topics,
                    page_len=trainer.corpus.page_len,
                    query_len=trainer.corpus.query_len,
                    languages=trainer.corpus.languages)
    acfg = _cfg_with(cfg, serve={"index": "ivf"},
                     obs={"window_s": 3.0})
    svc = SearchService(acfg, emb, big, dstore, preload_hbm_gb=4.0)
    assert svc._index is not None
    svc.start_batcher()
    grown = {"n": 300}

    def _mutate():
        grown["n"] += 12                  # ~36/336 appended: under the
        c2 = ToyCorpus(num_pages=grown["n"], seed=big.seed,  # drift trigger
                       num_topics=big.num_topics, page_len=big.page_len,
                       query_len=big.query_len, languages=big.languages)
        append_corpus(emb, c2, dstore)
        svc.refresh()

    wl = make_workload("poisson", seed=2, distinct=16)
    queries = [big.query_text(i) for i in range(16)]
    mut = Mutator(_mutate, period_s=0.9)
    try:
        tr = run_trial(svc, wl, 25.0, queries, duration_s=2.2,
                       warmup_s=0.0, workers=4, mutator=mut)
    finally:
        svc.close()
    assert tr["mutator_calls"] >= 1
    assert not mut.errors, mut.errors
    assert tr["errors"] == 0
    assert tr["full_rebuilds"] == 0       # incremental updates only
    names = [e["event"] for e in tr["events"]]
    assert "view_swap" in names
    assert svc.incremental_updates >= 1
    assert dstore.num_vectors > 300       # the appends really landed