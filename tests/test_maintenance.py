"""Background maintenance (maintenance/, docs/MAINTENANCE.md): online
generation compaction (byte-deterministic fold, id preservation, exact
result parity, crash-mid-swap old-chain serving), off-path IVF rebuilds
hot-swapped under a concurrent query hammer, and multi-writer append
leases (two-writer contention with no double-assign, steal, fail-fast).

Presence checks query with the STORED vectors themselves (self-similarity
1 under the unit-norm invariant), mirroring tests/test_updates.py — they
pin the maintenance machinery, not the tiny model's generalization."""
import json
import os
import shutil
import threading
import time

import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.data.toy import ToyCorpus
from dnn_page_vectors_tpu.evals.recall import recall_vs_exact
from dnn_page_vectors_tpu.index.ivf import IVFIndex
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.serve import SearchService
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.maintenance import (
    AppendLease, LeaseHeld, LeaseLost, MaintenanceService, compact_store,
    purge_stale)
from dnn_page_vectors_tpu.ops.topk import topk_over_store
from dnn_page_vectors_tpu.train.loop import Trainer
from dnn_page_vectors_tpu.updates import append_corpus
from dnn_page_vectors_tpu.utils import faults, telemetry

pytestmark = pytest.mark.maint

_OV = {
    "data.num_pages": 300,
    "data.trigram_buckets": 2048,
    "model.embed_dim": 48,
    "model.conv_channels": 96,
    "model.out_dim": 48,
    "train.batch_size": 64,
    "train.steps": 60,
    "train.warmup_steps": 10,
    "train.learning_rate": 2e-3,
    "train.log_every": 1000,
    "eval.embed_batch_size": 100,
    "eval.store_shard_size": 100,   # 3 base shards; appends add gen shards
    # the two-writer contention test queues writer B on writer A's lease
    # for the WHOLE of A's append — give slow CI headroom over the 5s
    # production default
    "updates.lease_wait_s": 30.0,
}


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    telemetry.reset_default()
    yield
    faults.reset()
    telemetry.reset_default()


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    """One trained model + embedded 3-shard base store for the module;
    every mutating test works on a private copy."""
    wd = tmp_path_factory.mktemp("maint_env")
    cfg = get_config("cdssm_toy", _OV)
    trainer = Trainer(cfg, workdir=str(wd))
    state, _ = trainer.train()
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, query_tok=trainer.query_tok)
    store = VectorStore(os.path.join(str(wd), "store"),
                        dim=cfg.model.out_dim, shard_size=100)
    store.ensure_model_step(int(state.step))
    emb.embed_corpus(trainer.corpus, store)
    from dnn_page_vectors_tpu.train.checkpoint import CheckpointManager
    mgr = CheckpointManager(os.path.join(str(wd), "ckpt"))
    mgr.save(int(state.step), state, wait=True)
    mgr.close()
    return {"cfg": cfg, "trainer": trainer, "emb": emb, "store": store,
            "wd": str(wd)}


def _grown(corpus: ToyCorpus, num_pages: int) -> ToyCorpus:
    return ToyCorpus(num_pages=num_pages, seed=corpus.seed,
                     num_topics=corpus.num_topics, page_len=corpus.page_len,
                     query_len=corpus.query_len, languages=corpus.languages)


def _copy_store(env, tmp_path):
    dst = os.path.join(str(tmp_path), "store")
    shutil.copytree(env["store"].directory, dst)
    shutil.rmtree(os.path.join(dst, "ivf"), ignore_errors=True)
    return VectorStore(dst)


def _cfg(env, **over):
    import dataclasses
    cfg = env["cfg"]
    for section, kw in over.items():
        cfg = cfg.replace(**{section: dataclasses.replace(
            getattr(cfg, section), **kw)})
    return cfg


def _stored_vecs(store, ids):
    all_ids, all_vecs = store.load_all()
    lut = {int(i): np.asarray(v, np.float32)
           for i, v in zip(all_ids, all_vecs) if i >= 0}
    return np.stack([lut[i] for i in ids])


def _self_hits(store, mesh, ids, k=10):
    _, got = topk_over_store(_stored_vecs(store, ids), store, mesh, k=k)
    return {i: row.tolist() for i, row in zip(ids, got)}


def _grow_and_tombstone(env, store, total=450, tombs=(7, 12, 399)):
    """Two generations on top of the base: +100 pages with two deletions,
    then +50 more deleting an appended page — the chain a compaction
    folds."""
    emb, trainer = env["emb"], env["trainer"]
    append_corpus(emb, _grown(trainer.corpus, 400), store,
                  tombstone=[t for t in tombs if t < 300])
    append_corpus(emb, _grown(trainer.corpus, total), store,
                  tombstone=[t for t in tombs if 300 <= t < 400])
    return _grown(trainer.corpus, total)


def test_compaction_is_byte_deterministic_and_preserves_ids(env, tmp_path):
    """Two identical chains compact to byte-identical bases (data files
    AND manifest); live ids are preserved, dead rows dropped, the append
    cursor survives a tombstoned top id, and the next append chains past
    the folded epoch."""
    emb = env["emb"]
    stores = []
    for sub in ("a", "b"):
        store = _copy_store(env, tmp_path / sub)
        _grow_and_tombstone(env, store)
        assert store.generation == 2 and store.num_vectors == 450
        stats = compact_store(store)
        assert stats["action"] == "compacted"
        assert stats["epoch"] == 2 and stats["dead_rows_dropped"] == 3
        assert stats["rows"] == 447 and stats["bytes_reclaimed"] > 0
        stores.append(store)
    da = os.path.join(stores[0].directory, "compact-0002")
    db = os.path.join(stores[1].directory, "compact-0002")
    names = sorted(os.listdir(da))
    assert names == sorted(os.listdir(db)) and names
    for n in names:
        with open(os.path.join(da, n), "rb") as f:
            ba = f.read()
        with open(os.path.join(db, n), "rb") as f:
            bb = f.read()
        assert ba == bb, f"{n} differs between identical compactions"
    with open(os.path.join(stores[0].directory, "manifest.json"), "rb") as f:
        ma = f.read()
    with open(os.path.join(stores[1].directory, "manifest.json"), "rb") as f:
        mb = f.read()
    assert ma == mb, "compacted manifests differ"
    store = stores[0]
    # id preservation: exactly the live set, nothing renamed
    ids, _ = store.load_all()
    live = sorted(int(i) for i in ids if i >= 0)
    assert live == sorted(set(range(450)) - {7, 12, 399})
    assert store.num_vectors == 447
    # dead-byte accounting reset with the fold
    ms = store.maintenance_stats()
    assert ms["tombstone_density"] == 0.0 and ms["dead_rows"] == 0
    assert ms["compacted_through"] == 2
    # the tombstoned TOP id (399) must not be re-issued: cursor pinned
    assert store.next_page_id() == 450
    # sampled live rows still serve as their own top-1; dead rows gone
    hits = _self_hits(store, emb.mesh, [0, 150, 320, 449])
    for qi in (0, 150, 320, 449):
        assert hits[qi][0] == qi
    dead_vec = _stored_vecs(VectorStore(env["store"].directory), [7])
    _, got = topk_over_store(dead_vec, store, emb.mesh, k=10)
    assert 7 not in got[0].tolist()
    # the chain continues PAST the folded epoch: next append is gen 3
    stats = append_corpus(emb, _grown(env["trainer"].corpus, 500), store)
    assert stats["generation"] == 3
    assert os.path.isdir(os.path.join(store.directory, "gen-0003"))
    assert store.num_vectors == 497 and store.generation == 3
    # a cold re-open sees the same world
    cold = VectorStore(store.directory)
    assert cold.generation == 3 and cold.compacted_through == 2
    assert cold.num_vectors == 497


def test_compaction_exact_results_parity(env, tmp_path):
    """Search results over the compacted base are identical to the
    pre-compaction chain (tombstones were already masked at read time —
    compaction only reclaims their bytes), and a base re-embed over a
    compacted store is refused (it would double-assign)."""
    emb, trainer = env["emb"], env["trainer"]
    store = _copy_store(env, tmp_path)
    corpus2 = _grow_and_tombstone(env, store)
    cfg = env["cfg"]
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    queries = [corpus2.query_text(i) for i in range(0, 450, 23)]
    before = [[r["page_id"] for r in res]
              for res in svc.search_many(queries, k=10)]
    stats = compact_store(store)
    info = svc.refresh()
    assert info["store_generation"] == 2       # monotonic across the fold
    after = [[r["page_id"] for r in res]
             for res in svc.search_many(queries, k=10)]
    assert after == before, "compaction changed exact search results"
    # metrics surface the (now clean) dead-byte accounting
    met = svc.metrics()
    assert met["tombstone_density"] == 0.0 and met["dead_rows"] == 0
    assert met["reclaimable_bytes"] == 0
    svc.close()
    # purge reclaims the old chain once the view moved over
    purged = purge_stale(store, stats)
    assert purged["purged_dirs"] >= 2 and purged["purged_files"] >= 3
    assert not os.path.isdir(os.path.join(store.directory, "gen-0001"))
    fresh = SearchService(cfg, emb, trainer.corpus,
                          VectorStore(store.directory), preload_hbm_gb=4.0)
    again = [[r["page_id"] for r in res]
             for res in fresh.search_many(queries, k=10)]
    assert again == before
    fresh.close()
    with pytest.raises(ValueError, match="has been compacted"):
        emb.embed_corpus(trainer.corpus, VectorStore(store.directory))


def test_crash_mid_compaction_keeps_old_chain_byte_identical(env, tmp_path):
    """Seeded faults tear a compaction before and AT the swap: both leave
    the old chain serving byte-identical results, and a later fault-free
    compaction succeeds."""
    emb, trainer = env["emb"], env["trainer"]
    store = _copy_store(env, tmp_path)
    corpus2 = _grow_and_tombstone(env, store)
    cfg = env["cfg"]
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    queries = [corpus2.query_text(i) for i in (3, 77, 320, 449)]
    before = [[r["page_id"] for r in res]
              for res in svc.search_many(queries, k=10)]
    # crash during the data-file writes: the manifest never flipped
    faults.install(faults.FaultPlan.parse("compact_write:io_error:1", seed=0))
    with pytest.raises(IOError):
        compact_store(VectorStore(store.directory))
    # crash AT the swap itself (persistent, so the retry wrapper can't
    # save it): same outcome — the flip is the commit point
    faults.install(faults.FaultPlan.parse("compact_swap_dump:io_error:0:*",
                                          seed=0))
    with pytest.raises(IOError):
        compact_store(VectorStore(store.directory))
    faults.install(faults.FaultPlan())
    cold = VectorStore(store.directory)
    assert cold.compacted_through == 0 and cold.generation == 2
    assert cold.num_vectors == 450
    info = svc.refresh()
    assert info["store_generation"] == 2
    after = [[r["page_id"] for r in res]
             for res in svc.search_many(queries, k=10)]
    assert after == before, "torn compaction changed serving results"
    svc.close()
    # the torn attempt's debris does not block the fault-free retry
    stats = compact_store(VectorStore(store.directory))
    assert stats["action"] == "compacted" and stats["rows"] == 447
    assert VectorStore(store.directory).compacted_through == 2


def test_two_writer_lease_contention_never_double_assigns(env, tmp_path):
    """Two concurrent append_corpus writers on one store: the lease
    serializes the cursor — one appends the range, the other queues and
    finds nothing left (noop), and no page id is ever assigned twice."""
    emb, trainer = env["emb"], env["trainer"]
    store_dir = _copy_store(env, tmp_path).directory
    corpus2 = _grown(trainer.corpus, 400)
    results, errors = [], []
    gate = threading.Barrier(2)

    def _writer(wid):
        try:
            gate.wait()
            store = VectorStore(store_dir)
            results.append(append_corpus(emb, corpus2, store))
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=_writer, args=(w,)) for w in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, f"leased concurrent appends raised: {errors[:2]}"
    appended = sorted(r["appended"] for r in results)
    assert appended == [0, 100], appended   # one wrote, one found a noop
    store = VectorStore(store_dir)
    assert store.generation == 1 and store.num_vectors == 400
    ids, _ = store.load_all()
    live = [int(i) for i in ids if i >= 0]
    assert len(live) == len(set(live)) == 400, "double-assigned page ids"
    evs = telemetry.default_registry().events("lease_acquired")
    assert len(evs) >= 2


def test_lease_fail_fast_steal_and_lost_renew(env, tmp_path):
    """The lease protocol's edges: a held lease fails a zero-wait second
    writer fast; an EXPIRED lease is stolen (event recorded); the original
    holder's renew then reports LeaseLost."""
    store = _copy_store(env, tmp_path)
    a = AppendLease(store, owner="writer-a", ttl_s=0.4, wait_s=0.0).acquire()
    assert a.held and a.stole_from is None
    with pytest.raises(LeaseHeld, match="held by writer-a"):
        AppendLease(store, owner="writer-b", ttl_s=0.4,
                    wait_s=0.0).acquire()
    time.sleep(0.5)                         # writer-a's ttl runs out
    b = AppendLease(store, owner="writer-b", ttl_s=5.0, wait_s=0.0).acquire()
    assert b.held and b.stole_from == "writer-a"
    reg = telemetry.default_registry()
    assert len(reg.events("lease_stolen")) == 1
    with pytest.raises(LeaseLost):
        a.renew()
    b.renew()                               # the live holder renews fine
    b.release()
    assert not os.path.exists(os.path.join(store.directory,
                                           "append.lease.json"))
    # a queued writer acquires as soon as the holder releases
    c = AppendLease(store, owner="writer-c", ttl_s=1.0, wait_s=2.0)
    assert c.acquire().held
    c.release()


def test_background_rebuild_hot_swap_under_query_hammer(env, tmp_path):
    """The off-path rebuild pin (docs/MAINTENANCE.md): a drift overrun
    defers off the refresh() caller (incremental append still lands,
    full_rebuilds stays 0), then the background worker builds the next
    index generation beside the live one and pointer-flips it in while a
    concurrent query hammer observes zero errors and zero mixed result
    sets; full_rebuilds moves exactly once — in the worker."""
    import dataclasses
    emb, trainer = env["emb"], env["trainer"]
    store = _copy_store(env, tmp_path)
    IVFIndex.build(store, emb.mesh, nlist=8, iters=3, seed=0)
    cfg = _cfg(env, serve={"index": "ivf", "nlist": 8, "nprobe": 8,
                           "batch_window_ms": 2.0, "max_batch": 8},
               updates={"rebuild_drift": 0.05})
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    assert svc._index is not None
    maint = svc.start_maintenance(threads=False)
    assert svc._defer_rebuilds
    svc.start_batcher()
    corpus2 = _grown(trainer.corpus, 400)
    append_corpus(emb, corpus2, store)      # 100/400 = 0.25 drift > 0.05
    info = svc.refresh()
    # deferred: the incremental append served the new docs, no inline
    # rebuild ran, and the pending flag is the hand-off to the worker
    assert info["index_update"]["action"] == "incremental"
    assert info["index_update"]["rebuild_pending"] is True
    assert svc.full_rebuilds == 0 and svc.incremental_updates == 1
    assert svc.registry.gauge("serve.index_rebuild_pending").value == 1.0
    qids = [3, 42, 250, 320]
    queries = {qi: corpus2.query_text(qi) for qi in qids}
    before = {qi: tuple(r["page_id"] for r in svc.search(queries[qi], k=10))
              for qi in qids}
    stop = threading.Event()
    errors, observed = [], {qi: set() for qi in qids}

    def hammer(qi):
        while not stop.is_set():
            try:
                observed[qi].add(tuple(
                    r["page_id"] for r in svc.search(queries[qi], k=10)))
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=hammer, args=(qi,))
               for qi in qids for _ in range(2)]
    for t in threads:
        t.start()
    out = maint.run_once()                  # the background rebuild
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join()
    after = {qi: tuple(r["page_id"] for r in svc.search(queries[qi], k=10))
             for qi in qids}
    assert not errors, f"bg rebuild hot-swap raised: {errors[:3]}"
    for qi in qids:
        extra = observed[qi] - {before[qi], after[qi]}
        assert not extra, (f"query {qi} saw a mixed result set during the "
                           f"bg swap: {extra}")
    rb = out["rebuild"]
    assert rb["dirname"] == "ivf-0001" and rb["swap_ms"] >= 0
    # the rebuild happened ONLY in the worker, and the swap took
    assert svc.full_rebuilds == 1
    assert svc.registry.gauge("serve.index_rebuild_pending").value == 0.0
    assert svc.store.index_dirname == "ivf-0001"
    assert svc._index is not None and svc._index.index_generation == 0
    assert len(svc.registry.events("index_rebuild_bg")) == 1
    # recall contract on the merged corpus through the swapped index
    qv = np.asarray(emb.embed_texts(
        [corpus2.query_text(i) for i in range(0, 400, 13)],
        tower="query"), np.float32)
    r = recall_vs_exact(svc._index, svc.store, qv, emb.mesh, k=10, nprobe=8)
    assert r >= 0.95, f"post-bg-rebuild recall {r:.3f} < 0.95"
    # the janitor reclaims the superseded index generation
    out2 = maint.run_once()
    assert out2.get("janitor", {}).get("index_dirs_removed") == 1
    assert not os.path.isdir(os.path.join(store.directory, "ivf"))
    svc.close()


def test_maintenance_service_compaction_end_to_end(env, tmp_path):
    """The compactor pillar through the service: tombstone past the
    threshold, one run_once folds the chain, rebuilds the index over the
    compacted base, hot-swaps the serving view, and purges the old chain
    — results identical throughout, accounting visible in metrics()."""
    emb, trainer = env["emb"], env["trainer"]
    store = _copy_store(env, tmp_path)
    IVFIndex.build(store, emb.mesh, nlist=8, iters=3, seed=0)
    cfg = _cfg(env, serve={"index": "ivf", "nlist": 8, "nprobe": 8},
               maintenance={"compact_tombstone_density": 0.05})
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    maint = svc.start_maintenance(threads=False)
    # 30 dead of 300 = 10% > 5% threshold
    append_corpus(emb, trainer.corpus, store,
                  tombstone=list(range(40, 70)))
    svc.refresh()
    met = svc.metrics()
    assert met["dead_rows"] == 30 and met["tombstone_density"] == 0.1
    assert met["reclaimable_bytes"] > 0
    queries = [trainer.corpus.query_text(i) for i in (2, 99, 222)]
    before = [[r["page_id"] for r in res]
              for res in svc.search_many(queries, k=10)]
    out = maint.run_once()
    comp = out["compaction"]
    assert comp["action"] == "compacted"
    assert comp["dead_rows_dropped"] == 30 and comp["bytes_reclaimed"] > 0
    assert comp["index_rebuild"]["dirname"] == "ivf-0001"
    after = [[r["page_id"] for r in res]
             for res in svc.search_many(queries, k=10)]
    assert after == before
    met = svc.metrics()
    assert met["dead_rows"] == 0 and met["tombstone_density"] == 0.0
    assert met["store_generation"] == 1      # monotonic across the fold
    assert svc.full_rebuilds == 1            # the compaction's bg rebuild
    assert svc.ann_fallbacks == 0
    assert len(svc.registry.events("compaction")) == 1
    # the old chain's bytes are gone (purged after the view swap)
    assert not os.path.isdir(os.path.join(store.directory, "gen-0001"))
    assert not os.path.exists(os.path.join(store.directory,
                                           "shard_00000.vec.npy"))
    # quiescent second pass: nothing to do
    out2 = maint.run_once()
    assert "compaction" not in out2 and "rebuild" not in out2
    # pause/drain API surface
    maint.pause()
    maint.resume()
    assert maint.drain(timeout_s=1.0)
    assert maint.stats()["passes"]["compaction"] >= 1
    svc.close()


def test_maintenance_under_fire_loadgen_pin(env, tmp_path):
    """The end-to-end acceptance pin (docs/MAINTENANCE.md): a seeded
    loadgen trial with the compaction+rebuild mutator active — tombstone
    bursts alternate with full maintenance passes — keeps serving with
    zero errors and a bounded windowed p99 vs the quiescent trial;
    compaction measurably reclaims bytes, every full rebuild happens in
    the background worker (none inline), and post-compaction recall@10
    vs exact holds the 0.95 contract on the merged corpus."""
    from dnn_page_vectors_tpu.loadgen import (Mutator, make_workload,
                                              run_trial)
    emb, trainer = env["emb"], env["trainer"]
    store = _copy_store(env, tmp_path)
    IVFIndex.build(store, emb.mesh, nlist=8, iters=3, seed=0)
    cfg = _cfg(env, serve={"index": "ivf", "nlist": 8, "nprobe": 8,
                           "batch_window_ms": 2.0, "max_batch": 8},
               obs={"window_s": 2.5},
               maintenance={"compact_tombstone_density": 0.02})
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    maint = svc.start_maintenance(threads=False)
    svc.start_batcher()
    queries = [trainer.corpus.query_text(i) for i in range(16)]
    wl = make_workload("poisson", seed=3, distinct=16)
    quiet = run_trial(svc, wl, 25.0, queries, duration_s=2.5,
                      warmup_s=0.0, workers=4)
    assert quiet["errors"] == 0 and quiet["p99_ms"] > 0

    tomb = {"next": 0}

    def _tombstone_refresh():
        ids = list(range(tomb["next"], tomb["next"] + 12))
        tomb["next"] += 12
        append_corpus(emb, trainer.corpus, svc.store, tombstone=ids)
        svc.refresh()

    mut = Mutator(ops=[("tombstone_refresh", _tombstone_refresh),
                       ("maintain", maint.run_once)], period_s=0.8)
    fire = run_trial(svc, wl, 25.0, queries, duration_s=2.5,
                     warmup_s=0.0, workers=4, mutator=mut)
    assert not mut.errors, mut.errors
    assert fire["errors"] == 0
    assert fire["mutator_calls_by_op"]["tombstone_refresh"] >= 1
    assert fire["mutator_calls_by_op"]["maintain"] >= 1
    reg = svc.registry
    # the compactor really fired and reclaimed bytes, under load
    assert len(reg.events("compaction")) >= 1
    reclaimed = reg.counter("maintenance.compact_bytes_reclaimed").value
    assert reclaimed > 0
    assert svc.store.compacted_through >= 1
    # full rebuilds happened ONLY in the background worker: every one is
    # an index_rebuild_bg event, and the inline drift_rebuild path never
    # ran (the deferral gauge mechanism, docs/MAINTENANCE.md)
    assert svc.full_rebuilds == len(reg.events("index_rebuild_bg")) >= 1
    assert len(reg.events("drift_rebuild")) == 0
    # serving stayed within the maintenance SLO envelope of the quiescent
    # trial (25% + a small toy-scale noise floor; bench measures the
    # operator-facing serve_p99_during_compaction_ms on the real store)
    budget = 1.25 * quiet["p99_ms"] + 5.0
    assert fire["p99_ms"] <= budget, (
        f"p99 under maintenance {fire['p99_ms']:.2f} ms vs quiescent "
        f"{quiet['p99_ms']:.2f} ms (budget {budget:.2f} ms)")
    # recall contract through the swapped-in post-compaction index
    assert svc._index is not None and svc.ann_fallbacks == 0
    qv = np.asarray(emb.embed_texts(
        [trainer.corpus.query_text(i) for i in range(0, 300, 11)],
        tower="query"), np.float32)
    r = recall_vs_exact(svc._index, svc.store, qv, emb.mesh, k=10, nprobe=8)
    assert r >= 0.95, f"post-compaction recall {r:.3f} < 0.95"
    svc.close()


def test_cli_maintain_once_json(env, tmp_path, capsys):
    """`cli maintain --once` over a tombstoned store: one JSON line whose
    compaction block reports the fold; a second pass is quiescent."""
    from dnn_page_vectors_tpu import cli
    wd = os.path.join(str(tmp_path), "wd")
    shutil.copytree(env["wd"], wd)
    base = ["--config", "cdssm_toy", "--workdir", wd] + [
        x for key, val in _OV.items() for x in ("--set", f"{key}={val}")]
    low = ["--set", "maintenance.compact_tombstone_density=0.05"]
    cli.main(["append"] + base + ["--tombstone",
                                  ",".join(str(i) for i in range(40, 70))])
    capsys.readouterr()
    cli.main(["maintain", "--once"] + base + low)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["compaction"]["action"] == "compacted"
    assert out["compaction"]["dead_rows_dropped"] == 30
    assert out["compaction"]["bytes_reclaimed"] > 0
    cli.main(["maintain", "--once"] + base + low)
    out2 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "compaction" not in out2
    store = VectorStore(os.path.join(wd, "store"))
    assert store.compacted_through == 1 and store.num_vectors == 270
