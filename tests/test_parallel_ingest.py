"""Parallel host ingestion + overlapped shard writeback (ISSUE 1 tentpole).

Four contracts:

1. The multi-worker producer (data.tokenize_workers) yields batches in
   deterministic order and the embedded store is BYTE-identical to the
   serial path — parallelism must be invisible in the output.
2. A tokenizer-worker exception mid-sweep re-raises consumer-side and
   leaves no shard falsely recorded as complete (resume correctness).
3. A background-writer failure propagates out of embed_corpus instead of
   being swallowed on the writer thread.
4. The pipeline profiler's stage keys land in the metrics log, for both
   the embed sweep and the train loop.
"""
import json
import os

import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.data.loader import (
    TrainBatcher, iter_corpus_batches, ordered_parallel_map)
from dnn_page_vectors_tpu.data.toy import ToyCorpus
from dnn_page_vectors_tpu.data.trigram import TrigramTokenizer
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.train.loop import Trainer
from dnn_page_vectors_tpu.utils.logging import MetricsLogger
from dnn_page_vectors_tpu.utils.profiling import PipelineProfiler

CFG_OVERRIDES = {
    "data.num_pages": 640,
    "data.trigram_buckets": 1024,
    "model.embed_dim": 16,
    "model.conv_channels": 16,
    "model.out_dim": 16,
    "train.batch_size": 32,
    "train.log_every": 1000,
    "eval.embed_batch_size": 64,
    "eval.store_shard_size": 256,
    "mesh.data": 1,
}


def _embedder(trainer, state, cfg):
    return BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                        trainer.mesh, query_tok=trainer.query_tok)


def _embed_store(emb, cfg, corpus, directory, workers, **kw):
    store = VectorStore(directory, dim=cfg.model.out_dim,
                        shard_size=cfg.eval.store_shard_size)
    emb.embed_corpus(corpus, store, workers=workers, **kw)
    return store


def _shard_bytes(store):
    out = {}
    for s in store.shards():
        for key in ("vec", "ids", "scl"):
            if key in s:
                with open(os.path.join(store.directory, s[key]), "rb") as f:
                    out[s[key]] = f.read()
    return out


def test_ordered_parallel_map_order_and_bound():
    seen = []

    def f(x):
        seen.append(x)
        return x * x

    got = list(ordered_parallel_map(f, range(50), workers=4))
    assert got == [x * x for x in range(50)]     # strict output order
    assert sorted(seen) == list(range(50))       # every item ran exactly once


def test_ordered_parallel_map_reraises_at_position():
    def f(x):
        if x == 7:
            raise ValueError("boom at 7")
        return x

    it = ordered_parallel_map(f, range(20), workers=3)
    got = [next(it) for _ in range(7)]
    assert got == list(range(7))                 # everything before the crash
    with pytest.raises(ValueError, match="boom at 7"):
        next(it)


def test_parallel_corpus_batches_match_serial():
    corpus = ToyCorpus(num_pages=200, seed=5)
    tok = TrigramTokenizer(buckets=512, max_words=16, k=4)
    serial = list(iter_corpus_batches(corpus, tok, 32, workers=1))
    para = list(iter_corpus_batches(corpus, tok, 32, workers=4))
    assert len(serial) == len(para) == 7          # 200/32 -> 6 full + padded
    for a, b in zip(serial, para):
        np.testing.assert_array_equal(a["page"], b["page"])
        np.testing.assert_array_equal(a["page_id"], b["page_id"])


def test_parallel_train_batcher_matches_serial():
    corpus = ToyCorpus(num_pages=96, seed=2)
    tok = TrigramTokenizer(buckets=512, max_words=8, k=4)
    serial = iter(TrainBatcher(corpus, tok, tok, batch_size=32, seed=7,
                               workers=1))
    para = iter(TrainBatcher(corpus, tok, tok, batch_size=32, seed=7,
                             workers=3))
    for _ in range(7):   # 3 steps/epoch -> crosses epoch boundaries
        want, got = next(serial), next(para)
        for key in want:
            np.testing.assert_array_equal(got[key], want[key], err_msg=key)


def test_parallel_embed_store_byte_identical(tmp_path):
    cfg = get_config("cdssm_toy", CFG_OVERRIDES)
    trainer = Trainer(cfg, workdir=str(tmp_path))
    state = trainer.init_state()   # random params: equality is what matters
    emb = _embedder(trainer, state, cfg)
    s1 = _embed_store(emb, cfg, trainer.corpus, str(tmp_path / "serial"),
                      workers=1)
    s2 = _embed_store(emb, cfg, trainer.corpus, str(tmp_path / "parallel"),
                      workers=4)
    assert s1.num_vectors == s2.num_vectors == 640
    b1, b2 = _shard_bytes(s1), _shard_bytes(s2)
    assert b1.keys() == b2.keys()
    for name in b1:
        assert b1[name] == b2[name], f"{name} differs serial vs parallel"


class _FailingCorpus:
    """Delegates to a ToyCorpus but raises on reads past `fail_at` — a
    tokenizer worker dying mid-sweep (disk error, bad record...)."""

    def __init__(self, inner, fail_at):
        self._inner = inner
        self.fail_at = fail_at
        self.num_pages = inner.num_pages

    def fingerprint(self):
        return self._inner.fingerprint()

    def page_texts(self, ids):
        if max(int(i) for i in ids) >= self.fail_at:
            raise RuntimeError("injected read failure")
        return [self._inner.page_text(int(i)) for i in ids]

    def page_text(self, i):
        return self.page_texts([i])[0]

    def query_text(self, i):
        return self._inner.query_text(i)


def test_worker_exception_reraises_and_no_false_complete_shard(tmp_path):
    """Contract 2: the failure lands in shard 1 (pages 256..), so shard 0
    may complete but the failing shard — and anything after — must not be
    recorded. A resumed job re-embeds exactly the missing shards."""
    cfg = get_config("cdssm_toy", CFG_OVERRIDES)
    trainer = Trainer(cfg, workdir=str(tmp_path))
    state = trainer.init_state()
    corpus = _FailingCorpus(trainer.corpus, fail_at=400)
    store = VectorStore(str(tmp_path / "store"), dim=cfg.model.out_dim,
                        shard_size=cfg.eval.store_shard_size)
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, query_tok=trainer.query_tok)
    with pytest.raises(RuntimeError):
        emb.embed_corpus(corpus, store, workers=3)
    done = store.completed_shards()
    assert 1 not in done and 2 not in done, done   # failing shard unrecorded
    assert done <= {0}, done
    # resume completes the remaining shards once the corpus heals
    corpus.fail_at = 10**9
    emb.embed_corpus(corpus, store, workers=3)
    assert store.num_vectors == 640


def test_writer_failure_propagates(tmp_path):
    """Contract 3: write_shard raising on the background writer thread must
    fail embed_corpus (join + re-raise), and nothing may be recorded."""
    cfg = get_config("cdssm_toy", CFG_OVERRIDES)
    trainer = Trainer(cfg, workdir=str(tmp_path))
    state = trainer.init_state()
    store = VectorStore(str(tmp_path / "store"), dim=cfg.model.out_dim,
                        shard_size=cfg.eval.store_shard_size)

    def _broken_write(*a, **kw):
        raise OSError("disk full (injected)")

    store.write_shard = _broken_write
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, query_tok=trainer.query_tok)
    # the writer-thread exception surfaces AS ITSELF from embed_corpus —
    # moving writeback off-thread must not change the exception surface
    with pytest.raises(OSError, match="disk full"):
        emb.embed_corpus(trainer.corpus, store, workers=2)
    fresh = VectorStore(str(tmp_path / "store"), dim=cfg.model.out_dim)
    assert fresh.completed_shards() == set()


def test_embed_stage_keys_in_metrics_log(tmp_path):
    """Contract 4a: embed_corpus writes the per-stage breakdown to the
    metrics log (the observability half of the tentpole)."""
    cfg = get_config("cdssm_toy", CFG_OVERRIDES)
    trainer = Trainer(cfg, workdir=str(tmp_path))
    state = trainer.init_state()
    log = MetricsLogger(str(tmp_path), echo=False)
    prof = PipelineProfiler()
    _embed_store(_embedder(trainer, state, cfg), cfg, trainer.corpus,
                 str(tmp_path / "store"), workers=2, log=log, profiler=prof)
    log.close()
    with open(os.path.join(str(tmp_path), "metrics.jsonl")) as f:
        recs = [json.loads(line) for line in f]
    final = [r for r in recs if "bulk_embed_pages" in r]
    assert final, recs
    for key in ("stage_produce_wait_s", "stage_read_s", "stage_tokenize_s",
                "stage_h2d_s", "stage_compute_s", "stage_d2h_s",
                "stage_write_s"):
        assert key in final[-1], (key, sorted(final[-1]))
    # per-shard rate lines still come through (now from the writer thread)
    assert [r for r in recs if "bulk_embed_shard" in r]
    # the caller-supplied profiler saw the same stages
    assert prof.stages().get("write", 0) > 0


def test_train_stage_keys_in_metrics_log(tmp_path):
    """Contract 4b: the train loop logs stage_*_s next to pages/sec."""
    cfg = get_config("cdssm_toy", {**CFG_OVERRIDES, "train.log_every": 2})
    trainer = Trainer(cfg, workdir=str(tmp_path))
    log = MetricsLogger(str(tmp_path), name="train_metrics", echo=False)
    trainer.train(steps=2, log=log)
    log.close()
    with open(os.path.join(str(tmp_path), "train_metrics.jsonl")) as f:
        recs = [json.loads(line) for line in f]
    assert recs
    for key in ("stage_produce_wait_s", "stage_compute_s", "stage_h2d_s"):
        assert key in recs[-1], (key, sorted(recs[-1]))
