"""Observability subsystem (utils/telemetry.py, utils/tracing.py,
docs/OBSERVABILITY.md): typed registry instruments with bounded memory and
rolling windows, request-scoped tracing through the serving path —
including the micro-batcher's thread hop — the slow-query log, Chrome
trace_event export, windowed SLO gauges, and the obs.* knob/doc drift
check."""
import dataclasses
import json
import os
import re
import threading

import pytest

from dnn_page_vectors_tpu.config import ObsConfig, get_config
from dnn_page_vectors_tpu.utils import faults
from dnn_page_vectors_tpu.utils.logging import MetricsLogger
from dnn_page_vectors_tpu.utils.telemetry import (
    MetricsRegistry, Reservoir, default_registry, reset_default)
from dnn_page_vectors_tpu.utils.tracing import NULL_SPAN, Tracer

pytestmark = pytest.mark.obs

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# registry instruments
# ---------------------------------------------------------------------------

class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    c = reg.counter("x.count")
    c.inc()
    c.inc(4)
    assert c.value == 5
    assert reg.counter("x.count") is c          # get-or-create by name
    g = reg.gauge("x.gauge")
    g.set(2.5)
    assert reg.gauge("x.gauge").value == 2.5
    h = reg.histogram("x.hist", window_s=None)
    for v in (1.0, 2.0, 3.0, 4.0):
        h.observe(v)
    assert h.count == 4 and h.mean == 2.5
    assert h.percentile(50) == 2.0              # lower middle, even count
    assert h.percentile(100) == 4.0
    with pytest.raises(TypeError):              # a name is one kind forever
        reg.gauge("x.count")


def test_windowed_counter_rate_rolls_off():
    clock = _FakeClock()
    reg = MetricsRegistry(clock=clock)
    c = reg.counter("qps", window_s=10.0)
    c.inc(20)
    clock.t = 5.0
    c.inc(10)
    assert c.window_count() == 30
    assert c.rate() == pytest.approx(3.0)
    clock.t = 12.0                              # first burst aged out
    assert c.window_count() == 10
    assert c.rate() == pytest.approx(1.0)
    clock.t = 50.0
    assert c.rate() == 0.0
    assert c.value == 30                        # the total never rolls off


def test_windowed_histogram_percentiles_roll_off():
    clock = _FakeClock()
    reg = MetricsRegistry(clock=clock)
    h = reg.histogram("lat", window_s=10.0)
    h.observe(100.0)
    clock.t = 8.0
    h.observe(1.0)
    assert h.window_percentile(99) == 100.0
    clock.t = 15.0                              # the 100ms sample aged out
    assert h.window_percentile(99) == 1.0
    assert h.percentile(99) == 100.0            # since-boot view keeps it


def test_reservoir_is_bounded_with_exact_count_and_mean():
    r = Reservoir(cap=128, seed=0)
    n = 50_000
    for i in range(n):
        r.add(float(i))
    assert r.count == n
    assert len(r._buf) == 128                   # bounded, not 50k
    assert r.sum == pytest.approx(n * (n - 1) / 2)
    # the sampled median of 0..n-1 lands near the true median
    assert 0.2 * n < r.percentile(50) < 0.8 * n


def test_registry_snapshot_is_json_serializable_and_prometheus_exposes():
    reg = MetricsRegistry()
    reg.counter("serve.requests", window_s=10.0).inc(7)
    reg.gauge("serve.degraded").set(0.0)
    reg.histogram("serve.latency_ms").observe(1.5)
    reg.event("view_swap", {"store_generation": 2}, trace_id="t-abc")
    snap = json.loads(json.dumps(reg.snapshot()))     # round-trips
    assert snap["counters"]["serve.requests"]["value"] == 7
    assert "rate_per_s" in snap["counters"]["serve.requests"]
    assert snap["gauges"]["serve.degraded"] == 0.0
    assert snap["histograms"]["serve.latency_ms"]["count"] == 1
    assert snap["events"][0]["event"] == "view_swap"
    assert snap["events"][0]["trace_id"] == "t-abc"
    text = reg.prometheus_text()
    assert "# TYPE serve_requests counter" in text
    assert "serve_requests 7" in text
    assert 'serve_latency_ms{quantile="0.99"}' in text
    assert "serve_latency_ms_count 1" in text


def test_event_ring_is_bounded():
    reg = MetricsRegistry(events=4)
    for i in range(10):
        reg.event("e", {"i": i})
    evs = reg.events("e")
    assert len(evs) == 4 and evs[0]["attrs"]["i"] == 6


def test_fault_counters_mirror_into_default_registry():
    reset_default()
    faults.reset()
    try:
        faults.count("test_mirror_event", 3)
        c = default_registry().counter("fault.test_mirror_event")
        assert c.value == 3
    finally:
        faults.reset()
        reset_default()


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------

def test_span_tree_nesting_and_attrs():
    tr = Tracer()
    with tr.trace("root", k=10) as root:
        with tr.span("a"):
            with tr.span("b") as b:
                b.set_attrs(x=1)
        with tr.span("c"):
            pass
    d = tr.last_trace()
    assert d["name"] == "root" and d["attrs"]["k"] == 10
    assert [c["name"] for c in d["children"]] == ["a", "c"]
    assert d["children"][0]["children"][0]["attrs"]["x"] == 1
    assert d["dur_ms"] >= 0.0
    assert root.names() == ["root", "a", "b", "c"]


def test_disabled_tracer_is_a_null_no_op():
    tr = Tracer(enabled=False)
    with tr.trace("root") as root:
        assert root is NULL_SPAN
        with tr.span("a") as sp:
            assert sp is NULL_SPAN
        root.set_attrs(x=1).child("q", 0.1)     # mutators must not raise
    assert tr.traces() == [] and tr.current() is None


def test_span_survives_thread_hop_via_explicit_handoff():
    """The micro-batcher pattern: capture current() on the caller thread,
    re-activate with use() on the worker thread."""
    tr = Tracer()
    done = threading.Event()

    def worker(ctx):
        with tr.use(ctx):
            with tr.span("worker_stage"):
                pass
        ctx.child("queue_wait", 0.002)
        done.set()

    with tr.trace("request") as root:
        t = threading.Thread(target=worker, args=(tr.current(),))
        t.start()
        done.wait(5)
        t.join(5)
    names = tr.last_trace()
    names = [c["name"] for c in names["children"]]
    assert "worker_stage" in names and "queue_wait" in names


def test_slow_query_log_threshold_semantics():
    never = Tracer(slow_ms=-1)                  # negative disables
    with never.trace("r"):
        pass
    assert never.slow_queries() == []
    every = Tracer(slow_ms=0)                   # 0 captures everything
    with every.trace("r"):
        pass
    assert len(every.slow_queries()) == 1
    high = Tracer(slow_ms=60_000)
    with high.trace("r"):
        pass
    assert high.slow_queries() == []


def test_chrome_trace_export_is_valid_trace_event_json():
    tr = Tracer()
    with tr.trace("root"):
        with tr.span("tokenize"):
            pass
        with tr.span("topk"):
            pass
    out = json.loads(json.dumps(tr.chrome_trace()))
    evs = out["traceEvents"]
    assert len(evs) == 3
    names = {e["name"] for e in evs}
    assert names == {"root", "tokenize", "topk"}
    for e in evs:
        assert e["ph"] == "X"
        assert isinstance(e["ts"], (int, float)) and e["dur"] >= 0
        assert "trace_id" in e["args"]
    root = next(e for e in evs if e["name"] == "root")
    for e in evs:                               # children inside the root
        assert e["ts"] >= root["ts"] - 1e-3
        assert e["ts"] + e["dur"] <= root["ts"] + root["dur"] + 1e-3


# ---------------------------------------------------------------------------
# MetricsLogger re-base (satellite)
# ---------------------------------------------------------------------------

def test_metrics_logger_context_manager_and_post_close_write(tmp_path):
    path = os.path.join(str(tmp_path), "metrics.jsonl")
    with MetricsLogger(str(tmp_path), echo=False) as log:
        log.write({"a": 1})
    assert log.closed
    log.write({"b": 2})                         # tolerated, not written
    log.close()                                 # idempotent
    lines = [json.loads(ln) for ln in open(path)]
    assert len(lines) == 1
    # jsonl shape unchanged: ts + the written keys, nothing else
    assert set(lines[0]) == {"ts", "a"} and lines[0]["a"] == 1


def test_metrics_logger_mirrors_scalars_into_registry(tmp_path):
    reg = MetricsRegistry()
    with MetricsLogger(str(tmp_path), echo=False, registry=reg) as log:
        log.write({"pages_per_sec_per_chip": 123.5, "note": "text",
                   "degraded": False})
    assert reg.gauge("pages_per_sec_per_chip").value == 123.5
    snap = reg.snapshot()
    assert "note" not in snap["gauges"]         # only numeric scalars
    assert "degraded" not in snap["gauges"]     # bools are flags, not gauges


# ---------------------------------------------------------------------------
# obs.* knob / doc drift (satellite)
# ---------------------------------------------------------------------------

def _drift_findings(rule: str):
    """The generalized graftcheck drift rules (docs/ANALYSIS.md) subsume
    the two hand-rolled checks that used to live here; these wrappers
    keep the old test names so history and `-k` habits survive."""
    from dnn_page_vectors_tpu.tools.analyze import analyze
    return analyze(root=_REPO, rules=[rule]).findings


def test_documented_obs_knobs_match_config():
    """Every `obs.*` knob named in docs/OBSERVABILITY.md exists as an
    ObsConfig field, and every field is documented — the knob table and
    the dataclass cannot drift apart silently. (Thin wrapper over the
    `drift-knobs` rule, which now covers EVERY config section.)"""
    findings = _drift_findings("drift-knobs")
    assert not findings, "\n".join(f.human() for f in findings)
    # the wrapped rule really is checking the obs section, not vacuously
    # passing on a renamed dataclass
    assert {f.name for f in dataclasses.fields(ObsConfig)}


def test_emitted_event_names_are_documented():
    """Every lifecycle event name emitted through `registry.event(...)`
    anywhere in the package appears (backticked) in the
    docs/OBSERVABILITY.md event table — a new PR cannot add a silent
    event; conversely every documented name is really emitted somewhere,
    so the table never advertises dead events. (Thin wrapper over the
    `drift-events` rule.)"""
    findings = _drift_findings("drift-events")
    assert not findings, "\n".join(f.human() for f in findings)
    # the scan itself still sees a healthy event population
    doc = open(os.path.join(_REPO, "docs", "OBSERVABILITY.md")).read()
    documented = set(re.findall(r"^\|\s*`([a-z_]+)`", doc, re.M))
    assert len(documented) >= 10, f"event-table drift? {documented}"


def test_obs_config_round_trips_through_overrides():
    cfg = get_config("cdssm_toy", {"obs.slow_ms": "5.5",
                                   "obs.enabled": "false",
                                   "obs.window_s": "3"})
    assert cfg.obs.slow_ms == 5.5
    assert cfg.obs.enabled is False
    assert cfg.obs.window_s == 3.0


# ---------------------------------------------------------------------------
# end to end: the traced serving path on a real toy store
# ---------------------------------------------------------------------------

_OV = {
    "data.num_pages": 300,
    "data.trigram_buckets": 2048,
    "model.embed_dim": 48,
    "model.conv_channels": 96,
    "model.out_dim": 48,
    "train.batch_size": 64,
    "train.steps": 60,
    "train.warmup_steps": 10,
    "train.learning_rate": 2e-3,
    "train.log_every": 1000,
    "eval.embed_batch_size": 100,
    "eval.store_shard_size": 100,   # 3 shards: exercises the device merge
}


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    """One trained model + embedded 3-shard store + IVF index for the
    whole module (training dominates; services stage cheaply per test)."""
    from dnn_page_vectors_tpu.index.ivf import IVFIndex
    from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore
    from dnn_page_vectors_tpu.train.loop import Trainer
    wd = str(tmp_path_factory.mktemp("telemetry_serve"))
    cfg = get_config("cdssm_toy", _OV)
    trainer = Trainer(cfg, workdir=wd)
    state, _ = trainer.train()
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, query_tok=trainer.query_tok)
    store = VectorStore(os.path.join(wd, "store"), dim=cfg.model.out_dim,
                        shard_size=100)
    store.ensure_model_step(int(state.step))
    emb.embed_corpus(trainer.corpus, store)
    IVFIndex.build(store, emb.mesh, seed=0)
    return cfg, trainer, emb, store


def _cfg_with(cfg, obs=None, serve=None):
    out = cfg
    if obs:
        out = out.replace(obs=dataclasses.replace(out.obs, **obs))
    if serve:
        out = out.replace(serve=dataclasses.replace(out.serve, **serve))
    return out


def _svc(served, preload=0.0, obs=None, serve=None):
    from dnn_page_vectors_tpu.infer.serve import SearchService
    cfg, trainer, emb, store = served
    return SearchService(_cfg_with(cfg, obs=obs, serve=serve), emb,
                         trainer.corpus, store, preload_hbm_gb=preload)


def test_traced_search_span_tree_slow_log_and_export(served):
    """THE acceptance pin: a traced search() through the micro-batcher on
    the HBM-resident toy store produces a span tree covering
    queue_wait -> tokenize -> encode -> topk -> merge -> format, the trace
    lands in the slow-query log at obs.slow_ms=0, and the recent-trace
    ring exports as valid Chrome trace_event JSON."""
    _, trainer, _, _ = served
    svc = _svc(served, preload=4.0, obs={"slow_ms": 0.0})
    assert svc.preloaded
    svc.start_batcher()
    try:
        res = svc.search(trainer.corpus.query_text(7), k=5)
    finally:
        svc.close()
    assert res and all("page_id" in r for r in res)
    roots = [t for t in svc.tracer.traces() if t["name"] == "search"]
    assert roots, [t["name"] for t in svc.tracer.traces()]

    def _names(d):
        out = [d["name"]]
        for c in d["children"]:
            out.extend(_names(c))
        return set(out)

    want = {"search", "queue_wait", "tokenize", "encode", "topk", "merge",
            "format"}
    assert want <= _names(roots[-1]), _names(roots[-1])
    # slow_ms=0 captures every request, full tree included
    slow = svc.tracer.slow_queries()
    assert slow and want <= _names(slow[-1])
    # export: valid trace_event JSON, one complete event per span
    chrome = json.loads(json.dumps(svc.tracer.chrome_trace()))
    evs = chrome["traceEvents"]
    assert {e["name"] for e in evs} >= want
    for e in evs:
        assert e["ph"] == "X" and e["dur"] >= 0 and "trace_id" in e["args"]


def test_ann_topk_span_carries_index_attributes(served):
    """With an active IVF index the request's topk span reports the ANN
    cost triple — lists_scanned / gather_bytes / rows_reranked — and the
    registry counters move with it."""
    _, trainer, _, _ = served
    svc = _svc(served, serve={"index": "ivf"})
    assert svc._index is not None
    svc.search_many([trainer.corpus.query_text(3)], k=5)
    trace = svc.tracer.last_trace()
    assert trace["name"] == "search_many"

    def _find(d, name):
        if d["name"] == name:
            return d
        for c in d["children"]:
            hit = _find(c, name)
            if hit:
                return hit
        return None

    topk = _find(trace, "topk")
    assert topk is not None
    assert topk["attrs"]["lists_scanned"] > 0
    assert topk["attrs"]["gather_bytes"] > 0
    assert topk["attrs"]["rows_reranked"] > 0
    assert svc.ann_fallbacks == 0
    assert svc.ann_lists_scanned == topk["attrs"]["lists_scanned"]
    assert svc.registry.counter("serve.ann_gather_bytes").value > 0


def test_windowed_slo_gauges_move_across_bursts(served):
    """Two serve bursts: the windowed qps/p99 gauges change between them
    (the live SLO view tracks traffic), while the since-boot metrics keys
    the bench and dashboards already pin stay present and the snapshot
    stays json-serializable."""
    _, trainer, _, _ = served
    svc = _svc(served)
    queries = [trainer.corpus.query_text(i) for i in range(6)]
    svc.search_many(queries, k=5)
    m1 = svc.metrics()
    assert m1["serve_window_qps"] > 0
    svc.search_many(queries, k=5)
    svc.search_many(queries, k=5)
    m2 = svc.metrics()
    assert m2["serve_window_qps"] > m1["serve_window_qps"]
    assert m2["serve_window_p99_ms"] > 0
    assert m2["serve_window_s"] == svc.cfg.obs.window_s
    # the pre-registry metrics surface is intact
    for key in ("serve_cache_hits", "serve_cache_misses",
                "serve_cache_hit_rate", "store_generation", "refreshes"):
        assert key in m2
    assert any(k.startswith("serve_stage_") and k.endswith("_s")
               for k in m2)
    assert any(k.startswith("serve_stage_") and k.endswith("_n")
               for k in m2)
    # exposition endpoints: JSON snapshot round-trips, Prometheus text
    # exposes the same instruments
    snap = json.loads(json.dumps(svc.metrics_snapshot()))
    assert snap["counters"]["serve.requests"]["value"] == 18
    assert "serve_requests 18" in svc.prometheus_text()


def test_cache_hit_annotation_on_request_trace(served):
    _, trainer, _, _ = served
    svc = _svc(served)
    q = trainer.corpus.query_text(11)
    svc.search_many([q], k=5)
    first = svc.tracer.last_trace()
    assert first["attrs"]["cache_misses"] == 1
    assert any(c["name"] == "encode" for c in first["children"])
    svc.search_many([q], k=5)                   # repeat: embedding cached
    second = svc.tracer.last_trace()
    assert second["attrs"]["cache_hits"] == 1
    assert second["attrs"]["cache_misses"] == 0
    assert not any(c["name"] == "encode" for c in second["children"])
    assert svc.cache_hits == 1 and svc.cache_misses == 1


def test_refresh_emits_view_swap_event(served):
    svc = _svc(served)
    info = svc.refresh()
    evs = svc.registry.events("view_swap")
    assert len(evs) == 1
    assert evs[0]["attrs"]["store_generation"] == info["store_generation"]
    assert svc.registry.gauge("serve.store_generation").value == \
        info["store_generation"]
    assert svc.refreshes == 1


def test_disabled_tracing_serves_identically(served):
    _, trainer, _, _ = served
    on = _svc(served)
    off = _svc(served, obs={"enabled": False})
    q = trainer.corpus.query_text(42)
    want = on.search_many([q], k=5)[0]
    got = off.search_many([q], k=5)[0]
    assert [r["page_id"] for r in got] == [r["page_id"] for r in want]
    assert off.tracer.traces() == [] and off.tracer.slow_queries() == []
    assert off.metrics()["serve_window_qps"] > 0   # metrics still live
