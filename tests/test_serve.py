"""SearchService: the loaded-once serving path must return exactly what the
streaming store search returns (HBM pre-staging is an optimization, not a
different algorithm), and the interactive CLI must answer a stdin stream."""
import io
import json
import os

import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.serve import SearchService
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.train.loop import Trainer

_OV = {
    "data.num_pages": 300,
    "data.trigram_buckets": 2048,
    "model.embed_dim": 48,
    "model.conv_channels": 96,
    "model.out_dim": 48,
    "train.batch_size": 64,
    "train.steps": 60,
    "train.warmup_steps": 10,
    "train.learning_rate": 2e-3,
    "train.log_every": 1000,
    "eval.embed_batch_size": 100,
    "eval.store_shard_size": 100,   # 3 shards: exercises the shard merge
}


def _trained_service(tmp_path, preload_hbm_gb):
    cfg = get_config("cdssm_toy", _OV)
    trainer = Trainer(cfg, workdir=str(tmp_path))
    state, _ = trainer.train()
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, query_tok=trainer.query_tok)
    store = VectorStore(os.path.join(str(tmp_path), "store"),
                        dim=cfg.model.out_dim, shard_size=100)
    emb.embed_corpus(trainer.corpus, store)
    svc = SearchService(cfg, emb, trainer.corpus, store,
                        preload_hbm_gb=preload_hbm_gb)
    return cfg, trainer, svc


def test_preloaded_matches_streaming_and_finds_gold(tmp_path):
    cfg, trainer, svc = _trained_service(tmp_path, preload_hbm_gb=4.0)
    assert svc.preloaded
    # per-query encode is O(1 query) (VERDICT r4 Weak #2): queries pad to a
    # small bucket, NOT the 512-row bulk batch, and warmup measures latency
    assert svc.query_batch <= 8
    svc.warmup(k=10)
    assert svc.warm_latency_ms and svc.warm_latency_ms > 0
    # row-independence: the small-bucket encode returns the same vector as
    # the bulk-batch encode, so serving changes no ranking
    q = trainer.corpus.query_text(0)
    small = svc.embedder.embed_texts([q], tower="query", batch_size=8)
    bulk = svc.embedder.embed_texts([q], tower="query", batch_size=100)
    np.testing.assert_allclose(small, bulk, rtol=2e-4, atol=2e-5)
    # a zero-budget service streams from disk instead
    stream = SearchService(cfg, svc.embedder, trainer.corpus, svc.store,
                           preload_hbm_gb=0.0)
    assert not stream.preloaded
    hits = 0
    for qi in (0, 7, 42, 123, 299):
        query = trainer.corpus.query_text(qi)
        a = svc.search(query, k=10)
        b = stream.search(query, k=10)
        assert [r["page_id"] for r in a] == [r["page_id"] for r in b]
        np.testing.assert_allclose([r["score"] for r in a],
                                   [r["score"] for r in b], atol=1e-4)
        assert all(r["snippet"] for r in a)
        scores = [r["score"] for r in a]
        assert scores == sorted(scores, reverse=True)
        hits += qi in [r["page_id"] for r in a]
    assert hits >= 4, f"only {hits}/5 gold pages retrieved"


@pytest.mark.slow
def test_cli_interactive_search(tmp_path, capsys, monkeypatch):
    from dnn_page_vectors_tpu import cli
    from dnn_page_vectors_tpu.data.loader import build_corpus

    wd = str(tmp_path)
    base = ["--config", "cdssm_toy", "--workdir", wd] + [
        x for key, val in _OV.items() for x in ("--set", f"{key}={val}")]
    cli.main(["train"] + base)
    cli.main(["embed"] + base)
    capsys.readouterr()

    # oracle corpus built EXACTLY as the pipeline builds it (a bare
    # ToyCorpus uses different page/query lengths -> different text)
    corpus = build_corpus(get_config("cdssm_toy", _OV))
    queries = [corpus.query_text(3), corpus.query_text(250)]
    monkeypatch.setattr("sys.stdin",
                        io.StringIO("\n".join(queries) + "\n\n"))
    cli.main(["search", "--interactive"] + base + ["--topk", "10"])
    lines = [json.loads(l) for l in
             capsys.readouterr().out.strip().splitlines()]
    ready, answers = lines[0], lines[1:]
    assert ready["ready"] and ready["vectors"] == 300
    assert ready["latency_ms"] > 0          # measured warm per-query latency
    assert len(answers) == 2
    hits = 0
    for qi, ans in zip((3, 250), answers):
        assert ans["query"] == corpus.query_text(qi)
        assert len(ans["results"]) == 10
        assert all(r["snippet"] for r in ans["results"])
        hits += qi in [r["page_id"] for r in ans["results"]]
    # 60-step model: not every query lands its gold page at k=10, but a
    # majority must (random chance per query ~ 10/300)
    assert hits >= 1, answers


def test_service_all_empty_store_streams_and_returns_nothing(tmp_path):
    """A store holding only zero-count shards (all-padding writes) must not
    trip the preload gate via need == 0 (which would pass even an explicit
    0.0 budget) nor crash the device merge on an empty shard list — it
    serves through the streaming path and returns no results."""
    cfg = get_config("cdssm_toy", _OV)
    trainer = Trainer(cfg, workdir=str(tmp_path))
    state = trainer.init_state()
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, query_tok=trainer.query_tok)
    store = VectorStore(os.path.join(str(tmp_path), "store"),
                        dim=cfg.model.out_dim, shard_size=100)
    store.write_shard(0, np.full(8, -1, np.int64),
                      np.zeros((8, cfg.model.out_dim), np.float32))
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    assert not svc.preloaded
    assert svc.search("anything", k=5) == []


def test_preloaded_int8_store_matches_streaming(tmp_path):
    """The HBM-resident serving path over an INT8 store: codes + scales are
    staged to the device and dequantized inside the top-k matmul; results
    must equal the streaming path on the same store (both int8, so the
    comparison isolates the preload/merge machinery, not quantization)."""
    cfg = get_config("cdssm_toy", dict(_OV, **{"eval.store_dtype": "int8"}))
    trainer = Trainer(cfg, workdir=str(tmp_path))
    state, _ = trainer.train()
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, query_tok=trainer.query_tok)
    store = VectorStore(os.path.join(str(tmp_path), "store"),
                        dim=cfg.model.out_dim, shard_size=100, dtype="int8")
    emb.embed_corpus(trainer.corpus, store)
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    stream = SearchService(cfg, emb, trainer.corpus, store,
                           preload_hbm_gb=0.0)
    assert svc.preloaded and not stream.preloaded
    hits = 0
    for qi in (0, 42, 299):
        q = trainer.corpus.query_text(qi)
        a, b = svc.search(q, k=10), stream.search(q, k=10)
        assert [r["page_id"] for r in a] == [r["page_id"] for r in b]
        np.testing.assert_allclose([r["score"] for r in a],
                                   [r["score"] for r in b], atol=1e-4)
        hits += qi in [r["page_id"] for r in a]
    assert hits >= 2
