"""Real multi-process end-to-end test (VERDICT r3 Missing #1/#5, next-round
item 1).

Spawns 2 actual OS processes that form a jax.distributed job over a
localhost coordinator (2 fake CPU devices each -> a 4-device global mesh),
run train -> embed -> eval -> mine end-to-end, and writes a result summary;
a 1-process reference run (4 fake devices, same global mesh shape) does the
same. The multi-process store must match the single-process store
BIT-FOR-BIT, and recall / mined negatives must be identical — proving the
per-process batch slicing, the process-local inference meshes, the
multi-writer store protocol, and the cross-process reductions all compose
to the exact single-controller semantics.

Two equality regimes, deliberately separated (see mh_worker.py): trained
params compare at float tolerance (the cross-process all-reduce may sum in
a different order than the intra-process one — last-ulp drift is inherent
to DP collectives, not a bug), while the inference layer must be EXACTLY
topology-invariant and is compared bit-for-bit from seeded-identical
params.
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "mh_worker.py")
ELASTIC_WORKER = os.path.join(REPO, "tests", "mh_elastic_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(workdir: str, nproc: int, devices_per_proc: int, argv,
            timeout: int = 600, log_prefix: str = "worker") -> None:
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count="
                        f"{devices_per_proc}")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    procs = []
    for pid in range(nproc):
        out = open(os.path.join(workdir, f"{log_prefix}_{pid}.log"), "w")
        procs.append((subprocess.Popen(
            [sys.executable, argv[0], str(port), str(nproc), str(pid)]
            + argv[1:],
            env=env, stdout=out, stderr=subprocess.STDOUT), out))
    fails = []
    for pid, (p, out) in enumerate(procs):
        try:
            rc = p.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            rc = -9
        out.close()
        if rc != 0:
            with open(os.path.join(workdir,
                                   f"{log_prefix}_{pid}.log")) as f:
                fails.append(f"worker {pid} rc={rc}:\n{f.read()[-4000:]}")
    assert not fails, "\n\n".join(fails)


def _run_job(workdir: str, nproc: int, devices_per_proc: int,
             timeout: int = 600) -> dict:
    _launch(workdir, nproc, devices_per_proc, [WORKER, workdir],
            timeout=timeout)
    with open(os.path.join(workdir, "result.json")) as f:
        return json.load(f)


def _store_files(store_dir: str) -> dict:
    out = {}
    for name in sorted(os.listdir(store_dir)):
        if name.endswith(".npy"):
            with open(os.path.join(store_dir, name), "rb") as f:
                out[name] = f.read()
    return out


@pytest.mark.slow
def test_two_process_pipeline_matches_single_process(tmp_path):
    multi_dir, single_dir = str(tmp_path / "multi"), str(tmp_path / "single")
    os.makedirs(multi_dir), os.makedirs(single_dir)
    multi = _run_job(multi_dir, nproc=2, devices_per_proc=2)
    single = _run_job(single_dir, nproc=1, devices_per_proc=4)

    assert multi["processes"] == 2 and multi["devices"] == 4
    assert single["processes"] == 1 and single["devices"] == 4

    # DP training is topology-invariant up to collective reduction order
    assert multi["train_params_sum"] == pytest.approx(
        single["train_params_sum"], rel=1e-6)
    assert multi["train_params_absmax"] == pytest.approx(
        single["train_params_absmax"], rel=1e-5)

    # the 2-writer store equals the single-controller store bit-for-bit
    m_files = _store_files(os.path.join(multi_dir, "store"))
    s_files = _store_files(os.path.join(single_dir, "store"))
    assert sorted(m_files) == sorted(s_files)
    for name in s_files:
        assert m_files[name] == s_files[name], f"store file {name} differs"
    # after merge_writers no per-writer manifests remain
    assert not [f for f in os.listdir(os.path.join(multi_dir, "store"))
                if f.startswith("manifest.w")]
    with open(os.path.join(multi_dir, "store", "manifest.json")) as f:
        manifest = json.load(f)
    assert [s["index"] for s in manifest["shards"]] == [0, 1, 2, 3]

    assert multi["num_vectors"] == single["num_vectors"] == 64
    assert multi["recall"] == pytest.approx(single["recall"])
    assert np.array_equal(np.asarray(multi["negatives"]),
                          np.asarray(single["negatives"]))


@pytest.mark.slow
def test_elastic_restore_across_process_counts(tmp_path):
    """VERDICT r4 Missing #3, the process-count half: a checkpoint saved by
    a 1-process job restores into a 2-process jax.distributed job (same
    4-device global mesh) and training continues — and the reverse. Both
    elastic runs must match an uninterrupted 1-process run at the
    established DP tolerance (reduction order differs across process
    topologies; tests/mh_worker.py docs)."""

    def elastic(tag, save_np, save_dpp, resume_np, resume_dpp):
        wd = str(tmp_path / tag)
        os.makedirs(wd)
        _launch(wd, save_np, save_dpp,
                [ELASTIC_WORKER, wd, "save", "4"], log_prefix="save")
        _launch(wd, resume_np, resume_dpp,
                [ELASTIC_WORKER, wd, "resume", "4"], log_prefix="resume")
        return np.load(os.path.join(wd, "params_after_resume.npy"))

    ref_dir = str(tmp_path / "ref")
    os.makedirs(ref_dir)
    _launch(ref_dir, 1, 4, [ELASTIC_WORKER, ref_dir, "save", "8"],
            log_prefix="ref")
    ref = np.load(os.path.join(ref_dir, "params_after_save.npy"))

    up = elastic("up", save_np=1, save_dpp=4, resume_np=2, resume_dpp=2)
    np.testing.assert_allclose(up, ref, rtol=2e-4, atol=2e-5)

    down = elastic("down", save_np=2, save_dpp=2, resume_np=1, resume_dpp=4)
    np.testing.assert_allclose(down, ref, rtol=2e-4, atol=2e-5)
