"""Flash-attention kernel vs the reference implementation (interpret mode on
CPU), including padding masks, T5 bias, non-block-multiple lengths, grads."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_page_vectors_tpu.ops.flash_attention import (
    flash_attention, reference_attention)


def _mk(B=2, H=2, L=48, S=48, Dh=16, seed=0, pad_tail=5):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, H, L, Dh)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, S, Dh)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, S, Dh)), jnp.float32)
    mask = np.ones((B, S), bool)
    if pad_tail:
        mask[:, -pad_tail:] = False
    return q, k, v, jnp.asarray(mask)


@pytest.mark.parametrize("block_q,block_kv", [(16, 16), (32, 16), (128, 128)])
def test_matches_reference(block_q, block_kv):
    q, k, v, mask = _mk()
    want = reference_attention(q, k, v, mask)
    got = flash_attention(q, k, v, mask, None, block_q, block_kv)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_with_t5_bias():
    q, k, v, mask = _mk(H=3, L=32, S=32, pad_tail=3)
    rng = np.random.default_rng(1)
    bias = jnp.asarray(rng.normal(size=(3, 32, 32)), jnp.float32)
    want = reference_attention(q, k, v, mask, bias)
    got = flash_attention(q, k, v, mask, bias, 16, 16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_non_multiple_lengths():
    # L=37, S=53 with blocks of 16: exercises the pad/slice path
    q, k, v, mask = _mk(L=37, S=53, pad_tail=7)
    want = reference_attention(q, k, v, mask)
    got = flash_attention(q, k, v, mask, None, 16, 16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_bf16_inputs():
    q, k, v, mask = _mk()
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    want = reference_attention(qb, kb, vb, mask)
    got = flash_attention(qb, kb, vb, mask, None, 16, 16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)


def test_gradients_match_reference():
    q, k, v, mask = _mk(B=1, H=2, L=32, S=32, pad_tail=4)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, mask, None, 16, 16).sum()

    def loss_ref(q, k, v):
        return reference_attention(q, k, v, mask).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_gradients_non_multiple_lengths():
    # exercises the backward's pad/slice path (L=37, S=53, blocks of 16)
    q, k, v, mask = _mk(B=1, H=2, L=37, S=53, pad_tail=6)
    g = jnp.asarray(np.random.default_rng(3).normal(size=(1, 2, 37, 16)),
                    jnp.float32)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, mask, None, 16, 16) * g).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, mask) * g).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_gradients_with_t5_bias():
    # Pallas biased backward (dq/dk/dv kernels take the bias; dbias comes
    # from the batch-innermost accumulating kernel): grads incl. dbias must
    # match the reference VJP (VERDICT r3 Missing #3 done-criterion).
    q, k, v, mask = _mk(B=3, H=2, L=32, S=32, pad_tail=4)
    bias = jnp.asarray(np.random.default_rng(2).normal(size=(2, 32, 32)),
                       jnp.float32)

    def loss_flash(q, k, v, b):
        return flash_attention(q, k, v, mask, b, 16, 16).sum()

    def loss_ref(q, k, v, b):
        return reference_attention(q, k, v, mask, b).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2, 3))(q, k, v, bias)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(q, k, v, bias)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_gradients_with_t5_bias_non_multiple_lengths():
    # biased backward through the pad/slice path: padded KV columns must
    # not leak into dbias, padded Q rows must be sliced off
    q, k, v, mask = _mk(B=2, H=2, L=37, S=53, pad_tail=6)
    bias = jnp.asarray(np.random.default_rng(5).normal(size=(2, 37, 53)),
                       jnp.float32)

    def loss_flash(q, k, v, b):
        return flash_attention(q, k, v, mask, b, 16, 16).sum()

    def loss_ref(q, k, v, b):
        return reference_attention(q, k, v, mask, b).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2, 3))(q, k, v, bias)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(q, k, v, bias)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_kv_bound_raises_directed_error():
    """Over-bound KV lengths must raise the directed ValueError pointing at
    ring attention, not an opaque Mosaic allocation failure (ADVICE r3).
    interpret=False makes the guard active; the raise happens before any
    compilation, so this runs fine on CPU."""
    q, k, v, mask = _mk(B=1, H=1, L=16, S=8_200, Dh=8, pad_tail=0)
    with pytest.raises(ValueError, match="ring"):
        flash_attention(q, k, v, mask, None, 128, 128, interpret=False)
    # the biased bound is tighter (bias + dbias tiles share VMEM)
    q, k, v, mask = _mk(B=1, H=1, L=16, S=4_200, Dh=8, pad_tail=0)
    bias = jnp.zeros((1, 16, 4_200), jnp.float32)
    with pytest.raises(ValueError, match="with bias"):
        flash_attention(q, k, v, mask, bias, 128, 128, interpret=False)


def test_biased_backward_never_materializes_scores():
    """The T5-bias train path is now kernel-only: no [B,H,L,S] tensor in the
    compiled grad program (the old fallback re-materialised it)."""
    import re

    B, H, L, S = 2, 2, 64, 64
    q, k, v, mask = _mk(B=B, H=H, L=L, S=S, pad_tail=4)
    bias = jnp.asarray(np.random.default_rng(7).normal(size=(H, L, S)),
                       jnp.float32)

    def loss_flash(q, k, v, b):
        return flash_attention(q, k, v, mask, b, 16, 16).sum()

    hlo = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2, 3))).lower(
        q, k, v, bias).compile().as_text()
    # anchored on the literal brackets: the unanchored form matched
    # substrings of larger shapes, e.g. '2,2,64,64' in f32[12,2,64,64]
    assert not re.compile(rf"\[{B},{H},{L},{S}\]").search(hlo), \
        "biased flash backward materialized the [B,H,L,S] score tensor"


def test_backward_never_materializes_scores():
    """VERDICT r1 #7 done-criterion: the compiled train-direction program
    must contain no [B, H, L, S] tensor (the flash memory shape holds in
    backward too). The reference path, by contrast, does."""
    import re

    B, H, L, S, Dh = 2, 2, 64, 64, 16
    q, k, v, mask = _mk(B=B, H=H, L=L, S=S, Dh=Dh, pad_tail=4)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, mask, None, 16, 16).sum()

    def loss_ref(q, k, v):
        return reference_attention(q, k, v, mask).sum()

    # anchored (see test_biased_backward_never_materializes_scores); the
    # hlo_ref oracle below keeps this honest if HLO shape syntax changes
    score_shape = re.compile(rf"\[{B},{H},{L},{S}\]")
    hlo_flash = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2))).lower(
        q, k, v).compile().as_text()
    hlo_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2))).lower(
        q, k, v).compile().as_text()
    assert score_shape.search(hlo_ref), "oracle: reference must materialize"
    assert not score_shape.search(hlo_flash), \
        "flash backward materialized the [B,H,L,S] score tensor"
