"""Profiling produces an actual trace (VERDICT r3 Weak #4: `--profile` was
smoke-only; nothing asserted a trace appears) — plus the LatencyStats /
PipelineProfiler contracts the observability PR leans on: bounded-memory
reservoir with nearest-rank percentile semantics stable across the change,
and per-stage call counts next to the cumulative seconds."""
import os

import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.train.loop import Trainer
from dnn_page_vectors_tpu.utils.profiling import (
    LatencyStats, PipelineProfiler, maybe_profile)


def _tree_files(root):
    return [os.path.join(d, f) for d, _, fs in os.walk(root) for f in fs]


@pytest.mark.slow
def test_maybe_profile_writes_trace_around_train_step(tmp_path):
    cfg = get_config("cdssm_toy", {
        "data.num_pages": 64, "data.trigram_buckets": 512,
        "model.embed_dim": 16, "model.conv_channels": 16,
        "model.out_dim": 16,
        "train.batch_size": 16, "train.log_every": 1000,
    })
    trainer = Trainer(cfg, workdir=str(tmp_path))
    with maybe_profile(True, str(tmp_path)):
        trainer.train(steps=1)
    trace_dir = os.path.join(str(tmp_path), "trace")
    assert os.path.isdir(trace_dir)
    files = _tree_files(trace_dir)
    assert files, "profiler produced an empty trace directory"
    # jax.profiler writes TensorBoard-readable artifacts under
    # plugins/profile/<run>/
    assert any("plugins" in f for f in files), files


def test_maybe_profile_disabled_is_a_no_op(tmp_path):
    with maybe_profile(False, str(tmp_path / "w")):
        pass
    assert not os.path.exists(str(tmp_path / "w" / "trace"))


# -- LatencyStats: nearest-rank percentile edges on the bounded reservoir --

def _ref_percentile_ms(samples, q):
    """The pre-reservoir implementation, verbatim: nearest rank over ALL
    samples. The bounded version must match it exactly below the cap."""
    if not samples:
        return 0.0
    s = sorted(samples)
    rank = max(0, min(len(s) - 1, int(-(-q * len(s) // 100)) - 1))
    return s[rank] * 1000.0


def test_percentile_empty_and_single_sample():
    lat = LatencyStats()
    assert lat.percentile_ms(50) == 0.0 and lat.percentile_ms(99) == 0.0
    lat.add(0.004)
    for q in (0, 1, 50, 99, 100):    # n=1: every percentile IS the sample
        assert lat.percentile_ms(q) == pytest.approx(4.0)


@pytest.mark.parametrize("n", [2, 3, 4, 5, 10, 11])
def test_percentile_q0_q100_even_odd_match_unbounded_semantics(n):
    samples = [(i * 7 % n + 1) / 1000.0 for i in range(n)]   # shuffled-ish
    lat = LatencyStats()
    for s in samples:
        lat.add(s)
    assert len(lat) == n
    for q in (0, 25, 50, 75, 99, 100):
        assert lat.percentile_ms(q) == pytest.approx(
            _ref_percentile_ms(samples, q)), (n, q)
    # q=0 is the min, q=100 the max, even-count p50 the LOWER middle
    assert lat.percentile_ms(0) == pytest.approx(min(samples) * 1000.0)
    assert lat.percentile_ms(100) == pytest.approx(max(samples) * 1000.0)
    if n % 2 == 0:
        assert lat.percentile_ms(50) == pytest.approx(
            sorted(samples)[n // 2 - 1] * 1000.0)


def test_latency_stats_summary_keys_stable_and_memory_bounded():
    """summary() keys are byte-identical to the pre-reservoir version, and
    a long-lived service stops growing: past `cap` samples the buffer is
    bounded while count/mean stay exact."""
    lat = LatencyStats(cap=64, seed=0)
    for i in range(10_000):
        lat.add((i % 100 + 1) / 1000.0)
    assert list(lat.summary()) == ["lat_count", "lat_mean_ms",
                                   "lat_p50_ms", "lat_p99_ms"]
    s = lat.summary()
    assert s["lat_count"] == 10_000                 # exact, not sampled
    assert s["lat_mean_ms"] == pytest.approx(50.5, abs=0.1)
    assert len(lat._res._buf) == 64                 # bounded buffer
    assert 1.0 <= s["lat_p50_ms"] <= 100.0          # a delivered sample


def test_pipeline_profiler_summary_emits_counts_next_to_seconds():
    prof = PipelineProfiler()
    for _ in range(3):
        prof.add("tokenize", 0.5)
    prof.add("h2d", 0.25)
    s = prof.summary()
    assert s["stage_tokenize_s"] == pytest.approx(1.5)
    assert s["stage_tokenize_n"] == 3               # mean-per-call from
    assert s["stage_h2d_s"] == pytest.approx(0.25)  # ONE metrics line
    assert s["stage_h2d_n"] == 1
    assert list(s) == ["stage_h2d_s", "stage_h2d_n",
                       "stage_tokenize_s", "stage_tokenize_n"]
