"""Profiling produces an actual trace (VERDICT r3 Weak #4: `--profile` was
smoke-only; nothing asserted a trace appears)."""
import os

import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.train.loop import Trainer
from dnn_page_vectors_tpu.utils.profiling import maybe_profile


def _tree_files(root):
    return [os.path.join(d, f) for d, _, fs in os.walk(root) for f in fs]


@pytest.mark.slow
def test_maybe_profile_writes_trace_around_train_step(tmp_path):
    cfg = get_config("cdssm_toy", {
        "data.num_pages": 64, "data.trigram_buckets": 512,
        "model.embed_dim": 16, "model.conv_channels": 16,
        "model.out_dim": 16,
        "train.batch_size": 16, "train.log_every": 1000,
    })
    trainer = Trainer(cfg, workdir=str(tmp_path))
    with maybe_profile(True, str(tmp_path)):
        trainer.train(steps=1)
    trace_dir = os.path.join(str(tmp_path), "trace")
    assert os.path.isdir(trace_dir)
    files = _tree_files(trace_dir)
    assert files, "profiler produced an empty trace directory"
    # jax.profiler writes TensorBoard-readable artifacts under
    # plugins/profile/<run>/
    assert any("plugins" in f for f in files), files


def test_maybe_profile_disabled_is_a_no_op(tmp_path):
    with maybe_profile(False, str(tmp_path / "w")):
        pass
    assert not os.path.exists(str(tmp_path / "w" / "trace"))
