"""Checkpoint/resume + failure-recovery tests (SURVEY.md §5.3-5.4):
mid-run checkpointing, restore equivalence (params AND data order), and the
fault-injection bulk-embed resume test.
"""
import dataclasses
import pytest

import jax
import numpy as np

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.data.loader import TrainBatcher
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.train.checkpoint import CheckpointManager
from dnn_page_vectors_tpu.train.loop import Trainer


def _cfg():
    return get_config("cdssm_toy", {
        "data.num_pages": 256,
        "data.trigram_buckets": 1024,
        "model.embed_dim": 32,
        "model.conv_channels": 32,
        "model.out_dim": 32,
        "model.dtype": "float32",
        "train.batch_size": 64,
        "train.steps": 6,
        "train.warmup_steps": 2,
        "train.log_every": 100,
        "train.checkpoint_every": 2,
    })


def _params_flat(state):
    return jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, state.params))


@pytest.mark.slow
def test_resume_equals_uninterrupted(tmp_path):
    """train 6 == train 3 + restore + train 3, params AND data order."""
    cfg = _cfg()
    t1 = Trainer(cfg, workdir=str(tmp_path / "a"))
    full, _ = t1.train(steps=6)

    t2 = Trainer(cfg, workdir=str(tmp_path / "b"))
    mgr = CheckpointManager(str(tmp_path / "b" / "ckpt"))
    half, _ = t2.train(steps=3)
    mgr.save(3, half, wait=True)

    t3 = Trainer(cfg, workdir=str(tmp_path / "b"))
    restored = mgr.restore(t3.init_state())
    assert int(restored.step) == 3
    resumed, _ = t3.train(steps=3, state=restored)
    mgr.close()

    for a, b in zip(_params_flat(full), _params_flat(resumed)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_midrun_checkpointing(tmp_path):
    cfg = _cfg()
    trainer = Trainer(cfg, workdir=str(tmp_path))
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    trainer.train(steps=5, ckpt_manager=mgr)  # checkpoint_every=2
    mgr._mgr.wait_until_finished()
    # saves at steps 2 and 4 (step 5 is the caller's final save)
    assert mgr.latest_step() == 4
    mgr.close()


def test_batcher_resume_matches_data_order():
    cfg = _cfg()
    t = Trainer(cfg, workdir=None)
    b_full = TrainBatcher(t.corpus, t.query_tok, t.page_tok, 64, seed=5)
    it = iter(b_full)
    want = [next(it)["page_id"] for _ in range(7)]  # crosses epoch boundary
    b_resumed = TrainBatcher(t.corpus, t.query_tok, t.page_tok, 64, seed=5,
                             start_step=5)
    it2 = iter(b_resumed)
    got = [next(it2)["page_id"] for _ in range(2)]
    np.testing.assert_array_equal(want[5], got[0])
    np.testing.assert_array_equal(want[6], got[1])


def test_batcher_rejects_oversized_batch():
    cfg = _cfg()
    t = Trainer(cfg, workdir=None)
    try:
        TrainBatcher(t.corpus, t.query_tok, t.page_tok, batch_size=10_000)
    except ValueError as e:
        assert "batch_size" in str(e)
    else:
        raise AssertionError("expected ValueError")


def test_bulk_embed_fault_injection_resume(tmp_path):
    """Kill the job mid-embed (simulated), restart, assert the final store
    equals an uninterrupted run's (SURVEY.md §5.3)."""
    cfg = _cfg()
    trainer = Trainer(cfg, workdir=str(tmp_path / "t"))
    state = trainer.init_state()
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, trainer.query_tok)

    clean = VectorStore(str(tmp_path / "clean"), dim=32, shard_size=64)
    emb.embed_corpus(trainer.corpus, clean, batch_size=32)

    crashy = VectorStore(str(tmp_path / "crashy"), dim=32, shard_size=64)

    class Boom(RuntimeError):
        pass

    real_write = crashy.write_shard
    calls = {"n": 0}

    def failing_write(index, ids, vecs):
        if calls["n"] == 2:
            raise Boom("simulated crash mid-job")
        calls["n"] += 1
        real_write(index, ids, vecs)

    crashy.write_shard = failing_write
    try:
        emb.embed_corpus(trainer.corpus, crashy, batch_size=32)
        raise AssertionError("expected simulated crash")
    except Boom:
        pass

    # restart: fresh store object on the same dir resumes from the manifest
    resumed = VectorStore(str(tmp_path / "crashy"))
    assert len(resumed.completed_shards()) == 2
    emb.embed_corpus(trainer.corpus, resumed, batch_size=32)

    ids_a, vecs_a = clean.load_all()
    ids_b, vecs_b = resumed.load_all()
    oa, ob = np.argsort(ids_a), np.argsort(ids_b)
    np.testing.assert_array_equal(ids_a[oa], ids_b[ob])
    np.testing.assert_allclose(vecs_a[oa].astype(np.float32),
                               vecs_b[ob].astype(np.float32), atol=1e-3)


def test_jsonl_corpus_roundtrip(tmp_path):
    import json
    path = tmp_path / "corpus.jsonl"
    with open(path, "w") as f:
        for i in range(8):
            f.write(json.dumps({"query": f"find page {i}",
                                "page": f"this is page {i} about topic {i % 3}"})
                    + "\n")
    cfg = get_config("cdssm_toy", {"data.corpus": f"jsonl:{path}",
                                   "data.num_pages": 8})
    from dnn_page_vectors_tpu.data.loader import build_corpus
    corpus = build_corpus(cfg)
    assert corpus.num_pages == 8
    assert corpus.page_text(3) == "this is page 3 about topic 0"
    assert corpus.query_text(3) == "find page 3"
    assert len(list(corpus.all_texts())) == 16


@pytest.mark.slow
def test_elastic_restore_across_mesh_shapes(tmp_path, eight_devices):
    """Elastic resume (VERDICT r4 Missing #3): save on a 4-device DP mesh,
    restore INTO AN 8-DEVICE MESH's shardings and continue — the
    preempted-pod-resumes-on-a-different-slice story. Orbax restores into
    the target state's shardings (train/checkpoint.py:restore); the elastic
    run must match an uninterrupted 8-device run at DP tolerance (batch
    order is global, so it is mesh-shape-invariant by construction)."""
    def cfg(d):
        c = _cfg()
        return c.replace(mesh=dataclasses.replace(c.mesh, data=d))

    ref_full, _ = Trainer(cfg(8), workdir=str(tmp_path / "ref")).train(steps=6)

    t4 = Trainer(cfg(4), workdir=str(tmp_path / "el"))
    half, _ = t4.train(steps=3)
    assert t4.mesh.devices.size == 4
    mgr = CheckpointManager(str(tmp_path / "el" / "ckpt"))
    mgr.save(3, half, wait=True)

    t8 = Trainer(cfg(8), workdir=str(tmp_path / "el"))
    restored = mgr.restore(t8.init_state())
    assert int(restored.step) == 3
    # restored leaves carry the 8-device mesh's shardings, not the saved 4s
    leaf = jax.tree_util.tree_leaves(restored.params)[0]
    assert leaf.sharding.mesh.devices.size == 8, leaf.sharding
    resumed, _ = t8.train(steps=3, state=restored)

    for a, b in zip(_params_flat(ref_full), _params_flat(resumed)):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)

    # and back DOWN a slice size: the same checkpoint restores into a
    # 2-device mesh and continues without error (shrink direction)
    t2 = Trainer(cfg(2), workdir=str(tmp_path / "el"))
    down = mgr.restore(t2.init_state())
    resumed2, _ = t2.train(steps=3, state=down)
    mgr.close()
    for a, b in zip(_params_flat(ref_full), _params_flat(resumed2)):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)
