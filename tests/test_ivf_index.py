"""IVF ANN index (index/kmeans.py, index/ivf.py, docs/ANN.md): seeded
build determinism, the recall-vs-exact contract on the toy corpus,
model-step re-stamp invalidation, and quarantined-posting fallback to the
exact serving path under a seeded FaultPlan."""
import json
import os
import shutil

import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.evals.recall import recall_vs_exact
from dnn_page_vectors_tpu.index.ivf import (
    IndexUnavailable, IVFIndex, index_dir)
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.serve import SearchService
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.ops.topk import topk_over_store
from dnn_page_vectors_tpu.train.loop import Trainer
from dnn_page_vectors_tpu.utils import faults

pytestmark = pytest.mark.ann

_OV = {
    "data.num_pages": 300,
    "data.trigram_buckets": 2048,
    "model.embed_dim": 48,
    "model.conv_channels": 96,
    "model.out_dim": 48,
    "train.batch_size": 64,
    "train.steps": 60,
    "train.warmup_steps": 10,
    "train.learning_rate": 2e-3,
    "train.log_every": 1000,
    "eval.embed_batch_size": 100,
    "eval.store_shard_size": 100,   # 3 shards: per-shard posting lists
}


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    """One trained model + embedded 3-shard store for the whole module;
    destructive tests copy the store directory instead of mutating it."""
    wd = tmp_path_factory.mktemp("ivf_env")
    cfg = get_config("cdssm_toy", _OV)
    trainer = Trainer(cfg, workdir=str(wd))
    state, _ = trainer.train()
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, query_tok=trainer.query_tok)
    store = VectorStore(os.path.join(str(wd), "store"),
                        dim=cfg.model.out_dim, shard_size=100)
    store.ensure_model_step(int(state.step))
    emb.embed_corpus(trainer.corpus, store)
    # checkpoint so CLI subcommands restore THESE params (store stamp and
    # restored step must agree for the index to be valid under `search`)
    from dnn_page_vectors_tpu.train.checkpoint import CheckpointManager
    mgr = CheckpointManager(os.path.join(str(wd), "ckpt"))
    mgr.save(int(state.step), state, wait=True)
    mgr.close()
    return {"cfg": cfg, "trainer": trainer, "emb": emb, "store": store,
            "wd": str(wd)}


def _copy_store(env, tmp_path):
    """Private byte-identical copy of the embedded store (no index)."""
    dst = os.path.join(str(tmp_path), "store")
    shutil.copytree(env["store"].directory, dst)
    shutil.rmtree(os.path.join(dst, "ivf"), ignore_errors=True)
    return VectorStore(dst)


def _ivf_cfg(env, nprobe=None):
    import dataclasses
    serve = dataclasses.replace(env["cfg"].serve, index="ivf",
                                **({} if nprobe is None
                                   else {"nprobe": nprobe}))
    return env["cfg"].replace(serve=serve)


def test_build_is_seed_deterministic(env, tmp_path):
    """Same store bytes + seed -> byte-identical centroids and postings
    (the manifest differs only in build_seconds)."""
    a = _copy_store(env, tmp_path / "a")
    b = _copy_store(env, tmp_path / "b")
    mesh = env["emb"].mesh
    ia = IVFIndex.build(a, mesh, nlist=16, iters=5, seed=3)
    ib = IVFIndex.build(b, mesh, nlist=16, iters=5, seed=3)
    names = sorted(n for n in os.listdir(index_dir(a))
                   if n.endswith(".npy"))
    assert names and names == sorted(
        n for n in os.listdir(index_dir(b)) if n.endswith(".npy"))
    for n in names:
        with open(os.path.join(index_dir(a), n), "rb") as f:
            bytes_a = f.read()
        with open(os.path.join(index_dir(b), n), "rb") as f:
            bytes_b = f.read()
        assert bytes_a == bytes_b, f"{n} differs between seeded builds"
    # manifests agree on everything but wall-clock
    ma, mb = dict(ia.manifest), dict(ib.manifest)
    ma.pop("build_seconds"), mb.pop("build_seconds")
    assert ma == mb
    # a different seed is allowed to (and here does) move centroids
    c = _copy_store(env, tmp_path / "c")
    ic = IVFIndex.build(c, mesh, nlist=16, iters=5, seed=4)
    assert not np.array_equal(ic.centroids, ia.centroids)


def test_recall_vs_exact_and_serving_contract(env):
    """On the toy corpus at the DEFAULT nprobe: index recall@10 >= 0.95 of
    the exact top-10, search_many through serve.index=ivf matches that
    contract, the exact path stays the default, and ANN counters move."""
    cfg = env["cfg"]
    assert cfg.serve.index == "exact"        # pre-PR behavior is default
    store, emb, trainer = env["store"], env["emb"], env["trainer"]
    IVFIndex.build(store, emb.mesh, seed=0)  # auto nlist (~sqrt N)
    idx = IVFIndex.open(store)
    queries = [trainer.corpus.query_text(i) for i in range(0, 300, 7)]
    qv = np.asarray(emb.embed_texts(queries, tower="query"), np.float32)
    r = recall_vs_exact(idx, store, qv, emb.mesh, k=10,
                        nprobe=cfg.serve.nprobe)
    assert r >= 0.95, f"ANN recall@10 vs exact {r:.3f} < 0.95"

    exact_svc = SearchService(cfg, emb, trainer.corpus, store,
                              preload_hbm_gb=4.0)
    ann_svc = SearchService(_ivf_cfg(env), emb, trainer.corpus, store,
                            preload_hbm_gb=0.0)
    assert ann_svc._index is not None
    got = ann_svc.search_many(queries, k=10)
    want = exact_svc.search_many(queries, k=10)
    overlap = np.mean([
        len({r["page_id"] for r in g} & {r["page_id"] for r in w})
        / max(len(w), 1)
        for g, w in zip(got, want)])
    assert overlap >= 0.95, f"serving overlap {overlap:.3f} < 0.95"
    assert ann_svc.ann_fallbacks == 0
    met = ann_svc.metrics()
    assert met["ann_lists_scanned"] >= len(queries) * cfg.serve.nprobe
    assert met["ann_candidates_reranked"] > 0
    assert met["ann_index"]["available"] and \
        met["ann_index"]["nlist"] == idx.nlist
    # the exact service reports no ann keys at all (counter pattern only
    # activates with the feature)
    assert "ann_lists_scanned" not in exact_svc.metrics()


def test_full_probe_equals_exact(env):
    """nprobe == nlist scans every list: result ids must EQUAL the exact
    sweep (the ANN path is exact search plus routing at full probe)."""
    store, emb = env["store"], env["emb"]
    IVFIndex.build(store, emb.mesh, nlist=8, iters=4, seed=0)
    idx = IVFIndex.open(store)
    qv = np.asarray(emb.embed_texts(
        [env["trainer"].corpus.query_text(i) for i in (0, 11, 123)],
        tower="query"), np.float32)
    _, ann_ids, _ = idx.search(qv, k=10, nprobe=8)
    _, exact_ids = topk_over_store(qv, store, emb.mesh, k=10)
    for a, e in zip(ann_ids, exact_ids):
        assert set(a.tolist()) == set(e.tolist())


def test_int8_store_full_probe_equals_exact(tmp_path):
    """INT8 stores end to end: k-means assignment, posting gather, and the
    re-rank all run on stored-width codes with the per-row scales fused on
    device — at full probe the ANN ids must equal the exact sweep's over
    the same quantized store."""
    from dnn_page_vectors_tpu.config import MeshConfig
    from dnn_page_vectors_tpu.parallel.mesh import make_mesh
    rng = np.random.default_rng(3)
    N, D = 500, 32
    vecs = rng.normal(size=(N, D)).astype(np.float32)
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    store = VectorStore(str(tmp_path / "s"), dim=D, shard_size=200,
                        dtype="int8")
    store.ensure_model_step(1)
    for i in range(0, N, 200):
        store.write_shard(i // 200, np.arange(i, min(i + 200, N)),
                          vecs[i: i + 200])
    mesh = make_mesh(MeshConfig(data=4))
    idx = IVFIndex.build(store, mesh, nlist=10, iters=4, seed=0)
    q = vecs[rng.choice(N, 20, replace=False)]
    _, ann_ids, _ = idx.search(q, k=5, nprobe=10)
    _, exact_ids = topk_over_store(q, store, mesh, k=5)
    for a, e in zip(ann_ids, exact_ids):
        assert set(a.tolist()) == set(e.tolist())


def test_model_step_restamp_invalidates(env, tmp_path):
    """An ensure_model_step re-stamp (stale vectors dropped, new stamp)
    must structurally invalidate the index: open() raises, and a running
    ivf service falls back to exact per request."""
    store = _copy_store(env, tmp_path)
    emb, trainer = env["emb"], env["trainer"]
    IVFIndex.build(store, emb.mesh, nlist=8, iters=3, seed=0)
    IVFIndex.open(store)                                   # valid now
    svc = SearchService(_ivf_cfg(env), emb, trainer.corpus, store,
                        preload_hbm_gb=0.0)
    assert svc._index is not None
    step = store.model_step
    store.ensure_model_step(step + 1)                      # reset + restamp
    with pytest.raises(IndexUnavailable, match="stale"):
        IVFIndex.open(store)
    # the already-open service re-checks the stamp per request: exact
    # fallback (empty store now -> no results), counted
    assert svc.search("anything", k=5) == []
    assert svc.ann_fallbacks == 1
    assert svc.metrics()["ann_fallbacks"] == 1


def test_quarantined_posting_falls_back_to_exact(env, tmp_path):
    """A seeded FaultPlan corrupts one posting file post-fsync (media rot
    the writer can't see). open() must quarantine it and report the index
    unavailable; a serve.index=ivf service then answers every query
    through the exact path — same results as an exact service — and
    counts the fallbacks."""
    store = _copy_store(env, tmp_path)
    emb, trainer = env["emb"], env["trainer"]
    faults.install(faults.FaultPlan.parse("index_file:bit_flip:1", seed=7))
    IVFIndex.build(store, emb.mesh, nlist=8, iters=3, seed=0)
    with pytest.raises(IndexUnavailable):
        IVFIndex.open(store)
    assert faults.counters().get("quarantined_index_files") == 1
    svc = SearchService(_ivf_cfg(env), emb, trainer.corpus, store,
                        preload_hbm_gb=4.0)
    assert svc._index is None and "rebuild" in (svc._index_error or "")
    exact = SearchService(env["cfg"], emb, trainer.corpus, store,
                          preload_hbm_gb=4.0)
    queries = [trainer.corpus.query_text(i) for i in (2, 77, 290)]
    got = svc.search_many(queries, k=10)
    want = exact.search_many(queries, k=10)
    assert [[r["page_id"] for r in g] for g in got] == \
        [[r["page_id"] for r in w] for w in want]
    assert svc.ann_fallbacks == len(queries)
    assert svc.metrics()["ann_fallbacks"] == len(queries)
    assert not svc.metrics()["ann_index"]["available"]


def test_cli_index_and_nprobe_search(env, capsys):
    """The `index` subcommand builds from the on-disk store + config and
    reports nlist/build seconds/imbalance; `search --nprobe N` routes the
    query through the index."""
    from dnn_page_vectors_tpu import cli
    base = ["--config", "cdssm_toy", "--workdir", env["wd"]] + [
        x for key, val in _OV.items() for x in ("--set", f"{key}={val}")]
    cli.main(["index"] + base + ["--set", "serve.nlist=16"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["nlist"] == 16 and out["vectors"] == 300
    assert out["build_seconds"] > 0 and out["imbalance"] >= 1.0
    gold = 3
    query = env["trainer"].corpus.query_text(gold)
    cli.main(["search", "--query", query, "--nprobe", "8"] + base)
    res = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(res["results"]) == 10
    assert gold in [r["page_id"] for r in res["results"]]


@pytest.mark.slow
def test_large_nlist_build(env, tmp_path):
    """Large-nlist build on the toy store: every centroid survives (or is
    reseeded), every row lands in exactly one posting list, and recall at
    full probe stays exact."""
    store = _copy_store(env, tmp_path)
    emb = env["emb"]
    idx = IVFIndex.build(store, emb.mesh, nlist=128, iters=8, seed=0)
    assert idx.nlist == 128
    assert int(idx.list_sizes.sum()) == store.num_vectors
    qv = np.asarray(emb.embed_texts(
        [env["trainer"].corpus.query_text(i) for i in range(40)],
        tower="query"), np.float32)
    r = recall_vs_exact(idx, store, qv, emb.mesh, k=10, nprobe=128)
    assert r == 1.0
