"""Over-the-wire partitioned serving (docs/SERVING.md "Network front
end"): the wire protocol must REJECT malformed streams cleanly (fuzzed
truncation/garbage/oversize — never a hung connection), over-the-wire
results must be BYTE-identical to the in-process scatter-gather
(including under kill-a-worker and torn-response faults, which degrade
exactly like the in-process shed path), deadline admission must shed at
the door — an expired request never consumes a micro-batch bucket slot
(pinned on a fake clock) — and the tail-latency controls (hedged
fan-out, liveness routing, heartbeat-bounded recovery) are pinned with
their counters and events."""
import json
import socket
import struct
import threading
import time

import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.infer import transport
from dnn_page_vectors_tpu.infer.transport import (
    DeadlineExceeded, FrameError, SocketSearchClient)

pytestmark = pytest.mark.net

DIM = 32
SHARD = 50
NSHARDS = 6


# ---------------------------------------------------------------------------
# fixtures: a synthetic store + model-free services (no training — the
# socket layer is exercised by pre-computed vectors and a stub embedder)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def net_store(tmp_path_factory):
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore
    sdir = str(tmp_path_factory.mktemp("net_store") / "store")
    rng = np.random.default_rng(0)
    store = VectorStore(sdir, dim=DIM, shard_size=SHARD)
    for si in range(NSHARDS):
        v = rng.standard_normal((SHARD, DIM)).astype(np.float32)
        v /= np.linalg.norm(v, axis=1, keepdims=True)
        store.write_shard(si, np.arange(si * SHARD, (si + 1) * SHARD,
                                        dtype=np.int64), v)
    return VectorStore(sdir)


@pytest.fixture(scope="module")
def mesh():
    import jax
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:1]), ("data",))


def _qv(n=3, seed=1):
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((n, DIM)).astype(np.float32)
    return q / np.linalg.norm(q, axis=1, keepdims=True)


def _fake_embed(queries):
    """Deterministic text -> unit vector (no model): the socket text
    path is exercised without a trained encoder."""
    out = np.zeros((len(queries), DIM), np.float32)
    for i, q in enumerate(queries):
        r = np.random.default_rng(
            np.frombuffer(q.encode()[:8].ljust(8, b"\0"),
                          np.uint64)[0] % (2 ** 32))
        v = r.standard_normal(DIM).astype(np.float32)
        out[i] = v / np.linalg.norm(v)
    return out


class _StubCorpus:
    def page_text(self, i):
        return f"page {i}"


def _service(net_store, mesh, **serve_over):
    import dataclasses

    from dnn_page_vectors_tpu.infer.partition_host import MeshEmbedder
    from dnn_page_vectors_tpu.infer.serve import SearchService
    cfg = get_config("cdssm_toy", {"model.out_dim": DIM})
    if serve_over:
        cfg = cfg.replace(serve=dataclasses.replace(cfg.serve,
                                                    **serve_over))
    svc = SearchService(cfg, MeshEmbedder(mesh), None, net_store,
                        preload_hbm_gb=4.0)
    svc._embed_queries_cached = _fake_embed
    svc.corpus = _StubCorpus()
    return svc


def _thread_worker(cfg, store_dir, port, partition, partitions, replica,
                   mesh):
    from dnn_page_vectors_tpu.infer.partition_host import PartitionWorker
    w = PartitionWorker(cfg, store_dir, ("127.0.0.1", port),
                        partition=partition, partitions=partitions,
                        replica=replica, mesh=mesh)
    t = threading.Thread(target=w.run, daemon=True)
    t.start()
    return w, t


# ---------------------------------------------------------------------------
# wire protocol: round trips + fuzz (truncation / garbage / oversize)
# ---------------------------------------------------------------------------

def test_frame_codec_roundtrip():
    p = transport.encode_query(7, ["hello", "wörld"], k=10, nprobe=4,
                               deadline_ms=25.5)
    r = transport.decode_query(p)
    assert (r.req_id, r.k, r.nprobe) == (7, 10, 4)
    assert r.queries == ("hello", "wörld")
    assert abs(r.deadline_ms - 25.5) < 1e-9
    qv = _qv(3)
    v = transport.decode_vquery(transport.encode_vquery(9, qv, k=5,
                                                        nprobe=2))
    assert np.array_equal(v.qv, qv) and (v.k, v.nprobe) == (5, 2)
    scores = _qv(2, seed=3)[:, :5].copy()
    ids = np.arange(10, dtype=np.int64).reshape(2, 5)
    rid, s2, i2, scan = transport.decode_result(
        transport.encode_result(11, scores, ids, scan_bytes=777))
    assert rid == 11 and scan == 777
    assert np.array_equal(s2, scores) and np.array_equal(i2, ids)
    assert transport.decode_shed(transport.encode_shed(
        3, transport.SHED_DEADLINE, "late")) == (
            3, transport.SHED_DEADLINE, "late")
    assert transport.decode_register(
        transport.encode_register(2, 1, 999)) == (2, 1, 999, 0, 0)
    # the extended REGISTER carries capability flags + store generation;
    # the legacy 16-byte form (a raw pre-compression worker) still
    # decodes — mixed fleets register on one gateway
    assert transport.decode_register(transport.encode_register(
        2, 1, 999, flags=transport.FLAG_WIRE_COMPRESS,
        generation=7)) == (2, 1, 999, transport.FLAG_WIRE_COMPRESS, 7)
    assert transport.decode_register(
        transport._REGISTER_HEAD.pack(3, 0, 42)) == (3, 0, 42, 0, 0)
    assert transport.decode_hello(transport.encode_hello(1)) == 1
    assert transport.decode_refresh(transport.encode_refresh(9)) == (9, 0)
    assert transport.decode_refresh(
        transport.encode_refresh(9, partitions=3)) == (9, 3)


def test_frame_fuzz_truncation_garbage_oversize():
    """Seeded fuzz of the reject paths: every truncation of a valid
    payload, random garbage, and oversize headers must raise FrameError
    (or IndexError-free clean decode) — never hang, never crash the
    decoder with anything else."""
    rng = np.random.default_rng(42)
    valid = [
        transport.encode_query(1, ["abc", "def"], k=3),
        transport.encode_vquery(2, _qv(2)),
        transport.encode_result(3, _qv(2)[:, :4].copy(),
                                np.zeros((2, 4), np.int64)),
    ]
    decoders = [transport.decode_query, transport.decode_vquery,
                transport.decode_result]
    for payload, decode in zip(valid, decoders):
        decode(payload)                       # sanity: full payload OK
        for cut in range(len(payload)):       # EVERY proper prefix rejects
            with pytest.raises(FrameError):
                decode(payload[:cut])
        # trailing garbage is a framing violation too
        with pytest.raises(FrameError):
            decode(payload + b"\x00")
        # random byte flips may still decode (flipping a float is legal)
        # but must never raise anything but FrameError
        for _ in range(50):
            mutated = bytearray(payload)
            pos = int(rng.integers(0, len(mutated)))
            mutated[pos] = int(rng.integers(0, 256))
            try:
                decode(bytes(mutated))
            except FrameError:
                pass
    # header checks: bad magic, unknown type, oversize length
    with pytest.raises(FrameError):
        transport._check_header(struct.pack("!IBI", 0xDEADBEEF, 1, 4))
    with pytest.raises(FrameError):
        transport._check_header(struct.pack("!IBI", transport.MAGIC,
                                            200, 4))
    with pytest.raises(FrameError):
        transport._check_header(struct.pack("!IBI", transport.MAGIC, 1,
                                            transport.MAX_FRAME + 1))


def test_read_frame_truncation_vs_clean_eof():
    """Socket-level framing: clean EOF at a boundary -> None; EOF inside
    a header or payload -> FrameError (a torn peer, not a clean bye)."""
    a, b = socket.socketpair()
    try:
        b.sendall(transport.pack_frame(transport.T_HEARTBEAT))
        assert transport.read_frame(a) == (transport.T_HEARTBEAT, b"")
        b.close()
        assert transport.read_frame(a) is None        # clean EOF
    finally:
        a.close()
    a, b = socket.socketpair()
    try:
        frame = transport.pack_frame(transport.T_QUERY,
                                     transport.encode_query(1, ["x"]))
        b.sendall(frame[: len(frame) - 3])            # torn mid-payload
        b.close()
        with pytest.raises(FrameError):
            transport.read_frame(a)
    finally:
        a.close()


# ---------------------------------------------------------------------------
# compressed wire extensions: codec roundtrip + adversarial fuzz
# ---------------------------------------------------------------------------

def test_compressed_result_roundtrip_and_fuzz():
    """The compressed RESULT codec is LOSSLESS (exact f32 scores, exact
    i64 ids incl. -1 padding and int64 extremes) and measurably smaller;
    every truncation — mid-score-block, mid-varint, short of n*k ids —
    plus trailing bytes, unterminated varint continuation runs, and
    deltas that overflow int64 all REJECT with FrameError."""
    rng = np.random.default_rng(7)
    ids = rng.integers(0, 131072, size=(3, 10)).astype(np.int64)
    ids[1, 7:] = -1                       # -1-padded short result rows
    ids[2, 0] = 2 ** 63 - 1               # zigzag's worst-case neighbors
    ids[2, 1] = -1
    scores = rng.standard_normal((3, 10)).astype(np.float32)
    comp = transport.encode_result_c(11, scores, ids, scan_bytes=777)
    raw = transport.encode_result(11, scores, ids, scan_bytes=777)
    rid, s2, i2, scan = transport.decode_result_c(comp)
    assert rid == 11 and scan == 777
    assert np.array_equal(s2, scores) and np.array_equal(i2, ids)
    assert len(comp) < len(raw)           # the id block actually shrank
    # decode_result_any dispatches on frame type
    rid2, s3, i3, _ = transport.decode_result_any(transport.T_RESULT_C,
                                                  comp)
    assert rid2 == 11 and np.array_equal(i3, ids)
    for cut in range(len(comp)):          # EVERY proper prefix rejects
        with pytest.raises(FrameError):
            transport.decode_result_c(comp[:cut])
    with pytest.raises(FrameError):       # trailing bytes reject
        transport.decode_result_c(comp + b"\x00")
    # adversarial continuation bytes: a varint that never terminates
    # must reject at the 10-byte cap, not parse unboundedly
    head = comp[: transport._RESULT_HEAD.size + 3 * 10 * 4]
    with pytest.raises(FrameError):
        transport.decode_result_c(head + b"\x80" * 64)
    # an oversize delta: a maximal terminated varint walks the running
    # id out of int64 range -> clean REJECT (never a wrapped id)
    big = bytearray()
    for _ in range(30):
        transport._append_uvarint(big, (1 << 64) - 2)   # delta 2^63 - 1
    with pytest.raises(FrameError):
        transport.decode_result_c(head + bytes(big))
    # random byte flips decode or FrameError — nothing else ever
    for _ in range(200):
        mutated = bytearray(comp)
        pos = int(rng.integers(0, len(mutated)))
        mutated[pos] = int(rng.integers(0, 256))
        try:
            transport.decode_result_c(bytes(mutated))
        except FrameError:
            pass


def test_vquery_intern_put_ref_codec():
    """Per-connection query-block interning: PUT stores + serves, REF
    resolves byte-identically, and every protocol violation — an empty
    or out-of-range slot, a REF on a connection that never negotiated,
    truncation — REJECTS."""
    qv = _qv(2, seed=9)
    block = np.ascontiguousarray(qv, "<f4").tobytes()
    slots = {}
    put = transport.encode_vquery_put(5, 3, block, 2, DIM, k=7,
                                      deadline_ms=12.5)
    r = transport.decode_vquery_any(transport.T_VQUERY_PUT, put, slots)
    assert np.array_equal(r.qv, qv) and r.k == 7 and 3 in slots
    ref = transport.encode_vquery_ref(6, 3, 2, DIM, k=7)
    r2 = transport.decode_vquery_any(transport.T_VQUERY_REF, ref, slots)
    assert np.array_equal(r2.qv, qv) and r2.req_id == 6
    for cut in range(len(ref)):
        with pytest.raises(FrameError):
            transport.decode_vquery_any(transport.T_VQUERY_REF,
                                        ref[:cut], slots)
    with pytest.raises(FrameError):       # REF to a slot never PUT
        transport.decode_vquery_any(
            transport.T_VQUERY_REF,
            transport.encode_vquery_ref(7, 9, 2, DIM), slots)
    with pytest.raises(FrameError):       # slot id past WIRE_SLOTS
        transport.decode_vquery_any(
            transport.T_VQUERY_REF,
            transport.encode_vquery_ref(7, transport.WIRE_SLOTS, 2, DIM),
            slots)
    with pytest.raises(FrameError):       # un-negotiated connection
        transport.decode_vquery_any(transport.T_VQUERY_REF, ref, None)
    # a mismatched REF geometry (stored block vs claimed [n, dim])
    with pytest.raises(FrameError):
        transport.decode_vquery_any(
            transport.T_VQUERY_REF,
            transport.encode_vquery_ref(8, 3, 3, DIM), slots)
    # sender-side ring: deterministic slot reuse, stale keys forgotten
    tab = transport.InternTable(cap=2)
    s0, fresh0 = tab.slot_for(b"a")
    s1, fresh1 = tab.slot_for(b"b")
    assert (fresh0, fresh1) == (True, True) and s0 != s1
    assert tab.slot_for(b"a") == (s0, False)        # warm hit
    s2, fresh2 = tab.slot_for(b"c")                 # evicts the ring slot
    assert fresh2 and s2 == s0
    assert tab.slot_for(b"a")[1] is True            # "a" was evicted


# ---------------------------------------------------------------------------
# the asyncio front end
# ---------------------------------------------------------------------------

def test_server_results_match_inprocess_and_rejects_garbage(net_store,
                                                            mesh):
    from dnn_page_vectors_tpu.infer.server import serve_in_background
    svc = _service(net_store, mesh, partitions=2)
    srv = serve_in_background(svc)
    client = SocketSearchClient(srv.host, srv.port)
    try:
        qv = _qv(3)
        base_s, base_i = svc.topk_vectors(qv, k=10)
        s, i, _ = client.topk_vectors(qv, k=10)
        assert np.array_equal(s, base_s) and np.array_equal(i, base_i)
        # text path: wire scores/ids == the formatted local results
        queries = ["alpha", "beta"]
        local = svc.search_many(queries, k=10)
        ws, wi, _ = client.search_raw(queries, k=10)
        for qi, res in enumerate(local):
            assert [r["page_id"] for r in res] == \
                [int(x) for x in wi[qi] if x >= 0]
            assert [r["score"] for r in res] == \
                [round(float(x), 4) for x, pid in zip(ws[qi], wi[qi])
                 if pid >= 0]
        assert svc.wire_bytes > 0
        # garbage header -> ERROR frame + close, never a hang; the
        # server keeps serving fresh connections afterwards
        raw = socket.create_connection((srv.host, srv.port), timeout=5)
        raw.sendall(b"GET / HTTP/1.1\r\n\r\n")
        raw.settimeout(5)
        frame = transport.read_frame(raw)
        assert frame is not None and frame[0] == transport.T_ERROR
        assert transport.read_frame(raw) is None      # closed cleanly
        raw.close()
        # truncated frame (header promises more than arrives) -> closed
        raw = socket.create_connection((srv.host, srv.port), timeout=5)
        raw.sendall(transport.HEADER.pack(transport.MAGIC,
                                          transport.T_QUERY, 100))
        raw.sendall(b"short")
        raw.close()
        s2, i2, _ = client.topk_vectors(qv, k=10)     # still serving
        assert np.array_equal(i2, base_i)
    finally:
        client.close()
        srv.close()
        svc.close()


# ---------------------------------------------------------------------------
# deadline-aware admission (the fake-clock pins)
# ---------------------------------------------------------------------------

def test_expired_deadline_never_consumes_bucket_slot(net_store, mesh):
    """THE acceptance pin: a request whose deadline already expired at
    admission is shed before it can touch the micro-batcher — no queue
    entry, no bucket slot, counted in serve.deadline_shed (never
    serve.errors), with the deadline_shed event emitted."""
    svc = _service(net_store, mesh)
    fake = {"t": 100.0}
    svc._clock = lambda: fake["t"]
    svc.start_batcher()
    b = svc._batcher
    try:
        deadline = svc.default_deadline(5.0)     # anchored at t=100
        fake["t"] += 1.0                         # ... and long expired
        n_batches = len(b.batch_sizes)
        with pytest.raises(DeadlineExceeded):
            svc.search("gamma", k=10, deadline=deadline)
        assert len(b.batch_sizes) == n_batches   # no bucket slot
        assert b._q.qsize() == 0                 # never entered the queue
        assert svc.deadline_sheds == 1
        assert svc._m_errors.value == 0          # a shed is not an error
        evs = [e for e in svc.registry.events()
               if e["event"] == "deadline_shed"]
        assert evs and evs[-1]["attrs"]["reason"] == "expired"
        # no-deadline requests always admit
        assert svc.search("hello", k=10)
    finally:
        svc.close()


def test_door_shed_when_deadline_expires_in_queue(net_store, mesh):
    """A request that admits but expires while queued is shed at the
    micro-batch DOOR: its future carries DeadlineExceeded and the batch
    it would have ridden never counts it as a slot."""
    from concurrent.futures import Future
    svc = _service(net_store, mesh)
    fake = {"t": 50.0}
    svc._clock = lambda: fake["t"]
    svc.start_batcher()
    b = svc._batcher
    try:
        fut: Future = Future()
        item = ("q", (10, None, None), fut, 0.0, None,
                svc.default_deadline(5.0))
        fake["t"] += 1.0                         # expires in the queue
        n_batches = len(b.batch_sizes)
        b._dispatch([item])
        assert len(b.batch_sizes) == n_batches   # the shed freed the slot
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=5)
        evs = [e for e in svc.registry.events()
               if e["event"] == "deadline_shed"]
        assert evs[-1]["attrs"]["reason"] == "expired_in_queue"
        # a mixed batch shed only the expired request; the live one
        # still dispatched and answered
        dead: Future = Future()
        live: Future = Future()
        b._dispatch([
            ("d", (10, None, None), dead, 0.0, None, fake["t"] - 0.001),
            ("l", (10, None, None), live, 0.0, None, None)])
        assert live.result(timeout=30)
        with pytest.raises(DeadlineExceeded):
            dead.result(timeout=5)
        assert b.batch_sizes[-1] == 1            # the shed freed its slot
    finally:
        svc.close()


def test_slo_budget_shed_from_queue_wait_p99(net_store, mesh):
    """Admission rung 2: when the windowed queue-wait p99 exceeds the
    remaining budget, the request cannot make its deadline — shed at the
    door (reason slo_budget) instead of timing out in a bucket."""
    svc = _service(net_store, mesh)
    svc.start_batcher()
    try:
        for _ in range(8):
            svc._m_queue_wait.observe(500.0)
        with pytest.raises(DeadlineExceeded):
            svc.search("q", k=10, deadline_ms=10.0)
        evs = [e for e in svc.registry.events()
               if e["event"] == "deadline_shed"]
        assert evs[-1]["attrs"]["reason"] == "slo_budget"
        assert evs[-1]["attrs"]["queue_wait_p99_ms"] >= 10.0
        # a budget ABOVE the p99 admits
        assert svc.search("q", k=10, deadline_ms=5000.0)
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# worker gateway: fan-out identity, liveness, faults, hedging
# ---------------------------------------------------------------------------

def test_gateway_fanout_byte_identical_and_transport_metrics(net_store,
                                                             mesh):
    from dnn_page_vectors_tpu.infer.partition_host import WorkerGateway
    svc = _service(net_store, mesh, partitions=2)
    qv = _qv(3)
    base_s, base_i = svc.topk_vectors(qv, k=10)
    gw = WorkerGateway(svc, heartbeat_s=0.2)
    svc.attach_gateway(gw)
    workers = []
    try:
        for p in range(2):
            workers.append(_thread_worker(svc.cfg, net_store.directory,
                                          gw.port, p, 2, 0, mesh))
        assert gw.wait_for_workers(2, timeout_s=30.0)
        s, i = svc.topk_vectors(qv, k=10)
        assert np.array_equal(s, base_s) and np.array_equal(i, base_i)
        st = gw.stats()
        assert st["rpcs"] >= 2 and st["rpc_fallbacks"] == 0
        assert st["workers_live"] == 2
        met = svc.metrics()
        assert met["transport"]["wire_bytes"] > 0
        assert met["transport"]["workers_live"] == 2
        evs = [e["event"] for e in svc.registry.events()]
        assert evs.count("worker_registered") == 2
        # the registered events carry the topology
        reg = [e for e in svc.registry.events()
               if e["event"] == "worker_registered"]
        assert sorted((e["attrs"]["partition"], e["attrs"]["replica"])
                      for e in reg) == [(0, 0), (1, 0)]
    finally:
        for w, _ in workers:
            w.stop()
        gw.close()
        svc.close()


def test_kill_worker_mid_trial_zero_mixed_results(net_store, mesh):
    """The kill-a-worker drill: a continuous query hammer sees ZERO
    errors, zero empty and zero non-identical result sets while a
    partition worker dies abruptly mid-trial; the gateway notices within
    one heartbeat interval and routing sheds the dead replica with
    reason "liveness" (R=2), with the worker_lost event emitted."""
    from dnn_page_vectors_tpu.infer.partition_host import WorkerGateway
    svc = _service(net_store, mesh, partitions=1, replicas=2)
    qv = _qv(2)
    base_s, base_i = svc.topk_vectors(qv, k=10)
    gw = WorkerGateway(svc, heartbeat_s=0.25)
    svc.attach_gateway(gw)
    workers = []
    errors, mismatches, results = [], [], [0]
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                s, i = svc.topk_vectors(qv, k=10)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                return
            results[0] += 1
            if i.size == 0 or not np.array_equal(i, base_i):
                mismatches.append(i)

    try:
        for r in range(2):
            workers.append(_thread_worker(svc.cfg, net_store.directory,
                                          gw.port, 0, 1, r, mesh))
        assert gw.wait_for_workers(2, timeout_s=30.0)
        threads = [threading.Thread(target=hammer) for _ in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.3)
        workers[0][0].stop()                  # kill the primary's worker
        t_kill = time.perf_counter()
        while gw.worker_alive(0, 0) and \
                time.perf_counter() - t_kill < 2.0:
            time.sleep(0.005)
        detect_s = time.perf_counter() - t_kill
        time.sleep(0.4)                       # hammer through the loss
        stop.set()
        for t in threads:
            t.join()
        assert not errors, errors[:2]
        assert not mismatches, "mixed/empty result set after worker kill"
        assert results[0] > 0
        assert detect_s <= gw.heartbeat_s, \
            f"loss detection took {detect_s:.3f}s (> one heartbeat)"
        assert any(e["event"] == "worker_lost"
                   for e in svc.registry.events())
        # post-kill traffic sheds the dead-worker replica by liveness
        svc.topk_vectors(qv, k=10)
        sheds = [e for e in svc.registry.events()
                 if e["event"] == "replica_shed"]
        assert sheds and sheds[-1]["attrs"]["reason"] == "liveness"
    finally:
        stop.set()
        for w, _ in workers:
            w.stop()
        gw.close()
        svc.close()


def test_torn_response_degrades_like_inprocess_shed(net_store, mesh):
    """A worker that answers with a TORN frame is marked lost (the
    worker_lost event carries the torn-frame reason) and its in-flight
    request falls back to the local view — results stay byte-identical;
    the connection never wedges the gateway."""
    from dnn_page_vectors_tpu.infer.partition_host import WorkerGateway
    svc = _service(net_store, mesh, partitions=1)
    qv = _qv(2)
    base_s, base_i = svc.topk_vectors(qv, k=10)
    gw = WorkerGateway(svc, heartbeat_s=0.25, rpc_timeout_s=5.0)
    svc.attach_gateway(gw)
    evil_done = threading.Event()

    def evil_worker():
        sock = socket.create_connection(("127.0.0.1", gw.port))
        transport.write_frame(sock, transport.T_REGISTER,
                              transport.encode_register(0, 0, 4242))
        frame = transport.read_frame(sock)       # the VQUERY arrives ...
        assert frame is not None
        # ... and the reply is a RESULT header promising bytes that
        # never come: a torn response
        sock.sendall(transport.HEADER.pack(transport.MAGIC,
                                           transport.T_RESULT, 4096))
        sock.sendall(b"\x00" * 16)
        sock.close()
        evil_done.set()

    t = threading.Thread(target=evil_worker, daemon=True)
    t.start()
    try:
        assert gw.wait_for_workers(1, timeout_s=30.0)
        s, i = svc.topk_vectors(qv, k=10)        # torn -> local fallback
        assert np.array_equal(s, base_s) and np.array_equal(i, base_i)
        assert evil_done.wait(5.0)
        t.join(timeout=5.0)
        lost = [e for e in svc.registry.events()
                if e["event"] == "worker_lost"]
        assert lost and "torn" in lost[-1]["attrs"]["reason"]
        assert gw.stats()["rpc_fallbacks"] >= 1
        # the gateway keeps serving (now wholly local)
        s2, i2 = svc.topk_vectors(qv, k=10)
        assert np.array_equal(i2, base_i)
    finally:
        gw.close()
        svc.close()


def test_hedge_fires_to_sibling_after_quantile(net_store, mesh):
    """Hedged fan-out: once the latency history is warm, a primary that
    turns slow trips a hedge to the sibling at the quantile point — the
    fast answer wins, results stay identical, serve.hedge_fired moves,
    and the hedge_fired event carries the topology."""
    from dnn_page_vectors_tpu.infer.partition_host import WorkerGateway
    svc = _service(net_store, mesh, partitions=1, replicas=2,
                   hedge_quantile=0.5)
    qv = _qv(2)
    base_s, base_i = svc.topk_vectors(qv, k=10)
    gw = WorkerGateway(svc, heartbeat_s=0.25)
    svc.attach_gateway(gw)
    workers = []
    try:
        for r in range(2):
            workers.append(_thread_worker(svc.cfg, net_store.directory,
                                          gw.port, 0, 1, r, mesh))
        assert gw.wait_for_workers(2, timeout_s=30.0)
        for _ in range(10):                   # warm the latency history
            s, i = svc.topk_vectors(qv, k=10)
            assert np.array_equal(i, base_i)
        # at quantile 0.5 the hedge delay sits within scheduler noise of
        # the healthy ~2 ms latency on a loaded 1-core box, so a warm-up
        # query may legitimately hedge; the pin is that the DELIBERATELY
        # slow primary below adds exactly one more, not that noise never
        # trips the quantile
        warm_hedges = svc.hedge_fires
        assert gw._hedge_delay_s(0) is not None
        workers[0][0].slow_ms = 300.0         # the primary turns slow
        t0 = time.perf_counter()
        s, i = svc.topk_vectors(qv, k=10)
        dt = time.perf_counter() - t0
        assert np.array_equal(s, base_s) and np.array_equal(i, base_i)
        assert svc.hedge_fires == warm_hedges + 1
        assert dt < 0.28, f"hedge did not save the call ({dt * 1e3:.0f} ms)"
        evs = [e for e in svc.registry.events()
               if e["event"] == "hedge_fired"]
        assert evs and evs[-1]["attrs"]["partition"] == 0
        assert evs[-1]["attrs"]["to_replica"] == 1
        assert svc.metrics()["transport"]["hedge_fires"] == warm_hedges + 1
    finally:
        for w, _ in workers:
            w.stop()
        gw.close()
        svc.close()


def test_cli_partition_worker_subprocess(net_store, mesh):
    """The production shape: `cli partition-worker` as a REAL process —
    registers over the socket, serves its slice byte-identically, and a
    kill -9 is detected as worker_lost with local-fallback continuity."""
    import os
    import subprocess
    import sys

    from dnn_page_vectors_tpu.infer.partition_host import WorkerGateway
    svc = _service(net_store, mesh, partitions=2)
    qv = _qv(2)
    base_s, base_i = svc.topk_vectors(qv, k=10)
    gw = WorkerGateway(svc, heartbeat_s=0.3)
    svc.attach_gateway(gw)
    workdir = os.path.dirname(net_store.directory)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    try:
        for p in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "dnn_page_vectors_tpu.cli",
                 "partition-worker", "--config", "cdssm_toy",
                 "--workdir", workdir, "--set", f"model.out_dim={DIM}",
                 "--connect", f"127.0.0.1:{gw.port}",
                 "--partition", str(p), "--partitions", "2"],
                cwd=repo, env=dict(os.environ, JAX_PLATFORMS="cpu"),
                stdout=subprocess.PIPE, text=True))
        assert gw.wait_for_workers(2, timeout_s=120.0), \
            "partition-worker subprocesses never registered"
        ready = json.loads(procs[0].stdout.readline())
        assert ready["partition_worker"] == 0 and ready["partitions"] == 2
        s, i = svc.topk_vectors(qv, k=10)
        assert np.array_equal(s, base_s) and np.array_equal(i, base_i)
        assert gw.stats()["rpc_fallbacks"] == 0
        procs[0].kill()                       # a real SIGKILL
        t_kill = time.perf_counter()
        while gw.worker_alive(0, 0) and \
                time.perf_counter() - t_kill < 3.0:
            time.sleep(0.01)
        assert not gw.worker_alive(0, 0)
        s, i = svc.topk_vectors(qv, k=10)     # continuity via fallback
        assert np.array_equal(i, base_i)
        assert any(e["event"] == "worker_lost"
                   for e in svc.registry.events())
    finally:
        for pr in procs:
            pr.kill()
            pr.wait(timeout=10)
        gw.close()
        svc.close()


# ---------------------------------------------------------------------------
# compressed path end to end: byte identity, mixed fleets, refresh, drain
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("P,R", [(2, 1), (4, 1), (2, 2)])
def test_compressed_path_byte_identity(net_store, mesh, P, R):
    """THE acceptance pin, compressed edition: with wire compression
    negotiated fleet-wide, socket results stay byte-identical to the
    in-process scatter at every tested (P, R) — and the wire accounting
    proves compression actually engaged (raw-equivalent bytes > actual,
    zero fallbacks, every worker answering compressed)."""
    from dnn_page_vectors_tpu.infer.partition_host import WorkerGateway
    svc = _service(net_store, mesh, partitions=P, replicas=R)
    qv = _qv(3)
    base_s, base_i = svc.topk_vectors(qv, k=10)
    gw = WorkerGateway(svc, heartbeat_s=0.25)
    svc.attach_gateway(gw)
    workers = []
    try:
        for p in range(P):
            for r in range(R):
                workers.append(_thread_worker(
                    svc.cfg, net_store.directory, gw.port, p, P, r, mesh))
        assert gw.wait_for_workers(P * R, timeout_s=60.0)
        for seed in (1, 2, 3):            # repeats exercise the REF path
            s, i = svc.topk_vectors(_qv(3, seed=seed), k=10)
            if seed == 1:
                assert np.array_equal(s, base_s)
                assert np.array_equal(i, base_i)
        st = gw.stats()
        assert st["rpc_fallbacks"] == 0
        assert st["workers_compressing"] == P * R
        assert svc.wire_raw_bytes > svc.wire_bytes
        met = svc.metrics()["transport"]
        assert met["wire_compression_ratio"] > 1.0
        assert met["wire_raw_bytes"] == svc.wire_raw_bytes
    finally:
        for w, _ in workers:
            w.stop()
        gw.close()
        svc.close()


def test_mixed_compressed_raw_fleet_interop(net_store, mesh):
    """Negotiation keeps a mixed fleet coherent: one worker advertises
    compression, its sibling partition runs raw (wire_compress off) —
    both register on one gateway, the scatter spans both, and results
    stay byte-identical to in-process."""
    import dataclasses

    from dnn_page_vectors_tpu.infer.partition_host import WorkerGateway
    svc = _service(net_store, mesh, partitions=2)
    qv = _qv(3)
    base_s, base_i = svc.topk_vectors(qv, k=10)
    gw = WorkerGateway(svc, heartbeat_s=0.25)
    svc.attach_gateway(gw)
    raw_cfg = svc.cfg.replace(serve=dataclasses.replace(
        svc.cfg.serve, wire_compress=False))
    workers = []
    try:
        workers.append(_thread_worker(svc.cfg, net_store.directory,
                                      gw.port, 0, 2, 0, mesh))
        workers.append(_thread_worker(raw_cfg, net_store.directory,
                                      gw.port, 1, 2, 0, mesh))
        assert gw.wait_for_workers(2, timeout_s=60.0)
        for _ in range(3):
            s, i = svc.topk_vectors(qv, k=10)
            assert np.array_equal(s, base_s)
            assert np.array_equal(i, base_i)
        st = gw.stats()
        assert st["rpc_fallbacks"] == 0 and st["workers_live"] == 2
        assert st["workers_compressing"] == 1      # the mixed fleet
        reg = {(e["attrs"]["partition"], e["attrs"]["wire_compress"])
               for e in svc.registry.events()
               if e["event"] == "worker_registered"}
        assert reg == {(0, True), (1, False)}
    finally:
        for w, _ in workers:
            w.stop()
        gw.close()
        svc.close()


@pytest.mark.parametrize("P", [1, 2])
def test_refresh_control_frame_no_worker_restart(tmp_path, mesh, P):
    """ROADMAP item 1 residue: a store generation swap reaches the wire
    fleet as a T_REFRESH control frame — the worker re-opens the store,
    rebuilds its restricted view, acks the generation it now serves, and
    answers byte-identically to a freshly RESTARTED worker, with no
    restart. Until the ack lands, routing treats the worker as
    generation-stale and serves its slice locally, so results never mix
    generations across the wire. P=1 exercises the single-view service
    whose gateway owns its private 1-partition set (that table must
    follow the refresh too)."""
    from dnn_page_vectors_tpu.infer.partition_host import WorkerGateway
    from dnn_page_vectors_tpu.infer.vector_store import VectorStore
    sdir = str(tmp_path / "store")
    rng = np.random.default_rng(3)
    store = VectorStore(sdir, dim=DIM, shard_size=SHARD)
    for si in range(4):
        v = rng.standard_normal((SHARD, DIM)).astype(np.float32)
        v /= np.linalg.norm(v, axis=1, keepdims=True)
        store.write_shard(si, np.arange(si * SHARD, (si + 1) * SHARD,
                                        dtype=np.int64), v)
    store = VectorStore(sdir)
    svc = _service(store, mesh, partitions=P)
    qv = _qv(2)
    gw = WorkerGateway(svc, heartbeat_s=0.25)
    svc.attach_gateway(gw)
    workers = []
    try:
        for p in range(P):
            workers.append(_thread_worker(svc.cfg, sdir, gw.port, p, P, 0,
                                          mesh))
        assert gw.wait_for_workers(P, timeout_s=60.0)
        s0, i0 = svc.topk_vectors(qv, k=10)
        rpcs0 = gw.stats()["rpcs"]
        assert rpcs0 >= P and gw.stats()["rpc_fallbacks"] == 0
        # the store appends a generation behind the fleet's back ...
        grow = VectorStore(sdir)
        writer = grow.begin_generation()
        start = grow.next_page_id()
        v = rng.standard_normal((SHARD, DIM)).astype(np.float32)
        v /= np.linalg.norm(v, axis=1, keepdims=True)
        writer.write_shard(np.arange(start, start + SHARD,
                                     dtype=np.int64), v)
        writer.commit()
        # ... refresh() swaps the front end AND broadcasts T_REFRESH
        info = svc.refresh()
        assert info["workers_refresh"]["workers_told"] == P
        new_gen = svc._view.generation
        assert gw.wait_for_generation(new_gen, timeout_s=60.0), \
            "workers never acked the refreshed generation"
        s1, i1 = svc.topk_vectors(qv, k=10)
        rpcs1 = gw.stats()["rpcs"]
        assert rpcs1 > rpcs0, "post-refresh queries stopped using workers"
        assert gw.stats()["rpc_fallbacks"] == 0
        # the restarted-worker oracle: a FRESH service over the grown
        # store is what a restarted worker would serve by construction
        oracle = _service(VectorStore(sdir), mesh, partitions=P)
        try:
            so, io = oracle.topk_vectors(qv, k=10)
        finally:
            oracle.close()
        assert np.array_equal(s1, so) and np.array_equal(i1, io)
        evs = [e for e in svc.registry.events()
               if e["event"] == "worker_refreshed"]
        assert len(evs) >= P
        assert all(e["attrs"]["generation"] == new_gen for e in evs[-P:])
    finally:
        for w, _ in workers:
            w.stop()
        gw.close()
        svc.close()


def test_graceful_drain_finishes_inflight_sheds_new(net_store, mesh):
    """serve.listen close path: an in-flight request FINISHES and gets
    its result; a request arriving while draining is shed with reason
    "draining" (counted in serve.deadline_shed, never an error, never a
    dropped socket mid-frame)."""
    from dnn_page_vectors_tpu.infer.server import serve_in_background
    svc = _service(net_store, mesh)
    srv = serve_in_background(svc)
    hold = threading.Event()
    entered = threading.Event()
    real_topk = svc.topk_vectors

    def slow_topk(qv, **kw):
        entered.set()
        hold.wait(10.0)
        return real_topk(qv, **kw)

    svc.topk_vectors = slow_topk
    c1 = SocketSearchClient(srv.host, srv.port)
    c2 = SocketSearchClient(srv.host, srv.port)
    qv = _qv(2)
    result = {}
    try:
        hold.set()                        # connection warm-up passes
        c2.topk_vectors(qv, k=10)
        hold.clear()
        entered.clear()                   # the warmup tripped it too

        def inflight():
            result["out"] = c1.topk_vectors(qv, k=10)

        t1 = threading.Thread(target=inflight)
        t1.start()
        assert entered.wait(10.0)         # request 1 is mid-dispatch
        closer = threading.Thread(target=lambda: srv.close(drain_s=10.0))
        closer.start()
        deadline = time.perf_counter() + 5.0
        while not srv._draining and time.perf_counter() < deadline:
            time.sleep(0.005)
        assert srv._draining
        with pytest.raises(DeadlineExceeded, match="draining"):
            c2.topk_vectors(qv, k=10)     # fresh request -> clean shed
        hold.set()                        # let the in-flight one finish
        t1.join(timeout=10.0)
        closer.join(timeout=15.0)
        s, i, _ = result["out"]           # ... and it answered normally
        base_s, base_i = real_topk(qv, k=10)
        assert np.array_equal(s, base_s) and np.array_equal(i, base_i)
        assert svc.deadline_sheds >= 1 and svc._m_errors.value == 0
        evs = [e for e in svc.registry.events()
               if e["event"] == "deadline_shed"]
        assert evs[-1]["attrs"]["reason"] == "draining"
    finally:
        hold.set()
        svc.topk_vectors = real_topk
        c1.close()
        c2.close()
        srv.close()
        svc.close()


# ---------------------------------------------------------------------------
# loadgen over the wire + report-shape stability
# ---------------------------------------------------------------------------

def test_run_trial_over_socket_carries_transport_block(net_store, mesh):
    from dnn_page_vectors_tpu.infer.server import serve_in_background
    from dnn_page_vectors_tpu.loadgen import (
        make_workload, run_trial, snapshot_line)
    svc = _service(net_store, mesh)
    svc.start_batcher()
    srv = serve_in_background(svc)
    client = SocketSearchClient(srv.host, srv.port)
    queries = [f"query {i}" for i in range(8)]
    wl = make_workload("poisson", seed=3, distinct=8)
    try:
        tr = run_trial(svc, wl, 50.0, queries, duration_s=0.6,
                       warmup_s=0.2, workers=8, client=client)
        assert tr["errors"] == 0 and tr["requests_sent"] > 0
        assert tr["transport"]["wire_bytes"] > 0
        line = json.loads(snapshot_line(svc))
        assert line["wire_bytes"] > 0
    finally:
        client.close()
        srv.close()
        svc.close()


def test_span_tree_starts_at_socket_and_crosses_rpc_hop(net_store, mesh):
    """Tracing through the transport (docs/OBSERVABILITY.md): a request
    arriving over the wire records ONE span tree rooted at the socket,
    with the executor hand-off, the scatter, and the per-partition RPC
    spans nested under it."""
    from dnn_page_vectors_tpu.infer.partition_host import WorkerGateway
    from dnn_page_vectors_tpu.infer.server import serve_in_background
    svc = _service(net_store, mesh, partitions=2)
    gw = WorkerGateway(svc, heartbeat_s=0.25)
    svc.attach_gateway(gw)
    workers = []
    srv = serve_in_background(svc)
    client = SocketSearchClient(srv.host, srv.port)
    try:
        for p in range(2):
            workers.append(_thread_worker(svc.cfg, net_store.directory,
                                          gw.port, p, 2, 0, mesh))
        assert gw.wait_for_workers(2, timeout_s=30.0)
        client.topk_vectors(_qv(2), k=10)
        # the client thread can observe its response a hair before the
        # server coroutine exits the root span: poll, don't race
        trace = None
        for _ in range(200):
            trace = svc.tracer.last_trace()
            if trace is not None:
                break
            time.sleep(0.005)
        assert trace["name"] == "socket"
        assert trace["attrs"]["protocol"] == "vquery"

        def names(d):
            out = [d["name"]]
            for c in d["children"]:
                out.extend(names(c))
            return out

        got = names(trace)
        assert "scatter" in got and "merge" in got
        assert got.count("rpc") == 2          # one RPC hop per partition
    finally:
        client.close()
        srv.close()
        for w, _ in workers:
            w.stop()
        gw.close()
        svc.close()


def test_inprocess_records_stay_byte_stable(net_store, mesh):
    """The satellite pin: without a transport, metrics(), trial records,
    and snapshot lines carry NO transport block — their shape is
    byte-identical to the pre-transport format."""
    from dnn_page_vectors_tpu.loadgen import (
        make_workload, run_trial, snapshot_line)
    svc = _service(net_store, mesh)
    try:
        assert "transport" not in svc.metrics()
        wl = make_workload("poisson", seed=1, distinct=4)
        tr = run_trial(svc, wl, 30.0, ["a", "b", "c", "d"],
                       duration_s=0.3, warmup_s=0.0, workers=2)
        assert "transport" not in tr
        line = json.loads(snapshot_line(svc))
        for key in ("wire_bytes", "deadline_sheds", "hedge_fires",
                    "workers_live"):
            assert key not in line
    finally:
        svc.close()
