"""Flash vs dense attention must agree through the whole transformer tower
(same params, f32): the kernel is a drop-in swap behind model.attention."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.models.factory import build_two_tower


@pytest.mark.parametrize("encoder", ["bert", "t5"])
@pytest.mark.slow
def test_flash_transformer_matches_dense(encoder):
    name = {"bert": "bert_mini_v5p16", "t5": "mt5_multilingual"}[encoder]
    base = {
        "model.num_layers": 2, "model.model_dim": 64, "model.num_heads": 4,
        "model.mlp_dim": 128, "model.out_dim": 32, "model.dropout": 0.0,
        "model.dtype": "float32",
    }
    cfg_d = get_config(name, {**base, "model.attention": "dense"})
    cfg_f = get_config(name, {**base, "model.attention": "flash"})
    dense = build_two_tower(cfg_d, vocab_size=64)
    flash = build_two_tower(cfg_f, vocab_size=64)

    rng = np.random.default_rng(0)
    B, L = 4, cfg_d.data.page_len
    ids = rng.integers(1, 64, size=(B, L)).astype(np.int32)
    ids[:, -7:] = 0  # padding tail
    ids = jnp.asarray(ids)
    q_ids = jnp.asarray(rng.integers(1, 64, size=(B, cfg_d.data.query_len)),
                        jnp.int32)

    params = dense.init(jax.random.PRNGKey(0), q_ids, ids)
    out_d = dense.apply(params, ids, method="encode_page")
    out_f = flash.apply(params, ids, method="encode_page")  # same params
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_f),
                               rtol=2e-4, atol=2e-5)

    # gradients flow through the kernel's custom VJP identically
    def loss(model):
        def f(p):
            return (model.apply(p, ids, method="encode_page") ** 2).sum()
        return f

    gd = jax.grad(loss(dense))(params)
    gf = jax.grad(loss(flash))(params)
    for a, b in zip(jax.tree_util.tree_leaves(gd),
                    jax.tree_util.tree_leaves(gf)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)
