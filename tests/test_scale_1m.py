"""1M-page scale demonstration (VERDICT r4 Missing #1 / next-round #1).

The configs claim 1M/100M pages (BASELINE.md:21-24) but nothing had ever
run beyond ~100k toy pages, and nothing had ever exercised the production
text -> tokenize -> device -> store path at scale. This test materializes a
REAL 1,000,000-page jsonl corpus on disk (data/synth.py, indexed by the C++
line-offset index), trains briefly, bulk-embeds ALL 1M pages from text
through the store, and evals Recall@10 over the 1M-page store — the full
call-stack §4.1-4.3 loop at 10x the previous largest corpus and 800x the
previous largest e2e test.

Runtime budget: generation ~20 s, embed ~35 s on the 8-fake-device CPU
mesh (~33k pages/s measured), eval streams all 16 store shards; ~2-3 min
total, slow-marked.

Training runs on a SINGLE fake device while embed/eval run on the
8-device mesh. This is deliberate, not a shortcut: the sandbox host has
ONE physical core, and XLA:CPU's collective rendezvous spin-waits — with
8 device threads timesharing one core, any program whose pre-collective
compute window is long (the 512-row DP train step here) starves the last
partitions past the 40 s rendezvous termination and aborts the process.
The bulk-embed path has NO collectives (row-local encode) and the
sharded top-k's windows are one 8k-row chunk (~ms), so the SCALE path —
the thing this test demonstrates — runs fully sharded. DP/TP train
equality at realistic windows is pinned by tests/test_parallel.py.
"""
import os

import numpy as np
import pytest

from dnn_page_vectors_tpu.config import MeshConfig, get_config
from dnn_page_vectors_tpu.data.jsonl import JsonlCorpus
from dnn_page_vectors_tpu.data.synth import write_synth_jsonl
from dnn_page_vectors_tpu.evals.recall import evaluate_recall
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.parallel.mesh import make_mesh
from dnn_page_vectors_tpu.train.loop import Trainer

N_PAGES = 1_000_000


@pytest.mark.slow
def test_one_million_pages_end_to_end(tmp_path, eight_devices):
    path = str(tmp_path / "corpus_1m.jsonl")
    write_synth_jsonl(path, N_PAGES, seed=11, page_len=32, query_len=8)
    corpus = JsonlCorpus(path)
    assert corpus.num_pages == N_PAGES

    cfg = get_config("cdssm_toy", {
        "data.corpus": f"jsonl:{path}",
        "data.num_pages": N_PAGES,
        "data.trigram_buckets": 16_384,
        "data.page_len": 32,
        "model.embed_dim": 48,
        "model.conv_channels": 96,
        "model.out_dim": 48,
        "train.batch_size": 512,
        # single-epoch regime (0.3 epochs over 1M pages): recall comes from
        # GENERALIZED trigram overlap, not memorization; lr swept at 100k
        # scale (5e-3 -> recall .67 vs .13 at 2e-3, 600 steps)
        "train.steps": 600,
        "train.warmup_steps": 20,
        "train.learning_rate": 5e-3,
        "train.log_every": 1000,
        "eval.embed_batch_size": 512,
        "eval.store_shard_size": 65_536,
        "mesh.data": 1,          # see module docstring: 1-core rendezvous
    })
    trainer = Trainer(cfg, workdir=str(tmp_path))
    state, _ = trainer.train()

    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       make_mesh(MeshConfig(data=8)),
                       query_tok=trainer.query_tok)
    store = VectorStore(os.path.join(str(tmp_path), "store"),
                        dim=cfg.model.out_dim,
                        shard_size=cfg.eval.store_shard_size)
    emb.embed_corpus(trainer.corpus, store)
    assert store.num_vectors == N_PAGES
    assert len(store.shards()) == -(-N_PAGES // 65_536)     # 16 shards

    # Recall@10 among 1M candidates: random is 1e-5; the briefly-trained
    # trigram model must put the gold page in the top 10 for a large
    # fraction of queries (the lexical key-word signal, data/synth.py).
    recall, nq = evaluate_recall(emb, trainer.corpus, store,
                                 num_queries=512, k=10)
    assert nq == 512
    assert recall > 0.2, f"recall@10 {recall} barely above random at 1M scale"

    # resume invariant holds at scale: a second sweep is a manifest no-op
    # (every shard already recorded), not a re-embed
    import time
    t0 = time.perf_counter()
    emb.embed_corpus(trainer.corpus, store)
    assert time.perf_counter() - t0 < 5.0
    assert store.num_vectors == N_PAGES
