"""Filtered retrieval (docs/ANN.md "Filtered retrieval"): the per-row
attribute substrate and the predicate-intersected scan must be an
OPTIMIZATION over post-filtering, never a different answer — filtered
results byte-identical to the single-process filtered oracle at every
tested topology (local, P=2/R=2 in-process, socket server, and the
2-front-end gateway fleet), the predicate codec surviving reject fuzz,
attributes riding append -> compact -> migrate unchanged, the
under-filled-probe escalation draining more lists instead of returning
short, the no-negotiation degrade (a non-filtering worker is simply
unroutable for filtered requests — the gateway's local filtered view
answers, never wrong results), and the result cache keying on the
canonical predicate so a filtered hit never serves an unfiltered
entry."""
import threading

import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.index import attrs as A
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.utils import faults, telemetry

pytestmark = pytest.mark.filt

DIM = 32
SHARD = 50
NSHARDS = 6
ROWS = SHARD * NSHARDS

# predicate arms pinned to the fixture's attribute layout below:
# lang==1 keeps 1/2 the rows, site in {0} keeps 1/10, recency>=3 keeps
# the 6 planted rows (one per shard)
ARMS = (("lang==1", 0.5), ("site in {0}", 0.1), ("recency>=3", 0.02))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    telemetry.reset_default()
    yield
    faults.reset()
    telemetry.reset_default()


def _words(n=ROWS):
    ids = np.arange(n)
    return A.pack_words(lang=(ids % 2).astype(np.uint32),
                        site=(ids % 10).astype(np.uint32),
                        recency=np.where(ids % SHARD == 0, 3,
                                         0).astype(np.uint32))


@pytest.fixture(scope="module")
def attr_store(tmp_path_factory):
    """Synthetic 6-shard store with one packed attribute word per row."""
    sdir = str(tmp_path_factory.mktemp("filtered_store") / "store")
    rng = np.random.default_rng(0)
    store = VectorStore(sdir, dim=DIM, shard_size=SHARD)
    store.ensure_model_step(0)
    store.init_attrs()
    words = _words()
    for si in range(NSHARDS):
        lo, hi = si * SHARD, (si + 1) * SHARD
        v = rng.standard_normal((SHARD, DIM)).astype(np.float32)
        v /= np.linalg.norm(v, axis=1, keepdims=True)
        store.write_shard(si, np.arange(lo, hi, dtype=np.int64), v,
                          attrs=words[lo:hi])
    return VectorStore(sdir)


@pytest.fixture(scope="module")
def mesh():
    import jax
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:1]), ("data",))


def _qv(n=3, seed=1):
    rng = np.random.default_rng(seed)
    q = rng.standard_normal((n, DIM)).astype(np.float32)
    return q / np.linalg.norm(q, axis=1, keepdims=True)


def _fake_embed(queries):
    out = np.zeros((len(queries), DIM), np.float32)
    for i, q in enumerate(queries):
        r = np.random.default_rng(
            np.frombuffer(q.encode()[:8].ljust(8, b"\0"),
                          np.uint64)[0] % (2 ** 32))
        v = r.standard_normal(DIM).astype(np.float32)
        out[i] = v / np.linalg.norm(v)
    return out


class _StubCorpus:
    def page_text(self, i):
        return f"page {i}"


def _service(store, mesh, **serve_over):
    import dataclasses

    from dnn_page_vectors_tpu.infer.partition_host import MeshEmbedder
    from dnn_page_vectors_tpu.infer.serve import SearchService
    cfg = get_config("cdssm_toy", {"model.out_dim": DIM})
    if serve_over:
        cfg = cfg.replace(serve=dataclasses.replace(cfg.serve,
                                                    **serve_over))
    svc = SearchService(cfg, MeshEmbedder(mesh), None, store,
                        preload_hbm_gb=4.0)
    svc._embed_queries_cached = _fake_embed
    svc.corpus = _StubCorpus()
    return svc


def _oracle(store, qv, words, pred, k=10):
    """Exact post-filter top-k over the DEQUANTIZED store rows (the
    store holds fp16 — comparing against the fp32 originals would
    charge quantization error to the filter)."""
    deq = np.concatenate([store._load_entry(e)[1] for e in store.shards()])
    sc = qv @ np.asarray(deq, np.float32).T
    keep = pred.matches(words)
    sc[:, ~keep] = -np.inf
    order = np.argsort(-sc, axis=1)[:, :k]
    s = np.take_along_axis(sc, order, axis=1).astype(np.float32)
    ids = order.astype(np.int64)
    ids[~np.isfinite(s)] = -1
    s[~np.isfinite(s)] = -np.inf
    return s, ids


# ---------------------------------------------------------------------------
# attribute word + predicate codec
# ---------------------------------------------------------------------------

def test_attr_word_codec_roundtrip():
    rng = np.random.default_rng(7)
    for _ in range(200):
        lang = int(rng.integers(0, A.LANG_MAX + 1))
        site = int(rng.integers(0, A.SITE_MAX + 1))
        rec = int(rng.integers(0, A.REC_MAX + 1))
        assert A.unpack_word(A.pack_word(lang=lang, site=site,
                                         recency=rec)) == (lang, site, rec)
    # vectorized pack == the scalar loop, little-endian on disk
    langs = rng.integers(0, A.LANG_MAX + 1, 64).astype(np.uint32)
    sites = rng.integers(0, A.SITE_MAX + 1, 64).astype(np.uint32)
    recs = rng.integers(0, A.REC_MAX + 1, 64).astype(np.uint32)
    vec = A.pack_words(lang=langs, site=sites, recency=recs)
    assert vec.dtype == A.ATTR_DTYPE
    assert [int(x) for x in vec] == [
        A.pack_word(lang=int(a), site=int(b), recency=int(c))
        for a, b, c in zip(langs, sites, recs)]
    # a site NAME hashes to a stable bucket; ints pass through
    assert A.site_bucket("example.org") == A.site_bucket("example.org")
    assert A.site_bucket(123) == 123
    assert A.pack_word(site="example.org") == A.pack_word(
        site=A.site_bucket("example.org"))
    with pytest.raises(A.FilterError):
        A.pack_word(lang=A.LANG_MAX + 1)
    with pytest.raises(A.FilterError):
        A.pack_words(lang=np.array([0]), site=np.array([A.SITE_MAX + 1]),
                     recency=np.array([0]))


def test_predicate_canonical_form_and_eval():
    # term order and whitespace never change the canonical text
    p1 = A.Predicate.parse("site in {3, 1} & lang==2 & recency >= 1")
    p2 = A.Predicate.parse("recency>=1&lang == 2&site in {1,3}")
    assert p1.text == p2.text
    words = A.pack_words(
        lang=np.array([2, 2, 1, 2], np.uint32),
        site=np.array([1, 5, 3, 3], np.uint32),
        recency=np.array([1, 3, 2, 0], np.uint32))
    assert list(p1.matches(words)) == [True, False, False, False]
    # host and device evaluation agree bit for bit
    import jax.numpy as jnp
    dev = np.asarray(p1.matches_device(jnp.asarray(words)))
    assert list(dev) == list(p1.matches(words))
    # recency>=B is a lower bound, not equality
    pr = A.Predicate.parse("recency>=2")
    assert list(pr.matches(words)) == [False, True, True, False]


def test_predicate_codec_roundtrip_and_reject_fuzz():
    for text, _ in ARMS + (("lang==2 & site in {1,example.org} "
                            "& recency>=1", 0),):
        p = A.Predicate.parse(text)
        q = A.decode_predicate(p.encode())
        assert q.text == p.text
        words = _words(100)
        assert list(q.matches(words)) == list(p.matches(words))
    bad = ["", "lang", "lang==", "lang==999", "bogus==1", "site in {",
           "site in 3", "recency>=99", "lang==1 &", "lang=1",
           "site in {" + ",".join(map(str, range(65))) + "}",
           " & ".join(["lang==1"] * 17), "x" * 600]
    for text in bad:
        with pytest.raises(A.FilterError):
            A.Predicate.parse(text)
    # wire bytes: oversize + seeded garbage must raise FilterError,
    # never hang or leak a different exception type
    with pytest.raises(A.FilterError):
        A.decode_predicate(b"x" * (A.MAX_PREDICATE_BYTES + 1))
    rng = np.random.default_rng(11)
    for _ in range(200):
        blob = rng.integers(0, 256, int(rng.integers(0, 80))).astype(
            np.uint8).tobytes()
        try:
            A.decode_predicate(blob)
        except A.FilterError:
            pass


def test_parse_attr_assignments():
    w = A.parse_attr_assignments(["lang=3", "site=wiki.org", "recency=2"])
    assert A.unpack_word(w) == (3, A.site_bucket("wiki.org"), 2)
    assert A.parse_attr_assignments(["site=7"]) == A.pack_word(site=7)
    for bad in (["tag=1"], ["lang"], ["lang=x"], ["recency=99"]):
        with pytest.raises(A.FilterError):
            A.parse_attr_assignments(bad)


# ---------------------------------------------------------------------------
# filtered exact path: oracle identity + the scan-bytes contract
# ---------------------------------------------------------------------------

def test_filtered_exact_matches_post_filter_oracle(attr_store, mesh):
    svc = _service(attr_store, mesh)
    words = _words()
    qv = _qv(4, seed=3)
    try:
        for text, _sel in ARMS:
            pred = A.Predicate.parse(text)
            os_, oi = _oracle(attr_store, qv, words, pred)
            s, ids = svc.topk_vectors(qv, k=10, filters=text)
            assert np.array_equal(ids, oi), text
            np.testing.assert_allclose(s, os_, rtol=1e-5)
            # every served row satisfies the predicate
            for row in ids:
                live = row[row >= 0]
                assert pred.matches(words[live]).all()
        # the text path records one filtered_query event per dispatch
        res = svc.search("event probe", k=5, filters="lang==1")
        for r in res:
            assert A.unpack_word(words[r["page_id"]])[0] == 1
        ev = svc.registry.events("filtered_query")
        assert ev and ev[-1]["attrs"]["predicate"] == "lang==1"
    finally:
        svc.close()


def test_filtered_scan_bytes_contract(attr_store, mesh):
    """The acceptance gate: at selectivity 0.1 the filtered exact scan
    reads <= 0.3x the unfiltered exact bytes (attr words + matching
    rows only), and scan bytes scale DOWN with selectivity."""
    svc = _service(attr_store, mesh)
    qv = _qv(1, seed=5)
    try:
        _, _, base = svc._topk_view(svc._view, qv, 1, 10, None)
        scans = {}
        for text, sel in ARMS:
            _, _, sb = svc._topk_view(svc._view, qv, 1, 10, None,
                                      predicate=A.Predicate.parse(text))
            scans[sel] = sb
            assert 0 < sb < base
        assert scans[0.1] <= 0.3 * base
        assert scans[0.02] < scans[0.1] < scans[0.5]
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# IVF: predicate intersection before ADC + drain-more-lists escalation
# ---------------------------------------------------------------------------

def test_ivf_filtered_recall_contract(attr_store, mesh):
    from dnn_page_vectors_tpu.index.ivf import IVFIndex
    idx = IVFIndex.build(attr_store, mesh, nlist=8, iters=5, seed=0)
    words = _words()
    qv = _qv(4, seed=3)
    for text, _sel in ARMS:
        pred = A.Predicate.parse(text)
        _, oi = _oracle(attr_store, qv, words, pred)
        _, ids, _ = idx.search(qv, 10, nprobe=8, predicate=pred)
        for q in range(qv.shape[0]):
            want = set(int(x) for x in oi[q] if x >= 0)
            got = set(int(x) for x in ids[q] if x >= 0)
            # full probe: the filtered gather covers every list, so the
            # >=0.95 recall contract must hold with room to spare
            assert len(got & want) >= 0.95 * len(want), text
            assert pred.matches(words[list(got)]).all()


def test_ivf_underfilled_probe_escalates(attr_store, mesh):
    """A selective predicate under a narrow probe must drain more lists
    (counted) instead of returning a short result set."""
    from dnn_page_vectors_tpu.index.ivf import IVFIndex
    idx = IVFIndex.build(attr_store, mesh, nlist=8, iters=5, seed=0)
    words = _words()
    pred = A.Predicate.parse("recency>=3")        # 6 rows in 300
    qv = _qv(3, seed=9)
    _, ids, st = idx.search(qv, 4, nprobe=1, predicate=pred)
    assert st.get("filter_escalations", 0) > 0
    want = set(int(x) for x in np.nonzero(pred.matches(words))[0])
    for q in range(3):
        got = [int(x) for x in ids[q] if x >= 0]
        assert got and set(got) <= want
    assert telemetry.default_registry().counter(
        "ivf.filter_escalations").value > 0


# ---------------------------------------------------------------------------
# byte identity across topologies vs the single-process filtered oracle
# ---------------------------------------------------------------------------

def test_filtered_byte_identity_partitioned_and_socket(attr_store, mesh):
    from dnn_page_vectors_tpu.infer.server import serve_in_background
    from dnn_page_vectors_tpu.infer.transport import SocketSearchClient
    qv = _qv(6, seed=13)
    svc1 = _service(attr_store, mesh)
    base = {t: svc1.topk_vectors(qv, k=10, filters=t) for t, _ in ARMS}
    svcp = _service(attr_store, mesh, partitions=2, replicas=2)
    srv_svc = _service(attr_store, mesh)
    srv = serve_in_background(srv_svc)
    client = SocketSearchClient(srv.host, srv.port)
    try:
        assert svcp.partition_set is not None
        for text, _ in ARMS:
            bs, bi = base[text]
            ps, pi = svcp.topk_vectors(qv, k=10, filters=text)
            assert np.array_equal(pi, bi), f"P=2 R=2 {text}"
            assert np.array_equal(ps, bs)
            ws, wi, _ = client.topk_vectors(qv, k=10, filters=text)
            assert np.array_equal(wi, bi), f"socket {text}"
            assert np.array_equal(ws, bs)
    finally:
        client.close()
        srv.close()
        srv_svc.close()
        svcp.close()
        svc1.close()


def test_filtered_byte_identity_two_front_ends(attr_store, mesh):
    """2 front ends x (P=2, R=2) over one shared worker fleet: every
    filtered answer byte-identical to the single-process filtered
    oracle captured before any gateway attached."""
    from dnn_page_vectors_tpu.infer.partition_host import (PartitionWorker,
                                                           WorkerGateway)
    over = dict(partitions=2, replicas=2, heartbeat_s=0.5)
    qv = _qv(4, seed=17)
    svc0 = _service(attr_store, mesh, **over)
    oracle = {t: svc0.topk_vectors(qv, k=10, filters=t) for t, _ in ARMS}
    svc1 = _service(attr_store, mesh, **over)
    gw0 = WorkerGateway(svc0, heartbeat_s=0.5)
    svc0.attach_gateway(gw0)
    gw1 = WorkerGateway(svc1, heartbeat_s=0.5)
    svc1.attach_gateway(gw1)
    cfg = get_config("cdssm_toy", {"model.out_dim": DIM,
                                   "serve.partitions": 2,
                                   "serve.replicas": 2})
    workers = []
    try:
        for p in range(2):
            for r in range(2):
                w = PartitionWorker(
                    cfg, attr_store.directory,
                    [("127.0.0.1", gw0.port), ("127.0.0.1", gw1.port)],
                    partition=p, partitions=2, replica=r, mesh=mesh)
                threading.Thread(target=w.run, daemon=True).start()
                workers.append(w)
        assert gw0.wait_for_workers(4, timeout_s=60.0)
        assert gw1.wait_for_workers(4, timeout_s=60.0)
        assert gw0.stats()["workers_filtering"] == 4
        for text, _ in ARMS:
            bs, bi = oracle[text]
            for svc in (svc0, svc1):
                s, ids = svc.topk_vectors(qv, k=10, filters=text)
                assert np.array_equal(ids, bi), text
                assert np.array_equal(s, bs)
    finally:
        for w in workers:
            w.stop()
        gw0.close()
        gw1.close()
        svc0.close()
        svc1.close()


# ---------------------------------------------------------------------------
# no-negotiation degrade: old peers never produce wrong results
# ---------------------------------------------------------------------------

def test_non_filtering_worker_unroutable_gateway_serves_locally(
        attr_store, mesh):
    """A worker that did not negotiate FLAG_FILTERS (serve.filters off —
    the pre-attrs build) is simply not a candidate for filtered
    requests: the gateway's own filtered view answers its partition,
    byte-identical to the local oracle — never unfiltered results."""
    from dnn_page_vectors_tpu.infer.partition_host import (PartitionWorker,
                                                           WorkerGateway)
    import dataclasses
    qv = _qv(3, seed=19)
    svc = _service(attr_store, mesh, partitions=2, replicas=1,
                   heartbeat_s=0.5)
    oracle = {t: svc.topk_vectors(qv, k=10, filters=t) for t, _ in ARMS}
    unf_oracle = svc.topk_vectors(qv, k=10)
    gw = WorkerGateway(svc, heartbeat_s=0.5)
    svc.attach_gateway(gw)
    cfg = get_config("cdssm_toy", {"model.out_dim": DIM,
                                   "serve.partitions": 2})
    old_cfg = cfg.replace(serve=dataclasses.replace(cfg.serve,
                                                    filters=False))
    workers = []
    try:
        for p in range(2):
            w = PartitionWorker(old_cfg, attr_store.directory,
                                ("127.0.0.1", gw.port), partition=p,
                                partitions=2, replica=0, mesh=mesh)
            threading.Thread(target=w.run, daemon=True).start()
            workers.append(w)
        assert gw.wait_for_workers(2, timeout_s=60.0)
        assert gw.stats()["workers_filtering"] == 0
        for text, _ in ARMS:
            bs, bi = oracle[text]
            s, ids = svc.topk_vectors(qv, k=10, filters=text)
            assert np.array_equal(ids, bi), text
            assert np.array_equal(s, bs)
        # unfiltered requests still fan out to the legacy workers
        s, ids = svc.topk_vectors(qv, k=10)
        assert np.array_equal(ids, unf_oracle[1])
    finally:
        for w in workers:
            w.stop()
        gw.close()
        svc.close()


def test_socket_client_refuses_unnegotiated_filters(attr_store, mesh):
    """Against a server that never confirmed FLAG_FILTERS the client
    raises instead of silently serving unfiltered results; unfiltered
    requests on the same connection keep working."""
    from dnn_page_vectors_tpu.infer.server import serve_in_background
    from dnn_page_vectors_tpu.infer.transport import (RemoteError,
                                                      SocketSearchClient)
    svc = _service(attr_store, mesh, filters=False)
    srv = serve_in_background(svc)
    client = SocketSearchClient(srv.host, srv.port)
    qv = _qv(2, seed=23)
    try:
        s, ids, _ = client.topk_vectors(qv, k=10)       # negotiates HELLO
        base_s, base_i = svc.topk_vectors(qv, k=10)
        assert np.array_equal(ids, base_i)
        with pytest.raises(RemoteError):
            client.topk_vectors(qv, k=10, filters="lang==1")
        s2, i2, _ = client.topk_vectors(qv, k=10)       # still serving
        assert np.array_equal(i2, base_i)
    finally:
        client.close()
        srv.close()
        svc.close()


# ---------------------------------------------------------------------------
# result cache: the canonical predicate is part of the key
# ---------------------------------------------------------------------------

def test_result_cache_never_crosses_filter_boundary(attr_store, mesh):
    """An unfiltered entry must never serve a filtered request (or the
    reverse), on the local, partitioned, and socket paths. The planted
    check: the unfiltered top set contains lang==0 rows, so a filter
    crossover is observably wrong."""
    from dnn_page_vectors_tpu.infer.server import serve_in_background
    from dnn_page_vectors_tpu.infer.transport import SocketSearchClient
    words = _words()
    q = "query zero"
    for topo in ("local", "p2r2", "socket"):
        over = dict(result_cache=True)
        if topo == "p2r2":
            over.update(partitions=2, replicas=2)
        svc = _service(attr_store, mesh, **over)
        srv = client = None
        try:
            if topo == "socket":
                srv = serve_in_background(svc)
                client = SocketSearchClient(srv.host, srv.port)
                search = client.search
            else:
                search = svc.search
            unfiltered = search(q, k=10)
            assert any(A.unpack_word(words[r["page_id"]])[0] == 0
                       for r in unfiltered), "planted check needs lang==0"
            # same text, filtered: a cache crossover would replay the
            # unfiltered rows — every row must satisfy the predicate
            filtered = search(q, k=10, filters="lang==1")
            assert filtered and filtered != unfiltered
            for r in filtered:
                assert A.unpack_word(words[r["page_id"]])[0] == 1
            # the filtered entry is cached under its own key: a repeat
            # serves the SAME filtered rows, and the unfiltered entry
            # is untouched
            assert search(q, k=10, filters="lang==1") == filtered
            assert search(q, k=10) == unfiltered
            # canonical form keys the cache: a differently-spelled
            # equivalent predicate hits the same entry
            met0 = svc.metrics().get("result_cache") or {}
            assert search(q, k=10, filters=" lang == 1 ") == filtered
            met1 = svc.metrics().get("result_cache") or {}
            assert met1.get("hits", 0) > met0.get("hits", 0)
        finally:
            if client is not None:
                client.close()
            if srv is not None:
                srv.close()
            svc.close()


# ---------------------------------------------------------------------------
# attributes survive append -> compact -> migrate
# ---------------------------------------------------------------------------

def test_attrs_survive_append_compact_migrate(tmp_path, mesh):
    from dnn_page_vectors_tpu.maintenance.compact import compact_store
    from dnn_page_vectors_tpu.maintenance.migrate import migrate_store
    sdir = str(tmp_path / "store")
    rng = np.random.default_rng(2)
    store = VectorStore(sdir, dim=DIM, shard_size=SHARD)
    store.ensure_model_step(1)
    store.init_attrs()
    base_words = _words(2 * SHARD)
    for si in range(2):
        lo = si * SHARD
        v = rng.standard_normal((SHARD, DIM)).astype(np.float32)
        store.write_shard(si, np.arange(lo, lo + SHARD, dtype=np.int64),
                          v, attrs=base_words[lo:lo + SHARD])
    store = VectorStore(sdir)
    # append a generation carrying its own words + tombstone two rows
    new_ids = np.arange(100, 120, dtype=np.int64)
    new_words = A.pack_words(lang=np.full(20, 5, np.uint32),
                             site=np.full(20, 9, np.uint32),
                             recency=np.full(20, 2, np.uint32))
    w = store.begin_generation(tombstones=[3, 7])
    w.write_shard(new_ids, rng.standard_normal((20, DIM)).astype(
        np.float32), attrs=new_words)
    w.commit()
    store = VectorStore(sdir)
    expect = {int(i): int(wd) for i, wd in enumerate(base_words)}
    expect.update({int(i): int(wd) for i, wd in zip(new_ids, new_words)})
    for dead in (3, 7):
        expect.pop(dead)

    def _check(store, what):
        got = {}
        for e in store.shards():
            ids = store._load_entry(e)[0]
            for pid, wd in zip(ids, store.load_attrs(e)):
                if pid >= 0:        # tombstones mask to -1 at load
                    got[int(pid)] = int(wd)
        assert got == expect, what

    _check(store, "after append")
    stats = compact_store(store)
    assert stats.get("action") != "noop"
    store = VectorStore(sdir)
    _check(store, "after compact")

    class _Corpus:
        def page_text(self, i):
            return f"page {int(i)}"

    class _Embedder:
        step, params, mesh = 2, ("tower", 2), None
        query_tok = page_tok = None

        def embed_texts(self, texts, tower="page", batch_size=None):
            out = np.stack([np.random.default_rng(
                len(t)).standard_normal(DIM).astype(np.float32)
                for t in texts])
            return out / np.linalg.norm(out, axis=1, keepdims=True)

    out = migrate_store(VectorStore(sdir), _Corpus(), _Embedder(), 2)
    assert out["action"] == "migrated" and out["units"] > 0
    store = VectorStore(sdir)
    assert store.model_steps() == [2]
    _check(store, "after migrate")


def test_append_without_attr_table_refuses(tmp_path, mesh):
    """--attrs against a store with no attribute table is an explicit
    error (never a silent zero-fill), and init_attrs unlocks it."""
    from dnn_page_vectors_tpu.updates import append_corpus
    sdir = str(tmp_path / "plain")
    rng = np.random.default_rng(3)
    store = VectorStore(sdir, dim=DIM, shard_size=SHARD)
    store.ensure_model_step(0)
    store.write_shard(0, np.arange(SHARD, dtype=np.int64),
                      rng.standard_normal((SHARD, DIM)).astype(np.float32))
    store = VectorStore(sdir)
    assert not store.attrs_enabled
    with pytest.raises(ValueError, match="no attribute table"):
        append_corpus(None, None, store, attrs=A.pack_word(lang=1))
    with pytest.raises(ValueError):
        store.write_shard(1, np.arange(SHARD, 2 * SHARD, dtype=np.int64),
                          rng.standard_normal((SHARD, DIM)).astype(
                              np.float32),
                          attrs=np.zeros(SHARD, np.uint32))
    store.init_attrs()
    store = VectorStore(sdir)
    assert store.attrs_enabled
    # pre-attrs shards read as the all-zero default word
    entry = store.shards()[0]
    assert not store.load_attrs(entry).any()


# ---------------------------------------------------------------------------
# loadgen: seeded filtered mix determinism
# ---------------------------------------------------------------------------

def test_filtered_workload_mix_is_seeded_and_additive():
    from dnn_page_vectors_tpu.loadgen.workload import (
        DEFAULT_FILTER_SCENARIOS, make_workload)
    plain = make_workload("poisson", seed=5, distinct=16,
                          profile=((10, None, 1.0),))
    plain2 = make_workload("poisson", seed=5, distinct=16,
                           profile=((10, None, 1.0),))
    # the unfiltered stream is byte-identical with and without the
    # scenario machinery available (no extra RNG draws)
    base = plain.schedule(3.0, 50.0)
    assert base == plain2.schedule(3.0, 50.0)
    wl = make_workload("poisson", seed=5, distinct=16,
                       profile=((10, None, 1.0),),
                       filter_scenarios=DEFAULT_FILTER_SCENARIOS)
    wl2 = make_workload("poisson", seed=5, distinct=16,
                        profile=((10, None, 1.0),),
                        filter_scenarios=DEFAULT_FILTER_SCENARIOS)
    sched = wl.schedule(3.0, 50.0)
    assert sched == wl2.schedule(3.0, 50.0)
    assert wl.digest(sched) == wl2.digest(sched)
    # arrival times and query ids match the plain stream exactly: the
    # scenario draw rides on top, it never perturbs the schedule
    assert [t for t, _ in sched] == [t for t, _ in base]
    assert [r.query_id for _, r in sched] == [r.query_id for _, r in base]
    seen = {r.scenario for _, r in sched}
    assert "unfiltered" in seen and len(seen) > 1
    for _, r in sched:
        if r.filters is not None:
            # predicates are stored in canonical form
            assert r.filters == A.Predicate.parse(r.filters).text
    # an unfiltered schedule's digest is byte-identical to the
    # pre-filters format; a filtered schedule's is tagged
    assert wl.digest(base) == plain.digest(base)
    assert wl.digest(sched) != plain.digest(base)
