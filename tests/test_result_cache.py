"""Generation-keyed result cache + popularity tiering (docs/SERVING.md
"Result cache"): a repeat query must serve from cache WITHOUT becoming
stale — after an append + refresh() the same query must be byte-identical
to a cold-cache oracle on every tested topology (local, partitioned
P=2/R=2, socket fleet), because the generations live in the KEY and a
refresh makes every old entry unreachable. Plus: LRU eviction under a
small capacity, clear_cache() flushing everything with a `cache_cleared`
event, the CACHE_LOOKUP/CACHE_PUT wire codec (round-trip + reject fuzz),
fleet peering (a local miss served from a sibling's cache, fills pushed
fire-and-forget, stale pushes dropped), a concurrent refresh hammer that
must never surface a mixed-generation result, and the IVF popularity
table driving stage_hot's hot-set ranking."""
import threading
import time

import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.infer import transport
from dnn_page_vectors_tpu.infer.transport import (
    FrameError, SocketSearchClient)
from dnn_page_vectors_tpu.infer.vector_store import VectorStore

pytestmark = pytest.mark.rescache

DIM = 32
SHARD = 50
NSHARDS = 6


# ---------------------------------------------------------------------------
# fixtures: synthetic store + model-free services (the test_net idiom —
# the cache layer is exercised by a deterministic text -> vector stub)
# ---------------------------------------------------------------------------

def _fake_embed(queries):
    """Deterministic text -> unit vector (no model): the text-keyed cache
    path is exercised without a trained encoder."""
    out = np.zeros((len(queries), DIM), np.float32)
    for i, q in enumerate(queries):
        r = np.random.default_rng(
            np.frombuffer(q.encode()[:8].ljust(8, b"\0"),
                          np.uint64)[0] % (2 ** 32))
        v = r.standard_normal(DIM).astype(np.float32)
        out[i] = v / np.linalg.norm(v)
    return out


class _StubCorpus:
    def page_text(self, i):
        return f"page {i}"


@pytest.fixture(scope="module")
def mesh():
    import jax
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:1]), ("data",))


def _fresh_store(tmp_path):
    sdir = str(tmp_path / "store")
    rng = np.random.default_rng(0)
    store = VectorStore(sdir, dim=DIM, shard_size=SHARD)
    store.ensure_model_step(1)          # appends require a stamped store
    for si in range(NSHARDS):
        v = rng.standard_normal((SHARD, DIM)).astype(np.float32)
        v /= np.linalg.norm(v, axis=1, keepdims=True)
        store.write_shard(si, np.arange(si * SHARD, (si + 1) * SHARD,
                                        dtype=np.int64), v)
    return VectorStore(sdir)


def _service(store, mesh, **serve_over):
    import dataclasses

    from dnn_page_vectors_tpu.infer.partition_host import MeshEmbedder
    from dnn_page_vectors_tpu.infer.serve import SearchService
    cfg = get_config("cdssm_toy", {"model.out_dim": DIM})
    if serve_over:
        cfg = cfg.replace(serve=dataclasses.replace(cfg.serve,
                                                    **serve_over))
    svc = SearchService(cfg, MeshEmbedder(mesh), None, store,
                        preload_hbm_gb=4.0)
    svc._embed_queries_cached = _fake_embed
    svc.corpus = _StubCorpus()
    return svc


def _append_planted(sdir, query, n_new=10):
    """Commit one generation whose FIRST row is the query's own vector:
    post-refresh, the query's top-1 must be the planted id — so a stale
    cached answer is observably wrong, not merely old."""
    store = VectorStore(sdir)
    base = store.next_page_id()
    vecs = np.random.default_rng(base).standard_normal(
        (n_new, DIM)).astype(np.float32)
    vecs[0] = _fake_embed([query])[0]
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    w = store.begin_generation()
    w.write_shard(np.arange(base, base + n_new, dtype=np.int64), vecs)
    w.commit()
    return base


def _ids(hits):
    return tuple(r["page_id"] for r in hits)


# ---------------------------------------------------------------------------
# staleness-zero pins: local, partitioned, socket fleet
# ---------------------------------------------------------------------------

def test_local_hit_then_staleness_zero_after_refresh(tmp_path, mesh):
    store = _fresh_store(tmp_path)
    sdir = store.directory
    svc = _service(store, mesh, result_cache=True)
    q = "zipf head query"
    first = svc.search(q, k=10)
    assert svc.result_cache_misses == 1 and svc.result_cache_hits == 0
    again = svc.search(q, k=10)
    assert again == first                    # served from cache, identical
    assert svc.result_cache_hits == 1
    met = svc.metrics()["result_cache"]
    assert met["hits"] == 1 and met["misses"] == 1
    assert met["hit_rate"] == 0.5 and met["entries"] >= 1
    assert met["bytes"] > 0 and met["capacity"] == 4096

    planted = _append_planted(sdir, q)
    info = svc.refresh()
    assert info["new_docs"] == 10
    after = svc.search(q, k=10)              # generation bumped: NOT a hit
    assert svc.result_cache_misses == 2
    oracle = _service(VectorStore(sdir), mesh)   # cold, cache off
    want = oracle.search(q, k=10)
    assert after == want                     # byte-identical to cold cache
    assert after[0]["page_id"] == planted    # the new row actually ranks
    assert _ids(after) != _ids(first)
    # the repeat on the NEW generation hits again
    assert svc.search(q, k=10) == want
    assert svc.result_cache_hits == 2
    oracle.close()
    svc.close()


def test_staleness_zero_partitioned_p2_r2(tmp_path, mesh):
    store = _fresh_store(tmp_path)
    sdir = store.directory
    svc = _service(store, mesh, result_cache=True, partitions=2,
                   replicas=2)
    q = "partitioned zipf query"
    first = svc.search(q, k=10)
    assert svc.search(q, k=10) == first
    assert svc.result_cache_hits == 1
    planted = _append_planted(sdir, q)
    svc.refresh()
    after = svc.search(q, k=10)
    oracle = _service(VectorStore(sdir), mesh, partitions=2, replicas=2)
    want = oracle.search(q, k=10)
    assert after == want
    assert after[0]["page_id"] == planted
    oracle.close()
    svc.close()


def test_staleness_zero_over_socket_fleet(tmp_path, mesh):
    from dnn_page_vectors_tpu.infer.server import serve_in_background
    store = _fresh_store(tmp_path)
    sdir = store.directory
    svc = _service(store, mesh, result_cache=True, result_cache_fleet=True)
    srv = serve_in_background(svc)
    client = SocketSearchClient(srv.host, srv.port, result_cache=True)
    try:
        q = "socket zipf query"
        first = client.search(q, k=10)
        assert svc.result_cache_misses >= 1
        assert client.search(q, k=10) == first   # served at the door
        assert svc.result_cache_hits >= 1

        # the raw CACHE_LOOKUP probe answers the primed key...
        key = svc._result_cache_key(q, 10, None)
        got = client.cache_lookup(q, k=10, nprobe=key[2],
                                  store_gen=key[3], index_gen=key[4])
        assert got is not None
        np.testing.assert_array_equal(
            got[1][0][:len(first)], [r["page_id"] for r in first])
        # ...and a probe for generations nobody served is a miss (None),
        # not an error
        assert client.cache_lookup(q, k=10, nprobe=key[2],
                                   store_gen=key[3] + 7,
                                   index_gen=key[4]) is None

        planted = _append_planted(sdir, q)
        svc.refresh()
        after = client.search(q, k=10)
        oracle = _service(VectorStore(sdir), mesh)
        want = oracle.search(q, k=10)
        assert [r["page_id"] for r in after] \
            == [r["page_id"] for r in want]
        np.testing.assert_allclose([r["score"] for r in after],
                                   [r["score"] for r in want], atol=1e-3)
        assert after[0]["page_id"] == planted
        # a stale PUT (pre-refresh generations, a query nobody cached)
        # is silently dropped: the same stale-key probe stays a miss
        assert client.cache_put("stale put query", k=10, nprobe=key[2],
                                store_gen=key[3], index_gen=key[4],
                                scores=np.zeros(10, np.float32),
                                ids=np.arange(10, dtype=np.int64))
        time.sleep(0.3)                      # fire-and-forget: let it land
        assert client.cache_lookup("stale put query", k=10,
                                   nprobe=key[2], store_gen=key[3],
                                   index_gen=key[4]) is None
        # a LIVE-generation PUT for a never-searched query is accepted
        # and round-trips through LOOKUP
        key2 = svc._result_cache_key("planted put query", 10, None)
        ps = np.linspace(0.9, 0.1, 10).astype(np.float32)
        pi = np.arange(10, dtype=np.int64)
        assert client.cache_put("planted put query", k=10, nprobe=key2[2],
                                store_gen=key2[3], index_gen=key2[4],
                                scores=ps, ids=pi)
        got2 = None
        deadline = time.time() + 5.0
        while got2 is None and time.time() < deadline:
            time.sleep(0.02)
            got2 = client.cache_lookup("planted put query", k=10,
                                       nprobe=key2[2], store_gen=key2[3],
                                       index_gen=key2[4])
        assert got2 is not None, "live-generation CACHE_PUT never landed"
        np.testing.assert_array_equal(got2[1][0], pi)
        oracle.close()
    finally:
        client.close()
        srv.close()
        svc.close()


def test_client_without_negotiation_degrades_to_noop(tmp_path, mesh):
    """A peer that never negotiated FLAG_RESULT_CACHE gets no cache
    frames: lookup is None, put is False, and a caching client against a
    non-caching server degrades the same way (mixed-fleet interop)."""
    from dnn_page_vectors_tpu.infer.server import serve_in_background
    store = _fresh_store(tmp_path)
    svc = _service(store, mesh, result_cache=True, result_cache_fleet=True)
    srv = serve_in_background(svc)
    plain = SocketSearchClient(srv.host, srv.port)   # no result_cache
    try:
        assert plain.cache_lookup("q", k=10, nprobe=0, store_gen=0,
                                  index_gen=-1) is None
        assert not plain.cache_put("q", k=10, nprobe=0, store_gen=0,
                                   index_gen=-1,
                                   scores=np.zeros(10, np.float32),
                                   ids=np.zeros(10, np.int64))
    finally:
        plain.close()
        srv.close()
        svc.close()
    # caching client, non-fleet server: HELLO intersects the flag away
    svc2 = _service(_fresh_store(tmp_path / "b"), mesh, result_cache=True)
    srv2 = serve_in_background(svc2)
    eager = SocketSearchClient(srv2.host, srv2.port, result_cache=True)
    try:
        assert eager.cache_lookup("q", k=10, nprobe=0, store_gen=0,
                                  index_gen=-1) is None
    finally:
        eager.close()
        srv2.close()
        svc2.close()


# ---------------------------------------------------------------------------
# fleet peering: a local miss served from a sibling's cache
# ---------------------------------------------------------------------------

def test_peer_lookup_serves_local_miss_and_fills_propagate(tmp_path, mesh):
    from dnn_page_vectors_tpu.infer.server import serve_in_background
    store_a = _fresh_store(tmp_path)
    store_b = VectorStore(store_a.directory)         # same corpus fleet-wide
    svc_a = _service(store_a, mesh, result_cache=True,
                     result_cache_fleet=True)
    svc_b = _service(store_b, mesh, result_cache=True,
                     result_cache_fleet=True)
    srv_b = serve_in_background(svc_b)
    peer = SocketSearchClient(srv_b.host, srv_b.port, result_cache=True)
    svc_a.attach_cache_peers([peer])
    try:
        q = "fleet shared query"
        want = svc_b.search(q, k=10)                 # primes B's cache
        got = svc_a.search(q, k=10)                  # A: local miss -> peer
        assert _ids(got) == _ids(want)
        assert [r["score"] for r in got] == [r["score"] for r in want]
        assert svc_a.result_cache_hits == 1          # the peer hit counted
        # the peer answer was inserted locally: the repeat stays in-process
        key = svc_a._result_cache_key(q, 10, None)
        assert svc_a._result_cache_get(key, count=False) is not None

        # a query computed on A is pushed to B fire-and-forget
        q2 = "fleet pushed query"
        svc_a.search(q2, k=10)
        key2 = svc_b._result_cache_key(q2, 10, None)
        landed = None
        deadline = time.time() + 5.0
        while landed is None and time.time() < deadline:
            time.sleep(0.02)
            landed = svc_b._result_cache_get(key2, count=False)
        assert landed is not None, "CACHE_PUT to the peer never landed"
        assert _ids(landed) == _ids(svc_a.search(q2, k=10))
    finally:
        peer.close()
        srv_b.close()
        svc_b.close()
        svc_a.close()


# ---------------------------------------------------------------------------
# LRU + clear_cache
# ---------------------------------------------------------------------------

def test_lru_eviction_and_clear_cache_event(tmp_path, mesh):
    store = _fresh_store(tmp_path)
    svc = _service(store, mesh, result_cache=True, result_cache_size=4)
    queries = [f"distinct query {i}" for i in range(6)]
    for q in queries:
        svc.search(q, k=10)
    met = svc.metrics()["result_cache"]
    assert met["entries"] == 4 and met["capacity"] == 4
    # the two OLDEST entries were evicted, the newest four are resident
    for q in queries[:2]:
        key = svc._result_cache_key(q, 10, None)
        assert svc._result_cache_get(key, count=False) is None
    for q in queries[2:]:
        key = svc._result_cache_key(q, 10, None)
        assert svc._result_cache_get(key, count=False) is not None
    # a hit refreshes recency: re-touch the oldest survivor, insert one
    # more, and the survivor outlives the entry that was ahead of it
    svc.search(queries[2], k=10)
    svc.search("one more query", k=10)
    assert svc._result_cache_get(
        svc._result_cache_key(queries[2], 10, None),
        count=False) is not None
    assert svc._result_cache_get(
        svc._result_cache_key(queries[3], 10, None), count=False) is None

    svc.clear_cache()
    met = svc.metrics()["result_cache"]
    assert met["entries"] == 0 and met["bytes"] == 0
    evs = svc.registry.events("cache_cleared")
    assert evs and evs[-1]["attrs"]["result_entries"] == 4
    svc.close()


# ---------------------------------------------------------------------------
# concurrent refresh hammer: no mixed-generation result, ever
# ---------------------------------------------------------------------------

def test_concurrent_refresh_hammer_never_serves_stale(tmp_path, mesh):
    store = _fresh_store(tmp_path)
    sdir = store.directory
    svc = _service(store, mesh, result_cache=True)
    queries = [f"hammer query {i}" for i in range(4)]
    valid = {q: set() for q in queries}
    oracle = _service(VectorStore(sdir), mesh)
    for q in queries:
        valid[q].add(_ids(oracle.search(q, k=10)))
    oracle.close()
    stop = threading.Event()
    errors, observed = [], {q: set() for q in queries}

    def hammer(q):
        while not stop.is_set():
            try:
                observed[q].add(_ids(svc.search(q, k=10)))
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
                return
            # throttle: cache hits are pure Python — an unthrottled spin
            # starves the main thread's per-cycle oracle compile
            time.sleep(0.002)

    threads = [threading.Thread(target=hammer, args=(q,))
               for q in queries]
    for t in threads:
        t.start()
    for cycle in range(3):
        _append_planted(sdir, queries[cycle % len(queries)], n_new=5)
        svc.refresh()
        oracle = _service(VectorStore(sdir), mesh)
        for q in queries:
            valid[q].add(_ids(oracle.search(q, k=10)))
        oracle.close()
        time.sleep(0.05)
    stop.set()
    for t in threads:
        t.join()
    assert not errors, f"hammered search raised: {errors[:3]}"
    for q in queries:
        extra = observed[q] - valid[q]
        assert not extra, (f"{q!r} served a result matching NO store "
                           f"generation: {extra}")
    # the hammer actually exercised the cache, and the final answer is
    # the newest generation's cold-cache oracle
    assert svc.result_cache_hits > 0
    oracle = _service(VectorStore(sdir), mesh)
    for q in queries:
        assert svc.search(q, k=10) == oracle.search(q, k=10)
    oracle.close()
    svc.close()


# ---------------------------------------------------------------------------
# wire codec: round-trip + reject
# ---------------------------------------------------------------------------

def test_cache_frame_codec_roundtrip_and_reject():
    pay = transport.encode_cache_lookup(7, "què ry", k=10, nprobe=3,
                                        store_gen=2, index_gen=-1)
    ck = transport.decode_cache_lookup(pay)
    assert (ck.req_id, ck.k, ck.nprobe) == (7, 10, 3)
    assert (ck.store_gen, ck.index_gen, ck.query) == (2, -1, "què ry")
    scores = np.linspace(1.0, 0.1, 10).astype(np.float32)
    ids = np.arange(10, dtype=np.int64)
    ids[-2:] = -1                            # padded past the hit count
    ppay = transport.encode_cache_put(8, "q", k=10, nprobe=0, store_gen=1,
                                      index_gen=4, scores=scores, ids=ids)
    ck2, s2, i2 = transport.decode_cache_put(ppay)
    assert ck2.req_id == 8 and ck2.index_gen == 4
    np.testing.assert_array_equal(s2, scores)
    np.testing.assert_array_equal(i2, ids)
    # rejects: truncation, trailing bytes, short/long rows, bad k
    with pytest.raises(FrameError):
        transport.decode_cache_lookup(pay[:8])
    with pytest.raises(FrameError):
        transport.decode_cache_lookup(pay[:-1])
    with pytest.raises(FrameError):
        transport.decode_cache_lookup(pay + b"x")
    with pytest.raises(FrameError):
        transport.decode_cache_put(ppay[:-3])
    with pytest.raises(FrameError):
        transport.decode_cache_put(ppay + b"\0" * 4)
    bad_k = transport._CACHE_HEAD.pack(9, 0, 0, 0, 0, 1) + b"q"
    with pytest.raises(FrameError):
        transport.decode_cache_put(bad_k)
    with pytest.raises(ValueError):
        transport.encode_cache_put(9, "q", k=10, nprobe=0, store_gen=0,
                                   index_gen=0, scores=scores[:4], ids=ids)


# ---------------------------------------------------------------------------
# popularity tiering: measured scan counts rank the hot set
# ---------------------------------------------------------------------------

def test_popularity_counts_rank_stage_hot(tmp_path):
    from dnn_page_vectors_tpu.config import MeshConfig
    from dnn_page_vectors_tpu.index.ivf import IVFIndex
    from dnn_page_vectors_tpu.parallel.mesh import make_mesh
    rng = np.random.default_rng(3)
    n, d, nclust = 600, 32, 12
    centers = rng.normal(size=(nclust, d))
    vecs = (centers[rng.integers(0, nclust, n)]
            + 0.3 * rng.normal(size=(n, d))).astype(np.float32)
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    store = VectorStore(str(tmp_path / "synth"), dim=d, shard_size=200,
                        dtype="float16")
    store.ensure_model_step(1)
    for i in range(0, n, 200):
        store.write_shard(i // 200, np.arange(i, min(i + 200, n)),
                          vecs[i: i + 200])
    mesh = make_mesh(MeshConfig(data=4))
    idx = IVFIndex.build(store, mesh, nlist=8, iters=4, seed=0, pq_m=4)
    assert idx.scan_counts.shape == (8,) and idx.scan_counts.sum() == 0
    # a COLD table degrades to biggest-first and says so
    budget = 3 * n * (idx.pq.m + 4) // 8       # room for ~2-3 lists
    cold = idx.stage_hot(budget)
    assert not cold["hot_by_popularity"]
    assert 0 < cold["hot_lists"] < idx.nlist
    size_order_lists = np.nonzero(idx._hot["lists"])[0]

    # hammer ONE query at nprobe=1: its probed list dominates the window
    q = vecs[5:6]
    s_ref, ids_ref, _ = idx.search(q, k=10, nprobe=8, rerank=64)
    for _ in range(50):
        idx.search(q, k=10, nprobe=1, rerank=16)
    hot_list = int(np.argmax(idx.scan_counts))
    before = idx.scan_counts.copy()
    hot = idx.stage_hot(budget)
    assert hot["hot_by_popularity"]
    assert idx._hot is None or idx._hot["lists"][hot_list], \
        "the measured-hottest list was not staged"
    if idx._hot is not None:
        pop_order_lists = np.nonzero(idx._hot["lists"])[0]
        assert hot_list in pop_order_lists
    # the window decays: each restage halves the table
    np.testing.assert_array_equal(idx.scan_counts, before >> 1)
    # parity: popularity staging changes residency, never results
    s_pop, ids_pop, _ = idx.search(q, k=10, nprobe=8, rerank=64)
    np.testing.assert_array_equal(ids_pop, ids_ref)
    np.testing.assert_allclose(s_pop, s_ref, atol=1e-3)
    assert size_order_lists is not None      # both rankings exercised
