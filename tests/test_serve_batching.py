"""The concurrent serving layer (docs/SERVING.md): search_many must return
exactly what per-query search() returns on BOTH the HBM-resident and
streaming paths (batching is an optimization, not a different algorithm) —
including on a degraded store under a seeded FaultPlan — and the
micro-batcher must coalesce concurrent callers, flush partial buckets after
its window, isolate a poisoned request's failure to its own future, and the
query-embedding cache must hit on repeats and invalidate on a model-step
re-stamp."""
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from dnn_page_vectors_tpu.config import get_config
from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.serve import SearchService
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.train.loop import Trainer
from dnn_page_vectors_tpu.utils import faults

_OV = {
    "data.num_pages": 300,
    "data.trigram_buckets": 2048,
    "model.embed_dim": 48,
    "model.conv_channels": 96,
    "model.out_dim": 48,
    "train.batch_size": 64,
    "train.steps": 60,
    "train.warmup_steps": 10,
    "train.learning_rate": 2e-3,
    "train.log_every": 1000,
    "eval.embed_batch_size": 100,
    "eval.store_shard_size": 100,   # 3 shards: exercises the shard merge
}


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    """One trained model + embedded 3-shard store for the whole module
    (training dominates test cost; services stage cheaply per test)."""
    wd = str(tmp_path_factory.mktemp("serve_batching"))
    cfg = get_config("cdssm_toy", _OV)
    trainer = Trainer(cfg, workdir=wd)
    state, _ = trainer.train()
    emb = BulkEmbedder(cfg, trainer.model, state.params, trainer.page_tok,
                       trainer.mesh, query_tok=trainer.query_tok)
    store = VectorStore(wd + "/store", dim=cfg.model.out_dim, shard_size=100)
    emb.embed_corpus(trainer.corpus, store)
    return cfg, trainer, emb, store


def _assert_same(a, b):
    assert [r["page_id"] for r in a] == [r["page_id"] for r in b]
    np.testing.assert_allclose([r["score"] for r in a],
                               [r["score"] for r in b], atol=1e-4)


def test_search_many_matches_sequential_on_both_paths(served):
    cfg, trainer, emb, store = served
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    stream = SearchService(cfg, emb, trainer.corpus, store,
                           preload_hbm_gb=0.0)
    assert svc.preloaded and not stream.preloaded
    # 20 queries > the compiled bucket (8): exercises full-bucket tiling
    # plus a ragged final bucket
    qis = [0, 7, 42, 123, 299, 5, 13, 77, 200, 250,
           1, 2, 3, 4, 6, 8, 9, 10, 11, 12]
    queries = [trainer.corpus.query_text(qi) for qi in qis]
    many = svc.search_many(queries, k=10)
    many_stream = stream.search_many(queries, k=10)
    assert len(many) == len(queries)
    hits = 0
    for qi, query, batched, batched_s in zip(qis, queries, many, many_stream):
        seq = svc.search(query, k=10)
        _assert_same(batched, seq)
        _assert_same(batched_s, stream.search(query, k=10))
        _assert_same(batched, batched_s)        # HBM == streaming, batched
        scores = [r["score"] for r in batched]
        assert scores == sorted(scores, reverse=True)
        hits += qi in [r["page_id"] for r in batched]
    assert hits >= 12, f"only {hits}/20 gold pages retrieved"
    assert svc.search_many([], k=10) == []


def test_search_many_degraded_matches_streaming_under_faults(served,
                                                             tmp_path):
    """A quarantined shard (corrupt bytes) + a staging fault (seeded
    FaultPlan) leave the service half-resident; batched search over the
    degraded service must equal a fault-free streaming service on the
    surviving store — and the degraded tail folds once per bucket."""
    import os
    cfg, trainer, emb, _ = served
    # a fresh store so quarantine doesn't disturb the shared fixture
    dstore = VectorStore(str(tmp_path / "store"), dim=cfg.model.out_dim,
                         shard_size=100)
    emb.embed_corpus(trainer.corpus, dstore)
    victim = os.path.join(dstore.directory, "shard_00001.vec.npy")
    with open(victim, "r+b") as f:
        f.truncate(os.path.getsize(victim) // 2)
    faults.install(faults.FaultPlan.parse("hbm_stage:io_error:2", seed=0))
    svc = SearchService(cfg, emb, trainer.corpus, dstore, preload_hbm_gb=4.0)
    assert svc.degraded
    assert svc.fault_counters["serve_quarantined_shards"] == 1
    assert svc.fault_counters["serve_stage_faults"] == 1
    assert len(svc._stream_entries) == 1
    faults.reset()
    stream = SearchService(cfg, emb, trainer.corpus, dstore,
                           preload_hbm_gb=0.0)
    queries = [trainer.corpus.query_text(qi)
               for qi in (0, 42, 100, 150, 200, 250, 280, 299, 1, 2)]
    many = svc.search_many(queries, k=10)
    for query, batched in zip(queries, many):
        _assert_same(batched, stream.search(query, k=10))
        _assert_same(batched, svc.search(query, k=10))


def test_search_many_dedups_repeats_within_a_batch(served):
    """Duplicate queries in one coalesced batch encode once (intra-batch
    dedup) and every duplicate row gets the identical result."""
    cfg, trainer, emb, store = served
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    q = trainer.corpus.query_text(9)
    other = trainer.corpus.query_text(17)
    res = svc.search_many([q, other, q, " " + q + "  ", other], k=10)
    assert res[0] == res[2] == res[3]
    assert res[1] == res[4]
    _assert_same(res[0], svc.search(q, k=10))


def test_microbatcher_coalesces_concurrent_callers(served):
    cfg, trainer, emb, store = served
    cfg = get_config("cdssm_toy", dict(_OV, **{
        "serve.batch_window_ms": 150, "serve.max_batch": 8}))
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    direct = {qi: svc.search(trainer.corpus.query_text(qi), k=10)
              for qi in range(12)}
    svc.start_batcher()
    assert svc.batching
    # a lone caller: the window expires and the PARTIAL bucket dispatches
    res = svc.search(trainer.corpus.query_text(0), k=10)
    _assert_same(res, direct[0])
    assert svc._batcher.batch_sizes[-1] == 1
    # 12 concurrent callers with a long window coalesce into shared
    # dispatches (max_batch 8 forces at least two)
    before = len(svc._batcher.batch_sizes)
    with ThreadPoolExecutor(12) as ex:
        results = list(ex.map(
            lambda qi: svc.search(trainer.corpus.query_text(qi), k=10),
            range(12)))
    for qi, r in enumerate(results):
        _assert_same(r, direct[qi])
    sizes = svc._batcher.batch_sizes[before:]
    assert sum(sizes) == 12
    assert max(sizes) > 1, "concurrent callers never coalesced"
    assert max(sizes) <= 8                  # serve.max_batch respected
    svc.close()
    assert not svc.batching
    # after close, search() falls back to the direct path
    _assert_same(svc.search(trainer.corpus.query_text(0), k=10), direct[0])


def test_microbatcher_isolates_failing_request(served):
    """A poisoned query (not a string) coalesced with healthy ones must
    fail ONLY its own future; batch-mates still get results."""
    cfg, trainer, emb, store = served
    cfg = get_config("cdssm_toy", dict(_OV, **{
        "serve.batch_window_ms": 200, "serve.max_batch": 8}))
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    good_direct = svc.search(trainer.corpus.query_text(5), k=10)
    svc.start_batcher()
    results, errors = {}, {}

    def _call(tag, query):
        try:
            results[tag] = svc.search(query, k=10)
        except Exception as e:  # noqa: BLE001
            errors[tag] = e

    threads = [
        threading.Thread(target=_call, args=("good1", trainer.corpus.query_text(5))),
        threading.Thread(target=_call, args=("poison", None)),
        threading.Thread(target=_call, args=("good2", trainer.corpus.query_text(7))),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    svc.close()
    assert set(results) == {"good1", "good2"}
    assert set(errors) == {"poison"}
    _assert_same(results["good1"], good_direct)


def test_query_cache_hits_and_model_step_invalidation(served, tmp_path):
    cfg, trainer, emb, _ = served
    store = VectorStore(str(tmp_path / "store"), dim=cfg.model.out_dim,
                        shard_size=100)
    emb.embed_corpus(trainer.corpus, store)
    store.ensure_model_step(1)
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    q = trainer.corpus.query_text(3)
    first = svc.search(q, k=10)
    assert svc.cache_misses == 1 and svc.cache_hits == 0
    second = svc.search(q, k=10)
    assert svc.cache_hits == 1
    assert first == second          # a hit returns IDENTICAL results
    # whitespace-normalized key: surrounding/internal runs of spaces hit
    third = svc.search("  " + q.replace(" ", "  ") + " ", k=10)
    assert svc.cache_hits == 2
    assert third == first
    # a store re-stamp (model reload) changes the key -> miss, not stale hit
    store.ensure_model_step(2)
    svc.search(q, k=10)
    assert svc.cache_misses == 2
    met = svc.metrics()
    assert met["serve_cache_hits"] == 2
    assert met["serve_cache_misses"] == 2
    assert met["serve_cache_hit_rate"] == 0.5
    # the serving stage breakdown is in the metrics
    assert any(key.startswith("serve_stage_") for key in met)


def test_cache_lru_eviction_and_disable(served):
    cfg, trainer, emb, store = served
    cfg = get_config("cdssm_toy", dict(_OV, **{"serve.query_cache_size": 2}))
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    q0, q1, q2 = (trainer.corpus.query_text(i) for i in (0, 1, 2))
    svc.search(q0, k=5)
    svc.search(q1, k=5)
    svc.search(q2, k=5)             # evicts q0 (capacity 2, LRU)
    svc.search(q0, k=5)
    assert svc.cache_hits == 0 and svc.cache_misses == 4
    svc.search(q2, k=5)             # still resident
    assert svc.cache_hits == 1
    off = get_config("cdssm_toy", dict(_OV, **{"serve.query_cache_size": 0}))
    nsvc = SearchService(off, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    nsvc.search(q0, k=5)
    nsvc.search(q0, k=5)
    assert nsvc.cache_hits == 0 and nsvc.cache_misses == 0


def test_warmup_reports_median_and_bypasses_cache(served):
    cfg, trainer, emb, store = served
    svc = SearchService(cfg, emb, trainer.corpus, store, preload_hbm_gb=4.0)
    svc.warmup(k=10, timing_iters=3)
    assert svc.warm_latency_ms and svc.warm_latency_ms > 0
    # the timed iterations must NOT have come from the cache: only the
    # compile call may have populated it
    assert svc.cache_hits == 0
