"""Batching + host->device prefetch (SURVEY.md §3 #4).

The reference keeps tokenization and loading on the host feeding the
accelerator (BASELINE.json:5). Here the hot principle is: nothing host-side
may ever stall the jitted step. `prefetch_to_device` keeps `depth` batches
already transferred (with their target NamedSharding, so each host only
materialises its addressable shards) while the current step runs.
"""
from __future__ import annotations

import collections
import concurrent.futures
import os
import threading
import queue as queue_mod
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

import jax
import numpy as np

from dnn_page_vectors_tpu.config import Config
from dnn_page_vectors_tpu.data.jsonl import JsonlCorpus
from dnn_page_vectors_tpu.data.toy import ToyCorpus
from dnn_page_vectors_tpu.data.trigram import TrigramTokenizer
from dnn_page_vectors_tpu.data.words import WordTokenizer
from dnn_page_vectors_tpu.data.subword import SubwordTokenizer
from dnn_page_vectors_tpu.utils.profiling import PipelineProfiler

Batch = Dict[str, np.ndarray]


def ordered_parallel_map(fn: Callable[[Any], Any], items: Iterable[Any],
                         workers: int, depth: int = 2) -> Iterator[Any]:
    """Map `fn` over `items` with a pool of `workers` threads, yielding
    results strictly in item order — the reassembly half of the multi-worker
    host producer. In-flight work is bounded at workers + depth submissions
    (the bounded queue: host memory stays O(window), and an abandoned
    consumer never leaves an unbounded backlog).

    Exception contract: a worker exception re-raises HERE, at the failed
    item's position in the output order — the consumer sees it exactly
    where the serial path would have raised, so a downstream accumulator
    (e.g. a store shard) can never be silently truncated. Later items that
    already completed are discarded, pending ones are cancelled.

    Threads only: corpus readers keep per-thread file handles
    (data/jsonl.py) and the tokenizers' C++ batch encoders drop the GIL
    (data/subword.py), so CPython threads genuinely overlap the
    read+tokenize work.
    """
    if workers <= 1:
        for item in items:
            yield fn(item)
        return
    ex = concurrent.futures.ThreadPoolExecutor(
        max_workers=workers, thread_name_prefix="tokenize-worker")
    futs: collections.deque = collections.deque()
    try:
        for item in items:
            futs.append(ex.submit(fn, item))
            if len(futs) >= workers + depth:
                yield futs.popleft().result()
        while futs:
            yield futs.popleft().result()
    finally:
        ex.shutdown(wait=False, cancel_futures=True)


def build_corpus(cfg: Config):
    d = cfg.data
    if d.corpus == "toy":
        return ToyCorpus(num_pages=d.num_pages, seed=d.seed,
                         page_len=d.page_len, query_len=d.query_len,
                         languages=d.languages, num_topics=d.num_topics)
    if d.corpus.startswith("jsonl:"):
        return JsonlCorpus(d.corpus[len("jsonl:"):])
    raise ValueError(f"unknown corpus {d.corpus!r} (want 'toy' or 'jsonl:<path>')")


def _corpus_fingerprint(corpus) -> str:
    fp = getattr(corpus, "fingerprint", None)
    return fp() if callable(fp) else f"{type(corpus).__name__}:{corpus.num_pages}"


def build_tokenizer(cfg: Config, corpus, cache_dir: Optional[str] = None):
    """Builds (query_tok, page_tok). Trained vocabs (word/subword) are cached
    under cache_dir so later embed/eval/mine runs reuse the EXACT vocab the
    model was trained with — page vectors are only comparable across runs if
    token ids are (vector-store reproducibility, SURVEY.md §3 #20).

    Honesty contract (VERDICT r1 #3): the built tokenizer's vocab_size must
    EQUAL config.data.vocab_size — training raises rather than silently
    clamping, and a cached vocab is only reused when its recorded
    (vocab_size, corpus fingerprint) provenance matches the current config
    (ADVICE r1: stale-cache divergence).
    """
    d = cfg.data
    if d.tokenizer == "trigram":   # stateless hashing: nothing to cache
        q = TrigramTokenizer(d.trigram_buckets, max_words=d.query_len,
                             k=d.trigrams_per_word)
        p = TrigramTokenizer(d.trigram_buckets, max_words=d.page_len,
                             k=d.trigrams_per_word)
        return q, p
    cache = (os.path.join(cache_dir, f"tokenizer_{d.tokenizer}.json")
             if cache_dir else None)
    meta = {"vocab_size": d.vocab_size,
            "corpus": _corpus_fingerprint(corpus)}
    if d.tokenizer == "word":
        tok = None
        if cache and os.path.exists(cache):
            tok = WordTokenizer.load(cache)
            if tok.meta != meta:   # stale: config/corpus changed since save
                tok = None
        if tok is None:
            tok = WordTokenizer.train(
                corpus.all_texts(), vocab_size=d.vocab_size,
                max_words=d.page_len, strict_vocab=True)
            tok.meta = meta
            if cache:
                tok.save(cache)
        q = WordTokenizer(tok.vocab, max_words=d.query_len)
        return q, tok
    if d.tokenizer in ("wordpiece", "sentencepiece"):
        tok = None
        if cache and os.path.exists(cache):
            tok = SubwordTokenizer.load(cache)
            tok.max_tokens = d.page_len
            if tok.meta != meta:
                tok = None
        if tok is None:
            # sample size scales with the requested vocab: merge capacity is
            # bounded by unique-word count (~word-sample/27 on the toy
            # corpus), and a 250k-piece vocab needs a far bigger sample than
            # the 2M-word default that suits 30k
            tok = SubwordTokenizer.train(
                corpus.all_texts(), vocab_size=d.vocab_size,
                style=d.tokenizer, max_tokens=d.page_len, strict_vocab=True,
                max_train_words=max(2_000_000, 60 * d.vocab_size))
            tok.meta = meta
            if cache:
                tok.save(cache)
        q = SubwordTokenizer(tok.vocab, style=tok.style, max_tokens=d.query_len)
        q.threads = tok.threads = d.tokenize_threads
        return q, tok
    raise ValueError(f"unknown tokenizer {d.tokenizer!r}")


class TrainBatcher:
    """Deterministic shuffled (query, page) training batches.

    Yields {"query": [b, ...], "page": [b, ...], "page_id": [b]} numpy
    batches; static shapes so the jitted step compiles once.

    Multi-host (VERDICT r1 #6): every process derives the SAME global batch
    ids from the shared seed, but tokenizes/materialises ONLY its
    `process_index`-th contiguous slice (b = batch_size / process_count
    rows) — host work and memory stay O(global batch / hosts). The prefetch
    layer reassembles the global array with
    jax.make_array_from_process_local_data. Contiguous slicing matches the
    mesh 'data' axis order because make_mesh lays devices out in
    jax.devices() order (process-major).

    `workers` > 1 runs the per-step read+tokenize (query, page, and mined
    hard negatives — serially the largest host cost of a train step) on a
    pool of tokenizer workers, reassembled in batch order
    (ordered_parallel_map): batches are byte-identical to the serial path,
    just produced concurrently. The id schedule itself stays single-threaded
    (one permutation per epoch), so resume/multi-host determinism is
    untouched.
    """

    def __init__(self, corpus: ToyCorpus, query_tok, page_tok,
                 batch_size: int, seed: int = 0, start_step: int = 0,
                 hard_negative_lookup: Optional[Callable[[np.ndarray], np.ndarray]] = None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 workers: int = 1,
                 profiler: Optional[PipelineProfiler] = None):
        if batch_size > corpus.num_pages:
            raise ValueError(
                f"batch_size {batch_size} > corpus size {corpus.num_pages}: "
                "no full batch can ever be formed")
        self.corpus = corpus
        self.query_tok = query_tok
        self.page_tok = page_tok
        self.batch_size = batch_size
        self.seed = seed
        # resume point: global step -> (epoch, offset); makes a restored run
        # continue the exact data order of an uninterrupted one (§5.4)
        self.start_step = start_step
        # maps [B] gold page ids -> [B, H] hard-negative page ids (mine/ann.py)
        self.hard_negative_lookup = hard_negative_lookup
        self.process_index = (jax.process_index() if process_index is None
                              else process_index)
        self.process_count = (jax.process_count() if process_count is None
                              else process_count)
        if batch_size % self.process_count:
            raise ValueError(
                f"batch_size {batch_size} must divide process_count "
                f"{self.process_count} (contiguous per-host slices)")
        self.workers = max(1, workers)
        self.profiler = profiler

    @property
    def steps_per_epoch(self) -> int:
        return self.corpus.num_pages // self.batch_size

    def _id_stream(self) -> Iterator[np.ndarray]:
        """The deterministic batch-id schedule, independent of who
        materializes it — the work descriptors the tokenizer workers pull."""
        n = self.corpus.num_pages
        epoch = self.start_step // self.steps_per_epoch
        skip = self.start_step % self.steps_per_epoch
        local = self.batch_size // self.process_count
        lo = self.process_index * local
        while True:
            rng = np.random.default_rng(self.seed + epoch)
            order = rng.permutation(n)
            for b in range(skip, self.steps_per_epoch):
                s = b * self.batch_size
                yield order[s + lo: s + lo + local]   # this process's slice
            skip = 0
            epoch += 1

    def __iter__(self) -> Iterator[Batch]:
        return ordered_parallel_map(self._materialize, self._id_stream(),
                                    self.workers)

    def _materialize(self, ids: np.ndarray) -> Batch:
        prof = self.profiler or _NULL_PROFILER
        with prof.stage("read"):
            queries = _query_texts(self.corpus, ids)
            pages = _page_texts(self.corpus, ids)
        with prof.stage("tokenize"):
            batch: Batch = {
                "query": self.query_tok.encode_batch(queries),
                "page": self.page_tok.encode_batch(pages),
                "page_id": ids.astype(np.int32),
            }
        if self.hard_negative_lookup is not None:
            neg_ids = self.hard_negative_lookup(ids)  # [B, H]
            flat = neg_ids.reshape(-1)
            with prof.stage("read"):
                neg_pages = _page_texts(self.corpus, flat)
            with prof.stage("tokenize"):
                enc = self.page_tok.encode_batch(neg_pages)
            batch["neg_page"] = enc.reshape(neg_ids.shape + enc.shape[1:])
        return batch


_NULL_PROFILER = PipelineProfiler()   # shared sink when no profiler is wired


def _page_texts(corpus, ids) -> list:
    """Bulk page reads where the corpus supports them (JsonlCorpus's
    fast-extract path — the difference between the host producer keeping up
    with the chip or not); per-id fallback otherwise."""
    bulk = getattr(corpus, "page_texts", None)
    if bulk is not None:
        return bulk(ids)
    return [corpus.page_text(int(i)) for i in ids]


def _query_texts(corpus, ids) -> list:
    bulk = getattr(corpus, "query_texts", None)
    if bulk is not None:
        return bulk(ids)
    return [corpus.query_text(int(i)) for i in ids]


def iter_corpus_batches(corpus: ToyCorpus, page_tok, batch_size: int,
                        start: int = 0, stop: Optional[int] = None,
                        workers: int = 1,
                        profiler: Optional[PipelineProfiler] = None
                        ) -> Iterator[Batch]:
    """Fixed-order corpus sweep for bulk-embed; last batch is padded to keep
    shapes static (pad rows flagged with page_id == -1).

    `workers` > 1 fans the per-batch read+tokenize over a pool of tokenizer
    workers pulling id-range descriptors from the sweep, reassembled IN
    ORDER through a bounded window (ordered_parallel_map) — batches, and
    therefore the embedded vectors, are byte-identical to the serial path,
    and a worker exception re-raises at its batch's position instead of
    truncating the stream."""
    stop = corpus.num_pages if stop is None else min(stop, corpus.num_pages)
    prof = profiler or _NULL_PROFILER

    def _make(s: int) -> Batch:
        ids = np.arange(s, min(s + batch_size, stop))
        with prof.stage("read"):
            pages = _page_texts(corpus, ids)
        with prof.stage("tokenize"):
            enc = page_tok.encode_batch(pages)
        if len(ids) < batch_size:
            pad = batch_size - len(ids)
            enc = np.concatenate([enc, np.zeros((pad,) + enc.shape[1:], enc.dtype)])
            ids = np.concatenate([ids, -np.ones(pad, dtype=ids.dtype)])
        return {"page": enc, "page_id": ids.astype(np.int32)}

    return ordered_parallel_map(_make, range(start, stop, batch_size),
                                workers)


def prefetch_to_device(it: Iterator[Batch], sharding: Optional[Any] = None,
                       depth: int = 2,
                       profiler: Optional[PipelineProfiler] = None
                       ) -> Iterator[Any]:
    """Double-buffered host->HBM pipeline.

    A background thread tokenizes/materialises numpy batches; the consumer
    side issues the (async) device_put so `depth` batches are in flight while
    the TPU runs the current step. Producer exceptions re-raise in the
    consumer (a swallowed tokenizer crash must not look like end-of-stream —
    embed_corpus would record a short shard as complete). Abandoning the
    generator (GeneratorExit) unblocks and stops the producer thread.

    Multi-process: upstream batchers yield only this process's slice;
    jax.make_array_from_process_local_data assembles the global sharded
    array (each host feeds exactly its addressable shards, VERDICT r1 #6).

    `profiler` records the consumer-side stall waiting for a host batch
    (produce_wait — the number that says the job is host-production-bound)
    and the host->device placement (h2d).
    """
    prof = profiler or _NULL_PROFILER
    q: "queue_mod.Queue[Any]" = queue_mod.Queue(maxsize=depth)
    stop = threading.Event()
    _END = object()

    def _producer() -> None:
        try:
            for item in it:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue_mod.Full:
                        continue
                if stop.is_set():
                    return
            _finish(_END)
        except BaseException as e:  # re-raised consumer-side
            _finish(e)

    def _finish(token: Any) -> None:
        while not stop.is_set():
            try:
                q.put(token, timeout=0.1)
                return
            except queue_mod.Full:
                continue

    t = threading.Thread(target=_producer, daemon=True)
    t.start()

    buf: collections.deque[Any] = collections.deque()

    # Assemble-from-local-slices only when the target sharding actually spans
    # other processes (the SPMD training mesh). A process-LOCAL mesh in a
    # multi-process job (multihost embed) takes the plain device_put path —
    # its batches are complete, not per-process slices.
    multiprocess = (jax.process_count() > 1 and sharding is not None
                    and not sharding.is_fully_addressable)

    def _put(batch: Batch) -> Any:
        with prof.stage("h2d"):
            if sharding is None:
                return jax.device_put(batch)
            if multiprocess:
                return jax.tree_util.tree_map(
                    lambda arr: jax.make_array_from_process_local_data(
                        sharding, np.asarray(arr)), batch)
            return jax.device_put(batch, jax.tree_util.tree_map(
                lambda _: sharding, batch))

    try:
        while True:
            while len(buf) < depth:
                with prof.stage("produce_wait"):
                    item = q.get()
                if item is _END or isinstance(item, BaseException):
                    break
                buf.append(_put(item))
            else:
                yield buf.popleft()
                continue
            if isinstance(item, BaseException):
                raise RuntimeError("prefetch producer failed") from item
            while buf:  # producer finished cleanly: drain
                yield buf.popleft()
            return
    finally:
        stop.set()
