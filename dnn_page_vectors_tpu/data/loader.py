"""Batching + host->device prefetch (SURVEY.md §3 #4).

The reference keeps tokenization and loading on the host feeding the
accelerator (BASELINE.json:5). Here the hot principle is: nothing host-side
may ever stall the jitted step. `prefetch_to_device` keeps `depth` batches
already transferred (with their target NamedSharding, so each host only
materialises its addressable shards) while the current step runs.
"""
from __future__ import annotations

import collections
import concurrent.futures
import os
import threading
import queue as queue_mod
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

import jax
import numpy as np

from dnn_page_vectors_tpu.config import Config
from dnn_page_vectors_tpu.data.jsonl import JsonlCorpus
from dnn_page_vectors_tpu.data.toy import ToyCorpus
from dnn_page_vectors_tpu.data.trigram import TrigramTokenizer
from dnn_page_vectors_tpu.data.words import WordTokenizer
from dnn_page_vectors_tpu.data.subword import SubwordTokenizer
from dnn_page_vectors_tpu.utils.profiling import PipelineProfiler

Batch = Dict[str, np.ndarray]


def ordered_parallel_map(fn: Callable[[Any], Any], items: Iterable[Any],
                         workers: int, depth: int = 2) -> Iterator[Any]:
    """Map `fn` over `items` with a pool of `workers` threads, yielding
    results strictly in item order — the reassembly half of the multi-worker
    host producer. In-flight work is bounded at workers + depth submissions
    (the bounded queue: host memory stays O(window), and an abandoned
    consumer never leaves an unbounded backlog).

    Exception contract: a worker exception re-raises HERE, at the failed
    item's position in the output order — the consumer sees it exactly
    where the serial path would have raised, so a downstream accumulator
    (e.g. a store shard) can never be silently truncated. Later items that
    already completed are discarded, pending ones are cancelled.

    Threads only: corpus readers keep per-thread file handles
    (data/jsonl.py) and the tokenizers' C++ batch encoders drop the GIL
    (data/subword.py), so CPython threads genuinely overlap the
    read+tokenize work.
    """
    if workers <= 1:
        for item in items:
            yield fn(item)
        return
    ex = concurrent.futures.ThreadPoolExecutor(
        max_workers=workers, thread_name_prefix="tokenize-worker")
    futs: collections.deque = collections.deque()
    try:
        for item in items:
            futs.append(ex.submit(fn, item))
            if len(futs) >= workers + depth:
                yield futs.popleft().result()
        while futs:
            yield futs.popleft().result()
    finally:
        ex.shutdown(wait=False, cancel_futures=True)


def _waterfill(lens: np.ndarray, cap: int) -> np.ndarray:
    """Clip a row's page token-lengths to fit `cap` total: the classic
    waterfilling threshold — largest pages lose tokens first, small pages
    keep everything. Deterministic: threshold by binary search, leftover
    slack dealt one token at a time to the longest pages (stable order)."""
    lens = np.asarray(lens, np.int64)
    total = int(lens.sum())
    if total <= cap or lens.max(initial=0) == 0:
        return lens.copy()
    lo, hi = 0, int(lens.max())
    while lo < hi:                      # largest T with sum(min(len,T))<=cap
        mid = (lo + hi + 1) // 2
        if int(np.minimum(lens, mid).sum()) <= cap:
            lo = mid
        else:
            hi = mid - 1
    out = np.minimum(lens, lo)
    slack = cap - int(out.sum())
    for i in np.argsort(-lens, kind="stable"):
        if slack <= 0:
            break
        if lens[i] > out[i]:
            out[i] += 1
            slack -= 1
    return out


def pack_segments(enc: np.ndarray, pack: int
                  ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Sequence packing (train.pack_pages, docs/MFU.md): place `pack`
    consecutive tokenized pages into ONE row of the same length.

    enc: [B, L] int32 token ids, 0 = pad, tokens left-aligned (every
    tokenizer in data/ pads only at the tail). B must divide by `pack`.
    Returns (rows [B/pack, L], seg [B/pack, L], pos [B/pack, L]):
      rows  the packed token ids — page s of row r is the byte-identical
            token run of input page r*pack+s (clipped only when the row's
            combined length overflows L, largest pages first — waterfill);
      seg   segment ids, 0 = pad, s+1 on page s's tokens — the attention /
            pooling mask consumed by the transformer towers;
      pos   per-page LOCAL positions (0..len-1), so BERT's absolute
            position embedding restarts for every packed page.

    Everything is a pure function of the token lengths — deterministic,
    and byte-identical to the unpacked tokens whenever the row fits
    (pinned by tests/test_packing.py)."""
    B, L = enc.shape[:2]
    if enc.ndim != 2:
        raise ValueError("pack_segments wants [B, L] subword/word ids; "
                         "trigram [B, L, K] batches cannot pack")
    if B % pack:
        raise ValueError(f"batch of {B} pages must divide pack={pack}")
    R = B // pack
    rows = np.zeros((R, L), enc.dtype)
    seg = np.zeros((R, L), np.int32)
    pos = np.zeros((R, L), np.int32)
    lens = (enc != 0).sum(axis=1)
    for r in range(R):
        budget = _waterfill(lens[r * pack:(r + 1) * pack], L)
        c = 0
        for s in range(pack):
            n = int(budget[s])
            if n == 0:
                continue
            rows[r, c:c + n] = enc[r * pack + s, :n]
            seg[r, c:c + n] = s + 1
            pos[r, c:c + n] = np.arange(n)
            c += n
    return rows, seg, pos


def build_corpus(cfg: Config):
    d = cfg.data
    if d.corpus == "toy":
        return ToyCorpus(num_pages=d.num_pages, seed=d.seed,
                         page_len=d.page_len, query_len=d.query_len,
                         languages=d.languages, num_topics=d.num_topics)
    if d.corpus.startswith("jsonl:"):
        return JsonlCorpus(d.corpus[len("jsonl:"):])
    raise ValueError(f"unknown corpus {d.corpus!r} (want 'toy' or 'jsonl:<path>')")


def _corpus_fingerprint(corpus) -> str:
    fp = getattr(corpus, "fingerprint", None)
    return fp() if callable(fp) else f"{type(corpus).__name__}:{corpus.num_pages}"


def build_tokenizer(cfg: Config, corpus, cache_dir: Optional[str] = None):
    """Builds (query_tok, page_tok). Trained vocabs (word/subword) are cached
    under cache_dir so later embed/eval/mine runs reuse the EXACT vocab the
    model was trained with — page vectors are only comparable across runs if
    token ids are (vector-store reproducibility, SURVEY.md §3 #20).

    Honesty contract (VERDICT r1 #3): the built tokenizer's vocab_size must
    EQUAL config.data.vocab_size — training raises rather than silently
    clamping, and a cached vocab is only reused when its recorded
    (vocab_size, corpus fingerprint) provenance matches the current config
    (ADVICE r1: stale-cache divergence).
    """
    d = cfg.data
    if d.tokenizer == "trigram":   # stateless hashing: nothing to cache
        q = TrigramTokenizer(d.trigram_buckets, max_words=d.query_len,
                             k=d.trigrams_per_word)
        p = TrigramTokenizer(d.trigram_buckets, max_words=d.page_len,
                             k=d.trigrams_per_word)
        return q, p
    cache = (os.path.join(cache_dir, f"tokenizer_{d.tokenizer}.json")
             if cache_dir else None)
    meta = {"vocab_size": d.vocab_size,
            "corpus": _corpus_fingerprint(corpus)}
    if d.tokenizer == "word":
        tok = None
        if cache and os.path.exists(cache):
            tok = WordTokenizer.load(cache)
            if tok.meta != meta:   # stale: config/corpus changed since save
                tok = None
        if tok is None:
            tok = WordTokenizer.train(
                corpus.all_texts(), vocab_size=d.vocab_size,
                max_words=d.page_len, strict_vocab=True)
            tok.meta = meta
            if cache:
                tok.save(cache)
        q = WordTokenizer(tok.vocab, max_words=d.query_len)
        return q, tok
    if d.tokenizer in ("wordpiece", "sentencepiece"):
        tok = None
        if cache and os.path.exists(cache):
            tok = SubwordTokenizer.load(cache)
            tok.max_tokens = d.page_len
            if tok.meta != meta:
                tok = None
        if tok is None:
            # sample size scales with the requested vocab: merge capacity is
            # bounded by unique-word count (~word-sample/27 on the toy
            # corpus), and a 250k-piece vocab needs a far bigger sample than
            # the 2M-word default that suits 30k
            tok = SubwordTokenizer.train(
                corpus.all_texts(), vocab_size=d.vocab_size,
                style=d.tokenizer, max_tokens=d.page_len, strict_vocab=True,
                max_train_words=max(2_000_000, 60 * d.vocab_size))
            tok.meta = meta
            if cache:
                tok.save(cache)
        q = SubwordTokenizer(tok.vocab, style=tok.style, max_tokens=d.query_len)
        q.threads = tok.threads = d.tokenize_threads
        return q, tok
    raise ValueError(f"unknown tokenizer {d.tokenizer!r}")


class TrainBatcher:
    """Deterministic shuffled (query, page) training batches.

    Yields {"query": [b, ...], "page": [b, ...], "page_id": [b]} numpy
    batches; static shapes so the jitted step compiles once.

    Multi-host (VERDICT r1 #6): every process derives the SAME global batch
    ids from the shared seed, but tokenizes/materialises ONLY its
    `process_index`-th contiguous slice (b = batch_size / process_count
    rows) — host work and memory stay O(global batch / hosts). The prefetch
    layer reassembles the global array with
    jax.make_array_from_process_local_data. Contiguous slicing matches the
    mesh 'data' axis order because make_mesh lays devices out in
    jax.devices() order (process-major).

    `workers` > 1 runs the per-step read+tokenize (query, page, and mined
    hard negatives — serially the largest host cost of a train step) on a
    pool of tokenizer workers, reassembled in batch order
    (ordered_parallel_map): batches are byte-identical to the serial path,
    just produced concurrently. The id schedule itself stays single-threaded
    (one permutation per epoch), so resume/multi-host determinism is
    untouched.
    """

    def __init__(self, corpus: ToyCorpus, query_tok, page_tok,
                 batch_size: int, seed: int = 0, start_step: int = 0,
                 hard_negative_lookup: Optional[Callable[[np.ndarray], np.ndarray]] = None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 workers: int = 1,
                 profiler: Optional[PipelineProfiler] = None,
                 pack: int = 1):
        if batch_size > corpus.num_pages:
            raise ValueError(
                f"batch_size {batch_size} > corpus size {corpus.num_pages}: "
                "no full batch can ever be formed")
        self.corpus = corpus
        self.query_tok = query_tok
        self.page_tok = page_tok
        self.batch_size = batch_size
        self.seed = seed
        # resume point: global step -> (epoch, offset); makes a restored run
        # continue the exact data order of an uninterrupted one (§5.4)
        self.start_step = start_step
        # maps [B] gold page ids -> [B, H] hard-negative page ids (mine/ann.py)
        self.hard_negative_lookup = hard_negative_lookup
        self.process_index = (jax.process_index() if process_index is None
                              else process_index)
        self.process_count = (jax.process_count() if process_count is None
                              else process_count)
        if batch_size % self.process_count:
            raise ValueError(
                f"batch_size {batch_size} must divide process_count "
                f"{self.process_count} (contiguous per-host slices)")
        self.workers = max(1, workers)
        self.profiler = profiler
        # Sequence packing (train.pack_pages): each yielded batch carries
        # batch_size PAGES in batch_size/pack packed page ROWS (+ the
        # page_seg / page_pos mask arrays); the id schedule is untouched.
        self.pack = max(1, pack)
        if self.pack > 1 and (batch_size // self.process_count) % self.pack:
            raise ValueError(
                f"per-process batch {batch_size // self.process_count} must "
                f"divide train.pack_pages={self.pack}")

    @property
    def steps_per_epoch(self) -> int:
        return self.corpus.num_pages // self.batch_size

    def _id_stream(self) -> Iterator[np.ndarray]:
        """The deterministic batch-id schedule, independent of who
        materializes it — the work descriptors the tokenizer workers pull."""
        n = self.corpus.num_pages
        epoch = self.start_step // self.steps_per_epoch
        skip = self.start_step % self.steps_per_epoch
        local = self.batch_size // self.process_count
        lo = self.process_index * local
        while True:
            rng = np.random.default_rng(self.seed + epoch)
            order = rng.permutation(n)
            for b in range(skip, self.steps_per_epoch):
                s = b * self.batch_size
                yield order[s + lo: s + lo + local]   # this process's slice
            skip = 0
            epoch += 1

    def __iter__(self) -> Iterator[Batch]:
        return ordered_parallel_map(self._materialize, self._id_stream(),
                                    self.workers)

    def _materialize(self, ids: np.ndarray) -> Batch:
        prof = self.profiler or _NULL_PROFILER
        with prof.stage("read"):
            queries = _query_texts(self.corpus, ids)
            pages = _page_texts(self.corpus, ids)
        with prof.stage("tokenize"):
            batch: Batch = {
                "query": self.query_tok.encode_batch(queries),
                "page": self.page_tok.encode_batch(pages),
                "page_id": ids.astype(np.int32),
            }
        if self.pack > 1:
            with prof.stage("pack"):
                rows, seg, pos = pack_segments(batch["page"], self.pack)
            batch["page"] = rows
            batch["page_seg"] = seg
            batch["page_pos"] = pos
        if self.hard_negative_lookup is not None:
            neg_ids = self.hard_negative_lookup(ids)  # [B, H]
            flat = neg_ids.reshape(-1)
            with prof.stage("read"):
                neg_pages = _page_texts(self.corpus, flat)
            with prof.stage("tokenize"):
                enc = self.page_tok.encode_batch(neg_pages)
            batch["neg_page"] = enc.reshape(neg_ids.shape + enc.shape[1:])
        return batch


_NULL_PROFILER = PipelineProfiler()   # shared sink when no profiler is wired


def _page_texts(corpus, ids) -> list:
    """Bulk page reads where the corpus supports them (JsonlCorpus's
    fast-extract path — the difference between the host producer keeping up
    with the chip or not); per-id fallback otherwise."""
    bulk = getattr(corpus, "page_texts", None)
    if bulk is not None:
        return bulk(ids)
    return [corpus.page_text(int(i)) for i in ids]


def _query_texts(corpus, ids) -> list:
    bulk = getattr(corpus, "query_texts", None)
    if bulk is not None:
        return bulk(ids)
    return [corpus.query_text(int(i)) for i in ids]


def iter_corpus_batches(corpus: ToyCorpus, page_tok, batch_size: int,
                        start: int = 0, stop: Optional[int] = None,
                        workers: int = 1,
                        profiler: Optional[PipelineProfiler] = None
                        ) -> Iterator[Batch]:
    """Fixed-order corpus sweep for bulk-embed; last batch is padded to keep
    shapes static (pad rows flagged with page_id == -1).

    `workers` > 1 fans the per-batch read+tokenize over a pool of tokenizer
    workers pulling id-range descriptors from the sweep, reassembled IN
    ORDER through a bounded window (ordered_parallel_map) — batches, and
    therefore the embedded vectors, are byte-identical to the serial path,
    and a worker exception re-raises at its batch's position instead of
    truncating the stream."""
    stop = corpus.num_pages if stop is None else min(stop, corpus.num_pages)
    prof = profiler or _NULL_PROFILER
    # Fused native extract+tokenize (docs/MFU.md "host pipeline"): when
    # the corpus hands out raw jsonl lines and the tokenizer carries the
    # C++ encoder, the per-record Python field extract and the UTF-8
    # decode/re-encode round trip both disappear — the raw line buffer
    # goes straight into token ids. Byte-identical to the plain path
    # (tests/test_native.py); silently off when either side is missing.
    fused = (getattr(page_tok, "encode_jsonl_lines", None) is not None
             and getattr(corpus, "page_lines", None) is not None)

    def _make(s: int) -> Batch:
        nonlocal fused
        ids = np.arange(s, min(s + batch_size, stop))
        enc = None
        if fused:
            with prof.stage("read"):
                lines = corpus.page_lines(ids)
            with prof.stage("tokenize"):
                enc = page_tok.encode_jsonl_lines(lines, "page")
            if enc is None:      # no native encoder: stay on the plain path
                fused = False
        if enc is None:
            with prof.stage("read"):
                pages = _page_texts(corpus, ids)
            with prof.stage("tokenize"):
                enc = page_tok.encode_batch(pages)
        if len(ids) < batch_size:
            pad = batch_size - len(ids)
            enc = np.concatenate([enc, np.zeros((pad,) + enc.shape[1:], enc.dtype)])
            ids = np.concatenate([ids, -np.ones(pad, dtype=ids.dtype)])
        return {"page": enc, "page_id": ids.astype(np.int32)}

    return ordered_parallel_map(_make, range(start, stop, batch_size),
                                workers)


def prefetch_to_device(it: Iterator[Batch], sharding: Optional[Any] = None,
                       depth: int = 2,
                       profiler: Optional[PipelineProfiler] = None
                       ) -> Iterator[Any]:
    """Double-buffered host->HBM pipeline.

    A background thread tokenizes/materialises numpy batches; the consumer
    side issues the (async) device_put so `depth` batches are in flight while
    the TPU runs the current step. Producer exceptions re-raise in the
    consumer (a swallowed tokenizer crash must not look like end-of-stream —
    embed_corpus would record a short shard as complete). Abandoning the
    generator (GeneratorExit) unblocks and stops the producer thread.

    Multi-process: upstream batchers yield only this process's slice;
    jax.make_array_from_process_local_data assembles the global sharded
    array (each host feeds exactly its addressable shards, VERDICT r1 #6).

    `profiler` records the consumer-side stall waiting for a host batch
    (produce_wait — the number that says the job is host-production-bound)
    and the host->device placement (h2d).
    """
    prof = profiler or _NULL_PROFILER
    q: "queue_mod.Queue[Any]" = queue_mod.Queue(maxsize=depth)
    stop = threading.Event()
    _END = object()

    def _producer() -> None:
        try:
            for item in it:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue_mod.Full:
                        continue
                if stop.is_set():
                    return
            _finish(_END)
        except BaseException as e:  # re-raised consumer-side
            _finish(e)

    def _finish(token: Any) -> None:
        while not stop.is_set():
            try:
                q.put(token, timeout=0.1)
                return
            except queue_mod.Full:
                continue

    t = threading.Thread(target=_producer, daemon=True)
    t.start()

    buf: collections.deque[Any] = collections.deque()

    # Assemble-from-local-slices only when the target sharding actually spans
    # other processes (the SPMD training mesh). A process-LOCAL mesh in a
    # multi-process job (multihost embed) takes the plain device_put path —
    # its batches are complete, not per-process slices.
    multiprocess = (jax.process_count() > 1 and sharding is not None
                    and not sharding.is_fully_addressable)

    def _put(batch: Batch) -> Any:
        with prof.stage("h2d"):
            if sharding is None:
                return jax.device_put(batch)
            if multiprocess:
                return jax.tree_util.tree_map(
                    lambda arr: jax.make_array_from_process_local_data(
                        sharding, np.asarray(arr)), batch)
            return jax.device_put(batch, jax.tree_util.tree_map(
                lambda _: sharding, batch))

    try:
        while True:
            while len(buf) < depth:
                with prof.stage("produce_wait"):
                    item = q.get()
                if item is _END or isinstance(item, BaseException):
                    break
                buf.append(_put(item))
            else:
                yield buf.popleft()
                continue
            if isinstance(item, BaseException):
                raise RuntimeError("prefetch producer failed") from item
            while buf:  # producer finished cleanly: drain
                yield buf.popleft()
            return
    finally:
        stop.set()
