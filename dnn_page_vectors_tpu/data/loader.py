"""Batching + host->device prefetch (SURVEY.md §3 #4).

The reference keeps tokenization and loading on the host feeding the
accelerator (BASELINE.json:5). Here the hot principle is: nothing host-side
may ever stall the jitted step. `prefetch_to_device` keeps `depth` batches
already transferred (with their target NamedSharding, so each host only
materialises its addressable shards) while the current step runs.
"""
from __future__ import annotations

import collections
import os
import threading
import queue as queue_mod
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import numpy as np

from dnn_page_vectors_tpu.config import Config
from dnn_page_vectors_tpu.data.jsonl import JsonlCorpus
from dnn_page_vectors_tpu.data.toy import ToyCorpus
from dnn_page_vectors_tpu.data.trigram import TrigramTokenizer
from dnn_page_vectors_tpu.data.words import WordTokenizer
from dnn_page_vectors_tpu.data.subword import SubwordTokenizer

Batch = Dict[str, np.ndarray]


def build_corpus(cfg: Config):
    d = cfg.data
    if d.corpus == "toy":
        return ToyCorpus(num_pages=d.num_pages, seed=d.seed,
                         page_len=d.page_len, query_len=d.query_len)
    if d.corpus.startswith("jsonl:"):
        return JsonlCorpus(d.corpus[len("jsonl:"):])
    raise ValueError(f"unknown corpus {d.corpus!r} (want 'toy' or 'jsonl:<path>')")


def build_tokenizer(cfg: Config, corpus, cache_dir: Optional[str] = None):
    """Builds (query_tok, page_tok). Trained vocabs (word/subword) are cached
    under cache_dir so later embed/eval/mine runs reuse the EXACT vocab the
    model was trained with — page vectors are only comparable across runs if
    token ids are (vector-store reproducibility, SURVEY.md §3 #20)."""
    d = cfg.data
    if d.tokenizer == "trigram":   # stateless hashing: nothing to cache
        q = TrigramTokenizer(d.trigram_buckets, max_words=d.query_len,
                             k=d.trigrams_per_word)
        p = TrigramTokenizer(d.trigram_buckets, max_words=d.page_len,
                             k=d.trigrams_per_word)
        return q, p
    cache = (os.path.join(cache_dir, f"tokenizer_{d.tokenizer}.json")
             if cache_dir else None)
    if d.tokenizer == "word":
        if cache and os.path.exists(cache):
            tok = WordTokenizer.load(cache)
        else:
            tok = WordTokenizer.train(
                corpus.all_texts(limit=min(corpus.num_pages, 20_000)),
                vocab_size=d.vocab_size, max_words=d.page_len)
            if cache:
                tok.save(cache)
        q = WordTokenizer(tok.vocab, max_words=d.query_len)
        return q, tok
    if d.tokenizer in ("wordpiece", "sentencepiece"):
        if cache and os.path.exists(cache):
            tok = SubwordTokenizer.load(cache)
            tok.max_tokens = d.page_len
        else:
            tok = SubwordTokenizer.train(
                corpus.all_texts(limit=min(corpus.num_pages, 5_000)),
                vocab_size=min(d.vocab_size, 8_192), style=d.tokenizer,
                max_tokens=d.page_len)
            if cache:
                tok.save(cache)
        q = SubwordTokenizer(tok.vocab, style=tok.style, max_tokens=d.query_len)
        return q, tok
    raise ValueError(f"unknown tokenizer {d.tokenizer!r}")


class TrainBatcher:
    """Deterministic shuffled (query, page) training batches.

    Yields {"query": [B, ...], "page": [B, ...], "page_id": [B]} numpy
    batches; static shapes so the jitted step compiles once.
    """

    def __init__(self, corpus: ToyCorpus, query_tok, page_tok,
                 batch_size: int, seed: int = 0, start_step: int = 0,
                 hard_negative_lookup: Optional[Callable[[np.ndarray], np.ndarray]] = None):
        if batch_size > corpus.num_pages:
            raise ValueError(
                f"batch_size {batch_size} > corpus size {corpus.num_pages}: "
                "no full batch can ever be formed")
        self.corpus = corpus
        self.query_tok = query_tok
        self.page_tok = page_tok
        self.batch_size = batch_size
        self.seed = seed
        # resume point: global step -> (epoch, offset); makes a restored run
        # continue the exact data order of an uninterrupted one (§5.4)
        self.start_step = start_step
        # maps [B] gold page ids -> [B, H] hard-negative page ids (mine/ann.py)
        self.hard_negative_lookup = hard_negative_lookup

    @property
    def steps_per_epoch(self) -> int:
        return self.corpus.num_pages // self.batch_size

    def __iter__(self) -> Iterator[Batch]:
        n = self.corpus.num_pages
        epoch = self.start_step // self.steps_per_epoch
        skip = self.start_step % self.steps_per_epoch
        while True:
            rng = np.random.default_rng(self.seed + epoch)
            order = rng.permutation(n)
            for b in range(skip, self.steps_per_epoch):
                s = b * self.batch_size
                ids = order[s: s + self.batch_size]
                yield self._materialize(ids)
            skip = 0
            epoch += 1

    def _materialize(self, ids: np.ndarray) -> Batch:
        queries = [self.corpus.query_text(int(i)) for i in ids]
        pages = [self.corpus.page_text(int(i)) for i in ids]
        batch: Batch = {
            "query": self.query_tok.encode_batch(queries),
            "page": self.page_tok.encode_batch(pages),
            "page_id": ids.astype(np.int32),
        }
        if self.hard_negative_lookup is not None:
            neg_ids = self.hard_negative_lookup(ids)  # [B, H]
            flat = neg_ids.reshape(-1)
            neg_pages = [self.corpus.page_text(int(i)) for i in flat]
            enc = self.page_tok.encode_batch(neg_pages)
            batch["neg_page"] = enc.reshape(neg_ids.shape + enc.shape[1:])
        return batch


def iter_corpus_batches(corpus: ToyCorpus, page_tok, batch_size: int,
                        start: int = 0, stop: Optional[int] = None
                        ) -> Iterator[Batch]:
    """Fixed-order corpus sweep for bulk-embed; last batch is padded to keep
    shapes static (pad rows flagged with page_id == -1)."""
    stop = corpus.num_pages if stop is None else min(stop, corpus.num_pages)
    for s in range(start, stop, batch_size):
        ids = np.arange(s, min(s + batch_size, stop))
        pages = [corpus.page_text(int(i)) for i in ids]
        enc = page_tok.encode_batch(pages)
        if len(ids) < batch_size:
            pad = batch_size - len(ids)
            enc = np.concatenate([enc, np.zeros((pad,) + enc.shape[1:], enc.dtype)])
            ids = np.concatenate([ids, -np.ones(pad, dtype=ids.dtype)])
        yield {"page": enc, "page_id": ids.astype(np.int32)}


def prefetch_to_device(it: Iterator[Batch], sharding: Optional[Any] = None,
                       depth: int = 2) -> Iterator[Any]:
    """Double-buffered host->HBM pipeline.

    A background thread tokenizes/materialises numpy batches; the consumer
    side issues the (async) device_put so `depth` batches are in flight while
    the TPU runs the current step. Producer exceptions re-raise in the
    consumer (a swallowed tokenizer crash must not look like end-of-stream —
    embed_corpus would record a short shard as complete). Abandoning the
    generator (GeneratorExit) unblocks and stops the producer thread.
    """
    q: "queue_mod.Queue[Any]" = queue_mod.Queue(maxsize=depth)
    stop = threading.Event()
    _END = object()

    def _producer() -> None:
        try:
            for item in it:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue_mod.Full:
                        continue
                if stop.is_set():
                    return
            _finish(_END)
        except BaseException as e:  # re-raised consumer-side
            _finish(e)

    def _finish(token: Any) -> None:
        while not stop.is_set():
            try:
                q.put(token, timeout=0.1)
                return
            except queue_mod.Full:
                continue

    t = threading.Thread(target=_producer, daemon=True)
    t.start()

    buf: collections.deque[Any] = collections.deque()

    def _put(batch: Batch) -> Any:
        if sharding is None:
            return jax.device_put(batch)
        return jax.device_put(batch, jax.tree_util.tree_map(
            lambda _: sharding, batch))

    try:
        while True:
            while len(buf) < depth:
                item = q.get()
                if item is _END or isinstance(item, BaseException):
                    break
                buf.append(_put(item))
            else:
                yield buf.popleft()
                continue
            if isinstance(item, BaseException):
                raise RuntimeError("prefetch producer failed") from item
            while buf:  # producer finished cleanly: drain
                yield buf.popleft()
            return
    finally:
        stop.set()
