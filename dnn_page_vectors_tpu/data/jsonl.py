"""Real-corpus reader: jsonl files of {"query": ..., "page": ...} records
(SURVEY.md §3 #4 'corpus readers'). Record id = line number, mirroring the
ToyCorpus interface so every pipeline runs unchanged on user data.

Memory model (VERDICT r1 #6): one startup pass builds an int64 line-offset
index (8 bytes/record — 800 MB for 100M records, vs holding the text);
record reads seek + parse on demand, so host memory stays O(batch) no
matter the corpus size. File handles are per-thread (the prefetch producer
runs in its own thread). At 1B-page scale a deployment shards the corpus
into one jsonl file per host and each process reads only its shard (the
bulk-embed job already sweeps [start, stop) ranges, call stack §4.2).
"""
from __future__ import annotations

import json
import os
import sys
import threading
from typing import Iterator, Tuple

import numpy as np


class JsonlCorpus:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        offsets = self._index_offsets()
        if offsets.size == 0:
            raise ValueError(f"empty corpus: {path}")
        self._offsets = offsets
        self._local = threading.local()
        st = os.stat(self.path)
        self._fingerprint = (f"jsonl:{self.path}:{st.st_size}:"
                             f"{st.st_mtime_ns}:{len(offsets)}")

    def _index_offsets(self) -> np.ndarray:
        """Startup scan: byte offset of every non-blank line. C++ fast path
        (native/jsonl_index.cpp, measured 3.6x over the interpreter loop —
        ~7min -> ~2min at 1B records), pure-Python fallback with identical
        semantics (tests/test_native.py asserts bit-equality)."""
        self.native_index = False
        try:
            from dnn_page_vectors_tpu.native import jsonl_native
            out = jsonl_native.index_offsets(self.path)
            self.native_index = True
            return out
        except Exception as e:
            # visible, once per corpus: at 1B records the silent fallback
            # would cost ~5 min of startup with no signal to the operator
            print(f"WARNING: native jsonl index unavailable "
                  f"({type(e).__name__}: {e}); falling back to the Python "
                  "scan", file=sys.stderr)
        offsets = []
        with open(self.path, "rb") as f:
            pos = 0
            for line in f:
                if line.strip():
                    offsets.append(pos)
                pos += len(line)
        return np.asarray(offsets, dtype=np.int64)

    def fingerprint(self) -> str:
        """Stable identity for tokenizer-cache invalidation."""
        return self._fingerprint

    def _file(self):
        f = getattr(self._local, "f", None)
        if f is None:
            f = self._local.f = open(self.path, "rb")
        return f

    def _record(self, i: int) -> dict:
        f = self._file()
        f.seek(int(self._offsets[i]))
        return json.loads(f.readline())

    @property
    def num_pages(self) -> int:
        return len(self._offsets)

    @staticmethod
    def _extract(line: bytes, key: bytes):
        """Pull one string field out of a jsonl line without a full JSON
        parse. json.loads costs ~9 us/record — at the bulk-embed producer
        that caps the host at ~90k pages/s, right AT the measured single
        chip device rate, so the full parse is the difference between the
        host keeping up or not (docs/SCALING.md host budget). Returns None
        whenever the value needs real parsing (escapes / non-string / key
        absent / duplicate key / any nested object, where a nested key
        could shadow the top-level one) and the caller falls back to
        json.loads — correctness never depends on the fast path.
        Duplicate keys (ADVICE r5): json.loads keeps the LAST occurrence
        while a naive find returns the FIRST, so any second occurrence
        punts to the full parse."""
        if b"\\" in line or line.find(b"{", 1) >= 0:
            return None                       # escapes or nesting: punt
        j = line.find(key)                    # e.g. b'"page":'
        if j < 0:
            return None
        if line.find(key, j + len(key)) >= 0:
            return None                   # duplicate key: json semantics
        j += len(key)
        while j < len(line) and line[j] in b" \t":
            j += 1
        if j >= len(line) or line[j] != 0x22:           # opening '"'
            return None
        j += 1
        e = line.find(b'"', j)
        if e < 0:
            return None
        return line[j:e].decode("utf-8")

    def _texts_bulk(self, ids, key: bytes, getter):
        """Batched record reads: one seek+readline per record, fast field
        extraction with per-record json.loads fallback (measured ~4x over
        per-record json.loads on the synth corpus)."""
        f = self._file()
        out = []
        for i in ids:
            f.seek(int(self._offsets[int(i)]))
            line = f.readline()
            v = self._extract(line, key)
            out.append(getter(json.loads(line)) if v is None else v)
        return out

    def page_lines(self, ids) -> list:
        """Raw line buffers for the fused native extract+tokenize path
        (SubwordTokenizer.encode_jsonl_lines): one seek+readline per
        record and NOTHING else on the Python side — no field extract,
        no bytes->str->bytes round trip."""
        f = self._file()
        out = []
        for i in ids:
            f.seek(int(self._offsets[int(i)]))
            out.append(f.readline())
        return out

    def page_texts(self, ids) -> list:
        return self._texts_bulk(ids, b'"page":', lambda r: r["page"])

    def query_texts(self, ids) -> list:
        return self._texts_bulk(ids, b'"query":',
                                lambda r: r.get("query", ""))

    def page_text(self, i: int) -> str:
        return self.page_texts([i])[0]

    def query_text(self, i: int) -> str:
        return self.query_texts([i])[0]

    def pairs(self, start: int = 0, stop: int | None = None
              ) -> Iterator[Tuple[int, str, str]]:
        stop = self.num_pages if stop is None else min(stop, self.num_pages)
        for i in range(start, stop):
            rec = self._record(i)
            yield i, rec.get("query", ""), rec["page"]

    def all_texts(self, limit: int | None = None) -> Iterator[str]:
        stop = self.num_pages if limit is None else min(limit, self.num_pages)
        for i in range(stop):
            rec = self._record(i)
            yield rec["page"]
            if rec.get("query", ""):
                yield rec["query"]
