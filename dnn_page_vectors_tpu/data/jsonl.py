"""Real-corpus reader: jsonl files of {"query": ..., "page": ...} records
(SURVEY.md §3 #4 'corpus readers'). Record id = line number, mirroring the
ToyCorpus interface so every pipeline runs unchanged on user data.

Texts are held in memory on the host (the loader is host-side per
BASELINE.json:5); at 1B-page scale a deployment shards the corpus into one
jsonl file per host and each process reads only its shard (the bulk-embed
job already sweeps [start, stop) ranges, call stack §4.2).
"""
from __future__ import annotations

import json
from typing import Iterator, Tuple


class JsonlCorpus:
    def __init__(self, path: str):
        self.path = path
        self._queries: list[str] = []
        self._pages: list[str] = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                self._queries.append(rec.get("query", ""))
                self._pages.append(rec["page"])
        if not self._pages:
            raise ValueError(f"empty corpus: {path}")

    @property
    def num_pages(self) -> int:
        return len(self._pages)

    def page_text(self, i: int) -> str:
        return self._pages[i]

    def query_text(self, i: int) -> str:
        return self._queries[i]

    def pairs(self, start: int = 0, stop: int | None = None
              ) -> Iterator[Tuple[int, str, str]]:
        stop = self.num_pages if stop is None else min(stop, self.num_pages)
        for i in range(start, stop):
            yield i, self._queries[i], self._pages[i]

    def all_texts(self, limit: int | None = None) -> Iterator[str]:
        stop = self.num_pages if limit is None else min(limit, self.num_pages)
        for i in range(stop):
            yield self._pages[i]
            if self._queries[i]:
                yield self._queries[i]
