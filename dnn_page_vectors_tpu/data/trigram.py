"""Char-trigram hashing tokenizer (CDSSM-style; SURVEY.md §3 #1).

The classic CDSSM letter-trigram representation is a ~30k-dim count vector
per word. That layout wastes MXU cycles on TPU; instead each word is encoded
as up to K hashed trigram ids and the encoder sums their embeddings
(embedding-bag), which is a dense [B, L, K] gather + reduction XLA maps onto
the MXU-friendly path. Output ids are 1..buckets with 0 reserved for padding.

Hashing is FNV-1a — stable across processes/runs (Python's builtin hash() is
salted and would break vector-store reproducibility). If the optional C++
fast path (dnn_page_vectors_tpu.native) has been built, encode() dispatches
to it; otherwise the pure-Python loop below runs.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = 0xFFFFFFFFFFFFFFFF


def fnv1a(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & _MASK64
    return h


def word_trigrams(word: str) -> List[str]:
    padded = f"#{word}#"
    if len(padded) < 3:
        return [padded]
    return [padded[i:i + 3] for i in range(len(padded) - 2)]


class TrigramTokenizer:
    """text -> int32 ids of shape [max_words, k] (0 = pad)."""

    def __init__(self, buckets: int = 16_384, max_words: int = 64, k: int = 8,
                 use_native: bool = True):
        self.buckets = buckets
        self.max_words = max_words
        self.k = k
        self._native = None
        if use_native:
            try:  # C++ fast path (builds on first import); Python fallback
                from dnn_page_vectors_tpu.native import trigram_native
                # Self-check: the two paths must agree bit-exactly or the
                # vector store is not reproducible across hosts (ADVICE r1).
                # The probe covers Unicode whitespace (NBSP, LS), multi-byte
                # words, a lone surrogate, and a word longer than any fixed
                # C buffer — a stale .so that mishandles any of these must
                # disable itself here, not diverge silently in production.
                probe = ("ab cd ef " + "x" * 300 + " fin"
                         + " 日本語 ünï " + chr(0xD800) + "g")
                native = trigram_native.encode(probe, self.buckets,
                                               self.max_words, self.k)
                if (native == self._encode_py(probe)).all():
                    self._native = trigram_native
            except Exception:
                self._native = None

    @property
    def vocab_size(self) -> int:
        return self.buckets + 1  # + padding id 0

    def _encode_py(self, text: str) -> np.ndarray:
        out = np.zeros((self.max_words, self.k), dtype=np.int32)
        for wi, word in enumerate(text.split()[: self.max_words]):
            tgs = word_trigrams(word)[: self.k]
            for ti, tg in enumerate(tgs):
                # surrogatepass: lone surrogates (a "\ud800" JSON escape in
                # a real corpus) must hash, not crash the loader
                data = tg.encode("utf-8", "surrogatepass")
                out[wi, ti] = 1 + fnv1a(data) % self.buckets
        return out

    def encode(self, text: str) -> np.ndarray:
        if self._native is not None:
            return self._native.encode(text, self.buckets, self.max_words,
                                       self.k)
        return self._encode_py(text)

    def encode_batch(self, texts: Sequence[str]) -> np.ndarray:
        if self._native is not None:
            return self._native.encode_batch(texts, self.buckets,
                                             self.max_words, self.k)
        return np.stack([self.encode(t) for t in texts]) if texts else \
            np.zeros((0, self.max_words, self.k), np.int32)
