"""Word-level tokenizer + frequency vocab (Kim-CNN input; SURVEY.md §3 #2)."""
from __future__ import annotations

import collections
import json
from typing import Iterable, Sequence

import numpy as np

PAD_ID = 0
UNK_ID = 1
_RESERVED = 2


class WordTokenizer:
    """Most-frequent-N word vocab; text -> int32 ids [max_words] (0 pad, 1 unk)."""

    def __init__(self, vocab: dict[str, int], max_words: int = 64):
        self.vocab = vocab
        self.max_words = max_words

    @classmethod
    def train(cls, texts: Iterable[str], vocab_size: int = 30_000,
              max_words: int = 64) -> "WordTokenizer":
        counts: collections.Counter[str] = collections.Counter()
        for text in texts:
            counts.update(text.split())
        # deterministic: sort by (-count, word)
        ranked = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
        vocab = {w: i + _RESERVED for i, (w, _) in
                 enumerate(ranked[: vocab_size - _RESERVED])}
        return cls(vocab, max_words=max_words)

    @property
    def vocab_size(self) -> int:
        return len(self.vocab) + _RESERVED

    def encode(self, text: str) -> np.ndarray:
        out = np.zeros(self.max_words, dtype=np.int32)
        for i, w in enumerate(text.split()[: self.max_words]):
            out[i] = self.vocab.get(w, UNK_ID)
        return out

    def encode_batch(self, texts: Sequence[str]) -> np.ndarray:
        return np.stack([self.encode(t) for t in texts])

    # -- persistence (vector-store reproducibility needs a stable vocab) ----
    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"max_words": self.max_words, "vocab": self.vocab}, f)

    @classmethod
    def load(cls, path: str) -> "WordTokenizer":
        with open(path) as f:
            blob = json.load(f)
        return cls(blob["vocab"], max_words=blob["max_words"])
