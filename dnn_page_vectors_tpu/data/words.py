"""Word-level tokenizer + frequency vocab (Kim-CNN input; SURVEY.md §3 #2)."""
from __future__ import annotations

import collections
import json
from typing import Dict, Iterable, Sequence

import numpy as np

PAD_ID = 0
UNK_ID = 1
_RESERVED = 2


class WordTokenizer:
    """Most-frequent-N word vocab; text -> int32 ids [max_words] (0 pad, 1 unk)."""

    def __init__(self, vocab: dict[str, int], max_words: int = 64,
                 meta: Dict | None = None):
        self.vocab = vocab
        self.max_words = max_words
        # provenance (config vocab_size, corpus fingerprint) — lets the
        # loader detect a stale cache instead of silently reusing it
        self.meta = meta or {}

    @classmethod
    def train(cls, texts: Iterable[str], vocab_size: int = 30_000,
              max_words: int = 64, strict_vocab: bool = False
              ) -> "WordTokenizer":
        """Scan texts until the vocabulary can be filled (early stop at 1.5x
        `vocab_size` unique words keeps the scan O(vocab), not O(corpus), on
        the 1M+/100M-page corpora). strict_vocab=True raises when the corpus
        has fewer unique words than the config claims (VERDICT r1 weak #4)."""
        counts: collections.Counter[str] = collections.Counter()
        target_unique = int((vocab_size - _RESERVED) * 1.5) + 1_000
        for text in texts:
            counts.update(text.split())
            if len(counts) >= target_unique:
                break
        # deterministic: sort by (-count, word)
        ranked = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
        vocab = {w: i + _RESERVED for i, (w, _) in
                 enumerate(ranked[: vocab_size - _RESERVED])}
        tok = cls(vocab, max_words=max_words)
        if strict_vocab and tok.vocab_size != vocab_size:
            raise ValueError(
                f"corpus has only {len(counts)} unique words; cannot build "
                f"the configured {vocab_size}-word vocab. Lower "
                "data.vocab_size or use a larger corpus.")
        return tok

    @property
    def vocab_size(self) -> int:
        return len(self.vocab) + _RESERVED

    def encode(self, text: str) -> np.ndarray:
        out = np.zeros(self.max_words, dtype=np.int32)
        for i, w in enumerate(text.split()[: self.max_words]):
            out[i] = self.vocab.get(w, UNK_ID)
        return out

    def encode_batch(self, texts: Sequence[str]) -> np.ndarray:
        return np.stack([self.encode(t) for t in texts])

    # -- persistence (vector-store reproducibility needs a stable vocab) ----
    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"max_words": self.max_words, "vocab": self.vocab,
                       "meta": self.meta}, f)

    @classmethod
    def load(cls, path: str) -> "WordTokenizer":
        with open(path) as f:
            blob = json.load(f)
        return cls(blob["vocab"], max_words=blob["max_words"],
                   meta=blob.get("meta"))
