"""Bulk synthetic-corpus writer: stream a ToyCorpus-structured query/page
corpus to jsonl at generation rates that keep up with the chip.

`ToyCorpus.page_text` generates one page at a time from a fresh per-page
rng (~6k pages/s — fine for tests, hopeless for materializing the 1M/100M
corpora of SURVEY.md §1 / BASELINE.md:21-24). This writer produces the same
corpus STRUCTURE (per-topic vocabularies over syllable words + two
page-unique key words shared with the gold query, so Recall@k stays
learnable and the eval oracle holds) with block-vectorized numpy sampling
and buffered writes — measured ~54k pages/s single-threaded (~9x the
per-page path; the residual cost is the per-row join+dumps). The output is a plain jsonl file of
{"query": ..., "page": ...} records for data/jsonl.py:JsonlCorpus, whose
C++ line-offset index (native/jsonl_index.cpp) makes random access O(1).

This is the intended scale path: generate once to disk, then train/embed
from the file — page text is read, not recomputed, exactly like a real
crawl (SURVEY.md §4.2 "each host reads its file shards").
"""
from __future__ import annotations

import json
import os
import time
from typing import Optional

import numpy as np

from dnn_page_vectors_tpu.data.toy import _SYLLABLES, _make_word


def write_synth_jsonl(path: str, num_pages: int, seed: int = 0,
                      num_topics: int = 64, page_len: int = 48,
                      query_len: int = 8, block: int = 16_384,
                      start: int = 0, log: Optional[object] = None) -> str:
    """Write pages [start, num_pages) as jsonl records; returns `path`.

    Deterministic in (seed, num_topics, page_len, query_len, block): each
    block re-seeds from its first page id, so page i's text depends on
    which block grid it falls in — `block` is part of the corpus identity,
    NOT a pure performance knob. `start` exists for multi-process
    generation (each host writes its own file shard and feeds it to a
    per-host embed slice, the SURVEY.md §4.2 layout) and must be
    block-aligned so every host draws the same per-block streams as a
    single-process run would.
    """
    if start % block:
        raise ValueError(f"start={start} must be a multiple of "
                         f"block={block} (block grid is part of the "
                         "corpus identity — see docstring)")
    master = np.random.default_rng(seed)
    # same construction order as ToyCorpus so the vocabularies match
    common = np.array(sorted({_make_word(master, 2) for _ in range(300)}),
                      dtype=object)
    topics = [np.array(sorted({_make_word(master, 3) for _ in range(48)}),
                       dtype=object) for _ in range(num_topics)]
    syll = np.array(_SYLLABLES, dtype=object)
    tmp = path + f".tmp.{os.getpid()}"
    t0 = time.perf_counter()
    written = 0
    with open(tmp, "w", buffering=1 << 22) as f:
        for lo in range(start, num_pages, block):
            hi = min(lo + block, num_pages)
            b = hi - lo
            rng = np.random.default_rng((seed * 1_000_003 + lo) & 0x7FFFFFFF)
            ids = np.arange(lo, hi)
            # page body: per-topic words w.p. 0.75 else common words
            topic_of = ids % num_topics
            body = np.empty((b, page_len), dtype=object)
            use_topic = rng.random((b, page_len)) < 0.75
            ci = rng.integers(0, len(common), size=(b, page_len))
            # raw draws mod the per-topic vocab size (set dedup makes each
            # topic's vocabulary a little under 48 words)
            ti = rng.integers(0, 1 << 30, size=(b, page_len))
            body[~use_topic] = common[ci[~use_topic]]
            for t in range(num_topics):          # group rows by topic
                rows = np.nonzero(topic_of == t)[0]
                if rows.size == 0:
                    continue
                m = use_topic[rows]
                sub = body[rows]
                sub[m] = topics[t][ti[rows][m] % len(topics[t])]
                body[rows] = sub
            # two key words per page (4 syllables; first carries the i%10
            # digit suffix like ToyCorpus._key_words), planted 3x each
            ks = rng.integers(0, len(syll), size=(b, 2, 4))
            key0 = syll[ks[:, 0, 0]] + syll[ks[:, 0, 1]] + \
                syll[ks[:, 0, 2]] + syll[ks[:, 0, 3]] + \
                np.array([str(i % 10) for i in ids], dtype=object)
            key1 = syll[ks[:, 1, 0]] + syll[ks[:, 1, 1]] + \
                syll[ks[:, 1, 2]] + syll[ks[:, 1, 3]]
            keys = np.stack([key0, key1], axis=1)
            for j in range(6):                   # each key appears 3x
                body[np.arange(b), (7 * (j + 1) + ids) % page_len] = \
                    keys[:, j % 2]
            # query: both keys + topic filler, deterministic shuffle
            qbody = np.empty((b, query_len), dtype=object)
            qti = rng.integers(0, 1 << 30, size=(b, query_len))
            for t in range(num_topics):
                rows = np.nonzero(topic_of == t)[0]
                if rows.size:
                    qbody[rows] = topics[t][qti[rows] % len(topics[t])]
            qpos = rng.integers(0, query_len - 1, size=b)
            qbody[np.arange(b), qpos] = keys[:, 0]
            qbody[np.arange(b), qpos + 1] = keys[:, 1]
            for r in range(b):
                f.write(json.dumps(
                    {"query": " ".join(qbody[r]), "page": " ".join(body[r])},
                    separators=(",", ":")))
                f.write("\n")
            written += b
            if log is not None and written % (block * 8) == 0:
                rate = written / (time.perf_counter() - t0)
                print(f"[synth] {written}/{num_pages - start} pages "
                      f"({rate:,.0f}/s)", file=log, flush=True)
    os.replace(tmp, path)
    return path
