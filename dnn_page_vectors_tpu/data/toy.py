"""Deterministic synthetic query->page corpus (SURVEY.md §3 #27).

Stands in for the reference's 10k-page toy corpus (BASELINE.json:7) and, at
larger `num_pages`, for its 1M/100M-page corpora. Pages and queries are
generated on demand from the page id, so a 100M-page corpus costs no storage.

Construction: every page belongs to a topic and is mostly topic words plus a
few page-unique "key" words; its query shares the key words and some topic
words. Lexical overlap (at word, trigram, and subword granularity — words are
built from syllables, so character n-grams carry topic signal too) makes
Recall@10 learnable by every encoder in the zoo, which is what the
integration oracle (SURVEY.md §5) needs.
"""
from __future__ import annotations

from typing import Iterator, List, Tuple

import numpy as np

_SYLLABLES = [
    "ba", "be", "bi", "bo", "bu", "da", "de", "di", "do", "du",
    "fa", "fe", "fi", "fo", "fu", "ga", "ge", "gi", "go", "gu",
    "ka", "ke", "ki", "ko", "ku", "la", "le", "li", "lo", "lu",
    "ma", "me", "mi", "mo", "mu", "na", "ne", "ni", "no", "nu",
    "pa", "pe", "pi", "po", "pu", "ra", "re", "ri", "ro", "ru",
    "sa", "se", "si", "so", "su", "ta", "te", "ti", "to", "tu",
    "va", "ve", "vi", "vo", "vu", "za", "ze", "zi", "zo", "zu",
]


def _make_word(rng: np.random.Generator, n_syll: int) -> str:
    idx = rng.integers(0, len(_SYLLABLES), size=n_syll)
    return "".join(_SYLLABLES[i] for i in idx)


class ToyCorpus:
    """Deterministic query->page corpus; page i's gold query is query_text(i).

    Multilingual mode (`languages` > 1, the config-5 cross-lingual eval,
    BASELINE.md:25): each language is a deterministic bijective permutation
    of the syllable inventory, applied to the same underlying content.
    Page i is written in language i % L while its query is written in
    language (i+1) % L — so retrieval only works if the model learns the
    cross-language syllable correspondences (pure lexical overlap is zero
    between different languages). Language 0 is the identity, so
    languages=1 reproduces the monolingual corpus exactly.
    """

    def __init__(self, num_pages: int = 10_000, seed: int = 0,
                 num_topics: int = 64, page_len: int = 48, query_len: int = 8,
                 languages: int = 1):
        self.num_pages = num_pages
        self.seed = seed
        self.num_topics = num_topics
        self.page_len = page_len
        self.query_len = query_len
        self.languages = max(1, languages)
        master = np.random.default_rng(seed)
        # Common words shared by all topics (noise floor).
        self.common_words: List[str] = sorted(
            {_make_word(master, 2) for _ in range(300)})
        # Per-topic vocabularies; each topic draws from its own syllable
        # subset so even character trigrams separate topics.
        self.topic_words: List[List[str]] = []
        for _ in range(num_topics):
            words = sorted({_make_word(master, 3) for _ in range(48)})
            self.topic_words.append(words)
        # Language l remaps syllable s -> _SYLLABLES[perm_l[s]]; language 0
        # is the identity.
        self._syll_index = {s: k for k, s in enumerate(_SYLLABLES)}
        self._lang_perm: List[np.ndarray] = [
            np.arange(len(_SYLLABLES))]
        for l in range(1, self.languages):
            lrng = np.random.default_rng(seed * 5_000_011 + l)
            self._lang_perm.append(lrng.permutation(len(_SYLLABLES)))

    def fingerprint(self) -> str:
        """Stable identity for tokenizer-cache invalidation."""
        return (f"toy:{self.num_pages}:{self.seed}:{self.num_topics}:"
                f"{self.page_len}:{self.query_len}:{self.languages}")

    # -- languages --------------------------------------------------------
    def page_language(self, i: int) -> int:
        return i % self.languages

    def query_language(self, i: int) -> int:
        return (i + 1) % self.languages

    def _translate_word(self, word: str, lang: int) -> str:
        if lang == 0:
            return word
        perm = self._lang_perm[lang]
        out = []
        for j in range(0, len(word) - 1, 2):
            syl = word[j: j + 2]
            k = self._syll_index.get(syl)
            out.append(_SYLLABLES[perm[k]] if k is not None else syl)
        if len(word) % 2:                   # key-word digit suffix survives
            out.append(word[-1])
        return "".join(out)

    def _translate(self, text: str, lang: int) -> str:
        if lang == 0:
            return text
        return " ".join(self._translate_word(w, lang) for w in text.split())

    # -- generation -------------------------------------------------------
    def _page_rng(self, i: int) -> np.random.Generator:
        return np.random.default_rng((self.seed * 1_000_003 + i) & 0x7FFFFFFF)

    def _key_words(self, i: int) -> List[str]:
        """Two words unique to page i, present in both page and query."""
        rng = np.random.default_rng((self.seed * 2_000_003 + i) & 0x7FFFFFFF)
        return [_make_word(rng, 4) + str(i % 10), _make_word(rng, 4)]

    def topic_of(self, i: int) -> int:
        return i % self.num_topics

    def page_text(self, i: int) -> str:
        rng = self._page_rng(i)
        topic = self.topic_words[self.topic_of(i)]
        n = self.page_len
        words = []
        for _ in range(n):
            if rng.random() < 0.75:
                words.append(topic[rng.integers(0, len(topic))])
            else:
                words.append(self.common_words[rng.integers(0, len(self.common_words))])
        keys = self._key_words(i)
        # plant key words at deterministic-but-spread positions
        for j, kw in enumerate(keys * 3):  # each key appears 3x
            words[(7 * (j + 1) + i) % n] = kw
        return self._translate(" ".join(words), self.page_language(i))

    def query_text(self, i: int) -> str:
        rng = np.random.default_rng((self.seed * 3_000_017 + i) & 0x7FFFFFFF)
        topic = self.topic_words[self.topic_of(i)]
        keys = self._key_words(i)
        words = list(keys)
        while len(words) < self.query_len:
            words.append(topic[rng.integers(0, len(topic))])
        order = rng.permutation(len(words))
        return self._translate(" ".join(words[k] for k in order),
                               self.query_language(i))

    # -- iteration --------------------------------------------------------
    def pairs(self, start: int = 0, stop: int | None = None
              ) -> Iterator[Tuple[int, str, str]]:
        stop = self.num_pages if stop is None else min(stop, self.num_pages)
        for i in range(start, stop):
            yield i, self.query_text(i), self.page_text(i)

    def all_texts(self, limit: int | None = None) -> Iterator[str]:
        """Text stream for vocab/subword training."""
        stop = self.num_pages if limit is None else min(limit, self.num_pages)
        for i in range(stop):
            yield self.page_text(i)
            yield self.query_text(i)
