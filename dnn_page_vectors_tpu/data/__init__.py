"""Host-side data layer: corpora, tokenizers, batching, device prefetch.

Per BASELINE.json:5 the tokenizer/data-loader stays on the (TPU-VM) host,
feeding device prefetch queues; nothing in this package traces into XLA.
"""
from dnn_page_vectors_tpu.data.toy import ToyCorpus
from dnn_page_vectors_tpu.data.trigram import TrigramTokenizer
from dnn_page_vectors_tpu.data.words import WordTokenizer
from dnn_page_vectors_tpu.data.subword import SubwordTokenizer

__all__ = ["ToyCorpus", "TrigramTokenizer", "WordTokenizer", "SubwordTokenizer"]
