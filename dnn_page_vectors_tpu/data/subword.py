"""Subword tokenizers for the transformer towers (SURVEY.md §3 #3).

BERT-mini wants a WordPiece-style vocabulary (BASELINE.json:9) and mT5 a
SentencePiece-style one (BASELINE.json:11). The sandbox has no network to
fetch the published vocab files, so both surface forms run over one
self-contained, deterministic BPE core trained on the corpus:

  * style="wordpiece":      pieces inside a word are prefixed "##" (BERT).
  * style="sentencepiece":  word-initial pieces are prefixed "▁" (T5/mT5).

The trainer is classic BPE (greedy highest-count pair merge, deterministic
tie-break by pair ordering) with incremental pair-count maintenance on a
lazy max-heap — one merge touches only the words containing the pair, so
real-scale vocabularies (30,522 BERT / 250,112 mT5; VERDICT r1 #3) train in
seconds instead of the O(merges x corpus) of the naive loop. Encoding is
greedy longest-match, which matches WordPiece inference and is a close,
deterministic stand-in for unigram-LM sampling-free SentencePiece inference;
batch encoding runs in C++ (native/bpe_encode.cpp, ~6x, bit-equal and
self-checked with Python fallback) because the host-side matcher is what
feeds the device at bulk-embed rates.
"""
from __future__ import annotations

import collections
import heapq
import json
import threading
from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

PAD_ID = 0
UNK_ID = 1
_RESERVED = 2
_WORD_BOUNDARY = "▁"  # ▁


def _train_bpe(word_counts: Dict[Tuple[str, ...], int], num_merges: int,
               min_pair_count: int = 1) -> List[Tuple[str, str]]:
    """Greedy BPE merge learning over symbol-tuple word counts.

    Selection rule: highest pair count, ties broken by lexicographically
    smallest pair (deterministic). Pair counts are maintained incrementally:
    merging pair P rewrites only the words that contain P, subtracting their
    old adjacent-pair counts and adding the new ones; the heap is lazy
    (stale entries are dropped/refreshed on pop).
    """
    merges: List[Tuple[str, str]] = []
    words: List[List[str]] = []
    counts: List[int] = []
    for sym, c in word_counts.items():
        words.append(list(sym))
        counts.append(c)

    pair_counts: Dict[Tuple[str, str], int] = collections.defaultdict(int)
    pair_words: Dict[Tuple[str, str], set] = collections.defaultdict(set)
    for wi, sym in enumerate(words):
        c = counts[wi]
        for a, b in zip(sym, sym[1:]):
            pair_counts[(a, b)] += c
            pair_words[(a, b)].add(wi)

    heap = [(-c, pair) for pair, c in pair_counts.items()]
    heapq.heapify(heap)

    # `num_merges` counts NOVEL piece strings: two different pairs can merge
    # to the same surface string (e.g. (a,bc) and (ab,c) -> "abc"), and the
    # final vocab dedups surfaces — counting novel strings keeps
    # len(vocab) == alphabet + num_merges exactly (the honesty contract).
    seen = {s for sym in words for s in sym}
    novel = 0
    while novel < num_merges and heap:
        neg, best = heapq.heappop(heap)
        cur = pair_counts.get(best, 0)
        if cur != -neg:                      # stale: refresh and re-queue
            if cur > 0:
                heapq.heappush(heap, (-cur, best))
            continue
        if cur < min_pair_count or cur <= 0:
            break
        merges.append(best)
        merged = best[0] + best[1]
        if merged not in seen:
            seen.add(merged)
            novel += 1
        touched: set = set()
        for wi in list(pair_words.get(best, ())):
            sym = words[wi]
            c = counts[wi]
            # left-to-right non-overlapping rewrite
            out: List[str] = []
            i = 0
            hit = False
            while i < len(sym):
                if (i + 1 < len(sym) and sym[i] == best[0]
                        and sym[i + 1] == best[1]):
                    out.append(merged)
                    i += 2
                    hit = True
                else:
                    out.append(sym[i])
                    i += 1
            if not hit:                      # stale index entry
                continue
            for a, b in zip(sym, sym[1:]):   # retract old adjacencies
                pair_counts[(a, b)] -= c
                if pair_counts[(a, b)] <= 0:
                    pair_counts.pop((a, b), None)
            for a, b in zip(out, out[1:]):   # add new adjacencies
                pair_counts[(a, b)] += c
                pair_words[(a, b)].add(wi)
                touched.add((a, b))
            words[wi] = out
        pair_words.pop(best, None)
        for pair in touched:
            if pair in pair_counts:
                heapq.heappush(heap, (-pair_counts[pair], pair))
    return merges


_POOL = None
_POOL_SIZE = 0
_POOL_LOCK = threading.Lock()


def _threaded_encode(native, texts: Sequence[str], max_tokens: int,
                     k: int) -> np.ndarray:
    """Chunk the batch over a shared thread pool. Correct because chunks are
    independent and the C ABI call drops the GIL for its whole duration.

    The lock covers BOTH pool replacement and task submission (ADVICE r3):
    Executor.map submits every future eagerly at call time, so submitting
    under the lock means no thread can observe a pool that another thread
    is about to shut down ('cannot schedule new futures after shutdown' —
    which encode_batch's fallback would silently turn into a ~6x slower
    pure-Python re-encode). shutdown(wait=False) never cancels futures
    already submitted, so results are consumed safely outside the lock."""
    global _POOL, _POOL_SIZE
    n = len(texts)
    bounds = [(i * n // k, (i + 1) * n // k) for i in range(k)]
    with _POOL_LOCK:  # prefetch producers may race first use / growth
        if _POOL is None or _POOL_SIZE < k:
            import concurrent.futures
            if _POOL is not None:
                _POOL.shutdown(wait=False)
            _POOL = concurrent.futures.ThreadPoolExecutor(max_workers=k)
            _POOL_SIZE = k
        parts = _POOL.map(
            lambda se: native.encode_batch(texts[se[0]:se[1]], max_tokens,
                                           UNK_ID),
            bounds)
    return np.concatenate(list(parts), axis=0)


class SubwordTokenizer:
    """BPE-core subword tokenizer with WordPiece / SentencePiece surfaces."""

    def __init__(self, vocab: Dict[str, int], style: str = "wordpiece",
                 max_tokens: int = 64, meta: Dict | None = None):
        assert style in ("wordpiece", "sentencepiece"), style
        self.vocab = vocab
        self.style = style
        self.max_tokens = max_tokens
        # provenance (config vocab_size, corpus fingerprint) — lets the
        # loader detect a stale cache instead of silently reusing it
        self.meta = meta or {}
        # >1 chunks native batch encoding across a thread pool (the C++
        # matcher releases the GIL, so it scales across host cores — a
        # v5e-8 host must feed ~8x one chip's embed rate). Set from
        # config.data.tokenize_threads by the loader.
        self.threads = 1

    # -- training ---------------------------------------------------------
    @classmethod
    def train(cls, texts: Iterable[str], vocab_size: int = 8_192,
              style: str = "wordpiece", max_tokens: int = 64,
              max_train_words: int = 2_000_000,
              strict_vocab: bool = False) -> "SubwordTokenizer":
        """Train a BPE vocab of (up to) `vocab_size` total ids.

        strict_vocab=True raises if the corpus sample cannot support exactly
        `vocab_size` pieces (merges run dry) — the named configs claim real
        vocab geometries (30,522 / 250,112) and silently training something
        smaller diverges the executed model from its config (VERDICT r1 #3).
        """
        counts: collections.Counter[str] = collections.Counter()
        seen = 0
        for text in texts:
            ws = text.split()
            counts.update(ws)
            seen += len(ws)
            if seen >= max_train_words:
                break
        word_counts = {tuple(w): c for w, c in counts.items()}
        alphabet = sorted({ch for w in word_counts for ch in w})
        num_merges = max(0, vocab_size - len(alphabet) - _RESERVED)
        merges = _train_bpe(word_counts, num_merges)
        pieces = list(alphabet) + [a + b for a, b in merges]
        # piece -> id, longest pieces preferred implicitly by greedy matcher
        vocab = {p: i + _RESERVED for i, p in enumerate(dict.fromkeys(pieces))}
        tok = cls(vocab, style=style, max_tokens=max_tokens)
        if strict_vocab and tok.vocab_size != vocab_size:
            raise ValueError(
                f"BPE training produced {tok.vocab_size} ids but the config "
                f"claims vocab_size={vocab_size}: the training sample "
                f"({seen} words, {len(word_counts)} unique) ran out of "
                "mergeable pairs. Use a larger corpus / max_train_words, or "
                "lower data.vocab_size to what the corpus supports.")
        return tok

    @property
    def vocab_size(self) -> int:
        return len(self.vocab) + _RESERVED

    # -- encoding ---------------------------------------------------------
    def _encode_word(self, word: str) -> List[int]:
        """Greedy longest-match over the BPE vocab."""
        ids: List[int] = []
        i = 0
        n = len(word)
        while i < n:
            j = n
            while j > i:
                piece = word[i:j]
                if piece in self.vocab:
                    ids.append(self.vocab[piece])
                    break
                j -= 1
            else:
                ids.append(UNK_ID)
                j = i + 1
            i = j
        return ids

    def encode(self, text: str) -> np.ndarray:
        out = np.zeros(self.max_tokens, dtype=np.int32)
        pos = 0
        for word in text.split():
            if pos >= self.max_tokens:
                break
            for tid in self._encode_word(word):
                if pos >= self.max_tokens:
                    break
                out[pos] = tid
                pos += 1
        return out

    def _native_encoder(self):
        """C++ greedy matcher (native/bpe_encode.cpp), built lazily and
        self-checked against the Python path on a probe covering Unicode,
        UNK fallback, and mid-word truncation — on any disagreement or
        build failure the tokenizer silently stays pure-Python (same
        contract as data/trigram.py)."""
        if not hasattr(self, "_native"):
            self._native = None
            try:
                from dnn_page_vectors_tpu.native import subword_native
                enc = subword_native.shared_encoder(self.vocab)
                probe = ["ab cd ef", "ünïcôdé wörds ärë fïne",
                         "日本語 テキスト", "", "  spaced out ",
                         "x" * 300,
                         " ".join("pq" for _ in range(self.max_tokens + 8))]
                want = np.stack([self.encode(t) for t in probe])
                got = enc.encode_batch(probe, self.max_tokens, UNK_ID)
                if (got == want).all():
                    self._native = enc
            except Exception:
                self._native = None
        return self._native

    def encode_batch(self, texts: Sequence[str]) -> np.ndarray:
        native = self._native_encoder()
        if native is not None:
            try:
                k = min(self.threads, len(texts) // 256)  # >=256 texts/chunk
                if k > 1:
                    return _threaded_encode(native, texts, self.max_tokens, k)
                return native.encode_batch(texts, self.max_tokens, UNK_ID)
            except Exception as e:
                # fallback contract: never crash where Python works — but a
                # silent fallback hides a ~6x host-throughput loss, so warn
                # ONCE per process (ADVICE r3)
                if not getattr(SubwordTokenizer, "_warned_fallback", False):
                    SubwordTokenizer._warned_fallback = True
                    import sys
                    print(f"WARNING: native batch encode failed "
                          f"({type(e).__name__}: {e}); falling back to "
                          "pure-Python encoding (~6x slower host "
                          "tokenization) — further falls are silent",
                          file=sys.stderr)
        return np.stack([self.encode(t) for t in texts])

    def encode_jsonl_lines(self, lines: Sequence[bytes],
                           field: str = "page"):
        """Fused jsonl-extract + batch encode (native/bpe_encode.cpp):
        raw jsonl line buffers in, token ids out, with the per-record
        field extract AND the UTF-8 decode/re-encode round trip both
        gone from the Python side — the measured producer bound of the
        bulk-embed sweep (docs/MFU.md "host pipeline"). Records the C++
        extractor punts on (escapes, nesting, duplicate/missing key —
        the same rules as data/jsonl.py _extract) fall back to
        json.loads + the plain encoder, so results are byte-identical to
        the unfused path (pinned by tests/test_native.py). Returns None
        when the native encoder is unavailable — callers use the plain
        read+tokenize path."""
        native = self._native_encoder()
        if native is None:
            return None
        key = f'"{field}":'.encode("utf-8")
        out, status = native.encode_jsonl_batch(lines, key,
                                                self.max_tokens, UNK_ID)
        bad = np.flatnonzero(status == 0)
        if bad.size:
            texts = []
            for i in bad:
                rec = json.loads(lines[int(i)])
                texts.append(rec[field] if field == "page"
                             else rec.get(field, ""))
            out[bad] = self.encode_batch(texts)
        return out

    def tokens(self, text: str) -> List[str]:
        """Human-readable pieces with style-appropriate decoration (debug/tests)."""
        inv = {v: k for k, v in self.vocab.items()}
        out: List[str] = []
        for word in text.split():
            for wi, tid in enumerate(self._encode_word(word)):
                piece = inv.get(tid, "<unk>")
                if self.style == "wordpiece":
                    out.append(piece if wi == 0 else "##" + piece)
                else:
                    out.append((_WORD_BOUNDARY + piece) if wi == 0 else piece)
        return out

    # -- persistence ------------------------------------------------------
    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"style": self.style, "max_tokens": self.max_tokens,
                       "vocab": self.vocab, "meta": self.meta}, f)

    @classmethod
    def load(cls, path: str) -> "SubwordTokenizer":
        with open(path) as f:
            blob = json.load(f)
        return cls(blob["vocab"], style=blob["style"],
                   max_tokens=blob["max_tokens"], meta=blob.get("meta"))
