"""Subword tokenizers for the transformer towers (SURVEY.md §3 #3).

BERT-mini wants a WordPiece-style vocabulary (BASELINE.json:9) and mT5 a
SentencePiece-style one (BASELINE.json:11). The sandbox has no network to
fetch the published vocab files, so both surface forms run over one
self-contained, deterministic BPE core trained on the corpus:

  * style="wordpiece":      pieces inside a word are prefixed "##" (BERT).
  * style="sentencepiece":  word-initial pieces are prefixed "▁" (T5/mT5).

The trainer is classic BPE (greedy highest-count pair merge, deterministic
tie-break by pair ordering); encoding is greedy longest-match, which matches
WordPiece inference and is a close, deterministic stand-in for unigram-LM
sampling-free SentencePiece inference.
"""
from __future__ import annotations

import collections
import json
from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

PAD_ID = 0
UNK_ID = 1
_RESERVED = 2
_WORD_BOUNDARY = "▁"  # ▁


def _train_bpe(word_counts: Dict[Tuple[str, ...], int], num_merges: int
               ) -> List[Tuple[str, str]]:
    """Greedy BPE merge learning over symbol-tuple word counts."""
    merges: List[Tuple[str, str]] = []
    words = dict(word_counts)
    for _ in range(num_merges):
        pair_counts: collections.Counter[Tuple[str, str]] = collections.Counter()
        for sym, c in words.items():
            for a, b in zip(sym, sym[1:]):
                pair_counts[(a, b)] += c
        if not pair_counts:
            break
        # deterministic: highest count, then lexicographic pair
        best = min(pair_counts.items(), key=lambda kv: (-kv[1], kv[0]))[0]
        if pair_counts[best] < 2:
            break
        merges.append(best)
        merged = best[0] + best[1]
        new_words: Dict[Tuple[str, ...], int] = {}
        for sym, c in words.items():
            out: List[str] = []
            i = 0
            while i < len(sym):
                if i + 1 < len(sym) and sym[i] == best[0] and sym[i + 1] == best[1]:
                    out.append(merged)
                    i += 2
                else:
                    out.append(sym[i])
                    i += 1
            new_words[tuple(out)] = new_words.get(tuple(out), 0) + c
        words = new_words
    return merges


class SubwordTokenizer:
    """BPE-core subword tokenizer with WordPiece / SentencePiece surfaces."""

    def __init__(self, vocab: Dict[str, int], style: str = "wordpiece",
                 max_tokens: int = 64):
        assert style in ("wordpiece", "sentencepiece"), style
        self.vocab = vocab
        self.style = style
        self.max_tokens = max_tokens

    # -- training ---------------------------------------------------------
    @classmethod
    def train(cls, texts: Iterable[str], vocab_size: int = 8_192,
              style: str = "wordpiece", max_tokens: int = 64,
              max_train_words: int = 2_000_000) -> "SubwordTokenizer":
        counts: collections.Counter[str] = collections.Counter()
        seen = 0
        for text in texts:
            ws = text.split()
            counts.update(ws)
            seen += len(ws)
            if seen >= max_train_words:
                break
        word_counts = {tuple(w): c for w, c in counts.items()}
        alphabet = sorted({ch for w in word_counts for ch in w})
        num_merges = max(0, vocab_size - len(alphabet) - _RESERVED)
        merges = _train_bpe(word_counts, num_merges)
        pieces = list(alphabet) + [a + b for a, b in merges]
        # piece -> id, longest pieces preferred implicitly by greedy matcher
        vocab = {p: i + _RESERVED for i, p in enumerate(dict.fromkeys(pieces))}
        return cls(vocab, style=style, max_tokens=max_tokens)

    @property
    def vocab_size(self) -> int:
        return len(self.vocab) + _RESERVED

    # -- encoding ---------------------------------------------------------
    def _encode_word(self, word: str) -> List[int]:
        """Greedy longest-match over the BPE vocab."""
        ids: List[int] = []
        i = 0
        n = len(word)
        while i < n:
            j = n
            while j > i:
                piece = word[i:j]
                if piece in self.vocab:
                    ids.append(self.vocab[piece])
                    break
                j -= 1
            else:
                ids.append(UNK_ID)
                j = i + 1
            i = j
        return ids

    def encode(self, text: str) -> np.ndarray:
        out = np.zeros(self.max_tokens, dtype=np.int32)
        pos = 0
        for word in text.split():
            if pos >= self.max_tokens:
                break
            for tid in self._encode_word(word):
                if pos >= self.max_tokens:
                    break
                out[pos] = tid
                pos += 1
        return out

    def encode_batch(self, texts: Sequence[str]) -> np.ndarray:
        return np.stack([self.encode(t) for t in texts])

    def tokens(self, text: str) -> List[str]:
        """Human-readable pieces with style-appropriate decoration (debug/tests)."""
        inv = {v: k for k, v in self.vocab.items()}
        out: List[str] = []
        for word in text.split():
            for wi, tid in enumerate(self._encode_word(word)):
                piece = inv.get(tid, "<unk>")
                if self.style == "wordpiece":
                    out.append(piece if wi == 0 else "##" + piece)
                else:
                    out.append((_WORD_BOUNDARY + piece) if wi == 0 else piece)
        return out

    # -- persistence ------------------------------------------------------
    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"style": self.style, "max_tokens": self.max_tokens,
                       "vocab": self.vocab}, f)

    @classmethod
    def load(cls, path: str) -> "SubwordTokenizer":
        with open(path) as f:
            blob = json.load(f)
        return cls(blob["vocab"], style=blob["style"],
                   max_tokens=blob["max_tokens"])
