"""Config system: dataclasses + the five canonical named configs.

The five configs mirror BASELINE.json:6-12 verbatim (SURVEY.md §3 #24):
  1. cdssm_toy      — CDSSM char-trigram CNN, 10k-page toy corpus, single CPU
  2. kim_cnn_v5e8   — Word-CNN (Kim-CNN) page encoder, 1M pages, DP pjit, v5e-8
  3. bert_mini_v5p16 — two-tower BERT-mini with in-batch negatives, v5p-16
  4. hardneg_v5p64  — ANN-mined hard-negative contrastive training, 100M pages
  5. mt5_multilingual — mT5-base page encoder + cross-lingual retrieval eval

Every CLI flag round-trips through these dataclasses (SURVEY.md §5.6).
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Host-side data pipeline settings."""
    tokenizer: str = "trigram"       # trigram | word | wordpiece | sentencepiece
    corpus: str = "toy"              # toy | jsonl:<path>
    num_pages: int = 10_000          # corpus size (toy generator)
    query_len: int = 16              # max words per query
    page_len: int = 64               # max words per page
    trigrams_per_word: int = 8       # K trigram ids kept per word (CDSSM)
    trigram_buckets: int = 16_384    # hash-bucket vocab for char trigrams
    vocab_size: int = 30_000         # word / subword vocab size
    languages: int = 1               # >1: cross-lingual toy corpus (config 5)
    num_topics: int = 64             # toy-corpus topics; fewer => more
                                     # near-duplicate pages per topic, harder
                                     # within-topic retrieval (mining tests)
    # >1 chunks subword batch encoding across host threads (the C++ matcher
    # releases the GIL). One thread feeds one chip (~164k pages/s measured);
    # multi-chip hosts (v5e-8) need roughly one thread per 1-2 chips.
    tokenize_threads: int = 1
    # Tokenizer WORKER pool: >1 runs the per-batch read+tokenize of the
    # bulk-embed sweep and the train batcher on N concurrent producer
    # threads, reassembled in batch order (data/loader.py
    # ordered_parallel_map) — batches stay byte-identical to the serial
    # path. Orthogonal to tokenize_threads (intra-batch C++ subword
    # chunking): workers parallelize ACROSS batches, threads WITHIN one.
    # 1 = serial producer.
    tokenize_workers: int = 4
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Encoder zoo settings. `encoder` selects the family."""
    encoder: str = "cdssm"           # cdssm | kim_cnn | lstm | bert | t5
    embed_dim: int = 128             # token/word embedding width
    out_dim: int = 128               # final vector dimension (both towers)
    # conv families
    conv_widths: Tuple[int, ...] = (3,)        # cdssm: (3,); kim_cnn: (3, 4, 5)
    conv_channels: int = 256
    # transformer families
    num_layers: int = 4
    num_heads: int = 4
    mlp_dim: int = 1024
    model_dim: int = 256
    dropout: float = 0.1
    # dense | flash | ring. flash = Pallas kernel, O(L) HBM in forward AND
    # backward for BOTH variants: the t5 relative-position bias has its own
    # Pallas dbias kernel (batch-innermost accumulating grid), so biased
    # training never materialises [B,H,L,S] either (round 4).
    attention: str = "dense"
    shared_towers: bool = False      # share params between query/page towers
    dtype: str = "bfloat16"          # compute dtype on MXU


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device-mesh shape. Axes: data (DP) and model (TP).

    The reference scaled with torch-DDP over NCCL (BASELINE.json:5); here the
    same role is played by GSPMD sharding over this mesh, with XLA emitting
    psum/all-gather over ICI.
    """
    data: int = 1
    model: int = 1
    seq: int = 1                     # sequence/context parallelism (ring attn)
    # strict=True: fail hard when fewer devices are visible than configured
    # (production pods); strict=False: shrink to fit with a loud warning
    # (dev boxes, tests, the 1-chip sandbox).
    strict: bool = False

    @property
    def num_devices(self) -> int:
        return self.data * self.model * self.seq


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    batch_size: int = 256            # GLOBAL batch (split across mesh 'data')
    steps: int = 1_000
    optimizer: str = "adamw"         # adamw | sgd
    learning_rate: float = 1e-3
    warmup_steps: int = 100
    weight_decay: float = 0.01
    temperature_init: float = 20.0   # learnable inverse-temperature init
    hard_negatives: int = 0          # ANN-mined negatives per positive
    checkpoint_every: int = 500
    log_every: int = 50
    # Steps fused into ONE compiled dispatch via lax.scan (host sees the
    # device every scan_steps steps instead of every step). >1 amortizes
    # per-dispatch host latency — the dominant single-chip overhead for
    # small models; log_every/checkpoint_every must be multiples of it.
    scan_steps: int = 1
    # Fused/chunked contrastive loss (models/losses.py): >0 streams query
    # rows against the global in-batch (+mined) negative pool this many
    # rows at a time — logits + log-sum-exp + grad contribution per tile,
    # never materializing the [B, B(1+H)] similarity matrix — so the
    # effective negative pool scales with the global batch instead of
    # with the biggest square matrix HBM can hold. Must divide
    # batch_size. 0 = the dense reference path (byte-identical
    # pre-chunking behavior); parity pinned by tests/test_losses_fused.py.
    loss_chunk: int = 0
    # Sequence packing for long-page configs (data/loader.py pack_segments,
    # docs/MFU.md): >1 packs this many consecutive short pages into ONE
    # [data.page_len] row with a segment mask (attention and pooling never
    # cross pages; BERT positions restart per segment), so a corpus of
    # short pages stops paying full-row pad compute. batch_size still
    # counts PAGES; the compiled row batch is batch_size / pack_pages.
    # Requires a transformer tower (bert/t5) with dense or flash
    # attention. 1 = unpacked (byte-identical pre-packing behavior);
    # parity pinned by tests/test_packing.py.
    pack_pages: int = 1
    # PRNG implementation for the per-step dropout keys. "rbg" (XLA's
    # hardware RngBitGenerator) measured +22% train throughput over
    # "threefry2x32" on v5e — threefry mask generation is the single
    # largest non-matmul cost of the bert-mini step. Trade-off: rbg mask
    # bits are not guaranteed stable across XLA versions/backends
    # (irrelevant for dropout; param init stays threefry).
    dropout_rng: str = "rbg"
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class EvalConfig:
    recall_k: int = 10               # Recall@10 query->page (BASELINE.json:2)
    eval_queries: int = 1_000
    embed_batch_size: int = 512
    # Batches fused into ONE bulk-embed dispatch (lax.map over a [K, B, L]
    # stack): amortizes per-dispatch host latency on the forward-only sweep
    # (+8% embed throughput measured on v5e at K=8, round 4). 1 = one
    # dispatch per batch.
    embed_stack: int = 8
    # vector-store shard rows: the resume/parallelism unit of the bulk-embed
    # job (one shard = one manifest entry = one fleet work item)
    store_shard_size: int = 65_536
    # float16 | int8 — int8 stores symmetric per-vector-quantized codes +
    # fp16 scales: ~2x smaller shards and half the read bandwidth at
    # 1B-page scale, with recall parity pinned by tests/test_store_quant.py
    store_dtype: str = "float16"
    # Bounded pending budget of the bulk-embed background writer: how many
    # finished shards may queue for disk writeback while the device embeds
    # ahead (infer/bulk_embed.py _ShardWriter). Bounds host memory at
    # budget * shard_size rows; a slow disk backpressures the device loop.
    writeback_depth: int = 2


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Query-serving knobs (infer/serve.py, docs/SERVING.md).

    The compiled encode/top-k bucket width itself comes from
    SearchService.query_batch (mesh-derived); these knobs govern how
    concurrent traffic is coalesced into that bucket and how repeat
    queries are deduplicated."""
    # Micro-batcher window: how long the dispatcher waits for more
    # concurrent search() callers after the first request arrives before
    # dispatching the coalesced batch. A lone caller pays at most one
    # window of extra latency; under load the window fills the compiled
    # bucket and aggregate QPS scales toward bucket width.
    batch_window_ms: float = 2.0
    # Telemetry-driven adaptive batching (docs/SERVING.md "SLO
    # methodology"): when on, the micro-batch window WIDENS toward
    # batch_window_max_ms while the windowed queue-wait p99 (the
    # serve.queue_wait_ms instrument) climbs past the current window —
    # requests are stacking faster than dispatches drain, so coalescing
    # harder buys throughput — and COLLAPSES back toward batch_window_ms
    # when traffic goes idle. Off (the default) keeps the fixed window:
    # byte-identical pre-adaptive behavior. The live window is exposed as
    # the serve.batch_window_ms gauge; every change emits a window_adapt
    # event (docs/OBSERVABILITY.md).
    batch_window_adaptive: bool = False
    # Ceiling for the adaptive window (ms). Bounds the extra latency a
    # lone caller can ever pay to one max-window flush.
    batch_window_max_ms: float = 25.0
    # Most queries one coalesced dispatch may carry (tiled over full
    # compiled buckets inside search_many). Bounds per-dispatch latency.
    max_batch: int = 32
    # Bounded request queue between callers and the dispatcher thread: a
    # full queue backpressures callers instead of buffering unboundedly.
    max_queue: int = 256
    # LRU query-embedding cache entries (0 disables). Keyed on
    # whitespace-normalized query text + the store's model step, so
    # head-of-distribution repeat queries skip tokenize+encode entirely
    # and a model/store reload (new step) invalidates every entry.
    query_cache_size: int = 4096
    # Retrieval algorithm (docs/ANN.md): "exact" = brute-force MXU top-k
    # over the whole store (byte-identical pre-index behavior, the
    # default); "ivf" = the inverted-file ANN index (index/ivf.py) with
    # automatic per-request fallback to exact when the index is missing,
    # stale, or quarantined (counted in metrics as ann_fallbacks).
    index: str = "exact"
    # IVF lists probed per query: the recall-vs-cost dial. Expected scanned
    # fraction ~ nprobe/nlist; recall-vs-exact is measured, not assumed
    # (evals.recall.recall_vs_exact, bench ann_recall_at_10).
    nprobe: int = 8
    # IVF list count for `cli index` builds. 0 = auto (~sqrt(store rows)).
    nlist: int = 0
    # k-means iterations for the IVF coarse quantizer build.
    kmeans_iters: int = 8
    # Quantizer seeding: "kmeans++" (D²-spread seeds — lower list
    # imbalance at large nlist; the build JSON reports the init->final
    # imbalance delta) or "random" (uniform pool draw). Both seeded and
    # byte-deterministic.
    kmeans_init: str = "kmeans++"
    # Balanced final assignment (docs/ANN.md): >0 caps every list at
    # ceil(factor * N / nlist) rows during the build's assignment sweep —
    # overflow rows spill to their next-best centroid (soft cap), cutting
    # hot-list imbalance at a small recall cost. 0 disables (pure argmax,
    # the pre-balance behavior); `cli index` reports the raw->balanced
    # imbalance delta.
    kmeans_balance: float = 0.0
    # OPQ+PQ compressed posting payloads (index/pq.py, docs/ANN.md):
    # number of PQ subspaces (must divide model.out_dim). 0 = plain IVF
    # (stored-width posting gather, the pre-PQ behavior); `cli index --pq`
    # picks an automatic m (~out_dim/8) when this is 0. With PQ on, the
    # candidate gather moves m bytes/row instead of the stored row width
    # and scoring runs as on-device ADC with an exact re-rank on top.
    pq_m: int = 0
    # Per-subspace codebook k-means iterations (PQ builds).
    pq_iters: int = 8
    # OPQ rotation/codebook alternations (Ge et al. 2013). 0 = plain PQ
    # (identity rotation).
    pq_opq_iters: int = 3
    # ADC candidates exact-reranked per query from the store (the final
    # top-k always comes from stored-width rows, preserving the
    # recall-vs-exact contract). 0 = auto max(8k, 64).
    pq_rerank: int = 0
    # HBM budget for the resident hot posting set (PQ indexes only): the
    # largest lists' codes + probed-list metadata stage to device at view
    # build so their per-request host gather disappears; the non-resident
    # tail falls back to the mmap path. 0 disables.
    hot_postings_gb: float = 0.0
    # Partitioned serving (infer/partition.py, docs/SCALING.md
    # "Partitioned serving"): >1 splits the store's shard table into this
    # many contiguous partitions, each owning its shard range, its slice
    # of the IVF posting lists, and its cut of serve.hot_postings_gb;
    # search_many scatter-gathers — the coalesced bucket broadcasts once,
    # every partition answers its local top-k over ONLY its rows, and
    # results fold through the ops/topk.py partition merge tree. Clamped
    # to the shard count. 1 (with replicas=1) keeps the single-view
    # serving path byte-identical to before.
    partitions: int = 1
    # Replica sets: R copies of every partition (each host-simulated as a
    # worker thread owning an independent _ServeView), with health-based
    # routing — a replica mid-restage, degraded to the streaming path, or
    # past its queue budget sheds traffic to its siblings (`replica_shed`
    # event); a partition whose replicas are ALL degraded serves degraded
    # locally (`partition_degraded`), never an empty result slice.
    replicas: int = 1
    # Queue-depth shed budget per partition replica: a replica with more
    # than this many requests in flight stops being preferred and traffic
    # sheds to its siblings. Only a routing preference — with every
    # replica over budget the least-loaded healthy one still serves.
    replica_shed_queue: int = 8
    # -- over-the-wire serving (infer/transport.py, infer/server.py,
    # infer/partition_host.py; docs/SERVING.md "Network front end") ------
    # Listen address of the asyncio socket front end ("host:port"; port 0
    # binds an ephemeral port, reported by the server handle/CLI).
    listen: str = "127.0.0.1:0"
    # Default per-request deadline budget (ms) applied at admission when
    # a request carries none. A request that cannot make its deadline is
    # shed AT THE DOOR (serve.deadline_shed + deadline_shed event) —
    # before it can consume a micro-batch bucket slot — and one whose
    # deadline expires while queued is shed at dispatch. 0 disables.
    deadline_ms: float = 0.0
    # Hedged fan-out (partition RPC): when a partition's answer has not
    # arrived within this quantile of its observed RPC latency, the same
    # request fires at a sibling replica's worker and the first answer
    # wins (serve.hedge_fired + hedge_fired event). Needs >= 8 latency
    # samples before it ever fires; <= 0 (or >= 1) disables hedging.
    hedge_quantile: float = 0.95
    # Partition-worker heartbeat interval (seconds). A worker whose last
    # heartbeat is older than 2x this — or whose registration connection
    # dropped — is LOST (worker_lost event): routing sheds its replica
    # (reason "liveness") and the fan-out serves its slice from the
    # front end's local view until it re-registers.
    heartbeat_s: float = 0.5
    # Wire compression (docs/SERVING.md "Network front end"): negotiated
    # per connection (REGISTER flags / T_HELLO), LOSSLESS — RESULT
    # frames ship raw f32 scores + zigzag-delta varint page ids, and
    # repeated query blocks intern into per-connection slots (sent once,
    # then a 2-byte reference), so socket results stay byte-identical to
    # in-process while wire bytes/query drop >= 2.5x on repeat-heavy
    # traffic. False = every connection negotiates down to raw frames
    # (the PR-13 wire format); mixed fleets interoperate either way.
    wire_compress: bool = True
    # Generation-keyed result cache (docs/SERVING.md "Result cache"):
    # formatted top-k results keyed by (normalized text, k, nprobe, store
    # generation, index generation), probed at the admission door before a
    # repeat can consume a micro-batch bucket slot. refresh() bumps the
    # generations, so invalidation is free — a post-append repeat can
    # never serve pre-append results. Off by default: repeats then take
    # the full path (embedding cache still applies).
    result_cache: bool = False
    # Result-cache capacity (entries, LRU). 0 disables even when
    # serve.result_cache is true.
    result_cache_size: int = 4096
    # Fleet-wide sharing of the result cache over the wire: advertise
    # FLAG_RESULT_CACHE in REGISTER/HELLO and answer CACHE_LOOKUP /
    # CACHE_PUT frames, so N front ends (and the worker RPC hop) share
    # one hot set. Requires serve.result_cache; mixed fleets where one
    # side never negotiated the flag degrade to local-only caching.
    result_cache_fleet: bool = False
    # Filtered retrieval (docs/ANN.md "Filtered retrieval"): accept and
    # serve per-query attribute predicates (`lang==X`, `site in {...}`,
    # `recency>=band`, '&'-conjunctions) — advertised/confirmed per
    # connection as FLAG_FILTERS, exactly like wire compression. False =
    # this end never negotiates the flag: a gateway serves filtered
    # slices from its local view, a client raises on a filtered call.
    filters: bool = True
    # Under-filled-probe escalation: when a filtered IVF probe set yields
    # fewer than k matching rows, the probe count multiplies by this
    # factor and the scan re-runs (ivf.filter_escalations counter) until
    # k fills or every list drains. <= 1 disables escalation.
    filter_escalate: float = 4.0
    # Self-healing fleet (docs/ROBUSTNESS.md "Network failure model").
    # A partition worker that loses its gateway connection (EOF, torn
    # frame, socket error) re-dials with exponential backoff + jitter and
    # re-REGISTERs with its current generation instead of exiting. False
    # restores the PR-13 behavior: connection loss is terminal.
    reconnect: bool = True
    # First re-dial delay (seconds); doubles per consecutive failure.
    reconnect_base_s: float = 0.05
    # Backoff cap for the re-dial ramp (seconds) — also the cap for the
    # wire retry profile around dial+REGISTER (faults.retry_wire).
    reconnect_max_s: float = 2.0
    # Gateway-side per-replica circuit breaker: after this many
    # CONSECUTIVE wire failures the replica's breaker opens
    # (breaker_open event) and routing skips it — requests go straight
    # to fallback instead of paying a timeout each. <= 0 disables.
    breaker_failures: int = 3
    # How long an open breaker blocks traffic before admitting one
    # half-open probe (seconds); doubles on every failed probe.
    breaker_open_s: float = 0.25
    # Cap for the open-interval ramp (seconds).
    breaker_max_s: float = 30.0
    # Elastic fleet membership (docs/SCALING.md "Scale-out tier"): the
    # gateway re-cuts the partition split to match the live worker set —
    # a worker joining at the next tail index widens it, a draining tail
    # worker shrinks it — via a deterministic partition_shard_ranges
    # re-split and the generation-gated REFRESH handoff (fleet_resplit
    # event), with no restarts and no result set ever mixing splits.
    # Off (the default): the split is fixed at boot, exactly as before.
    elastic: bool = False


@dataclasses.dataclass(frozen=True)
class UpdatesConfig:
    """Live corpus updates (dnn_page_vectors_tpu/updates/,
    docs/UPDATES.md): append-only store generations, incremental IVF
    refresh, zero-downtime serving hot-swap."""
    # Full-rebuild trigger for IVFIndex.update: when the fraction of the
    # corpus appended since the last full k-means exceeds this, the
    # incremental posting append stops (stale centroids mis-assign enough
    # new rows to erode recall) and update() runs a fresh build instead.
    rebuild_drift: float = 0.25
    # SearchService.refresh() / `cli append` bring the IVF index up to
    # date automatically when one exists. False = store-only refresh
    # (the index goes stale and serving falls back to exact, visibly).
    auto_update_index: bool = True
    # Tombstone-aware HBM restage policy (docs/UPDATES.md): a refresh()
    # REUSES a staged device shard whose only change is new tombstones as
    # long as the staged block's dead-row fraction stays <= this threshold
    # (the dead rows are masked in the id table instead — they can occupy
    # but never win a result slot), and restages it once density crosses
    # the threshold. metrics() reports restage_skipped/restage_forced.
    # 0.0 restores the exact-ids policy (any tombstone restages).
    restage_tombstone_density: float = 0.05
    # Multi-writer append leases (docs/MAINTENANCE.md): append_corpus
    # acquires a per-writer lease on the append cursor (lease file under
    # the store manifest dir) before reading next_page_id(), so two
    # concurrent `cli append` processes can never double-assign ids. The
    # lease expires after this many seconds — a crashed writer's lease is
    # stolen (lease_stolen event) instead of blocking appends forever.
    writer_lease_s: float = 30.0
    # How long a second writer QUEUES on a held lease before giving up
    # (seconds). 0 fails fast (LeaseHeld) instead of waiting.
    lease_wait_s: float = 5.0


@dataclasses.dataclass(frozen=True)
class MaintenanceConfig:
    """Background maintenance service (dnn_page_vectors_tpu/maintenance/,
    docs/MAINTENANCE.md): online generation compaction, off-path IVF
    rebuilds, and the stale-artifact janitor — a store that ingests,
    compacts, and re-indexes continuously while serving."""
    # Compaction trigger: when the tombstone density across the generation
    # chain (dead rows / total rows) crosses this, the background compactor
    # folds the gen-NNNN chain plus the base into a fresh compacted base —
    # dead rows dropped, ids preserved, one atomic manifest pointer flip.
    compact_tombstone_density: float = 0.2
    # Worker poll period (seconds): how often each pillar worker re-checks
    # its trigger. `cli maintain --once` / run_once() ignore it.
    interval_s: float = 5.0
    # Move drift-triggered IVF full rebuilds OFF the refresh() caller: with
    # a MaintenanceService attached, refresh() defers the rebuild (the
    # incremental posting append still runs; serve.index_rebuild_pending
    # flags it) and the background builder constructs the next index
    # generation beside the live one, hot-swapping via refresh(). False
    # keeps the PR-5 inline-rebuild behavior even with maintenance running.
    bg_rebuild: bool = True
    # Autoscale pillar (docs/SCALING.md "Scale-out tier"): drive worker
    # spawn/drain decisions from the serving telemetry — scale UP when
    # the windowed queue-wait p99 or the deadline-shed rate crosses its
    # up-threshold, DOWN when queue wait sits below the down-threshold
    # with zero sheds. Decisions only fire through hooks the operator
    # attaches (MaintenanceService.attach_scaler); without hooks the
    # pillar still evaluates and emits autoscale_up/autoscale_down
    # events, so the policy is observable before it is trusted. Off by
    # default.
    autoscale: bool = False
    # Fleet-size floor/ceiling the policy may move between.
    autoscale_min_workers: int = 1
    autoscale_max_workers: int = 4
    # Scale-up triggers: windowed queue-wait p99 (ms) or deadline-shed
    # rate (sheds/s over the telemetry window) at/above these.
    autoscale_up_queue_p99_ms: float = 50.0
    autoscale_up_shed_rate: float = 0.5
    # Scale-down trigger: queue-wait p99 at/below this with a zero shed
    # rate (and at least one full cooldown of calm).
    autoscale_down_queue_p99_ms: float = 5.0
    # Minimum seconds between scaling actions — a resize's own dip must
    # not read as new pressure before the fleet settles.
    autoscale_cooldown_s: float = 30.0


@dataclasses.dataclass(frozen=True)
class MigrationConfig:
    """Rolling model migration (dnn_page_vectors_tpu/maintenance/migrate.py,
    docs/MAINTENANCE.md "Rolling model migration"): re-embed a LIVE store
    to a new model step unit-by-unit while serving runs dual-stamp. The
    sweep itself is requested at runtime (`cli migrate`, or
    MaintenanceService.request_migration); these knobs shape how it
    runs."""
    # Host-side text rows per embed call while re-embedding a shard: the
    # memory/throughput trade of the sweep's bulk encode (same role as the
    # embed pipeline's batch, but off-path — it never blocks a query).
    batch_rows: int = 4096
    # Units the migrate pillar commits per maintenance pass before
    # hot-swapping the serving view. 1 keeps each refresh window small
    # (one unit's shards restage); raise it to trade refresh frequency
    # for sweep speed on large chains.
    units_per_pass: int = 1
    # Reclaim each unit's superseded shard files right after the serving
    # view moves past them (purge_stale). False leaves the bytes for the
    # janitor — the forensic setting.
    purge: bool = True


@dataclasses.dataclass(frozen=True)
class ObsConfig:
    """Observability (utils/telemetry.py, utils/tracing.py,
    docs/OBSERVABILITY.md): request-scoped tracing, the slow-query log,
    and the metrics registry's rolling windows. The knob table in
    docs/OBSERVABILITY.md is kept in lockstep with these fields by a
    drift test (tests/test_telemetry.py)."""
    # Request-scoped tracing on/off. Off, every span is a shared no-op
    # object — instrumented paths pay one None-check.
    enabled: bool = True
    # Slow-query threshold in milliseconds: a finished request trace whose
    # duration crosses this lands (as a full span tree) in the slow-query
    # log. 0 captures EVERY request; negative disables the log.
    slow_ms: float = -1.0
    # Bounded slow-query log entries (oldest evicted first).
    slow_log_size: int = 64
    # Recent finished traces kept for `cli trace` export (ring buffer).
    trace_buffer: int = 64
    # Rolling window (seconds) behind the live qps / error-rate /
    # cache-hit-rate / windowed-p99 numbers — "over the last N seconds",
    # not since boot.
    window_s: float = 10.0
    # Bounded percentile reservoir size (Algorithm R): histograms and
    # LatencyStats keep at most this many samples regardless of uptime;
    # below it, percentiles are exact nearest-rank.
    reservoir: int = 4096
    # Lifecycle event ring size (view hot-swap, shard quarantine, drift
    # rebuild, degraded/restored, checkpoint rollback).
    events: int = 256


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """Fault injection + transient-I/O retry policy (utils/faults.py,
    docs/ROBUSTNESS.md). Injection is OFF unless `plan` is non-empty; the
    retry policy is always on (real filesystems throw transient errors
    without any help from us)."""
    # "op:kind:at[:count],..." — e.g. "shard_write:io_error:1" fails the
    # second shard write once. Empty = no injection. See utils/faults.py
    # for the op-name table and docs/ROBUSTNESS.md for the failure model.
    plan: str = ""
    seed: int = 0                    # RNG for corruption offsets/bits
    retry_attempts: int = 3          # total attempts per I/O op
    retry_backoff_s: float = 0.05    # first backoff; doubles per retry
    retry_jitter_s: float = 0.02     # uniform jitter added to each backoff


@dataclasses.dataclass(frozen=True)
class Config:
    name: str
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    train: TrainConfig = dataclasses.field(default_factory=TrainConfig)
    eval: EvalConfig = dataclasses.field(default_factory=EvalConfig)
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    updates: UpdatesConfig = dataclasses.field(default_factory=UpdatesConfig)
    maintenance: MaintenanceConfig = dataclasses.field(
        default_factory=MaintenanceConfig)
    migrate: MigrationConfig = dataclasses.field(
        default_factory=MigrationConfig)
    obs: ObsConfig = dataclasses.field(default_factory=ObsConfig)
    faults: FaultConfig = dataclasses.field(default_factory=FaultConfig)
    workdir: str = "/tmp/dnn_page_vectors_tpu"

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    def replace(self, **kw: Any) -> "Config":
        return dataclasses.replace(self, **kw)


def _nested_replace(cfg: Config, overrides: Dict[str, Any]) -> Config:
    """Apply dotted-path overrides, e.g. {"train.steps": 10}."""
    for path, value in overrides.items():
        parts = path.split(".")
        if len(parts) == 1:
            cfg = dataclasses.replace(cfg, **{parts[0]: value})
            continue
        section = getattr(cfg, parts[0])
        if not isinstance(value, (tuple, list)):
            # coerce CLI strings to the dataclass field's current type
            current = getattr(section, parts[1])
            if isinstance(current, bool):
                if value in (True, "true", "True", "1", 1):
                    value = True
                elif value in (False, "false", "False", "0", 0):
                    value = False
                else:
                    raise ValueError(
                        f"bad boolean for {path}: {value!r} (use true/false)")
            elif isinstance(current, int):
                value = int(value)
            elif isinstance(current, float):
                value = float(value)
            elif isinstance(current, tuple):
                value = tuple(int(x) for x in str(value).split(","))
        elif isinstance(value, list):
            value = tuple(value)
        section = dataclasses.replace(section, **{parts[1]: value})
        cfg = dataclasses.replace(cfg, **{parts[0]: section})
    return cfg


# ---------------------------------------------------------------------------
# The five canonical configs (BASELINE.json:6-12).
# ---------------------------------------------------------------------------

def cdssm_toy() -> Config:
    """Config 1: 'CDSSM char-trigram CNN, 10k-page toy corpus, single-process
    CPU' (BASELINE.json:7). The integration oracle of SURVEY.md §5."""
    return Config(
        name="cdssm_toy",
        data=DataConfig(tokenizer="trigram", corpus="toy", num_pages=10_000),
        model=ModelConfig(encoder="cdssm", conv_widths=(3,), conv_channels=256,
                          embed_dim=128, out_dim=128, dtype="float32"),
        mesh=MeshConfig(data=1),
        train=TrainConfig(batch_size=256, steps=1_000),
    )


def kim_cnn_v5e8() -> Config:
    """Config 2: 'Word-CNN (Kim-CNN) page encoder, 1M pages, data-parallel
    pjit on v5e-8' (BASELINE.json:8)."""
    return Config(
        name="kim_cnn_v5e8",
        data=DataConfig(tokenizer="word", corpus="toy", num_pages=1_000_000,
                        vocab_size=100_000),
        model=ModelConfig(encoder="kim_cnn", conv_widths=(3, 4, 5),
                          conv_channels=256, embed_dim=256, out_dim=256),
        mesh=MeshConfig(data=8),
        train=TrainConfig(batch_size=4_096, steps=50_000),
    )


def lstm_words() -> Config:
    """BiLSTM word-level page encoder — the reference lineage's recurrent
    family (SURVEY.md §1 [PRIOR]; same word-tokenized corpus as config 2).
    Sized like kim_cnn_v5e8 so the two word-family encoders are directly
    comparable on the same data."""
    return Config(
        name="lstm_words",
        data=DataConfig(tokenizer="word", corpus="toy", num_pages=1_000_000,
                        vocab_size=100_000),
        model=ModelConfig(encoder="lstm", embed_dim=256, model_dim=256,
                          num_layers=1, out_dim=256),
        mesh=MeshConfig(data=8),
        train=TrainConfig(batch_size=4_096, steps=50_000),
    )


def bert_mini_v5p16() -> Config:
    """Config 3: 'Two-tower BERT-mini (query + page) with in-batch negatives
    on v5p-16' (BASELINE.json:9). BERT-mini: L=4, H=256, A=4."""
    return Config(
        name="bert_mini_v5p16",
        data=DataConfig(tokenizer="wordpiece", corpus="toy",
                        num_pages=10_000_000, vocab_size=30_522),
        model=ModelConfig(encoder="bert", num_layers=4, num_heads=4,
                          model_dim=256, mlp_dim=1024, out_dim=256),
        mesh=MeshConfig(data=16),
        train=TrainConfig(batch_size=8_192, steps=100_000,
                          learning_rate=5e-4),
    )


def hardneg_v5p64() -> Config:
    """Config 4: 'Hard-negative ANN-mined contrastive training, 100M pages,
    v5p-64' (BASELINE.json:10)."""
    return Config(
        name="hardneg_v5p64",
        data=DataConfig(tokenizer="wordpiece", corpus="toy",
                        num_pages=100_000_000, vocab_size=30_522),
        model=ModelConfig(encoder="bert", num_layers=4, num_heads=4,
                          model_dim=256, mlp_dim=1024, out_dim=256),
        mesh=MeshConfig(data=64),
        train=TrainConfig(batch_size=16_384, steps=200_000,
                          hard_negatives=7, learning_rate=5e-4),
    )


def mt5_multilingual() -> Config:
    """Config 5: 'Multilingual mT5-base page encoder + cross-lingual
    retrieval eval' (BASELINE.json:11). mT5-base encoder: L=12, d=768,
    heads=12, ff=2048; model axis gives optional TP (SURVEY.md §3 #14)."""
    return Config(
        name="mt5_multilingual",
        data=DataConfig(tokenizer="sentencepiece", corpus="toy",
                        num_pages=10_000_000, vocab_size=250_112,
                        page_len=128, languages=4),
        model=ModelConfig(encoder="t5", num_layers=12, num_heads=12,
                          model_dim=768, mlp_dim=2048, out_dim=768),
        mesh=MeshConfig(data=4, model=2),
        train=TrainConfig(batch_size=4_096, steps=100_000,
                          learning_rate=1e-4),
    )


def bert_long_sp() -> Config:
    """Long-page variant beyond the five canonical configs: 1024-token pages
    with ring-attention sequence parallelism over the mesh 'seq' axis
    (parallel/ring_attention.py) and Pallas flash attention available via
    model.attention=flash for the single-chip case. Covers the long-context
    scaling requirement the short-sequence canonical configs don't exercise."""
    return Config(
        name="bert_long_sp",
        data=DataConfig(tokenizer="wordpiece", corpus="toy",
                        num_pages=1_000_000, vocab_size=30_522,
                        page_len=1024, query_len=32),
        model=ModelConfig(encoder="bert", num_layers=4, num_heads=8,
                          model_dim=512, mlp_dim=2048, out_dim=256,
                          attention="ring"),
        mesh=MeshConfig(data=16, seq=4),
        train=TrainConfig(batch_size=2_048, steps=100_000,
                          learning_rate=5e-4),
    )


CONFIGS = {
    "cdssm_toy": cdssm_toy,
    "kim_cnn_v5e8": kim_cnn_v5e8,
    "lstm_words": lstm_words,
    "bert_mini_v5p16": bert_mini_v5p16,
    "hardneg_v5p64": hardneg_v5p64,
    "mt5_multilingual": mt5_multilingual,
    "bert_long_sp": bert_long_sp,
}


def get_config(name: str, overrides: Optional[Dict[str, Any]] = None) -> Config:
    if name not in CONFIGS:
        raise KeyError(f"unknown config {name!r}; have {sorted(CONFIGS)}")
    cfg = CONFIGS[name]()
    if overrides:
        cfg = _nested_replace(cfg, overrides)
    return cfg
