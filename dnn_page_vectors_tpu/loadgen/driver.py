"""The SLO driver (docs/SERVING.md "SLO methodology").

Runs timed load trials against a live `SearchService` and searches offered
load for the production metric ROADMAP item 2 asked for by name: the
maximum sustained QPS at which the windowed p99 stays under a target —
"qps @ p99 < X ms".

Measurement discipline:

  * every trial number is read FROM THE PR-7 REGISTRY
    (`SearchService.metrics()`: `serve_window_qps`, `serve_window_p50_ms`
    / `serve_window_p99_ms`, error/cache-hit rates over the last
    `obs.window_s` seconds) — the driver never re-derives latency from
    its own wall clocks, so the number an operator sees on the
    `serve-metrics` exposition and the number a trial reports are THE
    SAME instrument;
  * a trial runs `warmup_s + duration_s` of offered traffic and reads the
    registry once at the end: with `duration_s >= obs.window_s` the
    rolling window has fully turned over past the warmup, so compile
    spikes and cold caches age out of the measurement by construction
    (the warmup is discarded by the window, not by special-casing);
  * lifecycle events (`view_swap`, `window_adapt`, `recompile`,
    `index_degraded`, ...) observed DURING the trial ride along in the
    trial record — a p99 excursion correlates to the swap/compile that
    caused it instead of being averaged into mystery.

Open-loop trials replay the workload's seeded arrival schedule on a
thread pool (`workers` in-flight submissions; `workers=0` issues
synchronously — the deterministic mode the fake-clock tests use).
Closed-loop trials run `int(load)` workers. `clock`/`sleep` are
injectable so the whole driver runs on a fake clock with no real sleeps.

`find_qps_at_p99` is the search loop: double offered load while the
target holds, then bisect the bracket — each probe is one full trial, and
every trial (passing or failing) lands in the report so the latency/load
curve is auditable after the fact.
"""
from __future__ import annotations

import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Sequence

from dnn_page_vectors_tpu.infer.transport import DeadlineExceeded
from dnn_page_vectors_tpu.loadgen.workload import Mutator, Workload


def snapshot_line(svc, extra: Optional[Dict] = None) -> str:
    """One single-line JSON tick of the live SLO view — the format
    `cli serve-metrics --watch` prints and the driver reuses for trial
    progress. Keys are the windowed registry block plus counters an
    operator eyeballs during a run."""
    m = svc.metrics()
    rec = {
        "ts": round(time.time(), 3),
        "window_qps": m.get("serve_window_qps"),
        "window_p50_ms": m.get("serve_window_p50_ms"),
        "window_p99_ms": m.get("serve_window_p99_ms"),
        "window_error_rate": m.get("serve_window_error_rate"),
        "window_cache_hit_rate": m.get("serve_window_cache_hit_rate"),
        "queue_wait_p99_ms": m.get("serve_window_queue_wait_p99_ms"),
        "batch_window_ms": m.get("serve_batch_window_ms"),
        "recompiles": m.get("serve_recompiles"),
        "degraded": m.get("serve_degraded"),
    }
    # over-the-wire block (docs/SERVING.md "Network front end"): only
    # when the service reports one — an in-process service's tick stays
    # byte-identical to the pre-transport format
    transport = m.get("transport") or {}
    rec["wire_bytes"] = transport.get("wire_bytes")
    rec["wire_compression_ratio"] = transport.get("wire_compression_ratio")
    rec["deadline_sheds"] = transport.get("deadline_sheds")
    rec["hedge_fires"] = transport.get("hedge_fires")
    rec["workers_live"] = transport.get("workers_live")
    if extra:
        rec.update(extra)
    return json.dumps({k: v for k, v in rec.items() if v is not None})


class BalancedClient:
    """Client-side balancer over N front ends (docs/SCALING.md
    "Scale-out tier").

    Wraps one search client per front end and spreads `search()` calls
    across them, so a multi-front-end loadtest hammers the tier as ONE
    unit. Two seeded policies:

      * ``round_robin`` — deterministic rotation starting at
        ``seed % n``; with a fixed workload seed the (request -> front
        end) assignment replays exactly;
      * ``least_loaded`` — pick the front end with the fewest in-flight
        requests; ties break by the same seeded rotation so the policy
        stays deterministic under a synchronous (workers=0) trial.

    The balancer only routes — every measured number still reads from
    each front end's OWN registry (`run_trial`'s `front_ends=` block),
    keeping the driver's one-instrument measurement discipline.
    """

    POLICIES = ("round_robin", "least_loaded")

    def __init__(self, clients: Sequence, policy: str = "round_robin",
                 seed: int = 0):
        if not clients:
            raise ValueError("BalancedClient needs at least one client")
        if policy not in self.POLICIES:
            raise ValueError(
                f"unknown balance policy {policy!r} (want one of "
                f"{self.POLICIES})")
        self.clients = list(clients)
        self.policy = policy
        self._lock = threading.Lock()
        n = len(self.clients)
        self._next = int(seed) % n            # guarded-by: _lock
        self._inflight = [0] * n              # guarded-by: _lock
        self._sent = [0] * n                  # guarded-by: _lock
        self._errors = [0] * n                # guarded-by: _lock

    def _pick(self) -> int:
        with self._lock:
            n = len(self.clients)
            if self.policy == "least_loaded":
                # tie-break by seeded rotation distance so equal-load
                # picks stay deterministic
                nxt = self._next
                i = min(range(n),
                        key=lambda j: (self._inflight[j], (j - nxt) % n))
            else:
                i = self._next
            self._next = (i + 1) % n
            self._inflight[i] += 1
            self._sent[i] += 1
            return i

    def search(self, query, k: int = 10, nprobe: Optional[int] = None,
               filters=None):
        i = self._pick()
        try:
            if filters is not None:
                return self.clients[i].search(query, k=k, nprobe=nprobe,
                                              filters=filters)
            return self.clients[i].search(query, k=k, nprobe=nprobe)
        except Exception:
            with self._lock:
                self._errors[i] += 1
            raise
        finally:
            with self._lock:
                self._inflight[i] -= 1

    def stats(self) -> Dict:
        """Per-front-end routing tallies (client-side view; the
        authoritative latency numbers come from each front end's
        registry)."""
        with self._lock:
            return {
                "policy": self.policy,
                "sent": list(self._sent),
                "errors": list(self._errors),
            }


def run_trial(svc, workload: Workload, offered: float, queries: Sequence[str],
              *, duration_s: float = 10.0, warmup_s: float = 0.0,
              workers: int = 16, mutator: Optional[Mutator] = None,
              clock: Callable[[], float] = time.monotonic,
              sleep: Callable[[float], None] = time.sleep,
              progress: Optional[Callable[[str], None]] = None,
              progress_every_s: float = 0.0, client=None,
              front_ends: Optional[Sequence] = None) -> Dict:
    """One timed trial at one offered load; returns the trial record.

    `offered` is a rate (qps) for open-loop workloads and a worker count
    for closed-loop ones. `queries` maps the workload's distinct query
    ids onto real query texts (`query_id % len(queries)`).

    `client` (a transport.SocketSearchClient, or anything with the same
    `search(query, k, nprobe)` shape) reroutes the ISSUE path over the
    wire while every measured number still reads from `svc`'s registry —
    qps@p99 then covers the full network path: framing, admission,
    batcher, RPC fan-out, and the socket round trip back.

    `front_ends` (a sequence of SearchService, `svc` first) turns the
    trial into a TIER measurement (docs/SCALING.md "Scale-out tier"):
    `client` should be a `BalancedClient` spreading load across them,
    and the record's headline numbers become tier aggregates — achieved
    qps is the SUM of the per-front-end window qps, p99 the MAX (the
    tier is only as fast as its slowest member), error rate the
    qps-weighted mean — with a per-front-end block riding along so an
    imbalance or a single hot front end is attributable."""
    ev0 = len(svc.registry.events()) if hasattr(svc, "registry") else 0
    mut0 = mutator.calls if mutator is not None else 0
    m0 = svc.metrics()
    transport0 = dict(m0.get("transport") or {})
    rcache0 = dict(m0.get("result_cache") or {})
    sent = 0
    errors = 0
    sheds = 0
    err_lock = threading.Lock()
    # per-scenario client-side latency samples under a filtered mix
    # (docs/ANN.md "Filtered retrieval"): the registry can't attribute a
    # window sample to a predicate, so the scenario block is the one
    # place the driver measures with its own clock — labeled as such
    scen_lat: Dict[str, List[float]] = {}
    issue_to = client if client is not None else svc

    def _issue(req):
        nonlocal errors, sheds
        kw = {"filters": req.filters} if req.filters else {}
        t_req = clock()
        try:
            issue_to.search(queries[req.query_id % len(queries)], k=req.k,
                            nprobe=req.nprobe, **kw)
        except DeadlineExceeded:
            # an admission shed is an availability decision the trial
            # reports separately, not a server error
            with err_lock:
                sheds += 1
        except Exception:  # noqa: BLE001 — errors are a trial METRIC
            with err_lock:
                errors += 1
        else:
            if req.scenario is not None:
                with err_lock:
                    scen_lat.setdefault(req.scenario, []).append(
                        clock() - t_req)

    total_s = float(warmup_s) + float(duration_s)
    t0 = clock()
    next_tick = progress_every_s or float("inf")

    def _tick(now):
        nonlocal next_tick
        if progress is not None and now - t0 >= next_tick:
            next_tick += progress_every_s
            progress(snapshot_line(
                svc, {"offered": offered, "elapsed_s": round(now - t0, 2)}))

    if workload.kind == "closed":
        n_workers = max(1, int(offered))
        stop = t0 + total_s

        def _worker(wid: int):
            nonlocal sent
            stream = workload.worker_stream(wid)
            while clock() < stop:
                _issue(next(stream))
                with err_lock:
                    sent += 1
                if workload.think_s:
                    sleep(workload.think_s)
                _tick(clock())
                if mutator is not None:
                    mutator.maybe_fire(clock() - t0, base=mut0)

        if workers == 0:
            _worker(0)
        else:
            threads = [threading.Thread(target=_worker, args=(w,),
                                        daemon=True)
                       for w in range(n_workers)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        schedule_digest = None
    else:
        schedule = workload.schedule(total_s, float(offered))
        schedule_digest = Workload.digest(schedule)
        pool = ThreadPoolExecutor(max_workers=workers) if workers else None
        futures = []
        try:
            for t_arr, req in schedule:
                now = clock()
                if t0 + t_arr > now:
                    sleep(t0 + t_arr - now)
                    now = t0 + t_arr
                if mutator is not None:
                    mutator.maybe_fire(now - t0, base=mut0)
                _tick(now)
                if pool is None:
                    _issue(req)          # synchronous deterministic mode
                else:
                    futures.append(pool.submit(_issue, req))
                sent += 1
            rem = t0 + total_s - clock()
            if rem > 0:
                sleep(rem)
        finally:
            if pool is not None:
                for f in futures:
                    f.result()
                pool.shutdown(wait=True)

    m = svc.metrics()
    events = (svc.registry.events()[ev0:]
              if hasattr(svc, "registry") else [])
    rec = {
        "offered_qps": round(float(offered), 3),
        "achieved_qps": m.get("serve_window_qps", 0.0),
        "p50_ms": m.get("serve_window_p50_ms", 0.0),
        "p99_ms": m.get("serve_window_p99_ms", 0.0),
        "error_rate": m.get("serve_window_error_rate", 0.0),
        "cache_hit_rate": m.get("serve_window_cache_hit_rate", 0.0),
        "queue_wait_p99_ms": m.get("serve_window_queue_wait_p99_ms"),
        "batch_window_ms": m.get("serve_batch_window_ms"),
        "recompiles": m.get("serve_recompiles"),
        "degraded": bool(m.get("serve_degraded", False)),
        "ann_fallbacks": m.get("ann_fallbacks", 0),
        "full_rebuilds": m.get("full_rebuilds", 0),
        "requests_sent": sent,
        "errors": errors,
        "shape": workload.shape,
        "duration_s": round(float(duration_s), 3),
        "warmup_s": round(float(warmup_s), 3),
        "events": [{"event": e["event"], "attrs": e["attrs"],
                    "trace_id": e.get("trace_id")} for e in events],
    }
    if front_ends is not None and len(front_ends) > 1:
        # scale-out tier (docs/SCALING.md): per-front-end qps/p99 block
        # mirrors the partitions block — each row reads that front end's
        # OWN registry — and the headline numbers become tier aggregates
        fes = []
        for i, fe in enumerate(front_ends):
            fm = fe.metrics() if fe is not svc else m
            fes.append({
                "front_end": i,
                "qps": fm.get("serve_window_qps", 0.0),
                "p50_ms": fm.get("serve_window_p50_ms", 0.0),
                "p99_ms": fm.get("serve_window_p99_ms", 0.0),
                "error_rate": fm.get("serve_window_error_rate", 0.0),
            })
        tier_qps = sum(f["qps"] for f in fes)
        rec["front_ends"] = fes
        rec["achieved_qps"] = round(tier_qps, 3)
        rec["p99_ms"] = max(f["p99_ms"] for f in fes)
        rec["p50_ms"] = max(f["p50_ms"] for f in fes)
        rec["error_rate"] = (
            round(sum(f["error_rate"] * f["qps"] for f in fes)
                  / tier_qps, 4) if tier_qps else
            max(f["error_rate"] for f in fes))
        if isinstance(client, BalancedClient):
            rec["balance"] = client.stats()
    if "partitions" in m:
        # partitioned serving (docs/SCALING.md): the per-partition
        # qps/p99/shed block + routing counters ride each trial record,
        # so a p99 excursion attributes to the partition that shed
        rec["partitions"] = m["partitions"]
        rec["replica_shed"] = m.get("replica_shed", 0)
        rec["partition_degraded"] = m.get("partition_degraded", 0)
    transport1 = m.get("transport")
    if transport1 or sheds:
        # over-the-wire block (docs/SERVING.md "Network front end"),
        # ONLY when the trial actually crossed a transport (or shed):
        # in-process trial records stay byte-identical to before.
        # Counter keys are PER-TRIAL deltas against the trial-start
        # snapshot; topology keys (workers_live) report the end state.
        blk: Dict = {}
        for key in ("wire_bytes", "wire_raw_bytes", "deadline_sheds",
                    "hedge_fires", "rpcs", "rpc_fallbacks",
                    "breaker_trips"):
            new = (transport1 or {}).get(key)
            if new is not None:
                blk[key] = new - transport0.get(key, 0)
        if "wire_raw_bytes" in blk and blk.get("wire_bytes"):
            blk["wire_compression_ratio"] = round(
                blk["wire_raw_bytes"] / blk["wire_bytes"], 3)
        for key in ("workers_live", "workers_registered",
                    "workers_compressing", "breakers_open"):
            if transport1 and key in transport1:
                blk[key] = transport1[key]
        if sheds:
            blk["client_sheds"] = sheds
        rec["transport"] = blk
    rcache1 = m.get("result_cache")
    if rcache1:
        # result-cache block (docs/SERVING.md "Result cache"), ONLY when
        # the feature is on: hit/miss counters are per-trial deltas
        # against the trial-start snapshot; entries/bytes are end state
        rhits = rcache1.get("hits", 0) - rcache0.get("hits", 0)
        rmiss = rcache1.get("misses", 0) - rcache0.get("misses", 0)
        rec["result_cache"] = {
            "hits": rhits, "misses": rmiss,
            "hit_rate": round(rhits / (rhits + rmiss), 4)
            if (rhits + rmiss) else 0.0,
            "entries": rcache1.get("entries", 0),
            "bytes": rcache1.get("bytes", 0),
        }
    if scen_lat:
        # filtered-mix block (docs/ANN.md "Filtered retrieval"): one row
        # per scenario — CLIENT-side latency around the issue call (the
        # registry's window p99 stays the headline; this block only
        # attributes load across predicates)
        import numpy as _np
        rec["filter_scenarios"] = {
            name: {
                "requests": len(lat),
                "qps": round(len(lat) / max(total_s, 1e-9), 2),
                "p50_ms": round(
                    float(_np.percentile(lat, 50)) * 1000.0, 3),
                "p99_ms": round(
                    float(_np.percentile(lat, 99)) * 1000.0, 3),
            } for name, lat in sorted(scen_lat.items())}
    if schedule_digest is not None:
        rec["schedule_digest"] = schedule_digest
    if mutator is not None:
        rec["mutator_calls"] = mutator.calls - mut0
        if len(mutator.ops) > 1:
            rec["mutator_calls_by_op"] = dict(mutator.calls_by_op)
        if mutator.errors:
            rec["mutator_errors"] = mutator.errors
    return rec


def _meets(trial: Dict, p99_target_ms: float, max_error_rate: float,
           sustain_frac: float) -> bool:
    """Did a trial hold the objective? p99 under target, errors under the
    budget, and — open loop only — the service actually KEPT UP with the
    offered rate (an overloaded open-loop service shows a sagging
    achieved rate as its queue grows; that is a miss even if the window's
    p99 lags behind the cliff)."""
    if trial["p99_ms"] > p99_target_ms:
        return False
    if trial["error_rate"] > max_error_rate:
        return False
    if trial["shape"] != "closed" and trial["offered_qps"] > 0:
        if trial["achieved_qps"] < sustain_frac * trial["offered_qps"]:
            return False
    return True


def find_qps_at_p99(svc, workload: Workload, queries: Sequence[str],
                    p99_target_ms: float, *, start: float = 8.0,
                    max_load: float = 65_536.0, iters: int = 5,
                    duration_s: float = 10.0, warmup_s: float = 2.0,
                    workers: int = 16, max_error_rate: float = 0.0,
                    sustain_frac: float = 0.8,
                    mutator: Optional[Mutator] = None,
                    clock: Callable[[], float] = time.monotonic,
                    sleep: Callable[[float], None] = time.sleep,
                    progress: Optional[Callable[[str], None]] = None,
                    progress_every_s: float = 0.0, client=None,
                    front_ends: Optional[Sequence] = None) -> Dict:
    """Binary-search offered load for the max sustained QPS meeting the
    p99 target. Doubling phase brackets the cliff, bisection sharpens it;
    `qps_at_p99` is the best ACHIEVED qps among passing trials (what the
    service demonstrably served, not what was merely offered). With
    `client` set the issue path crosses the socket (run_trial) so the
    measured qps@p99 covers the full network path."""
    trials: List[Dict] = []

    def _trial(load: float) -> Dict:
        tr = run_trial(svc, workload, load, queries, duration_s=duration_s,
                       warmup_s=warmup_s, workers=workers, mutator=mutator,
                       clock=clock, sleep=sleep, progress=progress,
                       progress_every_s=progress_every_s, client=client,
                       front_ends=front_ends)
        tr["met"] = _meets(tr, p99_target_ms, max_error_rate, sustain_frac)
        trials.append(tr)
        if progress is not None:
            progress(json.dumps({
                "trial": len(trials), "offered": tr["offered_qps"],
                "achieved": tr["achieved_qps"], "p99_ms": tr["p99_ms"],
                "met": tr["met"]}))
        return tr

    lo, hi = 0.0, float(start)
    tr = _trial(hi)
    if tr["met"]:
        # doubling phase: raise offered load until the target breaks
        while hi < max_load:
            lo, hi = hi, min(max_load, hi * 2.0)
            if not _trial(hi)["met"]:
                break
        else:
            lo = hi
    # bisection phase inside (lo, hi]
    for _ in range(max(0, int(iters))):
        if hi - lo <= max(1.0, 0.05 * lo):
            break
        mid = (lo + hi) / 2.0
        if workload.kind == "closed":
            mid = float(int(mid))
            if mid <= lo:
                break
        if _trial(mid)["met"]:
            lo = mid
        else:
            hi = mid
    passing = [t for t in trials if t["met"]]
    qps = max((t["achieved_qps"] for t in passing), default=0.0)
    return {
        "qps_at_p99": round(qps, 2),
        "p99_target_ms": float(p99_target_ms),
        "shape": workload.shape,
        "seed": workload.seed,
        "load_sustained": lo,
        "trials": trials,
        "trial_duration_s": float(duration_s),
        "trial_warmup_s": float(warmup_s),
        "sustain_frac": sustain_frac,
        "events": [e for t in trials for e in t["events"]],
    }
