"""Production SLO harness (docs/SERVING.md "SLO methodology"):

  * `workload` — seeded open-loop (Poisson, burst) and closed-loop
    traffic models over a Zipfian query mix with mixed (k, nprobe)
    profiles, plus the optional concurrent append/refresh `Mutator`;
  * `driver` — timed trials against a live `SearchService`, every number
    read from the PR-7 telemetry registry, and the binary search for
    "qps @ p99 < X ms".

Entry points: `cli loadtest` (one-shot report), the bench `slo` phase
(regression-gated trajectory), and `tests/test_loadgen.py` (the `slo`
marker).
"""
from dnn_page_vectors_tpu.loadgen.driver import (
    BalancedClient, find_qps_at_p99, run_trial, snapshot_line)
from dnn_page_vectors_tpu.loadgen.workload import (
    DEFAULT_PROFILE, SHAPES, BurstWorkload, ClosedLoopWorkload, Mutator,
    PoissonWorkload, QueryMix, Request, Workload, make_workload)

__all__ = [
    "BalancedClient", "BurstWorkload", "ClosedLoopWorkload",
    "DEFAULT_PROFILE", "Mutator",
    "PoissonWorkload", "QueryMix", "Request", "SHAPES", "Workload",
    "find_qps_at_p99", "make_workload", "run_trial", "snapshot_line",
]
