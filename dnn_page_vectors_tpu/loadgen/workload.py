"""Seeded workload models for the SLO harness (docs/SERVING.md).

A production latency objective is meaningless without saying what traffic
it holds under — and the bench serve phase's "N threads hammer as fast as
they can" is CLOSED-loop traffic: when the service slows down, the
offered load politely slows down with it, which is exactly the
coordination that hides latency cliffs (the coordinated-omission trap).
This module models the shapes that matter and nothing else:

  * **open-loop Poisson** (`PoissonWorkload`) — requests arrive on an
    exponential inter-arrival clock regardless of how the service is
    doing; a service slower than the offered rate builds a queue and the
    p99 shows it. The honest default for "qps @ p99 < X ms".
  * **open-loop burst** (`BurstWorkload`) — an on/off modulated Poisson
    process (mean rate preserved: the on-phase rate is scaled up by the
    duty cycle) that slams the micro-batcher window with alternating
    silence and bursts — the shape adaptive batching exists for.
  * **closed-loop** (`ClosedLoopWorkload`) — N workers issue, wait,
    think, repeat. The classic benchmark shape, kept because its
    concurrency knob maps directly onto "how many callers fit under the
    target" — and because comparing it against the open-loop number
    exposes coordination effects.

Every workload draws queries from one `QueryMix`: a Zipfian repeat
distribution over `distinct` query ids (head-skewed traffic exercises the
LRU embedding cache like production does; `alpha=0` degrades to uniform)
crossed with a mixed (k, nprobe) profile, so one trial exercises several
compiled top-k shapes the way mixed tenants would.

Determinism: everything derives from ONE integer seed. `schedule()` and
`worker_stream()` re-derive their RNG from (seed, call parameters) on
every call, so two runs with the same seed produce IDENTICAL offered-load
schedules — the property the acceptance test pins and the reason a bench
regression between rounds means the SERVICE changed, not the traffic.

The optional `Mutator` wraps an append/refresh callable with a period, so
the driver can exercise the zero-downtime hot-swap path (docs/UPDATES.md)
under fire.
"""
from __future__ import annotations

import dataclasses
import hashlib
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

# one (k, nprobe, weight) entry: nprobe None = the service's serve.nprobe
Profile = Sequence[Tuple[int, Optional[int], float]]
DEFAULT_PROFILE: Profile = ((10, None, 1.0),)

# one (scenario name, predicate text or None, weight) entry — the
# filtered-query mix `cli loadtest --filters` arms (docs/ANN.md "Filtered
# retrieval"). The default predicates all match the all-zero attribute
# word, so the mix exercises the filtered scan path even on a store whose
# shards predate init_attrs().
FilterScenarios = Sequence[Tuple[str, Optional[str], float]]
DEFAULT_FILTER_SCENARIOS: FilterScenarios = (
    ("unfiltered", None, 0.5),
    ("lang", "lang==0", 0.25),
    ("site", "site in {0}", 0.15),
    ("recent", "recency>=0", 0.10),
)

SHAPES = ("poisson", "burst", "closed")


def _rng(seed: int, *parts) -> np.random.Generator:
    """Deterministic per-call generator: the seed folded with the call
    parameters, so the same (seed, params) always replays the same stream
    and different trials never share one."""
    h = hashlib.sha256(repr((int(seed),) + tuple(parts)).encode())
    return np.random.default_rng(
        int.from_bytes(h.digest()[:8], "little"))


@dataclasses.dataclass(frozen=True)
class Request:
    """One offered request: which distinct query, its (k, nprobe) drawn
    from the workload's profile, and — under a filtered mix — the
    scenario name plus the canonical predicate text it carries."""
    query_id: int
    k: int
    nprobe: Optional[int] = None
    filters: Optional[str] = None
    scenario: Optional[str] = None


class QueryMix:
    """Zipfian query-repeat distribution + a mixed (k, nprobe) profile.

    Rank-i query probability ~ 1/(i+1)^alpha over `distinct` ids: rank 0
    is the head query the LRU cache should pin, the tail keeps missing.
    """

    def __init__(self, distinct: int, alpha: float = 1.1,
                 profile: Profile = DEFAULT_PROFILE,
                 filter_scenarios: Optional[FilterScenarios] = None):
        self.distinct = max(1, int(distinct))
        self.alpha = float(alpha)
        self.profile = tuple(
            (int(k), None if np_ is None else int(np_), float(w))
            for k, np_, w in profile)
        p = np.arange(1, self.distinct + 1, dtype=np.float64) ** -self.alpha
        self._p = p / p.sum()
        w = np.asarray([w for _, _, w in self.profile], np.float64)
        self._pw = w / w.sum()
        # filtered-query scenarios (docs/ANN.md "Filtered retrieval"):
        # predicate texts canonicalize at construction so every request
        # of one scenario carries ONE exact text — the form the result
        # cache keys on. None = the pre-filters sampler, byte-identical
        # request streams included (no extra RNG draws).
        self.scenarios: Optional[Tuple[Tuple[str, Optional[str], float],
                                       ...]] = None
        self._ps = None
        if filter_scenarios is not None:
            from dnn_page_vectors_tpu.index import attrs as attrs_mod
            self.scenarios = tuple(
                (str(name),
                 None if pred is None
                 else attrs_mod.Predicate.parse(pred).text,
                 float(w))
                for name, pred, w in filter_scenarios)
            ws = np.asarray([w for _, _, w in self.scenarios], np.float64)
            self._ps = ws / ws.sum()

    def sample(self, rng: np.random.Generator, n: int) -> List[Request]:
        qids = rng.choice(self.distinct, size=n, p=self._p)
        prof = rng.choice(len(self.profile), size=n, p=self._pw)
        if self.scenarios is None:
            return [Request(int(q), self.profile[j][0], self.profile[j][1])
                    for q, j in zip(qids, prof)]
        scen = rng.choice(len(self.scenarios), size=n, p=self._ps)
        return [Request(int(q), self.profile[j][0], self.profile[j][1],
                        filters=self.scenarios[s][1],
                        scenario=self.scenarios[s][0])
                for q, j, s in zip(qids, prof, scen)]


class Workload:
    """Base: a seed + a QueryMix. Subclasses are either `kind="open"`
    (implement `schedule()`) or `kind="closed"` (implement
    `worker_stream()`)."""

    shape = "base"
    kind = "open"

    def __init__(self, mix: QueryMix, seed: int = 0):
        self.mix = mix
        self.seed = int(seed)

    def schedule(self, duration_s: float,
                 rate_qps: float) -> List[Tuple[float, Request]]:
        raise NotImplementedError

    def worker_stream(self, worker_id: int) -> Iterator[Request]:
        raise NotImplementedError

    @staticmethod
    def digest(schedule: Sequence[Tuple[float, Request]]) -> str:
        """Stable fingerprint of an offered-load schedule (arrival times
        at microsecond grain + the request stream) — two runs with the
        same seed must report the same digest."""
        h = hashlib.sha256()
        for t, req in schedule:
            # the scenario tag folds in only for FILTERED requests, so an
            # unfiltered schedule's digest is byte-identical to the
            # pre-filters format
            scen = f":{req.scenario}" if req.filters else ""
            h.update(f"{t:.6f}:{req.query_id}:{req.k}:{req.nprobe}{scen};"
                     .encode())
        return h.hexdigest()[:16]


class PoissonWorkload(Workload):
    """Open-loop Poisson arrivals at a given offered rate."""

    shape = "poisson"
    kind = "open"

    def schedule(self, duration_s: float,
                 rate_qps: float) -> List[Tuple[float, Request]]:
        rate = max(1e-9, float(rate_qps))
        rng = _rng(self.seed, "poisson", round(float(duration_s), 6),
                   round(rate, 6))
        times: List[float] = []
        t = 0.0
        while True:
            t += rng.exponential(1.0 / rate)
            if t >= duration_s:
                break
            times.append(t)
        reqs = self.mix.sample(rng, len(times))
        return list(zip(times, reqs))


class BurstWorkload(Workload):
    """Open-loop on/off bursts: Poisson arrivals during `on_s` windows,
    silence during `off_s` windows, with the ON rate scaled by the duty
    cycle so the MEAN offered rate equals `rate_qps` — trials at the same
    nominal load are comparable across shapes."""

    shape = "burst"
    kind = "open"

    def __init__(self, mix: QueryMix, seed: int = 0, on_s: float = 0.5,
                 off_s: float = 0.5):
        super().__init__(mix, seed)
        self.on_s = max(1e-3, float(on_s))
        self.off_s = max(0.0, float(off_s))

    def schedule(self, duration_s: float,
                 rate_qps: float) -> List[Tuple[float, Request]]:
        duty = self.on_s / (self.on_s + self.off_s)
        burst_rate = max(1e-9, float(rate_qps)) / duty
        rng = _rng(self.seed, "burst", round(float(duration_s), 6),
                   round(float(rate_qps), 6), round(self.on_s, 6),
                   round(self.off_s, 6))
        times: List[float] = []
        period = self.on_s + self.off_s
        start = 0.0
        while start < duration_s:
            t = start
            end = min(start + self.on_s, duration_s)
            while True:
                t += rng.exponential(1.0 / burst_rate)
                if t >= end:
                    break
                times.append(t)
            start += period
        reqs = self.mix.sample(rng, len(times))
        return list(zip(times, reqs))


class ClosedLoopWorkload(Workload):
    """Closed loop: the driver runs `int(load)` workers, each drawing its
    own seeded request stream and optionally thinking `think_s` between
    requests. Offered load is the worker count, not a rate."""

    shape = "closed"
    kind = "closed"

    def __init__(self, mix: QueryMix, seed: int = 0, think_s: float = 0.0):
        super().__init__(mix, seed)
        self.think_s = max(0.0, float(think_s))

    def worker_stream(self, worker_id: int) -> Iterator[Request]:
        rng = _rng(self.seed, "closed", int(worker_id))
        while True:
            yield self.mix.sample(rng, 1)[0]


class Mutator:
    """A concurrent corpus mutation riding along with the load
    (docs/UPDATES.md): every `period_s` of trial time the driver invokes
    the next op (typically append_corpus + SearchService.refresh) so the
    SLO trial measures serving UNDER hot-swap, not beside it. `calls`
    counts invocations; exceptions are stored, never raised into the
    trial.

    `ops` generalizes the single `fn` to a NAMED round-robin of
    mutations — the maintenance-under-fire mode (docs/MAINTENANCE.md)
    alternates tombstone+refresh with a full maintenance pass
    (compaction + background rebuild), so `cli loadtest --mutate-mode
    maintain` measures serve p99 with the compactor and rebuilder
    actually running. `calls_by_op` records how often each fired."""

    def __init__(self, fn: Optional[Callable[[], None]] = None,
                 period_s: float = 1.0,
                 ops: Optional[Sequence[Tuple[str, Callable[[], None]]]]
                 = None):
        if (fn is None) == (ops is None):
            raise ValueError("Mutator wants exactly one of fn= or ops=")
        self.ops: List[Tuple[str, Callable[[], None]]] = (
            list(ops) if ops is not None else [("mutate", fn)])
        self.period_s = max(1e-3, float(period_s))
        self.calls = 0
        self.calls_by_op = {name: 0 for name, _ in self.ops}
        self.errors: List[str] = []

    def maybe_fire(self, elapsed_s: float, base: int = 0) -> bool:
        """Fire when `elapsed_s` of trial time covers the next period.
        `base` is the call count at trial start, so one Mutator shared
        across a whole qps@p99 search fires on EVERY trial's schedule
        instead of slowing down as calls accumulate."""
        if elapsed_s < (self.calls - base + 1) * self.period_s:
            return False
        name, op = self.ops[self.calls % len(self.ops)]
        self.calls += 1
        self.calls_by_op[name] += 1
        try:
            op()
        except Exception as e:  # noqa: BLE001 — the trial must survive
            self.errors.append(f"{name}: {type(e).__name__}: {e}"[:200])
        return True


def make_workload(shape: str, *, seed: int = 0, distinct: int = 64,
                  alpha: float = 1.1, profile: Profile = DEFAULT_PROFILE,
                  on_s: float = 0.5, off_s: float = 0.5,
                  think_s: float = 0.0,
                  filter_scenarios: Optional[FilterScenarios] = None
                  ) -> Workload:
    """One factory for the CLI/bench/driver: shape name -> Workload."""
    mix = QueryMix(distinct, alpha=alpha, profile=profile,
                   filter_scenarios=filter_scenarios)
    if shape == "poisson":
        return PoissonWorkload(mix, seed=seed)
    if shape == "burst":
        return BurstWorkload(mix, seed=seed, on_s=on_s, off_s=off_s)
    if shape == "closed":
        return ClosedLoopWorkload(mix, seed=seed, think_s=think_s)
    raise ValueError(f"unknown workload shape {shape!r}; have {SHAPES}")
