"""Live corpus updates (docs/UPDATES.md): append-only store generations,
incremental IVF refresh, zero-downtime serving hot-swap.

The batch pipeline treats the corpus as immutable — embed once, index
once, serve until the next full rebuild. This subsystem makes the
store/index/serve stack mutable end to end:

  * `append_corpus` embeds ONLY the new id-range (plus any updated pages)
    into a fresh store generation, with tombstones masking the stale rows
    (infer/vector_store.py GenerationWriter);
  * `IVFIndex.update` (index/ivf.py) assigns only the new generation's
    shards to the existing centroids — O(new shards), not O(corpus) —
    until accumulated drift triggers a full k-means rebuild; on a PQ
    index (docs/ANN.md) the new shards' codes encode with the existing
    rotation/codebooks, the same O(new shards) append;
  * `SearchService.refresh` (infer/serve.py) atomically swaps the new
    store view + index generation under live traffic.

Appends run under a per-writer lease on the id cursor
(dnn_page_vectors_tpu/maintenance/lease.py, docs/MAINTENANCE.md), so
concurrent writers queue or fail fast instead of double-assigning ids;
the background maintenance service folds the resulting generation chain
back down once tombstone density crosses the compaction threshold.
"""
from dnn_page_vectors_tpu.updates.append import append_corpus

__all__ = ["append_corpus"]
