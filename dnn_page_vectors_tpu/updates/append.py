"""Append new/updated pages to an embedded store as one generation
(docs/UPDATES.md; the write half of the live-update subsystem).

`embed_corpus` sweeps the WHOLE corpus and owns the base (generation-0)
layout; this path embeds only the delta — the id-range past the store's
append cursor (`next_page_id`, which counts quarantined ranges so a lost
shard's ids are never re-issued to new documents) plus any explicitly
updated pages — and publishes it atomically through the GenerationWriter
protocol: data files first, generation manifest last, so a crash or an
injected fault mid-append costs exactly the uncommitted generation and
readers keep serving the previous one.

Determinism: the same corpus range, params, and store dtype produce
byte-identical generation files (the page tower's fp16 cast and the int8
quantization math are shared with the bulk path), so an append is as
reproducible as the base embed — test-pinned in tests/test_updates.py.
"""
from __future__ import annotations

import time
from typing import Dict, Iterable, Optional

import numpy as np

from dnn_page_vectors_tpu.infer.bulk_embed import BulkEmbedder
from dnn_page_vectors_tpu.infer.vector_store import VectorStore
from dnn_page_vectors_tpu.maintenance.lease import AppendLease
from dnn_page_vectors_tpu.utils import faults, telemetry


def append_corpus(embedder: BulkEmbedder, corpus, store: VectorStore,
                  start: Optional[int] = None, stop: Optional[int] = None,
                  tombstone: Iterable[int] = (),
                  update_ids: Iterable[int] = (),
                  batch_size: Optional[int] = None,
                  log=None, lease: bool = True,
                  attrs: Optional[int] = None) -> Dict:
    """Embed corpus pages [start, stop) — default: everything past the
    store's append cursor — plus `update_ids` (existing pages re-embedded
    with fresh text) into a new generation; `tombstone` page ids are
    deleted outright. Updated ids are tombstoned automatically, so their
    old rows mask out while the new rows serve.

    `attrs` (docs/ANN.md "Filtered retrieval"): one packed uint32
    attribute word (`index/attrs.pack_word`) stamped on EVERY row this
    append writes — the batch-level grain `cli append --attrs` exposes.
    Requires an attrs-enabled store (`init_attrs()`); on a store with no
    attribute table the refusal happens before any embedding work.

    Multi-writer safety (docs/MAINTENANCE.md): the whole cursor-read →
    embed → commit window runs under a per-writer append lease
    (`updates.writer_lease_s` ttl, renewed per shard so long appends
    never outlive it; `updates.lease_wait_s` queue budget) — a second
    concurrent writer queues on the lease or fails fast with LeaseHeld,
    and can never read the same cursor. `lease=False` opts out for
    callers that hold their own serialization.

    Returns the append stats dict (generation, appended, updated,
    tombstoned, id range, shards, seconds). A no-op delta (nothing new,
    nothing updated, nothing tombstoned) returns without creating a
    generation.
    """
    if store.model_step is None:
        raise ValueError(
            "store is unstamped (no model_step); run the base 'embed' "
            "before appending — appends must share the base params")
    if attrs is not None and not store.attrs_enabled:
        raise ValueError(
            "append has --attrs but the store has no attribute table; "
            "initialize one first (cli append --init-attrs, or "
            "store.init_attrs())")
    upd_cfg = getattr(embedder.cfg, "updates", None)
    held = None
    if lease:
        held = AppendLease(
            store,
            ttl_s=getattr(upd_cfg, "writer_lease_s", 30.0),
            wait_s=getattr(upd_cfg, "lease_wait_s", 5.0)).acquire()
        # another writer may have committed while this one queued on the
        # lease: re-read the manifest + chain so cursor and generation
        # number reflect the store as the lease found it
        store.reload()
        store.reload_generations()
    try:
        return _append_leased(embedder, corpus, store, start, stop,
                              tombstone, update_ids, batch_size, log, held,
                              attrs)
    finally:
        if held is not None:
            held.release()


def _append_leased(embedder, corpus, store, start, stop, tombstone,
                   update_ids, batch_size, log, held,
                   attrs=None) -> Dict:
    cursor = store.next_page_id()
    start = cursor if start is None else int(start)
    if start < cursor:
        raise ValueError(
            f"append start={start} overlaps ids already assigned (append "
            f"cursor {cursor}, incl. quarantined ranges "
            f"{store.missing_id_ranges()}); appends must never re-issue "
            "an id — use update_ids to re-embed existing pages")
    stop = corpus.num_pages if stop is None else min(int(stop),
                                                     corpus.num_pages)
    update_ids = sorted({int(i) for i in update_ids})
    tombstone = sorted({int(i) for i in tombstone})
    for i in update_ids + tombstone:
        if i >= start:
            raise ValueError(
                f"page id {i} is not an existing page (append range starts "
                f"at {start}); only already-assigned ids can be updated or "
                "tombstoned")
    new_ids = list(range(start, stop))
    if not new_ids and not update_ids and not tombstone:
        return {"generation": store.generation, "appended": 0, "updated": 0,
                "tombstoned": 0, "shards": 0, "seconds": 0.0}
    t0 = time.perf_counter()
    # updated pages ride in the same generation AFTER the new range, so a
    # pure append and an append+update share the new-range shard bytes
    all_ids = np.array(new_ids + update_ids, np.int64)
    writer = store.begin_generation(tombstones=set(tombstone) | set(update_ids))
    shard_size = store.manifest["shard_size"]
    bs = batch_size or embedder.cfg.eval.embed_batch_size
    try:
        for s in range(0, all_ids.shape[0], shard_size):
            ids = all_ids[s: s + shard_size]
            vecs = embedder.embed_texts(
                [corpus.page_text(int(i)) for i in ids], tower="page",
                batch_size=bs)
            words = (np.full(ids.shape[0], int(attrs), np.uint32)
                     if attrs is not None else None)
            writer.write_shard(ids, vecs, attrs=words)
            if held is not None:
                # a long append must not outlive its own lease: renew per
                # shard; LeaseLost here aborts before a double-assigned
                # commit can land (docs/MAINTENANCE.md)
                held.renew()
        man = writer.commit()
    except BaseException:
        writer.abort()     # readers never see a half-written generation
        raise
    dt = time.perf_counter() - t0
    stats = {
        "generation": man["gen"],
        "appended": len(new_ids),
        "updated": len(update_ids),
        "tombstoned": len(tombstone) + len(update_ids),
        "id_start": man["id_start"],
        "id_end": man["id_end"],
        "shards": len(man["shards"]),
        "seconds": round(dt, 3),
        "append_docs_per_s": round(all_ids.shape[0] / max(dt, 1e-9), 2),
    }
    # registry instruments + lifecycle event (docs/OBSERVABILITY.md): the
    # update counters feed the same exposition as serving, and the event
    # channel records the generation transition itself
    reg = telemetry.default_registry()
    reg.counter("updates.docs_appended").inc(len(new_ids))
    reg.counter("updates.docs_updated").inc(len(update_ids))
    reg.counter("updates.docs_tombstoned").inc(stats["tombstoned"])
    reg.counter("updates.generations").inc()
    reg.gauge("updates.append_docs_per_s").set(stats["append_docs_per_s"])
    reg.event("generation_append", {
        "generation": man["gen"], "appended": len(new_ids),
        "updated": len(update_ids), "tombstoned": stats["tombstoned"]})
    if log is not None:
        rec = {"append_generation": man["gen"], **stats}
        fc = faults.counters()
        if fc:
            rec["fault_counters"] = fc
        log.write(rec)
    return stats
