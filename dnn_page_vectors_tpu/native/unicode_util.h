// Shared UTF-8 / whitespace helpers for the native host-side tokenizers.
// Semantics match Python: decode_cp mirrors str iteration over codepoints
// (invalid sequences decode as the single lead byte), is_space_cp is the
// exact str.split() whitespace set, so hosts with and without the built .so
// tokenize multilingual text identically (ADVICE r1).
#ifndef DPV_NATIVE_UNICODE_UTIL_H_
#define DPV_NATIVE_UNICODE_UTIL_H_

#include <cstdint>

namespace dpv {

// Number of bytes in the UTF-8 sequence starting at lead byte `c`.
inline int utf8_len(unsigned char c) {
  if (c < 0x80) return 1;
  if ((c >> 5) == 0x6) return 2;
  if ((c >> 4) == 0xE) return 3;
  if ((c >> 3) == 0x1E) return 4;
  return 1;  // invalid lead byte: treat as one unit (matches Python repair)
}

// Decode the codepoint at s (n bytes left); *len gets bytes consumed.
// Invalid sequences decode as the single lead byte (inputs come from
// Python str.encode("utf-8") and are always valid in practice).
inline uint32_t decode_cp(const char* s, int64_t n, int* len) {
  unsigned char c = static_cast<unsigned char>(s[0]);
  int l = utf8_len(c);
  if (l == 1 || l > n) { *len = 1; return c; }
  uint32_t cp = c & (0xFF >> (l + 1));
  for (int i = 1; i < l; ++i) {
    unsigned char cc = static_cast<unsigned char>(s[i]);
    if ((cc >> 6) != 0x2) { *len = 1; return c; }
    cp = (cp << 6) | (cc & 0x3F);
  }
  *len = l;
  return cp;
}

// Python str.split() whitespace = Unicode WSpace (str.isspace()).
inline bool is_space_cp(uint32_t cp) {
  switch (cp) {
    case 0x09: case 0x0A: case 0x0B: case 0x0C: case 0x0D: case 0x20:
    case 0x1C: case 0x1D: case 0x1E: case 0x1F:
    case 0x85: case 0xA0: case 0x1680:
    case 0x2000: case 0x2001: case 0x2002: case 0x2003: case 0x2004:
    case 0x2005: case 0x2006: case 0x2007: case 0x2008: case 0x2009:
    case 0x200A: case 0x2028: case 0x2029: case 0x202F: case 0x205F:
    case 0x3000:
      return true;
    default:
      return false;
  }
}

}  // namespace dpv

#endif  // DPV_NATIVE_UNICODE_UTIL_H_
