// Native hot path for subword ENCODING (data/subword.py). Vocab training
// stays in Python (one-off, seconds); encoding runs per page of the 1B-page
// corpus on the TPU-VM host (BASELINE.json:5) and the Python greedy matcher
// measures ~27k pages/s — enough to feed one chip's train step, 3.5x too
// slow for the bulk-embed sweep and 8x short of a v5e-8 host. This path
// measures ~164k pages/s (6x); ctypes drops the GIL during the call, so
// multi-threaded prefetch producers scale it across host cores.
//
// Semantics mirror SubwordTokenizer exactly (tests assert bit-equality):
//   * text split on UNICODE whitespace (unicode_util.h, Python str.split())
//   * per word: greedy longest-match over the piece vocab, matching
//     CODEPOINT substrings longest-first (word[i:j] in Python); on no
//     match, emit unk_id and advance one codepoint
//   * stop mid-word at max_tokens, exactly like SubwordTokenizer.encode
//
// Handle-based: dpv_bpe_new builds the piece hash map once per tokenizer
// (250,112 pieces for mT5 — far too costly per batch); encode calls share
// it. The handle owns a copy of the piece blob; map keys are string_views
// into that copy.

#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

#include "unicode_util.h"

namespace {

using dpv::decode_cp;
using dpv::is_space_cp;
using dpv::utf8_len;

struct BpeVocab {
  std::string blob;  // '\n'-joined pieces (pieces never contain whitespace)
  std::unordered_map<std::string_view, int32_t> pieces;
  int32_t max_piece_cps = 1;  // longest piece in codepoints, bounds the scan
};

inline int count_cps(std::string_view s) {
  int n = 0;
  size_t i = 0;
  while (i < s.size()) {
    i += static_cast<size_t>(utf8_len(static_cast<unsigned char>(s[i])));
    ++n;
  }
  return n;
}

// Greedy longest-match of word into out ids; returns tokens written
// (stops at cap). `offs` is a reusable scratch buffer.
inline int32_t encode_word(const BpeVocab& v, const char* w, int64_t wlen,
                           int32_t unk_id, int32_t cap, int32_t* out,
                           std::vector<int32_t>& offs) {
  offs.clear();
  int64_t i = 0;
  while (i < wlen) {
    offs.push_back(static_cast<int32_t>(i));
    i += utf8_len(static_cast<unsigned char>(w[i]));
  }
  offs.push_back(static_cast<int32_t>(wlen));
  const int32_t ncp = static_cast<int32_t>(offs.size()) - 1;
  int32_t pos = 0;
  int32_t ci = 0;
  while (ci < ncp && pos < cap) {
    int32_t hi = ci + v.max_piece_cps;
    if (hi > ncp) hi = ncp;
    int32_t id = unk_id;
    int32_t next = ci + 1;
    for (int32_t cj = hi; cj > ci; --cj) {
      std::string_view piece(w + offs[ci],
                             static_cast<size_t>(offs[cj] - offs[ci]));
      auto it = v.pieces.find(piece);
      if (it != v.pieces.end()) {
        id = it->second;
        next = cj;
        break;
      }
    }
    out[pos++] = id;
    ci = next;
  }
  return pos;
}

}  // namespace

extern "C" {

// pieces_blob: '\n'-joined piece strings (blob_len bytes, no trailing
// separator); ids[j] is the id of the j-th piece. Returns an opaque handle
// (never null; allocation failure aborts, as all small mallocs here would).
void* dpv_bpe_new(const char* pieces_blob, int64_t blob_len,
                  const int32_t* ids, int64_t n_pieces) {
  auto* v = new BpeVocab();
  v->blob.assign(pieces_blob, static_cast<size_t>(blob_len));
  v->pieces.reserve(static_cast<size_t>(n_pieces) * 2);
  size_t start = 0;
  int64_t j = 0;
  const std::string_view blob(v->blob);
  while (j < n_pieces && start <= blob.size()) {
    size_t end = blob.find('\n', start);
    if (end == std::string_view::npos) end = blob.size();
    std::string_view piece = blob.substr(start, end - start);
    v->pieces.emplace(piece, ids[j]);
    int cps = count_cps(piece);
    if (cps > v->max_piece_cps) v->max_piece_cps = cps;
    start = end + 1;
    ++j;
  }
  return v;
}

void dpv_bpe_free(void* h) { delete static_cast<BpeVocab*>(h); }

// texts: concatenated; lens[j] = byte length of text j. out holds
// n * max_tokens int32, pre-zeroed (0 = pad, as in subword.py).
void dpv_bpe_encode_batch(void* h, const char* texts, const int64_t* lens,
                          int64_t n, int32_t max_tokens, int32_t unk_id,
                          int32_t* out) {
  const auto& v = *static_cast<BpeVocab*>(h);
  std::vector<int32_t> offs;  // reused codepoint-offset scratch
  int64_t off = 0;
  for (int64_t t = 0; t < n; ++t) {
    const char* text = texts + off;
    const int64_t text_len = lens[t];
    int32_t* row = out + t * max_tokens;
    int32_t pos = 0;
    int64_t i = 0;
    while (i < text_len && pos < max_tokens) {
      int cl;
      while (i < text_len &&
             is_space_cp(decode_cp(text + i, text_len - i, &cl))) {
        i += cl;
      }
      if (i >= text_len) break;
      int64_t start = i;
      while (i < text_len &&
             !is_space_cp(decode_cp(text + i, text_len - i, &cl))) {
        i += cl;
      }
      pos += encode_word(v, text + start, i - start, unk_id,
                         max_tokens - pos, row + pos, offs);
    }
    off += text_len;
  }
}

// Fused jsonl-extract + encode (round 11): the bulk-embed producer's
// measured Python bound is the per-record field extract + UTF-8
// decode/re-encode round trip between the jsonl reader and this encoder
// (~40% of single-worker producer time at synth-corpus shapes, see
// docs/MFU.md "host pipeline"). This entry point takes the RAW jsonl
// lines and does extract + greedy encode in one C++ pass, so the value
// bytes go straight from the line buffer into token ids. Extraction
// mirrors data/jsonl.py _extract's punt rules EXACTLY — any backslash,
// a '{' past index 0 (nesting), missing or duplicate key, non-string
// value, or no closing quote sets status[t] = 0 and the caller falls
// back to json.loads for that record — so correctness never depends on
// the fast path, only speed does.
void dpv_bpe_encode_jsonl_batch(void* h, const char* lines,
                                const int64_t* lens, int64_t n,
                                const char* key, int64_t key_len,
                                int32_t max_tokens, int32_t unk_id,
                                int32_t* out, int8_t* status) {
  const auto& v = *static_cast<BpeVocab*>(h);
  std::vector<int32_t> offs;  // reused codepoint-offset scratch
  int64_t off = 0;
  const std::string_view k(key, static_cast<size_t>(key_len));
  for (int64_t t = 0; t < n; ++t) {
    const std::string_view line(lines + off, static_cast<size_t>(lens[t]));
    off += lens[t];
    status[t] = 0;
    if (line.find('\\') != std::string_view::npos) continue;
    if (line.find('{', 1) != std::string_view::npos) continue;
    size_t j = line.find(k);
    if (j == std::string_view::npos) continue;
    if (line.find(k, j + k.size()) != std::string_view::npos) continue;
    j += k.size();
    while (j < line.size() && (line[j] == ' ' || line[j] == '\t')) ++j;
    if (j >= line.size() || line[j] != '"') continue;
    ++j;
    const size_t e = line.find('"', j);
    if (e == std::string_view::npos) continue;
    status[t] = 1;
    const char* text = line.data() + j;
    const int64_t text_len = static_cast<int64_t>(e - j);
    int32_t* row = out + t * max_tokens;
    int32_t pos = 0;
    int64_t i = 0;
    while (i < text_len && pos < max_tokens) {
      int cl;
      while (i < text_len &&
             is_space_cp(decode_cp(text + i, text_len - i, &cl))) {
        i += cl;
      }
      if (i >= text_len) break;
      int64_t start = i;
      while (i < text_len &&
             !is_space_cp(decode_cp(text + i, text_len - i, &cl))) {
        i += cl;
      }
      pos += encode_word(v, text + start, i - start, unk_id,
                         max_tokens - pos, row + pos, offs);
    }
  }
}

}  // extern "C"
