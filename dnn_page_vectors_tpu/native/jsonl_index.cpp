// Jsonl line-offset indexer: the startup pass of data/jsonl.py.
//
// JsonlCorpus seeks records through an int64 offset index built by scanning
// the corpus once. In Python that scan iterates file lines in the
// interpreter (measured 3.6x slower; ~7 minutes before the first batch at
// 1B records, SURVEY.md §3 #4 scale). This is the same scan as a single
// buffered pass: record the byte offset of every line that contains a
// non-whitespace byte (exactly Python's `if line.strip()` — ASCII
// whitespace), including a final line with no trailing newline.
//
// C ABI (ctypes, no pybind11 in the image): dpv_jsonl_index allocates the
// offsets array and returns the count; the caller copies into numpy and
// frees via dpv_free_i64. Returns -1 when the file cannot be opened.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

constexpr int64_t kBuf = 1 << 20;  // 1 MiB read buffer

inline bool is_space(unsigned char c) {
  // Python bytes.strip() whitespace: space, \t, \n, \r, \v, \f.
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\v' ||
         c == '\f';
}

struct OffsetVec {
  int64_t* data = nullptr;
  int64_t size = 0;
  int64_t cap = 0;

  bool push(int64_t v) {
    if (size == cap) {
      int64_t next = cap ? cap * 2 : 4096;
      auto* p = static_cast<int64_t*>(
          std::realloc(data, static_cast<size_t>(next) * sizeof(int64_t)));
      if (!p) return false;
      data = p;
      cap = next;
    }
    data[size++] = v;
    return true;
  }
};

}  // namespace

extern "C" {

// Scans `path`, writes a malloc'd array of line-start offsets for every
// non-blank line into *out. Returns the line count, or -1 on I/O or
// allocation failure (*out is left null).
int64_t dpv_jsonl_index(const char* path, int64_t** out) {
  *out = nullptr;
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return -1;

  OffsetVec offsets;
  char* buf = static_cast<char*>(std::malloc(kBuf));
  if (!buf) {
    std::fclose(f);
    return -1;
  }

  int64_t pos = 0;          // absolute offset of buf[i]
  int64_t line_start = 0;   // absolute offset of the current line's first byte
  bool has_content = false; // current line has a non-whitespace byte
  bool ok = true;

  for (;;) {
    size_t n = std::fread(buf, 1, kBuf, f);
    if (n == 0) break;
    for (size_t i = 0; i < n; ++i) {
      unsigned char c = static_cast<unsigned char>(buf[i]);
      if (c == '\n') {
        if (has_content && !offsets.push(line_start)) { ok = false; break; }
        line_start = pos + static_cast<int64_t>(i) + 1;
        has_content = false;
      } else if (!is_space(c)) {
        has_content = true;
      }
    }
    if (!ok) break;
    pos += static_cast<int64_t>(n);
  }
  // final line without trailing newline
  if (ok && has_content) ok = offsets.push(line_start);

  bool read_error = std::ferror(f) != 0;
  std::fclose(f);
  std::free(buf);
  if (!ok || read_error) {
    std::free(offsets.data);
    return -1;
  }
  *out = offsets.data;  // may be null when the file has no non-blank lines
  return offsets.size;
}

void dpv_free_i64(int64_t* p) { std::free(p); }

}  // extern "C"
