// Native hot path for the char-trigram hashing tokenizer
// (dnn_page_vectors_tpu/data/trigram.py). The tokenizer runs on the TPU-VM
// host for every page of a 1B-page corpus (BASELINE.json:5), so the
// per-character Python loop is the bulk-embed job's host-side bottleneck;
// this C++ implementation is the equivalent of the reference's native data
// loader layer, exposed to Python via ctypes (no pybind11 in the image).
//
// Semantics mirror trigram.py exactly (tests assert bit-equality):
//   * words split on UNICODE whitespace — the same set as Python's
//     str.split() (ASCII ws, U+1C-1F, NEL, NBSP, U+1680, U+2000-200A,
//     LS/PS, U+202F, U+205F, U+3000) — so hosts with and without the
//     built .so tokenize multilingual text identically (ADVICE r1)
//   * per word: "#" + word + "#", trigrams over UTF-8 *codepoints*
//   * id = 1 + FNV1a64(utf8 bytes of the trigram) % buckets, 0 = pad
//   * at most `k` trigrams per word, at most `max_words` words; words are
//     never length-truncated (Python doesn't truncate either).

#include <cstdint>
#include <cstring>
#include <string>

#include "unicode_util.h"

namespace {

using dpv::decode_cp;
using dpv::is_space_cp;
using dpv::utf8_len;

constexpr uint64_t kFnvOffset = 0xCBF29CE484222325ULL;
constexpr uint64_t kFnvPrime = 0x100000001B3ULL;

inline uint64_t fnv1a(const char* data, int64_t n) {
  uint64_t h = kFnvOffset;
  for (int64_t i = 0; i < n; ++i) {
    h ^= static_cast<uint8_t>(data[i]);
    h *= kFnvPrime;
  }
  return h;
}

// Only the first k+2 codepoints of "#word#" can contribute trigrams, so
// offset bookkeeping is bounded even for unbounded word lengths. 512
// covers any practical k (trigram.py defaults k=8).
constexpr int kMaxWordCps = 512;

// Encode one word (already bracketed with '#') into out[0..k).
inline void encode_word(const char* w, int64_t wlen, int32_t buckets,
                        int32_t k, int32_t* out) {
  // codepoint start offsets
  int32_t offs[kMaxWordCps + 1];
  int ncp = 0;
  int64_t i = 0;
  while (i < wlen && ncp < kMaxWordCps) {
    offs[ncp++] = static_cast<int32_t>(i);
    i += utf8_len(static_cast<unsigned char>(w[i]));
  }
  offs[ncp] = static_cast<int32_t>(i < wlen ? i : wlen);
  if (ncp < 3) {  // word shorter than one trigram: hash the whole unit
    out[0] = 1 + static_cast<int32_t>(fnv1a(w, offs[ncp]) %
                                      static_cast<uint64_t>(buckets));
    return;
  }
  int n_tg = ncp - 2;
  if (n_tg > k) n_tg = k;
  for (int t = 0; t < n_tg; ++t) {
    const char* start = w + offs[t];
    int64_t len = offs[t + 3 <= ncp ? t + 3 : ncp] - offs[t];
    out[t] = 1 + static_cast<int32_t>(fnv1a(start, len) %
                                      static_cast<uint64_t>(buckets));
  }
}

}  // namespace

extern "C" {

// out must hold max_words * k int32, pre-zeroed by the caller.
void dpv_encode_trigrams(const char* text, int64_t text_len, int32_t buckets,
                         int32_t max_words, int32_t k, int32_t* out) {
  int64_t i = 0;
  int32_t wi = 0;
  std::string buf;  // reused "#word#" buffer; grows to the longest word
  while (i < text_len && wi < max_words) {
    int cl;
    while (i < text_len &&
           is_space_cp(decode_cp(text + i, text_len - i, &cl))) {
      i += cl;
    }
    if (i >= text_len) break;
    int64_t start = i;
    while (i < text_len &&
           !is_space_cp(decode_cp(text + i, text_len - i, &cl))) {
      i += cl;
    }
    buf.assign(1, '#');
    buf.append(text + start, static_cast<size_t>(i - start));
    buf.push_back('#');
    encode_word(buf.data(), static_cast<int64_t>(buf.size()), buckets, k,
                out + wi * k);
    ++wi;
  }
}

// Batch API: texts are concatenated; lens[j] is the byte length of text j.
// out holds n * max_words * k int32, pre-zeroed.
void dpv_encode_trigrams_batch(const char* texts, const int64_t* lens,
                               int64_t n, int32_t buckets, int32_t max_words,
                               int32_t k, int32_t* out) {
  int64_t off = 0;
  const int64_t stride = static_cast<int64_t>(max_words) * k;
  for (int64_t j = 0; j < n; ++j) {
    dpv_encode_trigrams(texts + off, lens[j], buckets, max_words, k,
                        out + j * stride);
    off += lens[j];
  }
}

}  // extern "C"
