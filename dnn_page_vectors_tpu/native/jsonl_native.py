"""numpy-facing wrapper over the ctypes C++ jsonl line-offset indexer."""
from __future__ import annotations

import ctypes

import numpy as np

from dnn_page_vectors_tpu.native import _lib


def index_offsets(path: str) -> np.ndarray:
    """Byte offsets of every non-blank line of `path` (int64), matching the
    pure-Python scan in data/jsonl.py bit for bit. Raises OSError when the
    file cannot be read."""
    out = ctypes.POINTER(ctypes.c_int64)()
    n = _lib.dpv_jsonl_index(path.encode("utf-8"), ctypes.byref(out))
    if n < 0:
        raise OSError(f"native jsonl index failed for {path}")
    try:
        if n == 0:
            return np.empty(0, dtype=np.int64)
        return np.ctypeslib.as_array(out, shape=(n,)).astype(np.int64,
                                                             copy=True)
    finally:
        if out:
            _lib.dpv_free_i64(out)
