"""numpy-facing wrappers over the ctypes C++ trigram tokenizer."""
from __future__ import annotations

import ctypes
from typing import Sequence

import numpy as np

from dnn_page_vectors_tpu.native import _lib


def encode(text: str, buckets: int, max_words: int, k: int) -> np.ndarray:
    out = np.zeros((max_words, k), dtype=np.int32)
    # surrogatepass matches the Python path: the C++ side decodes the
    # surrogate's 3-byte sequence as one codepoint and hashes its bytes
    data = text.encode("utf-8", "surrogatepass")
    _lib.dpv_encode_trigrams(
        data, len(data), buckets, max_words, k,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return out


def encode_batch(texts: Sequence[str], buckets: int, max_words: int,
                 k: int) -> np.ndarray:
    n = len(texts)
    out = np.zeros((n, max_words, k), dtype=np.int32)
    if n == 0:
        return out
    blobs = [t.encode("utf-8", "surrogatepass") for t in texts]
    lens = np.asarray([len(b) for b in blobs], dtype=np.int64)
    concat = b"".join(blobs)
    _lib.dpv_encode_trigrams_batch(
        concat, lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
        buckets, max_words, k,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return out
