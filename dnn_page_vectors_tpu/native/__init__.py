"""Native (C++) host-side runtime components, bound via ctypes.

The compute path is JAX/XLA (TPU); this package holds the host-side hot
loops that feed it — currently the char-trigram tokenizer, whose Python
inner loop would bottleneck the 1B-page bulk-embed job's host side
(BASELINE.json:5 keeps tokenization on the TPU VM host).

The shared library is built on first import with g++ (no pybind11 in the
image; plain C ABI + ctypes). Build failure is non-fatal: importers fall
back to the pure-Python implementation.
"""
from __future__ import annotations

import ctypes
import glob
import hashlib
import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRCS = [os.path.join(_DIR, "trigram_hash.cpp"),
         os.path.join(_DIR, "jsonl_index.cpp"),
         os.path.join(_DIR, "bpe_encode.cpp")]
_HDRS = [os.path.join(_DIR, "unicode_util.h")]


def _so_path() -> str:
    # The library name carries a digest of the sources, so a stale build —
    # however its mtime compares — can never be dlopen'd: a source change
    # changes the path. (A stale same-named .so missing a newer symbol
    # would otherwise fail the whole package import and take down the
    # already-working fast paths with it.)
    h = hashlib.sha1()
    for s in _SRCS + _HDRS:
        with open(s, "rb") as f:
            h.update(f.read())
    return os.path.join(_DIR, f"libdpv_native_{h.hexdigest()[:12]}.so")


def _build(so: str) -> None:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", so, *_SRCS]
    res = subprocess.run(cmd, capture_output=True, text=True)
    if res.returncode != 0:
        raise RuntimeError(f"native build failed: {res.stderr[-2000:]}")
    for old in glob.glob(os.path.join(_DIR, "libdpv_native*.so")):
        if old != so:
            try:
                os.remove(old)
            except OSError:
                pass


def _load() -> ctypes.CDLL:
    so = _so_path()
    if not os.path.exists(so):
        _build(so)
    lib = ctypes.CDLL(so)
    lib.dpv_encode_trigrams.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int32)]
    lib.dpv_encode_trigrams.restype = None
    lib.dpv_encode_trigrams_batch.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32)]
    lib.dpv_encode_trigrams_batch.restype = None
    lib.dpv_jsonl_index.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_int64))]
    lib.dpv_jsonl_index.restype = ctypes.c_int64
    lib.dpv_free_i64.argtypes = [ctypes.POINTER(ctypes.c_int64)]
    lib.dpv_free_i64.restype = None
    lib.dpv_bpe_new.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
    lib.dpv_bpe_new.restype = ctypes.c_void_p
    lib.dpv_bpe_free.argtypes = [ctypes.c_void_p]
    lib.dpv_bpe_free.restype = None
    lib.dpv_bpe_encode_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32)]
    lib.dpv_bpe_encode_batch.restype = None
    lib.dpv_bpe_encode_jsonl_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int8)]
    lib.dpv_bpe_encode_jsonl_batch.restype = None
    return lib


_lib = _load()  # raises on failure; data/trigram.py catches and falls back
