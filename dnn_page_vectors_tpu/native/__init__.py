"""Native (C++) host-side runtime components, bound via ctypes.

The compute path is JAX/XLA (TPU); this package holds the host-side hot
loops that feed it — currently the char-trigram tokenizer, whose Python
inner loop would bottleneck the 1B-page bulk-embed job's host side
(BASELINE.json:5 keeps tokenization on the TPU VM host).

The shared library is built on first import with g++ (no pybind11 in the
image; plain C ABI + ctypes). Build failure is non-fatal: importers fall
back to the pure-Python implementation.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "trigram_hash.cpp")
_SO = os.path.join(_DIR, "libdpv_native.so")


def _build() -> None:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", _SO, _SRC]
    res = subprocess.run(cmd, capture_output=True, text=True)
    if res.returncode != 0:
        raise RuntimeError(f"native build failed: {res.stderr[-2000:]}")


def _load() -> ctypes.CDLL:
    if (not os.path.exists(_SO)
            or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
        _build()
    lib = ctypes.CDLL(_SO)
    lib.dpv_encode_trigrams.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int32)]
    lib.dpv_encode_trigrams.restype = None
    lib.dpv_encode_trigrams_batch.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32)]
    lib.dpv_encode_trigrams_batch.restype = None
    return lib


_lib = _load()  # raises on failure; data/trigram.py catches and falls back
