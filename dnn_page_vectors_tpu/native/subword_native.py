"""numpy-facing wrapper over the ctypes C++ greedy BPE encoder.

One NativeBpeEncoder per piece vocab: the constructor ships the vocab to
C++ once (a 250k-piece hash map is far too costly to rebuild per batch);
encode_batch then runs the whole batch without touching the interpreter
(ctypes drops the GIL, so prefetch threads scale across host cores).
`shared_encoder` dedups by vocab content — the query and page tokenizers
share one vocab dict (loader.py) and must not build two identical maps.
"""
from __future__ import annotations

import collections
import ctypes
import hashlib
from typing import Dict, Sequence

import numpy as np

from dnn_page_vectors_tpu.native import _lib


class NativeBpeEncoder:
    def __init__(self, vocab: Dict[str, int]):
        blob, ids = _vocab_blob(vocab)
        self._init(blob, ids)

    def _init(self, blob: bytes, ids: np.ndarray) -> None:
        self._blob = blob          # keep alive for the c_char_p view
        self._h = _lib.dpv_bpe_new(
            blob, len(blob),
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(ids))

    def encode_batch(self, texts: Sequence, max_tokens: int,
                     unk_id: int) -> np.ndarray:
        n = len(texts)
        out = np.zeros((n, max_tokens), dtype=np.int32)
        if n == 0:
            return out
        # surrogatepass: a lone surrogate (e.g. a "\ud800" JSON escape)
        # must encode rather than raise; C++ decodes it back to one
        # codepoint, finds no piece, and emits UNK — exactly the Python
        # path's behavior for that character. Items may already BE utf-8
        # bytes (the jsonl raw-field fast path) — those skip the str
        # round trip entirely.
        blobs = [t if isinstance(t, bytes)
                 else t.encode("utf-8", "surrogatepass") for t in texts]
        lens = np.asarray([len(b) for b in blobs], dtype=np.int64)
        concat = b"".join(blobs)
        _lib.dpv_bpe_encode_batch(
            self._h, concat, lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n, max_tokens, unk_id,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return out

    def encode_jsonl_batch(self, lines: Sequence[bytes], key: bytes,
                           max_tokens: int, unk_id: int
                           ) -> tuple[np.ndarray, np.ndarray]:
        """Fused jsonl-extract + encode (bpe_encode.cpp): `lines` are raw
        jsonl line buffers; C++ pulls `key`'s string value (same punt
        rules as data/jsonl.py _extract) and greedy-encodes it in one
        pass. Returns (ids [n, max_tokens], status [n] int8) — status 0
        rows were punted (escapes / nesting / duplicate or missing key)
        and must be filled by the caller's json.loads fallback."""
        n = len(lines)
        out = np.zeros((n, max_tokens), dtype=np.int32)
        status = np.zeros(n, dtype=np.int8)
        if n == 0:
            return out, status
        lens = np.asarray([len(b) for b in lines], dtype=np.int64)
        concat = b"".join(lines)
        _lib.dpv_bpe_encode_jsonl_batch(
            self._h, concat,
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n, key, len(key), max_tokens, unk_id,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            status.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)))
        return out, status

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            _lib.dpv_bpe_free(h)
            self._h = None


def _vocab_blob(vocab: Dict[str, int]) -> tuple[bytes, np.ndarray]:
    pieces = list(vocab.keys())
    # pieces derive from str.split() words, so they can never contain the
    # '\n' separator (or any whitespace)
    blob = "\n".join(pieces).encode("utf-8")
    ids = np.asarray([vocab[p] for p in pieces], dtype=np.int32)
    return blob, ids


_CACHE: "collections.OrderedDict[bytes, NativeBpeEncoder]" = \
    collections.OrderedDict()
_CACHE_MAX = 4


def shared_encoder(vocab: Dict[str, int]) -> NativeBpeEncoder:
    """Content-keyed encoder cache (hashing the blob is milliseconds; the
    250k-piece map build it skips is not)."""
    blob, ids = _vocab_blob(vocab)
    key = hashlib.sha1(blob + ids.tobytes()).digest()
    enc = _CACHE.get(key)
    if enc is None:
        enc = NativeBpeEncoder.__new__(NativeBpeEncoder)
        enc._init(blob, ids)
        _CACHE[key] = enc
        while len(_CACHE) > _CACHE_MAX:
            _CACHE.popitem(last=False)
    else:
        _CACHE.move_to_end(key)
    return enc
