"""Hot ops: chunked on-device top-k scoring shared by eval + ANN mining
(SURVEY.md §3 #21-22)."""
from dnn_page_vectors_tpu.ops.topk import chunked_topk

__all__ = ["chunked_topk"]
