"""Pallas TPU flash attention for the transformer towers (encoder-only,
bidirectional, padding-masked, optional additive bias for T5 relative
positions).

Why a kernel: naive attention materialises [B, H, L, S] scores in HBM; for
long pages that array dominates HBM traffic. This kernel streams KV blocks
through VMEM with an online softmax (running max m, denominator l, f32
accumulator), so HBM sees only Q, K, V and the output — the standard
flash-attention memory shape, written for the MXU (score and value matmuls
with f32 accumulation) per /opt/skills/guides/pallas_guide.md.

Autodiff: the backward pass recomputes attention with the plain-XLA
reference implementation via jax.vjp (custom_vjp below). Training pays one
extra fused forward; the 1B-page bulk-embed job (the headline workload,
BASELINE.json:5) is forward-only and gets the full benefit.

On CPU (tests, fake meshes) the kernel runs in interpret mode automatically.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def reference_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        kv_mask: jnp.ndarray,
                        bias: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Plain-XLA attention; the kernel's oracle and its backward path.

    q: [B, H, L, Dh]; k, v: [B, H, S, Dh]; kv_mask: [B, S] (True = real
    token); bias: optional [H, L, S] additive (T5 relative positions).
    Returns [B, H, L, Dh] float32.
    """
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhld,bhsd->bhls", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias[None].astype(jnp.float32)
    s = jnp.where(kv_mask[:, None, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhls,bhsd->bhld", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32)


def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, bias_ref, out_ref, *,
                  block_kv: int):
    # Block shapes (leading grid dims are 1):
    # q_ref: [1,1,BQ,Dh]; k_ref/v_ref: [1,1,S,Dh]; mask_ref: [1,1,S] int32;
    # bias_ref: [1,BQ,S] f32 or None; out_ref: [1,1,BQ,Dh] f32.
    bq, dh = q_ref.shape[2], q_ref.shape[3]
    s_len = k_ref.shape[2]
    scale = 1.0 / np.sqrt(dh)
    n_blocks = s_len // block_kv

    q = q_ref[0, 0].astype(jnp.float32) * scale
    k_all = k_ref[0, 0]
    v_all = v_ref[0, 0]
    mask_all = mask_ref[0, 0]                                # [S] int32
    bias_all = None if bias_ref is None else bias_ref[0]

    def body(i, carry):
        acc, m_i, l_i = carry
        start = i * block_kv
        k_blk = jax.lax.dynamic_slice_in_dim(
            k_all, start, block_kv, axis=0).astype(jnp.float32)  # [BKV, Dh]
        v_blk = jax.lax.dynamic_slice_in_dim(
            v_all, start, block_kv, axis=0).astype(jnp.float32)
        s = jax.lax.dot_general(                             # [BQ, BKV]
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if bias_all is not None:
            s = s + jax.lax.dynamic_slice_in_dim(bias_all, start, block_kv,
                                                 axis=1)
        mask = jax.lax.dynamic_slice_in_dim(mask_all, start, block_kv,
                                            axis=0)          # [BKV] int32
        s = jnp.where(mask[None, :] > 0, s, _NEG_INF)

        m_new = jnp.maximum(m_i, s.max(axis=1))              # [BQ]
        p = jnp.exp(s - m_new[:, None])                      # [BQ, BKV]
        alpha = jnp.exp(m_i - m_new)                         # [BQ]
        l_new = alpha * l_i + p.sum(axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    acc0 = jnp.zeros((bq, dh), jnp.float32)
    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m_i, l_i = jax.lax.fori_loop(0, n_blocks, body, (acc0, m0, l0))
    # fully-masked rows (padding queries): l == 0 -> emit zeros, not NaN
    out_ref[0, 0] = acc / jnp.maximum(l_i, 1e-30)[:, None]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    kv_mask: jnp.ndarray, bias: Optional[jnp.ndarray] = None,
                    block_q: int = 128, block_kv: int = 128,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    return _flash_forward(q, k, v, kv_mask, bias, block_q, block_kv,
                          interpret)


def _flash_forward(q, k, v, kv_mask, bias, block_q, block_kv, interpret):
    B, H, L, Dh = q.shape
    S = k.shape[2]
    if interpret is None:  # compiled on TPU, interpreted elsewhere
        interpret = jax.default_backend() != "tpu"
    block_q = min(block_q, L)
    block_kv = min(block_kv, S)
    # pad L and S up to block multiples; padded KV is masked out, padded Q
    # rows are sliced off after
    pad_l, pad_s = (-L) % block_q, (-S) % block_kv
    if pad_l:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_l), (0, 0)))
    if pad_s:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_s), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_s), (0, 0)))
        kv_mask = jnp.pad(kv_mask, ((0, 0), (0, pad_s)))
    if bias is not None and (pad_l or pad_s):
        bias = jnp.pad(bias, ((0, 0), (0, pad_l), (0, pad_s)))
    Lp, Sp = L + pad_l, S + pad_s

    mask_i32 = kv_mask.astype(jnp.int32)[:, None, :]         # [B, 1, S]

    grid = (B, H, Lp // block_q)
    in_specs = [
        pl.BlockSpec((1, 1, block_q, Dh), lambda b, h, i: (b, h, i, 0)),
        pl.BlockSpec((1, 1, Sp, Dh), lambda b, h, i: (b, h, 0, 0)),
        pl.BlockSpec((1, 1, Sp, Dh), lambda b, h, i: (b, h, 0, 0)),
        pl.BlockSpec((1, 1, Sp), lambda b, h, i: (b, 0, 0)),
    ]
    args = [q, k, v, mask_i32]
    if bias is not None:
        in_specs.append(
            pl.BlockSpec((1, block_q, Sp), lambda b, h, i: (h, i, 0)))
        args.append(bias.astype(jnp.float32))

    def kernel(*refs):
        if bias is not None:
            q_ref, k_ref, v_ref, m_ref, b_ref, o_ref = refs
        else:
            q_ref, k_ref, v_ref, m_ref, o_ref = refs
            b_ref = None
        _flash_kernel(q_ref, k_ref, v_ref, m_ref, b_ref, o_ref,
                      block_kv=block_kv)

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, block_q, Dh),
                               lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, Lp, Dh), jnp.float32),
        interpret=interpret,
    )(*args)
    return out[:, :, :L]


def _fwd(q, k, v, kv_mask, bias, block_q, block_kv, interpret):
    out = _flash_forward(q, k, v, kv_mask, bias, block_q, block_kv,
                         interpret)
    return out, (q, k, v, kv_mask, bias)


def _bwd(block_q, block_kv, interpret, res, g):
    q, k, v, kv_mask, bias = res
    # exact gradients by differentiating the reference implementation
    # (one recomputed forward; see module docstring)
    if bias is None:
        _, vjp = jax.vjp(
            lambda q_, k_, v_: reference_attention(q_, k_, v_, kv_mask),
            q, k, v)
        dq, dk, dv = vjp(g)
        return dq, dk, dv, None, None
    _, vjp = jax.vjp(
        lambda q_, k_, v_, b_: reference_attention(q_, k_, v_, kv_mask, b_),
        q, k, v, bias)
    dq, dk, dv, db = vjp(g)
    return dq, dk, dv, None, db


flash_attention.defvjp(_fwd, _bwd)
