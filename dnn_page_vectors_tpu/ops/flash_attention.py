"""Pallas TPU flash attention for the transformer towers (encoder-only,
bidirectional, padding-masked, optional additive bias for T5 relative
positions).

Why a kernel: naive attention materialises [B, H, L, S] scores in HBM; for
long pages that array dominates HBM traffic. Here each grid program scores
one Q block against its FULL KV slice inside VMEM — the [block_q, S] score
tile never touches HBM, so HBM sees only Q, K, V and the output: the flash-
attention memory shape. Unlike GPU flash there is no online-softmax KV loop:
a [128, S] f32 tile fits VMEM to S ≈ 8k (this jax's Mosaic also lacks
in-kernel dynamic_slice, which a KV loop needs), and the exact one-shot
softmax is both simpler and faster at that scale. Beyond ~8k tokens the
sequence-parallel path (parallel/ring_attention.py) shards S over the mesh
'seq' axis, keeping each per-chip slice inside this kernel's bound. Matmuls
run on the MXU with f32 accumulation per /opt/skills/guides/pallas_guide.md.

Autodiff (VERDICT r1 #7): the backward is ALSO Pallas — kernels that
recompute attention probabilities per block from the saved log-sum-exp
(dq gridded over Q blocks, dk/dv gridded over KV blocks), so long-page
TRAINING keeps the flash memory shape too; no [B, H, L, S] tensor exists
in forward or backward. With a T5 relative-position `bias`, a third
kernel accumulates dbias[h,l,s] = sum_b ds[b,h,l,s] across a
batch-innermost sequential grid (VERDICT r3 Missing #3), so the biased
path also never materialises [B, H, L, S] — dbias itself is [H, L, S],
the same footprint as the bias input.

Sequence packing (train.pack_pages): the kernels optionally take packed-page
segment ids `seg` [B, L] — the q side rides lane-broadcast (the lse layout
trick), the kv side as a mask-like row, and each score tile is masked to
within-segment pairs by one broadcast compare in VMEM. The packed path
keeps the flash memory shape in forward and backward: no [B, L, S] segment
mask ever exists in HBM.

On CPU (tests, fake meshes) the kernels run in interpret mode automatically.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_NEG_INF = -1e30
# Row vectors (lse, delta) are stored [B, H, L, _LSE_LANES] with the value
# broadcast across the trailing lane dim: Mosaic requires the last two block
# dims to be (sublane ÷ 8, lane ÷ 128) or equal to the array dims, so a
# [.., block_q] row-vector block is unlowerable ([.., block_q, 8] is fine —
# 8 lanes is the smallest legal trailing dim, kept small to bound HBM).
_LSE_LANES = 8


def reference_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        kv_mask: jnp.ndarray,
                        bias: Optional[jnp.ndarray] = None,
                        seg: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Plain-XLA attention; the kernel's oracle (and the bias-path backward).

    q: [B, H, L, Dh]; k, v: [B, H, S, Dh]; kv_mask: [B, S] (True = real
    token); bias: optional [H, L, S] additive (T5 relative positions);
    seg: optional [B, L(==S)] packed-page segment ids (0 = pad) — scores
    are additionally masked to within-segment pairs (sequence packing).
    Returns [B, H, L, Dh] float32.
    """
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhld,bhsd->bhls", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias[None].astype(jnp.float32)
    allowed = kv_mask[:, None, None, :]
    if seg is not None:
        allowed = allowed & ((seg[:, :, None] == seg[:, None, :])
                             & (seg > 0)[:, None, :])[:, None]
    s = jnp.where(allowed, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhls,bhsd->bhld", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32)


def _tile_mask(mask, sq_ref, sk_ref):
    """[rows, S] bool tile mask from the kv-pad row `mask` [1, S] plus,
    when segment refs are given (sequence packing), the within-segment
    restriction. sq_ref holds lane-broadcast q-side segment ids
    ([1, rows, LANE] view -> [rows, 1] column), sk_ref the kv-side row
    ([1, 1, S] view -> [1, S]); their broadcast equality is the
    block-diagonal packed-page mask, computed per score tile in VMEM —
    no [B, L, S] mask array ever exists in HBM."""
    ok = mask > 0                                            # [1, S]
    if sq_ref is None:
        return ok
    qs = sq_ref[0][:, 0:1]                                   # [rows, 1]
    ks = sk_ref[0]                                           # [1, S]
    return (qs == ks) & (ks > 0) & ok


def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, bias_ref, sq_ref, sk_ref,
                  out_ref, lse_ref):
    # Block shapes (leading grid dims are 1):
    # q_ref: [1,1,BQ,Dh]; k_ref/v_ref: [1,1,S,Dh]; mask_ref: [1,1,S] int32;
    # bias_ref: [1,BQ,S] f32 or None; sq_ref: [1,BQ,LANE] int32 or None
    # (lane-broadcast q-side segment ids, same layout trick as lse_ref);
    # sk_ref: [1,1,S] int32 or None; out_ref: [1,1,BQ,Dh] f32;
    # lse_ref: [1,1,BQ,LANE] f32 (log-sum-exp, lane-broadcast — Mosaic's
    # tiling rule forbids row-vector [..,BQ] blocks, see _LSE_LANES).
    # All row statistics are kept 2D ([BQ,1], not [BQ]): Mosaic lowers 2D
    # vector ops; 1D shapes trip layout inference on real TPUs.
    bq = q_ref.shape[2]
    dh = q_ref.shape[3]
    scale = 1.0 / np.sqrt(dh)

    q = q_ref[0, 0].astype(jnp.float32) * scale
    k = k_ref[0, 0].astype(jnp.float32)                      # [S, Dh]
    v = v_ref[0, 0].astype(jnp.float32)
    mask = mask_ref[0]                                       # [1, S] int32

    s = jax.lax.dot_general(                                 # [BQ, S]
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if bias_ref is not None:
        s = s + bias_ref[0]
    s = jnp.where(_tile_mask(mask, sq_ref, sk_ref), s, _NEG_INF)

    m = s.max(axis=1, keepdims=True)                         # [BQ,1]
    p = jnp.exp(s - m)                                       # [BQ, S]
    l = p.sum(axis=1, keepdims=True)                         # [BQ,1]
    acc = jax.lax.dot_general(                               # [BQ, Dh]
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    # Fully-masked rows (all scores _NEG_INF): m == _NEG_INF, s - m == 0,
    # p == 1 everywhere, l == S — the output is mean(V), matching the
    # reference's uniform softmax over _NEG_INF scores (downstream pooling
    # masks those rows out; do NOT rely on zeros here). The epsilon only
    # guards l == 0, which cannot occur for S >= 1.
    out_ref[0, 0] = acc / jnp.maximum(l, 1e-30)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))                 # [BQ,1]
    lse_ref[0, 0] = jnp.broadcast_to(lse, (bq, lse_ref.shape[3]))


def _block_ds(q_ref, k_ref, v_ref, mask_ref, bias_ref, g_ref, lse_ref,
              delta_ref, sq_ref=None, sk_ref=None):
    """Recompute ds = p * (dp - delta) for one Q block against the full KV
    slice from the saved lse (no [B,H,L,S] in HBM). Shared by the dq and
    dbias kernels; returns (ds [BQ,S], k [S,Dh]) in float32.
    lse_ref/delta_ref: [1,1,BQ,LANE] lane-broadcast (see _LSE_LANES);
    sq_ref/sk_ref: optional segment ids (packing), same masking as fwd."""
    dh = q_ref.shape[3]
    scale = 1.0 / np.sqrt(dh)

    q = q_ref[0, 0].astype(jnp.float32)
    g = g_ref[0, 0].astype(jnp.float32)                       # [BQ, Dh]
    lse = lse_ref[0, 0][:, 0:1]                               # [BQ,1]
    delta = delta_ref[0, 0][:, 0:1]                           # [BQ,1]
    k = k_ref[0, 0].astype(jnp.float32)                       # [S, Dh]
    v = v_ref[0, 0].astype(jnp.float32)
    mask = mask_ref[0]                                        # [1, S]

    s = scale * jax.lax.dot_general(                          # [BQ, S]
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if bias_ref is not None:
        s = s + bias_ref[0]
    s = jnp.where(_tile_mask(mask, sq_ref, sk_ref), s, _NEG_INF)
    p = jnp.exp(s - lse)                                      # [BQ, S]
    dp = jax.lax.dot_general(                                 # g @ v^T
        g, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    return p * (dp - delta), k                                # ds, k


def _flash_dq_kernel(q_ref, k_ref, v_ref, mask_ref, sq_ref, sk_ref, g_ref,
                     lse_ref, delta_ref, dq_ref):
    # Unbiased path. Grid (B, H, Lp/BQ): one Q block vs the full KV slice.
    dh = q_ref.shape[3]
    scale = 1.0 / np.sqrt(dh)
    ds, k = _block_ds(q_ref, k_ref, v_ref, mask_ref, None, g_ref,
                      lse_ref, delta_ref, sq_ref, sk_ref)
    dq_ref[0, 0] = scale * jax.lax.dot_general(               # ds @ k
        ds, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _flash_dq_dbias_kernel(q_ref, k_ref, v_ref, mask_ref, bias_ref, sq_ref,
                           sk_ref, g_ref, lse_ref, delta_ref, dq_ref,
                           db_ref):
    # Biased path: ONE pass produces both dq and dbias from the same ds.
    # Grid (H, Lp/BQ, B) with the BATCH dim INNERMOST: dq's index map uses
    # all three dims, while db's drops b — consecutive grid steps revisit
    # the same [1, BQ, Sp] db block, and TPU grids run sequentially, so
    # `db += ds` accumulates the cross-batch reduction dbias[h,l,s] =
    # sum_b ds[b,h,l,s] without any [B,H,L,S] tensor — the piece the old
    # reference-VJP fallback re-materialised (VERDICT r3 Missing #3).
    dh = q_ref.shape[3]
    scale = 1.0 / np.sqrt(dh)
    ds, k = _block_ds(q_ref, k_ref, v_ref, mask_ref, bias_ref, g_ref,
                      lse_ref, delta_ref, sq_ref, sk_ref)
    dq_ref[0, 0] = scale * jax.lax.dot_general(               # ds @ k
        ds, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    b = pl.program_id(2)

    @pl.when(b == 0)
    def _init():
        db_ref[0] = ds

    @pl.when(b > 0)
    def _acc():
        db_ref[0] += ds


def _flash_dkv_kernel(q_ref, k_ref, v_ref, mask_ref, bias_ref, sq_ref,
                      sk_ref, g_ref, lse_ref, delta_ref, dk_ref, dv_ref):
    # Grid (B, H, Sp/BKV). Per program: one KV block vs the full Q slice.
    # sq_ref here is the FULL q-side segment column ([1, Lp, LANE] view),
    # sk_ref the KV block's segment row ([1, 1, BKV] view).
    dh = k_ref.shape[3]
    scale = 1.0 / np.sqrt(dh)

    k_blk = k_ref[0, 0].astype(jnp.float32)                   # [BKV, Dh]
    v_blk = v_ref[0, 0].astype(jnp.float32)
    mask = mask_ref[0]                                        # [1, BKV]
    q = q_ref[0, 0].astype(jnp.float32)                       # [L, Dh]
    g = g_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0][:, 0:1]                               # [L,1]
    delta = delta_ref[0, 0][:, 0:1]

    s = scale * jax.lax.dot_general(                          # [L, BKV]
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if bias_ref is not None:
        s = s + bias_ref[0]
    s = jnp.where(_tile_mask(mask, sq_ref, sk_ref), s, _NEG_INF)
    p = jnp.exp(s - lse)                                      # [L, BKV]
    dv_ref[0, 0] = jax.lax.dot_general(                       # p^T @ g
        p, g, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(                                 # g @ v^T
        g, v_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta)                                     # [L, BKV]
    dk_ref[0, 0] = scale * jax.lax.dot_general(               # ds^T @ q
        ds, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _flash_attention(q, k, v, kv_mask, bias, seg, block_q, block_kv,
                     interpret):
    out, _ = _flash_forward(q, k, v, kv_mask, bias, seg, block_q, block_kv,
                            interpret)
    return out


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    kv_mask: jnp.ndarray, bias: Optional[jnp.ndarray] = None,
                    block_q: int = 128, block_kv: int = 128,
                    interpret: Optional[bool] = None,
                    seg: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Flash attention with optional T5 bias and optional packed-page
    segment ids `seg` [B, L] (sequence packing, train.pack_pages): scores
    are restricted to within-segment pairs, with the pairwise segment
    comparison computed per score tile inside the kernel — the packed
    path keeps the flash memory shape (no [B, L, S] mask in HBM) in
    forward AND backward."""
    return _flash_attention(q, k, v, kv_mask, bias, seg, block_q, block_kv,
                            interpret)


def _pad_inputs(q, k, v, kv_mask, bias, block_q, block_kv):
    B, H, L, Dh = q.shape
    S = k.shape[2]
    block_q = min(block_q, L)
    block_kv = min(block_kv, S)
    pad_l, pad_s = (-L) % block_q, (-S) % block_kv
    if pad_l:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_l), (0, 0)))
    if pad_s:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_s), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_s), (0, 0)))
        kv_mask = jnp.pad(kv_mask, ((0, 0), (0, pad_s)))
    if bias is not None and (pad_l or pad_s):
        bias = jnp.pad(bias, ((0, 0), (0, pad_l), (0, pad_s)))
    return q, k, v, kv_mask, bias, block_q, block_kv, L, S


def _seg_operands(seg, Lp, Sp):
    """Kernel-ready segment operands from [B, L(==S)] ids: the q side is
    lane-broadcast to [B, Lp, _LSE_LANES] (the same Mosaic row-vector
    layout trick as lse), the kv side rides as a [B, 1, Sp] row like the
    pad mask. Pad ids are 0, which can never equal a real (>=1) segment,
    so padded tails mask themselves."""
    seg = seg.astype(jnp.int32)
    L = seg.shape[1]
    seg_q = seg if Lp == L else jnp.pad(seg, ((0, 0), (0, Lp - L)))
    seg_kv = seg if Sp == L else jnp.pad(seg, ((0, 0), (0, Sp - L)))
    seg_q = jnp.broadcast_to(seg_q[..., None],
                             seg_q.shape + (_LSE_LANES,))
    return seg_q, seg_kv[:, None, :]


# Single-device KV bound: each grid program holds the full [Sp, Dh] K/V
# slice plus a [block_q, Sp] f32 score tile in VMEM (~16 MB on v5e). Beyond
# this, Mosaic fails with an opaque allocation error, so raise a directed
# one instead (ADVICE r3). The BIASED path additionally holds [block_q, Sp]
# bias and (in backward) the revisited dbias output block — roughly 3x the
# per-program tile budget — so its bound is halved. The over-bound path is
# ring-attention sequence parallelism (parallel/ring_attention.py), which
# keeps each per-chip KV slice inside these bounds.
_MAX_KV_TOKENS = 8_192
_MAX_KV_TOKENS_BIASED = 4_096


def _flash_forward(q, k, v, kv_mask, bias, seg, block_q, block_kv,
                   interpret):
    """Returns (out [B,H,L,Dh] f32, lse [B,H,L] f32)."""
    if interpret is None:  # compiled on TPU, interpreted elsewhere
        interpret = jax.default_backend() != "tpu"
    (q, k, v, kv_mask, bias, block_q, block_kv, L, S) = _pad_inputs(
        q, k, v, kv_mask, bias, block_q, block_kv)
    B, H, Lp, Dh = q.shape
    Sp = k.shape[2]
    limit = _MAX_KV_TOKENS if bias is None else _MAX_KV_TOKENS_BIASED
    if not interpret and Sp > limit:
        raise ValueError(
            f"flash_attention: KV length {Sp} exceeds the single-device "
            f"VMEM bound (~{limit} tokens{' with bias' if bias is not None else ''}): "
            "the [block_q, S] score tile + full KV slice must fit VMEM. "
            "Shard the sequence over the mesh 'seq' axis instead "
            "(model.attention='ring', parallel/ring_attention.py), which "
            "keeps each per-chip KV slice inside this kernel's bound.")

    mask_i32 = kv_mask.astype(jnp.int32)[:, None, :]         # [B, 1, S]

    grid = (B, H, Lp // block_q)
    in_specs = [
        pl.BlockSpec((1, 1, block_q, Dh), lambda b, h, i: (b, h, i, 0)),
        pl.BlockSpec((1, 1, Sp, Dh), lambda b, h, i: (b, h, 0, 0)),
        pl.BlockSpec((1, 1, Sp, Dh), lambda b, h, i: (b, h, 0, 0)),
        pl.BlockSpec((1, 1, Sp), lambda b, h, i: (b, 0, 0)),
    ]
    args = [q, k, v, mask_i32]
    if bias is not None:
        in_specs.append(
            pl.BlockSpec((1, block_q, Sp), lambda b, h, i: (h, i, 0)))
        args.append(bias.astype(jnp.float32))
    if seg is not None:
        seg_q, seg_kv = _seg_operands(seg, Lp, Sp)
        in_specs.append(pl.BlockSpec((1, block_q, _LSE_LANES),
                                     lambda b, h, i: (b, i, 0)))
        in_specs.append(pl.BlockSpec((1, 1, Sp), lambda b, h, i: (b, 0, 0)))
        args.extend([seg_q, seg_kv])

    def kernel(*refs):
        refs = list(refs)
        q_ref, k_ref, v_ref, m_ref = refs[:4]
        i = 4
        b_ref = None
        if bias is not None:
            b_ref = refs[i]
            i += 1
        sq_ref = sk_ref = None
        if seg is not None:
            sq_ref, sk_ref = refs[i], refs[i + 1]
            i += 2
        o_ref, l_ref = refs[i], refs[i + 1]
        _flash_kernel(q_ref, k_ref, v_ref, m_ref, b_ref, sq_ref, sk_ref,
                      o_ref, l_ref)

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, Dh), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, _LSE_LANES),
                         lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Lp, Dh), jnp.float32),
            jax.ShapeDtypeStruct((B, H, Lp, _LSE_LANES), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return out[:, :, :L], lse[:, :, :L, 0]


def _flash_backward(q, k, v, kv_mask, bias, seg, g, out, lse, block_q,
                    block_kv, interpret):
    """Pallas dq/dk/dv (+ dbias when `bias` is given) with per-block
    recompute from the saved lse. Returns (dq, dk, dv, db-or-None)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    in_dtypes = (q.dtype, k.dtype, v.dtype)
    bias_dtype = None if bias is None else bias.dtype
    (q, k, v, kv_mask, bias, block_q, block_kv, L, S) = _pad_inputs(
        q, k, v, kv_mask, bias, block_q, block_kv)
    B, H, Lp, Dh = q.shape
    Sp = k.shape[2]
    pad_l = Lp - L

    # delta_i = sum_d dO_i * O_i (the softmax-jacobian row term)
    delta = jnp.einsum("bhld,bhld->bhl", g.astype(jnp.float32), out)
    if pad_l:
        g = jnp.pad(g, ((0, 0), (0, 0), (0, pad_l), (0, 0)))
        lse = jnp.pad(lse, ((0, 0), (0, 0), (0, pad_l)))
        delta = jnp.pad(delta, ((0, 0), (0, 0), (0, pad_l)))
    mask_i32 = kv_mask.astype(jnp.int32)[:, None, :]
    # lane-broadcast the row vectors into Mosaic-lowerable layout
    lse = jnp.broadcast_to(lse[..., None], lse.shape + (_LSE_LANES,))
    delta = jnp.broadcast_to(delta[..., None], delta.shape + (_LSE_LANES,))
    bias_f = None if bias is None else bias.astype(jnp.float32)
    seg_q = seg_kv = None
    if seg is not None:
        seg_q, seg_kv = _seg_operands(seg, Lp, Sp)

    db = None
    if bias is None:
        qspec = pl.BlockSpec((1, 1, block_q, Dh),
                             lambda b, h, i: (b, h, i, 0))
        kfull = pl.BlockSpec((1, 1, Sp, Dh), lambda b, h, i: (b, h, 0, 0))
        rowspec = pl.BlockSpec((1, 1, block_q, _LSE_LANES),
                               lambda b, h, i: (b, h, i, 0))
        in_specs = [qspec, kfull, kfull,
                    pl.BlockSpec((1, 1, Sp), lambda b, h, i: (b, 0, 0))]
        args = [q, k, v, mask_i32]
        if seg is not None:
            in_specs.append(pl.BlockSpec((1, block_q, _LSE_LANES),
                                         lambda b, h, i: (b, i, 0)))
            in_specs.append(pl.BlockSpec((1, 1, Sp),
                                         lambda b, h, i: (b, 0, 0)))
            args.extend([seg_q, seg_kv])

        def dq_kernel(*refs):
            refs = list(refs)
            sq_ref = sk_ref = None
            i = 4
            if seg is not None:
                sq_ref, sk_ref = refs[4], refs[5]
                i = 6
            _flash_dq_kernel(refs[0], refs[1], refs[2], refs[3], sq_ref,
                             sk_ref, refs[i], refs[i + 1], refs[i + 2],
                             refs[i + 3])

        dq = pl.pallas_call(
            dq_kernel,
            grid=(B, H, Lp // block_q),
            in_specs=in_specs + [qspec, rowspec, rowspec],
            out_specs=qspec,
            out_shape=jax.ShapeDtypeStruct((B, H, Lp, Dh), jnp.float32),
            interpret=interpret,
        )(*args, g, lse, delta)
    else:
        # biased: ONE fused pass for dq + dbias, grid (H, Q-blocks, B) with
        # b innermost (see _flash_dq_dbias_kernel)
        qspec = pl.BlockSpec((1, 1, block_q, Dh),
                             lambda h, i, b: (b, h, i, 0))
        kfull = pl.BlockSpec((1, 1, Sp, Dh), lambda h, i, b: (b, h, 0, 0))
        rowspec = pl.BlockSpec((1, 1, block_q, _LSE_LANES),
                               lambda h, i, b: (b, h, i, 0))
        in_specs = [qspec, kfull, kfull,
                    pl.BlockSpec((1, 1, Sp), lambda h, i, b: (b, 0, 0)),
                    pl.BlockSpec((1, block_q, Sp),
                                 lambda h, i, b: (h, i, 0))]
        args = [q, k, v, mask_i32, bias_f]
        if seg is not None:
            in_specs.append(pl.BlockSpec((1, block_q, _LSE_LANES),
                                         lambda h, i, b: (b, i, 0)))
            in_specs.append(pl.BlockSpec((1, 1, Sp),
                                         lambda h, i, b: (b, 0, 0)))
            args.extend([seg_q, seg_kv])

        def dq_db_kernel(*refs):
            refs = list(refs)
            sq_ref = sk_ref = None
            i = 5
            if seg is not None:
                sq_ref, sk_ref = refs[5], refs[6]
                i = 7
            _flash_dq_dbias_kernel(refs[0], refs[1], refs[2], refs[3],
                                   refs[4], sq_ref, sk_ref, refs[i],
                                   refs[i + 1], refs[i + 2], refs[i + 3],
                                   refs[i + 4])

        dq, db = pl.pallas_call(
            dq_db_kernel,
            grid=(H, Lp // block_q, B),
            in_specs=in_specs + [qspec, rowspec, rowspec],
            out_specs=[qspec,
                       pl.BlockSpec((1, block_q, Sp),
                                    lambda h, i, b: (h, i, 0))],
            out_shape=[jax.ShapeDtypeStruct((B, H, Lp, Dh), jnp.float32),
                       jax.ShapeDtypeStruct((H, Lp, Sp), jnp.float32)],
            interpret=interpret,
        )(*args, g, lse, delta)
        db = db[:, :L, :S].astype(bias_dtype)

    kvspec = pl.BlockSpec((1, 1, block_kv, Dh), lambda b, h, j: (b, h, j, 0))
    qfull = pl.BlockSpec((1, 1, Lp, Dh), lambda b, h, j: (b, h, 0, 0))
    rowfull = pl.BlockSpec((1, 1, Lp, _LSE_LANES),
                           lambda b, h, j: (b, h, 0, 0))

    def dkv_kernel(*refs):
        refs = list(refs)
        q_ref, k_ref, v_ref, m_ref = refs[:4]
        i = 4
        b_ref = None
        if bias is not None:
            b_ref = refs[i]
            i += 1
        sq_ref = sk_ref = None
        if seg is not None:
            sq_ref, sk_ref = refs[i], refs[i + 1]
            i += 2
        _flash_dkv_kernel(q_ref, k_ref, v_ref, m_ref, b_ref, sq_ref, sk_ref,
                          refs[i], refs[i + 1], refs[i + 2], refs[i + 3],
                          refs[i + 4])

    in_specs = [qfull, kvspec, kvspec,
                pl.BlockSpec((1, 1, block_kv), lambda b, h, j: (b, 0, j))]
    args = [q, k, v, mask_i32]
    if bias is not None:
        in_specs.append(
            pl.BlockSpec((1, Lp, block_kv), lambda b, h, j: (h, 0, j)))
        args.append(bias_f)
    if seg is not None:
        in_specs.append(pl.BlockSpec((1, Lp, _LSE_LANES),
                                     lambda b, h, j: (b, 0, 0)))
        in_specs.append(pl.BlockSpec((1, 1, block_kv),
                                     lambda b, h, j: (b, 0, j)))
        args.extend([seg_q, seg_kv])
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B, H, Sp // block_kv),
        in_specs=in_specs + [qfull, rowfull, rowfull],
        out_specs=[kvspec, kvspec],
        out_shape=[jax.ShapeDtypeStruct((B, H, Sp, Dh), jnp.float32),
                   jax.ShapeDtypeStruct((B, H, Sp, Dh), jnp.float32)],
        interpret=interpret,
    )(*args, g, lse, delta)

    dq = dq[:, :, :L].astype(in_dtypes[0])
    dk = dk[:, :, :S].astype(in_dtypes[1])
    dv = dv[:, :, :S].astype(in_dtypes[2])
    return dq, dk, dv, db


def _fwd(q, k, v, kv_mask, bias, seg, block_q, block_kv, interpret):
    out, lse = _flash_forward(q, k, v, kv_mask, bias, seg, block_q,
                              block_kv, interpret)
    return out, (q, k, v, kv_mask, bias, seg, out, lse)


def _bwd(block_q, block_kv, interpret, res, g):
    q, k, v, kv_mask, bias, seg, out, lse = res
    dq, dk, dv, db = _flash_backward(q, k, v, kv_mask, bias, seg, g, out,
                                     lse, block_q, block_kv, interpret)
    return dq, dk, dv, None, db, None


_flash_attention.defvjp(_fwd, _bwd)
