"""Chunked brute-force top-k over page vectors (SURVEY.md §3 #21-22).

This is the TPU-native ANN substrate: instead of a CPU FAISS index, score
queries against the corpus with MXU matmuls and keep a running top-k via
`lax.scan` + `lax.top_k` — HBM never holds more than one [Bq, chunk] score
block, so the corpus side streams at HBM bandwidth while compute stays on
the MXU. Exact (brute-force) search; at 1B pages it shards over the mesh
'data' axis with a final cross-shard merge (see mine/ann.py, evals/recall.py).
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


@partial(jax.jit, static_argnames=("k", "chunk"))
def chunked_topk(q: jnp.ndarray, pages: jnp.ndarray, k: int = 10,
                 chunk: int = 8192) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Running top-k of q @ pages.T.

    q: [Bq, D] (pre-normalized for cosine); pages: [N, D]; returns
    (scores [Bq, k], indices [Bq, k]) with indices into `pages` rows.
    N is padded up to a chunk multiple internally; pad rows score -inf.
    """
    Bq, D = q.shape
    N = pages.shape[0]
    chunk = min(chunk, max(N, 1))
    pad = (-N) % chunk
    if pad:
        pages = jnp.concatenate(
            [pages, jnp.zeros((pad, D), pages.dtype)], axis=0)
    n_chunks = pages.shape[0] // chunk
    pages = pages.reshape(n_chunks, chunk, D)
    valid = N  # rows >= valid are padding

    init_scores = jnp.full((Bq, k), -jnp.inf, jnp.float32)
    init_idx = jnp.full((Bq, k), -1, jnp.int32)

    def body(carry, inp):
        best_s, best_i = carry
        ci, block = inp                                  # block: [chunk, D]
        # HIGHEST precision: ranking fidelity matters more than the ~2x MXU
        # cost of the fp32-via-bf16-passes matmul on TPU.
        s = jnp.matmul(q, block.T, precision=lax.Precision.HIGHEST,
                       preferred_element_type=jnp.float32)  # [Bq, chunk]
        base = ci * chunk
        ids = base + jnp.arange(chunk, dtype=jnp.int32)
        s = jnp.where(ids[None, :] < valid, s, -jnp.inf)
        cat_s = jnp.concatenate([best_s, s], axis=1)
        cat_i = jnp.concatenate(
            [best_i, jnp.broadcast_to(ids[None], (Bq, chunk))], axis=1)
        top_s, pos = lax.top_k(cat_s, k)
        top_i = jnp.take_along_axis(cat_i, pos, axis=1)
        return (top_s, top_i), None

    (scores, idx), _ = lax.scan(
        body, (init_scores, init_idx),
        (jnp.arange(n_chunks, dtype=jnp.int32), pages))
    return scores, idx
