"""Brute-force top-k over page vectors (SURVEY.md §3 #21-22).

This is the TPU-native ANN substrate: instead of a CPU FAISS index, score
queries against the corpus with MXU matmuls and keep a running top-k via
`lax.scan` + `lax.top_k` — HBM never holds more than one [Bq, chunk] score
block, so the corpus side streams at HBM bandwidth while compute stays on
the MXU. Exact (brute-force) search, three tiers:

  * `chunked_topk`   — one device, pages resident in HBM.
  * `sharded_topk`   — pages row-sharded over the mesh 'data' axis; each
    device scores its slice, per-shard top-k candidates are all-gathered
    over ICI and merged. HBM per device holds only N/n_data rows.
  * `topk_over_store`— streams vector-store shards from disk through
    `sharded_topk`, merging on host. Peak footprint is ONE store shard
    spread over the mesh, so 1B-page retrieval (BASELINE.md:16) runs on a
    fixed memory budget. Used by evals/recall.py and mine/ann.py.

`rerank_candidates` is the exact half of the IVF ANN path (index/ivf.py,
docs/ANN.md): the same fused-widening matmul over a GATHERED candidate
block instead of the whole corpus, masked per query to its probed lists.
"""
from __future__ import annotations

from functools import partial
from typing import Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from dnn_page_vectors_tpu.utils.compat import (
    pcast_varying, shard_map_unchecked)


def _topk_scan(q: jnp.ndarray, pages: jnp.ndarray, k: int, chunk: int,
               valid: jnp.ndarray, scales: jnp.ndarray | None = None,
               init=None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Running top-k of q @ pages.T. pages [N, D] with N % chunk == 0;
    rows >= `valid` (traced scalar) are padding and score -inf. `init` lets
    shard_map callers pass a carry pcast to the right varying axes.

    pages may be narrow (fp16 rows, or int8 codes with per-row `scales`):
    the widening happens HERE, fused into the matmul's HBM read, so device
    memory and host->device traffic stay at the stored width. For int8 the
    per-row scale factors out of the dot product — score[b, j] =
    (q[b] . codes[j]) * scale[j] — so dequant is one [Bq, chunk] multiply
    on the score block, never a materialized fp32 page matrix."""
    Bq = q.shape[0]
    n_chunks = pages.shape[0] // chunk
    blocks = pages.reshape(n_chunks, chunk, -1)
    scale_blocks = (None if scales is None
                    else scales.astype(jnp.float32).reshape(n_chunks, chunk))

    if init is None:
        init = (jnp.full((Bq, k), -jnp.inf, jnp.float32),
                jnp.full((Bq, k), -1, jnp.int32))
    init_scores, init_idx = init

    def body(carry, inp):
        best_s, best_i = carry
        ci, block, scl = inp                             # block: [chunk, D]
        # HIGHEST precision: ranking fidelity matters more than the ~2x MXU
        # cost of the fp32-via-bf16-passes matmul on TPU. fp16->fp32 widening
        # is exact; int8 codes (<= 127 in magnitude) are exact in any float.
        s = jnp.matmul(q, block.T.astype(jnp.float32),
                       precision=lax.Precision.HIGHEST,
                       preferred_element_type=jnp.float32)  # [Bq, chunk]
        if scl is not None:
            s = s * scl[None, :]
        ids = ci * chunk + jnp.arange(chunk, dtype=jnp.int32)
        s = jnp.where(ids[None, :] < valid, s, -jnp.inf)
        cat_s = jnp.concatenate([best_s, s], axis=1)
        cat_i = jnp.concatenate(
            [best_i, jnp.broadcast_to(ids[None], (Bq, chunk))], axis=1)
        top_s, pos = lax.top_k(cat_s, k)
        top_i = jnp.take_along_axis(cat_i, pos, axis=1)
        # padding / -inf slots must not report a bogus row id
        top_i = jnp.where(jnp.isfinite(top_s), top_i, -1)
        return (top_s, top_i), None

    # None is a static empty pytree node: body sees scl=None when unscaled
    (scores, idx), _ = lax.scan(
        body, (init_scores, init_idx),
        (jnp.arange(n_chunks, dtype=jnp.int32), blocks, scale_blocks))
    return scores, idx


@partial(jax.jit, static_argnames=("k", "chunk"))
def chunked_topk(q: jnp.ndarray, pages: jnp.ndarray, k: int = 10,
                 chunk: int = 8192) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Single-device running top-k of q @ pages.T.

    q: [Bq, D] (pre-normalized for cosine); pages: [N, D]; returns
    (scores [Bq, k], indices [Bq, k]) with indices into `pages` rows.
    N is padded up to a chunk multiple internally; pad rows score -inf.
    """
    N, D = pages.shape
    chunk = min(chunk, max(N, 1))
    pad = (-N) % chunk
    if pad:
        pages = jnp.concatenate(
            [pages, jnp.zeros((pad, D), pages.dtype)], axis=0)
    return _topk_scan(q, pages, k, chunk, jnp.int32(N))


_SHARDED_CACHE: Dict[Tuple, Tuple] = {}


def _build_sharded_topk(mesh: Mesh, k: int, chunk: int, scaled: bool):
    """Jitted (q, pages[, scales], valid) -> (scores, global row idx) with
    pages (and int8 scales) row-sharded over 'data'. Cached per
    (mesh, k, chunk, scaled); jit retraces per pages dtype within a key."""
    n_data = mesh.shape["data"]

    def run(q, pages_local, scales_local, valid):
        rows = pages_local.shape[0]                  # per-shard row count
        shard = lax.axis_index("data")
        valid_local = jnp.clip(valid - shard * rows, 0, rows).astype(jnp.int32)
        c = min(chunk, rows)
        pad = (-rows) % c
        if pad:
            pages_local = jnp.concatenate(
                [pages_local,
                 jnp.zeros((pad, pages_local.shape[1]), pages_local.dtype)])
            if scales_local is not None:
                scales_local = jnp.concatenate(
                    [scales_local, jnp.zeros((pad,), scales_local.dtype)])
        # carry starts as a constant; pcast marks it varying over 'data' so
        # the scan's in/out types agree under shard_map
        init = jax.tree_util.tree_map(
            lambda x: pcast_varying(x, ("data",)),
            (jnp.full((q.shape[0], k), -jnp.inf, jnp.float32),
             jnp.full((q.shape[0], k), -1, jnp.int32)))
        s, i = _topk_scan(q, pages_local, k, c, valid_local,
                          scales=scales_local, init=init)
        gi = jnp.where(i >= 0, i + shard * rows, -1)
        # gather every shard's k candidates over ICI and merge everywhere
        all_s = lax.all_gather(s, "data")            # [n_data, Bq, k]
        all_i = lax.all_gather(gi, "data")
        Bq = q.shape[0]
        cat_s = jnp.transpose(all_s, (1, 0, 2)).reshape(Bq, n_data * k)
        cat_i = jnp.transpose(all_i, (1, 0, 2)).reshape(Bq, n_data * k)
        kk = min(k, n_data * k)
        top_s, pos = lax.top_k(cat_s, kk)
        top_i = jnp.take_along_axis(cat_i, pos, axis=1)
        top_i = jnp.where(jnp.isfinite(top_s), top_i, -1)
        return top_s, top_i

    # After the all_gather every shard computes the identical merge, so the
    # P() outputs ARE replicated over 'data' — but that's a dynamic fact the
    # static varying-axis checker can't infer; check_vma=False is the
    # documented escape hatch for exactly this collective-then-merge shape.
    if scaled:
        fn = run
        in_specs = (P(), P("data"), P("data"), P())
    else:
        fn = lambda q, pages, valid: run(q, pages, None, valid)  # noqa: E731
        in_specs = (P(), P("data"), P())
    mapped = shard_map_unchecked(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=(P(), P()))
    return jax.jit(mapped)


def sharded_topk(q: jnp.ndarray, pages, mesh: Mesh, k: int = 10,
                 chunk: int = 8192, valid: int | None = None, scales=None
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k with pages [N, D] row-sharded over the mesh 'data' axis.

    N must divide by mesh 'data'; rows >= `valid` are padding (score -inf,
    index -1). q is replicated. Returns replicated (scores, indices) with
    indices global into the sharded row order. `pages` may be fp16 rows or
    int8 codes with per-row `scales` [N] — widened on-device (_topk_scan).
    """
    key = (mesh, int(k), int(chunk), scales is not None)
    fn = _SHARDED_CACHE.get(key)
    if fn is None:
        fn = _SHARDED_CACHE[key] = _build_sharded_topk(
            mesh, k, chunk, scales is not None)
    N = pages.shape[0]
    if N % mesh.shape["data"]:
        raise ValueError(f"pages rows {N} must divide mesh data axis "
                         f"{mesh.shape['data']}; pad the input")
    v = jnp.int32(N if valid is None else valid)
    return fn(q, pages, v) if scales is None else fn(q, pages, scales, v)


@partial(jax.jit, static_argnames=("k",))
def rerank_candidates(q: jnp.ndarray, cand, scales, cand_cent: jnp.ndarray,
                      selected: jnp.ndarray, k: int
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact re-rank of gathered IVF candidates (index/ivf.py): one MXU
    matmul of q [B, D] against the candidate block cand [C, D] (fp16 rows
    or int8 codes with per-row `scales` — widening fused into the matmul,
    same contract as _topk_scan), masked so each query only keeps
    candidates whose centroid id (cand_cent [C], -1 = padding) is in ITS
    probed set (selected [B, nprobe]), then lax.top_k. Returns
    (scores [B, k], positions into cand [B, k], -1 where fewer than k
    candidates matched). nprobe is a static shape, so the mask is an
    unrolled OR over nprobe [B, C] comparisons — never an [B, nprobe, C]
    materialization."""
    s = jnp.matmul(q, cand.T.astype(jnp.float32),
                   precision=lax.Precision.HIGHEST,
                   preferred_element_type=jnp.float32)        # [B, C]
    if scales is not None:
        s = s * scales.astype(jnp.float32)[None, :]
    hit = cand_cent[None, :] == selected[:, 0:1]
    for p in range(1, selected.shape[1]):
        hit = hit | (cand_cent[None, :] == selected[:, p:p + 1])
    s = jnp.where(hit, s, -jnp.inf)      # padding (cent -1) never matches
    top_s, pos = lax.top_k(s, min(k, s.shape[1]))
    pos = jnp.where(jnp.isfinite(top_s), pos, -1)
    return top_s, pos


@partial(jax.jit, static_argnames=("k",))
def rerank_positions(q: jnp.ndarray, cand, scales, pos: jnp.ndarray, k: int
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Exact top-k over PER-QUERY candidate positions into one gathered
    block — the final stage of the PQ/ADC path (index/pq.py, docs/ANN.md):
    `cand` [U, D] holds the union of every query's ADC-surviving rows at
    STORED width (fp16 rows or int8 codes with per-row `scales`, widening
    fused into the matmul exactly like _topk_scan), and `pos` [B, R] maps
    each query to ITS candidates (-1 = empty slot). One [B, U] matmul
    scores the whole block, take_along_axis keeps each query's own R, and
    lax.top_k picks the winners. Returns (scores [B, k], positions into
    `cand` [B, k], -1 where fewer than k candidates survived)."""
    s = jnp.matmul(q, cand.T.astype(jnp.float32),
                   precision=lax.Precision.HIGHEST,
                   preferred_element_type=jnp.float32)          # [B, U]
    if scales is not None:
        s = s * scales.astype(jnp.float32)[None, :]
    sp = jnp.take_along_axis(s, jnp.clip(pos, 0, None), axis=1)  # [B, R]
    sp = jnp.where(pos >= 0, sp, -jnp.inf)
    top_s, rpos = lax.top_k(sp, min(k, sp.shape[1]))
    out_pos = jnp.take_along_axis(pos, jnp.clip(rpos, 0, None), axis=1)
    out_pos = jnp.where(jnp.isfinite(top_s), out_pos, -1)
    return top_s, out_pos


def merge_topk_host(best_s: np.ndarray, best_i: np.ndarray,
                    new_s: np.ndarray, new_i: np.ndarray
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side running-top-k merge of two [Nq, k] candidate sets (ids are
    global page ids; -1 = empty slot).

    O(W) argpartition down to the winning k, then an O(k log k) sort of
    just those — not a full-row argsort: this runs once per disk shard per
    query-batch on the streaming path, so at 1B-page scale it is the
    hottest host loop serving owns. Ties at the selection boundary may
    admit a different equal-scored candidate than a stable full sort would
    (scores are unchanged; only which of the tied ids survives)."""
    k = best_s.shape[1]
    cat_s = np.concatenate([best_s, new_s], axis=1)
    cat_i = np.concatenate([best_i, new_i], axis=1)
    cat_s = np.where(cat_i < 0, -np.inf, cat_s)
    if cat_s.shape[1] > k:
        part = np.argpartition(-cat_s, k - 1, axis=1)[:, :k]
        order = np.argsort(-np.take_along_axis(cat_s, part, axis=1),
                           axis=1, kind="stable")
        pos = np.take_along_axis(part, order, axis=1)
    else:
        pos = np.argsort(-cat_s, axis=1, kind="stable")
    return (np.take_along_axis(cat_s, pos, axis=1),
            np.take_along_axis(cat_i, pos, axis=1))


def merge_partition_topk(parts) -> Tuple[np.ndarray, np.ndarray]:
    """Balanced pairwise merge tree over per-partition top-k candidate
    sets — the host half of the partitioned scatter-gather
    (infer/partition.py, docs/SCALING.md "Partitioned serving").

    `parts` is a sequence of (scores [Nq, k], page_ids [Nq, k]) — one
    entry per partition, ids global (-1 = empty slot). Each partition
    already merged its own shards on device (`sharded_topk` + the
    per-view merge program); this fold generalizes `merge_shard_topk`'s
    running merge to partition granularity: pairs merge through
    `merge_topk_host`, log2(P) levels deep, so the host-side merge cost
    per level stays O(Nq * k) regardless of partition count. With
    distinct scores the result is identical to a single global top-k
    over the union — the byte-identity contract tests/test_partition.py
    pins against the single-partition exact path."""
    merged = [(np.asarray(s, np.float32), np.asarray(i, np.int64))
              for s, i in parts]
    if not merged:
        raise ValueError("merge_partition_topk needs at least one partition")
    while len(merged) > 1:
        nxt = [merge_topk_host(merged[j][0], merged[j][1],
                               merged[j + 1][0], merged[j + 1][1])
               for j in range(0, len(merged) - 1, 2)]
        if len(merged) % 2:
            nxt.append(merged[-1])
        merged = nxt
    return merged[0]


def stage_shard(vecs, rows: int, dim: int, mesh: Mesh, scales=None
                ) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Zero-pad one store shard to `rows` (the static compiled shape) and
    place it row-sharded over the mesh 'data' axis, AT ITS STORED WIDTH
    (fp16 rows / int8 codes + fp16 `scales`): host->device traffic and HBM
    per shard are 2x / 4x under the old fp32 staging, and the widening fuses
    into the device matmul (VERDICT r4 Weak #3). Shared by the streaming
    sweep below and the HBM-resident serving path (infer/serve.py).
    Returns (pages, scales-or-None)."""
    dtype = np.asarray(vecs).dtype
    if dtype not in (np.float16, np.int8):
        dtype = np.float32
    buf = np.zeros((rows, dim), dtype)
    buf[: vecs.shape[0]] = vecs
    pages = jax.device_put(buf, NamedSharding(mesh, P("data")))
    if scales is None:
        return pages, None
    sbuf = np.zeros((rows,), np.float16)
    sbuf[: scales.shape[0]] = scales
    return pages, jax.device_put(sbuf, NamedSharding(mesh, P("data")))


def merge_shard_topk(q: jnp.ndarray, pages, page_ids: np.ndarray, valid: int,
                     mesh: Mesh, k: int, best_s: np.ndarray,
                     best_i: np.ndarray, chunk: int = 8192, scales=None
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Fold ONE device-resident shard's top-k into the running host merge:
    sharded_topk over `pages` (rows >= valid are padding), row indices
    mapped through `page_ids`, -inf masking, merge. Shared by the streaming
    path below and the HBM-resident serving path (infer/serve.py) so the
    clip/mask edge cases live in exactly one place."""
    if valid == 0:          # empty shard (all-padding write): nothing to add
        return best_s, best_i
    sc, idx = sharded_topk(q, pages, mesh, k=k, chunk=chunk, valid=valid,
                           scales=scales)
    sc, idx = np.asarray(sc), np.asarray(idx)
    pids = np.where(
        idx >= 0, page_ids[np.clip(idx, 0, valid - 1)], -1)
    return merge_topk_host(best_s, best_i,
                           np.where(np.isfinite(sc), sc, -np.inf), pids)


def topk_over_store(query_vecs: np.ndarray, store, mesh: Mesh, k: int = 10,
                    chunk: int = 8192, query_batch: int = 1024,
                    entries=None) -> Tuple[np.ndarray, np.ndarray]:
    """Stream the vector store through `sharded_topk`, one disk shard at a
    time, merging a host-side running top-k. Returns (scores [Nq, k],
    page_ids [Nq, k] int64, -1 padded). This is the cross-shard merge path
    for 1B-page retrieval: peak HBM = one store shard / n_data per device,
    peak host memory = TWO store shards + the query matrix — the sweep is
    double-buffered (store.iter_shards(prefetch=1)): shard i+1's disk read
    runs on a background reader thread while shard i is staged and scored,
    so disk latency overlaps device top-k instead of serializing after it.
    `entries` sweeps an explicit shard-table snapshot instead of the live
    one (the serving hot-swap's old-view isolation, docs/UPDATES.md).
    """
    nq, dim = query_vecs.shape
    n_data = mesh.shape["data"]
    best_s = np.full((nq, k), -np.inf, np.float32)
    best_i = np.full((nq, k), -1, np.int64)
    if entries is None:
        entries = store.shards()
    if sum(s["count"] for s in entries) == 0 or nq == 0:
        return best_s, best_i
    # one static shape for every disk shard -> a single compiled program
    shard_rows = max((s["count"] for s in entries), default=0)
    shard_rows += (-shard_rows) % max(n_data, 1)
    qb = min(query_batch, nq)
    for ids, vecs, scl in store.iter_shards(raw=True, prefetch=1,
                                            entries=entries):
        n = vecs.shape[0]
        if n == 0:        # empty shard: nothing to score, don't stage it
            continue
        pages, scales = stage_shard(vecs, shard_rows, dim, mesh, scales=scl)
        ids = np.asarray(ids, np.int64)
        for s in range(0, nq, qb):
            q = query_vecs[s: s + qb]
            pad_q = qb - q.shape[0]
            if pad_q:                                # pad to compiled shape
                q = np.concatenate(
                    [q, np.zeros((pad_q, dim), q.dtype)])
            merged_s, merged_i = merge_shard_topk(
                jnp.asarray(q, jnp.float32), pages, ids, n, mesh, k,
                np.concatenate([best_s[s: s + qb],
                                np.full((pad_q, k), -np.inf, np.float32)]),
                np.concatenate([best_i[s: s + qb],
                                np.full((pad_q, k), -1, np.int64)]),
                chunk=chunk, scales=scales)
            keep = qb - pad_q
            best_s[s: s + qb] = merged_s[:keep]
            best_i[s: s + qb] = merged_i[:keep]
    return best_s, best_i
