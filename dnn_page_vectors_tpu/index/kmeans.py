"""Sharded mini-batch k-means: the IVF coarse quantizer (docs/ANN.md).

Trains `nlist` centroids over the vector store's L2-normalized rows with
the SAME memory contract as `ops/topk.py:topk_over_store`: one disk shard
at a time, row-sharded over the mesh 'data' axis, scored on the MXU. Each
pass streams shards through a shard_mapped scan — per chunk, one
[chunk, nlist] row-vs-centroid matmul picks assignments and one
one-hot-transpose matmul accumulates per-centroid sums — then psums the
[nlist, D] sums / [nlist] counts over ICI, so device memory never exceeds
O(chunk * max(D, nlist)) per device and host memory never exceeds one
shard plus the centroid matrix.

Spherical k-means: store rows are unit-normalized (the store invariant, so
retrieval is a pure dot product), and centroids are re-normalized after
every update — assignment by max dot product IS cosine assignment, and the
per-row int8 dequant scale factors out of the argmax entirely, so int8
codes ship to the device at 1 B/dim and only the accumulation pass pays
the widening.

Determinism (test-pinned, tests/test_ivf_index.py): seeded init sample,
seeded empty-cluster reseed, fixed shard/chunk reduction order — the same
store + seed produces byte-identical centroids on the same backend.
"""
from __future__ import annotations

from typing import Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dnn_page_vectors_tpu.ops.topk import stage_shard
from dnn_page_vectors_tpu.utils.compat import (
    pcast_varying, shard_map_unchecked)

_PASS_CACHE: Dict[Tuple, object] = {}


def _build_shard_pass(mesh: Mesh, nlist: int, chunk: int, scaled: bool,
                      choices: int = 1):
    """Jitted (rows[, scales], valid, centroids) -> (sums [nlist, D] f32,
    counts [nlist] f32, assign i32) with rows row-sharded over 'data' and
    sums/counts psummed (replicated). Assignments come back in global row
    order; padding rows (>= valid) carry assignment -1 and contribute
    nothing to sums/counts. `choices` > 1 returns each row's top-`choices`
    centroids [rows, choices] instead of the bare argmax [rows] — the
    balanced final-assignment sweep (docs/ANN.md) spills overflow rows to
    their next choice; sums/counts always accumulate the FIRST choice."""

    def run(rows_local, scales_local, valid, centroids):
        rows = rows_local.shape[0]
        shard = lax.axis_index("data")
        valid_local = jnp.clip(valid - shard * rows, 0, rows).astype(jnp.int32)
        c = min(chunk, rows)
        pad = (-rows) % c
        if pad:
            rows_local = jnp.concatenate(
                [rows_local,
                 jnp.zeros((pad, rows_local.shape[1]), rows_local.dtype)])
            if scales_local is not None:
                scales_local = jnp.concatenate(
                    [scales_local, jnp.zeros((pad,), scales_local.dtype)])
        n_chunks = rows_local.shape[0] // c
        blocks = rows_local.reshape(n_chunks, c, -1)
        sblocks = (None if scales_local is None
                   else scales_local.astype(jnp.float32).reshape(n_chunks, c))
        D = centroids.shape[1]
        # carry starts as a constant; pcast marks it varying over 'data' so
        # the scan's in/out types agree under shard_map (see ops/topk.py)
        init = jax.tree_util.tree_map(
            lambda x: pcast_varying(x, ("data",)),
            (jnp.zeros((nlist, D), jnp.float32),
             jnp.zeros((nlist,), jnp.float32)))

        def body(carry, inp):
            sums, counts = carry
            ci, block, scl = inp                         # block: [c, D]
            rf = block.astype(jnp.float32)
            if scl is not None:                          # int8 dequant
                rf = rf * scl[:, None]
            s = jnp.matmul(rf, centroids.T,
                           precision=lax.Precision.HIGHEST,
                           preferred_element_type=jnp.float32)  # [c, nlist]
            if choices > 1:
                _, a_top = lax.top_k(s, min(choices, nlist))
                a_top = a_top.astype(jnp.int32)
                a = a_top[:, 0]
            else:
                a = jnp.argmax(s, axis=1).astype(jnp.int32)
                a_top = a[:, None]
            ridx = ci * c + jnp.arange(c, dtype=jnp.int32)
            w = (ridx < valid_local).astype(jnp.float32)
            oh = jax.nn.one_hot(a, nlist, dtype=jnp.float32) * w[:, None]
            sums = sums + jnp.matmul(oh.T, rf,
                                     precision=lax.Precision.HIGHEST)
            counts = counts + oh.sum(axis=0)
            out = jnp.where((ridx < valid_local)[:, None], a_top, -1)
            return (sums, counts), (out if choices > 1 else out[:, 0])

        (sums, counts), assign = lax.scan(
            body, init,
            (jnp.arange(n_chunks, dtype=jnp.int32), blocks, sblocks))
        sums = lax.psum(sums, "data")
        counts = lax.psum(counts, "data")
        assign = (assign.reshape(-1, choices)[:rows] if choices > 1
                  else assign.reshape(-1)[:rows])
        return sums, counts, assign

    if scaled:
        fn = run
        in_specs = (P("data"), P("data"), P(), P())
    else:
        fn = lambda rows, valid, cents: run(rows, None, valid, cents)  # noqa: E731
        in_specs = (P("data"), P(), P())
    # psum makes sums/counts replicated — a dynamic fact the static
    # varying-axis checker can't infer (same escape hatch as sharded_topk)
    mapped = shard_map_unchecked(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=(P(), P(), P("data")))
    return jax.jit(mapped)


def shard_pass(pages, scales, valid: int, centroids, mesh: Mesh,
               nlist: int, chunk: int = 8192, choices: int = 1):
    """One staged shard through the assignment/accumulation pass. `pages`
    and `scales` come from ops.topk.stage_shard (stored width, row-sharded);
    `centroids` is a replicated [nlist, D] f32 array."""
    key = (mesh, int(nlist), int(chunk), scales is not None, int(choices))
    fn = _PASS_CACHE.get(key)
    if fn is None:
        fn = _PASS_CACHE[key] = _build_shard_pass(
            mesh, nlist, chunk, scales is not None, choices=choices)
    v = jnp.int32(valid)
    return (fn(pages, v, centroids) if scales is None
            else fn(pages, scales, v, centroids))


def _normalize(c: np.ndarray) -> np.ndarray:
    n = np.linalg.norm(c, axis=1, keepdims=True)
    return (c / np.maximum(n, 1e-12)).astype(np.float32)


def _kmeans_pp(pool: np.ndarray, nlist: int,
               rng: np.random.Generator) -> np.ndarray:
    """Seeded k-means++ (Arthur & Vassilvitskii 2007) over the sampled
    pool: each next seed is drawn with probability proportional to its
    cosine distance from the nearest already-chosen seed, so seeds spread
    across the data instead of clumping where the density is — measurably
    lower list imbalance at large nlist than uniform seeding (the ROADMAP
    open item; `init_imbalance` in the build stats shows the delta).
    Incremental O(nlist * pool * D): one pool-vs-new-seed matvec per seed,
    never a full distance matrix. Deterministic for a given (pool, rng
    state); an already-chosen row has distance 0 and is never re-drawn."""
    n = pool.shape[0]
    out = np.empty((nlist, pool.shape[1]), np.float32)
    first = int(rng.integers(0, n))
    out[0] = pool[first]
    best = pool @ out[0]                     # nearest-seed cosine sim [n]
    for j in range(1, nlist):
        d = np.maximum(1.0 - best, 0.0)      # cosine distance to nearest
        total = d.sum()
        if total <= 0.0:                     # degenerate pool: uniform draw
            nxt = int(rng.integers(0, n))
        else:
            nxt = int(rng.choice(n, p=d / total))
        out[j] = pool[nxt]
        best = np.maximum(best, pool @ out[j])
    return out


def sample_rows(store, n: int, seed: int) -> np.ndarray:
    """Seeded deterministic sample of up to `n` dequantized f32 rows,
    proportional per shard, in (shard, row) order — the k-means init set
    and the empty-cluster reseed pool."""
    N = store.num_vectors
    out = []
    for entry in store.shards():
        cnt = entry["count"]
        if cnt == 0:
            continue
        quota = min(cnt, max(1, -(-n * cnt // max(N, 1))))
        rng = np.random.default_rng([seed, entry["index"]])
        rows = np.sort(rng.choice(cnt, size=quota, replace=False))
        _, vecs = store._load_entry(entry)           # dequantized rows
        out.append(np.asarray(vecs[rows], np.float32))
    if not out:
        return np.zeros((0, store.dim), np.float32)
    return np.concatenate(out)[:n]


def _padded_rows(store, mesh: Mesh) -> int:
    """One static row count for every staged shard -> one compiled pass."""
    rows = max((s["count"] for s in store.shards()), default=0)
    return rows + (-rows) % max(mesh.shape["data"], 1)


def _iter_staged(store, mesh: Mesh, rows: int, sample_per_shard=None,
                 rng_key=None, entries=None):
    """Yield (entry, valid_n, pages, scales) for every non-empty shard,
    staged at stored width. With `sample_per_shard`, a seeded per-shard row
    subset (the mini-batch) is staged instead of the full shard. `entries`
    restricts the sweep to a shard subset (the incremental index update's
    O(new shards) path); disk reads run one shard ahead on a reader
    thread either way."""
    from dnn_page_vectors_tpu.infer.vector_store import read_ahead
    entries = store.shards() if entries is None else entries

    def _load():
        for e in entries:
            ids, vecs, scl = store._load_entry(e, raw=True)
            yield e, np.asarray(vecs), (None if scl is None
                                        else np.asarray(scl))

    for entry, vecs, scl in read_ahead(_load(), depth=1):
        n = vecs.shape[0]
        if n == 0:
            continue
        if sample_per_shard is not None and n > sample_per_shard:
            rng = np.random.default_rng([*rng_key, entry["index"]])
            take = np.sort(rng.choice(n, size=sample_per_shard,
                                      replace=False))
            vecs = np.asarray(vecs)[take]
            scl = None if scl is None else np.asarray(scl)[take]
            n = sample_per_shard
        pages, scales = stage_shard(vecs, rows, store.dim, mesh, scales=scl)
        yield entry, n, pages, scales


def train_kmeans(store, mesh: Mesh, nlist: int, iters: int = 8,
                 seed: int = 0, chunk: int = 8192,
                 sample_per_shard: Optional[int] = None,
                 init_sample: int = 65_536,
                 init: str = "kmeans++") -> Tuple[np.ndarray, Dict]:
    """Train `nlist` unit-norm centroids over the store. Returns
    (centroids [nlist, D] f32, stats). Deterministic for a given
    (store bytes, seed, mesh, backend, init). `init` is "kmeans++"
    (default: D²-spread seeds, lower imbalance at large nlist) or
    "random" (uniform pool draw, the pre-update behavior); stats record
    `init_imbalance` — the faiss imbalance factor of the FIRST assignment
    pass — next to the final one so the seeding's contribution is
    measurable (`cli index` reports the delta)."""
    N = store.num_vectors
    if N == 0:
        raise ValueError("cannot train k-means over an empty store")
    nlist = int(min(max(1, nlist), N))
    pool = sample_rows(store, max(nlist, min(init_sample, N)), seed)
    rng = np.random.default_rng(seed)
    if init == "kmeans++":
        centroids = _normalize(_kmeans_pp(pool, nlist, rng))
    elif init == "random":
        centroids = _normalize(
            pool[rng.choice(pool.shape[0], size=nlist, replace=False)])
    else:
        raise ValueError(f"unknown k-means init {init!r} "
                         "(want kmeans++ or random)")
    rows = _padded_rows(store, mesh)
    reseeded = 0
    init_imbalance = 0.0
    for it in range(max(1, iters)):
        sums = np.zeros((nlist, store.dim), np.float64)
        counts = np.zeros((nlist,), np.float64)
        cdev = jnp.asarray(centroids)
        for _, n, pages, scales in _iter_staged(
                store, mesh, rows, sample_per_shard=sample_per_shard,
                rng_key=(seed, 1 + it)):
            s, c, _ = shard_pass(pages, scales, n, cdev, mesh, nlist,
                                 chunk=chunk)
            sums += np.asarray(s, np.float64)
            counts += np.asarray(c, np.float64)
        if it == 0:                    # seeding quality, before any update
            tot = counts.sum()
            init_imbalance = float(nlist * np.square(counts).sum()
                                   / max(tot, 1.0) ** 2)
        new = centroids.astype(np.float64).copy()
        nz = counts > 0
        new[nz] = sums[nz] / counts[nz, None]
        empty = np.nonzero(~nz)[0]
        if empty.size:                 # reseed dead clusters from the pool
            r2 = np.random.default_rng([seed, 2, it])
            new[empty] = pool[r2.integers(0, pool.shape[0], empty.size)]
            reseeded += int(empty.size)
        centroids = _normalize(new.astype(np.float32))
    return centroids, {"nlist": nlist, "iters": int(max(1, iters)),
                       "reseeded": reseeded, "init": init,
                       "init_imbalance": round(init_imbalance, 4),
                       "trained_rows": int(N if sample_per_shard is None
                                           else min(N, sample_per_shard
                                                    * len(store.shards())))}


# -- grouped per-subspace k-means (the PQ codebook trainer, index/pq.py) ----

_GROUPED_CACHE: Dict[Tuple, object] = {}


def _build_grouped_pass(m: int, k: int, dsub: int, chunk: int):
    """Jitted (X3 [n, m, dsub], valid, C [m, k, dsub]) ->
    (sums [m, k, dsub] f32, counts [m, k] f32, assign [n, m] i32): one
    EUCLIDEAN assignment + one-hot-accumulation pass over every subspace
    at once, chunked through a lax.scan so device memory stays
    O(chunk * m * k) — the same mini-batch MXU discipline as the coarse
    quantizer above, minus the mesh (codebook pools are host-sample
    sized). Euclidean, not spherical: sub-vectors of unit-norm rows are
    NOT unit-norm, so argmin ||x-c||^2 = argmax (x.c - ||c||^2/2)."""

    def run(x3, valid, cb):
        n = x3.shape[0]
        cn = -0.5 * jnp.sum(cb.astype(jnp.float32) ** 2, axis=-1)  # [m, k]
        blocks = x3.reshape(n // chunk, chunk, m, dsub)

        def body(carry, inp):
            sums, counts = carry
            ci, blk = inp                               # blk [chunk, m, dsub]
            bf = blk.astype(jnp.float32)
            s = jnp.einsum("cmd,mkd->cmk", bf, cb,
                           precision=lax.Precision.HIGHEST) + cn[None]
            a = jnp.argmax(s, axis=-1).astype(jnp.int32)        # [chunk, m]
            ridx = ci * chunk + jnp.arange(chunk, dtype=jnp.int32)
            w = (ridx < valid).astype(jnp.float32)
            oh = jax.nn.one_hot(a, k, dtype=jnp.float32) * w[:, None, None]
            sums = sums + jnp.einsum("cmk,cmd->mkd", oh, bf,
                                     precision=lax.Precision.HIGHEST)
            counts = counts + oh.sum(axis=0)
            return (sums, counts), jnp.where(ridx[:, None] < valid, a, -1)

        init = (jnp.zeros((m, k, dsub), jnp.float32),
                jnp.zeros((m, k), jnp.float32))
        (sums, counts), assign = lax.scan(
            body, init,
            (jnp.arange(n // chunk, dtype=jnp.int32), blocks))
        return sums, counts, assign.reshape(-1, m)

    return jax.jit(run)


def _grouped_pass(x3: np.ndarray, valid: int, cb, chunk: int = 2048):
    n, m, dsub = x3.shape
    k = cb.shape[1]
    chunk = min(chunk, n)
    pad = (-n) % chunk
    if pad:
        x3 = np.concatenate([x3, np.zeros((pad, m, dsub), x3.dtype)])
    key = (int(m), int(k), int(dsub), int(chunk))
    fn = _GROUPED_CACHE.get(key)
    if fn is None:
        fn = _GROUPED_CACHE[key] = _build_grouped_pass(m, k, dsub, chunk)
    sums, counts, assign = fn(jnp.asarray(x3), jnp.int32(valid),
                              jnp.asarray(cb, jnp.float32))
    return sums, counts, assign[:valid]


def grouped_kmeans(x3: np.ndarray, k: int, iters: int = 8, seed: int = 0,
                   chunk: int = 2048) -> Tuple[np.ndarray, Dict]:
    """Train `m` independent k-means codebooks — one per PQ subspace —
    over the pool `x3` [n, m, dsub], all subspaces per pass (index/pq.py,
    docs/ANN.md). Seeded and byte-deterministic for a given (pool bytes,
    k, iters, seed): seeded distinct-row init per subspace, seeded
    empty-cluster reseed, fixed chunk reduction order. Returns
    (codebooks [m, k, dsub] f32, stats)."""
    n, m, dsub = x3.shape
    if k > n:
        raise ValueError(f"PQ codebook k={k} exceeds pool size {n}")
    x3 = np.asarray(x3, np.float32)
    skey = (tuple(int(s) for s in seed)
            if isinstance(seed, (tuple, list)) else (int(seed),))
    rng = np.random.default_rng(skey)
    cb = np.stack([x3[np.sort(rng.choice(n, size=k, replace=False)), j]
                   for j in range(m)])                     # [m, k, dsub]
    reseeded = 0
    for it in range(max(1, iters)):
        sums, counts, _ = _grouped_pass(x3, n, cb, chunk=chunk)
        sums = np.asarray(sums, np.float64)
        counts = np.asarray(counts, np.float64)
        new = cb.astype(np.float64).copy()
        nz = counts > 0
        new[nz] = sums[nz] / counts[nz][:, None]
        empty = np.argwhere(~nz)
        if empty.size:                 # reseed dead codewords from the pool
            r2 = np.random.default_rng([*skey, 2, it])
            rows = r2.integers(0, n, empty.shape[0])
            for (j, c), r in zip(empty, rows):
                new[j, c] = x3[r, j]
            reseeded += int(empty.shape[0])
        cb = new.astype(np.float32)
    return cb, {"k": int(k), "iters": int(max(1, iters)),
                "reseeded": reseeded}


def grouped_assign(x3: np.ndarray, cb: np.ndarray,
                   chunk: int = 2048) -> np.ndarray:
    """Nearest-codeword id per (row, subspace): [n, m] i32 — the PQ
    encode assignment, same compiled pass as the trainer."""
    if x3.shape[0] == 0:
        return np.zeros((0, cb.shape[0]), np.int32)
    _, _, assign = _grouped_pass(np.asarray(x3, np.float32), x3.shape[0],
                                 cb, chunk=chunk)
    return np.asarray(assign, np.int32)


def assign_store(store, mesh: Mesh, centroids: np.ndarray,
                 chunk: int = 8192, entries=None, choices: int = 1
                 ) -> Iterator[Tuple[Dict, np.ndarray]]:
    """Final assignment sweep: yield (shard entry, assign i32) for every
    non-empty shard, streaming one shard at a time through the same
    compiled pass the trainer used (sums/counts are discarded). `entries`
    restricts the sweep to a shard subset — the incremental index update
    assigns ONLY the new generation's shards this way (docs/UPDATES.md).
    `choices` > 1 yields each row's top-`choices` centroids
    [count, choices] for the balanced-assignment spill (docs/ANN.md)."""
    nlist = centroids.shape[0]
    rows = _padded_rows(store, mesh)
    cdev = jnp.asarray(centroids, jnp.float32)
    for entry, n, pages, scales in _iter_staged(store, mesh, rows,
                                                entries=entries):
        _, _, assign = shard_pass(pages, scales, n, cdev, mesh, nlist,
                                  chunk=chunk, choices=choices)
        yield entry, np.asarray(assign, np.int32)[:n]
