"""OPQ+PQ codec: compressed posting payloads for the IVF index
(docs/ANN.md). The IVF candidate gather is the serving bottleneck at
scale — it moves STORED-width rows (1 B/dim at int8, 2 B/dim at fp16)
over the host mmap path per query. Product quantization (Jegou et al.
2011) cuts that to `m` bytes/row: split the rotated vector into `m`
subspaces of `dsub = D/m` dims, train a 256-codeword codebook per
subspace (so one code byte per subspace), and score candidates with
asymmetric distance computation (ADC) — per query, one [m, 256] lookup
table of query-subvector x codeword dot products, then each candidate's
score is m table lookups instead of a D-wide matmul row. The optimized
rotation (Ge et al., OPQ, 2013) alternates Procrustes rotation solves
with codebook re-training so the subspace split loses less signal than
a naive coordinate split.

Division of labor with the rest of `index/`:

  * codebooks train on the SAME mini-batch MXU k-means machinery as the
    coarse quantizer — `index.kmeans.grouped_kmeans` runs every
    subspace's Euclidean assignment + one-hot accumulation per chunked
    pass — over the store's seeded sample pool (`sample_rows`), so PQ
    builds inherit the streamed, seeded, byte-deterministic build
    discipline (test-pinned, tests/test_pq.py);
  * `ivf.py` persists the rotation / codebooks / per-shard code files
    under the store's manifest+CRC machinery and runs the ADC search
    path (codes gathered at m B/row, on-device LUT + running top-r, the
    exact re-rank from stored-width rows kept for the final top-k so the
    recall@10 >= 0.95 contract is measured on real scores, not codes).

Scores are INNER-PRODUCT ADC: rows are unit-norm (store invariant) and
the rotation is orthogonal, so q.x = (qR).(xR) ~= sum_m (qR)_m . c_m —
the reconstruction error is bounded by the per-subspace quantization
error, and the exact re-rank erases it for the returned top-k.
"""
from __future__ import annotations

import time
from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dnn_page_vectors_tpu.index.kmeans import (
    grouped_assign, grouped_kmeans, sample_rows)

KSUB = 256                      # codewords per subspace: one uint8 per code


def auto_pq_m(dim: int) -> int:
    """Default subspace count for `cli index --pq`: ~8 dims per subspace
    (the faiss-style operating point — m bytes/row at 256 codewords),
    falling back to coarser splits for dims 8 doesn't divide."""
    for dsub in (8, 6, 4, 2, 1):
        if dim % dsub == 0:
            return dim // dsub
    return dim


@jax.jit
def _pq_lut(q: jnp.ndarray, rotation: jnp.ndarray, codebooks: jnp.ndarray
            ) -> jnp.ndarray:
    """Per-query ADC lookup tables, on device: rotate q [B, D], split into
    subspaces, dot every codeword — [B, m, ksub] f32. One einsum; the
    whole table is ~m*256 floats per query."""
    m, _, dsub = codebooks.shape
    qr = jnp.matmul(q, rotation, precision=lax.Precision.HIGHEST,
                    preferred_element_type=jnp.float32)
    q3 = qr.reshape(q.shape[0], m, dsub)
    return jnp.einsum("bmd,mkd->bmk", q3, codebooks,
                      precision=lax.Precision.HIGHEST)


@partial(jax.jit, static_argnames=("r", "chunk"))
def adc_topr(lut: jnp.ndarray, codes: jnp.ndarray, cent: jnp.ndarray,
             selected: jnp.ndarray, r: int, chunk: int = 2048
             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Running top-`r` ADC scores of every candidate code row against
    every query: lut [B, m, ksub], codes [C, m] uint8 (C % chunk == 0),
    cent [C] i32 (the candidate's posting list; -1 padding, -2 dead),
    selected [B, nprobe] (each query's probed lists). Per chunk the code
    bytes expand to a multi-hot [chunk, m*ksub] matrix and ONE MXU matmul
    against the flattened tables scores the block — the same
    one-hot-matmul idiom as the k-means accumulation pass — masked so a
    query only scores candidates from ITS probed lists, then merged into
    a running top-r exactly like ops.topk._topk_scan. Returns
    (scores [B, r] f32, positions into C [B, r] i32, -1 padded)."""
    B, m, ksub = lut.shape
    C = codes.shape[0]
    chunk = min(chunk, C)
    flat = lut.reshape(B, m * ksub)
    blocks = codes.reshape(C // chunk, chunk, m)
    cblocks = cent.reshape(C // chunk, chunk)
    offs_base = jnp.arange(m, dtype=jnp.int32) * ksub

    def body(carry, inp):
        best_s, best_i = carry
        ci, blk, centblk = inp
        offs = blk.astype(jnp.int32) + offs_base[None, :]    # [chunk, m]
        oh = jnp.zeros((chunk, m * ksub), jnp.bfloat16).at[
            jnp.arange(chunk)[:, None], offs].set(1)
        s = jnp.matmul(flat, oh.T, precision=lax.Precision.HIGHEST,
                       preferred_element_type=jnp.float32)   # [B, chunk]
        hit = centblk[None, :] == selected[:, 0:1]
        for p in range(1, selected.shape[1]):
            hit = hit | (centblk[None, :] == selected[:, p:p + 1])
        s = jnp.where(hit, s, -jnp.inf)
        ids = ci * chunk + jnp.arange(chunk, dtype=jnp.int32)
        cat_s = jnp.concatenate([best_s, s], axis=1)
        cat_i = jnp.concatenate(
            [best_i, jnp.broadcast_to(ids[None], (B, chunk))], axis=1)
        top_s, pos = lax.top_k(cat_s, r)
        top_i = jnp.take_along_axis(cat_i, pos, axis=1)
        top_i = jnp.where(jnp.isfinite(top_s), top_i, -1)
        return (top_s, top_i), None

    init = (jnp.full((B, r), -jnp.inf, jnp.float32),
            jnp.full((B, r), -1, jnp.int32))
    (scores, pos), _ = lax.scan(
        body, init,
        (jnp.arange(C // chunk, dtype=jnp.int32), blocks, cblocks))
    return scores, pos


class PQCodec:
    """A trained OPQ rotation + per-subspace codebooks. Encoding and the
    LUT run through jitted device passes; the arrays themselves are tiny
    (D^2 + m*256*dsub floats) and persist as two npy files next to the
    posting lists (ivf.py)."""

    def __init__(self, rotation: np.ndarray, codebooks: np.ndarray):
        self.rotation = np.ascontiguousarray(rotation, dtype=np.float32)
        self.codebooks = np.ascontiguousarray(codebooks, dtype=np.float32)
        self._dev: Optional[Tuple] = None

    @property
    def dim(self) -> int:
        return self.rotation.shape[0]

    @property
    def m(self) -> int:
        return self.codebooks.shape[0]

    @property
    def ksub(self) -> int:
        return self.codebooks.shape[1]

    @property
    def dsub(self) -> int:
        return self.codebooks.shape[2]

    def device(self) -> Tuple:
        """(rotation, codebooks) as device arrays, cached."""
        if self._dev is None:
            self._dev = (jnp.asarray(self.rotation),
                         jnp.asarray(self.codebooks))
        return self._dev

    def encode(self, vecs: np.ndarray) -> np.ndarray:
        """f32 rows [n, D] -> PQ codes [n, m] uint8 (nearest codeword per
        rotated subspace, through the chunked grouped-assignment pass)."""
        x = np.asarray(vecs, np.float32)
        xr = x @ self.rotation
        codes = grouped_assign(xr.reshape(-1, self.m, self.dsub),
                               self.codebooks)
        return codes.astype(np.uint8)

    def lut(self, q_dev) -> jnp.ndarray:
        """Device ADC tables [B, m, ksub] for device queries [B, D]."""
        rot, cb = self.device()
        return _pq_lut(q_dev, rot, cb)

    def reconstruct(self, codes: np.ndarray) -> np.ndarray:
        """Decode codes [n, m] back to approximate f32 rows [n, D] (the
        rotation is orthogonal, so decode = codewords @ R^T). Test/debug
        aid; the search path never materializes reconstructions."""
        c = np.asarray(codes, np.int64)
        recon = self.codebooks[np.arange(self.m)[None, :], c]
        return recon.reshape(-1, self.dim) @ self.rotation.T


def train_pq(store, m: int, ksub: int = KSUB, iters: int = 8,
             opq_iters: int = 3, seed: int = 0,
             sample: int = 65_536) -> Tuple[PQCodec, Dict]:
    """Train an OPQ rotation + PQ codebooks over the store's seeded
    sample pool. Alternation (Ge et al. 2013): train codebooks in the
    current rotation (grouped_kmeans, the MXU pass), reconstruct the
    pool from its codes, solve the orthogonal Procrustes problem
    R = UV^T from SVD(X^T X_hat) for the rotation that best aligns the
    data with its reconstruction, repeat; identity rotation to start
    (opq_iters=0 is plain PQ). Deterministic for a given (store bytes,
    m, iters, opq_iters, seed, backend). Returns (codec, stats)."""
    t0 = time.perf_counter()
    D = store.dim
    if D % m:
        raise ValueError(f"pq_m={m} must divide the store dim {D}")
    N = store.num_vectors
    if N == 0:
        raise ValueError("cannot train PQ codebooks over an empty store")
    pool = sample_rows(store, max(2, min(sample, N)), seed)
    n = pool.shape[0]
    k = min(int(ksub), n)
    dsub = D // m
    R = np.eye(D, dtype=np.float32)
    reseeded = 0
    for t in range(max(0, int(opq_iters))):
        xr = pool @ R
        cb, st = grouped_kmeans(xr.reshape(n, m, dsub), k, iters=iters,
                                seed=(seed, 3, t))
        reseeded += st["reseeded"]
        codes = grouped_assign(xr.reshape(n, m, dsub), cb)
        recon = cb[np.arange(m)[None, :], codes].reshape(n, D)
        u, _, vt = np.linalg.svd(pool.T.astype(np.float64)
                                 @ recon.astype(np.float64))
        R = np.ascontiguousarray((u @ vt).astype(np.float32))
    xr = pool @ R
    cb, st = grouped_kmeans(xr.reshape(n, m, dsub), k, iters=iters,
                            seed=(seed, 3, max(0, int(opq_iters))))
    codec = PQCodec(R, cb)
    stats = {"m": int(m), "ksub": int(k), "dsub": int(dsub),
             "iters": int(iters), "opq_iters": int(opq_iters),
             "seed": int(seed), "pool": int(n),
             "reseeded": reseeded + st["reseeded"],
             "train_seconds": round(time.perf_counter() - t0, 3)}
    return codec, stats
