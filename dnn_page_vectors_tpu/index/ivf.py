"""IVF inverted-file ANN index over the vector store (docs/ANN.md).

Every retrieval path used to pay O(corpus) per query through
`ops/topk.py:topk_over_store`. This index makes retrieval sublinear the
canonical way (Jegou et al. 2011; Johnson et al. 2017 / faiss): a coarse
k-means quantizer (index/kmeans.py, trained on the MXU over streamed store
shards) partitions the store's rows into `nlist` inverted lists; a query
scores the tiny [nlist, D] centroid matrix on device, gathers only the
rows of its top-`nprobe` lists from the store's memory-mapped shards (int8
codes at stored width — dequant fuses into the re-rank matmul), and
exact-reranks that candidate block with `ops.topk.rerank_candidates`.
Recall-vs-exact is a measured contract (`evals.recall.recall_vs_exact`,
bench `ann_recall_at_10`), not a hope.

Layout (next to the store, same manifest machinery as VectorStore):

  <store>/ivf/manifest.json     nlist, dim, model_step stamp, seed, per-file
                                byte sizes + CRC32s, per-shard posting table
  <store>/ivf/centroids.npy     [nlist, D] float32 unit-norm centroids
  <store>/ivf/posting_NNNNN.ord.npy   [count] int32 shard-row order, grouped
                                      by centroid (CSR values)
  <store>/ivf/posting_NNNNN.off.npy   [nlist+1] int64 CSR offsets

Validity contract (docs/ROBUSTNESS.md semantics): `open()` re-checks the
recorded model step against the store's stamp, the recorded shard table
(index, count) against the store's live one, and every file's bytes+CRC32.
A stale index (ensure_model_step re-stamp, re-embed, shard quarantine)
raises `IndexUnavailable`; a corrupt file is quarantined (renamed aside,
counted in the fault counters) and the index reports unavailable — callers
(SearchService, eval, mine) fall back to the exact brute-force path
per request, visibly, and `cli index` rebuilds.

Live updates (docs/UPDATES.md): a store APPEND (new generation of shards)
makes the recorded table a strict subset of the live one — `update()`
extends the index in O(new shards) by assigning only the unrecorded shards
to the existing centroids and appending their posting files, until the
drift (corpus fraction appended since the last full k-means,
`updates.rebuild_drift`) forces a fresh build. Tombstoned rows stay in
their posting lists; the store's read-time id masking turns them into
dead (-1) candidates the re-rank already drops.
"""
from __future__ import annotations

import json
import math
import os
import time
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from dnn_page_vectors_tpu.index.kmeans import assign_store, train_kmeans
from dnn_page_vectors_tpu.infer.vector_store import crc_file
from dnn_page_vectors_tpu.ops.topk import chunked_topk, rerank_candidates
from dnn_page_vectors_tpu.utils import faults

DIRNAME = "ivf"
MANIFEST = "manifest.json"


class IndexUnavailable(RuntimeError):
    """The IVF index cannot serve (missing / stale / quarantined). Callers
    catch this and fall back to exact search — it is a routing signal, not
    a crash."""


def index_dir(store) -> str:
    return os.path.join(store.directory, DIRNAME)


def auto_nlist(num_vectors: int) -> int:
    """Default list count: ~sqrt(N) (the standard IVF operating point),
    clamped so tiny toy stores still get a few multi-row lists and huge
    stores don't pay a megarow centroid scan."""
    return max(4, min(int(math.isqrt(max(num_vectors, 1))), 65_536,
                      max(num_vectors, 1)))


def _bucket(n: int, lo: int) -> int:
    """Next power of two >= max(n, lo): one compiled shape per octave, so
    varying candidate/query counts don't retrace every call."""
    return 1 << max(int(math.ceil(math.log2(max(n, 1)))), int(lo - 1).bit_length())


def _write_npy(path: str, arr: np.ndarray) -> Tuple[int, int]:
    """Durable seeded-fault-aware array write (the write_shard pattern):
    bytes land + fsync, size+CRC recorded from the written bytes, and the
    post-fsync corruption hook fires AFTER the record — so injected rot is
    caught by the verify gate, not hidden by the writer."""
    plan = faults.active()

    def _w():
        plan.check("index_write")
        np.save(path, arr)
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    faults.retry(_w, op="index_write")
    rec = (os.path.getsize(path), crc_file(path))
    plan.corrupt("index_file", path)
    return rec


def _atomic_dump(obj, path: str) -> None:
    plan = faults.active()

    def _dump():
        plan.check("index_write")
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    faults.retry(_dump, op="index_write")


class IVFIndex:
    def __init__(self, store, manifest: Dict, centroids: np.ndarray,
                 postings: Dict[int, Tuple[np.ndarray, np.ndarray]]):
        self.store = store
        self.manifest = manifest
        self.centroids = centroids                 # [nlist, D] f32
        self._postings = postings                  # {shard: (order, offsets)}
        self._entries = {s["index"]: s for s in store.shards()}
        self._raw: Dict[int, tuple] = {}           # lazy mmap cache
        self._dev_centroids = None
        # total rows per list across shards: candidate accounting without
        # touching the postings at search time
        sizes = np.zeros((self.nlist,), np.int64)
        for _, offsets in postings.values():
            sizes += np.diff(offsets)
        self.list_sizes = sizes
        self.stats = {"searches": 0, "lists_scanned": 0,
                      "candidates_reranked": 0}

    # -- identity ----------------------------------------------------------
    @property
    def nlist(self) -> int:
        return int(self.manifest["nlist"])

    @property
    def model_step(self) -> Optional[int]:
        return self.manifest.get("model_step")

    @property
    def imbalance(self) -> float:
        return float(self.manifest.get("imbalance", 0.0))

    @property
    def index_generation(self) -> int:
        """Incremental updates applied since the last full k-means build
        (0 = freshly built; docs/UPDATES.md)."""
        return int(self.manifest.get("index_generation", 0))

    # -- build -------------------------------------------------------------
    @staticmethod
    def _assign_postings(d: str, store, mesh, centroids: np.ndarray,
                         entries, chunk: int):
        """Assign `entries`' rows to `centroids` and persist their CSR
        posting files. Returns (shards_meta, postings, sizes [nlist]) for
        exactly those entries — build runs it over the whole store,
        update() over only the new generation's shards."""
        nlist = centroids.shape[0]
        shards_meta = []
        postings: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        sizes = np.zeros((nlist,), np.int64)
        nonzero = [e for e in entries if e["count"] > 0]
        for entry, assign in assign_store(store, mesh, centroids,
                                          chunk=chunk, entries=nonzero):
            order = np.argsort(assign, kind="stable").astype(np.int32)
            counts = np.bincount(assign, minlength=nlist)
            offsets = np.zeros((nlist + 1,), np.int64)
            offsets[1:] = np.cumsum(counts)
            sizes += counts
            stem = f"posting_{entry['index']:05d}"
            ob, oc = _write_npy(os.path.join(d, stem + ".ord.npy"), order)
            fb, fc = _write_npy(os.path.join(d, stem + ".off.npy"), offsets)
            shards_meta.append({
                "index": entry["index"], "count": int(entry["count"]),
                "ord": stem + ".ord.npy", "off": stem + ".off.npy",
                "bytes": {"ord": ob, "off": fb},
                "crc": {"ord": oc, "off": fc}})
            postings[entry["index"]] = (order, offsets)
        # zero-count shards carry no postings but must stay in the recorded
        # table, or open() would read an honest store change into them
        for entry in entries:
            if entry["count"] == 0:
                shards_meta.append({"index": entry["index"], "count": 0})
        return shards_meta, postings, sizes

    @classmethod
    def build(cls, store, mesh, nlist: int = 0, iters: int = 8,
              seed: int = 0, chunk: int = 8192,
              sample_per_shard: Optional[int] = None,
              init: str = "kmeans++") -> "IVFIndex":
        """Train the quantizer, assign every store row, and persist the
        inverted file next to the store (atomic manifest last, so a crash
        mid-build leaves the previous index or none — never a torn one
        that passes verification)."""
        t0 = time.perf_counter()
        N = store.num_vectors
        if N == 0:
            raise ValueError("cannot build an IVF index over an empty store")
        nlist = int(nlist) if nlist and nlist > 0 else auto_nlist(N)
        nlist = min(nlist, N)
        centroids, kstats = train_kmeans(
            store, mesh, nlist, iters=iters, seed=seed, chunk=chunk,
            sample_per_shard=sample_per_shard, init=init)
        d = index_dir(store)
        os.makedirs(d, exist_ok=True)
        cb, cc = _write_npy(os.path.join(d, "centroids.npy"), centroids)
        shards_meta, postings, sizes = cls._assign_postings(
            d, store, mesh, centroids, store.shards(), chunk)
        shards_meta.sort(key=lambda s: s["index"])
        imbalance = float(nlist * np.square(sizes, dtype=np.float64).sum()
                          / max(N, 1) ** 2)
        manifest = {
            "version": 1, "nlist": nlist, "dim": store.dim,
            "dtype": store.manifest["dtype"],
            "model_step": store.model_step, "seed": int(seed),
            "iters": kstats["iters"], "reseeded": kstats["reseeded"],
            "init": kstats["init"],
            "init_imbalance": kstats["init_imbalance"],
            "num_vectors": int(N), "imbalance": round(imbalance, 4),
            # live-update bookkeeping (docs/UPDATES.md): rows covered by
            # the last full k-means vs rows appended incrementally since —
            # their ratio is the drift that triggers the next full rebuild
            "built_num_vectors": int(N),
            "appended_since_build": 0,
            "index_generation": 0,
            "build_seconds": round(time.perf_counter() - t0, 3),
            "centroids": {"file": "centroids.npy", "bytes": cb, "crc": cc},
            "shards": shards_meta,
        }
        _atomic_dump(manifest, os.path.join(d, MANIFEST))
        return cls(store, manifest, centroids, postings)

    # -- incremental update (docs/UPDATES.md) ------------------------------
    @classmethod
    def update(cls, store, mesh, rebuild_drift: float = 0.25,
               nlist: int = 0, iters: int = 8, seed: Optional[int] = None,
               chunk: int = 8192, init: str = "kmeans++"
               ) -> Tuple["IVFIndex", Dict]:
        """Bring the persisted index up to date with the store after an
        append: assign ONLY the shards the recorded table doesn't know to
        the EXISTING centroids and append their posting files — O(new
        shards), not O(corpus) — then atomically re-dump the manifest.

        Falls back to a FULL rebuild (fresh k-means) when the existing
        index can't be extended: missing/torn/corrupt files, a model-step
        re-stamp, a recorded shard that changed or vanished (quarantine /
        re-embed), or accumulated drift — the fraction of the corpus
        appended since the last full k-means — crossing `rebuild_drift`
        (stale centroids mis-assign enough new rows to erode recall).

        Returns (index, info) where info["action"] is "noop" |
        "incremental" | "rebuild" plus the decision inputs, so callers
        (SearchService.refresh, cli refresh, bench) can count
        incremental_updates vs full_rebuilds. Raises (IOError etc.) only
        when the write path itself fails — the manifest is untouched then,
        so readers keep the previous index generation."""
        t0 = time.perf_counter()
        d = index_dir(store)
        mpath = os.path.join(d, MANIFEST)

        def _rebuild(reason: str) -> Tuple["IVFIndex", Dict]:
            idx = cls.build(store, mesh, nlist=nlist, iters=iters,
                            seed=0 if seed is None else seed, chunk=chunk,
                            init=init)
            faults.count("index_full_rebuilds")
            return idx, {"action": "rebuild", "reason": reason,
                         "seconds": round(time.perf_counter() - t0, 3)}

        if not os.path.exists(mpath):
            return _rebuild("no index on disk")
        try:
            with open(mpath) as f:
                man = json.load(f)
        except (json.JSONDecodeError, ValueError):
            return _rebuild("torn index manifest")
        if (man.get("model_step") != store.model_step
                or man.get("dim") != store.dim):
            return _rebuild("model step / dim changed")
        live = store.shards()
        live_by_idx = {s["index"]: s["count"] for s in live}
        recorded = {s["index"]: s["count"] for s in man.get("shards", [])}
        if any(recorded.get(i) != c for i, c in live_by_idx.items()
               if i in recorded) or any(i not in live_by_idx
                                        for i in recorded):
            return _rebuild("recorded shards changed (quarantine/re-embed)")
        new_entries = [e for e in live if e["index"] not in recorded]
        if not new_entries:
            return (cls.open(store),
                    {"action": "noop",
                     "seconds": round(time.perf_counter() - t0, 3)})
        try:
            cls._verify_files(d, man)      # don't extend corrupt postings
        except IndexUnavailable as e:
            return _rebuild(f"existing index unhealthy ({e})")
        total = store.num_vectors
        appended = (int(man.get("appended_since_build", 0))
                    + sum(e["count"] for e in new_entries))
        drift = appended / max(total, 1)
        if drift > rebuild_drift:
            return _rebuild(
                f"drift {drift:.3f} > rebuild_drift {rebuild_drift}")
        centroids = np.asarray(
            np.load(os.path.join(d, man["centroids"]["file"])), np.float32)
        new_meta, _, new_sizes = cls._assign_postings(
            d, store, mesh, centroids, new_entries, chunk)
        man["shards"] = sorted(man["shards"] + new_meta,
                               key=lambda s: s["index"])
        man["num_vectors"] = int(total)
        man["appended_since_build"] = appended
        man["index_generation"] = int(man.get("index_generation", 0)) + 1
        # imbalance over the FULL posting set: old sizes from the small
        # [nlist+1] offset files, new from the assignment just done
        sizes = new_sizes.astype(np.float64)
        for s in man["shards"]:
            if s["count"] == 0 or s["index"] in {m["index"]
                                                 for m in new_meta}:
                continue
            off = np.load(os.path.join(d, s["off"]))
            sizes += np.diff(off)
        man["imbalance"] = round(
            float(man["nlist"] * np.square(sizes).sum()
                  / max(total, 1) ** 2), 4)
        _atomic_dump(man, mpath)
        faults.count("index_incremental_updates")
        return (cls.open(store, verify=False),
                {"action": "incremental", "new_shards": len(new_entries),
                 "appended_rows": sum(e["count"] for e in new_entries),
                 "drift": round(drift, 4),
                 "index_generation": man["index_generation"],
                 "seconds": round(time.perf_counter() - t0, 3)})

    # -- open / verify -----------------------------------------------------
    @classmethod
    def open(cls, store, verify: bool = True) -> "IVFIndex":
        """Load the persisted index, re-checking stamp, shard table, and
        bytes+CRC32. Raises IndexUnavailable (with the reason) on any
        mismatch — corrupt files are quarantined first."""
        d = index_dir(store)
        mpath = os.path.join(d, MANIFEST)
        if not os.path.exists(mpath):
            raise IndexUnavailable(
                f"no IVF index at {d} (run the 'index' command to build)")
        try:
            with open(mpath) as f:
                man = json.load(f)
        except (json.JSONDecodeError, ValueError):
            q = mpath + ".quarantined"
            os.replace(mpath, q)
            faults.count("quarantined_index_manifests")
            faults.warn(f"IVF manifest {mpath} is torn (invalid JSON); "
                        f"moved aside to {q}")
            raise IndexUnavailable(f"torn IVF manifest (quarantined to {q})")
        if man.get("model_step") != store.model_step:
            raise IndexUnavailable(
                f"stale IVF index: built at model step "
                f"{man.get('model_step')}, store is stamped "
                f"{store.model_step} (rebuild after re-embedding)")
        if man.get("dim") != store.dim:
            raise IndexUnavailable(
                f"stale IVF index: built for {man.get('dim')}-d vectors, "
                f"store holds {store.dim}-d")
        live = {s["index"]: s["count"] for s in store.shards()}
        recorded = {s["index"]: s["count"] for s in man.get("shards", [])}
        if live != recorded:
            raise IndexUnavailable(
                "stale IVF index: store shard table changed since the "
                f"build ({len(recorded)} recorded vs {len(live)} live "
                "shards or row counts differ); rebuild")
        if verify:
            cls._verify_files(d, man)
        plan = faults.active()
        centroids = np.load(os.path.join(d, man["centroids"]["file"]))
        postings: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        for s in man["shards"]:
            if s["count"] == 0:
                continue
            plan.check("index_read")
            postings[s["index"]] = (
                np.load(os.path.join(d, s["ord"])),
                np.load(os.path.join(d, s["off"])))
        return cls(store, man, np.asarray(centroids, np.float32), postings)

    @staticmethod
    def _verify_files(d: str, man: Dict) -> None:
        files = [(man["centroids"]["file"], man["centroids"]["bytes"],
                  man["centroids"]["crc"])]
        for s in man["shards"]:
            if s["count"] == 0:
                continue
            for key in ("ord", "off"):
                files.append((s[key], s["bytes"][key], s["crc"][key]))
        for name, want_bytes, want_crc in files:
            path = os.path.join(d, name)
            err = None
            if not os.path.exists(path):
                err = "missing"
            elif os.path.getsize(path) != want_bytes:
                err = (f"{os.path.getsize(path)} bytes, manifest records "
                       f"{want_bytes} (truncated?)")
            elif crc_file(path) != want_crc:
                err = "CRC mismatch (corrupt)"
            if err is None:
                continue
            if err != "missing":
                os.replace(path, path + ".quarantined")
                faults.count("quarantined_index_files")
                faults.warn(f"quarantined IVF index file {path} ({err}); "
                            "exact search serves until a rebuild")
            raise IndexUnavailable(
                f"IVF index file {name} {err}; rebuild the index")

    # -- search ------------------------------------------------------------
    def _shard_raw(self, sidx: int):
        raw = self._raw.get(sidx)
        if raw is None:
            raw = self._raw[sidx] = self.store._load_entry(
                self._entries[sidx], raw=True)
        return raw

    def _gather(self, cents: np.ndarray):
        """Candidate block for one probed-list union: rows of every listed
        centroid across every shard, at STORED width (int8 codes / fp16
        rows straight off the mmap — the rerank matmul widens on device).
        Returns (vecs [C, D], scales [C]|None, page_ids [C] i64,
        cand_cent [C] i32). Tombstoned rows (id -1 after the store's
        read-time masking, docs/UPDATES.md) get centroid -2 — matching no
        probed list — so a dead vector can never OCCUPY a top-k slot, not
        merely be filtered after winning one."""
        v_parts, s_parts, i_parts, c_parts = [], [], [], []
        for sidx in sorted(self._postings):
            order, offsets = self._postings[sidx]
            rows = [order[offsets[c]: offsets[c + 1]] for c in cents]
            lens = np.array([r.shape[0] for r in rows], np.int64)
            if lens.sum() == 0:
                continue
            take = np.concatenate(rows)
            ids, vecs, scl = self._shard_raw(sidx)
            taken_ids = np.asarray(ids[take], np.int64)
            v_parts.append(np.asarray(vecs[take]))
            i_parts.append(taken_ids)
            if scl is not None:
                s_parts.append(np.asarray(scl[take]))
            cent = np.repeat(cents.astype(np.int32), lens)
            c_parts.append(np.where(taken_ids >= 0, cent, np.int32(-2)))
        if not v_parts:
            return (np.zeros((0, self.store.dim), np.float16), None,
                    np.zeros((0,), np.int64), np.zeros((0,), np.int32))
        return (np.concatenate(v_parts),
                np.concatenate(s_parts) if s_parts else None,
                np.concatenate(i_parts), np.concatenate(c_parts))

    def search(self, qvecs: np.ndarray, k: int, nprobe: Optional[int] = None,
               block: int = 256
               ) -> Tuple[np.ndarray, np.ndarray, Dict[str, int]]:
        """ANN top-k: (scores [Nq, k] f32, page_ids [Nq, k] i64 -1-padded,
        stats). Centroid scoring runs on device through `chunked_topk`
        (queries padded to a power-of-two bucket, one compiled program per
        octave); queries are then processed in `block`-sized sub-blocks —
        per sub-block ONE gathered candidate matmul via
        `rerank_candidates`, dispatched async so sub-block i+1's host
        gather overlaps sub-block i's device re-rank."""
        qvecs = np.asarray(qvecs, np.float32)
        nq = qvecs.shape[0]
        k = int(k)
        out_s = np.full((nq, k), -np.inf, np.float32)
        out_i = np.full((nq, k), -1, np.int64)
        if nq == 0:
            return out_s, out_i, {}
        nprobe = int(min(max(1, nprobe or 1), self.nlist))
        if self._dev_centroids is None:
            self._dev_centroids = jnp.asarray(self.centroids)
        qb = _bucket(nq, lo=8)
        qpad = np.concatenate(
            [qvecs, np.zeros((qb - nq, qvecs.shape[1]), np.float32)]) \
            if qb > nq else qvecs
        _, sel = chunked_topk(jnp.asarray(qpad), self._dev_centroids,
                              k=nprobe, chunk=8192)
        sel = np.asarray(sel, np.int32)[:nq]
        stats = {"searches": nq, "lists_scanned": nq * nprobe,
                 "candidates_reranked":
                     int(self.list_sizes[sel].sum())}
        pending = []
        for s in range(0, nq, block):
            e = min(s + block, nq)
            sel_b = sel[s:e]
            cents = np.unique(sel_b)
            cand, scl, cids, ccent = self._gather(cents)
            C = cand.shape[0]
            if C == 0:
                pending.append((s, e, None, None))
                continue
            cp = _bucket(C, lo=max(512, k))
            if cp > C:
                cand = np.concatenate(
                    [cand, np.zeros((cp - C, cand.shape[1]), cand.dtype)])
                ccent = np.concatenate(
                    [ccent, np.full((cp - C,), -1, np.int32)])
                if scl is not None:
                    scl = np.concatenate(
                        [scl, np.zeros((cp - C,), scl.dtype)])
            # pow-2 query bucket: a lone serve bucket of 8 must not pad to
            # the full mining block width (32x wasted matmul rows)
            bq = min(_bucket(e - s, lo=8), _bucket(block, lo=8))
            qblk = qvecs[s:e]
            if bq > e - s:
                qblk = np.concatenate(
                    [qblk, np.zeros((bq - (e - s), qvecs.shape[1]),
                                    np.float32)])
                sel_b = np.concatenate(
                    [sel_b, np.full((bq - (e - s), nprobe), -1, np.int32)])
            packed = rerank_candidates(
                jnp.asarray(qblk), jnp.asarray(cand),
                None if scl is None else jnp.asarray(scl),
                jnp.asarray(ccent), jnp.asarray(sel_b), k)
            pending.append((s, e, packed, cids))
        for s, e, packed, cids in pending:
            if packed is None:
                continue
            top_s, pos = (np.asarray(packed[0]), np.asarray(packed[1]))
            top_s, pos = top_s[: e - s], pos[: e - s]
            kk = pos.shape[1]
            out_i[s:e, :kk] = np.where(
                pos >= 0, cids[np.clip(pos, 0, None)], -1)
            out_s[s:e, :kk] = np.where(pos >= 0, top_s, -np.inf)
        for key, val in stats.items():
            self.stats[key] = self.stats.get(key, 0) + val
        return out_s, out_i, stats
